"""graftcheck engine — file walking, suppressions, baseline, cache, CLI.

Two passes over the scanned tree: pass 1 parses every file and collects
the cross-file :class:`~.rules.ProjectIndex` (registry stub constants +
alias functions, plus the interprocedural summary index), pass 2 runs
every rule per module. Suppression comments (``# graftcheck:
disable=GC02`` — trailing on the flagged line, or alone on the line
above) are honored before the baseline is applied.

Baseline semantics (``--baseline graftcheck_baseline.json``): a JSON
list of finding fingerprints tolerated for now. The gate fails on any
NON-baselined finding AND on any stale entry — a fixed finding must
leave the baseline in the same PR, so the debt list only ever shrinks.

Findings cache (``.graftcheck_cache.json`` under the scan root):
content-hashed and stamped with :data:`~.rules.RULESTAMP`. Because the
rules are INTERPROCEDURAL, per-file reuse is unsound — editing one file
can change another file's findings through the summary index — so
invalidation is whole-scan: when the rule stamp, the scanned file set
and every file's sha256 match the cache, the findings are replayed with
zero parsing (the CI re-run case); any difference re-analyzes
everything (a few seconds). ``--no-cache`` bypasses both directions.
"""

from __future__ import annotations

import argparse
import ast
import difflib
import hashlib
import io
import json
import os
import re
import sys
import tokenize
from typing import Dict, Iterable, List, Optional, Set, Tuple

import time

from . import interproc
from .rules import (Finding, ModuleContext, ProjectIndex, RULES,
                    RULESTAMP, collect_project, project_from_facts,
                    run_rules)

__all__ = ["Finding", "run_paths", "scan_file", "load_baseline",
           "write_baseline", "main"]

_DIRECTIVE = re.compile(r"graftcheck:\s*disable=([A-Z0-9,\s]+)")
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}

CACHE_NAME = ".graftcheck_cache.json"


def iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS
                                     and not d.startswith("."))
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)


def _comment_map(source: str) -> Tuple[Dict[int, str], Set[int]]:
    """line -> comment text, plus the set of comment-ONLY lines (a
    directive alone on its own line applies to the next code line)."""
    comments: Dict[int, str] = {}
    only: Set[int] = set()
    try:
        toks = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return comments, only
    code_lines: Set[int] = set()
    for tok in toks:
        if tok.type == tokenize.COMMENT:
            comments[tok.start[0]] = tok.string
        elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                              tokenize.INDENT, tokenize.DEDENT,
                              tokenize.ENCODING, tokenize.ENDMARKER):
            for line in range(tok.start[0], tok.end[0] + 1):
                code_lines.add(line)
    only = {ln for ln in comments if ln not in code_lines}
    return comments, only


def _suppressions(comments: Dict[int, str],
                  comment_only: Set[int]) -> Dict[int, Set[str]]:
    """Effective per-line suppressed codes: a trailing directive covers
    its own line; a directive alone on a line covers the next line."""
    supp: Dict[int, Set[str]] = {}
    for line, text in comments.items():
        m = _DIRECTIVE.search(text)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        supp.setdefault(line, set()).update(codes)
        if line in comment_only:
            supp.setdefault(line + 1, set()).update(codes)
    return supp


def _parse_one(path: str, relpath: str) \
        -> Tuple[Optional[ModuleContext], Optional[Finding]]:
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return None, Finding("GC00", relpath, e.lineno or 0, 0,
                             f"syntax error: {e.msg}",
                             "graftcheck cannot analyze unparseable "
                             "source", "<module>")
    comments, only = _comment_map(source)
    ctx = ModuleContext(relpath, tree, comments)
    ctx.suppressions = _suppressions(comments, only)  # type: ignore
    return ctx, None


def scan_file(path: str, root: Optional[str] = None,
              project: Optional[ProjectIndex] = None) -> List[Finding]:
    """Analyze one file (convenience for tests); cross-file GC05 parity
    and interprocedural edges only see this file unless ``project`` is
    given."""
    rel = os.path.relpath(path, root or os.getcwd()).replace(os.sep, "/")
    ctx, err = _parse_one(path, rel)
    if err is not None:
        return [err]
    assert ctx is not None
    if project is None:
        project = collect_project([ctx])
    return _apply_suppressions(ctx, run_rules(ctx, project))


def _apply_suppressions(ctx: ModuleContext,
                        findings: List[Finding]) -> List[Finding]:
    supp = getattr(ctx, "suppressions", {})
    return [f for f in findings if f.code not in supp.get(f.line, set())]


# -- findings cache ---------------------------------------------------------

def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def _finding_from_json(d: dict) -> Finding:
    return Finding(code=d["code"], path=d["path"], line=d["line"],
                   col=d["col"], message=d["message"],
                   hint=d.get("hint", ""),
                   symbol=d.get("symbol", "<module>"),
                   fix_kind=d.get("fix_kind"),
                   fix_lines=tuple(d.get("fix_lines", ())))


def _cache_load(cache_path: str, shas: Dict[str, str]) \
        -> Optional[List[Finding]]:
    """Replay cached findings iff the rule stamp, the file SET and every
    file's content hash match — else None (full re-analysis)."""
    try:
        with open(cache_path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError, json.JSONDecodeError):
        return None
    if data.get("stamp") != RULESTAMP:
        return None
    cached = data.get("files")
    if not isinstance(cached, dict) or set(cached) != set(shas):
        return None
    for rel, entry in cached.items():
        if not isinstance(entry, dict) or entry.get("sha") != shas[rel]:
            return None                  # mangled entry: just re-scan
    try:
        out = [_finding_from_json(d)
               for entry in cached.values()
               for d in entry.get("findings", [])]
    except (KeyError, TypeError):
        return None
    out.sort(key=lambda f: (f.path, f.line, f.code))
    return out


def _cache_store(cache_path: str, shas: Dict[str, str],
                 findings: List[Finding]) -> None:
    by_file: Dict[str, List[dict]] = {rel: [] for rel in shas}
    for f in findings:
        by_file.setdefault(f.path, []).append(f.to_json())
    data = {"stamp": RULESTAMP,
            "comment": "graftcheck findings cache — whole-scan "
                       "invalidation (interprocedural rules make "
                       "per-file reuse unsound); delete freely",
            "files": {rel: {"sha": sha,
                            "findings": by_file.get(rel, [])}
                      for rel, sha in shas.items()}}
    tmp = cache_path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
        os.replace(tmp, cache_path)
    except OSError:
        pass                             # a read-only tree just re-scans


def _worker_main(conn, shard: List[Tuple[str, str]]) -> None:
    """One scan worker: parse + extract facts for its shard, ship the
    (picklable) facts to the main process, receive the assembled
    project view back, run the rule pass on the contexts it kept.
    Fork-spawned — the shard arrives through the closure-free args so
    the protocol also survives a spawn start method."""
    try:
        contexts: List[ModuleContext] = []
        errors: List[Finding] = []
        facts = []
        for rel, ap in shard:
            ctx, err = _parse_one(ap, rel)
            if err is not None:
                errors.append(err)
                continue
            assert ctx is not None
            try:
                facts.append(interproc.extract_module(ctx))
            except Exception:  # noqa: BLE001 — degrade to unknown
                pass
            contexts.append(ctx)
        conn.send(("facts", facts, errors))
        msg = conn.recv()
        if not (isinstance(msg, tuple) and msg and msg[0] == "project"):
            return
        project: ProjectIndex = msg[1]
        rule_wall: Dict[str, float] = {}
        findings: List[Finding] = []
        for ctx in contexts:
            findings.extend(_apply_suppressions(
                ctx, run_rules(ctx, project, rule_wall)))
        conn.send(("findings", findings, rule_wall))
    except Exception:  # noqa: BLE001 — the main process falls back
        import traceback
        try:
            conn.send(("error", traceback.format_exc()))
        except Exception:  # noqa: BLE001 — pipe already gone
            pass
    finally:
        conn.close()


def _run_parallel(files: Dict[str, str], jobs: int,
                  timings: Optional[dict]) -> Optional[List[Finding]]:
    """Fan the parse/summary pass AND the rule pass across ``jobs``
    worker processes (the 2-CPU CI container is the floor this exists
    for). Returns None on ANY failure — the caller falls back to the
    serial path, so a multiprocessing quirk can never take the gate
    down."""
    try:
        import multiprocessing as mp
        mpc = mp.get_context("fork")
    except (ImportError, ValueError):
        return None
    # balance shards by size: big modules dominate the summary pass
    def _size(kv):
        try:
            return -os.path.getsize(kv[1])
        except OSError:
            return 0                     # vanished mid-scan: the worker
            #                              degrades it to a parse error
    sized = sorted(files.items(), key=_size)
    shards = [sized[i::jobs] for i in range(jobs)]
    shards = [s for s in shards if s]
    procs, conns = [], []
    t0 = time.perf_counter()
    try:
        for shard in shards:
            parent, child = mpc.Pipe()
            p = mpc.Process(target=_worker_main, args=(child, shard),
                            daemon=True)
            p.start()
            child.close()
            procs.append(p)
            conns.append(parent)
        all_facts, findings = [], []
        for parent in conns:
            msg = parent.recv()
            if msg[0] != "facts":
                raise RuntimeError(f"worker failed: {msg[1][:2000]}")
            all_facts.extend(msg[1])
            findings.extend(msg[2])
        t1 = time.perf_counter()
        project = project_from_facts(all_facts)
        t2 = time.perf_counter()
        for parent in conns:
            parent.send(("project", project))
        rule_wall: Dict[str, float] = {}
        for parent in conns:
            msg = parent.recv()
            if msg[0] != "findings":
                raise RuntimeError(f"worker failed: {msg[1][:2000]}")
            findings.extend(msg[1])
            for k, v in msg[2].items():
                # workers run each rule concurrently over disjoint
                # shards — the busiest worker IS the rule's wall-clock
                # contribution; summing would report CPU-seconds that
                # grow with --jobs and overstate the CI budget
                rule_wall[k] = max(rule_wall.get(k, 0.0), v)
        if timings is not None:
            timings["jobs"] = len(shards)
            timings["rules_s"] = {k: round(v, 4)
                                  for k, v in sorted(rule_wall.items())}
            timings["phases_s"] = {
                "parse_extract": round(t1 - t0, 4),
                "assemble": round(t2 - t1, 4),
                "rules": round(time.perf_counter() - t2, 4),
            }
        return findings
    except Exception:  # noqa: BLE001 — serial fallback handles it
        return None
    finally:
        for parent in conns:
            try:
                parent.close()
            except OSError:
                pass
        for p in procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()


def _run_serial(files: Dict[str, str],
                timings: Optional[dict]) -> List[Finding]:
    t0 = time.perf_counter()
    contexts: List[ModuleContext] = []
    findings: List[Finding] = []
    for rel, ap in files.items():
        ctx, err = _parse_one(ap, rel)
        if err is not None:
            findings.append(err)
            continue
        assert ctx is not None
        contexts.append(ctx)
    project = collect_project(contexts)
    t1 = time.perf_counter()
    rule_wall: Dict[str, float] = {}
    for ctx in contexts:
        findings.extend(_apply_suppressions(
            ctx, run_rules(ctx, project, rule_wall)))
    if timings is not None:
        timings["jobs"] = 1
        timings["rules_s"] = {k: round(v, 4)
                              for k, v in sorted(rule_wall.items())}
        timings["phases_s"] = {
            "parse_extract_assemble": round(t1 - t0, 4),
            "rules": round(time.perf_counter() - t1, 4),
        }
    return findings


#: below this many files the fork+pickle overhead outweighs the win
#: (selfcheck scratch trees and single-file scans stay serial)
_PARALLEL_MIN_FILES = 24


def run_paths(paths: Iterable[str], root: Optional[str] = None,
              cache: Optional[str] = None, jobs: Optional[int] = None,
              timings: Optional[dict] = None) -> List[Finding]:
    """Scan every .py under ``paths``; returns suppression-filtered
    findings (baseline is the caller's concern). Paths in findings are
    relative to ``root`` (default: cwd), '/'-separated — baseline
    fingerprints stay stable across machines. ``cache``: path of the
    findings cache to consult/update (None = no caching). ``jobs``:
    worker processes for the parse/summary + rule passes (default: the
    CPU count; 1 forces serial). ``timings``: optional dict that
    receives the per-rule and per-phase wall breakdown."""
    root = os.path.abspath(root or os.getcwd())
    files: Dict[str, str] = {}           # rel -> abs
    for path in iter_py_files(paths):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, root).replace(os.sep, "/")
        files[rel] = ap

    shas: Optional[Dict[str, str]] = None
    if cache:
        shas = {rel: _sha256_file(ap) for rel, ap in files.items()}
        cached = _cache_load(cache, shas)
        if cached is not None:
            if timings is not None:
                timings["cached"] = True
            return cached

    njobs = jobs if jobs is not None else (os.cpu_count() or 1)
    findings: Optional[List[Finding]] = None
    if njobs >= 2 and len(files) >= _PARALLEL_MIN_FILES:
        findings = _run_parallel(files, njobs, timings)
    if findings is None:
        findings = _run_serial(files, timings)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    if cache and shas is not None:
        _cache_store(cache, shas, findings)
    return findings


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> List[str]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get("findings", [])
    if not isinstance(data, list) \
            or not all(isinstance(x, str) for x in data):
        raise ValueError(f"{path}: baseline must be a JSON list of "
                         f"fingerprint strings (or {{'findings': [...]}})")
    return data


def write_baseline(path: str, findings: List[Finding]) -> None:
    data = {"version": 1,
            "comment": "graftcheck debt list — fixing a finding MUST "
                       "remove its entry (the gate flags stale entries); "
                       "see docs/STATIC_ANALYSIS.md",
            "findings": sorted(f.fingerprint for f in findings)}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1)
        f.write("\n")


def gate(findings: List[Finding], baseline: List[str],
         covered: Optional[List[str]] = None) \
        -> Tuple[List[Finding], List[str]]:
    """(new findings not in baseline, stale baseline entries).

    ``covered`` — scan-root prefixes (relpaths, '/'-separated): an entry
    is judged stale only when its file lies UNDER a scanned root; a
    partial scan (one file/dir) must not flag the rest of the repo's
    baseline as stale. ``None`` = the scan covered everything."""
    prints = {f.fingerprint for f in findings}
    base = set(baseline)
    fresh = [f for f in findings if f.fingerprint not in base]

    def in_scope(fp: str) -> bool:
        if covered is None:
            return True
        path = fp.split("::", 1)[0]
        return any(p in (".", "") or path == p or path.startswith(p + "/")
                   for p in covered)

    stale = sorted(fp for fp in base - prints if in_scope(fp))
    return fresh, stale


# -- mechanical fixes (--fix) -----------------------------------------------

_GC06_ANNOTATION = ("  # isolation: TODO(graftcheck --fix) name why "
                    "this catch-all is required")

# np.<fn> names with a drop-in jnp twin — the ONLY rewrites the
# mechanical GC09 fix may make; anything else on a flagged line
# (np.random.*, I/O, twin-less APIs) stays for a human and the finding
# survives the rescan
_JNP_TWINS = frozenset((
    "abs", "absolute", "add", "all", "any", "arange", "argmax",
    "argmin", "argsort", "array", "asarray", "ceil", "clip",
    "concatenate", "cos", "cumprod", "cumsum", "diag", "divide", "dot",
    "einsum", "exp", "expand_dims", "eye", "floor", "full", "full_like",
    "inner", "isfinite", "isinf", "isnan", "linspace", "log", "log10",
    "log1p", "log2", "matmul", "max", "maximum", "mean", "median",
    "min", "minimum", "multiply", "ones", "ones_like", "outer",
    "power", "prod", "reshape", "round", "sign", "sin", "sort", "split",
    "sqrt", "square", "squeeze", "stack", "std", "subtract", "sum",
    "take", "tanh", "tensordot", "transpose", "tril", "triu", "unique",
    "var", "where", "zeros", "zeros_like",
))


def _in_noncode(line: str, pos: int) -> bool:
    """True when ``pos`` sits inside a string literal or after a ``#``
    comment marker — spans a mechanical rewrite must never touch."""
    q = None
    i = 0
    while i < pos:
        c = line[i]
        if q is not None:
            if c == "\\":
                i += 2
                continue
            if c == q:
                q = None
        elif c in "\"'":
            q = c
        elif c == "#":
            return True
        i += 1
    return q is not None


def _sub_np_jnp(line: str) -> str:
    """``np.<fn>`` → ``jnp.<fn>`` on ONE flagged line — only for fns
    with a drop-in jnp twin, never inside strings or comments (a
    blanket rewrite would mint ``jnp.random...`` AttributeErrors and
    mutate log text)."""
    def repl(m: "re.Match[str]") -> str:
        if m.group(1) not in _JNP_TWINS or _in_noncode(line, m.start()):
            return m.group(0)
        return "jnp." + m.group(1)
    return re.sub(r"\b(?:np|numpy)\.([A-Za-z_][A-Za-z0-9_]*)",
                  repl, line)


def _apply_fixes(findings: List[Finding], root: str,
                 write: bool) -> Tuple[str, int]:
    """Build the mechanical rewrites for fixable findings. Returns
    (unified diff across all touched files, number of findings fixed);
    with ``write`` the new contents also land on disk.

    GC02 ``gc02-monotonic``: every literal ``time.time()`` on the
    finding's fix lines becomes ``time.monotonic()`` (the flagged
    arithmetic plus the taint-source assignments). GC06
    ``gc06-annotate``: the bare handler line gains a TODO annotation
    comment — the rule passes, and the placeholder text keeps a human
    on the hook for the real why.
    """
    per_file: Dict[str, Dict[int, str]] = {}   # rel -> line -> kind
    for f in findings:
        if f.fix_kind is None:
            continue
        for ln in (f.fix_lines or (f.line,)):
            per_file.setdefault(f.path, {})[ln] = f.fix_kind
    chunks: List[str] = []
    changed: Dict[str, Set[int]] = {}          # rel -> lines rewritten
    for rel in sorted(per_file):
        ap = os.path.join(root, rel.replace("/", os.sep))
        try:
            with open(ap, "r", encoding="utf-8") as fh:
                old_lines = fh.readlines()
        except OSError:
            continue
        new_lines = list(old_lines)
        for ln, kind in per_file[rel].items():
            i = ln - 1
            if not (0 <= i < len(new_lines)):
                continue
            if kind == "gc02-monotonic":
                new_lines[i] = new_lines[i].replace(
                    "time.time()", "time.monotonic()")
            elif kind == "gc09-jnp":
                # the mechanical GC09 subset: a numpy call on a traced
                # value becomes its jnp twin (twin-allowlisted, code
                # spans only — see _sub_np_jnp)
                new_lines[i] = _sub_np_jnp(new_lines[i])
            elif kind == "gc06-annotate":
                stripped = new_lines[i].rstrip("\n")
                if "#" not in stripped:
                    new_lines[i] = stripped + _GC06_ANNOTATION + "\n"
            if new_lines[i] != old_lines[i]:
                changed.setdefault(rel, set()).add(ln)
        if (any(per_file[rel].get(ln) == "gc09-jnp"
                for ln in changed.get(rel, ()))
                and not re.search(
                    r"^\s*(?:import\s+jax\.numpy\s+as\s+jnp\b"
                    r"|from\s+jax\s+import\s+numpy\s+as\s+jnp\b)",
                    "".join(new_lines), re.M)):
            # the rewrite references jnp — a module that only imported
            # numpy must gain the binding or --fix --write would leave
            # it raising NameError at import
            at = 0
            for i, txt in enumerate(new_lines):
                if re.match(r"(?:import|from)\s+numpy\b", txt):
                    at = i + 1
                    break
                if at == 0 and re.match(r"(?:import|from)\s+\w", txt):
                    at = i + 1           # fallback: after first import
            new_lines.insert(at, "import jax.numpy as jnp\n")
        if new_lines == old_lines:
            continue
        chunks.append("".join(difflib.unified_diff(
            old_lines, new_lines, fromfile=f"a/{rel}",
            tofile=f"b/{rel}")))
        if write:
            with open(ap, "w", encoding="utf-8") as fh:
                fh.writelines(new_lines)
    # a finding counts as fixed only when a line it owns actually
    # changed — a fixable-flagged finding whose rewrite was a no-op must
    # not let `--fix --write` report success on an unchanged file
    fixed = sum(
        1 for f in findings if f.fix_kind is not None
        and changed.get(f.path, set())
        & set(f.fix_lines or (f.line,)))
    return "".join(chunks), fixed


# -- selfcheck --------------------------------------------------------------

_FIXTURES = {
    # one seeded violation per rule — the gate must catch every one.
    # pkg/... fixture modules import each other with absolute names
    # (pkg.x.y) so the interprocedural resolver links them exactly as it
    # links real modules.
    "pkg/models/bad_model.py": (
        "import jax\n"
        "from functools import lru_cache\n\n"
        "def per_call_predict(f, x):\n"
        "    g = jax.jit(f)\n"
        "    return g(x)\n\n"
        "def nested_factory():\n"
        "    @lru_cache(maxsize=8)\n"
        "    def build(n):\n"
        "        return jax.jit(lambda v: v * n)\n"
        "    return build\n",
        {"GC01"}),
    "pkg/io/bad_io.py": (
        "import time\n\n"
        "def save_pointer(path, blob):\n"
        "    with open(path, 'w') as f:\n"
        "        f.write(blob)\n\n"
        "def wait(deadline_s):\n"
        "    deadline = time.time() + deadline_s\n"
        "    while time.time() < deadline:\n"
        "        pass\n",
        {"GC02", "GC03"}),
    "pkg/serve/bad_serve.py": (
        "import threading\n\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0\n"
        "        threading.Thread(target=self._a).start()\n"
        "        threading.Thread(target=self._b).start()\n"
        "    def _a(self):\n"
        "        self.count += 1\n"
        "    def _b(self):\n"
        "        try:\n"
        "            self.count -= 1\n"
        "        except Exception:\n"
        "            pass\n",
        {"GC04", "GC06"}),
    "pkg/obs/registry.py": (
        "FOO_STUB = {'ok': 0, 'bad-dash': 0}\n\n"
        "class P:\n"
        "    def obs_section(self):\n"
        "        return {'ok': 0, 'extra': 1}\n"
        "    def _register_obs(self):\n"
        "        def p():\n"
        "            return (self.obs_section() if self is not None\n"
        "                    else dict(FOO_STUB))\n"
        "        registry.register('bad.name', p)\n",
        {"GC05"}),
    # GC05 on the ISSUE-13 `retrain` section specifically: a provider
    # whose keys drift from RETRAIN_STUB must be caught the same way
    # (the autopilot's state machine is dashboard-keyed)
    "pkg/obs/retrain_registry.py": (
        "RETRAIN_STUB = {'state': 'idle', 'attempts': 0}\n\n"
        "class R:\n"
        "    def obs_section(self):\n"
        "        return {'state': 'idle', 'extra_key': 1}\n"
        "    def _register_obs(self):\n"
        "        def p():\n"
        "            return (self.obs_section() if self is not None\n"
        "                    else dict(RETRAIN_STUB))\n"
        "        registry.register('retrain', p)\n",
        {"GC05"}),
    # GC07: a direct fetch in a per-step loop, and a call to a helper
    # that fetches (one function boundary away)
    "pkg/models/bad_hot.py": (
        "import numpy as np\n\n"
        "def fetch_loss(x):\n"
        "    return float(np.asarray(x))\n\n"
        "def train(step, batches):\n"
        "    losses = []\n"
        "    for b in batches:\n"
        "        out = step(b)\n"
        "        losses.append(fetch_loss(out))\n"
        "    return losses\n",
        {"GC07"}),
    # GC08: a stored looping thread no shutdown path ever joins/signals
    "pkg/serve/bad_thread.py": (
        "import threading\n\n"
        "class Daemon:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._run,\n"
        "                                   daemon=True)\n"
        "        self._t.start()\n"
        "    def _run(self):\n"
        "        while True:\n"
        "            pass\n",
        {"GC08"}),
    # interprocedural upgrades: each pair is INVISIBLE to the PR 11
    # intra-module analysis (tests/test_graftcheck.py pins the
    # single-module miss); the summaries must connect them
    "pkg/utils/clockutil.py": (
        "import time\n\n"
        "def now_s():\n"
        "    return time.time()\n",
        set()),
    "pkg/io/bad_deadline.py": (
        "from pkg.utils.clockutil import now_s\n\n"
        "def wait(seconds):\n"
        "    deadline = now_s() + seconds\n"
        "    while now_s() < deadline:\n"
        "        pass\n",
        {"GC02"}),
    "pkg/ops/jit_factory.py": (
        "import jax\n\n"
        "def make_step(f):\n"
        "    return jax.jit(f)\n",
        set()),
    "pkg/models/bad_factory_use.py": (
        "from pkg.ops.jit_factory import make_step\n\n"
        "def score_all(fns, x):\n"
        "    return [make_step(f)(x) for f in fns]\n",
        {"GC01"}),
    "pkg/serve/attr_helper.py": (
        "def bump_counter(obj):\n"
        "    obj.count += 1\n",
        set()),
    "pkg/serve/bad_cross_write.py": (
        "import threading\n"
        "from pkg.serve.attr_helper import bump_counter\n\n"
        "class X:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0\n"
        "        threading.Thread(target=self._a).start()\n"
        "        threading.Thread(target=self._b).start()\n"
        "    def _a(self):\n"
        "        bump_counter(self)\n"
        "    def _b(self):\n"
        "        with self._lock:\n"
        "            self.count -= 1\n",
        {"GC04"}),
    # -- v3: the XLA compile contract + resource lifecycle ---------------
    # GC09: np call, cast and Python branch all concretize jit-traced
    # params in one module
    "pkg/models/bad_tracer.py": (
        "import jax\n"
        "import numpy as np\n\n"
        "@jax.jit\n"
        "def step(w, g):\n"
        "    lr = float(np.mean(g))\n"
        "    if g > 0:\n"
        "        w = w - lr * g\n"
        "    return w\n",
        {"GC09"}),
    # GC09 cross-module: the np call lives in a helper that is only
    # traced because a jit body in ANOTHER module hands it a tracer
    "pkg/ops/helper_np.py": (
        "import numpy as np\n\n"
        "def host_norm(v):\n"
        "    return np.sum(v * v)\n",
        {"GC09"}),
    "pkg/models/bad_jit_cross.py": (
        "import jax\n"
        "from pkg.ops.helper_np import host_norm\n\n"
        "@jax.jit\n"
        "def fused(x):\n"
        "    return host_norm(x * 2.0)\n",
        set()),
    # GC10: a Python scalar literal entering the scan carry
    "pkg/ops/bad_scan.py": (
        "import jax\n\n"
        "def run(xs, w):\n"
        "    def body(carry, x):\n"
        "        w, t = carry\n"
        "        return (w + x, 0.0), w\n"
        "    return jax.lax.scan(body, (w, 0.0), xs)\n",
        {"GC10"}),
    # GC10 cross-module: the body with a dtype-changing carry leaf is
    # imported; only the OTHER module's lax.scan marks it a scan body
    "pkg/ops/scan_body.py": (
        "def body(carry, x):\n"
        "    s, t = carry\n"
        "    return (s + x, t.astype('float32')), s\n",
        {"GC10"}),
    "pkg/models/bad_scan_cross.py": (
        "import jax\n"
        "from pkg.ops.scan_body import body\n\n"
        "def run(xs, s0):\n"
        "    return jax.lax.scan(body, s0, xs)\n",
        set()),
    # GC11: an ops/ scannable step core registered without donation
    "pkg/ops/bad_nodonate.py": (
        "import jax\n\n"
        "def scannable(step, core):\n"
        "    step.core = core\n"
        "    return step\n\n"
        "def make_step():\n"
        "    def core(w, s, t, idx):\n"
        "        return w, s, 0.0\n"
        "    return scannable(jax.jit(core), core)\n",
        {"GC11"}),
    # GC11 cross-module: the factory's donation is declared in another
    # module; the caller reads the donated buffer after the call
    "pkg/ops/donate_factory.py": (
        "import jax\n\n"
        "def make_step(core):\n"
        "    return jax.jit(core, donate_argnums=(0, 1))\n",
        set()),
    "pkg/models/bad_donate_read.py": (
        "from pkg.ops.donate_factory import make_step\n\n"
        "def train(core, w, s, xs):\n"
        "    step = make_step(core)\n"
        "    w2, s2 = step(w, s)\n"
        "    return w2, s2, w.sum()\n",
        {"GC11"}),
    # GC12: straight-line-only close + the HTTPError probe shape
    "pkg/serve/bad_leak.py": (
        "import socket\n"
        "import urllib.error\n"
        "import urllib.request\n\n"
        "def probe(addr):\n"
        "    s = socket.create_connection(addr)\n"
        "    s.sendall(b'ping')\n"
        "    data = s.recv(16)\n"
        "    s.close()\n"
        "    return data\n\n"
        "def fetch(url):\n"
        "    try:\n"
        "        with urllib.request.urlopen(url) as r:\n"
        "            return r.read()\n"
        "    except urllib.error.HTTPError as e:\n"
        "        return e.read()\n",
        {"GC12"}),
    # GC12 cross-module: the acquisition hides behind a helper that
    # RETURNS the fresh socket (returns_resource closure)
    "pkg/io/opener.py": (
        "import socket\n\n"
        "def dial(addr):\n"
        "    return socket.create_connection(addr)\n",
        set()),
    "pkg/serve/bad_cross_leak.py": (
        "from pkg.io.opener import dial\n\n"
        "def ping(addr):\n"
        "    c = dial(addr)\n"
        "    c.sendall(b'x')\n"
        "    return c.recv(4)\n",
        {"GC12"}),
}


def selfcheck() -> int:
    """Prove the gate in both directions before trusting a clean run:
    every rule (including the interprocedural upgrades and GC07/GC08)
    fires on its seeded fixture; a baseline silences them; a fixed
    finding turns its baseline entry stale (nonzero); and the tsan
    lockset sanitizer detects the re-seeded PR 11
    ``last_reload_error`` race while passing its lock-guarded twin."""
    import shutil
    import tempfile
    tmp = tempfile.mkdtemp(prefix="graftcheck_selfcheck_")
    try:
        for rel, (src, _want) in _FIXTURES.items():
            p = os.path.join(tmp, rel)
            os.makedirs(os.path.dirname(p), exist_ok=True)
            with open(p, "w", encoding="utf-8") as f:
                f.write(src)
        findings = run_paths([os.path.join(tmp, "pkg")], root=tmp)
        got = {}
        for f in findings:
            got.setdefault(f.path, set()).add(f.code)
        failures = []
        for rel, (_src, want) in _FIXTURES.items():
            missing = want - got.get(rel, set())
            if missing:
                failures.append(f"{rel}: rule(s) {sorted(missing)} did "
                                f"not fire on the seeded violation")
        if not findings:
            failures.append("no findings at all on the seeded tree")
        # direction 2: baseline silences, then goes stale after a "fix"
        bl = os.path.join(tmp, "baseline.json")
        write_baseline(bl, findings)
        fresh, stale = gate(findings, load_baseline(bl))
        if fresh or stale:
            failures.append("baselined tree did not gate clean")
        kept = [f for f in findings if f.code != "GC03"]
        fresh, stale = gate(kept, load_baseline(bl))
        if not stale:
            failures.append("fixed finding did not turn its baseline "
                            "entry stale")
        # direction 3: the DYNAMIC layer — the lockset sanitizer must
        # flag the re-seeded PR 11 PredictEngine.last_reload_error race
        # (two unguarded writer threads) and stay quiet on the guarded
        # twin; a sanitizer that cannot fail is not a gate
        try:
            from ...testing import tsan
            ok, detail = tsan.selfcheck_race()
            if not ok:
                failures.append(f"tsan selfcheck: {detail}")
            tsan_msg = detail
        except Exception as e:  # noqa: BLE001 — a broken sanitizer
            failures.append(f"tsan selfcheck crashed: "
                            f"{type(e).__name__}: {e}")
            tsan_msg = "unavailable"
        # direction 4: the leak sanitizer (GC12's dynamic twin) must
        # catch a seeded fd leak and pass the closed twin
        try:
            from ...testing import leaktrack
            ok, detail = leaktrack.selfcheck_leak()
            if not ok:
                failures.append(f"leaktrack selfcheck: {detail}")
            leak_msg = detail
        except Exception as e:  # noqa: BLE001 — a broken sanitizer
            failures.append(f"leaktrack selfcheck crashed: "
                            f"{type(e).__name__}: {e}")
            leak_msg = "unavailable"
        if failures:
            for msg in failures:
                print(f"graftcheck --selfcheck FAIL: {msg}",
                      file=sys.stderr)
            return 1
        print(f"graftcheck --selfcheck: {len(findings)} seeded findings "
              f"caught across {len(_FIXTURES)} fixtures (incl. "
              f"cross-module GC01/GC02/GC04 + GC07-GC12); baseline gate "
              f"bidirectional (silences fresh, flags stale); "
              f"tsan: {tsan_msg}; leaktrack: {leak_msg}")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# -- CLI --------------------------------------------------------------------

def _default_paths() -> List[str]:
    """The full repo surface: the installed package tree plus the repo's
    out-of-package Python — tests/, bench.py, the graft entry point —
    so deadline idioms and thread workers in the harness obey the same
    invariants the package does (works from any cwd; paths that don't
    exist in an installed-package context are skipped)."""
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    repo = os.path.dirname(pkg)
    extras = [os.path.join(repo, "tests"),
              os.path.join(repo, "bench.py"),
              os.path.join(repo, "__graft_entry__.py")]
    return [pkg] + [p for p in extras if os.path.exists(p)]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hivemall_tpu.tools.graftcheck",
        description="project-invariant static analyzer "
                    "(docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: the hivemall_tpu "
                         "package + tests/ + bench.py + the graft entry)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: ./graftcheck_baseline"
                         ".json when present)")
    ap.add_argument("--write-baseline", metavar="PATH",
                    help="write current findings as the new baseline and "
                         "exit 0")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--json-out", metavar="PATH", default=None,
                    help="also write the full JSON report (all findings "
                         "+ gate verdict) to PATH — the CI artifact")
    ap.add_argument("--selfcheck", action="store_true",
                    help="prove every rule fires on seeded violations, "
                         "the baseline gate works both ways, and the "
                         "tsan sanitizer flags the seeded race")
    ap.add_argument("--root", default=None,
                    help="path-relativity root for fingerprints "
                         "(default: cwd)")
    ap.add_argument("--no-cache", action="store_true",
                    help="bypass the content-hash findings cache")
    ap.add_argument("--jobs", type=int, default=None, metavar="N",
                    help="worker processes for the parse/summary and "
                         "rule passes (default: CPU count; 1 = serial)")
    ap.add_argument("--fix", action="store_true",
                    help="emit a unified diff fixing the mechanical "
                         "rules (GC02 time.time()->time.monotonic(), "
                         "GC09 np.<fn> -> jnp.<fn> on traced values, "
                         "GC06 annotation insertion)")
    ap.add_argument("--write", action="store_true",
                    help="with --fix: rewrite the files in place "
                         "instead of only printing the diff")
    args = ap.parse_args(argv)

    if args.selfcheck:
        return selfcheck()
    if args.write and not args.fix:
        print("graftcheck: --write requires --fix", file=sys.stderr)
        return 2

    paths = args.paths or _default_paths()
    root = args.root
    if root is None and not args.paths:
        # default scan: relative to the repo root (the package's parent)
        pkg = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        root = os.path.dirname(pkg)
    abs_root = os.path.abspath(root or os.getcwd())
    cache = None
    if not args.no_cache and not args.fix and not args.paths:
        # the default full scan only: an explicit-path scan would drop
        # the cache file in the caller's cwd AND evict the whole-tree
        # cache (the cache is keyed by the scanned file SET)
        cache = os.path.join(abs_root, CACHE_NAME)
    timings: dict = {}
    t_scan = time.perf_counter()
    findings = run_paths(paths, root=root, cache=cache, jobs=args.jobs,
                         timings=timings)
    timings["total_s"] = round(time.perf_counter() - t_scan, 4)

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"graftcheck: wrote {len(findings)} fingerprint(s) to "
              f"{args.write_baseline}")
        return 0

    if args.fix:
        diff, fixed = _apply_fixes(findings, abs_root, args.write)
        if diff:
            sys.stdout.write(diff)
        verb = "rewrote" if args.write else "would fix"
        print(f"graftcheck --fix: {verb} {fixed} finding(s) "
              f"({len(findings)} total; non-mechanical findings need "
              f"human fixes)", file=sys.stderr)
        if args.write:
            return 0
        return 1 if fixed else 0

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists("graftcheck_baseline.json"):
        baseline_path = "graftcheck_baseline.json"
    baseline: List[str] = []
    if baseline_path:
        try:
            baseline = load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"graftcheck: cannot read baseline: {e}",
                  file=sys.stderr)
            return 2
    covered = [os.path.relpath(os.path.abspath(p), abs_root)
               .replace(os.sep, "/") for p in paths]
    fresh, stale = gate(findings, baseline, covered)

    report = {
        "findings": [f.to_json() for f in fresh],
        "baselined": len(findings) - len(fresh),
        "stale_baseline": stale,
        "rulestamp": RULESTAMP,
        "clean": not (fresh or stale),
        #: per-rule + per-phase wall breakdown — the CI budget evidence
        #: (empty phases on a cache replay)
        "wall": timings,
    }
    if args.json_out:
        try:
            with open(args.json_out, "w", encoding="utf-8") as f:
                json.dump(report, f, indent=1)
                f.write("\n")
        except OSError as e:
            print(f"graftcheck: cannot write --json-out: {e}",
                  file=sys.stderr)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        for f in fresh:
            print(f.render())
        for fp in stale:
            print(f"graftcheck: STALE baseline entry (fixed finding must "
                  f"leave the baseline): {fp}")
        n_base = len(findings) - len(fresh)
        status = "clean" if not (fresh or stale) else "FAIL"
        print(f"graftcheck: {status} — {len(fresh)} finding(s)"
              + (f", {n_base} baselined" if n_base else "")
              + (f", {len(stale)} stale baseline entr"
                 + ("y" if len(stale) == 1 else "ies") if stale else ""))
    return 1 if (fresh or stale) else 0
