"""graftcheck — project-invariant static analysis (docs/STATIC_ANALYSIS.md).

The codebase's hardest-won invariants were, until PR 11, enforced only at
runtime: the no-retrace sentinel (obs.devprof) catches a fresh-closure jit
site only after it has burned a compile, the atomic-write idiom
(tmp -> fsync -> ``os.replace``) is a convention copied by hand across
io/, and stub-vs-live registry parity is pinned by a test that must be
updated per section. graftcheck rejects violations at review time
instead, from source, with zero new dependencies (stdlib ``ast`` +
``tokenize`` only). Since PR 12 the analysis is INTERPROCEDURAL: a
project-wide call graph over per-function summaries (:mod:`.interproc`)
follows tainted clocks through helper returns, shared-attribute writes
through methods called from thread targets, and jit-closure factories
across modules — and pairs with the runtime lockset race sanitizer
(:mod:`hivemall_tpu.testing.tsan`) the serve/fleet smokes run under.
Since PR 14 a third layer understands the JAX side of the house —
tracer safety, scan-carry stability, buffer donation (GC09-GC11) —
plus exception-path resource lifetimes (GC12), with the leak census
sanitizer (:mod:`hivemall_tpu.testing.leaktrack`) as GC12's dynamic
twin; the scan itself fans the parse/summary and rule passes across
worker processes.

Rules (each with a fix-hint and a ``# graftcheck: disable=<code>``
suppression; see docs/STATIC_ANALYSIS.md for the full catalog):

========  ===============================================================
GC01      retrace-hazard: jit/``lru_cache`` compile factories defined
          inside functions/loops, jitted closures created AND called
          per-call instead of escaping through a module-level factory,
          and calls to fresh-jit factories in loops / immediately
          invoked (cross-module, via summaries).
GC02      clock-discipline: ``time.time()`` in duration arithmetic
          (subtraction / deadline comparison) where ``time.monotonic()``
          is required — directly, via tainted locals, or via helpers
          whose summaries prove a wall-derived return; legitimate
          wall-clock anchors carry an explicit suppression.
GC03      atomic-write: bare ``open(..., "w"/"wb")`` in io/ or serve/
          outside a tmp -> fsync -> ``os.replace`` helper.
GC04      lock-discipline: instance attributes mutated from more than
          one thread entry point without the owning lock held —
          including writes reached through method calls, with
          locks-held-at-call-site propagation — and ``Lock.acquire()``
          outside a ``with``.
GC05      surface-parity: registry stub constants must key-mirror their
          live provider dict literals; registry section names and stub
          keys must satisfy the ``to_prometheus`` name grammar.
GC06      broad-except: ``except Exception:`` in serve/ and obs/ hot
          paths must name why (a comment on the handler) or be narrowed.
GC07      transfer-discipline: ``np.asarray``/``device_get``/
          ``block_until_ready`` inside per-step loops in models//ops/
          (direct, or one function boundary away).
GC08      thread-lifecycle: self-stored looping threads whose class
          provably lacks a join / poison-pill shutdown path.
GC09      tracer-safety: ``np.*`` calls, ``float()``-family casts,
          ``.item()``/``.tolist()`` and Python branches on parameters
          reachable as TRACED values from a jit/pjit/pmap/shard_map or
          ``lax.scan`` root (worklist closure over call edges; the
          ``np.<fn>`` subset is ``--fix``-able to ``jnp.<fn>``).
GC10      carry-stability: ``lax.scan`` bodies whose returned carry can
          diverge from the input pytree — scalar literals as carry
          leaves, explicit-dtype ``.astype`` on carry leaves,
          length-divergent conditional returns.
GC11      donation-discipline: reads of a ``donate_argnums`` buffer
          after the donating call (factory returns followed
          cross-module) + undonated ops/ ``scannable`` step cores.
GC12      resource-lifecycle: socket/file/mmap/http handles in serve//
          io//parallel/ that can leak on an exception path — no
          with/finally/cleanup-and-reraise, no owner release path
          (helpers RETURNING a fresh resource make their call sites
          acquisitions).
========  ===============================================================

Run ``python -m hivemall_tpu.tools.graftcheck`` from the repo root; CI
wires it into run_tests.sh as a hard gate (``--selfcheck`` proves every
rule fires on seeded violations AND that the tsan sanitizer detects the
re-seeded PR 11 race before the real pass; ``--fix`` emits mechanical
diffs, ``--json-out`` the CI artifact; scans are content-hash cached).
"""

from .engine import (Finding, load_baseline, run_paths, scan_file,
                     write_baseline)
from .rules import RULES

__all__ = ["Finding", "RULES", "run_paths", "scan_file",
           "load_baseline", "write_baseline"]
