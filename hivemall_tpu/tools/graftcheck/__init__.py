"""graftcheck — project-invariant static analysis (docs/STATIC_ANALYSIS.md).

The codebase's hardest-won invariants were, until PR 11, enforced only at
runtime: the no-retrace sentinel (obs.devprof) catches a fresh-closure jit
site only after it has burned a compile, the atomic-write idiom
(tmp -> fsync -> ``os.replace``) is a convention copied by hand across
io/, and stub-vs-live registry parity is pinned by a test that must be
updated per section. graftcheck rejects violations at review time
instead, from source, with zero new dependencies (stdlib ``ast`` +
``tokenize`` only).

Rules (each with a fix-hint and a ``# graftcheck: disable=<code>``
suppression; see docs/STATIC_ANALYSIS.md for the full catalog):

========  ===============================================================
GC01      retrace-hazard: jit/``lru_cache`` compile factories defined
          inside functions/loops, or jitted closures created AND called
          per-call instead of escaping through a module-level factory.
GC02      clock-discipline: ``time.time()`` in duration arithmetic
          (subtraction / deadline comparison) where ``time.monotonic()``
          is required; legitimate wall-clock anchors carry an explicit
          suppression.
GC03      atomic-write: bare ``open(..., "w"/"wb")`` in io/ or serve/
          outside a tmp -> fsync -> ``os.replace`` helper.
GC04      lock-discipline: instance attributes mutated from more than
          one thread entry point without the owning lock held, and
          ``Lock.acquire()`` outside a ``with``.
GC05      surface-parity: registry stub constants must key-mirror their
          live provider dict literals; registry section names and stub
          keys must satisfy the ``to_prometheus`` name grammar.
GC06      broad-except: ``except Exception:`` in serve/ and obs/ hot
          paths must name why (a comment on the handler) or be narrowed.
========  ===============================================================

Run ``python -m hivemall_tpu.tools.graftcheck`` from the repo root; CI
wires it into run_tests.sh as a hard gate (``--selfcheck`` proves the
gate catches seeded violations before the real pass).
"""

from .engine import (Finding, load_baseline, run_paths, scan_file,
                     write_baseline)
from .rules import RULES

__all__ = ["Finding", "RULES", "run_paths", "scan_file",
           "load_baseline", "write_baseline"]
