import sys

from .engine import main

sys.exit(main())
