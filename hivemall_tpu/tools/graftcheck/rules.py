"""graftcheck rule implementations (stdlib ``ast`` only).

Each rule is a function ``(ctx: ModuleContext, project: ProjectIndex) ->
List[Finding]``. The engine builds one :class:`ModuleContext` per file
(parse tree + parent links + comment map) and a :class:`ProjectIndex`
from a cheap first pass over every scanned file (registry stub constants
and their alias functions — the only cross-file state any rule needs).

The rules encode PROJECT invariants, not general style: they must pass
the known-good compile-factory population clean — the ~67 jit/lru_cache
sites across models/, ops/ and parallel/ (floor 60 pinned by
tests/test_graftcheck.py) — along with the atomic-write helpers in io/,
while rejecting the seeded violations in the same test file. When a rule and reality disagree, the
escape hatch is an explicit ``# graftcheck: disable=<code>`` on the
flagged line (or alone on the line above) — intent on the record, not a
silent pass.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["Finding", "ModuleContext", "ProjectIndex", "RULES",
           "collect_project", "run_rules"]


@dataclass
class Finding:
    code: str
    path: str            # '/'-separated path relative to the scan root
    line: int
    col: int
    message: str
    hint: str = ""
    symbol: str = "<module>"

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity for the baseline file: stable across
        unrelated edits above the finding, invalidated when the finding's
        own symbol or message changes (a fixed finding MUST leave the
        baseline — the engine flags the stale entry)."""
        return f"{self.path}::{self.code}::{self.symbol}::{self.message}"

    def render(self) -> str:
        s = f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"
        if self.hint:
            s += f" [fix: {self.hint}]"
        return s


class ModuleContext:
    """One parsed file: tree, parent links, raw lines, comment map."""

    def __init__(self, relpath: str, tree: ast.Module,
                 comments: Dict[int, str]):
        self.relpath = relpath
        self.parts = tuple(relpath.split("/"))
        self.tree = tree
        self.comments = comments          # line -> comment text
        self._parent: Dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parent[id(child)] = parent

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parent.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def qualname(self, node: ast.AST) -> str:
        names = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            cur = self.parent(cur)
        return ".".join(reversed(names)) or "<module>"

    def enclosing_function(self, node: ast.AST) \
            -> Optional[ast.AST]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None


@dataclass
class ProjectIndex:
    """Cross-file state from the engine's first pass."""
    #: STUB const name -> (defining relpath, top-level literal keys)
    stubs: Dict[str, Tuple[str, Tuple[str, ...]]]
    #: alias function name -> STUB const name (e.g. promotion_stub)
    stub_aliases: Dict[str, str]


FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
LOOPS = (ast.For, ast.AsyncFor, ast.While)


def _dec_name(dec: ast.AST) -> str:
    """The rightmost identifier of a (possibly called) decorator."""
    target = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Name):
        return target.id
    return ""


_CACHE_NAMES = {"lru_cache", "_lru_cache", "cache", "cached"}
_FACTORY_NAMES = {"instrument_factory", "_instrument"}


def _is_cache_decorator(dec: ast.AST) -> bool:
    return _dec_name(dec) in _CACHE_NAMES


def _is_memo_decorated(fn: ast.AST) -> bool:
    """lru_cache / instrument_factory on the def: a memoized compile
    factory — jit creations inside it happen once per config key."""
    return any(_dec_name(d) in (_CACHE_NAMES | _FACTORY_NAMES)
               for d in getattr(fn, "decorator_list", []))


def _is_jit_name(node: ast.AST) -> bool:
    return (isinstance(node, ast.Name) and node.id == "jit") or \
        (isinstance(node, ast.Attribute) and node.attr == "jit")


def _is_partial(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and _dec_name(node) in (
        "partial", "_partial")


def _is_jit_creation(node: ast.AST) -> bool:
    """A Call producing a jit-compiled callable: ``jax.jit(f)``,
    ``jit(f)``, or ``partial(jax.jit, ...)(f)``."""
    if not isinstance(node, ast.Call):
        return False
    if _is_jit_name(node.func):
        return True
    if isinstance(node.func, ast.Call) and _is_partial(node.func) \
            and node.func.args and _is_jit_name(node.func.args[0]):
        return True
    return False


def _is_jit_decorator(dec: ast.AST) -> bool:
    if _is_jit_name(dec):
        return True
    if _is_partial(dec) and dec.args and _is_jit_name(dec.args[0]):
        return True
    if isinstance(dec, ast.Call) and _is_jit_name(dec.func):
        return True
    return False


# ---------------------------------------------------------------------------
# GC01 — retrace-hazard
# ---------------------------------------------------------------------------

_GC01_HINT = ("hoist into a module-level factory memoized with lru_cache "
              "+ obs.devprof.instrument_factory, or return/store the "
              "closure instead of re-creating it per call")


def gc01_retrace_hazard(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    out: List[Finding] = []

    def add(node, msg):
        out.append(Finding("GC01", ctx.relpath, node.lineno,
                           node.col_offset, msg, _GC01_HINT,
                           ctx.qualname(node)))

    def chain_memoized(fn) -> bool:
        cur = fn
        while cur is not None:
            if isinstance(cur, FUNCS) and _is_memo_decorated(cur):
                return True
            cur = ctx.parent(cur)
        return False

    def in_loop_below(node, fn) -> bool:
        """Is ``node`` inside a loop that is itself inside ``fn`` (or at
        module level when fn is None)?"""
        for a in ctx.ancestors(node):
            if a is fn:
                return False
            if isinstance(a, LOOPS):
                return True
            if isinstance(a, FUNCS) and a is not fn:
                return False
        return False

    def product_escapes(fn, name: str, skip: ast.AST) -> Tuple[bool, bool]:
        """(called, escapes) for loads of ``name`` in ``fn``'s scope.
        A load used as anything but a call's func position — returned,
        stored on self, passed as an argument, put in a container —
        counts as an escape: the closure outlives this call."""
        called = escapes = False
        for n in ast.walk(fn):
            if not (isinstance(n, ast.Name) and n.id == name
                    and isinstance(n.ctx, ast.Load)):
                continue
            if any(a is skip for a in ctx.ancestors(n)):
                continue                 # the creating statement itself
            p = ctx.parent(n)
            if isinstance(p, ast.Call) and p.func is n:
                called = True
            else:
                escapes = True
        return called, escapes

    for node in ast.walk(ctx.tree):
        # nested lru_cache factory: a fresh cache object per enclosing
        # call — the cache never hits, every call recompiles
        if isinstance(node, FUNCS) \
                and any(_is_cache_decorator(d) for d in node.decorator_list):
            encl = ctx.enclosing_function(node)
            if encl is not None and not chain_memoized(encl):
                add(node, f"lru_cache compile factory '{node.name}' defined "
                          f"inside a function — a fresh cache per call never "
                          f"hits (retrace hazard)")
            continue

        # decorator-form jit on a def nested inside an un-memoized fn:
        # fine when the closure escapes (factory pattern), a hazard when
        # it is only invoked locally or created in a loop
        if isinstance(node, FUNCS) \
                and any(_is_jit_decorator(d) for d in node.decorator_list):
            encl = ctx.enclosing_function(node)
            if encl is None or chain_memoized(encl):
                continue
            if in_loop_below(node, encl):
                add(node, f"jit-compiled closure '{node.name}' created "
                          f"inside a loop (fresh compile per iteration)")
                continue
            called, escapes = product_escapes(encl, node.name, node)
            if called and not escapes:
                add(node, f"jit-compiled closure '{node.name}' created and "
                          f"invoked in the same scope without escaping "
                          f"(fresh compile per call)")
            continue

        if not _is_jit_creation(node):
            continue
        # skip the inner partial(jax.jit,...) of an already-handled
        # creation, and decorator positions (handled above)
        p = ctx.parent(node)
        if isinstance(p, ast.Call) and _is_jit_creation(p):
            continue
        if isinstance(p, FUNCS) and node in p.decorator_list:
            continue
        encl = ctx.enclosing_function(node)
        if encl is None or chain_memoized(encl):
            continue
        if in_loop_below(node, encl):
            add(node, "jit-compiled closure created inside a loop "
                      "(fresh compile per iteration)")
            continue
        # immediate invoke: jax.jit(f)(x) — compiled, called, dropped
        if isinstance(p, ast.Call) and p.func is node:
            add(node, "jit-compiled closure created and invoked inline "
                      "(fresh compile per call)")
            continue
        # named product: track what happens to it in this scope
        stmt = node
        for a in ctx.ancestors(node):
            if isinstance(a, ast.stmt):
                stmt = a
                break
        if isinstance(stmt, ast.Assign) \
                and all(isinstance(t, ast.Name) for t in stmt.targets):
            called, escapes = product_escapes(
                encl, stmt.targets[0].id, stmt)
            if called and not escapes:
                add(node, f"jit-compiled closure "
                          f"'{stmt.targets[0].id}' created and invoked in "
                          f"the same scope without escaping (fresh compile "
                          f"per call)")
        # Return / self.attr store / argument position: escapes — OK
    return out


# ---------------------------------------------------------------------------
# GC02 — clock-discipline
# ---------------------------------------------------------------------------

_GC02_HINT = ("use time.monotonic() for durations and deadlines; a "
              "deliberate wall-clock anchor (chrome-trace ts, bundle "
              "mtime) must carry # graftcheck: disable=GC02")


def _has_bare_time_import(tree: ast.Module) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module == "time":
            if any(a.name == "time" for a in n.names):
                return True
    return False


def gc02_clock_discipline(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    out: List[Finding] = []
    bare = _has_bare_time_import(ctx.tree)

    def is_wall_call(n: ast.AST) -> bool:
        if not isinstance(n, ast.Call):
            return False
        f = n.func
        if isinstance(f, ast.Attribute) and f.attr == "time" \
                and isinstance(f.value, ast.Name) and f.value.id == "time":
            return True
        return bare and isinstance(f, ast.Name) and f.id == "time"

    def contains_wall(n: ast.AST) -> bool:
        return any(is_wall_call(x) for x in ast.walk(n))

    def contains_tainted(n: ast.AST, tainted: Set[str]) -> bool:
        return any(isinstance(x, ast.Name) and x.id in tainted
                   and isinstance(x.ctx, ast.Load) for x in ast.walk(n))

    def scan_scope(scope: ast.AST) -> None:
        """One function (or the module body): taint names assigned from
        time.time(), then flag subtraction / ordered comparison involving
        the wall clock. Nested functions are separate scopes."""
        tainted: Set[str] = set()
        body_nodes = []
        stack = list(scope.body)
        while stack:
            n = stack.pop()
            body_nodes.append(n)
            if isinstance(n, FUNCS + (ast.Lambda,)):
                continue                 # separate scope
            stack.extend(ast.iter_child_nodes(n))
        for n in body_nodes:
            if isinstance(n, ast.Assign) and contains_wall(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
            elif isinstance(n, ast.AnnAssign) and n.value is not None \
                    and contains_wall(n.value) \
                    and isinstance(n.target, ast.Name):
                tainted.add(n.target.id)
        flagged: Set[int] = set()        # one finding per line — a
        for n in body_nodes:             # deadline compare often wraps
            sides: List[ast.AST] = []    # the subtraction it contains
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub):
                sides = [n.left, n.right]
            elif isinstance(n, ast.Compare) and all(
                    isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
                    for op in n.ops):   # ordered = deadline semantics;
                sides = [n.left] + list(n.comparators)   # `is None` etc.
            if not sides or n.lineno in flagged:         # are not
                continue
            direct = any(contains_wall(s) for s in sides)
            via_name = any(contains_tainted(s, tainted) for s in sides)
            if direct or via_name:
                flagged.add(n.lineno)
                what = "time.time()" if direct \
                    else "a value derived from time.time()"
                kind = "subtraction" if isinstance(n, ast.BinOp) \
                    else "deadline comparison"
                out.append(Finding(
                    "GC02", ctx.relpath, n.lineno, n.col_offset,
                    f"{what} used in duration {kind} — wall clock is not "
                    f"monotonic (NTP steps corrupt intervals)",
                    _GC02_HINT, ctx.qualname(n)))

    scan_scope(ctx.tree)
    for n in ast.walk(ctx.tree):
        if isinstance(n, FUNCS):
            scan_scope(n)
    return out


# ---------------------------------------------------------------------------
# GC03 — atomic-write
# ---------------------------------------------------------------------------

_GC03_HINT = ("route through io.checkpoint._atomic_write_json or the "
              "tmp -> fsync -> os.replace idiom (crash mid-write must "
              "never leave a torn file)")
_GC03_DIRS = {"io", "serve"}


def _calls_os_replace(fn: Optional[ast.AST], tree: ast.Module) -> bool:
    scope = fn if fn is not None else tree
    for n in ast.walk(scope):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("replace", "rename"):
            v = n.func.value
            if isinstance(v, ast.Name) and v.id == "os":
                return True
    return False


def gc03_atomic_write(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    if not (_GC03_DIRS & set(ctx.parts[:-1])):
        return []
    out: List[Finding] = []
    for n in ast.walk(ctx.tree):
        if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "open"):
            continue
        mode = None
        if len(n.args) >= 2 and isinstance(n.args[1], ast.Constant):
            mode = n.args[1].value
        for kw in n.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if not (isinstance(mode, str) and "w" in mode):
            continue
        fn = ctx.enclosing_function(n)
        if _calls_os_replace(fn, ctx.tree):
            continue                     # the atomic helper itself
        out.append(Finding(
            "GC03", ctx.relpath, n.lineno, n.col_offset,
            f'bare open(..., "{mode}") in {ctx.parts[-2]}/ outside a '
            f"tmp -> fsync -> os.replace helper (non-atomic write to a "
            f"checkpoint/cache/pointer path)",
            _GC03_HINT, ctx.qualname(n)))
    return out


# ---------------------------------------------------------------------------
# GC04 — lock-discipline
# ---------------------------------------------------------------------------

_GC04_HINT = ("hold the owning lock (with self._lock:) around the write, "
              "or annotate the single-writer argument with "
              "# graftcheck: disable=GC04")
_LOCKISH = re.compile(r"lock|mutex", re.IGNORECASE)


def _is_thread_ctor(call: ast.Call) -> bool:
    return _dec_name(call) == "Thread"


def gc04_lock_discipline(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    out: List[Finding] = []

    # sub-rule: Lock.acquire() outside a with — with-discipline makes
    # release unconditional across every exit path
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "acquire":
            try:
                owner = ast.unparse(n.func.value)
            except Exception:  # noqa: BLE001 — unparse of odd nodes
                owner = ""
            if _LOCKISH.search(owner):
                out.append(Finding(
                    "GC04", ctx.relpath, n.lineno, n.col_offset,
                    f"{owner}.acquire() outside a with-statement — an "
                    f"exception between acquire and release deadlocks "
                    f"every other thread",
                    "use `with <lock>:` so release is unconditional",
                    ctx.qualname(n)))

    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        base_names = []
        for b in cls.bases:
            try:
                base_names.append(ast.unparse(b))
            except Exception:  # noqa: BLE001 — unparse of odd nodes
                pass
        # thread entry points: methods handed to Thread(target=...),
        # run() on Thread subclasses, do_* handlers on HTTP handler
        # classes — code that executes on a thread other than the
        # constructing one
        entries: List[Tuple[str, ast.AST]] = []
        methods = {m.name: m for m in cls.body if isinstance(m, FUNCS)}
        for n in ast.walk(cls):
            if not (isinstance(n, ast.Call) and _is_thread_ctor(n)):
                continue
            for kw in n.keywords:
                if kw.arg != "target":
                    continue
                t = kw.value
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" and t.attr in methods:
                    entries.append((t.attr, methods[t.attr]))
                elif isinstance(t, ast.Name):
                    # nested closure target: find its def in the class
                    for d in ast.walk(cls):
                        if isinstance(d, FUNCS) and d.name == t.id \
                                and ctx.enclosing_function(d) is not None:
                            host = ctx.enclosing_function(d)
                            entries.append(
                                (f"{getattr(host, 'name', '?')}.{d.name}",
                                 d))
        if any(b.endswith("Thread") for b in base_names) \
                and "run" in methods:
            entries.append(("run", methods["run"]))
        if any("RequestHandler" in b for b in base_names):
            entries.extend((name, m) for name, m in methods.items()
                           if name.startswith("do_"))
        if len(entries) < 2:
            continue
        seen = []
        uniq = []
        for name, node in entries:
            if id(node) not in seen:
                seen.append(id(node))
                uniq.append((name, node))
        if len(uniq) < 2:
            continue

        def under_lock(n: ast.AST, top: ast.AST) -> bool:
            for a in ctx.ancestors(n):
                if isinstance(a, ast.With):
                    for item in a.items:
                        try:
                            src = ast.unparse(item.context_expr)
                        except Exception:  # noqa: BLE001 — odd nodes
                            src = ""
                        if _LOCKISH.search(src):
                            return True
                if a is top:
                    break
            return False

        # attr -> entry-context name -> [(write node, guarded)]
        writes: Dict[str, Dict[str, List[Tuple[ast.AST, bool]]]] = {}
        for name, node in uniq:
            for n in ast.walk(node):
                tgt = None
                if isinstance(n, (ast.Assign,)):
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            tgt = t
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign)) \
                        and isinstance(n.target, ast.Attribute) \
                        and isinstance(n.target.value, ast.Name) \
                        and n.target.value.id == "self":
                    tgt = n.target
                if tgt is None:
                    continue
                writes.setdefault(tgt.attr, {}).setdefault(name, []) \
                    .append((n, under_lock(n, node)))
        for attr, by_entry in writes.items():
            if len(by_entry) < 2:
                continue
            for entry_name, sites in by_entry.items():
                for n, guarded in sites:
                    if guarded:
                        continue
                    others = sorted(e for e in by_entry if e != entry_name)
                    out.append(Finding(
                        "GC04", ctx.relpath, n.lineno, n.col_offset,
                        f"self.{attr} written from thread entry point "
                        f"'{entry_name}' without the owning lock, and "
                        f"also written from {', '.join(others)} — "
                        f"unsynchronized multi-thread mutation",
                        _GC04_HINT, ctx.qualname(n)))
    return out


# ---------------------------------------------------------------------------
# GC05 — surface-parity
# ---------------------------------------------------------------------------

_GC05_NAME_RE = re.compile(r"^[A-Za-z0-9_]+$")
_GC05_HINT = ("registry section names and stub keys become Prometheus "
              "metric name parts — [A-Za-z0-9_] only, and stub/live key "
              "sets must mirror (tests/test_obs.py pins the runtime "
              "side; this is the source-level gate)")


def _stub_defs(tree: ast.Module) -> Dict[str, Tuple[ast.AST,
                                                    Tuple[str, ...]]]:
    out = {}
    for n in tree.body:
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and n.targets[0].id.endswith("_STUB") \
                and isinstance(n.value, ast.Dict):
            keys = tuple(k.value for k in n.value.keys
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str))
            out[n.targets[0].id] = (n, keys)
    return out


def collect_project(contexts: List[ModuleContext]) -> ProjectIndex:
    """First pass: stub constants + their alias functions (a module-level
    def whose body references exactly one ``*_STUB`` name, e.g.
    ``serve.promote.promotion_stub``)."""
    stubs: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
    aliases: Dict[str, str] = {}
    for ctx in contexts:
        for name, (node, keys) in _stub_defs(ctx.tree).items():
            stubs[name] = (ctx.relpath, keys)
        for n in ctx.tree.body:
            if not isinstance(n, FUNCS):
                continue
            refs = {x.id for x in ast.walk(n)
                    if isinstance(x, ast.Name) and x.id.endswith("_STUB")}
            if len(refs) == 1:
                aliases[n.name] = refs.pop()
    return ProjectIndex(stubs=stubs, stub_aliases=aliases)


def _literal_keys_of(fn: ast.AST, ctx: ModuleContext,
                     project: ProjectIndex, stub_name: str):
    """(unconditional_keys, all_keys, dynamic, seeded) for the dict the
    live provider RETURNS: dict literals assigned to a returned name (or
    returned directly), ``d.update({...})`` calls and constant subscript
    assigns on it. Dicts bound to other locals (nested per-window
    payloads etc.) do not count. ``dynamic`` = a non-literal update or
    non-constant key feeds the dict (key set not statically closed);
    ``seeded`` = the dict starts as a copy of the stub."""
    uncond: Set[str] = set()
    allk: Set[str] = set()
    dynamic = seeded = False

    def conditional(n: ast.AST) -> bool:
        for a in ctx.ancestors(n):
            if a is fn:
                return False
            if isinstance(a, (ast.If, ast.Try, ast.IfExp)):
                return True
        return False

    def eat_dict(d: ast.Dict, cond: bool) -> None:
        nonlocal dynamic
        for k in d.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                allk.add(k.value)
                if not cond:
                    uncond.add(k.value)
            else:
                dynamic = True           # **spread or computed key

    nodes = []
    stack = list(fn.body)
    while stack:
        x = stack.pop()
        if isinstance(x, FUNCS + (ast.Lambda,)):
            continue                     # nested scope builds other dicts
        nodes.append(x)
        stack.extend(ast.iter_child_nodes(x))

    returned: Set[str] = set()           # names the provider returns
    for n in nodes:
        if isinstance(n, ast.Return) and isinstance(n.value, ast.Name):
            returned.add(n.value.id)

    def targets_of(n: ast.Assign):
        return [t.id for t in n.targets if isinstance(t, ast.Name)]

    for n in nodes:
        if isinstance(n, (ast.Assign, ast.AnnAssign)):
            v = n.value
            names = targets_of(n) if isinstance(n, ast.Assign) else (
                [n.target.id] if isinstance(n.target, ast.Name) else [])
            if v is not None and returned & set(names):
                if isinstance(v, ast.Dict):
                    eat_dict(v, conditional(n))
                if isinstance(v, ast.Call):
                    callee = _dec_name(v)
                    if project.stub_aliases.get(callee) == stub_name:
                        seeded = True
                    if callee == "dict" and v.args \
                            and isinstance(v.args[0], ast.Name) \
                            and v.args[0].id == stub_name:
                        seeded = True
            # d["k"] = v on the returned dict
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in returned:
                        s = t.slice
                        if isinstance(s, ast.Constant) \
                                and isinstance(s.value, str):
                            allk.add(s.value)
                            if not conditional(n):
                                uncond.add(s.value)
                        else:
                            dynamic = True
        elif isinstance(n, ast.Return) and isinstance(n.value, ast.Dict):
            eat_dict(n.value, conditional(n))
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "update" \
                and isinstance(n.func.value, ast.Name) \
                and n.func.value.id in returned:
            if n.args and isinstance(n.args[0], ast.Dict):
                eat_dict(n.args[0], conditional(n))
            else:
                dynamic = True
    return uncond, allk, dynamic, seeded


def gc05_surface_parity(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    out: List[Finding] = []

    # (b) name grammar: registry.register("<literal>", ...) everywhere,
    # and stub-dict keys (they all become /metrics name parts)
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "register" \
                and "registry" in _dec_name(n.func.value).lower():
            if n.args and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                name = n.args[0].value
                if not _GC05_NAME_RE.match(name):
                    out.append(Finding(
                        "GC05", ctx.relpath, n.lineno, n.col_offset,
                        f"registry section name {name!r} violates the "
                        f"to_prometheus name grammar ([A-Za-z0-9_] only)",
                        _GC05_HINT, ctx.qualname(n)))
    for stub_name, (node, keys) in _stub_defs(ctx.tree).items():
        bad = [k for k in keys if not _GC05_NAME_RE.match(k)]
        # nested dict literal keys feed metric names too
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict) and sub is not getattr(
                    node, "value", None):
                bad.extend(k.value for k in sub.keys
                           if isinstance(k, ast.Constant)
                           and isinstance(k.value, str)
                           and not _GC05_NAME_RE.match(k.value))
        for k in bad:
            out.append(Finding(
                "GC05", ctx.relpath, node.lineno, node.col_offset,
                f"stub {stub_name} key {k!r} violates the to_prometheus "
                f"name grammar ([A-Za-z0-9_] only)",
                _GC05_HINT, stub_name))

    # (a) stub-vs-live key parity: find provider closures referencing
    # exactly one stub and calling exactly one *_section method, then
    # compare that method's literal key set against the stub
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, FUNCS):
            continue
        refs = set()
        for x in ast.walk(fn):
            if isinstance(x, ast.Name) and x.id.endswith("_STUB"):
                refs.add(x.id)
            elif isinstance(x, ast.Call) \
                    and _dec_name(x) in project.stub_aliases:
                refs.add(project.stub_aliases[_dec_name(x)])
        section_calls = {x.func.attr for x in ast.walk(fn)
                         if isinstance(x, ast.Call)
                         and isinstance(x.func, ast.Attribute)
                         and x.func.attr.endswith("_section")}
        if len(refs) != 1 or len(section_calls) != 1:
            continue
        stub_name = refs.pop()
        if stub_name not in project.stubs:
            continue
        method_name = section_calls.pop()
        cls = None
        for a in ctx.ancestors(fn):
            if isinstance(a, ast.ClassDef):
                cls = a
                break
        if cls is None:
            continue
        live = next((m for m in cls.body if isinstance(m, FUNCS)
                     and m.name == method_name), None)
        if live is None or live is fn:
            continue
        stub_keys = set(project.stubs[stub_name][1])
        uncond, allk, dynamic, seeded = _literal_keys_of(
            live, ctx, project, stub_name)
        for k in sorted(uncond - stub_keys):
            out.append(Finding(
                "GC05", ctx.relpath, live.lineno, live.col_offset,
                f"live provider '{cls.name}.{method_name}' emits key "
                f"{k!r} absent from {stub_name} — stub/live key drift "
                f"(gauges appear and vanish across subsystem lifecycle)",
                _GC05_HINT, f"{cls.name}.{method_name}"))
        if not (dynamic or seeded):
            for k in sorted(stub_keys - allk):
                out.append(Finding(
                    "GC05", ctx.relpath, live.lineno, live.col_offset,
                    f"{stub_name} key {k!r} never emitted by live "
                    f"provider '{cls.name}.{method_name}' — stub/live "
                    f"key drift",
                    _GC05_HINT, f"{cls.name}.{method_name}"))
    return out


# ---------------------------------------------------------------------------
# GC06 — broad-except discipline (serve/ and obs/ hot paths)
# ---------------------------------------------------------------------------

_GC06_DIRS = {"serve", "obs"}
_GC06_HINT = ("narrow the exception type, or add a trailing comment on "
              "the handler naming why failure isolation is required "
              "(obs must never take serving down, etc.)")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [getattr(e, "id", getattr(e, "attr", "")) for e in t.elts]
    else:
        names = [getattr(t, "id", getattr(t, "attr", ""))]
    return any(n in ("Exception", "BaseException") for n in names)


def gc06_broad_except(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    if not (_GC06_DIRS & set(ctx.parts[:-1])):
        return []
    out: List[Finding] = []
    for n in ast.walk(ctx.tree):
        if not (isinstance(n, ast.ExceptHandler) and _is_broad(n)):
            continue
        first_body = n.body[0].lineno if n.body else n.lineno
        annotated = any(line in ctx.comments
                        for line in range(n.lineno, first_body + 1))
        if annotated:
            continue
        out.append(Finding(
            "GC06", ctx.relpath, n.lineno, n.col_offset,
            "broad `except Exception` without a why-comment — silent "
            "catch-alls in serving/observability hot paths hide real "
            "failures",
            _GC06_HINT, ctx.qualname(n)))
    return out


#: rule registry: code -> (function, one-line description)
RULES = {
    "GC01": (gc01_retrace_hazard,
             "retrace-hazard: per-call jit closures / nested compile "
             "factories"),
    "GC02": (gc02_clock_discipline,
             "clock-discipline: time.time() in duration arithmetic"),
    "GC03": (gc03_atomic_write,
             "atomic-write: bare write-open in io//serve/ outside the "
             "tmp->fsync->os.replace idiom"),
    "GC04": (gc04_lock_discipline,
             "lock-discipline: unsynchronized multi-thread attribute "
             "mutation / acquire() without with"),
    "GC05": (gc05_surface_parity,
             "surface-parity: stub/live registry key drift + Prometheus "
             "name grammar"),
    "GC06": (gc06_broad_except,
             "broad-except: unannotated `except Exception` in serve//obs/"),
}


def run_rules(ctx: ModuleContext, project: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for code, (fn, _desc) in RULES.items():
        for f in fn(ctx, project):
            # nested provider closures can satisfy an associator twice
            # (the closure AND its enclosing method) — one finding per
            # (line, code, message) is enough
            key = (f.code, f.line, f.message)
            if key not in seen:
                seen.add(key)
                findings.append(f)
    return findings
