"""graftcheck rule implementations (stdlib ``ast`` only).

Each rule is a function ``(ctx: ModuleContext, project: ProjectIndex) ->
List[Finding]``. The engine builds one :class:`ModuleContext` per file
(parse tree + parent links + comment map) and a :class:`ProjectIndex`
from a first pass over every scanned file: registry stub constants and
their alias functions, plus the INTERPROCEDURAL summary index
(:mod:`.interproc`) — a project-wide call graph with per-function
summaries (returns-tainted, param-escapes, locks-held-at-call) that
lets GC02 follow a ``time.time()`` value through helper returns, GC04
follow shared-attribute writes through methods called from thread
targets, and GC01 track jit-closure factories across modules.

The rules encode PROJECT invariants, not general style: they must pass
the known-good compile-factory population clean — the ~67 jit/lru_cache
sites across models/, ops/ and parallel/ (floor 60 pinned by
tests/test_graftcheck.py) — along with the atomic-write helpers in io/,
while rejecting the seeded violations in the same test file. When a rule and reality disagree, the
escape hatch is an explicit ``# graftcheck: disable=<code>`` on the
flagged line (or alone on the line above) — intent on the record, not a
silent pass.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import interproc
from .interproc import (FUNCS, LOOPS, LOCKISH, InterProcIndex,
                        collect_entry_writes, dec_name,
                        is_cache_decorator, is_jit_creation,
                        is_jit_decorator, is_memo_decorated,
                        is_thread_ctor, is_transfer_call, under_lock)

import re

__all__ = ["Finding", "ModuleContext", "ProjectIndex", "RULES",
           "RULESTAMP", "collect_project", "run_rules"]

#: bumped whenever ANY rule's behavior changes — invalidates the
#: engine's content-hash findings cache wholesale (a stale cache must
#: never outvote an upgraded rule)
RULESTAMP = "graftcheck-v2.2"


@dataclass
class Finding:
    code: str
    path: str            # '/'-separated path relative to the scan root
    line: int
    col: int
    message: str
    hint: str = ""
    symbol: str = "<module>"
    #: mechanical-fix payload (``--fix``): rule-specific. GC02 —
    #: source lines on which ``time.time()`` must become
    #: ``time.monotonic()``; GC06 — the handler line to annotate.
    fix_kind: Optional[str] = None
    fix_lines: Tuple[int, ...] = ()

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity for the baseline file: stable across
        unrelated edits above the finding, invalidated when the finding's
        own symbol or message changes (a fixed finding MUST leave the
        baseline — the engine flags the stale entry)."""
        return f"{self.path}::{self.code}::{self.symbol}::{self.message}"

    def render(self) -> str:
        s = f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"
        if self.hint:
            s += f" [fix: {self.hint}]"
        return s

    def to_json(self) -> dict:
        d = {k: v for k, v in vars(self).items()
             if k not in ("fix_kind", "fix_lines")}
        d["fingerprint"] = self.fingerprint
        d["fix_kind"] = self.fix_kind
        d["fix_lines"] = list(self.fix_lines)
        return d


class ModuleContext:
    """One parsed file: tree, parent links, raw lines, comment map."""

    def __init__(self, relpath: str, tree: ast.Module,
                 comments: Dict[int, str]):
        self.relpath = relpath
        self.parts = tuple(relpath.split("/"))
        self.tree = tree
        self.comments = comments          # line -> comment text
        self._parent: Dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parent[id(child)] = parent

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parent.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def qualname(self, node: ast.AST) -> str:
        names = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            cur = self.parent(cur)
        return ".".join(reversed(names)) or "<module>"

    def enclosing_function(self, node: ast.AST) \
            -> Optional[ast.AST]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def enclosing_class_name(self, node: ast.AST) -> Optional[str]:
        for a in self.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a.name
        return None

    def is_test_module(self) -> bool:
        """tests/ and test_*.py files: deliberate ad-hoc compiles there
        are not production retrace hazards (GC01 skips them)."""
        return self.parts[0] == "tests" \
            or self.parts[-1].startswith("test_")


@dataclass
class ProjectIndex:
    """Cross-file state from the engine's first pass."""
    #: STUB const name -> (defining relpath, top-level literal keys)
    stubs: Dict[str, Tuple[str, Tuple[str, ...]]]
    #: alias function name -> STUB const name (e.g. promotion_stub)
    stub_aliases: Dict[str, str]
    #: interprocedural summaries + call graph (None only when the
    #: summary pass failed — rules degrade to intra-module behavior)
    interproc: Optional[InterProcIndex] = field(default=None)

    def resolver_for(self, ctx: "ModuleContext"):
        """``resolve(call_node, class_name, self_name) -> summary|None``
        bound to ``ctx``'s module, or None without an interproc index."""
        idx = self.interproc
        if idx is None:
            return None
        mi = idx.modules_by_path.get(ctx.relpath)
        if mi is None:
            return None

        def resolve(call, class_name, self_name):
            try:
                fid = idx.resolve_call(mi, call, class_name, self_name)
            except Exception:  # noqa: BLE001 — degrade to unknown
                return None
            return idx.functions.get(fid) if fid is not None else None

        return resolve


def _scope_identity(ctx: ModuleContext, fn: Optional[ast.AST]) \
        -> Tuple[Optional[str], Optional[str]]:
    """(class_name, self_name) for resolving ``self.x()`` calls inside
    ``fn`` — direct methods use their first arg, closures nested under a
    class capture the literal ``self``."""
    if fn is None:
        return None, None
    cls = ctx.enclosing_class_name(fn)
    if cls is None:
        return None, None
    parent = ctx.parent(fn)
    if isinstance(parent, ast.ClassDef):
        args = fn.args
        params = list(args.posonlyargs) + list(args.args)
        if params and not any(dec_name(d) == "staticmethod"
                              for d in fn.decorator_list):
            return cls, params[0].arg
        return cls, None
    return cls, "self"


# ---------------------------------------------------------------------------
# GC01 — retrace-hazard
# ---------------------------------------------------------------------------

_GC01_HINT = ("hoist into a module-level factory memoized with lru_cache "
              "+ obs.devprof.instrument_factory, or return/store the "
              "closure instead of re-creating it per call")


def gc01_retrace_hazard(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    if ctx.is_test_module():
        return []    # tests compile ad hoc by design
    out: List[Finding] = []
    resolve = project.resolver_for(ctx)

    def add(node, msg):
        out.append(Finding("GC01", ctx.relpath, node.lineno,
                           node.col_offset, msg, _GC01_HINT,
                           ctx.qualname(node)))

    def chain_memoized(fn) -> bool:
        cur = fn
        while cur is not None:
            if isinstance(cur, FUNCS) and is_memo_decorated(cur):
                return True
            cur = ctx.parent(cur)
        return False

    def in_loop_below(node, fn) -> bool:
        """Is ``node`` inside a loop that is itself inside ``fn`` (or at
        module level when fn is None)?"""
        for a in ctx.ancestors(node):
            if a is fn:
                return False
            if isinstance(a, LOOPS):
                return True
            if isinstance(a, FUNCS) and a is not fn:
                return False
        return False

    def product_escapes(fn, name: str, skip: ast.AST) -> Tuple[bool, bool]:
        """(called, escapes) for loads of ``name`` in ``fn``'s scope.
        A load used as anything but a call's func position — returned,
        stored on self, passed as an argument, put in a container —
        counts as an escape: the closure outlives this call."""
        called = escapes = False
        for n in ast.walk(fn):
            if not (isinstance(n, ast.Name) and n.id == name
                    and isinstance(n.ctx, ast.Load)):
                continue
            if any(a is skip for a in ctx.ancestors(n)):
                continue                 # the creating statement itself
            p = ctx.parent(n)
            if isinstance(p, ast.Call) and p.func is n:
                called = True
            else:
                escapes = True
        return called, escapes

    for node in ast.walk(ctx.tree):
        # nested lru_cache factory: a fresh cache object per enclosing
        # call — the cache never hits, every call recompiles
        if isinstance(node, FUNCS) \
                and any(is_cache_decorator(d) for d in node.decorator_list):
            encl = ctx.enclosing_function(node)
            if encl is not None and not chain_memoized(encl):
                add(node, f"lru_cache compile factory '{node.name}' defined "
                          f"inside a function — a fresh cache per call never "
                          f"hits (retrace hazard)")
            continue

        # decorator-form jit on a def nested inside an un-memoized fn:
        # fine when the closure escapes (factory pattern), a hazard when
        # it is only invoked locally or created in a loop
        if isinstance(node, FUNCS) \
                and any(is_jit_decorator(d) for d in node.decorator_list):
            encl = ctx.enclosing_function(node)
            if encl is None or chain_memoized(encl):
                continue
            if in_loop_below(node, encl):
                add(node, f"jit-compiled closure '{node.name}' created "
                          f"inside a loop (fresh compile per iteration)")
                continue
            called, escapes = product_escapes(encl, node.name, node)
            if called and not escapes:
                add(node, f"jit-compiled closure '{node.name}' created and "
                          f"invoked in the same scope without escaping "
                          f"(fresh compile per call)")
            continue

        # interprocedural: a call to a FACTORY whose summary says it
        # returns a fresh jit closure per call — the per-call compile
        # hides behind the function boundary (cross-module included)
        if isinstance(node, ast.Call) and resolve is not None \
                and not is_jit_creation(node):
            encl = ctx.enclosing_function(node)
            cls_name, self_name = _scope_identity(ctx, encl)
            s = resolve(node, cls_name, self_name)
            if s is not None and s.returns_fresh_jit \
                    and not (encl is not None and chain_memoized(encl)) \
                    and (ctx.relpath, ctx.qualname(encl or node)) != s.fid:
                p = ctx.parent(node)
                if in_loop_below(node, encl):
                    add(node, f"call to jit-closure factory "
                              f"'{s.name}' inside a loop — a fresh "
                              f"compile per iteration hides behind the "
                              f"function boundary")
                    continue
                if isinstance(p, ast.Call) and p.func is node:
                    add(node, f"jit-closure factory '{s.name}' called "
                              f"and its product invoked inline (fresh "
                              f"compile per call across the function "
                              f"boundary)")
                    continue

        if not is_jit_creation(node):
            continue
        # skip the inner partial(jax.jit,...) of an already-handled
        # creation, and decorator positions (handled above)
        p = ctx.parent(node)
        if isinstance(p, ast.Call) and is_jit_creation(p):
            continue
        if isinstance(p, FUNCS) and node in p.decorator_list:
            continue
        encl = ctx.enclosing_function(node)
        if encl is None or chain_memoized(encl):
            continue
        if in_loop_below(node, encl):
            add(node, "jit-compiled closure created inside a loop "
                      "(fresh compile per iteration)")
            continue
        # immediate invoke: jax.jit(f)(x) — compiled, called, dropped
        if isinstance(p, ast.Call) and p.func is node:
            add(node, "jit-compiled closure created and invoked inline "
                      "(fresh compile per call)")
            continue
        # named product: track what happens to it in this scope
        stmt = node
        for a in ctx.ancestors(node):
            if isinstance(a, ast.stmt):
                stmt = a
                break
        if isinstance(stmt, ast.Assign) \
                and all(isinstance(t, ast.Name) for t in stmt.targets):
            called, escapes = product_escapes(
                encl, stmt.targets[0].id, stmt)
            if called and not escapes:
                add(node, f"jit-compiled closure "
                          f"'{stmt.targets[0].id}' created and invoked in "
                          f"the same scope without escaping (fresh compile "
                          f"per call)")
        # Return / self.attr store / argument position: escapes — OK
    return out


# ---------------------------------------------------------------------------
# GC02 — clock-discipline
# ---------------------------------------------------------------------------

_GC02_HINT = ("use time.monotonic() for durations and deadlines; a "
              "deliberate wall-clock anchor (chrome-trace ts, bundle "
              "mtime) must carry # graftcheck: disable=GC02")


def _has_bare_time_import(tree: ast.Module) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module == "time":
            if any(a.name == "time" for a in n.names):
                return True
    return False


def gc02_clock_discipline(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    out: List[Finding] = []
    bare = _has_bare_time_import(ctx.tree)
    resolve = project.resolver_for(ctx)

    def is_wall_call(n: ast.AST) -> bool:
        if not isinstance(n, ast.Call):
            return False
        f = n.func
        if isinstance(f, ast.Attribute) and f.attr == "time" \
                and isinstance(f.value, ast.Name) and f.value.id == "time":
            return True
        return bare and isinstance(f, ast.Name) and f.id == "time"

    def contains_wall(n: ast.AST) -> bool:
        return any(is_wall_call(x) for x in ast.walk(n))

    def helper_wall_name(n: ast.AST, cls_name, self_name) \
            -> Optional[str]:
        """Name of a called helper whose summary proves it RETURNS a
        time.time()-derived value (the interprocedural upgrade)."""
        if resolve is None:
            return None
        for x in ast.walk(n):
            if isinstance(x, ast.Call) and not is_wall_call(x):
                s = resolve(x, cls_name, self_name)
                if s is not None and s.returns_wall:
                    return s.name
        return None

    def contains_tainted(n: ast.AST, tainted: Set[str]) -> bool:
        return any(isinstance(x, ast.Name) and x.id in tainted
                   and isinstance(x.ctx, ast.Load) for x in ast.walk(n))

    def scan_scope(scope: ast.AST) -> None:
        """One function (or the module body): taint names assigned from
        time.time() — directly or via a helper whose summary returns a
        wall value — then flag subtraction / ordered comparison involving
        the wall clock. Nested functions are separate scopes."""
        fn = scope if isinstance(scope, FUNCS) else None
        cls_name, self_name = _scope_identity(ctx, fn)
        tainted: Set[str] = set()        # names carrying wall taint
        wall_lines: Dict[str, Set[int]] = {}   # name -> EVERY source
        #                 line assigning it from a literal wall call (a
        #                 name can be re-assigned; --fix must rewrite
        #                 all of them or the rescan still fails)
        body_nodes = []
        stack = list(scope.body)
        while stack:
            n = stack.pop()
            body_nodes.append(n)
            if isinstance(n, FUNCS + (ast.Lambda,)):
                continue                 # separate scope
            stack.extend(ast.iter_child_nodes(n))
        for n in body_nodes:
            tgt_names: List[str] = []
            value = None
            if isinstance(n, ast.Assign):
                tgt_names = [t.id for t in n.targets
                             if isinstance(t, ast.Name)]
                value = n.value
            elif isinstance(n, ast.AnnAssign) and n.value is not None \
                    and isinstance(n.target, ast.Name):
                tgt_names = [n.target.id]
                value = n.value
            if not tgt_names or value is None:
                continue
            literal = contains_wall(value)
            if literal or helper_wall_name(value, cls_name, self_name):
                for t in tgt_names:
                    tainted.add(t)
                    if literal:          # helper-tainted lines carry no
                        wall_lines.setdefault(t, set()).add(n.lineno)
                    #                      time.time() literal to rewrite
        dur_nodes: List[Tuple[ast.AST, List[ast.AST]]] = []
        for n in body_nodes:             # a deadline compare often wraps
            sides: List[ast.AST] = []    # the subtraction it contains
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub):
                sides = [n.left, n.right]
            elif isinstance(n, ast.Compare) and all(
                    isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
                    for op in n.ops):   # ordered = deadline semantics;
                sides = [n.left] + list(n.comparators)   # `is None` etc.
            if sides:                                    # are not
                dur_nodes.append((n, sides))
        # --fix closure analysis: a tainted name is rewritable only when
        # EVERY Load use of it in this scope sits inside duration
        # arithmetic — a name that also feeds an export (`ts = start *
        # 1e6` epoch anchors) keeps wall semantics, and rewriting either
        # its assignment or arithmetic that mixes it in would corrupt
        # the anchor / mix clocks. Uses inside nested scopes are opaque:
        # treated as anchors.
        in_duration: Set[int] = set()
        for n, _ in dur_nodes:
            for x in ast.walk(n):
                if isinstance(x, ast.Name):
                    in_duration.add(id(x))
        anchored: Set[str] = set()       # names used OUTSIDE duration
        for n in body_nodes:
            if isinstance(n, FUNCS + (ast.Lambda,)):
                for x in ast.walk(n):
                    if isinstance(x, ast.Name) \
                            and isinstance(x.ctx, ast.Load):
                        anchored.add(x.id)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and id(n) not in in_duration:
                anchored.add(n.id)
        flagged: Set[int] = set()        # one finding per line
        for n, sides in dur_nodes:
            if n.lineno in flagged:
                continue
            direct = any(contains_wall(s) for s in sides)
            helper = None
            if not direct:
                for s in sides:
                    helper = helper_wall_name(s, cls_name, self_name)
                    if helper:
                        break
            via_name = any(contains_tainted(s, tainted) for s in sides)
            if direct or helper or via_name:
                flagged.add(n.lineno)
                if direct:
                    what = "time.time()"
                elif helper:
                    what = (f"{helper}() (a helper returning a "
                            f"time.time()-derived value)")
                else:
                    what = "a value derived from time.time()"
                kind = "subtraction" if isinstance(n, ast.BinOp) \
                    else "deadline comparison"
                # --fix payload: only lines holding a LITERAL
                # time.time() to rewrite — the flagged line when the
                # wall call sits in the arithmetic, plus taint-source
                # assignments that contain the literal — and only when
                # the rewrite set is CLOSED: every tainted name feeding
                # this arithmetic must have literal source lines AND no
                # anchor use, or rewriting would mix clocks / corrupt a
                # wall anchor. Helper-return taint has no local
                # mechanical fix (the helper is elsewhere): claiming
                # fixability for it would make `--fix --write` report
                # success on a no-op rewrite.
                fix: Set[int] = set()
                names_involved = {x.id for s in sides
                                  for x in ast.walk(s)
                                  if isinstance(x, ast.Name)
                                  and x.id in tainted}
                closed = all(name not in anchored
                             and wall_lines.get(name)
                             for name in names_involved)
                if closed:
                    if direct:
                        fix.add(n.lineno)
                    for name in names_involved:
                        fix |= wall_lines.get(name, set())
                out.append(Finding(
                    "GC02", ctx.relpath, n.lineno, n.col_offset,
                    f"{what} used in duration {kind} — wall clock is not "
                    f"monotonic (NTP steps corrupt intervals)",
                    _GC02_HINT, ctx.qualname(n),
                    fix_kind="gc02-monotonic" if fix else None,
                    fix_lines=tuple(sorted(fix))))

    scan_scope(ctx.tree)
    for n in ast.walk(ctx.tree):
        if isinstance(n, FUNCS):
            scan_scope(n)
    return out


# ---------------------------------------------------------------------------
# GC03 — atomic-write
# ---------------------------------------------------------------------------

_GC03_HINT = ("route through io.checkpoint._atomic_write_json or the "
              "tmp -> fsync -> os.replace idiom (crash mid-write must "
              "never leave a torn file)")
_GC03_DIRS = {"io", "serve"}


def _calls_os_replace(fn: Optional[ast.AST], tree: ast.Module) -> bool:
    scope = fn if fn is not None else tree
    for n in ast.walk(scope):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("replace", "rename"):
            v = n.func.value
            if isinstance(v, ast.Name) and v.id == "os":
                return True
    return False


def gc03_atomic_write(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    if not (_GC03_DIRS & set(ctx.parts[:-1])):
        return []
    out: List[Finding] = []
    for n in ast.walk(ctx.tree):
        if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "open"):
            continue
        mode = None
        if len(n.args) >= 2 and isinstance(n.args[1], ast.Constant):
            mode = n.args[1].value
        for kw in n.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if not (isinstance(mode, str) and "w" in mode):
            continue
        fn = ctx.enclosing_function(n)
        if _calls_os_replace(fn, ctx.tree):
            continue                     # the atomic helper itself
        out.append(Finding(
            "GC03", ctx.relpath, n.lineno, n.col_offset,
            f'bare open(..., "{mode}") in {ctx.parts[-2]}/ outside a '
            f"tmp -> fsync -> os.replace helper (non-atomic write to a "
            f"checkpoint/cache/pointer path)",
            _GC03_HINT, ctx.qualname(n)))
    return out


# ---------------------------------------------------------------------------
# GC04 — lock-discipline
# ---------------------------------------------------------------------------

_GC04_HINT = ("hold the owning lock (with self._lock:) around the write, "
              "or annotate the single-writer argument with "
              "# graftcheck: disable=GC04")


def _thread_entries(ctx: ModuleContext, cls: ast.ClassDef) \
        -> List[Tuple[str, ast.AST]]:
    """Thread entry points of one class: methods handed to
    ``Thread(target=...)`` (including nested closures and
    ``target=lambda: self.m()``), ``run()`` on Thread subclasses, and
    ``do_*`` handlers on HTTP handler classes."""
    base_names = []
    for b in cls.bases:
        try:
            base_names.append(ast.unparse(b))
        except Exception:  # noqa: BLE001 — unparse of odd nodes
            pass
    entries: List[Tuple[str, ast.AST]] = []
    methods = {m.name: m for m in cls.body if isinstance(m, FUNCS)}
    for n in ast.walk(cls):
        if not (isinstance(n, ast.Call) and is_thread_ctor(n)):
            continue
        for kw in n.keywords:
            if kw.arg != "target":
                continue
            t = kw.value
            if isinstance(t, ast.Lambda) and isinstance(t.body, ast.Call):
                t = t.body.func          # target=lambda: self.m(...)
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self" and t.attr in methods:
                entries.append((t.attr, methods[t.attr]))
            elif isinstance(t, ast.Name):
                # nested closure target: find its def in the class
                for d in ast.walk(cls):
                    if isinstance(d, FUNCS) and d.name == t.id \
                            and ctx.enclosing_function(d) is not None:
                        host = ctx.enclosing_function(d)
                        entries.append(
                            (f"{getattr(host, 'name', '?')}.{d.name}",
                             d))
    if any(b.endswith("Thread") for b in base_names) \
            and "run" in methods:
        entries.append(("run", methods["run"]))
    if any("RequestHandler" in b for b in base_names):
        entries.extend((name, m) for name, m in methods.items()
                       if name.startswith("do_"))
    seen: List[int] = []
    uniq: List[Tuple[str, ast.AST]] = []
    for name, node in entries:
        if id(node) not in seen:
            seen.append(id(node))
            uniq.append((name, node))
    return uniq


def gc04_lock_discipline(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    out: List[Finding] = []
    idx = project.interproc

    # sub-rule: Lock.acquire() outside a with — with-discipline makes
    # release unconditional across every exit path
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "acquire":
            try:
                owner = ast.unparse(n.func.value)
            except Exception:  # noqa: BLE001 — unparse of odd nodes
                owner = ""
            if LOCKISH.search(owner):
                out.append(Finding(
                    "GC04", ctx.relpath, n.lineno, n.col_offset,
                    f"{owner}.acquire() outside a with-statement — an "
                    f"exception between acquire and release deadlocks "
                    f"every other thread",
                    "use `with <lock>:` so release is unconditional",
                    ctx.qualname(n)))

    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        uniq = _thread_entries(ctx, cls)
        if len(uniq) < 2:
            continue

        # attr -> entry name -> [(report line, guarded, via)]
        writes: Dict[str, Dict[str, List[Tuple[int, bool, str]]]] = {}

        def record(attr: str, entry: str, line: int, guarded: bool,
                   via: str) -> None:
            sites = writes.setdefault(attr, {}).setdefault(entry, [])
            if (line, guarded, via) not in sites:
                sites.append((line, guarded, via))

        for name, node in uniq:
            summarized = False
            if idx is not None:
                fid = (ctx.relpath, ctx.qualname(node))
                if fid in idx.functions:
                    for attr, line, guarded, via in \
                            collect_entry_writes(idx, ctx, fid):
                        record(attr, name, line, guarded, via)
                    summarized = True
            # walk the entry for direct self-writes: the WHOLE method
            # when no summary exists (pre-v2 view); with a summary,
            # only its nested defs — closures are absent from the
            # function's summary and a bare call to one resolves to
            # None, so their writes would otherwise vanish from the
            # index entirely
            if summarized:
                scan_roots = [d for d in ast.walk(node)
                              if isinstance(d, FUNCS) and d is not node]
            else:
                scan_roots = [node]
            for n in (x for root in scan_roots for x in ast.walk(root)):
                tgt = None
                if isinstance(n, (ast.Assign,)):
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            tgt = t
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign)) \
                        and isinstance(n.target, ast.Attribute) \
                        and isinstance(n.target.value, ast.Name) \
                        and n.target.value.id == "self":
                    tgt = n.target
                if tgt is None:
                    continue
                record(tgt.attr, name, n.lineno,
                       under_lock(ctx, n, node), "")

        for attr, by_entry in writes.items():
            if len(by_entry) < 2:
                continue
            for entry_name, sites in by_entry.items():
                for line, guarded, via in sites:
                    if guarded:
                        continue
                    others = sorted(e for e in by_entry
                                    if e != entry_name)
                    through = f" (via {via})" if via else ""
                    out.append(Finding(
                        "GC04", ctx.relpath, line, 0,
                        f"self.{attr} written from thread entry point "
                        f"'{entry_name}'{through} without the owning "
                        f"lock, and also written from "
                        f"{', '.join(others)} — unsynchronized "
                        f"multi-thread mutation",
                        _GC04_HINT, f"{cls.name}.{entry_name}"))
    return out


# ---------------------------------------------------------------------------
# GC05 — surface-parity
# ---------------------------------------------------------------------------

_GC05_NAME_RE = re.compile(r"^[A-Za-z0-9_]+$")
_GC05_HINT = ("registry section names and stub keys become Prometheus "
              "metric name parts — [A-Za-z0-9_] only, and stub/live key "
              "sets must mirror (tests/test_obs.py pins the runtime "
              "side; this is the source-level gate)")


def _stub_defs(tree: ast.Module) -> Dict[str, Tuple[ast.AST,
                                                    Tuple[str, ...]]]:
    out = {}
    for n in tree.body:
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and n.targets[0].id.endswith("_STUB") \
                and isinstance(n.value, ast.Dict):
            keys = tuple(k.value for k in n.value.keys
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str))
            out[n.targets[0].id] = (n, keys)
    return out


def collect_project(contexts: List[ModuleContext]) -> ProjectIndex:
    """First pass: stub constants + their alias functions (a module-level
    def whose body references exactly one ``*_STUB`` name, e.g.
    ``serve.promote.promotion_stub``), plus the interprocedural summary
    index every upgraded rule consumes. A summary-pass failure degrades
    to ``interproc=None`` (intra-module rule behavior), never a crash."""
    stubs: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
    aliases: Dict[str, str] = {}
    for ctx in contexts:
        for name, (node, keys) in _stub_defs(ctx.tree).items():
            stubs[name] = (ctx.relpath, keys)
        for n in ctx.tree.body:
            if not isinstance(n, FUNCS):
                continue
            refs = {x.id for x in ast.walk(n)
                    if isinstance(x, ast.Name) and x.id.endswith("_STUB")}
            if len(refs) == 1:
                aliases[n.name] = refs.pop()
    try:
        idx: Optional[InterProcIndex] = interproc.build_index(contexts)
    except Exception:  # noqa: BLE001 — summaries degrade to "unknown",
        idx = None     # never take the gate down with an analyzer crash
    return ProjectIndex(stubs=stubs, stub_aliases=aliases, interproc=idx)


def _literal_keys_of(fn: ast.AST, ctx: ModuleContext,
                     project: ProjectIndex, stub_name: str):
    """(unconditional_keys, all_keys, dynamic, seeded) for the dict the
    live provider RETURNS: dict literals assigned to a returned name (or
    returned directly), ``d.update({...})`` calls and constant subscript
    assigns on it. Dicts bound to other locals (nested per-window
    payloads etc.) do not count. ``dynamic`` = a non-literal update or
    non-constant key feeds the dict (key set not statically closed);
    ``seeded`` = the dict starts as a copy of the stub."""
    uncond: Set[str] = set()
    allk: Set[str] = set()
    dynamic = seeded = False

    def conditional(n: ast.AST) -> bool:
        for a in ctx.ancestors(n):
            if a is fn:
                return False
            if isinstance(a, (ast.If, ast.Try, ast.IfExp)):
                return True
        return False

    def eat_dict(d: ast.Dict, cond: bool) -> None:
        nonlocal dynamic
        for k in d.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                allk.add(k.value)
                if not cond:
                    uncond.add(k.value)
            else:
                dynamic = True           # **spread or computed key

    nodes = []
    stack = list(fn.body)
    while stack:
        x = stack.pop()
        if isinstance(x, FUNCS + (ast.Lambda,)):
            continue                     # nested scope builds other dicts
        nodes.append(x)
        stack.extend(ast.iter_child_nodes(x))

    returned: Set[str] = set()           # names the provider returns
    for n in nodes:
        if isinstance(n, ast.Return) and isinstance(n.value, ast.Name):
            returned.add(n.value.id)

    def targets_of(n: ast.Assign):
        return [t.id for t in n.targets if isinstance(t, ast.Name)]

    for n in nodes:
        if isinstance(n, (ast.Assign, ast.AnnAssign)):
            v = n.value
            names = targets_of(n) if isinstance(n, ast.Assign) else (
                [n.target.id] if isinstance(n.target, ast.Name) else [])
            if v is not None and returned & set(names):
                if isinstance(v, ast.Dict):
                    eat_dict(v, conditional(n))
                if isinstance(v, ast.Call):
                    callee = dec_name(v)
                    if project.stub_aliases.get(callee) == stub_name:
                        seeded = True
                    if callee == "dict" and v.args \
                            and isinstance(v.args[0], ast.Name) \
                            and v.args[0].id == stub_name:
                        seeded = True
            # d["k"] = v on the returned dict
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in returned:
                        s = t.slice
                        if isinstance(s, ast.Constant) \
                                and isinstance(s.value, str):
                            allk.add(s.value)
                            if not conditional(n):
                                uncond.add(s.value)
                        else:
                            dynamic = True
        elif isinstance(n, ast.Return) and isinstance(n.value, ast.Dict):
            eat_dict(n.value, conditional(n))
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "update" \
                and isinstance(n.func.value, ast.Name) \
                and n.func.value.id in returned:
            if n.args and isinstance(n.args[0], ast.Dict):
                eat_dict(n.args[0], conditional(n))
            else:
                dynamic = True
    return uncond, allk, dynamic, seeded


def gc05_surface_parity(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    out: List[Finding] = []

    # (b) name grammar: registry.register("<literal>", ...) everywhere,
    # and stub-dict keys (they all become /metrics name parts)
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "register" \
                and "registry" in dec_name(n.func.value).lower():
            if n.args and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                name = n.args[0].value
                if not _GC05_NAME_RE.match(name):
                    out.append(Finding(
                        "GC05", ctx.relpath, n.lineno, n.col_offset,
                        f"registry section name {name!r} violates the "
                        f"to_prometheus name grammar ([A-Za-z0-9_] only)",
                        _GC05_HINT, ctx.qualname(n)))
    for stub_name, (node, keys) in _stub_defs(ctx.tree).items():
        bad = [k for k in keys if not _GC05_NAME_RE.match(k)]
        # nested dict literal keys feed metric names too
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict) and sub is not getattr(
                    node, "value", None):
                bad.extend(k.value for k in sub.keys
                           if isinstance(k, ast.Constant)
                           and isinstance(k.value, str)
                           and not _GC05_NAME_RE.match(k.value))
        for k in bad:
            out.append(Finding(
                "GC05", ctx.relpath, node.lineno, node.col_offset,
                f"stub {stub_name} key {k!r} violates the to_prometheus "
                f"name grammar ([A-Za-z0-9_] only)",
                _GC05_HINT, stub_name))

    # (a) stub-vs-live key parity: find provider closures referencing
    # exactly one stub and calling exactly one *_section method, then
    # compare that method's literal key set against the stub
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, FUNCS):
            continue
        refs = set()
        for x in ast.walk(fn):
            if isinstance(x, ast.Name) and x.id.endswith("_STUB"):
                refs.add(x.id)
            elif isinstance(x, ast.Call) \
                    and dec_name(x) in project.stub_aliases:
                refs.add(project.stub_aliases[dec_name(x)])
        section_calls = {x.func.attr for x in ast.walk(fn)
                         if isinstance(x, ast.Call)
                         and isinstance(x.func, ast.Attribute)
                         and x.func.attr.endswith("_section")}
        if len(refs) != 1 or len(section_calls) != 1:
            continue
        stub_name = refs.pop()
        if stub_name not in project.stubs:
            continue
        method_name = section_calls.pop()
        cls = None
        for a in ctx.ancestors(fn):
            if isinstance(a, ast.ClassDef):
                cls = a
                break
        if cls is None:
            continue
        live = next((m for m in cls.body if isinstance(m, FUNCS)
                     and m.name == method_name), None)
        if live is None or live is fn:
            continue
        stub_keys = set(project.stubs[stub_name][1])
        uncond, allk, dynamic, seeded = _literal_keys_of(
            live, ctx, project, stub_name)
        for k in sorted(uncond - stub_keys):
            out.append(Finding(
                "GC05", ctx.relpath, live.lineno, live.col_offset,
                f"live provider '{cls.name}.{method_name}' emits key "
                f"{k!r} absent from {stub_name} — stub/live key drift "
                f"(gauges appear and vanish across subsystem lifecycle)",
                _GC05_HINT, f"{cls.name}.{method_name}"))
        if not (dynamic or seeded):
            for k in sorted(stub_keys - allk):
                out.append(Finding(
                    "GC05", ctx.relpath, live.lineno, live.col_offset,
                    f"{stub_name} key {k!r} never emitted by live "
                    f"provider '{cls.name}.{method_name}' — stub/live "
                    f"key drift",
                    _GC05_HINT, f"{cls.name}.{method_name}"))
    return out


# ---------------------------------------------------------------------------
# GC06 — broad-except discipline (serve/ and obs/ hot paths)
# ---------------------------------------------------------------------------

_GC06_DIRS = {"serve", "obs"}
_GC06_HINT = ("narrow the exception type, or add a trailing comment on "
              "the handler naming why failure isolation is required "
              "(obs must never take serving down, etc.)")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [getattr(e, "id", getattr(e, "attr", "")) for e in t.elts]
    else:
        names = [getattr(t, "id", getattr(t, "attr", ""))]
    return any(n in ("Exception", "BaseException") for n in names)


def gc06_broad_except(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    if not (_GC06_DIRS & set(ctx.parts[:-1])):
        return []
    out: List[Finding] = []
    for n in ast.walk(ctx.tree):
        if not (isinstance(n, ast.ExceptHandler) and _is_broad(n)):
            continue
        first_body = n.body[0].lineno if n.body else n.lineno
        annotated = any(line in ctx.comments
                        for line in range(n.lineno, first_body + 1))
        if annotated:
            continue
        out.append(Finding(
            "GC06", ctx.relpath, n.lineno, n.col_offset,
            "broad `except Exception` without a why-comment — silent "
            "catch-alls in serving/observability hot paths hide real "
            "failures",
            _GC06_HINT, ctx.qualname(n),
            fix_kind="gc06-annotate", fix_lines=(n.lineno,)))
    return out


# ---------------------------------------------------------------------------
# GC07 — transfer-discipline (models/ and ops/ hot loops)
# ---------------------------------------------------------------------------

_GC07_DIRS = {"models", "ops"}
_GC07_HINT = ("hoist the fetch out of the loop (batch it after the loop, "
              "or keep the value device-resident); a deliberate per-"
              "iteration sync (e.g. a measured once-per-epoch fetch) "
              "takes # graftcheck: disable=GC07 with the argument on "
              "the line")


def gc07_transfer_discipline(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    if not (_GC07_DIRS & set(ctx.parts[:-1])):
        return []
    if ctx.is_test_module():
        return []
    out: List[Finding] = []
    resolve = project.resolver_for(ctx)
    flagged: Set[int] = set()

    comps = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
    for loop in ast.walk(ctx.tree):
        # the loop BODY runs per iteration; the iterable expression and
        # the else-clause evaluate once — only the body is hot.
        # Comprehensions are loops too: the element expression (and
        # every generator clause past the first's iterable) runs per
        # element
        seeds: List[ast.AST]
        if isinstance(loop, LOOPS):
            seeds = list(loop.body)
        elif isinstance(loop, comps):
            if isinstance(loop, ast.DictComp):
                seeds = [loop.key, loop.value]
            else:
                seeds = [loop.elt]
            for g in loop.generators:
                seeds.extend(g.ifs)
            seeds.extend(g.iter for g in loop.generators[1:])
        else:
            continue
        body_nodes: List[ast.AST] = []
        stack: List[ast.AST] = list(seeds)
        while stack:
            n = stack.pop()
            if isinstance(n, FUNCS + (ast.Lambda,)):
                continue                 # defining != executing per iter
            body_nodes.append(n)
            stack.extend(ast.iter_child_nodes(n))
        encl = ctx.enclosing_function(loop)
        cls_name, self_name = _scope_identity(ctx, encl)
        for n in body_nodes:
            if not isinstance(n, ast.Call) or n.lineno in flagged:
                continue
            if is_transfer_call(n):
                try:
                    what = ast.unparse(n.func)
                except Exception:  # noqa: BLE001 — odd nodes
                    what = "host transfer"
                flagged.add(n.lineno)
                out.append(Finding(
                    "GC07", ctx.relpath, n.lineno, n.col_offset,
                    f"{what}() inside a per-step loop — a forced "
                    f"device->host sync per iteration serializes the "
                    f"pipeline (hot-loop transfer)",
                    _GC07_HINT, ctx.qualname(n)))
            elif resolve is not None:
                # one function boundary only: a callee that ITSELF
                # performs the transfer. Deeper chains in this codebase
                # always cross an intentional architecture boundary
                # (dispatch, checkpoint save) where the sync is the
                # point — flagging them would bury the real hazards
                s = resolve(n, cls_name, self_name)
                if s is not None and s.transfer_direct:
                    flagged.add(n.lineno)
                    out.append(Finding(
                        "GC07", ctx.relpath, n.lineno, n.col_offset,
                        f"call to '{s.name}' inside a per-step loop "
                        f"performs a device->host transfer "
                        f"(np.asarray/device_get/block_until_ready) — "
                        f"a forced sync per iteration serializes the "
                        f"pipeline",
                        _GC07_HINT, ctx.qualname(n)))
    return out


# ---------------------------------------------------------------------------
# GC08 — thread-lifecycle (shutdown must join / poison-pill / timeout)
# ---------------------------------------------------------------------------

_GC08_HINT = ("give the thread a shutdown path: join it (with a timeout) "
              "in close()/stop(), or gate its loop on an Event the "
              "shutdown sets (poison pill); a deliberately unmanaged "
              "daemon takes # graftcheck: disable=GC08 with the argument")


def _class_join_credits(ctx: ModuleContext, cls: ast.ClassDef) \
        -> Set[str]:
    """Attribute names the class provably joins: ``self.X.join(...)``
    anywhere, or ``for t in self.X: t.join(...)`` loop-join."""
    credits: Set[str] = set()
    # loop variables bound over self.<attr>
    loop_over: Dict[str, str] = {}       # loop var -> attr
    for n in ast.walk(cls):
        if isinstance(n, ast.For) and isinstance(n.target, ast.Name) \
                and isinstance(n.iter, ast.Attribute) \
                and isinstance(n.iter.value, ast.Name) \
                and n.iter.value.id == "self":
            loop_over[n.target.id] = n.iter.attr
    for n in ast.walk(cls):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "join"):
            continue
        base = n.func.value
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self":
            credits.add(base.attr)
        elif isinstance(base, ast.Name) and base.id in loop_over:
            credits.add(loop_over[base.id])
    return credits


def _class_event_sets(ctx: ModuleContext, cls: ast.ClassDef) -> Set[str]:
    """``self.<attr>.set()`` calls anywhere in the class — poison-pill
    senders for GC08's event-gate credit."""
    out: Set[str] = set()
    for n in ast.walk(cls):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "set":
            v = n.func.value
            if isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                out.add(v.attr)
    return out


def gc08_thread_lifecycle(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    idx = project.interproc
    if idx is None:
        return []                        # needs target summaries
    out: List[Finding] = []

    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        joins = _class_join_credits(ctx, cls)
        event_sets = _class_event_sets(ctx, cls)
        methods = {m.name: m for m in cls.body if isinstance(m, FUNCS)}

        for n in ast.walk(cls):
            if not (isinstance(n, ast.Call) and is_thread_ctor(n)):
                continue
            # where does the Thread object go? self.<attr> = Thread(...)
            # directly, or local = Thread(...) later stored/appended on
            # self — locals that never reach self are out of scope
            # (anonymous per-task threads, locally-joined workers)
            stored_attr: Optional[str] = None
            p = ctx.parent(n)
            local_name: Optional[str] = None
            if isinstance(p, ast.Assign):
                for t in p.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        stored_attr = t.attr
                    elif isinstance(t, ast.Name):
                        local_name = t.id
            if stored_attr is None and local_name is not None:
                host = ctx.enclosing_function(n)
                scope = host if host is not None else cls
                for m in ast.walk(scope):
                    if isinstance(m, ast.Assign):
                        for t in m.targets:
                            if isinstance(t, ast.Attribute) \
                                    and isinstance(t.value, ast.Name) \
                                    and t.value.id == "self" \
                                    and isinstance(m.value, ast.Name) \
                                    and m.value.id == local_name:
                                stored_attr = t.attr
                    elif isinstance(m, ast.Call) \
                            and isinstance(m.func, ast.Attribute) \
                            and m.func.attr == "append" \
                            and m.args \
                            and isinstance(m.args[0], ast.Name) \
                            and m.args[0].id == local_name:
                        v = m.func.value
                        if isinstance(v, ast.Attribute) \
                                and isinstance(v.value, ast.Name) \
                                and v.value.id == "self":
                            stored_attr = v.attr
            if stored_attr is None:
                continue

            # resolve the target's summary; unknown targets degrade
            target_summary = None
            for kw in n.keywords:
                if kw.arg != "target":
                    continue
                t = kw.value
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" and t.attr in methods:
                    target_summary = idx.functions.get(
                        (ctx.relpath, ctx.qualname(methods[t.attr])))
                elif isinstance(t, ast.Name):
                    for d in ast.walk(cls):
                        if isinstance(d, FUNCS) and d.name == t.id \
                                and ctx.enclosing_function(d) \
                                is not None:
                            target_summary = idx.functions.get(
                                (ctx.relpath, ctx.qualname(d)))
            if target_summary is None \
                    or not target_summary.has_while_loop:
                continue                 # run-once worker / unknown —
            #                              no shutdown obligation proven
            if stored_attr in joins:
                continue                 # join discipline
            gates = target_summary.loop_event_gates
            if gates & event_sets:
                continue                 # poison-pill discipline
            gate_note = ""
            if gates:
                gate_note = (f" (its loop waits on self."
                             f"{sorted(gates)[0]}, but nothing in the "
                             f"class ever set()s it)")
            out.append(Finding(
                "GC08", ctx.relpath, n.lineno, n.col_offset,
                f"long-running thread stored on self.{stored_attr} has "
                f"no shutdown path: target "
                f"'{target_summary.name}' loops forever and the class "
                f"never joins self.{stored_attr} or signals its "
                f"poison-pill event{gate_note}",
                _GC08_HINT, f"{cls.name}"))
    return out


#: rule registry: code -> (function, one-line description)
RULES = {
    "GC01": (gc01_retrace_hazard,
             "retrace-hazard: per-call jit closures / nested compile "
             "factories / fresh-jit factory calls across modules"),
    "GC02": (gc02_clock_discipline,
             "clock-discipline: time.time() in duration arithmetic, "
             "including through helper returns"),
    "GC03": (gc03_atomic_write,
             "atomic-write: bare write-open in io//serve/ outside the "
             "tmp->fsync->os.replace idiom"),
    "GC04": (gc04_lock_discipline,
             "lock-discipline: unsynchronized multi-thread attribute "
             "mutation (incl. via called methods) / acquire() without "
             "with"),
    "GC05": (gc05_surface_parity,
             "surface-parity: stub/live registry key drift + Prometheus "
             "name grammar"),
    "GC06": (gc06_broad_except,
             "broad-except: unannotated `except Exception` in serve//obs/"),
    "GC07": (gc07_transfer_discipline,
             "transfer-discipline: device->host sync reachable inside "
             "models//ops/ hot loops"),
    "GC08": (gc08_thread_lifecycle,
             "thread-lifecycle: long-running threads whose shutdown "
             "path lacks join/poison-pill"),
}


def run_rules(ctx: ModuleContext, project: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for code, (fn, _desc) in RULES.items():
        for f in fn(ctx, project):
            # nested provider closures can satisfy an associator twice
            # (the closure AND its enclosing method) — one finding per
            # (line, code, message) is enough
            key = (f.code, f.line, f.message)
            if key not in seen:
                seen.add(key)
                findings.append(f)
    return findings
