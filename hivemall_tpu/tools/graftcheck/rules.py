"""graftcheck rule implementations (stdlib ``ast`` only).

Each rule is a function ``(ctx: ModuleContext, project: ProjectIndex) ->
List[Finding]``. The engine builds one :class:`ModuleContext` per file
(parse tree + parent links + comment map) and a :class:`ProjectIndex`
from a first pass over every scanned file: registry stub constants and
their alias functions, plus the INTERPROCEDURAL summary index
(:mod:`.interproc`) — a project-wide call graph with per-function
summaries (returns-tainted, param-escapes, locks-held-at-call) that
lets GC02 follow a ``time.time()`` value through helper returns, GC04
follow shared-attribute writes through methods called from thread
targets, GC01 track jit-closure factories across modules, GC09 close
tracer taint over call edges from jit/scan roots, GC11 follow
``donate_argnums`` facts through factory returns, and GC12 treat a
helper that returns a fresh resource as an acquisition at its call
sites.

The rules encode PROJECT invariants, not general style: they must pass
the known-good compile-factory population clean — the ~67 jit/lru_cache
sites across models/, ops/ and parallel/ (floor 60 pinned by
tests/test_graftcheck.py) — along with the atomic-write helpers in io/,
while rejecting the seeded violations in the same test file. When a rule and reality disagree, the
escape hatch is an explicit ``# graftcheck: disable=<code>`` on the
flagged line (or alone on the line above) — intent on the record, not a
silent pass.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from . import interproc
from .interproc import (FUNCS, LOOPS, LOCKISH, InterProcIndex,
                        collect_entry_writes, dec_name,
                        is_cache_decorator, is_jit_creation,
                        is_jit_decorator, is_memo_decorated,
                        is_thread_ctor, is_transfer_call, under_lock)

import re

__all__ = ["Finding", "ModuleContext", "ProjectIndex", "RULES",
           "RULESTAMP", "collect_project", "project_from_facts",
           "run_rules"]

#: bumped whenever ANY rule's behavior changes — invalidates the
#: engine's content-hash findings cache wholesale (a stale cache must
#: never outvote an upgraded rule)
RULESTAMP = "graftcheck-v3.0"


@dataclass
class Finding:
    code: str
    path: str            # '/'-separated path relative to the scan root
    line: int
    col: int
    message: str
    hint: str = ""
    symbol: str = "<module>"
    #: mechanical-fix payload (``--fix``): rule-specific. GC02 —
    #: source lines on which ``time.time()`` must become
    #: ``time.monotonic()``; GC06 — the handler line to annotate.
    fix_kind: Optional[str] = None
    fix_lines: Tuple[int, ...] = ()

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity for the baseline file: stable across
        unrelated edits above the finding, invalidated when the finding's
        own symbol or message changes (a fixed finding MUST leave the
        baseline — the engine flags the stale entry)."""
        return f"{self.path}::{self.code}::{self.symbol}::{self.message}"

    def render(self) -> str:
        s = f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"
        if self.hint:
            s += f" [fix: {self.hint}]"
        return s

    def to_json(self) -> dict:
        d = {k: v for k, v in vars(self).items()
             if k not in ("fix_kind", "fix_lines")}
        d["fingerprint"] = self.fingerprint
        d["fix_kind"] = self.fix_kind
        d["fix_lines"] = list(self.fix_lines)
        return d


class ModuleContext:
    """One parsed file: tree, parent links, raw lines, comment map."""

    def __init__(self, relpath: str, tree: ast.Module,
                 comments: Dict[int, str]):
        self.relpath = relpath
        self.parts = tuple(relpath.split("/"))
        self.tree = tree
        self.comments = comments          # line -> comment text
        self._parent: Dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parent[id(child)] = parent

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parent.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def qualname(self, node: ast.AST) -> str:
        names = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            cur = self.parent(cur)
        return ".".join(reversed(names)) or "<module>"

    def enclosing_function(self, node: ast.AST) \
            -> Optional[ast.AST]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def enclosing_class_name(self, node: ast.AST) -> Optional[str]:
        for a in self.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a.name
        return None

    def is_test_module(self) -> bool:
        """tests/ and test_*.py files: deliberate ad-hoc compiles there
        are not production retrace hazards (GC01 skips them)."""
        return self.parts[0] == "tests" \
            or self.parts[-1].startswith("test_")


@dataclass
class ProjectIndex:
    """Cross-file state from the engine's first pass."""
    #: STUB const name -> (defining relpath, top-level literal keys)
    stubs: Dict[str, Tuple[str, Tuple[str, ...]]]
    #: alias function name -> STUB const name (e.g. promotion_stub)
    stub_aliases: Dict[str, str]
    #: interprocedural summaries + call graph (None only when the
    #: summary pass failed — rules degrade to intra-module behavior)
    interproc: Optional[InterProcIndex] = field(default=None)

    def resolver_for(self, ctx: "ModuleContext"):
        """``resolve(call_node, class_name, self_name) -> summary|None``
        bound to ``ctx``'s module, or None without an interproc index."""
        idx = self.interproc
        if idx is None:
            return None
        mi = idx.modules_by_path.get(ctx.relpath)
        if mi is None:
            return None

        def resolve(call, class_name, self_name):
            try:
                fid = idx.resolve_call(mi, call, class_name, self_name)
            except Exception:  # noqa: BLE001 — degrade to unknown
                return None
            return idx.functions.get(fid) if fid is not None else None

        return resolve


def _scope_identity(ctx: ModuleContext, fn: Optional[ast.AST]) \
        -> Tuple[Optional[str], Optional[str]]:
    """(class_name, self_name) for resolving ``self.x()`` calls inside
    ``fn`` — direct methods use their first arg, closures nested under a
    class capture the literal ``self``."""
    if fn is None:
        return None, None
    cls = ctx.enclosing_class_name(fn)
    if cls is None:
        return None, None
    parent = ctx.parent(fn)
    if isinstance(parent, ast.ClassDef):
        args = fn.args
        params = list(args.posonlyargs) + list(args.args)
        if params and not any(dec_name(d) == "staticmethod"
                              for d in fn.decorator_list):
            return cls, params[0].arg
        return cls, None
    return cls, "self"


# ---------------------------------------------------------------------------
# GC01 — retrace-hazard
# ---------------------------------------------------------------------------

_GC01_HINT = ("hoist into a module-level factory memoized with lru_cache "
              "+ obs.devprof.instrument_factory, or return/store the "
              "closure instead of re-creating it per call")


def gc01_retrace_hazard(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    if ctx.is_test_module():
        return []    # tests compile ad hoc by design
    out: List[Finding] = []
    resolve = project.resolver_for(ctx)

    def add(node, msg):
        out.append(Finding("GC01", ctx.relpath, node.lineno,
                           node.col_offset, msg, _GC01_HINT,
                           ctx.qualname(node)))

    def chain_memoized(fn) -> bool:
        cur = fn
        while cur is not None:
            if isinstance(cur, FUNCS) and is_memo_decorated(cur):
                return True
            cur = ctx.parent(cur)
        return False

    def in_loop_below(node, fn) -> bool:
        """Is ``node`` inside a loop that is itself inside ``fn`` (or at
        module level when fn is None)?"""
        for a in ctx.ancestors(node):
            if a is fn:
                return False
            if isinstance(a, LOOPS):
                return True
            if isinstance(a, FUNCS) and a is not fn:
                return False
        return False

    def product_escapes(fn, name: str, skip: ast.AST) -> Tuple[bool, bool]:
        """(called, escapes) for loads of ``name`` in ``fn``'s scope.
        A load used as anything but a call's func position — returned,
        stored on self, passed as an argument, put in a container —
        counts as an escape: the closure outlives this call."""
        called = escapes = False
        for n in ast.walk(fn):
            if not (isinstance(n, ast.Name) and n.id == name
                    and isinstance(n.ctx, ast.Load)):
                continue
            if any(a is skip for a in ctx.ancestors(n)):
                continue                 # the creating statement itself
            p = ctx.parent(n)
            if isinstance(p, ast.Call) and p.func is n:
                called = True
            else:
                escapes = True
        return called, escapes

    for node in ast.walk(ctx.tree):
        # nested lru_cache factory: a fresh cache object per enclosing
        # call — the cache never hits, every call recompiles
        if isinstance(node, FUNCS) \
                and any(is_cache_decorator(d) for d in node.decorator_list):
            encl = ctx.enclosing_function(node)
            if encl is not None and not chain_memoized(encl):
                add(node, f"lru_cache compile factory '{node.name}' defined "
                          f"inside a function — a fresh cache per call never "
                          f"hits (retrace hazard)")
            continue

        # decorator-form jit on a def nested inside an un-memoized fn:
        # fine when the closure escapes (factory pattern), a hazard when
        # it is only invoked locally or created in a loop
        if isinstance(node, FUNCS) \
                and any(is_jit_decorator(d) for d in node.decorator_list):
            encl = ctx.enclosing_function(node)
            if encl is None or chain_memoized(encl):
                continue
            if in_loop_below(node, encl):
                add(node, f"jit-compiled closure '{node.name}' created "
                          f"inside a loop (fresh compile per iteration)")
                continue
            called, escapes = product_escapes(encl, node.name, node)
            if called and not escapes:
                add(node, f"jit-compiled closure '{node.name}' created and "
                          f"invoked in the same scope without escaping "
                          f"(fresh compile per call)")
            continue

        # interprocedural: a call to a FACTORY whose summary says it
        # returns a fresh jit closure per call — the per-call compile
        # hides behind the function boundary (cross-module included)
        if isinstance(node, ast.Call) and resolve is not None \
                and not is_jit_creation(node):
            encl = ctx.enclosing_function(node)
            cls_name, self_name = _scope_identity(ctx, encl)
            s = resolve(node, cls_name, self_name)
            if s is not None and s.returns_fresh_jit \
                    and not (encl is not None and chain_memoized(encl)) \
                    and (ctx.relpath, ctx.qualname(encl or node)) != s.fid:
                p = ctx.parent(node)
                if in_loop_below(node, encl):
                    add(node, f"call to jit-closure factory "
                              f"'{s.name}' inside a loop — a fresh "
                              f"compile per iteration hides behind the "
                              f"function boundary")
                    continue
                if isinstance(p, ast.Call) and p.func is node:
                    add(node, f"jit-closure factory '{s.name}' called "
                              f"and its product invoked inline (fresh "
                              f"compile per call across the function "
                              f"boundary)")
                    continue

        if not is_jit_creation(node):
            continue
        # skip the inner partial(jax.jit,...) of an already-handled
        # creation, and decorator positions (handled above)
        p = ctx.parent(node)
        if isinstance(p, ast.Call) and is_jit_creation(p):
            continue
        if isinstance(p, FUNCS) and node in p.decorator_list:
            continue
        encl = ctx.enclosing_function(node)
        if encl is None or chain_memoized(encl):
            continue
        if in_loop_below(node, encl):
            add(node, "jit-compiled closure created inside a loop "
                      "(fresh compile per iteration)")
            continue
        # immediate invoke: jax.jit(f)(x) — compiled, called, dropped
        if isinstance(p, ast.Call) and p.func is node:
            add(node, "jit-compiled closure created and invoked inline "
                      "(fresh compile per call)")
            continue
        # named product: track what happens to it in this scope
        stmt = node
        for a in ctx.ancestors(node):
            if isinstance(a, ast.stmt):
                stmt = a
                break
        if isinstance(stmt, ast.Assign) \
                and all(isinstance(t, ast.Name) for t in stmt.targets):
            called, escapes = product_escapes(
                encl, stmt.targets[0].id, stmt)
            if called and not escapes:
                add(node, f"jit-compiled closure "
                          f"'{stmt.targets[0].id}' created and invoked in "
                          f"the same scope without escaping (fresh compile "
                          f"per call)")
        # Return / self.attr store / argument position: escapes — OK
    return out


# ---------------------------------------------------------------------------
# GC02 — clock-discipline
# ---------------------------------------------------------------------------

_GC02_HINT = ("use time.monotonic() for durations and deadlines; a "
              "deliberate wall-clock anchor (chrome-trace ts, bundle "
              "mtime) must carry # graftcheck: disable=GC02")


def _has_bare_time_import(tree: ast.Module) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module == "time":
            if any(a.name == "time" for a in n.names):
                return True
    return False


def gc02_clock_discipline(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    out: List[Finding] = []
    bare = _has_bare_time_import(ctx.tree)
    resolve = project.resolver_for(ctx)

    def is_wall_call(n: ast.AST) -> bool:
        if not isinstance(n, ast.Call):
            return False
        f = n.func
        if isinstance(f, ast.Attribute) and f.attr == "time" \
                and isinstance(f.value, ast.Name) and f.value.id == "time":
            return True
        return bare and isinstance(f, ast.Name) and f.id == "time"

    def contains_wall(n: ast.AST) -> bool:
        return any(is_wall_call(x) for x in ast.walk(n))

    def helper_wall_name(n: ast.AST, cls_name, self_name) \
            -> Optional[str]:
        """Name of a called helper whose summary proves it RETURNS a
        time.time()-derived value (the interprocedural upgrade)."""
        if resolve is None:
            return None
        for x in ast.walk(n):
            if isinstance(x, ast.Call) and not is_wall_call(x):
                s = resolve(x, cls_name, self_name)
                if s is not None and s.returns_wall:
                    return s.name
        return None

    def contains_tainted(n: ast.AST, tainted: Set[str]) -> bool:
        return any(isinstance(x, ast.Name) and x.id in tainted
                   and isinstance(x.ctx, ast.Load) for x in ast.walk(n))

    def scan_scope(scope: ast.AST) -> None:
        """One function (or the module body): taint names assigned from
        time.time() — directly or via a helper whose summary returns a
        wall value — then flag subtraction / ordered comparison involving
        the wall clock. Nested functions are separate scopes."""
        fn = scope if isinstance(scope, FUNCS) else None
        cls_name, self_name = _scope_identity(ctx, fn)
        tainted: Set[str] = set()        # names carrying wall taint
        wall_lines: Dict[str, Set[int]] = {}   # name -> EVERY source
        #                 line assigning it from a literal wall call (a
        #                 name can be re-assigned; --fix must rewrite
        #                 all of them or the rescan still fails)
        body_nodes = []
        stack = list(scope.body)
        while stack:
            n = stack.pop()
            body_nodes.append(n)
            if isinstance(n, FUNCS + (ast.Lambda,)):
                continue                 # separate scope
            stack.extend(ast.iter_child_nodes(n))
        for n in body_nodes:
            tgt_names: List[str] = []
            value = None
            if isinstance(n, ast.Assign):
                tgt_names = [t.id for t in n.targets
                             if isinstance(t, ast.Name)]
                value = n.value
            elif isinstance(n, ast.AnnAssign) and n.value is not None \
                    and isinstance(n.target, ast.Name):
                tgt_names = [n.target.id]
                value = n.value
            if not tgt_names or value is None:
                continue
            literal = contains_wall(value)
            if literal or helper_wall_name(value, cls_name, self_name):
                for t in tgt_names:
                    tainted.add(t)
                    if literal:          # helper-tainted lines carry no
                        wall_lines.setdefault(t, set()).add(n.lineno)
                    #                      time.time() literal to rewrite
        dur_nodes: List[Tuple[ast.AST, List[ast.AST]]] = []
        for n in body_nodes:             # a deadline compare often wraps
            sides: List[ast.AST] = []    # the subtraction it contains
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub):
                sides = [n.left, n.right]
            elif isinstance(n, ast.Compare) and all(
                    isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
                    for op in n.ops):   # ordered = deadline semantics;
                sides = [n.left] + list(n.comparators)   # `is None` etc.
            if sides:                                    # are not
                dur_nodes.append((n, sides))
        # --fix closure analysis: a tainted name is rewritable only when
        # EVERY Load use of it in this scope sits inside duration
        # arithmetic — a name that also feeds an export (`ts = start *
        # 1e6` epoch anchors) keeps wall semantics, and rewriting either
        # its assignment or arithmetic that mixes it in would corrupt
        # the anchor / mix clocks. Uses inside nested scopes are opaque:
        # treated as anchors.
        in_duration: Set[int] = set()
        for n, _ in dur_nodes:
            for x in ast.walk(n):
                if isinstance(x, ast.Name):
                    in_duration.add(id(x))
        anchored: Set[str] = set()       # names used OUTSIDE duration
        for n in body_nodes:
            if isinstance(n, FUNCS + (ast.Lambda,)):
                for x in ast.walk(n):
                    if isinstance(x, ast.Name) \
                            and isinstance(x.ctx, ast.Load):
                        anchored.add(x.id)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and id(n) not in in_duration:
                anchored.add(n.id)
        flagged: Set[int] = set()        # one finding per line
        for n, sides in dur_nodes:
            if n.lineno in flagged:
                continue
            direct = any(contains_wall(s) for s in sides)
            helper = None
            if not direct:
                for s in sides:
                    helper = helper_wall_name(s, cls_name, self_name)
                    if helper:
                        break
            via_name = any(contains_tainted(s, tainted) for s in sides)
            if direct or helper or via_name:
                flagged.add(n.lineno)
                if direct:
                    what = "time.time()"
                elif helper:
                    what = (f"{helper}() (a helper returning a "
                            f"time.time()-derived value)")
                else:
                    what = "a value derived from time.time()"
                kind = "subtraction" if isinstance(n, ast.BinOp) \
                    else "deadline comparison"
                # --fix payload: only lines holding a LITERAL
                # time.time() to rewrite — the flagged line when the
                # wall call sits in the arithmetic, plus taint-source
                # assignments that contain the literal — and only when
                # the rewrite set is CLOSED: every tainted name feeding
                # this arithmetic must have literal source lines AND no
                # anchor use, or rewriting would mix clocks / corrupt a
                # wall anchor. Helper-return taint has no local
                # mechanical fix (the helper is elsewhere): claiming
                # fixability for it would make `--fix --write` report
                # success on a no-op rewrite.
                fix: Set[int] = set()
                names_involved = {x.id for s in sides
                                  for x in ast.walk(s)
                                  if isinstance(x, ast.Name)
                                  and x.id in tainted}
                closed = all(name not in anchored
                             and wall_lines.get(name)
                             for name in names_involved)
                if closed:
                    if direct:
                        fix.add(n.lineno)
                    for name in names_involved:
                        fix |= wall_lines.get(name, set())
                out.append(Finding(
                    "GC02", ctx.relpath, n.lineno, n.col_offset,
                    f"{what} used in duration {kind} — wall clock is not "
                    f"monotonic (NTP steps corrupt intervals)",
                    _GC02_HINT, ctx.qualname(n),
                    fix_kind="gc02-monotonic" if fix else None,
                    fix_lines=tuple(sorted(fix))))

    scan_scope(ctx.tree)
    for n in ast.walk(ctx.tree):
        if isinstance(n, FUNCS):
            scan_scope(n)
    return out


# ---------------------------------------------------------------------------
# GC03 — atomic-write
# ---------------------------------------------------------------------------

_GC03_HINT = ("route through io.checkpoint._atomic_write_json or the "
              "tmp -> fsync -> os.replace idiom (crash mid-write must "
              "never leave a torn file)")
_GC03_DIRS = {"io", "serve"}


def _calls_os_replace(fn: Optional[ast.AST], tree: ast.Module) -> bool:
    scope = fn if fn is not None else tree
    for n in ast.walk(scope):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("replace", "rename"):
            v = n.func.value
            if isinstance(v, ast.Name) and v.id == "os":
                return True
    return False


def gc03_atomic_write(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    if not (_GC03_DIRS & set(ctx.parts[:-1])):
        return []
    out: List[Finding] = []
    for n in ast.walk(ctx.tree):
        if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "open"):
            continue
        mode = None
        if len(n.args) >= 2 and isinstance(n.args[1], ast.Constant):
            mode = n.args[1].value
        for kw in n.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if not (isinstance(mode, str) and "w" in mode):
            continue
        fn = ctx.enclosing_function(n)
        if _calls_os_replace(fn, ctx.tree):
            continue                     # the atomic helper itself
        out.append(Finding(
            "GC03", ctx.relpath, n.lineno, n.col_offset,
            f'bare open(..., "{mode}") in {ctx.parts[-2]}/ outside a '
            f"tmp -> fsync -> os.replace helper (non-atomic write to a "
            f"checkpoint/cache/pointer path)",
            _GC03_HINT, ctx.qualname(n)))
    return out


# ---------------------------------------------------------------------------
# GC04 — lock-discipline
# ---------------------------------------------------------------------------

_GC04_HINT = ("hold the owning lock (with self._lock:) around the write, "
              "or annotate the single-writer argument with "
              "# graftcheck: disable=GC04")


def _thread_entries(ctx: ModuleContext, cls: ast.ClassDef) \
        -> List[Tuple[str, ast.AST]]:
    """Thread entry points of one class: methods handed to
    ``Thread(target=...)`` (including nested closures and
    ``target=lambda: self.m()``), ``run()`` on Thread subclasses, and
    ``do_*`` handlers on HTTP handler classes."""
    base_names = []
    for b in cls.bases:
        try:
            base_names.append(ast.unparse(b))
        except Exception:  # noqa: BLE001 — unparse of odd nodes
            pass
    entries: List[Tuple[str, ast.AST]] = []
    methods = {m.name: m for m in cls.body if isinstance(m, FUNCS)}
    for n in ast.walk(cls):
        if not (isinstance(n, ast.Call) and is_thread_ctor(n)):
            continue
        for kw in n.keywords:
            if kw.arg != "target":
                continue
            t = kw.value
            if isinstance(t, ast.Lambda) and isinstance(t.body, ast.Call):
                t = t.body.func          # target=lambda: self.m(...)
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self" and t.attr in methods:
                entries.append((t.attr, methods[t.attr]))
            elif isinstance(t, ast.Name):
                # nested closure target: find its def in the class
                for d in ast.walk(cls):
                    if isinstance(d, FUNCS) and d.name == t.id \
                            and ctx.enclosing_function(d) is not None:
                        host = ctx.enclosing_function(d)
                        entries.append(
                            (f"{getattr(host, 'name', '?')}.{d.name}",
                             d))
    if any(b.endswith("Thread") for b in base_names) \
            and "run" in methods:
        entries.append(("run", methods["run"]))
    if any("RequestHandler" in b for b in base_names):
        entries.extend((name, m) for name, m in methods.items()
                       if name.startswith("do_"))
    seen: List[int] = []
    uniq: List[Tuple[str, ast.AST]] = []
    for name, node in entries:
        if id(node) not in seen:
            seen.append(id(node))
            uniq.append((name, node))
    return uniq


def gc04_lock_discipline(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    out: List[Finding] = []
    idx = project.interproc

    # sub-rule: Lock.acquire() outside a with — with-discipline makes
    # release unconditional across every exit path
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "acquire":
            try:
                owner = ast.unparse(n.func.value)
            except Exception:  # noqa: BLE001 — unparse of odd nodes
                owner = ""
            if LOCKISH.search(owner):
                out.append(Finding(
                    "GC04", ctx.relpath, n.lineno, n.col_offset,
                    f"{owner}.acquire() outside a with-statement — an "
                    f"exception between acquire and release deadlocks "
                    f"every other thread",
                    "use `with <lock>:` so release is unconditional",
                    ctx.qualname(n)))

    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        uniq = _thread_entries(ctx, cls)
        if len(uniq) < 2:
            continue

        # attr -> entry name -> [(report line, guarded, via)]
        writes: Dict[str, Dict[str, List[Tuple[int, bool, str]]]] = {}

        def record(attr: str, entry: str, line: int, guarded: bool,
                   via: str) -> None:
            sites = writes.setdefault(attr, {}).setdefault(entry, [])
            if (line, guarded, via) not in sites:
                sites.append((line, guarded, via))

        for name, node in uniq:
            summarized = False
            if idx is not None:
                fid = (ctx.relpath, ctx.qualname(node))
                if fid in idx.functions:
                    for attr, line, guarded, via in \
                            collect_entry_writes(idx, ctx, fid):
                        record(attr, name, line, guarded, via)
                    summarized = True
            # walk the entry for direct self-writes: the WHOLE method
            # when no summary exists (pre-v2 view); with a summary,
            # only its nested defs — closures are absent from the
            # function's summary and a bare call to one resolves to
            # None, so their writes would otherwise vanish from the
            # index entirely
            if summarized:
                scan_roots = [d for d in ast.walk(node)
                              if isinstance(d, FUNCS) and d is not node]
            else:
                scan_roots = [node]
            for n in (x for root in scan_roots for x in ast.walk(root)):
                tgt = None
                if isinstance(n, (ast.Assign,)):
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            tgt = t
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign)) \
                        and isinstance(n.target, ast.Attribute) \
                        and isinstance(n.target.value, ast.Name) \
                        and n.target.value.id == "self":
                    tgt = n.target
                if tgt is None:
                    continue
                record(tgt.attr, name, n.lineno,
                       under_lock(ctx, n, node), "")

        for attr, by_entry in writes.items():
            if len(by_entry) < 2:
                continue
            for entry_name, sites in by_entry.items():
                for line, guarded, via in sites:
                    if guarded:
                        continue
                    others = sorted(e for e in by_entry
                                    if e != entry_name)
                    through = f" (via {via})" if via else ""
                    out.append(Finding(
                        "GC04", ctx.relpath, line, 0,
                        f"self.{attr} written from thread entry point "
                        f"'{entry_name}'{through} without the owning "
                        f"lock, and also written from "
                        f"{', '.join(others)} — unsynchronized "
                        f"multi-thread mutation",
                        _GC04_HINT, f"{cls.name}.{entry_name}"))
    return out


# ---------------------------------------------------------------------------
# GC05 — surface-parity
# ---------------------------------------------------------------------------

_GC05_NAME_RE = re.compile(r"^[A-Za-z0-9_]+$")
_GC05_HINT = ("registry section names and stub keys become Prometheus "
              "metric name parts — [A-Za-z0-9_] only, and stub/live key "
              "sets must mirror (tests/test_obs.py pins the runtime "
              "side; this is the source-level gate)")


def _stub_defs(tree: ast.Module) -> Dict[str, Tuple[ast.AST,
                                                    Tuple[str, ...]]]:
    out = {}
    for n in tree.body:
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and n.targets[0].id.endswith("_STUB") \
                and isinstance(n.value, ast.Dict):
            keys = tuple(k.value for k in n.value.keys
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str))
            out[n.targets[0].id] = (n, keys)
    return out


def project_from_facts(all_facts: List[Any]) -> ProjectIndex:
    """Assemble the cross-file index from per-module
    :class:`~.interproc.ModuleFacts` — the join point of the engine's
    parallel scan (workers extract facts for their shard; the main
    process assembles ONE project view and broadcasts it back for the
    rule pass). An assembly failure degrades to ``interproc=None``
    (intra-module rule behavior), never a crash."""
    stubs: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
    aliases: Dict[str, str] = {}
    for facts in all_facts:
        for name, keys in facts.stubs.items():
            stubs[name] = (facts.info.relpath, keys)
        aliases.update(facts.stub_aliases)
    try:
        idx: Optional[InterProcIndex] = interproc.assemble_index(all_facts)
    except Exception:  # noqa: BLE001 — summaries degrade to "unknown",
        idx = None     # never take the gate down with an analyzer crash
    return ProjectIndex(stubs=stubs, stub_aliases=aliases, interproc=idx)


def collect_project(contexts: List[ModuleContext]) -> ProjectIndex:
    """First pass (serial convenience): extract every module's facts
    in-process, then assemble. A module whose extraction crashes
    degrades to absent-from-the-index, never a gate crash."""
    facts = []
    for ctx in contexts:
        try:
            facts.append(interproc.extract_module(ctx))
        except Exception:  # noqa: BLE001 — degrade to unknown
            pass
    return project_from_facts(facts)


def _literal_keys_of(fn: ast.AST, ctx: ModuleContext,
                     project: ProjectIndex, stub_name: str):
    """(unconditional_keys, all_keys, dynamic, seeded) for the dict the
    live provider RETURNS: dict literals assigned to a returned name (or
    returned directly), ``d.update({...})`` calls and constant subscript
    assigns on it. Dicts bound to other locals (nested per-window
    payloads etc.) do not count. ``dynamic`` = a non-literal update or
    non-constant key feeds the dict (key set not statically closed);
    ``seeded`` = the dict starts as a copy of the stub."""
    uncond: Set[str] = set()
    allk: Set[str] = set()
    dynamic = seeded = False

    def conditional(n: ast.AST) -> bool:
        for a in ctx.ancestors(n):
            if a is fn:
                return False
            if isinstance(a, (ast.If, ast.Try, ast.IfExp)):
                return True
        return False

    def eat_dict(d: ast.Dict, cond: bool) -> None:
        nonlocal dynamic
        for k in d.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                allk.add(k.value)
                if not cond:
                    uncond.add(k.value)
            else:
                dynamic = True           # **spread or computed key

    nodes = []
    stack = list(fn.body)
    while stack:
        x = stack.pop()
        if isinstance(x, FUNCS + (ast.Lambda,)):
            continue                     # nested scope builds other dicts
        nodes.append(x)
        stack.extend(ast.iter_child_nodes(x))

    returned: Set[str] = set()           # names the provider returns
    for n in nodes:
        if isinstance(n, ast.Return) and isinstance(n.value, ast.Name):
            returned.add(n.value.id)

    def targets_of(n: ast.Assign):
        return [t.id for t in n.targets if isinstance(t, ast.Name)]

    for n in nodes:
        if isinstance(n, (ast.Assign, ast.AnnAssign)):
            v = n.value
            names = targets_of(n) if isinstance(n, ast.Assign) else (
                [n.target.id] if isinstance(n.target, ast.Name) else [])
            if v is not None and returned & set(names):
                if isinstance(v, ast.Dict):
                    eat_dict(v, conditional(n))
                if isinstance(v, ast.Call):
                    callee = dec_name(v)
                    if project.stub_aliases.get(callee) == stub_name:
                        seeded = True
                    if callee == "dict" and v.args \
                            and isinstance(v.args[0], ast.Name) \
                            and v.args[0].id == stub_name:
                        seeded = True
            # d["k"] = v on the returned dict
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in returned:
                        s = t.slice
                        if isinstance(s, ast.Constant) \
                                and isinstance(s.value, str):
                            allk.add(s.value)
                            if not conditional(n):
                                uncond.add(s.value)
                        else:
                            dynamic = True
        elif isinstance(n, ast.Return) and isinstance(n.value, ast.Dict):
            eat_dict(n.value, conditional(n))
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "update" \
                and isinstance(n.func.value, ast.Name) \
                and n.func.value.id in returned:
            if n.args and isinstance(n.args[0], ast.Dict):
                eat_dict(n.args[0], conditional(n))
            else:
                dynamic = True
    return uncond, allk, dynamic, seeded


def gc05_surface_parity(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    out: List[Finding] = []

    # (b) name grammar: registry.register("<literal>", ...) everywhere,
    # and stub-dict keys (they all become /metrics name parts)
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "register" \
                and "registry" in dec_name(n.func.value).lower():
            if n.args and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                name = n.args[0].value
                if not _GC05_NAME_RE.match(name):
                    out.append(Finding(
                        "GC05", ctx.relpath, n.lineno, n.col_offset,
                        f"registry section name {name!r} violates the "
                        f"to_prometheus name grammar ([A-Za-z0-9_] only)",
                        _GC05_HINT, ctx.qualname(n)))
    for stub_name, (node, keys) in _stub_defs(ctx.tree).items():
        bad = [k for k in keys if not _GC05_NAME_RE.match(k)]
        # nested dict literal keys feed metric names too
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict) and sub is not getattr(
                    node, "value", None):
                bad.extend(k.value for k in sub.keys
                           if isinstance(k, ast.Constant)
                           and isinstance(k.value, str)
                           and not _GC05_NAME_RE.match(k.value))
        for k in bad:
            out.append(Finding(
                "GC05", ctx.relpath, node.lineno, node.col_offset,
                f"stub {stub_name} key {k!r} violates the to_prometheus "
                f"name grammar ([A-Za-z0-9_] only)",
                _GC05_HINT, stub_name))

    # (a) stub-vs-live key parity: find provider closures referencing
    # exactly one stub and calling exactly one *_section method, then
    # compare that method's literal key set against the stub
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, FUNCS):
            continue
        refs = set()
        for x in ast.walk(fn):
            if isinstance(x, ast.Name) and x.id.endswith("_STUB"):
                refs.add(x.id)
            elif isinstance(x, ast.Call) \
                    and dec_name(x) in project.stub_aliases:
                refs.add(project.stub_aliases[dec_name(x)])
        section_calls = {x.func.attr for x in ast.walk(fn)
                         if isinstance(x, ast.Call)
                         and isinstance(x.func, ast.Attribute)
                         and x.func.attr.endswith("_section")}
        if len(refs) != 1 or len(section_calls) != 1:
            continue
        stub_name = refs.pop()
        if stub_name not in project.stubs:
            continue
        method_name = section_calls.pop()
        cls = None
        for a in ctx.ancestors(fn):
            if isinstance(a, ast.ClassDef):
                cls = a
                break
        if cls is None:
            continue
        live = next((m for m in cls.body if isinstance(m, FUNCS)
                     and m.name == method_name), None)
        if live is None or live is fn:
            continue
        stub_keys = set(project.stubs[stub_name][1])
        uncond, allk, dynamic, seeded = _literal_keys_of(
            live, ctx, project, stub_name)
        for k in sorted(uncond - stub_keys):
            out.append(Finding(
                "GC05", ctx.relpath, live.lineno, live.col_offset,
                f"live provider '{cls.name}.{method_name}' emits key "
                f"{k!r} absent from {stub_name} — stub/live key drift "
                f"(gauges appear and vanish across subsystem lifecycle)",
                _GC05_HINT, f"{cls.name}.{method_name}"))
        if not (dynamic or seeded):
            for k in sorted(stub_keys - allk):
                out.append(Finding(
                    "GC05", ctx.relpath, live.lineno, live.col_offset,
                    f"{stub_name} key {k!r} never emitted by live "
                    f"provider '{cls.name}.{method_name}' — stub/live "
                    f"key drift",
                    _GC05_HINT, f"{cls.name}.{method_name}"))
    return out


# ---------------------------------------------------------------------------
# GC06 — broad-except discipline (serve/ and obs/ hot paths)
# ---------------------------------------------------------------------------

_GC06_DIRS = {"serve", "obs"}
_GC06_HINT = ("narrow the exception type, or add a trailing comment on "
              "the handler naming why failure isolation is required "
              "(obs must never take serving down, etc.)")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [getattr(e, "id", getattr(e, "attr", "")) for e in t.elts]
    else:
        names = [getattr(t, "id", getattr(t, "attr", ""))]
    return any(n in ("Exception", "BaseException") for n in names)


def gc06_broad_except(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    if not (_GC06_DIRS & set(ctx.parts[:-1])):
        return []
    out: List[Finding] = []
    for n in ast.walk(ctx.tree):
        if not (isinstance(n, ast.ExceptHandler) and _is_broad(n)):
            continue
        first_body = n.body[0].lineno if n.body else n.lineno
        annotated = any(line in ctx.comments
                        for line in range(n.lineno, first_body + 1))
        if annotated:
            continue
        out.append(Finding(
            "GC06", ctx.relpath, n.lineno, n.col_offset,
            "broad `except Exception` without a why-comment — silent "
            "catch-alls in serving/observability hot paths hide real "
            "failures",
            _GC06_HINT, ctx.qualname(n),
            fix_kind="gc06-annotate", fix_lines=(n.lineno,)))
    return out


# ---------------------------------------------------------------------------
# GC07 — transfer-discipline (models/ and ops/ hot loops)
# ---------------------------------------------------------------------------

_GC07_DIRS = {"models", "ops"}
_GC07_HINT = ("hoist the fetch out of the loop (batch it after the loop, "
              "or keep the value device-resident); a deliberate per-"
              "iteration sync (e.g. a measured once-per-epoch fetch) "
              "takes # graftcheck: disable=GC07 with the argument on "
              "the line")


def gc07_transfer_discipline(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    if not (_GC07_DIRS & set(ctx.parts[:-1])):
        return []
    if ctx.is_test_module():
        return []
    out: List[Finding] = []
    resolve = project.resolver_for(ctx)
    flagged: Set[int] = set()

    comps = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
    for loop in ast.walk(ctx.tree):
        # the loop BODY runs per iteration; the iterable expression and
        # the else-clause evaluate once — only the body is hot.
        # Comprehensions are loops too: the element expression (and
        # every generator clause past the first's iterable) runs per
        # element
        seeds: List[ast.AST]
        if isinstance(loop, LOOPS):
            seeds = list(loop.body)
        elif isinstance(loop, comps):
            if isinstance(loop, ast.DictComp):
                seeds = [loop.key, loop.value]
            else:
                seeds = [loop.elt]
            for g in loop.generators:
                seeds.extend(g.ifs)
            seeds.extend(g.iter for g in loop.generators[1:])
        else:
            continue
        body_nodes: List[ast.AST] = []
        stack: List[ast.AST] = list(seeds)
        while stack:
            n = stack.pop()
            if isinstance(n, FUNCS + (ast.Lambda,)):
                continue                 # defining != executing per iter
            body_nodes.append(n)
            stack.extend(ast.iter_child_nodes(n))
        encl = ctx.enclosing_function(loop)
        cls_name, self_name = _scope_identity(ctx, encl)
        for n in body_nodes:
            if not isinstance(n, ast.Call) or n.lineno in flagged:
                continue
            if is_transfer_call(n):
                try:
                    what = ast.unparse(n.func)
                except Exception:  # noqa: BLE001 — odd nodes
                    what = "host transfer"
                flagged.add(n.lineno)
                out.append(Finding(
                    "GC07", ctx.relpath, n.lineno, n.col_offset,
                    f"{what}() inside a per-step loop — a forced "
                    f"device->host sync per iteration serializes the "
                    f"pipeline (hot-loop transfer)",
                    _GC07_HINT, ctx.qualname(n)))
            elif resolve is not None:
                # one function boundary only: a callee that ITSELF
                # performs the transfer. Deeper chains in this codebase
                # always cross an intentional architecture boundary
                # (dispatch, checkpoint save) where the sync is the
                # point — flagging them would bury the real hazards
                s = resolve(n, cls_name, self_name)
                if s is not None and s.transfer_direct:
                    flagged.add(n.lineno)
                    out.append(Finding(
                        "GC07", ctx.relpath, n.lineno, n.col_offset,
                        f"call to '{s.name}' inside a per-step loop "
                        f"performs a device->host transfer "
                        f"(np.asarray/device_get/block_until_ready) — "
                        f"a forced sync per iteration serializes the "
                        f"pipeline",
                        _GC07_HINT, ctx.qualname(n)))
    return out


# ---------------------------------------------------------------------------
# GC08 — thread-lifecycle (shutdown must join / poison-pill / timeout)
# ---------------------------------------------------------------------------

_GC08_HINT = ("give the thread a shutdown path: join it (with a timeout) "
              "in close()/stop(), or gate its loop on an Event the "
              "shutdown sets (poison pill); a deliberately unmanaged "
              "daemon takes # graftcheck: disable=GC08 with the argument")


def _class_join_credits(ctx: ModuleContext, cls: ast.ClassDef) \
        -> Set[str]:
    """Attribute names the class provably joins: ``self.X.join(...)``
    anywhere, or ``for t in self.X: t.join(...)`` loop-join."""
    credits: Set[str] = set()
    # loop variables bound over self.<attr>
    loop_over: Dict[str, str] = {}       # loop var -> attr
    for n in ast.walk(cls):
        if isinstance(n, ast.For) and isinstance(n.target, ast.Name) \
                and isinstance(n.iter, ast.Attribute) \
                and isinstance(n.iter.value, ast.Name) \
                and n.iter.value.id == "self":
            loop_over[n.target.id] = n.iter.attr
    for n in ast.walk(cls):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "join"):
            continue
        base = n.func.value
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self":
            credits.add(base.attr)
        elif isinstance(base, ast.Name) and base.id in loop_over:
            credits.add(loop_over[base.id])
    return credits


def _class_event_sets(ctx: ModuleContext, cls: ast.ClassDef) -> Set[str]:
    """``self.<attr>.set()`` calls anywhere in the class — poison-pill
    senders for GC08's event-gate credit."""
    out: Set[str] = set()
    for n in ast.walk(cls):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "set":
            v = n.func.value
            if isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                out.add(v.attr)
    return out


def gc08_thread_lifecycle(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    idx = project.interproc
    if idx is None:
        return []                        # needs target summaries
    out: List[Finding] = []

    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        joins = _class_join_credits(ctx, cls)
        event_sets = _class_event_sets(ctx, cls)
        methods = {m.name: m for m in cls.body if isinstance(m, FUNCS)}

        for n in ast.walk(cls):
            if not (isinstance(n, ast.Call) and is_thread_ctor(n)):
                continue
            # where does the Thread object go? self.<attr> = Thread(...)
            # directly, or local = Thread(...) later stored/appended on
            # self — locals that never reach self are out of scope
            # (anonymous per-task threads, locally-joined workers)
            stored_attr: Optional[str] = None
            p = ctx.parent(n)
            local_name: Optional[str] = None
            if isinstance(p, ast.Assign):
                for t in p.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        stored_attr = t.attr
                    elif isinstance(t, ast.Name):
                        local_name = t.id
            if stored_attr is None and local_name is not None:
                host = ctx.enclosing_function(n)
                scope = host if host is not None else cls
                for m in ast.walk(scope):
                    if isinstance(m, ast.Assign):
                        for t in m.targets:
                            if isinstance(t, ast.Attribute) \
                                    and isinstance(t.value, ast.Name) \
                                    and t.value.id == "self" \
                                    and isinstance(m.value, ast.Name) \
                                    and m.value.id == local_name:
                                stored_attr = t.attr
                    elif isinstance(m, ast.Call) \
                            and isinstance(m.func, ast.Attribute) \
                            and m.func.attr == "append" \
                            and m.args \
                            and isinstance(m.args[0], ast.Name) \
                            and m.args[0].id == local_name:
                        v = m.func.value
                        if isinstance(v, ast.Attribute) \
                                and isinstance(v.value, ast.Name) \
                                and v.value.id == "self":
                            stored_attr = v.attr
            if stored_attr is None:
                continue

            # resolve the target's summary; unknown targets degrade
            target_summary = None
            for kw in n.keywords:
                if kw.arg != "target":
                    continue
                t = kw.value
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" and t.attr in methods:
                    target_summary = idx.functions.get(
                        (ctx.relpath, ctx.qualname(methods[t.attr])))
                elif isinstance(t, ast.Name):
                    for d in ast.walk(cls):
                        if isinstance(d, FUNCS) and d.name == t.id \
                                and ctx.enclosing_function(d) \
                                is not None:
                            target_summary = idx.functions.get(
                                (ctx.relpath, ctx.qualname(d)))
            if target_summary is None \
                    or not target_summary.has_while_loop:
                continue                 # run-once worker / unknown —
            #                              no shutdown obligation proven
            if stored_attr in joins:
                continue                 # join discipline
            gates = target_summary.loop_event_gates
            if gates & event_sets:
                continue                 # poison-pill discipline
            gate_note = ""
            if gates:
                gate_note = (f" (its loop waits on self."
                             f"{sorted(gates)[0]}, but nothing in the "
                             f"class ever set()s it)")
            out.append(Finding(
                "GC08", ctx.relpath, n.lineno, n.col_offset,
                f"long-running thread stored on self.{stored_attr} has "
                f"no shutdown path: target "
                f"'{target_summary.name}' loops forever and the class "
                f"never joins self.{stored_attr} or signals its "
                f"poison-pill event{gate_note}",
                _GC08_HINT, f"{cls.name}"))
    return out


# ---------------------------------------------------------------------------
# GC09 — tracer-safety (the XLA compile contract, half 1)
# ---------------------------------------------------------------------------

_GC09_HINT = ("use the jnp twin (np.<fn> -> jnp.<fn>; --fix rewrites the "
              "mechanical subset), lax.cond/jnp.where instead of Python "
              "branches, or mark the argument static_argnums; a deliberate "
              "host-side site takes # graftcheck: disable=GC09 with the "
              "argument on the line")


def gc09_tracer_safety(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    """Functions reachable as jit/pjit/pmap/shard_map/lax.scan bodies —
    directly or through helper hops (the interprocedural traced-param
    closure) — must not concretize a traced parameter: ``np.*`` calls,
    ``float()``/``int()``/``bool()`` casts, ``.item()``/``.tolist()``,
    or Python control flow on a tracer. Under jit these raise
    TracerConversionError at best; at worst they silently re-run host
    code per trace or force a device sync per call."""
    if ctx.is_test_module():
        return []                        # ad-hoc compiles by design
    idx = project.interproc
    if idx is None:
        return []
    out: List[Finding] = []
    for s in idx.functions.values():
        if s.fid[0] != ctx.relpath:
            continue
        for p in s.params:
            if (s.fid, p) not in idx.traced:
                continue
            for line, kind, what in s.param_np_calls.get(p, []):
                if kind == "np":
                    msg = (f"{what}() on a value derived from traced "
                           f"parameter '{p}' of '{s.name}' — numpy "
                           f"concretizes the tracer (host round-trip "
                           f"per trace inside a jit/scan region)")
                    fix_kind, fix_lines = "gc09-jnp", (line,)
                elif kind == "cast":
                    msg = (f"{what} cast of a value derived from traced "
                           f"parameter '{p}' of '{s.name}' — "
                           f"concretizes the tracer "
                           f"(TracerConversionError under jit)")
                    fix_kind, fix_lines = None, ()
                else:
                    msg = (f"{what} on a value derived from traced "
                           f"parameter '{p}' of '{s.name}' — forces a "
                           f"device sync + host conversion inside a "
                           f"traced region")
                    fix_kind, fix_lines = None, ()
                out.append(Finding(
                    "GC09", ctx.relpath, line, 0, msg, _GC09_HINT,
                    s.fid[1], fix_kind=fix_kind, fix_lines=fix_lines))
            for line in s.param_branches.get(p, []):
                out.append(Finding(
                    "GC09", ctx.relpath, line, 0,
                    f"Python control flow on a value derived from traced "
                    f"parameter '{p}' of '{s.name}' — branching on a "
                    f"tracer concretizes it (each taken branch is a "
                    f"separate trace)",
                    _GC09_HINT, s.fid[1]))
    return out


# ---------------------------------------------------------------------------
# GC10 — carry-stability (the XLA compile contract, half 2)
# ---------------------------------------------------------------------------

_GC10_HINT = ("the carry returned by a lax.scan body must match its "
              "input pytree structure AND dtypes exactly: seed new "
              "leaves outside the scan, use jnp.asarray(x, dtype) on "
              "entry, and keep every return's carry the same shape")


def _carry_leaves(expr: ast.AST) -> List[ast.AST]:
    """Leaf expressions of a carry tuple literal (nested tuples/lists
    flattened); a non-tuple carry is one leaf."""
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: List[ast.AST] = []
        for e in expr.elts:
            out.extend(_carry_leaves(e))
        return out
    return [expr]


def gc10_carry_stability(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    """A ``lax.scan`` body whose returned carry can diverge from its
    input carry: a Python scalar literal as a carry leaf (a weak-typed
    scalar never matches the array leaf it replaces — structure/dtype
    mismatch, at best a retrace), a dtype-changing ``.astype`` on a
    carry leaf, or returns whose carry tuples differ in length
    (conditional carry shape)."""
    if ctx.is_test_module():
        return []
    idx = project.interproc
    if idx is None:
        return []
    bodies = [fid for fid in idx.scan_bodies if fid[0] == ctx.relpath]
    if not bodies:
        return []
    by_qual: Dict[str, ast.AST] = {}
    for n in ast.walk(ctx.tree):
        if isinstance(n, FUNCS):
            by_qual.setdefault(ctx.qualname(n), n)
    out: List[Finding] = []
    for fid in bodies:
        fn = by_qual.get(fid[1])
        if fn is None:
            continue
        # body-scope nodes (nested defs are their own scans' business)
        nodes: List[ast.AST] = []
        stack = list(fn.body)
        while stack:
            n = stack.pop()
            if isinstance(n, FUNCS + (ast.Lambda,)):
                continue
            nodes.append(n)
            stack.extend(ast.iter_child_nodes(n))
        # one-hop name resolution: `c = (x, y)` ... `return c, ys`
        tuple_named: Dict[str, ast.AST] = {}
        for n in nodes:
            if isinstance(n, ast.Assign) \
                    and isinstance(n.value, (ast.Tuple, ast.List)):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        tuple_named[t.id] = n.value
        carries: List[Tuple[ast.AST, ast.AST, int]] = []
        for n in nodes:
            if not (isinstance(n, ast.Return) and n.value is not None):
                continue
            v = n.value
            # scan bodies return (carry, y); anything else is opaque
            if isinstance(v, ast.Tuple) and len(v.elts) == 2:
                carry = v.elts[0]
                if isinstance(carry, ast.Name) \
                        and carry.id in tuple_named:
                    carry = tuple_named[carry.id]
                carries.append((n, carry, n.lineno))
        for ret, carry, line in carries:
            for leaf in _carry_leaves(carry):
                if isinstance(leaf, ast.Constant) \
                        and isinstance(leaf.value, (int, float, bool)):
                    out.append(Finding(
                        "GC10", ctx.relpath, line, ret.col_offset,
                        f"scan body '{fid[1]}' returns the Python "
                        f"scalar literal {leaf.value!r} as a carry "
                        f"leaf — a weak-typed scalar never matches the "
                        f"incoming array leaf (carry structure/dtype "
                        f"mismatch => TypeError or retrace)",
                        _GC10_HINT, fid[1]))
                for sub in ast.walk(leaf):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == "astype":
                        arg = sub.args[0] if sub.args else None
                        # x.astype(y.dtype) PRESERVES a leaf dtype —
                        # only a literal/named dtype can change it
                        if not (isinstance(arg, ast.Attribute)
                                and arg.attr == "dtype"):
                            out.append(Finding(
                                "GC10", ctx.relpath, sub.lineno,
                                sub.col_offset,
                                f"scan body '{fid[1]}' applies .astype "
                                f"with an explicit dtype to a carry "
                                f"leaf — if it differs from the input "
                                f"leaf's dtype the carry diverges "
                                f"(dtype mismatch => TypeError or "
                                f"retrace)",
                                _GC10_HINT, fid[1]))
        lens = {len(_carry_leaves(c)) for _r, c, _l in carries
                if isinstance(c, (ast.Tuple, ast.List))}
        if len(lens) > 1:
            first = carries[0]
            out.append(Finding(
                "GC10", ctx.relpath, first[2], first[0].col_offset,
                f"scan body '{fid[1]}' has returns whose carry tuples "
                f"differ in length ({sorted(lens)}) — conditional "
                f"carry STRUCTURE can never match a fixed input carry",
                _GC10_HINT, fid[1]))
    return out


# ---------------------------------------------------------------------------
# GC11 — donation-discipline
# ---------------------------------------------------------------------------

_GC11_HINT = ("donated buffers are dead after the call — rebind the "
              "result to the same name (state = step(state, ...)) or "
              "drop the read; hot-path step cores take donate_argnums="
              "(0, 1) so XLA updates the tables in place")


def gc11_donation_discipline(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    """Two halves of the buffer-donation contract. (a) a caller of a
    ``donate_argnums``-jitted callable must not read the donated
    argument after the call — the buffer was surrendered to XLA and may
    alias the output. (b) ``ops/`` scannable step cores must BE donated:
    an undonated hot-path core copies the full parameter/optimizer
    tables every minibatch."""
    if ctx.is_test_module():
        return []
    idx = project.interproc
    if idx is None:
        return []
    out: List[Finding] = []

    # (b) scannable(jit(core)) registrations in ops/ must donate
    if "ops" in ctx.parts[:-1]:
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call) and dec_name(n) == "scannable" \
                    and n.args:
                jc = n.args[0]
                if is_jit_creation(jc) and not interproc._jit_call_kwargs(
                        jc, "donate_argnums"):
                    out.append(Finding(
                        "GC11", ctx.relpath, n.lineno, n.col_offset,
                        "scannable step core jitted WITHOUT "
                        "donate_argnums — every step copies the full "
                        "weight/optimizer tables instead of updating "
                        "them in place (O(dims) copy per minibatch)",
                        _GC11_HINT, ctx.qualname(n)))

    # (a) read-after-donate, interprocedural through factory returns
    resolve = project.resolver_for(ctx)

    def scope_nodes(scope: ast.AST) -> List[ast.AST]:
        nodes: List[ast.AST] = []
        stack = list(scope.body)
        while stack:
            n = stack.pop()
            if isinstance(n, FUNCS + (ast.Lambda,)):
                continue
            nodes.append(n)
            stack.extend(ast.iter_child_nodes(n))
        return nodes

    scopes: List[ast.AST] = [n for n in ast.walk(ctx.tree)
                             if isinstance(n, FUNCS)]
    for fn in scopes:
        cls_name, self_name = _scope_identity(ctx, fn)
        nodes = scope_nodes(fn)
        # names bound to donation-jitted callables, with their donated
        # positions: direct jit creations and factory-call returns
        donated: Dict[str, Tuple[int, ...]] = {}
        for n in nodes:
            if not (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Call)):
                continue
            tgt = [t.id for t in n.targets if isinstance(t, ast.Name)]
            if not tgt:
                continue
            dp = interproc._jit_call_kwargs(n.value, "donate_argnums")
            if is_jit_creation(n.value) and dp:
                for t in tgt:
                    donated[t] = dp
            elif resolve is not None:
                s = resolve(n.value, cls_name, self_name)
                if s is not None and s.returns_donated:
                    for t in tgt:
                        donated[t] = s.returns_donated
        if not donated:
            continue
        # call sites of the donated callables
        for call in nodes:
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Name)
                    and call.func.id in donated):
                continue
            positions = donated[call.func.id]
            donated_args = [call.args[i].id for i in positions
                            if i < len(call.args)
                            and isinstance(call.args[i], ast.Name)]
            if not donated_args:
                continue
            # result rebinding the donated name kills the hazard: the
            # old buffer is dead AND unreachable (state = step(state,…))
            stmt: Optional[ast.AST] = None
            for a in ctx.ancestors(call):
                if isinstance(a, ast.stmt):
                    stmt = a
                    break
            rebound: Set[str] = set()
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        rebound.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        rebound.update(e.id for e in t.elts
                                       if isinstance(e, ast.Name))
            stmt_ids = {id(x) for x in ast.walk(stmt)} if stmt else set()
            for name in donated_args:
                if name in rebound:
                    continue
                later = [n for n in nodes
                         if isinstance(n, ast.Name) and n.id == name
                         and isinstance(n.ctx, ast.Load)
                         and id(n) not in stmt_ids
                         and n.lineno > call.lineno]
                if later:
                    hit = min(later, key=lambda n: n.lineno)
                    out.append(Finding(
                        "GC11", ctx.relpath, hit.lineno, hit.col_offset,
                        f"'{name}' read after being DONATED to "
                        f"'{call.func.id}' on line {call.lineno} — the "
                        f"buffer was surrendered to XLA at the call and "
                        f"may alias the output (garbage reads / "
                        f"use-after-donate)",
                        _GC11_HINT, ctx.qualname(hit)))
    return out


# ---------------------------------------------------------------------------
# GC12 — resource-lifecycle (exception-path leak analysis)
# ---------------------------------------------------------------------------

_GC12_DIRS = {"serve", "io", "parallel"}
_GC12_HINT = ("own the resource with `with` (or contextlib.closing), "
              "close it in a finally/cleanup-and-reraise handler, or "
              "hand it to an owner whose close()/stop() releases it; a "
              "deliberately process-lifetime resource takes "
              "# graftcheck: disable=GC12 with the argument on the line")

#: method names that release a resource
_RELEASE_ATTRS = {"close", "shutdown", "stop", "release", "join",
                  "close_pool", "terminate"}

#: callees that cannot realistically raise — they don't open the
#: exception window the risky-call analysis is looking for
_GC12_SAFE_CALLS = {"Event", "Lock", "RLock", "Condition", "Semaphore",
                    "deque", "dict", "list", "set", "tuple", "frozenset",
                    "OrderedDict", "defaultdict", "Counter", "Queue",
                    "WeakKeyDictionary", "WeakValueDictionary",
                    "int", "float", "str", "bool", "bytes", "len",
                    "isinstance", "getattr", "hasattr", "id", "repr",
                    "monotonic", "time", "perf_counter"}


def _release_credits(ctx: ModuleContext, cls: ast.ClassDef) -> Set[str]:
    """self-attributes the class provably releases somewhere:
    ``self.X.close()`` (any release verb), loop-release ``for c in
    self.X: c.close()``, and the swap idiom ``pool, self.X = self.X,
    []`` followed by a release of the swapped local."""
    credits: Set[str] = set()
    aliases: Dict[str, str] = {}         # local name -> self attr
    for n in ast.walk(cls):
        if isinstance(n, ast.Assign):
            # plain alias and the tuple-swap idiom
            targets = n.targets[0].elts \
                if (len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Tuple)) \
                else n.targets
            values = n.value.elts if isinstance(n.value, ast.Tuple) \
                else [n.value]
            if len(targets) == len(values):
                for t, v in zip(targets, values):
                    if isinstance(t, ast.Name) \
                            and isinstance(v, ast.Attribute) \
                            and isinstance(v.value, ast.Name) \
                            and v.value.id == "self":
                        aliases[t.id] = v.attr
        elif isinstance(n, ast.For) and isinstance(n.target, ast.Name):
            it = n.iter
            if isinstance(it, ast.Attribute) \
                    and isinstance(it.value, ast.Name) \
                    and it.value.id == "self":
                aliases[n.target.id] = it.attr
            elif isinstance(it, ast.Name) and it.id in aliases:
                aliases[n.target.id] = aliases[it.id]
    for n in ast.walk(cls):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _RELEASE_ATTRS):
            continue
        base = n.func.value
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self":
            credits.add(base.attr)
        elif isinstance(base, ast.Name) and base.id in aliases:
            credits.add(aliases[base.id])
    return credits


def gc12_resource_lifecycle(ctx: ModuleContext, project: ProjectIndex) \
        -> List[Finding]:
    """Exception-path leak analysis for socket/file/mmap/http handles in
    serve//io//parallel/: a resource acquired outside ``with`` must be
    released on EVERY path — close in a finally (or a cleanup-and-
    reraise handler), or escape to an owner whose release path covers it
    (the interprocedural ``returns_resource`` closure makes a helper
    that returns a fresh resource count as an acquisition at its call
    sites). Flags: acquire-then-risky-call windows where an exception
    leaks the handle, straight-line-only closes, owner attributes no
    release path covers, and dropped acquisition results."""
    if not (_GC12_DIRS & set(ctx.parts[:-1])):
        return []
    if ctx.is_test_module():
        return []
    idx = project.interproc
    resolve = project.resolver_for(ctx)
    out: List[Finding] = []

    # targeted sub-rule: `except HTTPError as e: e.read()` — the bound
    # error owns the response socket; reading without closing leaks one
    # fd per probe (the fleet health-probe one-shot shape)
    for h in ast.walk(ctx.tree):
        if not isinstance(h, ast.ExceptHandler) or h.name is None:
            continue
        tname = ""
        if h.type is not None:
            tname = h.type.attr if isinstance(h.type, ast.Attribute) \
                else getattr(h.type, "id", "")
        if not tname.endswith("HTTPError"):
            continue
        reads = [n for n in ast.walk(h)
                 if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)
                 and n.func.attr == "read"
                 and isinstance(n.func.value, ast.Name)
                 and n.func.value.id == h.name]
        closes = [n for n in ast.walk(h)
                  if isinstance(n, ast.Call)
                  and isinstance(n.func, ast.Attribute)
                  and n.func.attr in _RELEASE_ATTRS
                  and isinstance(n.func.value, ast.Name)
                  and n.func.value.id == h.name]
        managed = any(
            isinstance(w, ast.With)
            and any(h.name in {x.id for x in ast.walk(it.context_expr)
                               if isinstance(x, ast.Name)}
                    for it in w.items)
            for w in ast.walk(h))
        if reads and not closes and not managed:
            n = reads[0]
            out.append(Finding(
                "GC12", ctx.relpath, n.lineno, n.col_offset,
                f"HTTPError '{h.name}' body read without closing the "
                f"response — the error object owns the probe socket; "
                f"every handled error leaks one fd",
                _GC12_HINT, ctx.qualname(n)))

    class_credits: Dict[int, Set[str]] = {}

    def credits_for(cls: Optional[ast.AST]) -> Set[str]:
        if not isinstance(cls, ast.ClassDef):
            return set()
        got = class_credits.get(id(cls))
        if got is None:
            got = _release_credits(ctx, cls)
            class_credits[id(cls)] = got
        return got

    for fn in (n for n in ast.walk(ctx.tree) if isinstance(n, FUNCS)):
        cls_name, self_name = _scope_identity(ctx, fn)
        cls_node = None
        for a in ctx.ancestors(fn):
            if isinstance(a, ast.ClassDef):
                cls_node = a
                break
        nodes: List[ast.AST] = []
        stack = list(fn.body)
        while stack:
            n = stack.pop()
            if isinstance(n, FUNCS + (ast.Lambda,)):
                continue
            nodes.append(n)
            stack.extend(ast.iter_child_nodes(n))

        def acquisition_kind(call: ast.Call) -> Optional[str]:
            kind = interproc.is_acquisition(call)
            if kind is not None:
                return kind
            if resolve is not None:
                s = resolve(call, cls_name, self_name)
                if s is not None and s.returns_resource:
                    return s.returns_resource
            return None

        # exception-protection map: statements inside a Try whose
        # finalbody OR cleanup-and-reraise handler releases name X —
        # releases on self attributes count as "any" protection (the
        # __init__ close-and-reraise pattern releases self.<attr>, not
        # a local)
        def protected_names(n: ast.AST) -> Set[str]:
            names: Set[str] = set()
            for a in ctx.ancestors(n):
                if a is fn:
                    break
                if not isinstance(a, ast.Try):
                    continue
                regions = list(a.finalbody)
                for h in a.handlers:
                    if any(isinstance(x, ast.Raise)
                           for x in ast.walk(h)):
                        regions.extend(h.body)
                for r in regions:
                    for c in ast.walk(r):
                        if isinstance(c, ast.Call) \
                                and isinstance(c.func, ast.Attribute) \
                                and c.func.attr in _RELEASE_ATTRS:
                            base = c.func.value
                            if isinstance(base, ast.Name):
                                names.add(base.id)
                            elif isinstance(base, ast.Attribute) \
                                    and isinstance(base.value, ast.Name):
                                names.add(f"{base.value.id}.{base.attr}")
                                names.add("<any-self-release>")
            return names

        for call in nodes:
            if not isinstance(call, ast.Call):
                continue
            kind = acquisition_kind(call)
            if kind is None:
                continue
            p = ctx.parent(call)
            # `with acquire() as x:` / `with closing(acquire()):`
            if isinstance(p, ast.withitem):
                continue
            if isinstance(p, ast.Call) and call in p.args:
                gp = ctx.parent(p)
                if isinstance(gp, ast.withitem):
                    continue             # with closing(acquire()):
                continue                 # handed straight to a callee
            if isinstance(p, ast.Return):
                continue                 # ownership moves to the caller
            if isinstance(p, ast.Expr):
                out.append(Finding(
                    "GC12", ctx.relpath, call.lineno, call.col_offset,
                    f"{kind} acquired and immediately dropped — the "
                    f"handle leaks until GC happens to collect it",
                    _GC12_HINT, ctx.qualname(call)))
                continue
            # method chain on a fresh acquisition:
            # urlopen(...).read() — never closed
            if isinstance(p, ast.Attribute) and p.value is call:
                out.append(Finding(
                    "GC12", ctx.relpath, call.lineno, call.col_offset,
                    f"{kind} acquired and used in a call chain without "
                    f"ever being closed — wrap it in `with` "
                    f"(one leaked handle per call)",
                    _GC12_HINT, ctx.qualname(call)))
                continue
            if not isinstance(p, ast.Assign):
                continue                 # exotic binding: degrade
            local: Optional[str] = None
            attr_store: Optional[str] = None
            for t in p.targets:
                if isinstance(t, ast.Name):
                    local = t.id
                elif isinstance(t, ast.Tuple):
                    for e in t.elts:
                        if isinstance(e, ast.Name) \
                                and not e.id.startswith("_"):
                            local = e.id
                            break
                elif isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in (self_name, "self"):
                    attr_store = t.attr

            risky_after = [
                n for n in nodes
                if isinstance(n, ast.Call) and n.lineno > call.lineno
                and dec_name(n) not in _GC12_SAFE_CALLS
                and not (isinstance(n.func, ast.Attribute)
                         and isinstance(n.func.value, ast.Name)
                         and n.func.value.id == local
                         and n.func.attr in _RELEASE_ATTRS)]

            if attr_store is not None and local is None:
                # self.X = acquire(): in __init__ a later raising call
                # drops the partially-built object WITH the live handle
                # (the constructor's caller never gets a reference to
                # close); elsewhere the owner holds it — check the class
                # has a release path for the attribute at all
                if fn.name == "__init__":
                    unprot = [n for n in risky_after
                              if not ({f"self.{attr_store}",
                                       "<any-self-release>"}
                                      & protected_names(n))]
                    if unprot:
                        hit = min(unprot, key=lambda n: n.lineno)
                        out.append(Finding(
                            "GC12", ctx.relpath, call.lineno,
                            call.col_offset,
                            f"{kind} stored on self.{attr_store} in "
                            f"__init__ with raising-capable calls after "
                            f"it (line {hit.lineno}) — an exception "
                            f"mid-constructor drops the object and "
                            f"leaks the handle (close-and-reraise "
                            f"needed)",
                            _GC12_HINT, ctx.qualname(call)))
                elif attr_store not in credits_for(cls_node):
                    out.append(Finding(
                        "GC12", ctx.relpath, call.lineno,
                        call.col_offset,
                        f"{kind} stored on self.{attr_store} but no "
                        f"method of the class ever releases it "
                        f"(no self.{attr_store}.close()/stop()/"
                        f"loop-release found)",
                        _GC12_HINT, ctx.qualname(call)))
                continue
            if local is None:
                continue

            # local-bound resource: classify every later use
            with_managed = any(
                isinstance(w, ast.With)
                and any((isinstance(it.context_expr, ast.Name)
                         and it.context_expr.id == local)
                        or any(isinstance(x, ast.Name) and x.id == local
                               for x in ast.walk(it.context_expr))
                        for it in w.items)
                for w in nodes if isinstance(w, ast.With))
            if with_managed:
                continue
            exception_protected = False
            plain_close: Optional[int] = None
            for n in nodes:
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in _RELEASE_ATTRS \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id == local:
                    for a in ctx.ancestors(n):
                        if a is fn:
                            break
                        if isinstance(a, ast.Try) and (
                                any(n in ast.walk(x)
                                    for x in a.finalbody)
                                or any(n in ast.walk(h) and
                                       any(isinstance(x, ast.Raise)
                                           for x in ast.walk(h))
                                       for h in a.handlers)):
                            exception_protected = True
                            break
                    if plain_close is None or n.lineno < plain_close:
                        plain_close = n.lineno
            if exception_protected:
                continue
            escape_line: Optional[int] = None
            escapes_self: Optional[str] = None
            for n in nodes:
                if isinstance(n, ast.Assign):
                    stores = [t for t in n.targets
                              if isinstance(t, ast.Attribute)]
                    srcs = {x.id for x in ast.walk(n.value)
                            if isinstance(x, ast.Name)}
                    if local in srcs and stores:
                        escape_line = n.lineno if escape_line is None \
                            else min(escape_line, n.lineno)
                        t0 = stores[0]
                        if isinstance(t0.value, ast.Name) \
                                and t0.value.id in (self_name, "self"):
                            escapes_self = t0.attr
                elif isinstance(n, (ast.Return, ast.Yield)):
                    # ownership transfers only when the HANDLE itself is
                    # returned (bare, or as a tuple/list element) —
                    # `return c.recv(4)` is a use, not a transfer
                    v = getattr(n, "value", None)
                    elems = [v] + (list(v.elts) if isinstance(
                        v, (ast.Tuple, ast.List)) else [])
                    if any(isinstance(e, ast.Name) and e.id == local
                           for e in elems):
                        escape_line = n.lineno if escape_line is None \
                            else min(escape_line, n.lineno)
                elif isinstance(n, ast.Call) and n.lineno > call.lineno:
                    f = n.func
                    own_method = (isinstance(f, ast.Attribute)
                                  and isinstance(f.value, ast.Name)
                                  and f.value.id == local)
                    args_all = list(n.args) + [k.value
                                               for k in n.keywords]
                    if not own_method and any(
                            isinstance(x, ast.Name) and x.id == local
                            for a in args_all for x in ast.walk(a)):
                        escape_line = n.lineno if escape_line is None \
                            else min(escape_line, n.lineno)
            if escape_line is not None:
                # ownership transfers at the escape — but every
                # raising-capable call BETWEEN acquire and escape runs
                # while this frame is the only owner
                window = [n for n in risky_after
                          if n.lineno < escape_line
                          and local not in protected_names(n)]
                if window:
                    hit = min(window, key=lambda n: n.lineno)
                    out.append(Finding(
                        "GC12", ctx.relpath, call.lineno,
                        call.col_offset,
                        f"{kind} '{local}' escapes on line "
                        f"{escape_line} but raising-capable calls run "
                        f"before the handoff (line {hit.lineno}) — an "
                        f"exception in the window leaks the handle "
                        f"(close-and-reraise needed)",
                        _GC12_HINT, ctx.qualname(call)))
                continue
            if plain_close is not None:
                window = [n for n in risky_after
                          if n.lineno < plain_close
                          and local not in protected_names(n)]
                if window:
                    hit = min(window, key=lambda n: n.lineno)
                    out.append(Finding(
                        "GC12", ctx.relpath, call.lineno,
                        call.col_offset,
                        f"{kind} '{local}' closed only on the straight-"
                        f"line path (line {plain_close}) — an exception "
                        f"in a call before it (line {hit.lineno}) "
                        f"leaks the handle (use try/finally or with)",
                        _GC12_HINT, ctx.qualname(call)))
                continue
            out.append(Finding(
                "GC12", ctx.relpath, call.lineno, call.col_offset,
                f"{kind} '{local}' acquired but never closed, escaped "
                f"to an owner, or managed by with/finally on any path",
                _GC12_HINT, ctx.qualname(call)))
    return out


#: rule registry: code -> (function, one-line description)
RULES = {
    "GC01": (gc01_retrace_hazard,
             "retrace-hazard: per-call jit closures / nested compile "
             "factories / fresh-jit factory calls across modules"),
    "GC02": (gc02_clock_discipline,
             "clock-discipline: time.time() in duration arithmetic, "
             "including through helper returns"),
    "GC03": (gc03_atomic_write,
             "atomic-write: bare write-open in io//serve/ outside the "
             "tmp->fsync->os.replace idiom"),
    "GC04": (gc04_lock_discipline,
             "lock-discipline: unsynchronized multi-thread attribute "
             "mutation (incl. via called methods) / acquire() without "
             "with"),
    "GC05": (gc05_surface_parity,
             "surface-parity: stub/live registry key drift + Prometheus "
             "name grammar"),
    "GC06": (gc06_broad_except,
             "broad-except: unannotated `except Exception` in serve//obs/"),
    "GC07": (gc07_transfer_discipline,
             "transfer-discipline: device->host sync reachable inside "
             "models//ops/ hot loops"),
    "GC08": (gc08_thread_lifecycle,
             "thread-lifecycle: long-running threads whose shutdown "
             "path lacks join/poison-pill"),
    "GC09": (gc09_tracer_safety,
             "tracer-safety: np/cast/item/branch concretization of "
             "parameters reachable from a jit/scan/shard_map root"),
    "GC10": (gc10_carry_stability,
             "carry-stability: lax.scan bodies whose returned carry "
             "can diverge from the input pytree structure/dtype"),
    "GC11": (gc11_donation_discipline,
             "donation-discipline: reads of donated buffers after the "
             "call + undonated ops/ scannable step cores"),
    "GC12": (gc12_resource_lifecycle,
             "resource-lifecycle: socket/file/mmap/http handles that "
             "leak on exception paths in serve//io//parallel/"),
}


def run_rules(ctx: ModuleContext, project: ProjectIndex,
              rule_wall: Optional[Dict[str, float]] = None) \
        -> List[Finding]:
    """Run every rule on one module. ``rule_wall`` accumulates per-rule
    wall seconds across calls (the --json-out CI breakdown that keeps
    the <=30 s budget honest as rules are added)."""
    import time as _time
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for code, (fn, _desc) in RULES.items():
        t0 = _time.perf_counter() if rule_wall is not None else 0.0
        got = fn(ctx, project)
        if rule_wall is not None:
            rule_wall[code] = rule_wall.get(code, 0.0) \
                + (_time.perf_counter() - t0)
        for f in got:
            # nested provider closures can satisfy an associator twice
            # (the closure AND its enclosing method) — one finding per
            # (line, code, message) is enough
            key = (f.code, f.line, f.message)
            if key not in seen:
                seen.add(key)
                findings.append(f)
    return findings
