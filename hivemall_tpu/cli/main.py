"""hivemall_tpu CLI — train/predict runners + mixserv packaging.

Reference analogs: the L6/L8 operational surface (SURVEY.md §2, §3.16) —
define-all DDL listing, bin/run_mixserv.sh, and the HiveQL train/predict
queries, here as subcommands:

  python -m hivemall_tpu.cli train   --algo train_classifier \
      --input a9a.libsvm --options '-loss logloss -opt adagrad' \
      --model model.tsv
  python -m hivemall_tpu.cli predict --algo train_classifier \
      --model model.tsv --input a9a.t --output scores.tsv --metric auc
  python -m hivemall_tpu.cli mixserv --port 11212
  python -m hivemall_tpu.cli define-all
  python -m hivemall_tpu.cli help train_ffm
"""

from __future__ import annotations

import argparse
import json
import shlex
import sys
import time


def _is_ffm(trainer) -> bool:
    return getattr(trainer, "F", None) is not None and \
        trainer.NAME == "train_ffm"


def _read_libsvm_for(trainer, path):
    """LIBSVM read with the trainer's parsing needs (FFM triples carry
    field ids; hashed names bound by the trainer's -dims). Shared by the
    train and predict commands so their ingest cannot diverge."""
    from ..io.libsvm import read_libsvm
    if _is_ffm(trainer):
        return read_libsvm(path, ffm=True, num_fields=trainer.F,
                           dims=getattr(trainer, "dims", None))
    return read_libsvm(path)


def _load_input(args, trainer):
    """Route --input by format: LIBSVM file (default), .csv, .parquet file,
    or a DIRECTORY of parquet shards (returns a ParquetStream for
    out-of-core training). FFM trainers get field-aware parsing."""
    import os

    path = args.input
    kw = dict(feature_col=args.feature_col, label_col=args.label_col,
              dims=getattr(trainer, "dims", None))
    if _is_ffm(trainer):
        kw.update(ffm=True, num_fields=trainer.F)
    if os.path.isdir(path):
        from ..io.arrow import ParquetStream
        # the trainer's -shard_cache_dir also caches each shard's decoded
        # CSR columns, so epoch >= 2 / restarts skip Parquet read + parse
        opts = getattr(trainer, "opts", None)
        cache_dir = opts.get("shard_cache_dir") if opts is not None else None
        return ParquetStream(path, cache_dir=cache_dir, **kw), True
    if path.endswith((".parquet", ".pq")):
        from ..io.arrow import read_parquet
        return read_parquet(path, **kw), False
    if path.endswith(".csv"):
        from ..io.arrow import read_csv
        return read_csv(path, label_col=args.label_col,
                        dims=getattr(trainer, "dims", None)), False
    return _read_libsvm_for(trainer, path), False


def _cmd_train(args) -> int:
    from ..catalog import lookup

    if getattr(args, "profile", None):
        # --profile DIR is the HIVEMALL_TPU_PROF env var as a flag: the
        # first fit captures a jax.profiler trace into DIR, routed
        # through obs.devprof (a `profile` jsonl event + span record the
        # capture — docs/OBSERVABILITY.md "Training profiling")
        import os
        os.environ["HIVEMALL_TPU_PROF"] = args.profile
    cls = lookup(args.algo).resolve()
    trainer = cls(args.options or "")
    if args.load_bundle or args.save_bundle \
            or getattr(args, "promote", False):   # fail fast, not post-train
        # every LearnerBase inherits load_bundle/save_bundle, so hasattr is
        # vacuous — probe the actual capability (checkpointable state);
        # --promote gates checkpoint bundles, so it needs the same probe
        try:
            trainer._checkpoint_arrays()
        except (NotImplementedError, AttributeError):
            flag = ("load-bundle" if args.load_bundle
                    else "save-bundle" if args.save_bundle else "promote")
            print(f"error: {args.algo} does not support checkpoint bundles "
                  f"(--{flag})", file=sys.stderr)
            return 2
    if args.load_bundle:
        trainer.load_bundle(args.load_bundle)
    resumed = False
    if getattr(args, "resume", False):
        if args.load_bundle:
            # both flags name a state source; silently letting the newer
            # autosave win would train something other than the bundle the
            # user pinned explicitly
            print("error: --resume and --load-bundle both restore trainer "
                  "state; pass one or the other", file=sys.stderr)
            return 2
        if not hasattr(trainer, "resume"):
            print(f"error: {args.algo} does not support --resume",
                  file=sys.stderr)
            return 2
        resumed = trainer.resume()
        if resumed:
            print(json.dumps({"resumed": True, "step": int(trainer._t),
                              "stream_pos": int(getattr(trainer,
                                                        "_stream_pos", 0))}),
                  file=sys.stderr)
        else:
            print("warning: --resume found no usable checkpoint in "
                  "-checkpoint_dir; starting fresh", file=sys.stderr)
    ds, streaming = _load_input(args, trainer)
    n_examples = len(ds)
    t0 = time.monotonic()
    if streaming:
        if not hasattr(trainer, "fit_stream"):
            print(f"error: {args.algo} cannot train from a shard directory "
                  f"(no streaming path); pass a single file instead",
                  file=sys.stderr)
            return 2
        epochs = int(getattr(trainer.opts, "iters", 1))
        bs = int(getattr(trainer.opts, "mini_batch", 256))
        trainer.fit_stream(ds.batches(bs, epochs=epochs), resume=resumed)
        n_examples *= max(1, epochs)   # the stream runs every epoch itself
        rows = None
    elif hasattr(trainer, "fit"):
        trainer.fit(ds)
        rows = None
    else:
        for i in range(len(ds)):
            trainer.process(ds.row(i), float(ds.labels[i]))
        rows = list(trainer.close())
    dt = time.monotonic() - t0
    if args.save_bundle:
        trainer.save_bundle(args.save_bundle)
    promotion = None
    if getattr(args, "promote", False):
        # train → validate → promote in one command: gate the newest
        # autosaved bundle against the currently-promoted one and flip
        # the PROMOTED pointer on pass (docs/RELIABILITY.md "Promotion
        # and rollback"). A failed gate quarantines the candidate; the
        # training run itself still succeeded (rc 0) — the verdict rides
        # in the final summary record.
        ckdir = getattr(trainer, "opts", {}).get("checkpoint_dir") \
            if hasattr(trainer, "opts") else None
        if not ckdir:
            print("error: --promote needs -checkpoint_dir in --options "
                  "(candidates are gated out of the autosave dir)",
                  file=sys.stderr)
            return 2
        import os
        holdout = args.holdout or args.input
        if os.path.isdir(holdout):
            print("error: --promote needs --holdout <libsvm file> when "
                  "--input is a shard directory", file=sys.stderr)
            return 2
        from ..io.checkpoint import newest_bundle
        from ..serve.promote import PromotionController, PromotionGate
        # make sure the FINAL state is a candidate: fit_stream autosaves
        # land one, but file-input fit() never writes bundles on its own
        nb = newest_bundle(ckdir, trainer.NAME)
        if nb is None or nb[0] < int(getattr(trainer, "_t", 0)):
            os.makedirs(ckdir, exist_ok=True)
            trainer.save_bundle(os.path.join(
                ckdir, f"{trainer.NAME}-step{trainer._t:010d}.npz"))
        gate = PromotionGate(args.algo, args.options or "",
                             holdout=holdout)
        # the local reference keeps the controller alive through the
        # final registry snapshot below — its weakly-held `promotion`
        # provider would otherwise revert to the stub mid-record
        controller = PromotionController(ckdir, gate)
        report = controller.check_once()
        promotion = report if report is not None else {"candidate": None}
        print(json.dumps({"promotion": promotion}, default=str),
              file=sys.stderr)
    if args.model:
        if hasattr(trainer, "save_model"):
            trainer.save_model(args.model)
        elif rows is not None:
            with open(args.model, "w") as f:
                for r in rows:
                    f.write("\t".join(str(x) for x in r) + "\n")
    # prefer the trainer's own processed-examples counter (covers -iters
    # epochs on every path); fall back to the input-size estimate
    n_examples = int(getattr(trainer, "_examples", 0)) or n_examples
    metrics = {"examples": n_examples, "seconds": round(dt, 3),
               "examples_per_sec": round(n_examples / max(dt, 1e-9), 1)}
    if hasattr(trainer, "cumulative_loss"):
        metrics["cumulative_loss"] = round(trainer.cumulative_loss, 6)
    if promotion is not None:
        metrics["promotion"] = {"verdict": promotion.get("verdict"),
                                "promoted": promotion.get("promoted"),
                                "bundle": promotion.get("bundle")}
    # the final record IS the obs-registry snapshot (docs/OBSERVABILITY.md):
    # CLI runs and library runs report one schema — the run summary rides
    # in its `run` section next to pipeline/train/mix/checkpoint/spans.
    # default=str mirrors MetricsStream.emit: a stray numpy scalar in a
    # provider must degrade, not crash a completed run at the last print.
    from ..obs.registry import registry
    registry.register("run", lambda: metrics)
    try:
        print(json.dumps(registry.snapshot(), default=str))
    finally:
        # the registry is process-global: a library caller embedding this
        # CLI must not see a stale `run` section in later snapshots
        registry.unregister("run")
    return 0


def _cmd_bulk_predict(args) -> int:
    """The warehouse path: Parquet shard dir (or one file) scored from a
    checkpoint bundle through io.bulk — packed shard caches, process
    fan-out, kernel/arena backend pick, scored Parquet + logloss/AUC in
    one pass, optional fused score→top-k (docs/PERFORMANCE.md "Bulk
    scoring"). The final record embeds the obs snapshot like train runs,
    so the `bulk` section rides next to ingest_cache/devprof."""
    import os
    from ..io.bulk import bulk_predict
    from ..obs.registry import registry

    result = bulk_predict(
        args.algo, args.input, args.output,
        options=args.options or "",
        bundle=args.bundle, checkpoint_dir=args.checkpoint_dir,
        backend=args.backend, precision=args.precision,
        workers=args.workers, batch_size=args.batch_size or None,
        cache_dir=args.cache_dir, top_k=args.top_k,
        group_col=args.group_col, feature_col=args.feature_col,
        label_col=args.label_col)
    result["snapshot"] = registry.snapshot()
    print(json.dumps(result, default=str))
    return 0


def _cmd_predict(args) -> int:
    import os
    from ..catalog import lookup
    from ..frame.evaluation import auc, logloss, rmse

    if args.bundle or args.checkpoint_dir or os.path.isdir(args.input):
        return _cmd_bulk_predict(args)
    if not args.model:
        print("error: --model (model TSV) is required unless bulk "
              "scoring via --bundle/--checkpoint-dir or a Parquet "
              "directory --input", file=sys.stderr)
        return 2
    cls = lookup(args.algo).resolve()
    trainer = cls((args.options or "")
                  + f" -loadmodel {shlex.quote(args.model)}")
    ds = _read_libsvm_for(trainer, args.input)
    # Classifiers score in probability space (auc/logloss need it);
    # regressors must emit raw predictions — sigmoid-squashing them would
    # make rmse/mae against real-valued labels meaningless.
    # Instance-level `classification` wins over the class default: FM/FFM
    # flip it per the -classification option at construction time.
    classification = getattr(trainer, "classification",
                             getattr(trainer, "CLASSIFICATION", True))
    if classification:
        # predict() sigmoids in classification mode for trainers without a
        # dedicated predict_proba (e.g. FM/FFM).
        scores = (trainer.predict_proba(ds)
                  if hasattr(trainer, "predict_proba") else trainer.predict(ds))
    elif hasattr(trainer, "decision_function"):
        scores = trainer.decision_function(ds)
    else:
        scores = trainer.predict(ds)
    if args.output:
        with open(args.output, "w") as f:
            for i, s in enumerate(scores):
                f.write(f"{i}\t{float(s):.6g}\n")
    out = {"rows": len(ds)}
    if args.metric == "auc":
        out["auc"] = round(auc(ds.labels, scores), 6)
    elif args.metric == "logloss":
        out["logloss"] = round(logloss(ds.labels, scores), 6)
    elif args.metric == "rmse":
        out["rmse"] = round(rmse(ds.labels, scores), 6)
    print(json.dumps(out))
    return 0


def _cmd_retrieve(args) -> int:
    """Offline top-k retrieval over a factor bundle (docs/SERVING.md
    "Retrieval plane"): load MF/BPR/word2vec factors through the weight
    arena, answer ``user→top-k items`` / ``item→k neighbors`` queries
    from the command line, and print one JSON object. The serving twin
    is ``serve --retrieval``."""
    from ..serve.retrieve import RetrievalEngine

    try:
        eng = RetrievalEngine(
            args.algo, args.options or "",
            bundle=args.bundle, checkpoint_dir=args.checkpoint_dir,
            precision=args.precision, k_default=args.k,
            tier=args.tier, rescore=args.rescore)
    except (FileNotFoundError, ValueError, NotImplementedError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        queries = []
        for tok in (args.user.split(",") if args.user else []):
            queries.append({"user": int(tok)})
        for tok in (args.item.split(",") if args.item else []):
            queries.append({"item": int(tok)})
        if not queries:
            print("error: give at least one --user or --item id",
                  file=sys.stderr)
            return 2
        rows = [eng.parse_query(q) for q in queries]
        packed, step = eng.retrieve_rows_versioned(rows)
        results = []
        for i, q in enumerate(queries):
            ids = packed[i, :, 0]
            valid = ids >= 0
            ids = ids[valid].astype(int)
            row = {**q, "ids": [int(v) for v in ids],
                   "scores": [round(float(v), 6)
                              for v in packed[i, valid, 1]]}
            words = eng.labels(ids)
            if words is not None:
                row["words"] = words
            results.append(row)
        print(json.dumps({"results": results, "model_step": int(step),
                          "tier": args.tier,
                          "model_path": eng.model_path}, default=str))
        return 0
    finally:
        eng.close()


def _cmd_mixserv(args) -> int:
    """The bin/run_mixserv.sh analog: a standalone mix server.

    --impl native runs the C++ epoll server (native/mix_server.cpp, the
    reference's Netty-runtime analog; same wire protocol); python runs
    the asyncio implementation (required for --ssl-*); auto prefers
    native when a toolchain built it and no TLS was requested."""
    from ..parallel.mix_service import MixServer, make_server_ssl_context

    ctx = None
    if bool(args.ssl_cert) != bool(args.ssl_key):
        print("--ssl-cert and --ssl-key must be given together",
              file=sys.stderr)
        return 2
    if args.ssl_cert:
        ctx = make_server_ssl_context(args.ssl_cert, args.ssl_key)
    def serve(srv, impl_name: str, ssl_on: bool) -> int:
        print(json.dumps({"host": srv.host, "port": srv.port,
                          "ssl": ssl_on, "impl": impl_name}))
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            srv.stop()
        return 0

    impl = args.impl
    if impl == "native" and ctx is not None:
        print("--impl native has no TLS; use --impl python with --ssl-*",
              file=sys.stderr)
        return 2
    if impl in ("auto", "native") and ctx is None:
        from ..parallel.mix_native import NativeMixServer, native_available
        if native_available():
            try:
                # only STARTUP failures fall back; once bound, serve()
                # owns the process (a post-start error must not leave the
                # native child running while python doubles the listener)
                nsrv = NativeMixServer(args.host, args.port).start()
            except (RuntimeError, OSError) as e:
                # e.g. hostname --host (the C++ server wants numeric IPv4)
                # or a bound port: auto falls back to the asyncio server,
                # an explicit --impl native reports the real cause
                nsrv = None
                if impl == "native":
                    print(f"native mix server failed: {e}", file=sys.stderr)
                    return 1
                print(f"native mix server failed ({e}); "
                      f"falling back to --impl python", file=sys.stderr)
            if nsrv is not None:
                return serve(nsrv, "native", False)
        elif impl == "native":
            print("native mix server unavailable (no g++?)",
                  file=sys.stderr)
            return 1
    return serve(MixServer(args.host, args.port, ssl_context=ctx).start(),
                 "python", bool(ctx))


def _cmd_serve(args) -> int:
    """Online prediction server (docs/SERVING.md): load a checkpoint
    bundle, serve /predict with dynamic micro-batching, hot-reload newer
    autosaved bundles from --checkpoint-dir (a live trainer writing into
    the same directory is the intended pairing).

    ``--replicas N`` switches to the fleet topology (docs/SERVING.md
    "Fleet topology"): N engine processes behind a health-gated router,
    with manager-coordinated rolling hot reload and crash respawn."""
    if args.replicas > 0:
        if args.retrieval:
            print("error: --retrieval is a single-server surface "
                  "(fleet retrieval is not wired yet)", file=sys.stderr)
            return 2
        return _cmd_serve_fleet(args)
    from ..serve.engine import PredictEngine
    from ..serve.http import PredictServer

    retrieval = None
    if args.retrieval:
        from ..serve.retrieve import RetrievalEngine
        try:
            retrieval = RetrievalEngine(
                args.algo, args.options or "",
                bundle=args.bundle, checkpoint_dir=args.checkpoint_dir,
                follow="promoted" if args.promote else "newest",
                precision=args.serve_precision,
                max_batch=args.serve_max_batch,
                k_default=args.retrieval_k,
                tier=args.retrieval_tier,
                watch_interval=args.watch_interval)
        except (FileNotFoundError, ValueError, NotImplementedError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    try:
        engine = PredictEngine(
            args.algo, args.options or "",
            bundle=args.bundle, checkpoint_dir=args.checkpoint_dir,
            max_batch=args.serve_max_batch,
            watch_interval=args.watch_interval,
            warmup=not args.no_warmup,
            follow="promoted" if args.promote else "newest",
            arena=args.serve_arena,
            precision=args.serve_precision)
    except (FileNotFoundError, ValueError, NotImplementedError,
            AttributeError) as e:
        # AttributeError = no make_scorer: pure factor families
        # (MF/BPR/word2vec) have no row-predict surface
        if retrieval is None:
            print(f"error: {e}", file=sys.stderr)
            return 2
        # --retrieval serves them retrieval-only (/predict 404s)
        engine = None
    if args.serve_plane == "evloop":
        from ..serve.evloop import EvloopPredictServer as _ServerCls
    else:
        _ServerCls = PredictServer
    srv = _ServerCls(
        engine, host=args.host, port=args.port,
        max_delay_ms=args.serve_max_delay_ms,
        max_queue_rows=args.serve_max_queue,
        deadline_ms=args.serve_deadline_ms,
        slo_p99_ms=args.slo_p99_ms,
        slo_availability=args.slo_availability,
        retrieval=retrieval).start()
    ctrl = None
    retrain_ctl = None
    if args.promote and args.checkpoint_dir:
        # single-server promotion: the engine follows the pointer; an
        # in-process controller gates candidates out of the autosave
        # dir (shadow-scoring mirrored traffic teed off the batcher)
        from ..serve.promote import (PromotionController, PromotionGate,
                                     ShadowBuffer)
        # --retrain additionally captures the RAW request rows the
        # replay buffer trains on (the label join is a feedback-side
        # concern — without one, retrains run over --train-input only)
        shadow = ShadowBuffer(capture_raw=args.retrain) \
            if engine is not None else None
        if engine is not None:
            srv.batcher.set_tee(shadow.add, raw=args.retrain)
        gate = PromotionGate(args.algo, args.options or "",
                             holdout=args.holdout, shadow=shadow,
                             precision=args.serve_precision)
        ctrl = PromotionController(args.checkpoint_dir, gate,
                                   interval=args.watch_interval,
                                   slo=srv.slo).start()
        if args.retrain and engine is None:
            print("error: --retrain needs a predict surface (the replay "
                  "buffer mirrors /predict traffic)", file=sys.stderr)
            srv.stop()
            return 2
        if args.retrain:
            from ..serve.retrain import RetrainController
            retrain_ctl = RetrainController(
                args.algo, args.options or "",
                checkpoint_dir=args.checkpoint_dir,
                slo=srv.slo, shadow=shadow,
                train_input=args.train_input,
                cooldown_s=args.retrain_cooldown_s,
                min_votes=args.retrain_min_votes,
                max_retrains_per_window=args.retrain_max_per_window,
                interval=args.watch_interval).start()
    elif args.retrain:
        print("error: --retrain needs --promote and --checkpoint-dir "
              "(candidates go through the promotion gate)",
              file=sys.stderr)
        srv.stop()
        return 2
    eng = engine if engine is not None else retrieval
    print(json.dumps({"host": srv.host, "port": srv.port,
                      "algo": args.algo,
                      "model_step": eng.model_step,
                      "model_path": eng.model_path,
                      "retrieval": retrieval is not None}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        if retrain_ctl is not None:
            retrain_ctl.stop()
        if ctrl is not None:
            ctrl.stop()
        srv.stop()
    return 0


def _cmd_serve_fleet(args) -> int:
    """`serve --replicas N`: replica manager + front-end router."""
    from ..serve.fleet import Fleet

    try:
        fleet = Fleet(
            args.algo, args.options or "",
            checkpoint_dir=args.checkpoint_dir, bundle=args.bundle,
            replicas=args.replicas, host=args.host, port=args.port,
            policy=args.router_policy, plane=args.serve_plane,
            watch_interval=args.watch_interval,
            slo_p99_ms=args.slo_p99_ms,
            slo_availability=args.slo_availability,
            trace_sample=args.trace_sample,
            promote=args.promote,
            holdout=args.holdout,
            canary_fraction=args.canary_fraction,
            canary_bake_s=args.canary_bake_s,
            retrain=args.retrain,
            train_input=args.train_input,
            retrain_opts={
                "cooldown_s": args.retrain_cooldown_s,
                "min_votes": args.retrain_min_votes,
                "max_retrains_per_window": args.retrain_max_per_window,
            } if args.retrain else None,
            result_cache_entries=args.router_cache,
            result_cache_bytes=int(args.router_cache_mb * (1 << 20)),
            serve_kwargs={
                "max_batch": args.serve_max_batch,
                "max_delay_ms": args.serve_max_delay_ms,
                "max_queue_rows": args.serve_max_queue,
                "deadline_ms": args.serve_deadline_ms,
                "precision": args.serve_precision,
                "arena": args.serve_arena,
            }).start(wait_ready=True)
    except (FileNotFoundError, ValueError, RuntimeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    ready = sum(1 for h in fleet.router.replicas() if h.ready)
    print(json.dumps({"host": fleet.host, "port": fleet.port,
                      "algo": args.algo, "replicas": args.replicas,
                      "ready_replicas": ready,
                      "policy": args.router_policy,
                      "plane": args.serve_plane,
                      "fleet_step": fleet.manager.fleet_step}), flush=True)
    # SIGTERM (systemd stop, docker stop, kill <pid>) must tear the fleet
    # down like Ctrl-C does — the default handler would kill this process
    # and orphan every replica worker on its ephemeral port
    import signal

    def on_term(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, on_term)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        fleet.stop()
    return 0


def _cmd_promote(args) -> int:
    """Promotion control plane, one dir at a time (docs/RELIABILITY.md
    "Promotion and rollback"): gate the newest candidate bundle against
    the promoted one and flip/quarantine (default), keep watching
    (--watch), print the pointer manifest (--status), or manually revert
    to the previous promotion (--rollback)."""
    from ..io.checkpoint import read_promoted, rollback_promoted

    if args.status:
        m = read_promoted(args.checkpoint_dir)
        print(json.dumps({"configured": m is not None, "manifest": m},
                         default=str))
        return 0
    if args.rollback:
        m = rollback_promoted(args.checkpoint_dir,
                              args.reason or "manual rollback")
        if m is None:
            print("error: no promotion history to roll back to",
                  file=sys.stderr)
            return 1
        print(json.dumps({"rolled_back_to": m["current"],
                          "rollbacks": m["rollbacks"]}, default=str))
        return 0
    from ..serve.promote import PromotionController, PromotionGate
    gate = PromotionGate(
        args.algo, args.options or "", holdout=args.holdout,
        max_logloss_increase=args.max_logloss_increase,
        max_auc_decrease=args.max_auc_decrease,
        max_calibration_gap=args.max_calibration_gap,
        precision=args.precision)
    ctrl = PromotionController(
        args.checkpoint_dir, gate, interval=args.interval,
        promote_state="canary" if args.canary else "serving")
    if not args.watch:
        report = ctrl.check_once()
        if report is None:
            print(json.dumps({"candidate": None,
                              "promoted": read_promoted(
                                  args.checkpoint_dir) is not None}))
            return 0
        print(json.dumps(report, default=str))
        return 0 if report["verdict"] == "pass" else 1
    ctrl.start()
    print(json.dumps({"watching": args.checkpoint_dir,
                      "interval": args.interval}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        ctrl.stop()
    return 0


def _cmd_arena(args) -> int:
    """Publish (or inspect) a bundle's weight-arena sidecar — the
    operator path for fleets that don't run the promotion gate (which
    publishes automatically on every admitted candidate)."""
    from ..catalog import lookup
    from ..io.weight_arena import (ArenaUnsupported, arena_path,
                                   open_arena, publish_arena)
    ap = arena_path(args.bundle)
    if args.status:
        try:
            a = open_arena(ap)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        h = dict(a.header)
        h.pop("arrays", None)            # per-array offsets: noise here
        print(json.dumps({"arena": ap, "mapped_bytes": a.mapped_bytes,
                          "matches_bundle": a.matches_bundle(args.bundle),
                          "header": h}, default=str, indent=1))
        return 0
    try:
        cls = lookup(args.algo).resolve()
        trainer = cls(args.options or "")
        trainer.load_bundle(args.bundle)
        path = publish_arena(args.bundle, trainer)
    except ArenaUnsupported as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except (OSError, ValueError, KeyError, FileNotFoundError) as e:
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2
    a = open_arena(path)
    print(json.dumps({"published": path, "family": a.family,
                      "precisions": list(a.precisions),
                      "mapped_bytes": a.mapped_bytes,
                      "step": a.step}))
    return 0


def _cmd_retrain(args) -> int:
    """Drift-driven retrain autopilot (docs/RELIABILITY.md "Autonomous
    retraining"): consume ``retrain_wanted`` votes (``--slo-url`` polls
    a serve/router ``/slo``), debounce them through cooldown/budget/flap
    storm controls, and launch supervised warm-start retrains whose
    candidates go through the normal promotion gate. ``--once`` forces
    one retrain now; ``--status`` prints the on-disk state."""
    from ..serve.retrain import RetrainController

    votes_fn = None
    if args.slo_url:
        import urllib.request
        url = args.slo_url.rstrip("/")
        if not url.endswith("/slo"):
            url += "/slo"

        def votes_fn() -> int:
            with urllib.request.urlopen(url, timeout=10) as resp:
                drift = json.loads(resp.read()).get("drift") or {}
            return int(drift.get("retrain_wanted") or 0)

    gate = None
    if args.holdout:
        from ..serve.promote import PromotionGate
        gate = PromotionGate(args.algo, args.options or "",
                             holdout=args.holdout)
    ctl = RetrainController(
        args.algo, args.options or "",
        checkpoint_dir=args.checkpoint_dir,
        votes_fn=votes_fn, gate=gate,
        train_input=args.train_input, replay_dir=args.replay_dir,
        min_votes=args.min_votes, cooldown_s=args.cooldown_s,
        window_s=args.window_s,
        max_retrains_per_window=args.max_retrains,
        backoff_factor=args.backoff_factor,
        train_timeout_s=args.train_timeout_s,
        interval=args.interval, batch_size=args.batch_size,
        epochs=args.epochs)
    if args.status:
        print(json.dumps(ctl.status(), default=str))
        return 0
    if args.once:
        # a manual retrain bypasses the vote debounce but still runs
        # the full train -> gate -> promote/quarantine path
        if not ctl.trigger("manual retrain (--once)"):
            print(f"error: {ctl.last_error}", file=sys.stderr)
            return 2
        ctl.wait_idle(timeout=args.train_timeout_s
                      + ctl.gate_timeout_s + 60.0)
        section = ctl.obs_section()
        print(json.dumps(section, default=str))
        return 0 if section["successes"] > 0 else 1
    if not args.slo_url:
        print("error: --watch needs --slo-url <serve/router base> as "
              "the retrain_wanted vote source (or run the controller "
              "in-process via `serve --retrain`)", file=sys.stderr)
        return 2
    ctl.start()
    print(json.dumps({"watching": args.checkpoint_dir,
                      "slo_url": args.slo_url,
                      "interval": args.interval}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        ctl.stop()
    return 0


def _cmd_obs(args) -> int:
    """Live-run summary off a metrics jsonl (docs/OBSERVABILITY.md): event
    counts, training rate, span stage breakdown, MIX breaker state,
    checkpoint age. ``--follow`` re-renders as the file grows. ``--slo``
    instead renders a serving SLO report (burn rates, windowed p99,
    drift events) from a serve/router ``/slo`` endpoint or a saved JSON
    file. ``obs postmortem <dir>`` merges every flight ring under
    ``<dir>`` into one wall-clock-ordered timeline (docs/OBSERVABILITY.md
    "Flight recorder"): death gaps flagged per ring, each victim's
    admitted-but-never-completed request ids, the last ``--tail``
    events. ``--since`` (shared with the jsonl summary) filters to
    seconds-ago (< 1e9) or an absolute epoch."""
    from ..obs.report import parse_since
    since = parse_since(args.since)
    if args.file == "postmortem":
        if not args.target:
            print("obs postmortem: needs a flight-ring directory "
                  "(e.g. <checkpoint_dir>/flight)", file=sys.stderr)
            return 2
        from ..obs.flight import merge_dir, render_postmortem
        merged = merge_dir(args.target, since=since)
        print(render_postmortem(merged, tail=args.tail), end="")
        if not merged["rings"]:
            print(f"obs postmortem: no *.ring files under {args.target}",
                  file=sys.stderr)
            return 1
        return 0
    if args.slo:
        from ..obs.report import render_slo_source
        return render_slo_source(args.file, follow=args.follow,
                                 interval=args.interval)
    from ..obs.report import render_file
    return render_file(args.file, follow=args.follow,
                       interval=args.interval, since=since)


def _cmd_define_all(args) -> int:
    from ..catalog import registry
    dialect = getattr(args, "dialect", "hive")
    fn = {"hive": registry.define_all,
          "spark": registry.define_all_spark,
          "pig": registry.define_all_pig,
          "td": registry.define_udfs_td}[dialect]
    print(fn())
    return 0


def _cmd_help(args) -> int:
    from ..catalog import help_for
    print(help_for(args.function))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="hivemall_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser(
        "train",
        help="train a catalog algorithm on LIBSVM/CSV/Parquet input "
             "(a directory of .parquet shards streams out-of-core)")
    t.add_argument("--algo", required=True)
    t.add_argument("--input", required=True)
    t.add_argument("--feature-col", default="features",
                   help="feature column for parquet/arrow input")
    t.add_argument("--label-col", default="label",
                   help="label column for parquet/csv/arrow input")
    t.add_argument("--options", default="")
    t.add_argument("--model", default=None)
    t.add_argument("--load-bundle", default=None,
                   help="resume from a full-state checkpoint bundle (.npz)")
    t.add_argument("--save-bundle", default=None,
                   help="write a full-state checkpoint bundle at the end")
    t.add_argument("--resume", action="store_true",
                   help="restore the newest usable autosaved bundle from "
                        "the trainer's -checkpoint_dir before training "
                        "(shard-directory input resumes mid-stream; file "
                        "input restarts its epoch with restored state)")
    t.add_argument("--promote", action="store_true",
                   help="after training, gate the newest autosaved bundle "
                        "(-checkpoint_dir) against the promoted one and "
                        "flip the PROMOTED pointer on pass; a failed gate "
                        "quarantines it (docs/RELIABILITY.md)")
    t.add_argument("--holdout", default=None,
                   help="LIBSVM holdout file for the --promote gate "
                        "(default: --input when it is a single file)")
    t.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the first fit "
                        "into DIR (sets HIVEMALL_TPU_PROF; open with "
                        "tensorboard/xprof — the capture is recorded as "
                        "a `profile` event in the metrics stream)")
    t.set_defaults(fn=_cmd_train)

    pr = sub.add_parser(
        "predict",
        help="score a LIBSVM file with a model table, or bulk-score a "
             "Parquet shard dir / file from a checkpoint bundle")
    pr.add_argument("--algo", required=True)
    pr.add_argument("--model", default=None,
                    help="model TSV (-loadmodel) for the single-file path")
    pr.add_argument("--input", required=True)
    pr.add_argument("--output", default=None,
                    help="scores TSV (single-file path) or scored-Parquet "
                         "output dir (bulk path)")
    pr.add_argument("--options", default="")
    pr.add_argument("--metric", default=None,
                    choices=[None, "auc", "logloss", "rmse"])
    # bulk path (docs/PERFORMANCE.md "Bulk scoring"): any of
    # --bundle/--checkpoint-dir, or a directory --input, routes here
    pr.add_argument("--bundle", default=None,
                    help="bulk: score with this checkpoint bundle")
    pr.add_argument("--checkpoint-dir", default=None,
                    help="bulk: resolve the model from this dir's PROMOTED "
                         "pointer (newest bundle if nothing promoted)")
    pr.add_argument("--backend", default="auto",
                    choices=("auto", "kernel", "arena"),
                    help="bulk: jitted kernels, mmap'd arena twins, or "
                         "probe-and-pick (default)")
    pr.add_argument("--precision", default="f32",
                    choices=("f32", "bf16", "int8"),
                    help="bulk: arena scoring tier (non-f32 implies "
                         "--backend arena)")
    pr.add_argument("--workers", type=int, default=1,
                    help="bulk: worker processes (1 = in-process)")
    pr.add_argument("--batch-size", type=int, default=0,
                    help="bulk: override the scoring batch size")
    pr.add_argument("--cache-dir", default=None,
                    help="bulk: shard decode cache dir (share it with "
                         "training's -shard_cache_dir for warm scans)")
    pr.add_argument("--top-k", type=int, default=0,
                    help="bulk: per-group top-k over scored rows "
                         "(each_top_k; negative = bottom-k)")
    pr.add_argument("--group-col", default=None,
                    help="bulk: Parquet group column for --top-k")
    pr.add_argument("--feature-col", default="features")
    pr.add_argument("--label-col", default="label")
    pr.set_defaults(fn=_cmd_predict)

    m = sub.add_parser("mixserv", help="run a standalone mix server")
    m.add_argument("--ssl-cert", default=None,
                   help="TLS certificate file (enables -ssl transport)")
    m.add_argument("--ssl-key", default=None, help="TLS private key file")
    m.add_argument("--host", default="0.0.0.0")
    m.add_argument("--port", type=int, default=11212)
    m.add_argument("--impl", default="auto",
                   choices=("auto", "native", "python"),
                   help="native = C++ epoll server (no TLS), python = "
                        "asyncio, auto = native when available")
    m.set_defaults(fn=_cmd_mixserv)

    sv = sub.add_parser(
        "serve", help="online prediction server over a checkpoint bundle "
                      "(dynamic micro-batching + hot reload; "
                      "docs/SERVING.md)")
    sv.add_argument("--algo", required=True,
                    help="catalog trainer the bundle was written by")
    sv.add_argument("--options", default="",
                    help="trainer options (must match the training config "
                         "— table shapes are validated at load)")
    sv.add_argument("--checkpoint-dir", default=None,
                    help="directory of autosaved step bundles to serve "
                         "and watch for hot reload (may be the live "
                         "trainer's -checkpoint_dir)")
    sv.add_argument("--bundle", default=None,
                    help="explicit bundle (.npz) to serve instead of the "
                         "newest in --checkpoint-dir")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8080)
    sv.add_argument("--serve-max-batch", type=int, default=256,
                    help="max rows coalesced into one predict dispatch")
    sv.add_argument("--serve-max-delay-ms", type=float, default=2.0,
                    help="max milliseconds a request waits for batch "
                         "coalescing")
    sv.add_argument("--serve-max-queue", type=int, default=None,
                    help="bounded queue size in rows (default "
                         "8x max-batch); submits past it are shed with "
                         "503")
    sv.add_argument("--serve-deadline-ms", type=float, default=0.0,
                    help="default per-request deadline (0 = none); "
                         "expired requests get 504")
    sv.add_argument("--watch-interval", type=float, default=2.0,
                    help="seconds between hot-reload checkpoint-dir polls")
    sv.add_argument("--no-warmup", action="store_true",
                    help="skip pre-compiling the batch-size buckets at "
                         "startup")
    sv.add_argument("--serve-precision", default="f32",
                    choices=("f32", "bf16", "int8"),
                    help="scoring precision tier (docs/PERFORMANCE.md "
                         "'Weight arena + quantized scoring'): f32 = "
                         "the bit-exact jitted path; bf16/int8 score "
                         "from the mmap'd weight arena's quantized "
                         "tables (bounded score error, ~2x+ qps on CPU "
                         "hosts, shared weight pages across replicas)")
    sv.add_argument("--serve-plane", default="threaded",
                    choices=("threaded", "evloop"),
                    help="serving plane (docs/SERVING.md 'Serving "
                         "planes'): threaded = thread-per-connection + "
                         "MicroBatcher (default), evloop = epoll event "
                         "loop with inline batch assembly — same "
                         "contracts, lower per-request overhead; in "
                         "fleet mode evloop replicas also expose a UDS "
                         "fast path the co-located router prefers")
    sv.add_argument("--serve-arena", default="auto",
                    choices=("auto", "off", "force"),
                    help="weight-arena policy: auto (quantized tiers "
                         "map the arena, f32 keeps the jitted scorer), "
                         "off (bundle path only), force (f32 also "
                         "scores zero-copy from the arena — ulp-level "
                         "deviation from the jitted path)")
    sv.add_argument("--router-cache", type=int, default=0,
                    help="fleet mode: router-level LRU result cache "
                         "entries for idempotent hot /predict bodies "
                         "(0 = off); invalidated on every reload/"
                         "promotion/rollback, bypassed during canary "
                         "bakes")
    sv.add_argument("--router-cache-mb", type=float, default=8.0,
                    help="fleet mode: result-cache byte bound in MiB")
    sv.add_argument("--replicas", type=int, default=0,
                    help="fleet mode: spawn N replica processes (one "
                         "engine each) behind a health-gated router with "
                         "rolling hot reload and crash respawn; 0 = "
                         "single in-process server")
    sv.add_argument("--router-policy", default="least_loaded",
                    choices=("least_loaded", "hash"),
                    help="fleet routing: least in-flight with "
                         "consistent-hash tiebreak (default), or strict "
                         "consistent hashing of the request body")
    sv.add_argument("--slo-p99-ms", type=float, default=100.0,
                    help="latency SLO: p99 objective in ms — /slo "
                         "reports the fraction of requests over it and "
                         "the burn rate vs the 1%% allowance")
    sv.add_argument("--slo-availability", type=float, default=0.999,
                    help="availability SLO target in (0,1); errors+shed "
                         "burn the error budget (/slo burn rates over "
                         "5m/1h windows)")
    sv.add_argument("--trace-sample", type=float, default=0.01,
                    help="fleet mode: fraction of routed requests the "
                         "router mints an x-hivemall-trace id for when "
                         "HIVEMALL_TPU_TRACE is enabled (client-supplied "
                         "ids are always honored)")
    sv.add_argument("--promote", action="store_true",
                    help="gated promotion: serve the PROMOTED pointer "
                         "instead of the newest bundle; new candidates "
                         "are gated (holdout + mirrored-traffic shadow "
                         "scoring) and, in fleet mode, canaried onto "
                         "--canary-fraction of replicas with auto-"
                         "rollback (docs/RELIABILITY.md)")
    sv.add_argument("--holdout", default=None,
                    help="LIBSVM holdout file the promotion gate scores "
                         "candidates against (omit to gate on digest + "
                         "mirrored traffic only)")
    sv.add_argument("--canary-fraction", type=float, default=0.25,
                    help="fleet --promote: fraction of replicas a "
                         "passing candidate bakes on before the full "
                         "roll (at least 1, at most replicas-1)")
    sv.add_argument("--canary-bake-s", type=float, default=10.0,
                    help="fleet --promote: seconds the canary cohort's "
                         "SLO totals are watched against the stable "
                         "cohort before completing the roll")
    sv.add_argument("--retrain", action="store_true",
                    help="autonomous drift-driven retraining (needs "
                         "--promote): consume the SLO engine's "
                         "retrain_wanted votes, warm-start retrains "
                         "from the PROMOTED bundle over --train-input "
                         "+ the live replay buffer, and gate the "
                         "candidates (docs/RELIABILITY.md)")
    sv.add_argument("--train-input", default=None,
                    help="base corpus for --retrain (LIBSVM file or a "
                         "directory of parquet shards; epochs go "
                         "through the shard caches when -shard_cache_"
                         "dir is in --options)")
    sv.add_argument("--retrain-cooldown-s", type=float, default=300.0,
                    help="--retrain: per-model cooldown after every "
                         "attempt (rejections back off exponentially)")
    sv.add_argument("--retrain-min-votes", type=int, default=2,
                    help="--retrain: drift votes within the vote "
                         "window needed to trigger")
    sv.add_argument("--retrain-max-per-window", type=int, default=4,
                    help="--retrain: max retrains per hour window")
    sv.add_argument("--retrieval", action="store_true",
                    help="also serve /retrieve top-k over the factor "
                         "tables (MF/BPR/word2vec; docs/SERVING.md "
                         "'Retrieval plane'): user→top-k items and "
                         "item→k neighbors, own batcher, hot reload "
                         "shared with /predict")
    sv.add_argument("--retrieval-tier", default="exact",
                    choices=("exact", "lsh"),
                    help="default candidate tier for /retrieve: exact "
                         "full scan (bit-matches each_top_k) or SRP-LSH "
                         "candidates + exact rescore (per-query "
                         "override via the 'tier' field)")
    sv.add_argument("--retrieval-k", type=int, default=10,
                    help="default k for /retrieve queries that omit it")
    sv.set_defaults(fn=_cmd_serve)

    rv = sub.add_parser(
        "retrieve",
        help="offline top-k retrieval over a factor bundle (user→items "
             "/ item→neighbors; the one-shot twin of serve "
             "--retrieval)")
    rv.add_argument("--algo", required=True,
                    help="factor trainer the bundle was written by "
                         "(train_mf_sgd, train_bprmf, train_word2vec)")
    rv.add_argument("--options", default="",
                    help="trainer options (must match the training "
                         "config — table shapes are validated at load)")
    rv.add_argument("--bundle", default=None,
                    help="explicit bundle (.npz) to query")
    rv.add_argument("--checkpoint-dir", default=None,
                    help="resolve the model from this dir (PROMOTED "
                         "pointer first, else newest bundle)")
    rv.add_argument("--user", default=None,
                    help="comma-separated user ids → top-k items each")
    rv.add_argument("--item", default=None,
                    help="comma-separated item ids → k neighbors each")
    rv.add_argument("-k", type=int, default=10,
                    help="results per query")
    rv.add_argument("--tier", default="exact", choices=("exact", "lsh"),
                    help="exact full scan or LSH candidates + exact "
                         "rescore")
    rv.add_argument("--precision", default="f32",
                    choices=("f32", "bf16", "int8"),
                    help="arena scoring tier for the rescore")
    rv.add_argument("--rescore", default="auto",
                    choices=("auto", "numpy", "kernel"),
                    help="rescore backend: numpy arena twins, jitted "
                         "kernels, or probe-and-pick (default)")
    rv.set_defaults(fn=_cmd_retrieve)

    rt = sub.add_parser(
        "retrain",
        help="drift-driven retrain controller: turn retrain_wanted "
             "votes into gated warm-start retrains "
             "(docs/RELIABILITY.md \"Autonomous retraining\")")
    rt.add_argument("--algo", required=True,
                    help="catalog trainer the bundles were written by")
    rt.add_argument("--options", default="",
                    help="trainer options (must match training)")
    rt.add_argument("--checkpoint-dir", required=True,
                    help="autosave dir holding the PROMOTED pointer, "
                         "candidates, replay segments and the "
                         "RETRAIN_STATE stamp")
    rt.add_argument("--train-input", default=None,
                    help="base corpus (LIBSVM file or parquet shard "
                         "dir) retrains run over, in addition to the "
                         "replay buffer")
    rt.add_argument("--replay-dir", default=None,
                    help="replay segment dir (default: <checkpoint-"
                         "dir>/replay)")
    rt.add_argument("--holdout", default=None,
                    help="LIBSVM holdout: gate candidates HERE instead "
                         "of leaving them to an external promote "
                         "watcher / fleet manager")
    rt.add_argument("--slo-url", default=None,
                    help="serve/router base URL whose /slo drift "
                         "counters are the retrain_wanted vote source")
    rt.add_argument("--watch", action="store_true",
                    help="keep consuming votes until Ctrl-C (default "
                         "when neither --once nor --status)")
    rt.add_argument("--once", action="store_true",
                    help="force one retrain now (bypasses the vote "
                         "debounce, still gated); rc 0 promoted, 1 "
                         "rejected/failed")
    rt.add_argument("--status", action="store_true",
                    help="print the controller state + on-disk stamp "
                         "and exit")
    rt.add_argument("--cooldown-s", type=float, default=300.0,
                    help="per-model cooldown seconds after every "
                         "attempt (storm control)")
    rt.add_argument("--min-votes", type=int, default=2,
                    help="votes within the vote window needed to "
                         "trigger")
    rt.add_argument("--window-s", type=float, default=3600.0,
                    help="storm-control window seconds")
    rt.add_argument("--max-retrains", type=int, default=4,
                    help="max retrains per --window-s (storm control)")
    rt.add_argument("--backoff-factor", type=float, default=2.0,
                    help="cooldown multiplier per consecutive gate "
                         "rejection")
    rt.add_argument("--train-timeout-s", type=float, default=900.0,
                    help="kill a retrain child past this wall time")
    rt.add_argument("--interval", type=float, default=2.0,
                    help="controller tick interval seconds")
    rt.add_argument("--batch-size", type=int, default=64,
                    help="retrain mini-batch rows")
    rt.add_argument("--epochs", type=int, default=1,
                    help="epochs over the retrain input")
    rt.set_defaults(fn=_cmd_retrain)

    pm = sub.add_parser(
        "promote",
        help="gate candidate checkpoint bundles and manage the PROMOTED "
             "pointer (shadow validation, rollback; docs/RELIABILITY.md)")
    pm.add_argument("--algo", required=True,
                    help="catalog trainer the bundles were written by")
    pm.add_argument("--options", default="",
                    help="trainer options (must match training)")
    pm.add_argument("--checkpoint-dir", required=True,
                    help="autosave dir holding candidates + the pointer")
    pm.add_argument("--holdout", default=None,
                    help="LIBSVM holdout the gate scores candidates on")
    pm.add_argument("--watch", action="store_true",
                    help="keep gating new candidates until Ctrl-C")
    pm.add_argument("--interval", type=float, default=2.0,
                    help="--watch poll interval seconds")
    pm.add_argument("--canary", action="store_true",
                    help="promote with state=canary so a promote-mode "
                         "fleet bakes it on a canary cohort first")
    pm.add_argument("--status", action="store_true",
                    help="print the PROMOTED pointer manifest and exit")
    pm.add_argument("--rollback", action="store_true",
                    help="revert the pointer to the previous promotion")
    pm.add_argument("--reason", default=None,
                    help="reason recorded with --rollback")
    pm.add_argument("--max-logloss-increase", type=float, default=0.05,
                    help="gate: max absolute holdout logloss increase vs "
                         "the promoted baseline")
    pm.add_argument("--max-auc-decrease", type=float, default=0.02,
                    help="gate: max holdout AUC decrease vs baseline")
    pm.add_argument("--max-calibration-gap", type=float, default=0.15,
                    help="gate: max |mean predicted prob - positive "
                         "rate| on the holdout")
    pm.add_argument("--precision", default="f32",
                    choices=("f32", "bf16", "int8"),
                    help="gate candidates at this scoring precision — "
                         "quantized fleets must gate on the quantized "
                         "scores they actually serve")
    pm.set_defaults(fn=_cmd_promote)

    ar = sub.add_parser(
        "arena",
        help="publish or inspect a bundle's mmap'd weight arena "
             "(zero-copy multi-precision serving weights; "
             "docs/PERFORMANCE.md 'Weight arena + quantized scoring')")
    ar.add_argument("--algo", required=True,
                    help="catalog trainer the bundle was written by")
    ar.add_argument("--options", default="",
                    help="trainer options (must match training)")
    ar.add_argument("--bundle", required=True,
                    help="checkpoint bundle (.npz) to publish/inspect "
                         "the arena for")
    ar.add_argument("--status", action="store_true",
                    help="print the existing arena's header instead of "
                         "publishing")
    ar.set_defaults(fn=_cmd_arena)

    o = sub.add_parser(
        "obs", help="summarize a HIVEMALL_TPU_METRICS jsonl stream "
                    "(rates, stage breakdown, breaker state, checkpoint "
                    "age); `obs postmortem <dir>` merges flight-recorder "
                    "rings into one post-mortem timeline")
    o.add_argument("file", help="metrics jsonl path (or, with --slo, a "
                                "serve/router base URL or /slo JSON "
                                "file); or the literal word `postmortem`")
    o.add_argument("target", nargs="?", default=None,
                   help="with `postmortem`: the flight-ring directory "
                        "(e.g. <checkpoint_dir>/flight)")
    o.add_argument("--follow", action="store_true",
                   help="keep watching; re-render when the file grows")
    o.add_argument("--interval", type=float, default=2.0,
                   help="--follow poll interval seconds")
    o.add_argument("--since", default=None, metavar="SECS",
                   help="only events in the window: seconds-ago when "
                        "< 1e9 (--since 300 = last 5 minutes) or an "
                        "absolute epoch timestamp; shared by the jsonl "
                        "summary and `obs postmortem`")
    o.add_argument("--tail", type=int, default=200,
                   help="postmortem: show the last N merged events")
    o.add_argument("--slo", action="store_true",
                   help="render a serving SLO report instead: FILE is a "
                        "http(s)://host:port serve/router base (its /slo "
                        "endpoint is fetched) or a saved /slo JSON file")
    o.set_defaults(fn=_cmd_obs)

    d = sub.add_parser("define-all", help="print the function manifest")
    d.add_argument("--dialect", default="hive",
                   choices=("hive", "spark", "pig", "td"),
                   help="registration dialect (define-all.hive/.spark/"
                        ".pig / define-udfs.td.hql analogs)")
    d.set_defaults(fn=_cmd_define_all)

    h = sub.add_parser("help", help="show a function's option grammar")
    h.add_argument("function")
    h.set_defaults(fn=_cmd_help)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
