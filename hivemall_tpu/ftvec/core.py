"""ftvec root helpers — feature-string make/split (SURVEY.md §3.12 root row).

Reference package: hivemall.ftvec.{AddBiasUDF,ExtractFeatureUDF,
ExtractWeightUDF,FeatureUDF,AddFeatureIndexUDF,SortByFeatureUDF}.
Feature strings are "name:value" (bare "name" means value 1.0), split on the
LAST ':' so names may contain colons.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

__all__ = ["add_bias", "extract_feature", "extract_weight", "feature",
           "add_feature_index", "sort_by_feature"]

BIAS_CLAUSE = "0:1.0"


def add_bias(features: Sequence[str]) -> List[str]:
    """SQL: add_bias(features) — append the constant bias feature "0:1.0"."""
    return list(features) + [BIAS_CLAUSE]


def _split(f: str):
    name, sep, v = str(f).rpartition(":")
    if not sep:
        return str(f), None
    return name, v


def extract_feature(feature_str: str) -> str:
    """SQL: extract_feature("idx:val") -> "idx"."""
    return _split(feature_str)[0]


def extract_weight(feature_str: str) -> float:
    """SQL: extract_weight("idx:val") -> val (1.0 when absent)."""
    v = _split(feature_str)[1]
    return 1.0 if v is None else float(v)


def feature(name, value=None) -> str:
    """SQL: feature(name[, value]) — build a "name:value" string."""
    return str(name) if value is None else f"{name}:{value}"


def add_feature_index(values: Sequence[float]) -> List[str]:
    """SQL: add_feature_index(array<double>) -> ["1:v1", "2:v2", ...]."""
    return [f"{i + 1}:{v}" for i, v in enumerate(values)]


def sort_by_feature(feature_map: Dict) -> Dict:
    """SQL: sort_by_feature(map) — map sorted by (int-able) feature key."""
    def key(k):
        try:
            return (0, int(k))
        except (TypeError, ValueError):
            return (1, str(k))
    return dict(sorted(feature_map.items(), key=lambda kv: key(kv[0])))
