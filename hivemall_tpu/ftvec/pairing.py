"""ftvec.pairing — explicit feature crosses (SURVEY.md §3.12 pairing row).

Reference: hivemall.ftvec.pairing.{PolynomialFeaturesUDF,PoweredFeaturesUDF}.
"""

from __future__ import annotations

from itertools import combinations_with_replacement
from typing import List, Sequence

from ..utils.options import OptionSpec
from .core import _split

__all__ = ["polynomial_features", "powered_features"]

POLY_SPEC = (OptionSpec("polynomial_features")
             .add("degree", type=int, default=2, help="max cross degree")
             .flag("interaction_only", help="exclude self-powers (x_i^2)")
             .flag("truncate", help="drop terms that include a 0/1-valued "
                                    "feature raised beyond power 1"))


def polynomial_features(features: Sequence[str], options: str = "-degree 2"
                        ) -> List[str]:
    """SQL: polynomial_features(features, '-degree d [-interaction_only]
    [-truncate]') — all monomials up to degree d over the row's features,
    named "a^b^c" with multiplied values."""
    ns = POLY_SPEC.parse(options)
    d = int(ns.degree)
    parsed = []
    for f in features:
        name, v = _split(f)
        parsed.append((name, 1.0 if v is None else float(v)))
    out = [f"{n}:{v}" for n, v in parsed]
    for deg in range(2, d + 1):
        for combo in combinations_with_replacement(range(len(parsed)), deg):
            if ns.interaction_only and len(set(combo)) != len(combo):
                continue
            if ns.truncate and any(
                    parsed[i][1] in (0.0, 1.0) and combo.count(i) > 1
                    for i in combo):
                continue
            name = "^".join(parsed[i][0] for i in combo)
            v = 1.0
            for i in combo:
                v *= parsed[i][1]
            out.append(f"{name}:{v}")
    return out


def powered_features(features: Sequence[str], degree: int = 2) -> List[str]:
    """SQL: powered_features(features, degree) — adds x_i^p terms named
    "name^p" for p in [2, degree]."""
    parsed = []
    for f in features:
        name, v = _split(f)
        parsed.append((name, 1.0 if v is None else float(v)))
    out = [f"{n}:{v}" for n, v in parsed]
    for p in range(2, degree + 1):
        out.extend(f"{n}^{p}:{v ** p}" for n, v in parsed)
    return out
