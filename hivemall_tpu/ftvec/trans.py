"""ftvec.trans — declarative row->feature-array builders (SURVEY.md §3.12
trans row). ``ffm_features`` is load-bearing for train_ffm (BASELINE #2).

Reference: hivemall.ftvec.trans.{BinarizeLabelUDTF,CategoricalFeaturesUDF,
QuantitativeFeaturesUDF,VectorizeFeaturesUDF,IndexedFeatures,
OnehotEncodingUDAF,FFMFeaturesUDF}.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ..utils.hashing import DEFAULT_NUM_FEATURES, mhash
from .conv import quantify

__all__ = ["binarize_label", "categorical_features", "quantitative_features",
           "vectorize_features", "indexed_features", "onehot_encoding",
           "ffm_features", "quantified_features"]


def categorical_features(names: Sequence[str], *values) -> List[str]:
    """SQL: categorical_features(array('col1',...), v1, ...) ->
    ["col1#v1", ...] (None values skipped)."""
    out = []
    for n, v in zip(names, values):
        if v is not None:
            out.append(f"{n}#{v}")
    return out


def quantitative_features(names: Sequence[str], *values) -> List[str]:
    """SQL: quantitative_features(array('col1',...), v1, ...) ->
    ["col1:v1", ...]."""
    out = []
    for n, v in zip(names, values):
        if v is not None:
            out.append(f"{n}:{float(v)}")
    return out


def vectorize_features(names: Sequence[str], *values) -> List[str]:
    """SQL: vectorize_features — categorical for strings, quantitative for
    numbers (the reference's combined builder)."""
    out = []
    for n, v in zip(names, values):
        if v is None:
            continue
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            if float(v) != 0.0:
                out.append(f"{n}:{float(v)}")
        else:
            out.append(f"{n}#{v}")
    return out


def indexed_features(*values) -> List[str]:
    """SQL: indexed_features(v1, v2, ...) -> ["1:v1", "2:v2", ...]."""
    return [f"{i + 1}:{float(v)}" for i, v in enumerate(values)
            if v is not None]


def binarize_label(pos_count: int, neg_count: int, *payload
                   ) -> Iterator[Tuple]:
    """SQL: binarize_label(pos, neg, features...) — UDTF expanding aggregated
    (pos, neg) counts back into one row per observation with label 1/0."""
    for _ in range(int(pos_count)):
        yield tuple(payload) + (1,)
    for _ in range(int(neg_count)):
        yield tuple(payload) + (0,)


def onehot_encoding(columns: Sequence[Sequence]) -> Dict:
    """SQL: onehot_encoding(col1, col2, ...) UDAF — a global category->index
    map per column, indices contiguous across columns (reference semantics:
    sorted per column, offset by previous columns' cardinality)."""
    out: Dict[int, Dict] = {}
    offset = 1
    for ci, col in enumerate(columns):
        cats = sorted({v for v in col if v is not None}, key=str)
        out[ci] = {c: offset + i for i, c in enumerate(cats)}
        offset += len(cats)
    return out


class quantified_features(quantify):
    """SQL: quantified_features(col1, col2, ...) — emit array<double> per row
    with categorical columns replaced by dense int codes (first-seen order
    over the stream) and numbers passed through.

    Reference: hivemall.ftvec.trans.QuantifiedFeaturesUDTF — the feature-array
    sibling of conv.quantify (SURVEY.md §3.12 trans row), so it shares
    quantify's encoder state machine and differs only in emitting doubles.
    Unlike the reference UDTF there is no leading ``output_row`` boolean: the
    reference uses it to gate row emission under Hive's streaming contract,
    which a stateful Python callable doesn't need. Stateful:

        q = quantified_features()
        vecs = [q(row) for row in rows]
    """

    def __call__(self, row: Sequence) -> List[float]:
        return [float(x) for x in super().__call__(row)]


def ffm_features(names: Sequence[str], *values,
                 num_features: int = DEFAULT_NUM_FEATURES,
                 num_fields: int = 1024) -> List[str]:
    """SQL: ffm_features(array('col1',...), v1, ...) ->
    ["<field>:<index>:<value>", ...] for train_ffm.

    field = column position (0-based); index = hashed "col#value" for
    categoricals / hashed "col" for numerics; value = 1 or the number.
    Reference: hivemall.ftvec.trans.FFMFeaturesUDF."""
    out = []
    for fi, (n, v) in enumerate(zip(names, values)):
        if v is None:
            continue
        field = fi % num_fields
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            idx = mhash(str(n), num_features - 1)
            out.append(f"{field}:{idx}:{float(v)}")
        else:
            idx = mhash(f"{n}#{v}", num_features - 1)
            out.append(f"{field}:{idx}:1")
    return out
