"""ftvec.conv — sparse<->dense conversion (SURVEY.md §3.12 conv row).

Reference: hivemall.ftvec.conv.{ToDenseFeaturesUDF,ToSparseFeaturesUDF,
QuantifyColumnsUDTF}.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from .core import _split

__all__ = ["to_dense_features", "to_sparse_features", "quantify"]


def to_dense_features(features: Sequence[str], size: int) -> List[float]:
    """SQL: to_dense_features(features, size) — dense double[size+1] by index."""
    out = [0.0] * (size + 1)
    for f in features:
        name, v = _split(f)
        i = int(name)
        if 0 <= i <= size:
            out[i] = 1.0 if v is None else float(v)
    return out


def to_sparse_features(dense: Sequence[float]) -> List[str]:
    """SQL: to_sparse_features(array<double>) — "i:v" for nonzero cells."""
    return [f"{i}:{v}" for i, v in enumerate(dense) if v not in (None, 0.0)]


class quantify:
    """SQL: quantify(output_row, col1, col2, ...) — UDTF assigning dense int
    codes to string columns over the whole stream (first-seen order), the
    reference's QuantifyColumnsUDTF. Use as a stateful transform:

        q = quantify()
        coded_rows = [q(row) for row in rows]
    """

    def __init__(self) -> None:
        self._maps: List[Dict[str, int]] = []

    def __call__(self, row: Sequence) -> List[int]:
        while len(self._maps) < len(row):
            self._maps.append({})
        out = []
        for i, v in enumerate(row):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out.append(v)
                continue
            m = self._maps[i]
            out.append(m.setdefault(v, len(m)))
        return out

    def mapping(self, col: int) -> Dict[str, int]:
        return dict(self._maps[col])
