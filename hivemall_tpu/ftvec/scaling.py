"""ftvec.scaling — rescale/zscore/normalize (SURVEY.md §3.12 scaling row).

Reference: hivemall.ftvec.scaling.{RescaleUDF,ZScoreUDF,L1NormalizationUDF,
L2NormalizationUDF}. Scalar forms take raw doubles; the array forms operate
on "name:value" feature strings (per-row normalization).
"""

from __future__ import annotations

import math
from typing import List, Sequence

from .core import _split

__all__ = ["rescale", "zscore", "l1_normalize", "l2_normalize"]


def rescale(value: float, minv: float, maxv: float) -> float:
    """SQL: rescale(v, min, max) — min-max to [0, 1] (0.5 when min==max)."""
    if maxv == minv:
        return 0.5
    return (float(value) - minv) / (maxv - minv)


def zscore(value: float, mean: float, stddev: float) -> float:
    """SQL: zscore(v, mean, stddev)."""
    if stddev == 0.0:
        return 0.0
    return (float(value) - mean) / stddev


def _norm(features: Sequence[str], p: int) -> List[str]:
    parsed = []
    for f in features:
        name, v = _split(f)
        parsed.append((name, 1.0 if v is None else float(v)))
    if p == 1:
        z = sum(abs(v) for _, v in parsed)
    else:
        z = math.sqrt(sum(v * v for _, v in parsed))
    if z == 0.0:
        return [f"{n}:0.0" for n, _ in parsed]
    return [f"{n}:{v / z}" for n, v in parsed]


def l1_normalize(features: Sequence[str]) -> List[str]:
    """SQL: l1_normalize(features) — row scaled to unit L1 norm."""
    return _norm(features, 1)


def l2_normalize(features: Sequence[str]) -> List[str]:
    """SQL: l2_normalize(features) — row scaled to unit L2 norm."""
    return _norm(features, 2)
