"""ftvec.binning — quantile binning (SURVEY.md §3.12 binning row, v0.5-era).

Reference: hivemall.ftvec.binning.{BuildBinsUDAF,FeatureBinningUDF}.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["build_bins", "feature_binning"]


def build_bins(values: Sequence[float], num_bins: int,
               auto_shrink: bool = False) -> List[float]:
    """SQL: build_bins(value, num_bins[, auto_shrink]) UDAF -> quantile bin
    edges [-inf, q1, ..., q_{n-1}, +inf]."""
    v = np.asarray([x for x in values if x is not None], np.float64)
    if num_bins < 2:
        raise ValueError("num_bins must be >= 2")
    qs = np.quantile(v, np.linspace(0, 1, num_bins + 1)[1:-1]) if v.size \
        else np.zeros(num_bins - 1)
    edges = [-np.inf] + list(qs) + [np.inf]
    if auto_shrink:
        uniq = sorted(set(edges))
        edges = uniq if len(uniq) >= 2 else [-np.inf, np.inf]
    return edges


def feature_binning(value: float, bins: Sequence[float]) -> int:
    """SQL: feature_binning(value, bins) -> bin index in [0, len(bins)-2]."""
    b = np.asarray(bins, np.float64)
    return int(np.clip(np.searchsorted(b, value, side="right") - 1,
                       0, len(b) - 2))
