"""ftvec.hashing — the hashing trick (SURVEY.md §3.12 hashing row) [B].

Reference: hivemall.ftvec.hashing.{FeatureHashingUDF,MurmurHash3UDF,
ArrayHashValuesUDF,ArrayPrefixedHashValuesUDF}, hivemall.tools.text Sha1UDF.
murmur3 itself lives in utils.hashing (bit-exact, vectorized).
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

from ..utils.hashing import DEFAULT_NUM_FEATURES, mhash
from ..utils.options import OptionSpec

__all__ = ["feature_hashing", "array_hash_values", "prefixed_hash_values",
           "sha1"]

FEATURE_HASHING_SPEC = (OptionSpec("feature_hashing")
                        .add("features", "num_features", type=int,
                             default=DEFAULT_NUM_FEATURES,
                             help="hashed feature-space size"))


def feature_hashing(features: Sequence[str], options: str = "") -> List[str]:
    """SQL: feature_hashing(array<string>[, '-features N']).

    Hash each non-integer feature name into [1, N] keeping values; integer
    indices pass through untouched (so already-hashed or libsvm-style input
    is stable under re-application), matching the reference UDF.
    """
    ns = FEATURE_HASHING_SPEC.parse(options)
    n = int(ns.features)
    out: List[str] = []
    for f in features:
        if f is None:
            continue
        name, sep, v = str(f).rpartition(":")
        if not sep:
            name, v = str(f), None
        try:
            int(name)
            out.append(str(f))
            continue
        except ValueError:
            pass
        h = mhash(name, n)
        out.append(f"{h}:{v}" if v is not None else str(h))
    return out


def array_hash_values(values: Sequence[str], prefix: Optional[str] = None,
                      num_features: int = DEFAULT_NUM_FEATURES) -> List[int]:
    """SQL: array_hash_values(array<string>[, prefix]) -> array<int>."""
    p = prefix or ""
    return [mhash(p + str(v), num_features) for v in values if v is not None]


def prefixed_hash_values(values: Sequence[str], prefix: str,
                         num_features: int = DEFAULT_NUM_FEATURES
                         ) -> List[str]:
    """SQL: prefixed_hash_values(array, prefix) -> ["<hash(prefix#v)>", ...]."""
    return [str(mhash(f"{prefix}#{v}", num_features))
            for v in values if v is not None]


def sha1(word: str, num_features: int = DEFAULT_NUM_FEATURES) -> int:
    """SQL: sha1(word) — SHA1-based feature hash into [1, N]."""
    d = hashlib.sha1(str(word).encode("utf-8")).digest()
    h = int.from_bytes(d[:4], "big", signed=True)
    return h % num_features + 1
