"""ftvec.ranking — negative-sampling UDTFs for implicit-feedback training
(SURVEY.md §3.7 last row).

Reference: hivemall.ftvec.ranking.{BprSamplingUDTF,ItemPairsSamplingUDTF,
PopulateNotInUDTF}: generate (user, pos, neg) / (pos, neg) training pairs
from positive-only interaction lists.
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import numpy as np

__all__ = ["bpr_sampling", "item_pairs_sampling", "populate_not_in"]


def bpr_sampling(user: int, pos_items: Sequence[int], max_item_id: int,
                 sampling_rate: float = 1.0, seed: int | None = None
                 ) -> Iterator[Tuple[int, int, int]]:
    """SQL: bpr_sampling(user, pos_items, max_item_id[, rate]) — emit
    (user, pos, neg) triples, neg uniform over items not in pos_items;
    about rate * |pos| triples per user."""
    pos = set(int(p) for p in pos_items)
    if not pos or max_item_id <= len(pos) - 1:
        return
    rng = np.random.default_rng(seed)
    n_emit = max(1, int(round(len(pos) * sampling_rate)))
    pos_arr = np.fromiter(pos, np.int64)
    for _ in range(n_emit):
        p = int(pos_arr[rng.integers(len(pos_arr))])
        while True:
            n = int(rng.integers(0, max_item_id + 1))
            if n not in pos:
                break
        yield (int(user), p, n)


def item_pairs_sampling(pos_items: Sequence[int], max_item_id: int,
                        sampling_rate: float = 1.0, seed: int | None = None
                        ) -> Iterator[Tuple[int, int]]:
    """SQL: item_pairs_sampling(pos_items, max_item_id[, rate]) — emit
    (pos_item, neg_item) pairs."""
    for _, p, n in bpr_sampling(0, pos_items, max_item_id, sampling_rate,
                                seed):
        yield (p, n)


def populate_not_in(items: Sequence[int], max_item_id: int
                    ) -> Iterator[int]:
    """SQL: populate_not_in(items, max_item_id) — emit every id in
    [0, max_item_id] not present in items."""
    have = set(int(i) for i in items)
    for i in range(max_item_id + 1):
        if i not in have:
            yield i
