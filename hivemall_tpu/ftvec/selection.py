"""ftvec.selection — chi2 / SNR feature selection (SURVEY.md §3.12 selection).

Reference: hivemall.ftvec.selection.{ChiSquareUDF,SignalNoiseRatioUDAF},
backed by tools.matrix transpose_and_dot accumulation.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

__all__ = ["chi2", "snr"]


def chi2(observed: np.ndarray, expected: np.ndarray
         ) -> Tuple[np.ndarray, np.ndarray]:
    """SQL: chi2(observed, expected) -> (chi2 stats, p-values) per feature.

    observed/expected: [n_classes, n_features] aggregates (the reference
    computes them with transpose_and_dot over one-hot labels x features).
    """
    obs = np.asarray(observed, np.float64)
    exp = np.asarray(expected, np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = np.where(exp > 0, (obs - exp) ** 2 / exp, 0.0)
    stat = terms.sum(axis=0)
    dof = max(1, obs.shape[0] - 1)
    p = _chi2_sf(stat, dof)
    return stat, p


def _chi2_sf(x: np.ndarray, k: int) -> np.ndarray:
    """Chi-square survival function via the regularized upper incomplete
    gamma Q(k/2, x/2) (series/continued-fraction, no scipy dependency)."""
    x = np.asarray(x, np.float64)
    return np.vectorize(lambda v: _gammaincc(k / 2.0, v / 2.0))(x)


def _gammaincc(a: float, x: float) -> float:
    if x < 0 or a <= 0:
        return 1.0
    if x == 0:
        return 1.0
    import math
    if x < a + 1:
        # lower series -> P, return 1-P
        term = 1.0 / a
        s = term
        for n in range(1, 500):
            term *= x / (a + n)
            s += term
            if abs(term) < abs(s) * 1e-15:
                break
        P = s * math.exp(-x + a * math.log(x) - math.lgamma(a))
        return max(0.0, 1.0 - P)
    # continued fraction for Q
    b = x + 1 - a
    c = 1e300
    d = 1.0 / b
    h = d
    for i in range(1, 500):
        an = -i * (i - a)
        b += 2
        d = an * d + b
        d = 1e-300 if abs(d) < 1e-300 else d
        c = b + an / c
        c = 1e-300 if abs(c) < 1e-300 else c
        d = 1.0 / d
        delta = d * c
        h *= delta
        if abs(delta - 1.0) < 1e-15:
            break
    return h * math.exp(-x + a * math.log(x) - math.lgamma(a))


def snr(X: np.ndarray, labels: np.ndarray) -> np.ndarray:
    """SQL: snr UDAF — per-feature signal-to-noise ratio across classes:
    |mu_c1 - mu_c2| / (sd_c1 + sd_c2) summed over class pairs."""
    X = np.asarray(X, np.float64)
    labels = np.asarray(labels)
    classes = np.unique(labels)
    mus = np.stack([X[labels == c].mean(0) for c in classes])
    sds = np.stack([X[labels == c].std(0) for c in classes])
    out = np.zeros(X.shape[1])
    for i in range(len(classes)):
        for j in range(i + 1, len(classes)):
            denom = sds[i] + sds[j]
            out += np.where(denom > 0, np.abs(mus[i] - mus[j]) / denom, 0.0)
    return out
