from .core import (add_bias, add_feature_index, extract_feature,  # noqa: F401
                   extract_weight, feature, sort_by_feature)
from .hashing import (array_hash_values, feature_hashing,  # noqa: F401
                      prefixed_hash_values, sha1)
from .scaling import l1_normalize, l2_normalize, rescale, zscore  # noqa: F401
from .conv import quantify, to_dense_features, to_sparse_features  # noqa: F401
from .pairing import polynomial_features, powered_features  # noqa: F401
from .trans import (binarize_label, categorical_features,  # noqa: F401
                    ffm_features, indexed_features, onehot_encoding,
                    quantitative_features, vectorize_features)
from .selection import chi2, snr  # noqa: F401
from .binning import build_bins, feature_binning  # noqa: F401
