"""Post-hoc model combination UDAFs — the GROUP BY feature ensemble path.

Reference (SURVEY.md §3.17 row 3): per-replica model tables are merged by
``GROUP BY feature`` + avg(weight) / voted_avg(weight) / weight_voted_avg /
argmin_kld over the emitted rows (hivemall.ensemble.*UDAF). Inputs here are
the per-group weight (and covar) arrays for one feature across replicas.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

__all__ = ["voted_avg", "weight_voted_avg", "argmin_kld", "merge_model_tables"]


def voted_avg(weights: Sequence[float]) -> float:
    """Mean of the weights on the majority-sign side (reference:
    hivemall.ensemble.bagging.VotedAvgUDAF)."""
    w = np.asarray(list(weights), np.float64)
    if w.size == 0:
        return 0.0
    pos = w > 0
    neg = w < 0
    if pos.sum() >= neg.sum():
        sel = w[pos]
        return float(sel.mean()) if sel.size else 0.0
    return float(w[neg].mean())


def weight_voted_avg(weights: Sequence[float]) -> float:
    """Weight-magnitude-weighted vote (reference:
    hivemall.ensemble.bagging.WeightVotedAvgUDAF): the side whose absolute
    weight mass dominates wins; returns that side's mean."""
    w = np.asarray(list(weights), np.float64)
    if w.size == 0:
        return 0.0
    pos_mass = w[w > 0].sum()
    neg_mass = -w[w < 0].sum()
    sel = w[w > 0] if pos_mass >= neg_mass else w[w < 0]
    return float(sel.mean()) if sel.size else 0.0


def argmin_kld(weights: Sequence[float], covars: Sequence[float]
               ) -> Tuple[float, float]:
    """Precision-weighted merge of (weight, covar) rows (reference:
    hivemall.ensemble.ArgminKLDistanceUDAF); see parallel.mix.argmin_kld_mix
    for the on-mesh collective form."""
    w = np.asarray(list(weights), np.float64)
    c = np.asarray(list(covars), np.float64)
    prec = 1.0 / c
    s = prec.sum()
    return float((w * prec).sum() / s), float(1.0 / s)


def merge_model_tables(tables: Iterable[Dict[str, float]],
                       how: str = "avg") -> Dict[str, float]:
    """Merge per-replica model tables (the SQL GROUP BY feature rollup)."""
    acc: Dict[str, List[float]] = {}
    for t in tables:
        for k, v in t.items():
            acc.setdefault(k, []).append(v)
    if how == "avg":
        return {k: float(np.mean(v)) for k, v in acc.items()}
    if how == "voted_avg":
        return {k: voted_avg(v) for k, v in acc.items()}
    if how == "weight_voted_avg":
        return {k: weight_voted_avg(v) for k, v in acc.items()}
    raise ValueError(f"unknown merge {how!r}")
