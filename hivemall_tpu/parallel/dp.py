"""Sharded training steps — GSPMD-partitioned minibatch updates.

The data-parallel rebuild of the reference's one-replica-per-map-task scheme
(SURVEY.md §3.17 row 1): the batch is sharded over the ``dp`` mesh axis, the
dense weight/optimizer tables over ``tp`` (feature-dim sharding), and XLA's
partitioner inserts the collectives (the scatter-add of per-shard gradients
becomes an all-reduce over dp — exactly the psum that replaces MixServer
averaging, at every-step cadence; configurable cadence lives in parallel.mix).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.losses import get_loss

__all__ = ["make_dp_linear_step"]


def make_dp_linear_step(mesh: Mesh, *, loss_name: str = "logloss",
                        eta0: float = 0.1):
    """AdaGrad logistic step, jit-partitioned over (dp, tp).

    in shardings: w, gg over P('tp'); idx, val over P('dp', None); label P('dp').
    """
    loss = get_loss(loss_name)

    @partial(
        jax.jit,
        in_shardings=(NamedSharding(mesh, P("tp")), NamedSharding(mesh, P("tp")),
                      NamedSharding(mesh, P("dp", None)),
                      NamedSharding(mesh, P("dp", None)),
                      NamedSharding(mesh, P("dp"))),
        out_shardings=(NamedSharding(mesh, P("tp")),
                       NamedSharding(mesh, P("tp")), None),
    )
    def step(w, gg, idx, val, label):
        margin = (w[idx] * val).sum(-1)
        d = loss.dloss(margin, label)
        g = jnp.zeros_like(w).at[idx.ravel()].add((d[:, None] * val).ravel())
        gg2 = gg + g * g
        w2 = w - eta0 * g / (jnp.sqrt(gg2) + 1e-6)
        return w2, gg2, loss.loss(margin, label).mean()

    return step
