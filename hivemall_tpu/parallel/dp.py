"""Sharded training steps — GSPMD-partitioned minibatch updates.

The data-parallel rebuild of the reference's one-replica-per-map-task scheme
(SURVEY.md §3.17 row 1): the batch is sharded over the ``dp`` mesh axis, the
dense weight/optimizer tables over ``tp`` (feature-dim sharding), and XLA's
partitioner inserts the collectives (the scatter-add of per-shard gradients
becomes an all-reduce over dp — exactly the psum that replaces MixServer
averaging, at every-step cadence; configurable cadence lives in parallel.mix).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.losses import get_loss

__all__ = ["make_dp_linear_step", "make_dp_ffm_step"]


def make_dp_linear_step(mesh: Mesh, *, loss_name: str = "logloss",
                        eta0: float = 0.1):
    """AdaGrad logistic step, jit-partitioned over (dp, tp).

    in shardings: w, gg over P('tp'); idx, val over P('dp', None); label P('dp').
    """
    loss = get_loss(loss_name)

    @partial(
        jax.jit,
        in_shardings=(NamedSharding(mesh, P("tp")), NamedSharding(mesh, P("tp")),
                      NamedSharding(mesh, P("dp", None)),
                      NamedSharding(mesh, P("dp", None)),
                      NamedSharding(mesh, P("dp"))),
        out_shardings=(NamedSharding(mesh, P("tp")),
                       NamedSharding(mesh, P("tp")), None),
    )
    def step(w, gg, idx, val, label):
        margin = (w[idx] * val).sum(-1)
        d = loss.dloss(margin, label)
        g = jnp.zeros_like(w).at[idx.ravel()].add((d[:, None] * val).ravel())
        gg2 = gg + g * g
        w2 = w - eta0 * g / (jnp.sqrt(gg2) + 1e-6)
        return w2, gg2, loss.loss(margin, label).mean()

    return step


def make_dp_ffm_step(mesh: Mesh, *, eta0: float = 0.1):
    """Full FFM training step partitioned over (dp, tp) — the flagship
    multi-chip path (SURVEY.md §8 M3: (feature,field) table sharded TP-like,
    batch DP, AdaGrad state co-sharded; XLA inserts the psum of partial
    gradients and the gather collectives over ICI).

    params: {"w0": (), "w": [N] P('tp'), "V": [N, F, K] P('tp', None, None)}
    opt_state gg co-shaped/co-sharded; batch idx/val/field P('dp', None).
    """
    from ..ops.fm import ffm_score
    from ..ops.losses import get_loss
    loss = get_loss("logloss")

    tp = NamedSharding(mesh, P("tp"))
    tp3 = NamedSharding(mesh, P("tp", None, None))
    dpb = NamedSharding(mesh, P("dp", None))
    dpv = NamedSharding(mesh, P("dp"))
    rep = NamedSharding(mesh, P())
    pspec = {"w0": rep, "w": tp, "V": tp3}

    @partial(jax.jit,
             in_shardings=(pspec, pspec, dpb, dpb, dpb, dpv),
             out_shardings=(pspec, pspec, None))
    def step(params, gg, idx, val, field, label):
        def batch_loss(p):
            phi = ffm_score(p["w0"], p["w"], p["V"], idx, val, field)
            return loss.loss(phi, label).sum()

        lsum, grads = jax.value_and_grad(batch_loss)(params)
        new_p, new_gg = {}, {}
        for k in params:
            g2 = gg[k] + grads[k] * grads[k]
            new_p[k] = params[k] - eta0 * grads[k] / (jnp.sqrt(g2) + 1e-6)
            new_gg[k] = g2
        return new_p, new_gg, lsum

    return step
