"""MIX on-mesh: parameter mixing as XLA collectives over ICI.

Reference: the MixServer subsystem (SURVEY.md §3.16) — asynchronous
parameter averaging over a custom Netty TCP protocol, with two combine ops:
  - average:    plain update-count-weighted mean of weights
  - argmin-KLD: precision-weighted mean for covariance-carrying models
    (CW/AROW/SCW) — the KL-minimizing merge of Gaussian weight posteriors.

TPU-native mapping [B]: within a slice, replicas live one-per-device on the
``dp`` mesh axis and mix by ``lax.pmean``/``psum`` at ``-mix_threshold``-step
cadence inside the jitted train loop (sync collectives over ICI at the same
cadence the reference would hit the mix server). Cross-slice/host async mixing
is parallel.mix_service (DCN path).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.jax_compat import shard_map
from ..ops.losses import Loss
from ..ops.optimizers import Optimizer

__all__ = ["mix_average", "argmin_kld_mix", "make_replica_train_step",
           "make_covariance_replica_step"]


def mix_average(w: jnp.ndarray, axis: str = "dp") -> jnp.ndarray:
    """The MixServer 'average' event: plain mean across replicas."""
    return lax.pmean(w, axis)


def argmin_kld_mix(w: jnp.ndarray, covar: jnp.ndarray, axis: str = "dp",
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The 'argminKLD' event (reference: PartialArgminKLD): precision-weighted
    mean — the argmin-KL merge of per-replica Gaussian posteriors
    N(w_i, covar_i). Returns (w_mixed, covar_mixed) where covar_mixed is the
    product-of-Gaussians posterior variance 1/sum(1/covar_i)."""
    prec = 1.0 / covar
    prec_sum = lax.psum(prec, axis)
    w_mixed = lax.psum(w * prec, axis) / prec_sum
    return w_mixed, 1.0 / prec_sum


def make_replica_train_step(mesh: Mesh, loss: Loss, optimizer: Optimizer,
                            mix_every: int = 16) -> Callable:
    """Per-device independent replicas + cadence mixing — the closest TPU
    analog of the reference's map-task replicas attached to a MixServer.

    w: [dp, N] (one replica per device, spec P('dp', None)); the batch is
    sharded over dp. Every ``mix_every`` steps the replicas pmean their
    weights (reference: clock-threshold mix exchange, SURVEY.md §4.3);
    optimizer state stays local, as MixServer never mixed it either.
    """

    def local_step(w, opt_state, t, idx, val, label):
        w = w[0]                                    # [N] local replica
        st = jax.tree_util.tree_map(lambda a: a[0], opt_state)
        margin = (w[idx] * val).sum(-1)
        d = loss.dloss(margin, label)
        g = jnp.zeros_like(w).at[idx.ravel()].add((d[:, None] * val).ravel())
        w2, st = optimizer.update(w, g, st, t)
        do_mix = (t + 1.0) % mix_every == 0.0
        w2 = lax.cond(do_mix, lambda x: lax.pmean(x, "dp"), lambda x: x, w2)
        loss_sum = lax.psum(loss.loss(margin, label).sum(), "dp")
        return (w2[None],
                jax.tree_util.tree_map(lambda a: a[None], st), loss_sum)

    # opt_state entries are [dp, N]-replicated per device as well
    pspec_state = jax.tree_util.tree_map(lambda _: P("dp", None),
                                         optimizer.init(1))

    # check_vma off: the mix branch of lax.cond returns a pmean-replicated
    # value while the skip branch stays device-varying; that asymmetry is
    # exactly the cadence semantics we want.
    return jax.jit(shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None), pspec_state, P(), P("dp", None),
                  P("dp", None), P("dp")),
        out_specs=(P("dp", None), pspec_state, P()),
        check_vma=False))


def make_covariance_replica_step(mesh: Mesh, rates: Callable,
                                 mix_every: int = 16) -> Callable:
    """Covariance-family (CW/AROW/SCW) replicas under a dp mesh with
    argmin-KLD mixing — the MixServer 'argminKLD' event as an ICI
    collective (reference: PartialArgminKLD folded by the server; SURVEY
    §3.16/§3.17). Each device trains a local (w, sigma) on its batch shard
    with the closed-form aggregate update (models.classifier._make_step
    math); every ``mix_every`` steps the replicas merge by precision
    weighting.

    w, sigma: [dp, N]; rates(margin_y, v) -> (alpha, beta) is the
    trainer's closed-form rate fn (e.g. AROWTrainer()._rates()).
    """

    def local_step(w, sigma, t, idx, val, label):
        w, sigma = w[0], sigma[0]
        wg = w[idx]
        m = (wg * val).sum(-1) * label
        sg = sigma[idx]
        v = (sg * val * val).sum(-1)
        alpha, beta = rates(m, v)
        dw = jnp.zeros_like(w).at[idx.ravel()].add(
            ((alpha * label)[:, None] * sg * val).ravel())
        ds = jnp.zeros_like(sigma).at[idx.ravel()].add(
            (beta[:, None] * (sg * val) ** 2).ravel())
        w2 = w + dw
        sig2 = jnp.maximum(sigma - ds, 1e-8)
        do_mix = (t + 1.0) % mix_every == 0.0

        def mix(args):
            return argmin_kld_mix(args[0], args[1], "dp")

        w2, sig2 = lax.cond(do_mix, mix, lambda a: a, (w2, sig2))
        loss_sum = lax.psum(
            jnp.maximum(0.0, 1.0 - m).sum(), "dp")
        return w2[None], sig2[None], loss_sum

    return jax.jit(shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None), P("dp", None), P(), P("dp", None),
                  P("dp", None), P("dp")),
        out_specs=(P("dp", None), P("dp", None), P()),
        check_vma=False))
