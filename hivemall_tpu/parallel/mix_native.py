"""Native MIX server wrapper — builds and supervises native/mix_server.cpp.

Reference: hivemall.mix.server.MixServer runs as a standalone native-code
(JVM/Netty) process started by `mixserv`; SURVEY.md §3.16/§4.3 demands a
native-runtime equivalent here, not only the asyncio implementation. The
C++ server speaks the SAME length-prefixed MixMessage wire protocol, so
`hivemall_tpu.parallel.mix_service.MixClient` (and trainers' `-mix`)
connect to either implementation unchanged. TLS and fault injection stay
on the Python server (they are test/ops tooling); this is the in-cluster
plaintext data path.

Build-on-first-use like utils/native.py: `g++ -O3` into
native/mix_server_native next to the source; environments without a
toolchain fall back to the Python server (start() raises with a clear
message; `mixserv --impl auto` handles the fallback).
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SRC = os.path.join(_DIR, "mix_server.cpp")
_BIN = os.path.join(_DIR, "mix_server_native")

__all__ = ["NativeMixServer", "native_available", "build_native_server"]


def build_native_server() -> Optional[str]:
    """Path to the server binary, building it if needed; None if the
    toolchain or source is unavailable (callers fall back to the asyncio
    server). Shares utils.native's build-on-first-use helper and the
    single HIVEMALL_TPU_NO_NATIVE=1 switch."""
    from ..utils.native import build_if_stale

    return _BIN if build_if_stale(_SRC, _BIN, []) else None


def native_available() -> bool:
    return build_native_server() is not None


class NativeMixServer:
    """Subprocess supervisor with the same start()/stop()/port surface as
    mix_service.MixServer, so tests and `mixserv` treat the two
    implementations interchangeably."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._proc: Optional[subprocess.Popen] = None

    def start(self) -> "NativeMixServer":
        binpath = build_native_server()
        if binpath is None:
            raise RuntimeError(
                "native mix server unavailable (no g++ toolchain or "
                "HIVEMALL_TPU_NO_NATIVE=1); use mix_service.MixServer")
        self._proc = subprocess.Popen(
            [binpath, "--host", self.host, "--port", str(self.port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        line = self._proc.stdout.readline().strip()
        if not line.startswith("PORT "):
            try:
                _, err = self._proc.communicate(timeout=5)
            except subprocess.TimeoutExpired:
                err = ""
            self.stop()
            raise RuntimeError(
                "native mix server failed to bind: "
                f"{(err or line).strip() or 'no output'!r}")
        self.port = int(line.split()[1])
        return self

    def stop(self) -> None:
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(timeout=5)
            self._proc = None

    def __enter__(self) -> "NativeMixServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def main(argv=None) -> int:
    """`python -m hivemall_tpu.parallel.mix_native --port N` — run the
    native server in the foreground (the mixserv CLI's --impl native)."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=11212)
    args = ap.parse_args(argv)
    binpath = build_native_server()
    if binpath is None:
        print("native mix server unavailable", file=sys.stderr)
        return 1
    proc = subprocess.Popen([binpath, "--host", args.host,
                             "--port", str(args.port)])
    try:
        return proc.wait()
    except KeyboardInterrupt:
        proc.terminate()
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
