"""Async host mix service — the DCN-path MixServer rebuild.

Reference: hivemall.mix.server.MixServer + client (SURVEY.md §3.16, §4.3):
a standalone TCP server holding per-(group, feature) partial aggregates;
clients send accumulated weight deltas when a per-weight clock passes
``-mix_threshold`` and fold the returned global average back into the local
model. Consistency: asynchronous, best-effort, fail-soft — a dead server
degrades training to replica-local SGD, never stops it.

This module reproduces that role for cross-slice (DCN) topologies where sync
ICI collectives (parallel.mix) don't reach:

- ``MixServer``: asyncio TCP server, same partial-aggregate semantics
  (average + argmin-KLD), session GC by group.
- ``MixClient``: attaches to a trainer (the ModelUpdateHandler analog);
  every ``threshold`` dispatched batches it ships the touched features'
  (weight, covar, delta-updates) and folds the mixed values back. Transport
  and framing faults NEVER reach the training loop: failed exchanges are
  retried with jittered exponential backoff, repeated failure opens a
  circuit breaker (half-open probe after a cooldown), and only a breaker
  that re-trips ``breaker_trips`` times with no intervening success
  degrades the client permanently — training continues unmixed either way
  (fail-soft, matching the reference's degrade-to-local-SGD semantics).
  See docs/RELIABILITY.md for the knob and counter surface.

Wire format (MixMessage analog), length-prefixed little-endian frames:
  u8 event (1=average, 2=argmin_kld, 3=closegroup), u16 group-utf8-len,
  group bytes, u32 n, then n * (i64 key, f32 weight, f32 covar,
  i32 delta_updates). Replies use the same frame shape.
"""

from __future__ import annotations

import asyncio
import random
import socket
import struct
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs.trace import get_tracer

__all__ = ["MixServer", "MixClient", "MixMessage", "EVENT_AVERAGE",
           "EVENT_ARGMIN_KLD", "EVENT_CLOSEGROUP", "EVENT_STATS",
           "MAX_FRAME_BYTES", "TRANSPORT_FAULTS"]

EVENT_AVERAGE = 1
EVENT_ARGMIN_KLD = 2
EVENT_CLOSEGROUP = 3
EVENT_STATS = 4          # JMX-analog counters probe (reference: MixServer
                         # exposes metrics over JMX; here a wire event)

_HDR = struct.Struct("<BH")
_LEN = struct.Struct("<I")
# frame-size ceiling (server and client): a corrupt/poisoned length prefix
# must never make readexactly buffer gigabytes before the decode can fail
MAX_FRAME_BYTES = 64 << 20
_EVENTS = frozenset((1, 2, 3, 4))
# one fault class, one fate: ssl.SSLError and socket.timeout are OSError
# subclasses; struct.error / ValueError / UnicodeDecodeError cover corrupt
# frames escaping MixMessage.decode. Anything here is handled fail-soft.
TRANSPORT_FAULTS = (OSError, EOFError, struct.error, ValueError,
                    UnicodeDecodeError, IndexError)
# one wire record — numpy structured dtype so whole messages encode/decode
# as single tobytes/frombuffer calls (no per-record Python)
_REC_DT = np.dtype([("k", "<i8"), ("w", "<f4"), ("c", "<f4"), ("d", "<i4")])


@dataclass
class MixMessage:
    event: int
    group: str
    keys: np.ndarray          # int64 [n]
    weights: np.ndarray       # float32 [n]
    covars: np.ndarray        # float32 [n]
    deltas: np.ndarray        # int32 [n]

    def encode(self) -> bytes:
        g = self.group.encode("utf-8")
        n = len(self.keys)
        recs = np.empty(n, _REC_DT)
        recs["k"] = self.keys
        recs["w"] = self.weights
        recs["c"] = self.covars
        recs["d"] = self.deltas
        body = (_HDR.pack(self.event, len(g)) + g + struct.pack("<I", n)
                + recs.tobytes())
        return _LEN.pack(len(body)) + body

    @classmethod
    def decode(cls, body: bytes) -> "MixMessage":
        event, glen = _HDR.unpack_from(body, 0)
        off = _HDR.size
        group = body[off:off + glen].decode("utf-8")
        off += glen
        (n,) = struct.unpack_from("<I", body, off)
        off += 4
        recs = np.frombuffer(body, _REC_DT, count=n, offset=off)
        return cls(event, group, recs["k"].astype(np.int64),
                   recs["w"].astype(np.float32),
                   recs["c"].astype(np.float32),
                   recs["d"].astype(np.int32))


_EMPTY = np.int64(-(1 << 62))      # open-addressing empty sentinel


class _NpIndex:
    """Vectorized int64 key -> dense row index: numpy open-addressing hash
    table with batched linear probing. Replaces the per-key Python dict
    walk (round 2's `rows_for` loop — ~1 us/key, the server's throughput
    ceiling); a whole message's keys now resolve in a handful of numpy
    passes. Single-writer (the asyncio loop thread), so batch claiming of
    empty slots needs no locking — colliding same-round claims are
    re-checked and losers keep probing."""

    def __init__(self, cap_bits: int = 12):
        self._bits = cap_bits
        self._keys = np.full(1 << cap_bits, _EMPTY, np.int64)
        self._rows = np.zeros(1 << cap_bits, np.int64)
        self.n = 0

    @staticmethod
    def _mix(k: np.ndarray) -> np.ndarray:
        h = k.astype(np.uint64)
        h ^= h >> np.uint64(33)
        h *= np.uint64(0xFF51AFD7ED558CCD)
        h ^= h >> np.uint64(33)
        return h

    def lookup_or_insert(self, keys: np.ndarray) -> np.ndarray:
        """rows [n] for int64 keys [n]; new keys get fresh rows n0, n0+1,
        ... assigned in SORTED-key order within the batch (np.unique sorts;
        any stable key->row map is valid for the aggregates, so order is
        an implementation detail, not a contract)."""
        uk, inv = np.unique(keys.astype(np.int64), return_inverse=True)
        if self.n + len(uk) > (len(self._keys) * 7) // 10:
            self._rehash(max(self._bits + 1,
                             int(np.ceil(np.log2((self.n + len(uk))
                                                 * 2 + 1)))))
        mask = np.uint64(len(self._keys) - 1)
        slot = (self._mix(uk) & mask).astype(np.int64)
        out = np.full(len(uk), -1, np.int64)
        pend = np.arange(len(uk))
        while len(pend):
            cur = self._keys[slot[pend]]
            hit = cur == uk[pend]
            out[pend[hit]] = self._rows[slot[pend[hit]]]
            free = cur == _EMPTY
            if free.any():
                cand = pend[free]
                self._keys[slot[cand]] = uk[cand]      # batch claim
                won = self._keys[slot[cand]] == uk[cand]
                winners = cand[won]
                rows_new = self.n + np.arange(len(winners))
                self._rows[slot[winners]] = rows_new
                out[winners] = rows_new
                self.n += len(winners)
            pend = pend[out[pend] < 0]
            slot[pend] = (slot[pend] + 1) & np.int64(mask)
        return out[inv]

    def _rehash(self, bits: int) -> None:
        live = self._keys != _EMPTY
        old_k, old_r = self._keys[live], self._rows[live]
        self._bits = bits
        self._keys = np.full(1 << bits, _EMPTY, np.int64)
        self._rows = np.zeros(1 << bits, np.int64)
        mask = np.uint64(len(self._keys) - 1)
        slot = (self._mix(old_k) & mask).astype(np.int64)
        pend = np.arange(len(old_k))
        while len(pend):
            cur = self._keys[slot[pend]]
            free = cur == _EMPTY
            cand = pend[free]
            self._keys[slot[cand]] = old_k[cand]
            won = self._keys[slot[cand]] == old_k[cand]
            winners = cand[won]
            self._rows[slot[winners]] = old_r[winners]
            pend = pend[self._keys[slot[pend]] != old_k[pend]]
            slot[pend] = (slot[pend] + 1) & np.int64(mask)


class _GroupStore:
    """Per-group partial aggregates in flat growable arrays (reference:
    SessionObject holding PartialResult per feature) — folds AND key->row
    indexing are fully numpy-vectorized (no per-key Python)."""

    def __init__(self, cap: int = 1024):
        self.index = _NpIndex()
        self._grow(cap)

    def _grow(self, cap: int) -> None:
        def g(a, dt=np.float64):
            out = np.zeros(cap, dt)
            if a is not None:
                out[:len(a)] = a
            return out
        old = getattr(self, "sum_w_du", None)
        self.sum_w_du = g(old)
        self.total_du = g(getattr(self, "total_du", None), np.int64)
        self.sum_prec = g(getattr(self, "sum_prec", None))
        self.sum_w_prec = g(getattr(self, "sum_w_prec", None))

    def rows_for(self, keys: np.ndarray) -> np.ndarray:
        rows = self.index.lookup_or_insert(keys)
        if self.index.n > len(self.sum_w_du):
            self._grow(max(self.index.n, 2 * len(self.sum_w_du)))
        return rows

    def fold_avg(self, rows: np.ndarray, w: np.ndarray, du: np.ndarray
                 ) -> np.ndarray:
        duf = np.maximum(1, du.astype(np.int64))
        # np.add.at: duplicate keys within one message accumulate correctly
        np.add.at(self.sum_w_du, rows, w.astype(np.float64) * duf)
        np.add.at(self.total_du, rows, duf)
        return (self.sum_w_du[rows]
                / np.maximum(1, self.total_du[rows])).astype(np.float32)

    def fold_kld(self, rows: np.ndarray, w: np.ndarray, c: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        prec = 1.0 / np.maximum(1e-12, c.astype(np.float64))
        np.add.at(self.sum_prec, rows, prec)
        np.add.at(self.sum_w_prec, rows, w.astype(np.float64) * prec)
        sp = self.sum_prec[rows]
        return ((self.sum_w_prec[rows] / sp).astype(np.float32),
                (1.0 / sp).astype(np.float32))


class MixServer:
    """In-process asyncio mix server. start()/stop() manage a daemon thread
    running the event loop, so tests exercise the real TCP path on localhost
    exactly like the reference's in-JVM MixServer tests (SURVEY.md §5.3)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None):
        self.host = host
        self.port = port          # 0 = ephemeral; real port set on start
        # TLS transport (the reference LearnerBase's -ssl MIX option,
        # SURVEY.md §3.1): pass make_server_ssl_context(cert, key)
        self.ssl_context = ssl_context
        # fault injection (SURVEY.md §6 failure detection): tests set these
        # to prove fail-soft parity — a dropping/stalling server degrades
        # training to replica-local SGD, never stops it.
        self.inject_drop_every = 0   # close the connection every Nth request
        self.inject_delay_s = 0.0    # stall each reply this long
        # throttle (reference: MixServer's per-connection throttling): cap
        # on key-updates/sec across all connections; 0 = unlimited
        self.throttle_keys_per_s = 0
        # a malformed or oversized frame closes ITS connection only — the
        # handler task is per-connection, other clients keep exchanging
        self.max_frame_bytes = MAX_FRAME_BYTES
        self._bad_frames = 0
        self._oversized_frames = 0
        self._requests = 0
        self._keys_folded = 0
        self._bytes_in = 0
        self._bytes_out = 0
        self._t0 = None
        self._sessions: Dict[str, _GroupStore] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()

    # -- protocol ------------------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                hdr = await reader.readexactly(_LEN.size)
                (ln,) = _LEN.unpack(hdr)
                if ln > self.max_frame_bytes:
                    self._oversized_frames += 1
                    break
                body = await reader.readexactly(ln)
                try:
                    msg = MixMessage.decode(body)
                    if msg.event not in _EVENTS:
                        raise ValueError(f"unknown event {msg.event}")
                except (struct.error, ValueError, UnicodeDecodeError,
                        IndexError, OverflowError):
                    self._bad_frames += 1
                    break
                self._bytes_in += ln + _LEN.size
                if msg.event == EVENT_CLOSEGROUP:
                    self._sessions.pop(msg.group, None)
                    continue
                if msg.event == EVENT_STATS:
                    import json as _json
                    payload = _json.dumps(self.counters())
                    reply = MixMessage(EVENT_STATS, payload,
                                       np.zeros(0, np.int64),
                                       np.zeros(0, np.float32),
                                       np.zeros(0, np.float32),
                                       np.zeros(0, np.int32))
                    buf = reply.encode()
                    self._bytes_out += len(buf)
                    writer.write(buf)
                    await writer.drain()
                    continue
                self._requests += 1
                if self.inject_delay_s:
                    await asyncio.sleep(self.inject_delay_s)
                if (self.inject_drop_every
                        and self._requests % self.inject_drop_every == 0):
                    writer.close()
                    return
                sess = self._sessions.setdefault(msg.group, _GroupStore())
                rows = sess.rows_for(msg.keys)
                if msg.event == EVENT_ARGMIN_KLD:
                    out_w, out_c = sess.fold_kld(rows, msg.weights,
                                                 msg.covars)
                else:
                    out_w = sess.fold_avg(rows, msg.weights, msg.deltas)
                    out_c = np.zeros_like(out_w)
                self._keys_folded += len(msg.keys)
                if self.throttle_keys_per_s:
                    import time as _time
                    if self._t0 is None:
                        self._t0 = _time.monotonic()
                    ahead = (self._keys_folded / self.throttle_keys_per_s
                             - (_time.monotonic() - self._t0))
                    if ahead > 0:
                        await asyncio.sleep(ahead)
                reply = MixMessage(msg.event, msg.group, msg.keys, out_w,
                                   out_c, msg.deltas)
                buf = reply.encode()
                self._bytes_out += len(buf)
                writer.write(buf)
                await writer.drain()
        except (asyncio.IncompleteReadError, OSError):
            pass               # peer vanished mid-frame / reset / TLS fault
        finally:
            try:
                writer.close()
            except RuntimeError:
                pass               # loop already closed during shutdown

    def counters(self) -> Dict[str, float]:
        """JMX-analog metrics surface (also served over the wire via
        EVENT_STATS): request/key/byte counters plus live session sizes."""
        return {
            "requests": self._requests,
            "keys_folded": self._keys_folded,
            "bytes_in": self._bytes_in,
            "bytes_out": self._bytes_out,
            "groups": len(self._sessions),
            "keys_tracked": int(sum(g.index.n
                                    for g in self._sessions.values())),
            "bad_frames": self._bad_frames,
            "oversized_frames": self._oversized_frames,
        }

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MixServer":
        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def boot():
                self._server = await asyncio.start_server(
                    self._handle, self.host, self.port,
                    ssl=self.ssl_context)
                self.port = self._server.sockets[0].getsockname()[1]
                self._started.set()

            self._loop.run_until_complete(boot())
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        if not self._started.wait(5):
            raise RuntimeError("mix server failed to start")
        # obs registry section — the JMX-bean analog, also reachable over
        # HTTP via -obs_port (weakly held: a stopped server must be
        # collectable)
        from ..obs.registry import registry
        ref = weakref.ref(self)

        def _obs() -> Dict[str, float]:
            srv = ref()                 # single deref: the server may be
            return srv.counters() if srv is not None else {}   # collected
        registry.register("mix_server", _obs)
        return self

    def stop(self) -> None:
        if self._loop:
            loop = self._loop

            def shutdown():
                for task in asyncio.all_tasks(loop):
                    task.cancel()       # unblock handlers stuck in delays
                # stop in a LATER callback so the cancellations (queued by
                # task.cancel via call_soon) deliver and finallys run first
                loop.call_soon(loop.stop)

            loop.call_soon_threadsafe(shutdown)
        if self._thread:
            self._thread.join(timeout=5)


class MixClient:
    """Trainer-attached mix client (the ModelUpdateHandler analog).

    Cadence: per-feature clocks would need an [N] counter array on device;
    instead the client counts dispatched batches and, every ``threshold``
    batches, ships all features touched since the last exchange with
    delta_updates = batches elapsed (documented approximation of the
    reference's per-weight clocks; convergence semantics match at minibatch
    granularity).

    Fault model (docs/RELIABILITY.md): every exchange gets up to
    ``retries + 1`` attempts inside a per-exchange wall-clock ``deadline``,
    reconnecting between attempts with jittered exponential backoff
    (``backoff`` base, doubled per attempt, capped at ``backoff_max``).
    ``breaker_threshold`` consecutive failed exchanges open a circuit
    breaker: exchanges are dropped (not attempted) for ``breaker_cooldown``
    seconds, then ONE half-open probe runs; a probe failure re-opens the
    breaker, a success closes it fully. Only ``breaker_trips`` consecutive
    opens with no intervening success set ``alive = False`` permanently.
    Training continues unmixed through every one of these states — no
    transport or framing fault ever propagates into the fit loop.
    A dropped exchange re-marks its keys as touched, so the features ship
    on the next successful exchange (delivery is at-least-once: a reply
    lost after the server folded may be re-sent and folded twice —
    acceptable under the reference's best-effort averaging semantics).
    """

    def __init__(self, hosts: str, group: str, threshold: int = 16,
                 event: int = EVENT_AVERAGE, timeout: float = 2.0,
                 ssl_context=None, *, retries: int = 2,
                 backoff: float = 0.05, backoff_max: float = 2.0,
                 deadline: Optional[float] = None,
                 breaker_threshold: int = 3, breaker_cooldown: float = 1.0,
                 breaker_trips: int = 3, max_touched: int = 1 << 20):
        host, _, port = hosts.partition(":")
        self.addr = (host or "127.0.0.1", int(port or 11212))
        self.group = group
        self.threshold = max(1, threshold)
        self.event = event
        self.timeout = timeout
        self.ssl_context = ssl_context    # -ssl: TLS-wrapped exchanges
        self.retries = max(0, int(retries))
        self.backoff = float(backoff)
        self.backoff_max = float(backoff_max)
        self.deadline = deadline          # None = 2 * timeout, resolved live
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.breaker_cooldown = float(breaker_cooldown)
        self.breaker_trips = max(1, int(breaker_trips))
        self.max_touched = int(max_touched)
        self.alive = True
        self.exchanges = 0
        self.reconnects = 0               # successful re-dials after a fault
        self.dropped_exchanges = 0        # exchange windows lost to faults
        self.transport_errors = 0         # individual failed attempts
        self.breaker_trip_count = 0       # lifetime breaker opens
        self.touched_overflow = 0         # touch() calls shed over the cap
        self._trips_since_ok = 0
        self._consec_failures = 0
        self._open_until: Optional[float] = None   # monotonic; None=closed
        self._ever_connected = False
        # deterministic jitter: tests injecting a fault schedule see the
        # same backoff sequence run to run (crc32, not hash() — str hash
        # is salted per interpreter)
        import zlib
        self._rng = random.Random(0x5EED ^ zlib.crc32(group.encode()))
        self._sock: Optional[socket.socket] = None
        self._batches = 0
        self._touched: set[int] = set()

    # -- observability -------------------------------------------------------
    @property
    def breaker_state(self) -> str:
        if not self.alive:
            return "dead"
        if self._open_until is None:
            return "closed"
        return "open" if time.monotonic() < self._open_until else "half-open"

    @property
    def degraded(self) -> bool:
        """True while exchanges are suspended (breaker open or permanently
        failed) — training is running unmixed."""
        return not self.alive or self._open_until is not None

    def counters(self) -> Dict[str, float]:
        """Client-side metrics, the peer of MixServer.counters()."""
        return {
            "exchanges": self.exchanges,
            "reconnects": self.reconnects,
            "dropped_exchanges": self.dropped_exchanges,
            "transport_errors": self.transport_errors,
            "breaker_trips": self.breaker_trip_count,
            "breaker_state": self.breaker_state,
            "touched_overflow": self.touched_overflow,
            "alive": self.alive,
        }

    # -- transport -----------------------------------------------------------
    def _connect(self) -> None:
        if self._sock is not None:
            return
        s = socket.create_connection(self.addr, timeout=self.timeout)
        try:
            if s.getsockname() == s.getpeername():
                # TCP simultaneous-open self-connect: dialing a dead
                # local port can land on source port == dest port, and
                # the client would happily read back its own frames as
                # "replies" — a real hazard for a RETRYING client once
                # the server's ephemeral port is freed. Treat it as the
                # connection refusal it morally is.
                raise OSError("self-connect detected — no server "
                              f"listening on {self.addr}")
            s.settimeout(self.timeout)
            if self.ssl_context is not None:
                s = self.ssl_context.wrap_socket(
                    s, server_hostname=self.addr[0])
        except OSError:
            s.close()    # wrap_socket/self-connect failure must not
            raise        # leak the connected socket (GC12)
        self._sock = s
        if self._ever_connected:
            self.reconnects += 1
        self._ever_connected = True

    def _drop_socket(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def touch(self, keys: np.ndarray) -> None:
        if not self.alive:
            return
        if len(self._touched) >= self.max_touched:
            # outage overflow guard: during a long breaker-open stretch the
            # touched set must not grow without bound; shed new keys (they
            # fold on a later touch once exchanges resume)
            self.touched_overflow += 1
            return
        self._touched.update(int(k) for k in np.unique(keys) if k != 0)

    def maybe_mix(self, trainer) -> None:
        """Called by LearnerBase after each dispatched batch.

        Exchange cost is O(touched keys), never O(dims): the touched
        weights (and covariances, for argmin-KLD trainers) are gathered on
        device and only they cross the wire and fold back — the reference's
        delta-exchange semantics, where MixClient ships accumulated deltas
        per clocked feature, not the model."""
        if not self.alive:
            return
        self._batches += 1
        if self._batches % self.threshold != 0 or not self._touched:
            return
        probing = False
        if self._open_until is not None:
            if time.monotonic() < self._open_until:
                self.dropped_exchanges += 1      # breaker open: skip cheap
                return
            probing = True                       # half-open: one attempt
        # the whole exchange window — gather, wire round-trips incl.
        # retries/backoff, fold-back — is ONE ``mix.exchange`` span: what
        # the fit loop actually pays per exchange (a faulted exchange's
        # span is its retry budget, which is exactly the number to watch)
        with get_tracer().span("mix.exchange"):
            self._exchange_window(trainer, probing)

    def _exchange_window(self, trainer, probing: bool) -> None:
        keys = np.fromiter(self._touched, np.int64)
        self._touched.clear()
        w_at = trainer._get_weights_at(keys)
        covar = trainer._get_covar_at(keys) \
            if hasattr(trainer, "_get_covar_at") else None
        msg = MixMessage(
            self.event, self.group, keys,
            np.asarray(w_at, np.float32),
            (np.asarray(covar, np.float32) if covar is not None
             else np.ones(len(keys), np.float32)),
            np.full(len(keys), self.threshold, np.int32))
        reply = self._exchange(msg, attempts=1 if probing
                               else self.retries + 1)
        if reply is None:
            self.dropped_exchanges += 1
            self._consec_failures += 1
            # keep the features on the books — they ship next exchange
            if len(self._touched) < self.max_touched:
                self._touched.update(int(k) for k in keys)
            if probing or self._consec_failures >= self.breaker_threshold:
                self._trip()
            return
        self._consec_failures = 0
        self._trips_since_ok = 0
        self._open_until = None                  # breaker fully closed
        self.exchanges += 1
        # fold-back runs OUTSIDE the fault guard: the reply is validated,
        # so an error here is a trainer bug and must surface
        trainer._set_weights_at(reply.keys, reply.weights)
        if (self.event == EVENT_ARGMIN_KLD and covar is not None
                and hasattr(trainer, "_set_covar_at")):
            trainer._set_covar_at(reply.keys, reply.covars)

    def _exchange(self, msg: MixMessage,
                  attempts: int) -> Optional[MixMessage]:
        """One exchange window: up to ``attempts`` tries within the
        per-exchange deadline; returns the validated reply or None."""
        payload = msg.encode()
        budget = self.deadline if self.deadline else 2.0 * self.timeout
        deadline = time.monotonic() + budget
        for attempt in range(max(1, attempts)):
            try:
                self._connect()
                self._sock.sendall(payload)
                reply = self._read_reply()
                if (reply.event != msg.event
                        or len(reply.keys) != len(msg.keys)):
                    raise ValueError(
                        f"mix reply mismatch: event {reply.event} "
                        f"n={len(reply.keys)} vs sent {msg.event} "
                        f"n={len(msg.keys)}")
                return reply
            except TRANSPORT_FAULTS:
                self.transport_errors += 1
                self._drop_socket()
            if attempt + 1 >= max(1, attempts):
                return None
            delay = min(self.backoff_max, self.backoff * (1 << attempt))
            delay *= 0.5 + self._rng.random()    # jitter in [0.5, 1.5)
            if time.monotonic() + delay >= deadline:
                return None                      # deadline would be blown
            time.sleep(delay)
        return None

    def _trip(self) -> None:
        """Open the breaker; after ``breaker_trips`` consecutive opens with
        no successful exchange between them, degrade permanently."""
        self.breaker_trip_count += 1
        self._trips_since_ok += 1
        self._consec_failures = 0
        self._drop_socket()
        if self._trips_since_ok >= self.breaker_trips:
            self.alive = False                   # permanent fail-soft
            self._open_until = None
        else:
            self._open_until = time.monotonic() + self.breaker_cooldown

    def _read_reply(self) -> MixMessage:
        hdr = self._recvn(_LEN.size)
        (ln,) = _LEN.unpack(hdr)
        if ln > MAX_FRAME_BYTES:
            raise ValueError(f"mix reply frame {ln} bytes exceeds "
                             f"{MAX_FRAME_BYTES} — corrupt length prefix?")
        return MixMessage.decode(self._recvn(ln))

    def _recvn(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise OSError("mix server closed connection")
            buf += chunk
        return buf

    def close_group(self) -> None:
        """Send CLOSEGROUP (bounded wait) and release the socket. Runs the
        socket cleanup even on a dead/degraded client — a permanently
        failed client must not leak its half-open socket — and bounds the
        send so shutdown can't hang on a wedged server."""
        sock, self._sock = self._sock, None
        if sock is None:
            return
        try:
            if self.alive:
                sock.settimeout(min(self.timeout, 0.5))
                sock.sendall(MixMessage(
                    EVENT_CLOSEGROUP, self.group, np.zeros(0, np.int64),
                    np.zeros(0, np.float32), np.zeros(0, np.float32),
                    np.zeros(0, np.int32)).encode())
        except TRANSPORT_FAULTS:
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass


# -- TLS transport (-ssl, SURVEY.md §3.1 LearnerBase MIX options) -----------

def make_server_ssl_context(certfile: str, keyfile: str):
    """TLS context for MixServer (the reference's -ssl transport): the
    server presents certfile/keyfile; clients connect with
    make_client_ssl_context."""
    import ssl
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    return ctx


def make_client_ssl_context(cafile: Optional[str] = None):
    """TLS context for MixClient. With ``cafile`` the server certificate
    is verified against it (self-signed deployments point this at the
    server cert); without, the channel is encrypted but the peer is NOT
    authenticated — the reference's -ssl is likewise transport encryption
    inside a trusted cluster."""
    import ssl
    if cafile:
        ctx = ssl.create_default_context(cafile=cafile)
        ctx.check_hostname = False      # cluster peers connect by IP
        return ctx
    import warnings
    # default warnings filter dedupes by caller location — no hand flag
    warnings.warn(
        "-ssl without -ssl_cafile encrypts the MIX channel but does NOT "
        "authenticate the server (an active MITM can read/alter mixed "
        "weights); pass -ssl_cafile to pin the server certificate",
        RuntimeWarning, stacklevel=2)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    return ctx
