"""Async host mix service — the DCN-path MixServer rebuild.

Reference: hivemall.mix.server.MixServer + client (SURVEY.md §3.16, §4.3):
a standalone TCP server holding per-(group, feature) partial aggregates;
clients send accumulated weight deltas when a per-weight clock passes
``-mix_threshold`` and fold the returned global average back into the local
model. Consistency: asynchronous, best-effort, fail-soft — a dead server
degrades training to replica-local SGD, never stops it.

This module reproduces that role for cross-slice (DCN) topologies where sync
ICI collectives (parallel.mix) don't reach:

- ``MixServer``: asyncio TCP server, same partial-aggregate semantics
  (average + argmin-KLD), session GC by group.
- ``MixClient``: attaches to a trainer (the ModelUpdateHandler analog);
  every ``threshold`` dispatched batches it ships the touched features'
  (weight, covar, delta-updates) and folds the mixed values back. Transport
  errors permanently disable it (fail-soft), matching the reference.

Wire format (MixMessage analog), length-prefixed little-endian frames:
  u8 event (1=average, 2=argmin_kld, 3=closegroup), u16 group-utf8-len,
  group bytes, u32 n, then n * (i64 key, f32 weight, f32 covar,
  i32 delta_updates). Replies use the same frame shape.
"""

from __future__ import annotations

import asyncio
import socket
import struct
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["MixServer", "MixClient", "MixMessage", "EVENT_AVERAGE",
           "EVENT_ARGMIN_KLD", "EVENT_CLOSEGROUP"]

EVENT_AVERAGE = 1
EVENT_ARGMIN_KLD = 2
EVENT_CLOSEGROUP = 3

_REC = struct.Struct("<qffi")
_HDR = struct.Struct("<BH")
_LEN = struct.Struct("<I")


@dataclass
class MixMessage:
    event: int
    group: str
    keys: np.ndarray          # int64 [n]
    weights: np.ndarray       # float32 [n]
    covars: np.ndarray        # float32 [n]
    deltas: np.ndarray        # int32 [n]

    def encode(self) -> bytes:
        g = self.group.encode("utf-8")
        n = len(self.keys)
        body = bytearray(_HDR.pack(self.event, len(g)))
        body += g
        body += struct.pack("<I", n)
        for i in range(n):
            body += _REC.pack(int(self.keys[i]), float(self.weights[i]),
                              float(self.covars[i]), int(self.deltas[i]))
        return _LEN.pack(len(body)) + bytes(body)

    @classmethod
    def decode(cls, body: bytes) -> "MixMessage":
        event, glen = _HDR.unpack_from(body, 0)
        off = _HDR.size
        group = body[off:off + glen].decode("utf-8")
        off += glen
        (n,) = struct.unpack_from("<I", body, off)
        off += 4
        keys = np.empty(n, np.int64)
        weights = np.empty(n, np.float32)
        covars = np.empty(n, np.float32)
        deltas = np.empty(n, np.int32)
        for i in range(n):
            k, w, c, d = _REC.unpack_from(body, off)
            off += _REC.size
            keys[i], weights[i], covars[i], deltas[i] = k, w, c, d
        return cls(event, group, keys, weights, covars, deltas)


@dataclass
class _Partial:
    """Per-(group, feature) running aggregate (reference: PartialResult /
    PartialAverage / PartialArgminKLD)."""
    sum_w_du: float = 0.0       # sum of weight * delta_updates
    total_du: int = 0
    sum_prec: float = 0.0       # argmin-KLD: sum of 1/covar
    sum_w_prec: float = 0.0     # argmin-KLD: sum of w/covar

    def fold_avg(self, w: float, du: int) -> None:
        self.sum_w_du += w * max(1, du)
        self.total_du += max(1, du)

    def fold_kld(self, w: float, covar: float) -> None:
        prec = 1.0 / max(1e-12, covar)
        self.sum_prec += prec
        self.sum_w_prec += w * prec

    def mixed_avg(self) -> float:
        return self.sum_w_du / max(1, self.total_du)

    def mixed_kld(self) -> Tuple[float, float]:
        return self.sum_w_prec / self.sum_prec, 1.0 / self.sum_prec


class MixServer:
    """In-process asyncio mix server. start()/stop() manage a daemon thread
    running the event loop, so tests exercise the real TCP path on localhost
    exactly like the reference's in-JVM MixServer tests (SURVEY.md §5.3)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port          # 0 = ephemeral; real port set on start
        # fault injection (SURVEY.md §6 failure detection): tests set these
        # to prove fail-soft parity — a dropping/stalling server degrades
        # training to replica-local SGD, never stops it.
        self.inject_drop_every = 0   # close the connection every Nth request
        self.inject_delay_s = 0.0    # stall each reply this long
        self._requests = 0
        self._sessions: Dict[str, Dict[int, _Partial]] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()

    # -- protocol ------------------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                hdr = await reader.readexactly(_LEN.size)
                (ln,) = _LEN.unpack(hdr)
                msg = MixMessage.decode(await reader.readexactly(ln))
                if msg.event == EVENT_CLOSEGROUP:
                    self._sessions.pop(msg.group, None)
                    continue
                self._requests += 1
                if self.inject_delay_s:
                    await asyncio.sleep(self.inject_delay_s)
                if (self.inject_drop_every
                        and self._requests % self.inject_drop_every == 0):
                    writer.close()
                    return
                sess = self._sessions.setdefault(msg.group, {})
                out_w = np.empty_like(msg.weights)
                out_c = np.empty_like(msg.covars)
                for i, k in enumerate(msg.keys):
                    p = sess.setdefault(int(k), _Partial())
                    if msg.event == EVENT_ARGMIN_KLD:
                        p.fold_kld(float(msg.weights[i]), float(msg.covars[i]))
                        out_w[i], out_c[i] = p.mixed_kld()
                    else:
                        p.fold_avg(float(msg.weights[i]), int(msg.deltas[i]))
                        out_w[i] = p.mixed_avg()
                        out_c[i] = 0.0
                reply = MixMessage(msg.event, msg.group, msg.keys, out_w,
                                   out_c, msg.deltas)
                writer.write(reply.encode())
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            try:
                writer.close()
            except RuntimeError:
                pass               # loop already closed during shutdown

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MixServer":
        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def boot():
                self._server = await asyncio.start_server(
                    self._handle, self.host, self.port)
                self.port = self._server.sockets[0].getsockname()[1]
                self._started.set()

            self._loop.run_until_complete(boot())
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        if not self._started.wait(5):
            raise RuntimeError("mix server failed to start")
        return self

    def stop(self) -> None:
        if self._loop:
            loop = self._loop

            def shutdown():
                for task in asyncio.all_tasks(loop):
                    task.cancel()       # unblock handlers stuck in delays
                # stop in a LATER callback so the cancellations (queued by
                # task.cancel via call_soon) deliver and finallys run first
                loop.call_soon(loop.stop)

            loop.call_soon_threadsafe(shutdown)
        if self._thread:
            self._thread.join(timeout=5)


class MixClient:
    """Trainer-attached mix client (the ModelUpdateHandler analog).

    Cadence: per-feature clocks would need an [N] counter array on device;
    instead the client counts dispatched batches and, every ``threshold``
    batches, ships all features touched since the last exchange with
    delta_updates = batches elapsed (documented approximation of the
    reference's per-weight clocks; convergence semantics match at minibatch
    granularity). Any transport failure disables the client permanently —
    training continues unmixed (fail-soft parity).
    """

    def __init__(self, hosts: str, group: str, threshold: int = 16,
                 event: int = EVENT_AVERAGE, timeout: float = 2.0):
        host, _, port = hosts.partition(":")
        self.addr = (host or "127.0.0.1", int(port or 11212))
        self.group = group
        self.threshold = max(1, threshold)
        self.event = event
        self.timeout = timeout
        self.alive = True
        self.exchanges = 0
        self._sock: Optional[socket.socket] = None
        self._batches = 0
        self._touched: set[int] = set()

    def _connect(self) -> None:
        if self._sock is None:
            s = socket.create_connection(self.addr, timeout=self.timeout)
            s.settimeout(self.timeout)
            self._sock = s

    def touch(self, keys: np.ndarray) -> None:
        self._touched.update(int(k) for k in np.unique(keys) if k != 0)

    def maybe_mix(self, trainer) -> None:
        """Called by LearnerBase after each dispatched batch."""
        if not self.alive:
            return
        self._batches += 1
        if self._batches % self.threshold != 0 or not self._touched:
            return
        try:
            keys = np.fromiter(self._touched, np.int64)
            self._touched.clear()
            w = np.array(trainer._finalized_weights())  # writable copy
            covar = getattr(trainer, "covar_table", lambda: None)()
            msg = MixMessage(
                self.event, self.group, keys,
                w[keys].astype(np.float32),
                (np.asarray(covar)[keys].astype(np.float32)
                 if covar is not None else np.ones(len(keys), np.float32)),
                np.full(len(keys), self.threshold, np.int32))
            self._connect()
            self._sock.sendall(msg.encode())
            reply = self._read_reply()
            w[reply.keys] = reply.weights
            trainer._load_weights(w)
            self.exchanges += 1
        except OSError:
            self.alive = False     # fail-soft: keep training unmixed
            self._sock = None

    def _read_reply(self) -> MixMessage:
        hdr = self._recvn(_LEN.size)
        (ln,) = _LEN.unpack(hdr)
        return MixMessage.decode(self._recvn(ln))

    def _recvn(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise OSError("mix server closed connection")
            buf += chunk
        return buf

    def close_group(self) -> None:
        if self.alive and self._sock is not None:
            try:
                self._sock.sendall(MixMessage(
                    EVENT_CLOSEGROUP, self.group, np.zeros(0, np.int64),
                    np.zeros(0, np.float32), np.zeros(0, np.float32),
                    np.zeros(0, np.int32)).encode())
                self._sock.close()
            except OSError:
                pass
            self._sock = None
