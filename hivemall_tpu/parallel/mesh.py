"""Device-mesh construction — the parallelism substrate.

Reference context (SURVEY.md §3.17): the reference's only compute parallelism
is data-parallel map tasks plus the async MixServer. The rebuild's axes:

  dp — data parallel (engine-task analog): batch sharded, grads psum-mixed
  tp — feature/table parallel: the hashed weight table (and FFM (feature,
       field) latent tables) sharded across devices; the framework's
       "context-parallel" analog is this feature-dim axis (SURVEY.md §6)

Collectives ride ICI within a slice; DCN handled by jax.distributed + the
async host mix service (parallel.mix_service).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

__all__ = ["make_mesh"]


def make_mesh(dp: int = 1, tp: int = 1, devices=None) -> Mesh:
    """Build a (dp, tp) mesh over the first dp*tp visible devices."""
    devices = devices if devices is not None else jax.devices()
    need = dp * tp
    if len(devices) < need:
        raise ValueError(f"mesh dp={dp} tp={tp} needs {need} devices, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(dp, tp)
    return Mesh(arr, ("dp", "tp"))
