"""Device-mesh construction — the parallelism substrate.

Reference context (SURVEY.md §3.17): the reference's only compute parallelism
is data-parallel map tasks plus the async MixServer. The rebuild's axes:

  dp — data parallel (engine-task analog): batch sharded, grads psum-mixed
  tp — feature/table parallel: the hashed weight table (and FFM (feature,
       field) latent tables) sharded across devices; the framework's
       "context-parallel" analog is this feature-dim axis (SURVEY.md §6)

Collectives ride ICI within a slice; DCN handled by jax.distributed + the
async host mix service (parallel.mix_service).
"""

from __future__ import annotations

import os

import numpy as np
import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "init_distributed", "parse_mesh_spec"]


def parse_mesh_spec(spec: str, n_devices: int = None) -> tuple:
    """Parse a trainer ``-mesh`` option into (dp, tp).

    Grammar: ``auto`` (dp = all visible devices, tp = 1) or a comma list of
    ``dp=<n>`` / ``tp=<n>`` assignments, e.g. ``dp=2,tp=4``. Unassigned axes
    default to 1."""
    if n_devices is None:
        n_devices = len(jax.devices())
    s = str(spec).strip().lower()
    if s == "auto":
        return n_devices, 1
    dp = tp = 1
    for part in s.split(","):
        k, sep, v = part.partition("=")
        k = k.strip()
        if not sep or k not in ("dp", "tp"):
            raise ValueError(
                f"bad -mesh spec {spec!r}: expected 'auto' or "
                f"'dp=<n>,tp=<n>' assignments, got {part!r}")
        if k == "dp":
            dp = int(v)
        else:
            tp = int(v)
    if dp < 1 or tp < 1:
        raise ValueError(f"-mesh axes must be >= 1, got dp={dp} tp={tp}")
    return dp, tp


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, **kwargs) -> int:
    """Multi-host (DCN) bootstrap — the NCCL/MPI-init analog.

    Thin wrapper over ``jax.distributed.initialize``: with no arguments the
    cluster-environment autodetection applies (TPU pods populate everything);
    explicit args serve manual DCN fleets. After this, ``jax.devices()`` is
    the GLOBAL device list, so ``make_mesh`` spans hosts and psum-mixing
    (parallel.mix) rides ICI within a slice and DCN across slices.

    Failure policy: when the call looks multi-host — any explicit argument,
    or a coordinator address in the environment — init errors RE-RAISE (a
    real fleet must not silently shrink to one worker). Only a bare local
    invocation with no cluster hints degrades to local devices.
    Returns the process index (0 when single-process)."""
    def _int_env(name):
        try:
            return int(os.environ.get(name, "") or 0)
        except ValueError:
            return 0

    # presence of a coordinator address, or a scheduler reporting >1 tasks
    # — NOT mere presence of scheduler/TPU-VM vars, which single-host runs
    # (salloc shells, every Cloud TPU VM) also carry
    multi_host_intent = (
        any(v is not None for v in (coordinator_address, num_processes,
                                    process_id))
        or bool(kwargs)
        or any(k in os.environ for k in (
            "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS"))
        or _int_env("SLURM_NTASKS") > 1
        or _int_env("OMPI_COMM_WORLD_SIZE") > 1
        # TPU pod slice: hostnames var lists every worker, single-host
        # TPU VMs carry it too but with exactly one entry
        or len([h for h in os.environ.get("TPU_WORKER_HOSTNAMES",
                                          "").split(",") if h]) > 1)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id, **kwargs)
    except (ValueError, RuntimeError):
        if multi_host_intent:
            raise
    return jax.process_index()


def make_mesh(dp: int = 1, tp: int = 1, devices=None) -> Mesh:
    """Build a (dp, tp) mesh over the first dp*tp visible devices."""
    devices = devices if devices is not None else jax.devices()
    need = dp * tp
    if len(devices) < need:
        raise ValueError(f"mesh dp={dp} tp={tp} needs {need} devices, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(dp, tp)
    return Mesh(arr, ("dp", "tp"))
