#!/bin/sh
# Run the test suite on CPU (8 virtual devices), never touching the TPU
# tunnel: PALLAS_AXON_POOL_IPS triggers a relay dial at interpreter boot via
# sitecustomize, and the relay is single-client — tests must stay off it.
exec env -u PALLAS_AXON_POOL_IPS -u JAX_PLATFORMS \
    python -m pytest tests/ -q "$@"
