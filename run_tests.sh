#!/bin/sh
# CI artifacts (graftcheck JSON report, tsan race log, leaktrack census
# log) land here; a fresh run starts from a clean slate so stale
# records can't confuse a read of the artifacts.
mkdir -p artifacts
rm -f artifacts/graftcheck_report.json artifacts/tsan_races.jsonl \
      artifacts/leaktrack_census.jsonl artifacts/retrain_smoke.json

# graftcheck gate (docs/STATIC_ANALYSIS.md): project-invariant static
# analysis, run FIRST because it is the cheapest phase (~17 s cold /
# <2 s cached, budget <=30 s — the parse/summary AND rule passes fan
# across cores, 2-CPU container floor; per-rule wall breakdown lands
# in the JSON artifact). --selfcheck proves the gate in four
# directions before the real scan — every rule (incl. the
# interprocedural GC01/GC02/GC04 upgrades, GC07/GC08, and the v3 XLA
# compile-contract + resource-lifecycle rules GC09-GC12) must fire on
# a seeded violation in a scratch tree, the baseline machinery must
# silence fresh findings / flag stale entries, the tsan lockset
# sanitizer must detect the re-seeded PR 11 last_reload_error race,
# and the leaktrack census sanitizer must catch a seeded fd leak —
# then the real scan (package + tests/ + bench.py + graft entry;
# content-hash cached, whole-scan invalidation on any edit or rule
# bump) fails on ANY finding (the tree's contract since PR 11 is an
# EMPTY baseline; a PR that must land with debt commits
# graftcheck_baseline.json, which the bare run picks up from the repo
# root, and the gate keeps failing once a baselined finding is fixed
# but its entry lingers). The full JSON report is emitted as a CI
# artifact.
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m hivemall_tpu.tools.graftcheck --selfcheck || exit $?
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m hivemall_tpu.tools.graftcheck \
    --json-out artifacts/graftcheck_report.json || exit $?

# Run the test suite on CPU (8 virtual devices), never touching the TPU
# tunnel: PALLAS_AXON_POOL_IPS triggers a relay dial at interpreter boot via
# sitecustomize, and the relay is single-client — tests must stay off it.
env -u PALLAS_AXON_POOL_IPS -u JAX_PLATFORMS \
    python -m pytest tests/ -q "$@" || exit $?

# fault-injection smoke (docs/RELIABILITY.md): a FlakyProxy'd MIX exchange
# survives a mid-run server kill + restart (reconnect counter > 0), and a
# crash-at-step-N fit_stream resumes from its autosaved bundle with
# bit-identical final weights. Seconds-scale; the long soak variants live
# in tests/ marked `slow`.
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m hivemall_tpu.testing.faults --smoke || exit $?

# observability smoke (docs/OBSERVABILITY.md): a seconds-scale traced fit
# must produce a parseable jsonl stream with train_step/train_done/
# span_rollup events, a registry snapshot carrying every subsystem
# section, a working `hivemall_tpu obs` render, and per-step tracing
# overhead within 5% of tracing disabled (min over alternating pairs).
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m hivemall_tpu.obs.smoke || exit $?

# serve smoke (docs/SERVING.md): a checkpoint trained in-process is served
# over HTTP with dynamic micro-batching — concurrent predicts must
# coalesce (mean batch > 1), bit-match offline predict_proba on the same
# rows, stay under the p99 latency budget, and a newer checkpoint written
# mid-traffic must hot-reload without dropping in-flight requests.
# HIVEMALL_TPU_TSAN=1 runs it under the Eraser-style lockset race
# sanitizer (hivemall_tpu.testing.tsan): every registered serve/obs
# class's attribute writes are lockset-checked across the HTTP handler
# / dispatch / watch / warmup threads, and ANY write/write race fails
# the smoke (the latency budget relaxes — a sanitizer build is never a
# perf build; the un-instrumented budget stays pinned by bench_serve).
# HIVEMALL_TPU_LEAKTRACK=1 additionally runs the FD/socket/thread leak
# census (hivemall_tpu.testing.leaktrack): a snapshot at smoke start
# must match the census after the full traffic+reload+drain+shutdown
# cycle — any tracked resource still alive fails the smoke with its
# creation stack appended to the JSONL artifact. The bench timed legs
# below never enable either sanitizer (a sanitizer build is never a
# perf build).
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    HIVEMALL_TPU_TSAN=1 HIVEMALL_TPU_TSAN_LOG=artifacts/tsan_races.jsonl \
    HIVEMALL_TPU_LEAKTRACK=1 \
    HIVEMALL_TPU_LEAKTRACK_LOG=artifacts/leaktrack_census.jsonl \
    python -m hivemall_tpu.serve.smoke || exit $?

# evloop serve smoke (docs/SERVING.md "Serving planes"): the SAME
# acceptance surface on the epoll event-loop plane — selectors front
# end + inline batch assembly (serve/evloop.py) must coalesce,
# bit-match, hot-reload with zero drops, and pass the identical tsan
# lockset + leaktrack census gates (the loop thread owns all per-
# connection and assembler state; everything crossing threads goes
# through message queues, so ANY write/write race here is a real bug).
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    HIVEMALL_TPU_TSAN=1 HIVEMALL_TPU_TSAN_LOG=artifacts/tsan_races.jsonl \
    HIVEMALL_TPU_LEAKTRACK=1 \
    HIVEMALL_TPU_LEAKTRACK_LOG=artifacts/leaktrack_census.jsonl \
    python -m hivemall_tpu.serve.smoke --plane evloop || exit $?

# retrieval smoke (docs/SERVING.md "Retrieval plane"): an MF factor
# bundle published through the weight arena serves /retrieve on BOTH
# planes — concurrent exact-tier top-k bit-matches the each_top_k
# oracle over the engine's own exact scores, the SRP-LSH candidate
# tier holds recall@10 >= 0.95 vs exact at the smoke catalog shape,
# a newly PROMOTED factor bundle hot-reloads mid-traffic with zero
# failed requests, HMR1 response frames decode to the JSON ids, and
# the retrieval obs section rides /snapshot + /metrics. Same tsan
# lockset + leaktrack census gates as the other serve smokes.
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    HIVEMALL_TPU_TSAN=1 HIVEMALL_TPU_TSAN_LOG=artifacts/tsan_races.jsonl \
    HIVEMALL_TPU_LEAKTRACK=1 \
    HIVEMALL_TPU_LEAKTRACK_LOG=artifacts/leaktrack_census.jsonl \
    python -m hivemall_tpu.serve.retrieve_smoke || exit $?
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    HIVEMALL_TPU_TSAN=1 HIVEMALL_TPU_TSAN_LOG=artifacts/tsan_races.jsonl \
    HIVEMALL_TPU_LEAKTRACK=1 \
    HIVEMALL_TPU_LEAKTRACK_LOG=artifacts/leaktrack_census.jsonl \
    python -m hivemall_tpu.serve.retrieve_smoke --plane evloop || exit $?

# fleet smoke (docs/SERVING.md "Fleet topology"): 2 replica PROCESSES
# behind the front-end router — concurrent routed predicts bit-match
# predict_proba and fan across both replicas; killing one replica under
# live traffic costs ZERO failed requests (router retry + manager
# respawn); a newer checkpoint rolls across the fleet one replica at a
# time with zero drops, converging every replica to the new step; the
# /slo burn-rate surface reports the traffic; and request tracing
# propagates END TO END — an x-hivemall-trace id is echoed with a
# per-hop latency breakdown that sums to the router-measured wall, and
# appears in spans exported from BOTH the router and the scoring
# replica processes via the router's merged /trace (the tracing-
# overhead floor itself stays pinned by the obs smoke above).
# The lockset sanitizer rides along here too: manager-side threads
# (health monitor, rolling reload, respawn, router accept/handlers,
# SLO sampler) gate on zero races in-process; replica subprocesses
# inherit the env and append any races to the shared artifact log.
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    HIVEMALL_TPU_TSAN=1 HIVEMALL_TPU_TSAN_LOG=artifacts/tsan_races.jsonl \
    HIVEMALL_TPU_LEAKTRACK=1 \
    HIVEMALL_TPU_LEAKTRACK_LOG=artifacts/leaktrack_census.jsonl \
    python -m hivemall_tpu.serve.fleet_smoke || exit $?

# evloop fleet smoke: the same fleet acceptance surface with evloop
# replicas behind the evloop router front end — including the
# router->replica UDS fast path (every forward must stay on the unix
# socket; a TCP fallback fails the uds_fast_path check), the kill/
# respawn zero-drop guarantee and the rolling reload, under the same
# tsan + leaktrack gates (replica workers census their own sockets,
# including the UDS listener, on drain).
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    HIVEMALL_TPU_TSAN=1 HIVEMALL_TPU_TSAN_LOG=artifacts/tsan_races.jsonl \
    HIVEMALL_TPU_LEAKTRACK=1 \
    HIVEMALL_TPU_LEAKTRACK_LOG=artifacts/leaktrack_census.jsonl \
    python -m hivemall_tpu.serve.fleet_smoke --plane evloop || exit $?

# promotion smoke (docs/RELIABILITY.md "Promotion and rollback"): gated
# model promotion over a 2-replica fleet under live traffic — a
# deliberately-poisoned candidate must be BLOCKED at the gate
# (quarantined with a .rejected marker, fleet untouched); a good
# candidate must promote through a 1-replica canary bake with zero
# failed requests; a synthetic latency regression injected into the
# canary cohort must AUTO-ROLL-BACK (pointer reverted, bundle
# quarantined, replicas restored) with zero failed requests; and the
# `promotion` section must be visible on /snapshot, /metrics,
# /promotion and the `hivemall_tpu obs` render.
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m hivemall_tpu.serve.promote_smoke || exit $?

# weight-arena smoke (docs/PERFORMANCE.md "Weight arena + quantized
# scoring"): zero-copy quantized serving end to end — the bootstrap
# promotion must PUBLISH the arena sidecar, a 2-replica int8 fleet must
# serve off it with zero per-replica publishes while mapping the SAME
# inode (verified via /proc/<pid>/maps), per-replica host-RSS +
# arena-mapped-bytes gauges must be live on /healthz, /snapshot and the
# fleet section, quantized scores must stay inside the documented int8
# bound of offline f32, the router result cache must hit on a repeated
# body and be invalidated by the promotion-driven rolling reload, and
# the roll must converge both replicas onto the NEW arena with zero
# failed requests. tsan + leaktrack enabled like the other serve smokes
# (the mmap'd arena views must be released on replica drain — a leaked
# mapping fails the census).
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    HIVEMALL_TPU_TSAN=1 HIVEMALL_TPU_TSAN_LOG=artifacts/tsan_races.jsonl \
    HIVEMALL_TPU_LEAKTRACK=1 \
    HIVEMALL_TPU_LEAKTRACK_LOG=artifacts/leaktrack_census.jsonl \
    python -m hivemall_tpu.serve.arena_smoke || exit $?

# retrain chaos smoke (docs/RELIABILITY.md "Autonomous retraining"):
# the closed train→validate→promote→rollback loop over a 2-replica
# fleet under live traffic — an injected label/covariate shift
# (testing/faults.LabelShiftSource) must drive retrain_wanted votes, a
# debounced trigger, a warm-start child retrain from the PROMOTED
# bundle over (base corpus ∪ replay buffer), a gate pass, a canary
# bake and a full roll (pointer advances, fleet converges) with ZERO
# failed requests; then a POISONED label join must be quarantined at
# the gate (.rejected marker) with the backoff cooldown holding — no
# retrain storm. tsan-enabled like the serve/fleet smokes; the JSON
# result summary lands in artifacts/.
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    HIVEMALL_TPU_TSAN=1 HIVEMALL_TPU_TSAN_LOG=artifacts/tsan_races.jsonl \
    HIVEMALL_TPU_LEAKTRACK=1 \
    HIVEMALL_TPU_LEAKTRACK_LOG=artifacts/leaktrack_census.jsonl \
    python -m hivemall_tpu.serve.retrain_smoke \
    --artifact artifacts/retrain_smoke.json || exit $?

# shard-cache smoke (docs/PERFORMANCE.md "Shard cache"): a cold fit must
# build the packed cache, a fresh-trainer warm fit must bit-match its loss
# trajectory with ZERO live prep, and the Parquet decode cache must keep
# serving the original bytes after the source shard's content is mutated
# in place (mtime/size preserved) — proof warm epochs never re-read the
# source.
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m hivemall_tpu.io.shard_cache --smoke || exit $?

# bulk-predict smoke (ISSUE 17, docs/PERFORMANCE.md "Bulk scoring"): a
# 2-worker-process bulk job over a multi-shard Parquet dir (ragged tail +
# an empty shard) must BIT-match the offline predict_proba path at f32,
# stay inside score_error_bound()/4 on the int8 arena twin, and — under
# tsan + the leaktrack census — leave ZERO leaked fds/threads/mmaps after
# the worker pool drains.
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    HIVEMALL_TPU_TSAN=1 HIVEMALL_TPU_TSAN_LOG=artifacts/tsan_races.jsonl \
    HIVEMALL_TPU_LEAKTRACK=1 \
    HIVEMALL_TPU_LEAKTRACK_LOG=artifacts/leaktrack_census.jsonl \
    python -m hivemall_tpu.io.bulk --smoke || exit $?

# native-canonicalizer CI guard: the C++ canonicalizer is the DEFAULT in
# every prep path (fit / fit_stream / serve-side scoring), with the numpy
# twin as the fallback — when _native.so exists, the bit-equality parity
# test must actually RUN (a silent skip would unpin the default path).
if [ -f native/_native.so ]; then
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python -m pytest \
        tests/test_native.py::test_canonicalize_native_matches_numpy -q \
        2>&1 | grep -q "1 passed" || {
        echo "FAIL: canonicalizer parity test skipped/failed although" \
             "native/_native.so exists"; exit 1; }
fi

# bench harness smoke: tiny-shape runs of the ingest-path benches assert
# every metric still emits and parses (pipeline refactors must not silently
# break bench.py), and the dispatch-fusion microbench enforces its floor —
# K=8 fused smoke throughput below the K=1 number fails the run (catches
# accidental defusion of the -steps_per_dispatch path). Same CPU isolation
# as the tests. Two ISSUE-9 guards ride in the same process (no second
# bench pass):
#   - no-retrace invariant (docs/OBSERVABILITY.md "Training profiling"):
#     a warmed FFM e2e epoch must add ZERO post-warmup XLA compiles, and a
#     deliberately-injected fresh-closure duplicate-config trainer (the
#     compile factories bypassed) MUST be caught by the devprof sentinel —
#     retrace counter up + a `retrace` event in the metrics jsonl;
#   - perf-regression gate: the fresh smoke numbers diff against the
#     newest committed smoke-shape BENCH_r*.json per benchmark key
#     (bench.py --compare machinery; HIVEMALL_TPU_BENCH_TOLERANCE
#     overrides the 70% CI tolerance — the 2-core container's
#     run-to-run swings reach ~3x, so the always-on gate flags only
#     the catastrophic class), and the gate self-tests by injecting a
#     synthetic 10x regression that must flip it.
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python bench.py --smoke
