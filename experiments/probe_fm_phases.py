"""Phase breakdown of the round-5 train_fm minibatch step (411k ex/s =
79.7 ms at B=32k, L=32, K=8, dims=2^24): where do the ~34 ms above the
gather+scatter floor go?"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

B, L, K = 32768, 32, 8
dims = 1 << 24
P, Wf = 8, 16
Np = dims // P
rng = np.random.default_rng(0)
idx = jnp.asarray(rng.integers(1, dims, (B, L)).astype(np.int32))
T = jnp.asarray(rng.standard_normal((Np, 128)) * 0.01, jnp.bfloat16)
S = jnp.zeros((Np, 128), jnp.float32)
lab = jnp.asarray((rng.integers(0, 2, B) * 2 - 1).astype(np.float32))


def sync(x):
    return float(np.asarray(jnp.asarray(x).astype(jnp.float32).sum()))


def timeit(fn, iters=10):
    sync(fn())
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


rows = idx // P


@jax.jit
def gather(T, idx):
    return T[idx // P].astype(jnp.float32).sum()


print(f"gather:         {timeit(lambda: gather(T, idx))*1e3:7.2f} ms",
      flush=True)

g128 = jnp.asarray(rng.standard_normal((B, L, 128)) * 1e-3, jnp.float32)


@jax.jit
def scat(g, rows):
    return jnp.zeros((Np, 128), jnp.float32).at[rows.reshape(-1)].add(
        g.reshape(-1, 128)).sum()


print(f"scatter-add:    {timeit(lambda: scat(g128, rows))*1e3:7.2f} ms",
      flush=True)


@jax.jit
def dense(T, S, G):
    gg = S + G * G
    Tn = T.astype(jnp.float32) - 0.1 * G / (jnp.sqrt(gg) + 1e-6)
    return Tn.astype(jnp.bfloat16).sum()


G = jnp.zeros((Np, 128), jnp.float32)
print(f"dense adagrad:  {timeit(lambda: dense(T, S, G))*1e3:7.2f} ms",
      flush=True)

from hivemall_tpu.ops.fm import _fm_slab_phi, _fm_unpack  # noqa: E402
from hivemall_tpu.ops.losses import get_loss  # noqa: E402

loss = get_loss("logloss")


@jax.jit
def fwdbwd(T, idx, lab):
    rows, sub = idx // P, idx % P
    slab = _fm_unpack(T[rows], sub, Wf, P)

    def bl(s):
        s32 = s.astype(jnp.float32)
        phi = _fm_slab_phi(0.0, s32[..., K], s32[..., :K],
                           jnp.ones((B, L)))
        return (loss.loss(phi, lab)).sum()

    return jax.grad(bl)(slab).sum()


print(f"gather+fwd/bwd: {timeit(lambda: fwdbwd(T, idx, lab))*1e3:7.2f} ms",
      flush=True)

gslab = jnp.asarray(rng.standard_normal((B, L, Wf)), jnp.float32)


@jax.jit
def onehot_expand(gslab, sub):
    oh = jax.nn.one_hot(sub, P, dtype=jnp.float32)
    return (oh[..., None] * gslab[..., None, :]).reshape(B, L, P * Wf).sum()


print(f"one-hot expand: "
      f"{timeit(lambda: onehot_expand(gslab, idx % P))*1e3:7.2f} ms",
      flush=True)


# --- round-5 follow-up: is there a cheap win left in the scatter+dense
# tail? Measured (same shapes, one jit per variant, value-synced):
#   zeros+scatter+dense fused in ONE jit : 28.28 ms   <- the step's actual
#       tail (better than the 23.6 + 9.8 sum of the isolated phases above:
#       XLA fuses the zero-init and the elementwise update around the
#       scatter when they share a jit)
#   donated pre-zeroed G (re-zeroed by the dense pass, no memset): 32.21 ms
#       — WORSE: donation pins the buffer and defeats the fusion
#   f32 table (no bf16<->f32 astype copies in the dense pass): 30.51 ms
#       — WORSE: the wider gather/update traffic costs more than the
#       conversions saved
# Conclusion: the minibatch step is at its structural floor —
# gather+fwd/bwd ~28 ms + fused tail ~28 ms = ~56-61 ms measured e2e
# (535k ex/s clean). The remaining alternatives all price out at net <= 0
# by the cost model (docs/PERFORMANCE.md "table-row operations are the
# scarce resource"):
#   - sort + segment-sum pre-aggregation: 1.05M slots into 2M rows is
#     mostly UNIQUE (uniform hashing, <=30% collisions) — nothing to
#     pre-aggregate, and the sorted-order permutation is itself a 1.05M
#     row gather (~18 ms).
#   - sorted-range Pallas VMEM accumulate + fused AdaGrad (the FFM parts
#     treatment): FM has no field structure, so slots hit the whole 2M-row
#     table; bucketing needs a device sort (~8-10 ms) AND the kernel's
#     random g128 reads pay the same ~17 ns/row the XLA scatter pays —
#     net ~0. The FFM kernel wins only because canonical field-major
#     batches arrive PRE-GROUPED by partition.
