"""Validate + time the parts-layout pallas FFM step vs the joint XLA step.

Usage: python experiments/proto_parts.py [small] [flagship]
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from hivemall_tpu.ops.losses import get_loss
from hivemall_tpu.ops import fm_pallas as fp

rng = np.random.default_rng(0)
loss = get_loss("logloss")
ETA = 0.1


def eta_fn(t):
    return ETA


def oracle_step(params, opt_state, t, idx, val, label, row_mask, F, K, MRF):
    """Same math as make_parts_step but with XLA scatter + dense AdaGrad."""
    wp = 128 * (-(-(F * K + 8) // 128))
    hp = wp // 128
    T2, w0 = params["T2"], params["w0"]
    S2 = opt_state["T2"]["gg"]
    B, L = idx.shape
    if val is None:
        val = (idx != 0).astype(jnp.float32)
    idxT, valT = idx.T, val.T
    fieldT = (jnp.arange(L, dtype=jnp.int32) % F)[:, None]
    rows = fp.parts_row_hash(idxT, fieldT, MRF)
    T3 = T2.reshape(F * MRF, hp, 128)
    slab = T3[rows]

    def batch_loss(w0f, slabf):
        s32 = slabf.astype(jnp.float32).reshape(L, B, wp)
        phi = fp._phi_parts(w0f, s32, valT, F, K)
        return (loss.loss(phi, label) * row_mask).sum()

    loss_sum, (g0, gslab) = jax.value_and_grad(
        batch_loss, argnums=(0, 1))(w0.astype(jnp.float32), slab)
    # match the kernel's bf16 gradient quantization
    gslab = gslab.astype(jnp.float32).astype(jnp.bfloat16).astype(jnp.float32)
    G = jnp.zeros((F * MRF, hp, 128), jnp.float32).at[rows].add(gslab)
    G2 = G.reshape(F * MRF * hp, 128)
    gg = S2 + G2 * G2
    T2n = (T2.astype(jnp.float32)
           - ETA * G2 / (jnp.sqrt(gg) + 1e-6)).astype(T2.dtype)
    gg0 = opt_state["w0"]["gg"] + g0 * g0
    w0n = (w0.astype(jnp.float32)
           - ETA * g0 / (jnp.sqrt(gg0) + 1e-6)).astype(w0.dtype)
    return ({"T2": T2n, "w0": w0n},
            {"T2": {"gg": gg}, "w0": {"gg": gg0}}, loss_sum)


def init_state(F, K, MRF, seed=0):
    wp = 128 * (-(-(F * K + 8) // 128))
    hp = wp // 128
    key = jax.random.PRNGKey(seed)
    FK = F * K
    # latent cols [0:FK) random, rest zero — build in logical [F*MRF, wp]
    Tl = jnp.concatenate([
        jax.random.normal(key, (F * MRF, FK)) * 0.1,
        jnp.zeros((F * MRF, wp - FK))], axis=1)
    T2 = Tl.reshape(F * MRF * hp, 128).astype(jnp.bfloat16)
    params = {"T2": T2, "w0": jnp.zeros((), jnp.float32)}
    opt_state = {"T2": {"gg": jnp.zeros((F * MRF * hp, 128), jnp.float32)},
                 "w0": {"gg": jnp.zeros((), jnp.float32)}}
    return params, opt_state


def small():
    B, F, K, MRF = 256, 8, 4, 1 << 10   # wp = 8*4+8 -> 128*1... need 256
    # force wp=256: F*K+8 must exceed 128 -> use F=31, K=8 (256)
    B, F, K, MRF = 256, 31, 8, 1 << 10
    L = F
    interp = jax.default_backend() != "tpu"
    step = fp.make_parts_step(loss, eta_fn, (0.0, 0.0, 0.0), F, K, MRF,
                              interpret=interp)
    idx = rng.integers(0, 1 << 20, (B, L)).astype(np.int32)
    idx[rng.random((B, L)) < 0.1] = 0          # padding slots
    val = (idx != 0).astype(np.float32)
    lab = (rng.integers(0, 2, B) * 2 - 1).astype(np.float32)
    mask = np.ones(B, np.float32)
    mask[-7:] = 0.0

    p0, s0 = init_state(F, K, MRF)
    p1, s1, l1 = step(p0, s0, 0.0, jnp.asarray(idx), jnp.asarray(val),
                      jnp.asarray(lab), jnp.asarray(mask))
    p0b, s0b = init_state(F, K, MRF)
    p2, s2, l2 = jax.jit(
        lambda *a: oracle_step(*a, F, K, MRF))(
            p0b, s0b, 0.0, jnp.asarray(idx), jnp.asarray(val),
            jnp.asarray(lab), jnp.asarray(mask))
    dl = abs(float(l1) - float(l2))
    gg_o = s2["T2"]["gg"]
    # AdaGrad's first step is sign-sensitive where G ~ 0 (gg ~ 1e-8):
    # f32 summation-order noise flips it even between two XLA orderings.
    # Compare T only on rows with a meaningful accumulator.
    sig = gg_o > 1e-5
    dT = float((jnp.abs(p1["T2"].astype(jnp.float32)
                        - p2["T2"].astype(jnp.float32)) * sig).max())
    dS = float(jnp.abs(s1["T2"]["gg"] - gg_o).max())
    rS = float((jnp.abs(s1["T2"]["gg"] - gg_o)
                / (gg_o + 1e-3)).max())
    print(f"small: dloss={dl:.3e} dT2|sig={dT:.3e} dS2={dS:.3e} "
          f"relS={rS:.3e}", flush=True)
    assert dl < 1e-2 and dT < 2e-2 and rS < 0.2, "MISMATCH"
    # multi-step loss trajectory must track the oracle
    pa, sa = init_state(F, K, MRF)
    pb, sb = init_state(F, K, MRF)
    orc = jax.jit(lambda *a: oracle_step(*a, F, K, MRF))
    for t in range(10):
        pa, sa, la = step(pa, sa, float(t), jnp.asarray(idx),
                          jnp.asarray(val), jnp.asarray(lab),
                          jnp.asarray(mask))
        pb, sb, lb = orc(pb, sb, float(t), jnp.asarray(idx),
                         jnp.asarray(val), jnp.asarray(lab),
                         jnp.asarray(mask))
        rel = abs(float(la) - float(lb)) / max(abs(float(lb)), 1e-6)
        print(f"  t={t} loss kernel={float(la):.5f} oracle={float(lb):.5f} "
              f"rel={rel:.2e}", flush=True)
        assert rel < 2e-2, "loss trajectory diverged"
    print("small: OK", flush=True)


def _sync(tree):
    for leaf in jax.tree_util.tree_leaves(tree):
        float(np.asarray(leaf.astype(jnp.float32).sum(), np.float64))


def flagship():
    from hivemall_tpu.io.sparse import SparseBatch
    from hivemall_tpu.models.fm import FFMTrainer

    B, L, F, K = 32768, 40, 40, 4
    dims = 1 << 24
    MRF, wp, hp = fp.parts_geometry(dims, F, K)
    print(f"MRF={MRF} wp={wp} hp={hp} rows={F*MRF}", flush=True)

    idx = rng.integers(1, dims, (B, L)).astype(np.int32)
    lab = (rng.integers(0, 2, B) * 2 - 1).astype(np.float32)
    didx = jnp.asarray(idx)
    dlab = jnp.asarray(lab)
    dmask = jnp.ones((B,), jnp.float32)

    # --- parts pallas step (unit-val) ---
    step = fp.make_parts_step(loss, eta_fn, (0.0, 0.0, 0.0), F, K, MRF,
                              unit_val=True)
    params, opt_state = init_state(F, K, MRF)
    t0 = time.perf_counter()
    params, opt_state, l0 = step(params, opt_state, 0.0, didx, dlab, dmask)
    _sync(l0)
    print(f"parts compile+first: {time.perf_counter()-t0:.1f}s", flush=True)
    n = 30
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(n):
            params, opt_state, l0 = step(params, opt_state, float(i), didx,
                                         dlab, dmask)
        _sync(l0)
        best = min(best, (time.perf_counter() - t0) / n)
    print(f"parts step: {best*1e3:.2f} ms -> {B/best/1e3:.0f}k ex/s",
          flush=True)

    # --- current joint fused step, same process ---
    t = FFMTrainer(f"-dims {dims} -factors {K} -fields {F} -mini_batch {B} "
                   f"-opt adagrad -classification -halffloat")
    hb = t._preprocess_batch(SparseBatch(
        idx, np.ones((B, L), np.float32), lab,
        np.tile(np.arange(L, dtype=np.int32) % F, (B, 1))))
    batch = SparseBatch(jnp.asarray(hb.idx), None, jnp.asarray(hb.label),
                        None, fieldmajor=True)
    for _ in range(2):
        t._train_batch(batch)
    _sync(t.params)
    best_j = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(n):
            lj = t._train_batch(batch)
        jax.tree_util.tree_map(lambda x: x.block_until_ready(), t.params)
        _sync(lj)
        best_j = min(best_j, (time.perf_counter() - t0) / n)
    print(f"joint step: {best_j*1e3:.2f} ms -> {B/best_j/1e3:.0f}k ex/s",
          flush=True)
    print(f"speedup: {best_j/best:.2f}x", flush=True)


if __name__ == "__main__":
    which = sys.argv[1:] or ["small", "flagship"]
    print(jax.devices(), flush=True)
    if "small" in which:
        small()
    if "flagship" in which:
        flagship()
