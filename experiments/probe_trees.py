"""Where does RF-16xd8 time go? Per-level histogram cost (flat vs sorted,
vmapped over 16 trees), the routing/argsort extras, and the full build."""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from hivemall_tpu.ops.pallas_hist import level_histogram, level_histogram_sorted

n, d, B, E = 100_000, 28, 64, 16
rng = np.random.default_rng(0)
bins = jnp.asarray(rng.integers(0, B, (n, d)).astype(np.uint8))
w = jnp.asarray(rng.poisson(1.0, (E, n)).astype(np.float32))
ws1 = jnp.asarray(rng.random((n, 2)).astype(np.float32))


def sync(x):
    return float(np.asarray(jnp.asarray(x).astype(jnp.float32).sum(), np.float64))


def timeit(fn, iters=3, repeats=2):
    sync(fn())
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def report(name, secs):
    print(f"{name:46s} {secs*1e3:9.2f} ms", flush=True)


def main():
    for M in (1, 8):
        loc = jnp.asarray(rng.integers(0, M, n).astype(np.int32))
        f = jax.jit(jax.vmap(
            lambda wv: level_histogram(bins, loc, ws1 * wv[:, None], M, B),
        ))
        report(f"flat hist M={M} vmapped x16", timeit(lambda: f(w)))

    for M in (32, 256):
        loc = jnp.asarray(rng.integers(0, M, n).astype(np.int32))
        f = jax.jit(jax.vmap(
            lambda wv: level_histogram_sorted(bins, loc, ws1 * wv[:, None],
                                              M, B)))
        report(f"sorted hist M={M} vmapped x16", timeit(lambda: f(w)))

    # the non-hist per-level machinery: gains/route on [M,d,B,S]
    M = 256
    loc = jnp.asarray(rng.integers(0, M, n).astype(np.int32))

    @jax.jit
    @jax.vmap
    def extras(wv):
        hist = jnp.zeros((M, d, B, 2), jnp.float32) + wv[0]
        parent = hist.sum(2).max(1)
        cum = jnp.cumsum(hist, axis=2)
        left = cum[:, :, :-1, :]
        right = parent[:, None, None, :] - left
        gains = (left[..., 0] * right[..., 0])
        arg = jnp.argmax(gains.reshape(M, -1), axis=1)
        return arg.sum()
    report("gains+argmax M=256 x16", timeit(lambda: extras(w)))

    # full builds
    from hivemall_tpu.ops.trees import build_tree_classifier
    labels = rng.integers(0, 2, n).astype(np.int32)
    wnp = np.asarray(w)
    edges = np.zeros((d, B - 1), np.float32)
    t0 = time.perf_counter()
    tree = build_tree_classifier(np.asarray(bins), labels, wnp, edges,
                                 2, depth=8, n_bins=B, n_trees=E)
    print(f"full RF-16 d8 build (compile+run): "
          f"{time.perf_counter()-t0:.1f}s", flush=True)
    for _ in range(2):
        t0 = time.perf_counter()
        tree = build_tree_classifier(np.asarray(bins), labels, wnp, edges,
                                     2, depth=8, n_bins=B, n_trees=E)
        dt = time.perf_counter() - t0
        print(f"full RF-16 d8 build (warm): {dt:.2f}s -> "
              f"{n/dt/1e3:.1f}k rows/s", flush=True)


if __name__ == "__main__":
    print(jax.devices(), flush=True)
    main()
