"""Round-4 tree-kernel probe (VERDICT r3 weak #5 / next #6).

Questions:
 1. Of the RF build's ~8.5 s at 1M x 28 x 16 trees, how much is the
    dense-channel histogram kernel vs routing/gains/bookkeeping?
 2. Does fusing the per-feature [n_bins, CHUNK] x [CHUNK, cs] matmuls into
    ONE [d*n_bins, CHUNK] x [CHUNK, cs] matmul per chunk-step (bigger
    M-axis, one VMEM accumulate instead of d slices) beat the shipped
    kernel?
"""
import sys, time
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax, jax.numpy as jnp
from functools import partial
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from hivemall_tpu.ops.pallas_hist import level_histogram_dense

def sync(x):
    return float(np.asarray(jnp.asarray(x).astype(jnp.float32).sum()))

n, d, E, Bn = 1_000_000, 28, 16, 64
depth = 8
rng = np.random.default_rng(0)
bins = rng.integers(0, Bn, (n, d)).astype(np.int32)
np_ = -(-n // 1024) * 1024
dp = -(-d // 8) * 8
bins_t = jnp.asarray(np.pad(bins, ((0, np_ - n), (0, dp - d)),
                            constant_values=-1).T)
S = 2
ws = jnp.asarray(rng.random((np_, S)).astype(np.float32))

# --- 1. hist-only cost across the level schedule, vmapped over E trees ---
LEVELS = (0, 4, 6, 8)   # probe the MAC-light and MAC-heavy ends
locs = {}
for t in LEVELS:
    M = 2 ** t
    locs[t] = jnp.asarray(rng.integers(0, M, (E, np_)).astype(np.int32))

times = {}
for t in LEVELS:
    M = 2 ** t
    f = jax.jit(jax.vmap(lambda l: level_histogram_dense(
        bins_t, l, ws, M, Bn, fast=True)))
    r = f(locs[t]); sync(r[..., 0].sum())           # warm
    t0 = time.perf_counter()
    r = f(locs[t]); sync(r[..., 0].sum())
    times[t] = time.perf_counter() - t0
tot = sum(times.values())
print("hist-only per level:",
      {t: round(v, 3) for t, v in times.items()})
print(f"hist-only total over probed levels: {tot:.2f}s")

# --- 2. fused-feature variant ---
_CHUNK = 512

def _fused_kernel(bins_ref, loc_ref, ws_ref, out_ref, *, d, n_bins, S, cs):
    g = pl.program_id(0)
    first = pl.program_id(1) == 0
    loc = loc_ref[0, :]
    col = jax.lax.broadcasted_iota(jnp.int32, (cs, _CHUNK), 0)
    node_col = col // S + g * (cs // S)
    s_col = col % S
    w2t = jnp.zeros((cs, _CHUNK), jnp.float32)
    for s in range(S):
        w2t = jnp.where(s_col == s, ws_ref[s, :][None, :], w2t)
    w2t = jnp.where(node_col == loc[None, :], w2t, 0.0)
    # fused one-hot over ALL features: [(f,b), CHUNK]
    fb = jax.lax.broadcasted_iota(jnp.int32, (d * n_bins, _CHUNK), 0)
    frow = fb // n_bins
    brow = fb % n_bins
    bv = jnp.zeros((d * n_bins, _CHUNK), jnp.int32)
    for f in range(d):
        bv = jnp.where(frow == f, bins_ref[f, :][None, :], bv)
    oh = (brow == bv).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        oh, w2t.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.DEFAULT,
        preferred_element_type=jnp.float32)

    @pl.when(first)
    def _():
        out_ref[0] = acc

    @pl.when(jnp.logical_not(first))
    def _():
        out_ref[0] += acc

def fused_hist(bins_t, loc, ws, n_nodes, n_bins):
    import math as _math
    dp, np_ = bins_t.shape
    S = ws.shape[1]
    cs_need = n_nodes * S
    cs0 = (S * 128) // _math.gcd(S, 128)
    cs = min(max(512 // cs0, 1) * cs0, -(-cs_need // cs0) * cs0)
    n_groups = -(-cs_need // cs)
    locp = loc.reshape(1, np_)
    wsp = ws.T
    out = pl.pallas_call(
        partial(_fused_kernel, d=dp, n_bins=n_bins, S=S, cs=cs),
        grid=(n_groups, np_ // _CHUNK),
        in_specs=[
            pl.BlockSpec((dp, _CHUNK), lambda g, r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _CHUNK), lambda g, r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((S, _CHUNK), lambda g, r: (0, r),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, dp * n_bins, cs), lambda g, r: (g, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_groups, dp * n_bins, cs),
                                       jnp.float32),
    )(bins_t.astype(jnp.int32), locp, wsp)
    npg = cs // S
    out = out.reshape(n_groups, dp, n_bins, npg, S)
    return out.transpose(0, 3, 1, 2, 4).reshape(
        n_groups * npg, dp, n_bins, S)[:n_nodes]

ftimes = {}
for t in LEVELS:
    M = 2 ** t
    f = jax.jit(jax.vmap(lambda l: fused_hist(bins_t, l, ws, M, Bn)))
    try:
        r = f(locs[t]); sync(r[..., 0].sum())
        t0 = time.perf_counter()
        r = f(locs[t]); sync(r[..., 0].sum())
        ftimes[t] = time.perf_counter() - t0
    except Exception as e:
        print(f"level {t}: fused FAILED: {type(e).__name__} {str(e)[:120]}")
        ftimes[t] = float("nan")
ftot = sum(v for v in ftimes.values() if v == v)
print("fused per level:", {t: round(v, 3) for t, v in ftimes.items()})
print(f"fused total: {ftot:.2f}s")

# numeric agreement at one level
ra = jax.vmap(lambda l: level_histogram_dense(bins_t, l, ws, 16, Bn,
                                              fast=True))(locs[4])
rb = jax.vmap(lambda l: fused_hist(bins_t, l, ws, 16, Bn))(locs[4])
print("agree:", bool(np.allclose(np.asarray(ra), np.asarray(rb),
                                 atol=0.5, rtol=1e-2)))

# --- 3. full-build phase breakdown (run as main part 2) -------------------
def phase_breakdown():
    import time
    from hivemall_tpu.ops.trees import quantize_bins, build_tree_classifier
    from hivemall_tpu.ops.trees import predict_bins_device
    y = (np.asarray(bins[:, :4]).sum(1) > 2 * Bn).astype(np.int32)
    X = rng.normal(0, 1, (n, d)).astype(np.float32)
    t0 = time.perf_counter()
    codes, edges = quantize_bins(X, Bn)
    t1 = time.perf_counter()
    print(f"quantize_bins host: {t1-t0:.2f}s")
    w = np.empty((E, n), np.int8)
    t0 = time.perf_counter()
    r2 = np.random.default_rng(1)
    for e in range(E):
        w[e] = np.bincount(r2.integers(0, n, n), minlength=n).astype(np.int8)
    t1 = time.perf_counter()
    print(f"bootstrap host: {t1-t0:.2f}s")
    t0 = time.perf_counter()
    cj = jnp.asarray(codes); sync(cj[:4, :4].astype(jnp.float32))
    t1 = time.perf_counter()
    print(f"h2d bins ({codes.nbytes/1e6:.0f} MB): {t1-t0:.2f}s")
    t0 = time.perf_counter()
    wj = jnp.asarray(w); sync(wj[:, :4].astype(jnp.float32))
    t1 = time.perf_counter()
    print(f"h2d w ({w.nbytes/1e6:.0f} MB): {t1-t0:.2f}s")
    # full build (includes everything again, warm compile from bench maybe)
    t0 = time.perf_counter()
    tree = build_tree_classifier(cj, y, w, edges, 2, depth=8, n_bins=Bn,
                                 mtry=5, seed=31, n_trees=E)
    jax.block_until_ready(tree.feat)
    sync(jnp.asarray(tree.value).sum())
    t1 = time.perf_counter()
    print(f"build_tree_classifier (given staged bins): {t1-t0:.2f}s "
          f"(first call INCLUDES compile)")
    t0 = time.perf_counter()
    tree = build_tree_classifier(cj, y, w, edges, 2, depth=8, n_bins=Bn,
                                 mtry=5, seed=32, n_trees=E)
    sync(jnp.asarray(tree.value).sum())
    t1 = time.perf_counter()
    print(f"build (warm): {t1-t0:.2f}s")
    t0 = time.perf_counter()
    preds = predict_bins_device(tree, cj)
    sync(preds.sum())
    t1 = time.perf_counter()
    print(f"OOB-style predict sweep: {t1-t0:.2f}s")

if __name__ == "__main__":
    phase_breakdown()

# --- round-5 A/B: is the fused kernel's one-hot CONSTRUCTION the lever?
# Variants of the [d*n_bins, CHUNK] bin one-hot build, measured at the RF
# bench shape (1M rows, d=28, 64 bins, S=2, chunk 512, 256 nodes):
#   d-loop of jnp.where (current)     : 25.26 ms   bit-identical
#   broadcast_to + reshape            : 33.99 ms   (sublane-collapse
#       reshape lowers WORSE than the where-chain)
#   pltpu.repeat(bins, n_bins, 0)     : 25.39 ms   (speed-neutral; row
#       order is b-major so out rows would need the inverse permute)
# Conclusion: the construction is NOT separable overhead — Mosaic already
# overlaps it; the ~27-31% MXU ceiling is the intrinsic compare+accumulate
# mix of this layout, and ROADMAP gap #3 ("a radically different binning
# layout for the next factor") stands confirmed.
