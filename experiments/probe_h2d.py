"""h2d relay characterization: bandwidth + latency vs transfer size, and
whether device_put transfers overlap jitted compute (the round-4 e2e
question: is the 25 MB/s + 200 ms/transfer model right, and does the
prefetcher actually hide transfers under compute?)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp

def sync(x):
    return float(np.asarray(jnp.asarray(x).sum()))

@jax.jit
def probe_sum(a):
    return a.astype(jnp.uint32).sum()

# warm
a = np.ones(1 << 16, np.uint8)
sync(probe_sum(jax.device_put(a)))

print("== h2d bandwidth vs size (uint8, single device_put) ==")
for mb in (0.25, 1, 4, 16, 64):
    n = int(mb * (1 << 20))
    a = np.random.default_rng(0).integers(0, 255, n, dtype=np.uint8)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        d = jax.device_put(a)
        sync(probe_sum(d))          # forces the transfer to complete
        ts.append(time.perf_counter() - t0)
    best = min(ts)
    print(f"{mb:6.2f} MB: best {best*1e3:8.1f} ms  -> {mb/best:7.1f} MB/s")

print("== overlap: device_put on a thread while a long matmul runs ==")
import threading
M = jnp.asarray(np.random.default_rng(0).normal(size=(8192, 8192)).astype(np.float32))
@jax.jit
def burn(M, k):
    def body(_, x):
        return jnp.tanh(x @ M)
    return jax.lax.fori_loop(0, k, body, M).sum()
# calibrate burn to ~1s
sync(burn(M, 2))
t0 = time.perf_counter(); sync(burn(M, 20)); t_burn = time.perf_counter() - t0
print(f"burn(20) alone: {t_burn:.2f}s")
payload = np.random.default_rng(0).integers(0, 255, 8 << 20, dtype=np.uint8)
t0 = time.perf_counter()
d = jax.device_put(payload); sync(probe_sum(d))
t_put = time.perf_counter() - t0
print(f"8MB put alone: {t_put:.2f}s")
res = {}
def putter():
    t0 = time.perf_counter()
    d = jax.device_put(payload)
    res["staged"] = d
    res["put_done"] = time.perf_counter() - t0
t0 = time.perf_counter()
th = threading.Thread(target=putter); th.start()
sync(burn(M, 20))
t_both_burn = time.perf_counter() - t0
th.join()
sync(probe_sum(res["staged"]))
t_total = time.perf_counter() - t0
ov = (t_burn + t_put - t_total) / min(t_burn, t_put)
print(f"concurrent: burn finished {t_both_burn:.2f}s, total {t_total:.2f}s, "
      f"overlap fraction ~{ov:.2f}")
