"""Phase breakdown of the WARM RandomForest fit (round 5: StagedMatrix +
-bootstrap poisson made the bench repeat-path 1.65 s at 1M x 28 x 16
trees — where does that go now that quantize/h2d/bootstrap-h2d are off
the clock?"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from hivemall_tpu.models.trees import RandomForestClassifier, StagedMatrix
from hivemall_tpu.ops.trees import build_tree_classifier, predict_bins_device

n, d, depth, E = 1_000_000, 28, 8, 16
rng = np.random.default_rng(0)
X = rng.normal(0, 1, (n, d)).astype(np.float32)
y = (X[:, :4].sum(1) + 0.5 * rng.normal(0, 1, n) > 0).astype(np.int32)

t0 = time.perf_counter()
Xs = StagedMatrix.stage(X, 64)
float(np.asarray(Xs.binsj[0, 0]))
print(f"stage (quantize + h2d): {time.perf_counter()-t0:6.2f} s", flush=True)

# warm compiles
RandomForestClassifier(f"-trees {E} -depth {depth} -seed 7 "
                       f"-bootstrap poisson").fit(Xs, y)

def timed(label, fn, reps=3):
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    print(f"{label:34s} {best:6.3f} s", flush=True)
    return out

# full warm fit
timed("full warm fit", lambda: RandomForestClassifier(
    f"-trees {E} -depth {depth} -seed 31 -bootstrap poisson").fit(Xs, y))

# build only (device bootstrap + builder, one value-synced fetch)
yj = np.searchsorted(np.unique(y), y)
key = jax.random.PRNGKey(38)
w = jax.random.poisson(key, 1.0, (E, n)).astype(jnp.int8)
w.block_until_ready()

def build_only():
    tree = build_tree_classifier(Xs.binsj, yj, w, Xs.edges, 2, depth=depth,
                                 n_bins=64, mtry=5, min_split=2.0,
                                 min_leaf=1.0, seed=31, n_trees=E)
    return tree

tree = timed("build_tree_classifier (synced)", build_only)

# OOB pass only
def oob_only():
    preds = predict_bins_device(tree, Xs.binsj)
    pe = preds.argmax(-1)
    oob = jnp.asarray(w) == 0
    n_oob = jnp.maximum(oob.sum(1), 1)
    err = ((pe != jnp.asarray(yj)[None, :]) & oob).sum(1) / n_oob
    return float(np.asarray(err.sum()))

timed("OOB predict+error (synced)", oob_only)

# poisson bootstrap generation alone
def boot_only():
    ww = jax.random.poisson(jax.random.PRNGKey(39), 1.0,
                            (E, n)).astype(jnp.int8)
    return float(np.asarray(ww.sum(), np.float64))

timed("poisson bootstrap (synced)", boot_only)
