"""Does XLA gather/scatter per-row cost depend on the table size?

If a VMEM-resident table gathers/scatters faster per row, the FFM table can
be partitioned by field (40 partitions of Mr/F rows) and each partition
processed with a small-table op.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

W = 168
N = 1310720  # total row-ops, matched to the flagship step

rng = np.random.default_rng(0)


def sync(x):
    return float(np.asarray(jnp.asarray(x).astype(jnp.float32).sum(), np.float64))


def timeit(fn, iters=20, repeats=3):
    out = fn()
    sync(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def report(name, secs, nrows):
    print(f"{name:44s} {secs*1e3:9.3f} ms  {nrows/secs/1e6:8.1f} Mrows/s  "
          f"{secs/nrows*1e9:6.2f} ns/row", flush=True)


def main():
    for mrows in (1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 21):
        T = jnp.asarray(rng.standard_normal((mrows, W)), jnp.bfloat16)
        rows = jnp.asarray(rng.integers(0, mrows, (N,)).astype(np.int32))
        g = jnp.asarray(rng.standard_normal((N, W)).astype(np.float32))

        gather_sum = jax.jit(lambda T, r: T[r].astype(jnp.float32).sum())
        report(f"gather  Mr=2^{int(np.log2(mrows))}",
               timeit(lambda: gather_sum(T, rows)), N)

        scat = jax.jit(lambda G, r, g: G.at[r].add(g))
        G = jnp.zeros((mrows, W), jnp.float32)
        report(f"scatter Mr=2^{int(np.log2(mrows))}",
               timeit(lambda: scat(G, rows, g)), N)

    # batched variant: L separate scatters of B rows each into one table
    # (the field-partitioned shape: one scatter per field partition)
    mrows, B, L = 1 << 13, 32768, 40
    T = jnp.asarray(rng.standard_normal((L, mrows, W)), jnp.bfloat16)
    rows2 = jnp.asarray(rng.integers(0, mrows, (L, B)).astype(np.int32))
    g2 = jnp.asarray(rng.standard_normal((L, B, W)).astype(np.float32))

    @jax.jit
    def scat_part(T, rows2, g2):
        G = jnp.zeros(T.shape, jnp.float32)
        # one scatter per partition, vmapped over the leading axis
        return jax.vmap(lambda Gp, r, g: Gp.at[r].add(g))(G, rows2, g2)
    report("scatter 40x(32k into 2^13) vmapped",
           timeit(lambda: scat_part(T, rows2, g2)), N)

    @jax.jit
    def gath_part(T, rows2):
        return jax.vmap(lambda Tp, r: Tp[r])(T, rows2).astype(
            jnp.float32).sum()
    report("gather  40x(32k from 2^13) vmapped",
           timeit(lambda: gath_part(T, rows2)), N)

    # one-hot matmul accumulation into a 2^13 partition (MXU scatter analog)
    @jax.jit
    def scat_onehot(rows2, g2):
        iota = jnp.arange(mrows, dtype=jnp.int32)
        def one(r, g):
            E = (r[:, None] == iota[None, :]).astype(jnp.bfloat16)
            return jax.lax.dot_general(
                E, g.astype(jnp.bfloat16),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return jax.vmap(one)(rows2, g2).sum()
    report("scatter 40x onehot-matmul 2^13",
           timeit(lambda: scat_onehot(rows2, g2), iters=5), N)


if __name__ == "__main__":
    print(jax.devices(), flush=True)
    main()
