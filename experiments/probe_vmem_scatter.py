"""Probe: Pallas per-slot RMW accumulation into a VMEM-resident G tile.

S3 of the planned field-partitioned FFM step: for each field partition g,
accumulate gslab_g [B, W] into G_g [Mr_f, W] (VMEM scratch), sequential
fori_loop RMW — no DMA per row, no XLA scatter. Question: cycles/slot?

Also: XLA scatter as a python loop of F independent small scatters
(non-vmapped), to see if the small-table fast path survives.
"""
from __future__ import annotations

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, L, W = 32768, 40, 256
F = 40
MRF = 8192          # per-field partition rows (pow2 >= 262144/40)
N = B * L

rng = np.random.default_rng(0)


def sync(x):
    return float(np.asarray(jnp.asarray(x).astype(jnp.float32).sum(), np.float64))


def timeit(fn, iters=10, repeats=3):
    out = fn()
    sync(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def report(name, secs, nrows=N):
    print(f"{name:46s} {secs*1e3:9.3f} ms  {nrows/secs/1e6:8.1f} Mrows/s  "
          f"{secs/nrows*1e9:6.2f} ns/row", flush=True)


def make_pallas_scatter(chunk: int, unroll: int = 1, w: int = W):
    """gslab [L, B, w] bf16 + rows [L, B//128, 128] -> G [L, MRF, w] f32.

    Grid (L, B//chunk); G block revisited across chunk steps (accumulate in
    VMEM), written out once per field.
    """
    nc = B // chunk

    def kernel(rows_ref, g_ref, G_ref):
        c = pl.program_id(1)

        @pl.when(c == 0)
        def _():
            G_ref[...] = jnp.zeros_like(G_ref)

        def body(i, _):
            for u in range(unroll):
                j = i * unroll + u
                jj = c * chunk + j
                r = rows_ref[0, jj >> 7, jj & 127]
                G_ref[r, :] += g_ref[j, :].astype(jnp.float32)
            return 0

        jax.lax.fori_loop(0, chunk // unroll, body, 0)

    return pl.pallas_call(
        kernel,
        grid=(L, nc),
        in_specs=[
            pl.BlockSpec((1, B // 128, 128), lambda g, c: (g, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((chunk, w), lambda g, c: (g * nc + c, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((MRF, w), lambda g, c: (g, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((L * MRF, w), jnp.float32),
    )


def main():
    rows_np = rng.integers(0, MRF, (L, B)).astype(np.int32)
    rows = jnp.asarray(rows_np.reshape(L, B // 128, 128))
    g16 = jnp.asarray(rng.standard_normal((L * B, W)).astype(np.float32),
                      jnp.bfloat16)

    for chunk, unroll in ((8192, 1), (8192, 4)):
        try:
            fn = jax.jit(make_pallas_scatter(chunk, unroll))
            secs = timeit(lambda: fn(rows, g16), iters=5)
            report(f"pallas vmem-scatter chunk={chunk} u={unroll}", secs)
        except Exception as e:
            print(f"pallas chunk={chunk} u={unroll}: FAIL "
                  f"{type(e).__name__}: {str(e)[:300]}", flush=True)

    # correctness check on small case
    fn = jax.jit(make_pallas_scatter(8192, 1))
    out = fn(rows, g16).reshape(L, MRF, W)
    ref = jax.jit(lambda r, g: jax.vmap(
        lambda rr, gg: jnp.zeros((MRF, W), jnp.float32).at[rr].add(
            gg.astype(jnp.float32)))(r, g))(jnp.asarray(rows_np),
                                            g16.reshape(L, B, W))
    err = float(jnp.abs(out - ref).max())
    print(f"correctness max|diff| = {err:.3e}", flush=True)

    # XLA: python loop of 40 small scatters into separate arrays
    g32 = jnp.asarray(rng.standard_normal((L, B, W)).astype(np.float32))
    Gs = [jnp.zeros((MRF, W), jnp.float32) for _ in range(L)]

    @jax.jit
    def scat_loop(rows, g32):
        outs = []
        for i in range(L):
            outs.append(jnp.zeros((MRF, W), jnp.float32).at[rows[i]].add(
                g32[i]))
        return outs

    rows2d = jnp.asarray(rows_np)
    report("xla 40x separate scatters 2^13",
           timeit(lambda: scat_loop(rows2d, g32), iters=5))

    # XLA: gather loop from 40 small tables
    Ts = jnp.asarray(rng.standard_normal((L, MRF, W)), jnp.bfloat16)

    @jax.jit
    def gath_loop(Ts, rows):
        acc = jnp.zeros((), jnp.float32)
        for i in range(L):
            acc += Ts[i][rows[i]].astype(jnp.float32).sum()
        return acc

    report("xla 40x separate gathers 2^13",
           timeit(lambda: gath_loop(Ts, rows2d), iters=5))


if __name__ == "__main__":
    print(jax.devices(), flush=True)
    main()
