"""A/B the FULL flagship parts step with the current field-major phi vs
the b-major phi (probe_phi.py winner) — same process, same inputs, so the
comparison survives cross-run weather."""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

import hivemall_tpu.ops.fm_pallas as fp
from hivemall_tpu.ops.losses import get_loss

B, L, F, K = 32768, 40, 40, 4
dims = 1 << 24
MRF, wp, hp = fp.parts_geometry(dims, F, K)
loss = get_loss("logloss")
rng = np.random.default_rng(0)


def eta_fn(t):
    return 0.05


def sync(x):
    return float(np.asarray(jnp.asarray(x).astype(jnp.float32).sum(),
                            np.float64))


def phi_bmajor(w0f, slab, val, F, K):
    L, Bx = val.shape
    m = L // F
    FK = F * K
    Vg = slab[..., :FK].reshape(m, F, Bx, F, K)
    wg = slab[..., FK].astype(jnp.float32)
    U = Vg * val.reshape(m, F, Bx, 1, 1).astype(Vg.dtype)
    Cm = U if m == 1 else U.astype(jnp.float32).sum(0, keepdims=True)
    Cb = Cm.reshape(F, Bx, F, K).transpose(1, 0, 2, 3)   # [B, g, f, k]
    full = jnp.einsum("bgfk,bfgk->b", Cb, Cb,
                      preferred_element_type=jnp.float32)
    own = jnp.einsum("bggk->bgk", Cb).astype(jnp.float32)
    diag = (own * own).sum((1, 2))
    return w0f + (wg * val).sum(0) + 0.5 * (full - diag)


def run(phi_impl, label):
    orig = fp._phi_parts
    if phi_impl is not None:
        fp._phi_parts = phi_impl
    try:
        step = fp.make_parts_step(loss, eta_fn, (0.0, 0.0, 0.0), F, K, MRF,
                                  unit_val=True)
        T2 = jnp.asarray(rng.standard_normal((F * MRF * hp, 128)) * 0.01,
                         jnp.bfloat16)
        params = {"T2": T2, "w0": jnp.zeros((), jnp.float32)}
        opt_state = {"T2": {"gg": jnp.zeros((F * MRF * hp, 128),
                                            jnp.float32)},
                     "w0": {"gg": jnp.zeros(())}}
        idx = jnp.asarray(rng.integers(1, dims, (B, L)).astype(np.int32))
        lab = jnp.asarray((rng.integers(0, 2, B) * 2 - 1).astype(np.float32))
        mask = jnp.ones((B,), jnp.float32)
        p, s, l0 = step(params, opt_state, 0.0, idx, lab, mask)
        sync(l0)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for i in range(20):
                p, s, l0 = step(p, s, float(i), idx, lab, mask)
            sync(l0)
            best = min(best, (time.perf_counter() - t0) / 20)
        print(f"{label:12s} {best*1e3:7.2f} ms -> {B/best/1e3:5.0f}k ex/s",
              flush=True)
        return float(np.asarray(l0))
    finally:
        fp._phi_parts = orig


l_a = run(None, "fieldmajor")
l_b = run(phi_bmajor, "bmajor")
print(f"loss agreement: {l_a:.6g} vs {l_b:.6g} "
      f"(rel {abs(l_a-l_b)/abs(l_a):.2e})")
