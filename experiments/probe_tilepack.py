"""Probe 2: (a) XLA loop-of-F small gathers/scatters (does the small-table
fast path survive as separate ops?), (b) Pallas RMW loop on a tile-packed
G3 [MRF/4, 8, 128] f32 with dynamic LEADING-dim indexing, which Mosaic
should allow (the last-two-dims tiling stays whole).
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, L, W = 32768, 40, 256   # W padded to two 128-lane groups
F = L
MRF = 8192
N = B * L

rng = np.random.default_rng(0)


def sync(x):
    return float(np.asarray(jnp.asarray(x).astype(jnp.float32).sum(), np.float64))


def timeit(fn, iters=10, repeats=3):
    out = fn()
    sync(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def report(name, secs, nrows=N):
    print(f"{name:48s} {secs*1e3:9.3f} ms  {nrows/secs/1e6:8.1f} Mrows/s  "
          f"{secs/nrows*1e9:6.2f} ns/row", flush=True)


def probe_xla_loops():
    rows_np = rng.integers(0, MRF, (L, B)).astype(np.int32)
    rows2d = jnp.asarray(rows_np)
    g32 = jnp.asarray(rng.standard_normal((L, B, W)).astype(np.float32))
    Ts = jnp.asarray(rng.standard_normal((L, MRF, W)), jnp.bfloat16)

    @jax.jit
    def scat_loop(rows, g32):
        outs = []
        for i in range(L):
            outs.append(jnp.zeros((MRF, W), jnp.float32).at[rows[i]].add(
                g32[i]))
        return jnp.stack([o.sum() for o in outs]).sum()

    report("xla 40x separate scatters 2^13",
           timeit(lambda: scat_loop(rows2d, g32), iters=5))

    @jax.jit
    def gath_loop(Ts, rows):
        acc = jnp.zeros((), jnp.float32)
        for i in range(L):
            acc += Ts[i][rows[i]].astype(jnp.float32).sum()
        return acc

    report("xla 40x separate gathers 2^13",
           timeit(lambda: gath_loop(Ts, rows2d), iters=5))

    # gather rate vs (Mr, W): find the fast-path boundary
    for mr_e in (12, 13, 14, 16):
        for w in (128, 168, 256):
            T1 = jnp.asarray(rng.standard_normal((1 << mr_e, w)), jnp.bfloat16)
            rf = jnp.asarray(rng.integers(0, 1 << mr_e, N).astype(np.int32))
            g1 = jax.jit(lambda T, r: T[r].astype(jnp.float32).sum())
            report(f"xla gather Mr=2^{mr_e} W={w}",
                   timeit(lambda: g1(T1, rf), iters=5))


def make_tilepack_rmw(chunk: int, unroll: int = 4):
    """G3 [MRF//4, 8, 128] f32 accumulation with per-slot dynamic
    leading-dim RMW. g comes tile-packed [chunk//4, 8, 128] f32 (4
    consecutive slots per tile). Each slot's (2,128) sub-row is rotated to
    its target sublane pair and masked-added into G3[r>>2].

    Grid (L, B//chunk). This probe DOES NOT produce the true scatter (the
    rotate/mask arithmetic is exercised, correctness checked separately).
    """
    nc = B // chunk

    def kernel(rows_ref, g_ref, sub_iota_ref, G_ref):
        c = pl.program_id(1)

        @pl.when(jnp.logical_and(c == 0, pl.program_id(0) == 0))
        def _():
            G_ref[...] = jnp.zeros_like(G_ref)

        sub = sub_iota_ref[...]          # [8,128] sublane-pair index 0..3

        def body(i, _):
            for u in range(unroll):
                jt = i * unroll + u      # tile index within chunk
                jj = c * chunk // 4 + jt
                gtile = g_ref[jt]                     # [8,128] 4 slots
                for s in range(4):                    # the 4 packed slots
                    k = jj * 4 + s
                    r = rows_ref[0, k >> 7, k & 127]
                    rt = r >> 2
                    p = r & 3
                    rolled = pltpu.roll(gtile, (p - s) * 2, 0)
                    add = jnp.where(sub == p, rolled, 0.0)
                    G_ref[rt] += add
            return 0

        jax.lax.fori_loop(0, chunk // 4 // unroll, body, 0)

    return pl.pallas_call(
        kernel,
        grid=(L, nc),
        in_specs=[
            pl.BlockSpec((1, B // 128, 128), lambda g, c: (g, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((chunk // 4, 8, 128),
                         lambda g, c: (g * nc + c, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, 128), lambda g, c: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((MRF // 4, 8, 128), lambda g, c: (0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((MRF // 4, 8, 128), jnp.float32),
    )


def probe_tilepack():
    rows_np = rng.integers(0, MRF, (L, B)).astype(np.int32)
    rows = jnp.asarray(rows_np.reshape(L, B // 128, 128))
    g = jnp.asarray(rng.standard_normal((L * B // 4, 8, 128)).astype(np.float32))
    sub = jnp.asarray(np.repeat(np.arange(4), 2)[:, None]
                      * np.ones((1, 128), np.int32), jnp.int32)

    for chunk, unroll in ((2048, 2), (2048, 4), (4096, 4)):
        try:
            fn = jax.jit(make_tilepack_rmw(chunk, unroll))
            secs = timeit(lambda: fn(rows, g, sub), iters=5)
            report(f"pallas tilepack-rmw chunk={chunk} u={unroll}", secs)
        except Exception as e:
            print(f"tilepack {chunk}/{unroll}: FAIL {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)


if __name__ == "__main__":
    print(jax.devices(), flush=True)
    which = sys.argv[1:] or ["xla", "tile"]
    if "xla" in which:
        probe_xla_loops()
    if "tile" in which:
        probe_tilepack()
