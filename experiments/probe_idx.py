"""Round-3 probes: where do the flagship step's 71.7 ms actually go, and
can a Pallas per-row DMA pipeline beat XLA's ~26 ns/row gather/scatter?

Flagship shapes (bench_ffm_kernel): B=32768, L=40, F=40, K=4, dims=2^24
=> T [Mr=262144, W=168] bf16, rows [B*L=1310720] int32.

Run:  python experiments/probe_idx.py [probe ...]
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, L, F, K = 32768, 40, 40, 4
Mr, W = 262144, F * K + 8
N = B * L

rng = np.random.default_rng(0)
rows_np = rng.integers(0, Mr, (N,)).astype(np.int32)


def sync(x):
    return float(np.asarray(jnp.asarray(x).astype(jnp.float32).sum(), np.float64))


def timeit(fn, *args, iters=20, repeats=3):
    """fn(*args) -> array; returns best seconds/iter with true value sync."""
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def report(name, secs, nrows=None):
    extra = ""
    if nrows:
        extra = f"  {nrows/secs/1e6:8.1f} Mrows/s  {secs/nrows*1e9:6.2f} ns/row"
    print(f"{name:42s} {secs*1e3:9.3f} ms{extra}", flush=True)


# ---------------------------------------------------------------- XLA probes

def probe_xla():
    T = jnp.asarray(rng.standard_normal((Mr, W)), jnp.bfloat16)
    rows = jnp.asarray(rows_np)
    g = jnp.asarray(rng.standard_normal((N, W)).astype(np.float32))

    gather_sum = jax.jit(lambda T, r: T[r].astype(jnp.float32).sum())
    report("xla gather+fusedsum", timeit(gather_sum, T, rows), N)

    gather_mat = jax.jit(lambda T, r: T[r])
    report("xla gather materialize bf16", timeit(gather_mat, T, rows), N)

    @jax.jit
    def scat(G, r, g):
        return G.at[r].add(g)
    G = jnp.zeros((Mr, W), jnp.float32)
    report("xla scatter-add f32", timeit(lambda: scat(G, rows, g)), N)

    # scatter of bf16 payload
    @jax.jit
    def scat16(G, r, g):
        return G.at[r].add(g)
    G16 = jnp.zeros((Mr, W), jnp.bfloat16)
    report("xla scatter-add bf16", timeit(lambda: scat16(G16, rows, g.astype(jnp.bfloat16))), N)

    # unique-ish: sorted rows
    rs = jnp.asarray(np.sort(rows_np))
    report("xla gather sorted rows", timeit(gather_sum, T, rs), N)

    # half the rows (index-count scaling check)
    half = jnp.asarray(rows_np[: N // 2])
    report("xla gather half rows", timeit(gather_sum, T, half), N // 2)


# ------------------------------------------------------- step decomposition

def probe_step():
    from hivemall_tpu.ops.losses import get_loss
    from hivemall_tpu.ops import fm as fmops

    T = jnp.asarray(rng.standard_normal((Mr, W)), jnp.bfloat16)
    w0 = jnp.zeros((), jnp.float32)
    rows2 = jnp.asarray(rows_np.reshape(B, L))
    val = jnp.ones((B, L), jnp.float32)
    lab = jnp.asarray((rng.integers(0, 2, B) * 2 - 1).astype(np.float32))
    mask = jnp.ones((B,), jnp.float32)
    loss = get_loss("logloss")

    @jax.jit
    def fwd_only(T, rows2):
        slab = T[rows2.reshape(-1)].reshape(B, L, W)
        phi = fmops._fused_phi_fieldmajor(w0, slab, val, F, K)
        return (loss.loss(phi, lab) * mask).sum()
    report("step: gather+fwd", timeit(fwd_only, T, rows2))

    @jax.jit
    def fwd_bwd(T, rows2):
        slab = T[rows2.reshape(-1)].reshape(B, L, W)

        def f(s):
            phi = fmops._fused_phi_fieldmajor(w0, s, val, F, K)
            return (loss.loss(phi, lab) * mask).sum()
        l, gs = jax.value_and_grad(f)(slab)
        return l + gs.astype(jnp.float32).sum()
    report("step: gather+fwd+bwd(slab)", timeit(fwd_bwd, T, rows2))

    @jax.jit
    def fwd_bwd_scat(T, rows2):
        slab = T[rows2.reshape(-1)].reshape(B, L, W)

        def f(s):
            phi = fmops._fused_phi_fieldmajor(w0, s, val, F, K)
            return (loss.loss(phi, lab) * mask).sum()
        l, gs = jax.value_and_grad(f)(slab)
        G = jnp.zeros((Mr, W), jnp.float32).at[rows2.reshape(-1)].add(
            gs.reshape(-1, W).astype(jnp.float32))
        return l + G.sum()
    report("step: +scatter G", timeit(fwd_bwd_scat, T, rows2))

    # the true full-table grad via autodiff on T (what the real step does)
    @jax.jit
    def full_grad(T, rows2):
        def f(Tf):
            slab = Tf[rows2.reshape(-1)].reshape(B, L, W)
            phi = fmops._fused_phi_fieldmajor(w0, slab, val, F, K)
            return (loss.loss(phi, lab) * mask).sum()
        l, gT = jax.value_and_grad(f)(T.astype(jnp.float32))
        return l + gT.sum()
    report("step: autodiff-through-table", timeit(full_grad, T, rows2))

    # dense adagrad pass alone
    @jax.jit
    def dense_opt(T, G, S):
        S2 = S + G * G
        Tn = T.astype(jnp.float32) - 0.1 * G * jax.lax.rsqrt(S2 + 1e-6)
        return Tn.astype(jnp.bfloat16), S2
    G = jnp.asarray(rng.standard_normal((Mr, W)).astype(np.float32))
    S = jnp.ones((Mr, W), jnp.float32)

    def run_opt():
        Tn, S2 = dense_opt(T, G, S)
        return Tn.astype(jnp.float32).sum() + S2.sum()
    report("step: dense adagrad pass", timeit(run_opt))


# -------------------------------------------------------------- pallas DMA

def make_pallas_gather(tile: int, nq: int, width: int, unroll: int = 1,
                       sequential: bool = False):
    """Gather rows of a [Mr, width] bf16 HBM table into VMEM slabs tile rows
    at a time with an nq-deep DMA pipeline. HBM slices must be 8-row
    aligned, so each slot copies the aligned [8, width] block containing its
    row (8x bytes; bandwidth floor ~4 ms -- issue rate is the question).
    sequential=True copies block i instead (randomness control)."""
    n_tiles = N // tile

    def kernel(rows_ref, T_ref, out_ref, slab, sems):
        t = pl.program_id(0)

        def copy(i, slot):
            if sequential:
                r8 = ((t * tile + i) * 8) % Mr
            else:
                r8 = (rows_ref[i] // 8) * 8
            return pltpu.make_async_copy(
                T_ref.at[pl.ds(r8, 8), :], slab.at[i], sems.at[slot])

        for q in range(nq):
            copy(q, q).start()

        def body(i, _):
            for u in range(unroll):
                j = i * unroll + u
                copy(j, (j % nq)).wait()

                @pl.when(j + nq < tile)
                def _():
                    copy(j + nq, (j % nq)).start()
            return 0

        jax.lax.fori_loop(0, tile // unroll, body, 0)
        s = slab[...].astype(jnp.float32).sum(axis=(0, 1),
                                              keepdims=True)[0, :, :128]
        out_ref[...] = jnp.broadcast_to(s, (8, 128))

    grid_spec = pl.GridSpec(
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile,), lambda t: (t,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((8, 128), lambda t: (t, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((tile, 8, width), jnp.bfloat16),
            pltpu.SemaphoreType.DMA((nq,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles * 8, 128), jnp.float32),
    )


def probe_pallas():
    for width in (256,):
        T = jnp.asarray(rng.standard_normal((Mr, width)), jnp.bfloat16)
        rows = jnp.asarray(rows_np)
        for tile, nq, seq in ((512, 4, False), (512, 8, False),
                              (512, 16, False), (2048, 16, False),
                              (512, 8, True)):
            try:
                fn = jax.jit(make_pallas_gather(tile, nq, width,
                                                sequential=seq))
                secs = timeit(fn, rows, T, iters=5)
                report(f"pallas g8 tile={tile} nq={nq} seq={int(seq)}",
                       secs, N)
            except Exception as e:  # noqa
                print(f"pallas tile={tile} nq={nq}: FAIL "
                      f"{type(e).__name__}: {e}", flush=True)


def probe_pallas_unroll():
    T = jnp.asarray(rng.standard_normal((Mr, W)), jnp.bfloat16)
    rows = jnp.asarray(rows_np)
    for tile, nq, un in ((2048, 8, 4), (2048, 16, 4), (2048, 16, 8)):
        try:
            fn = jax.jit(make_pallas_gather(tile, nq, W, un))
            secs = timeit(fn, rows, T, iters=5)
            report(f"pallas gather t={tile} nq={nq} unroll={un}", secs, N)
        except Exception as e:  # noqa
            print(f"pallas unroll {tile}/{nq}/{un}: FAIL {type(e).__name__}: {e}",
                  flush=True)


PROBES = {"xla": probe_xla, "step": probe_step, "pallas": probe_pallas,
          "unroll": probe_pallas_unroll}

if __name__ == "__main__":
    names = sys.argv[1:] or list(PROBES)
    print(jax.devices(), flush=True)
    for n in names:
        print(f"--- {n}", flush=True)
        PROBES[n]()
