"""Probe: cheaper formulations of the parts-layout FFM interaction.

probe_parts_phases.py split the 39.6 ms flagship step into gather 11.4 +
fwd/bwd 12.3 + kernel 16.6.  The fwd/bwd share moves ~10 GB against a
~5 GB lower bound — the einsum "gbfk,fbgk->b" forces a (g<->f) transpose
of the 420 MB C tensor with a K=4 inner dim (element-granular shuffles),
twice more in the backward.  Candidates keep the same math (phi values
must match) with friendlier layouts.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

B, F, K = 32768, 40, 4
FK = F * K
wp = 256
L = F
rng = np.random.default_rng(0)


def sync(x):
    return float(np.asarray(jnp.asarray(x).astype(jnp.float32).sum(),
                            np.float64))


def timeit(fn, iters=20, repeats=3):
    sync(fn())
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


slab = jnp.asarray(rng.standard_normal((L, B, wp)) * 0.1, jnp.bfloat16)
valT = jnp.asarray((rng.random((L, B)) > 0.0).astype(np.float32))
lab = jnp.asarray((rng.integers(0, 2, B) * 2 - 1).astype(np.float32))


def phi_current(s):
    Vg = s[..., :FK].reshape(F, B, F, K)
    wg = s[..., FK].astype(jnp.float32)
    U = Vg * valT.reshape(F, B, 1, 1).astype(Vg.dtype)
    C = U.reshape(F, B, F, K)
    full = jnp.einsum("gbfk,fbgk->b", C, C,
                      preferred_element_type=jnp.float32)
    own = jnp.einsum("gbgk->bgk", U).astype(jnp.float32)
    diag = (own * own).sum((1, 2))
    return (wg * valT).sum(0) + 0.5 * (full - diag)


def phi_kmajor(s):
    """k-major: full[b] = sum_k <P_kb, P_kb^T> with P [F, F] on the MINOR
    axes — the transpose is a standard small 2D minor-dim transpose."""
    Vg = s[..., :FK].reshape(F, B, F, K)
    wg = s[..., FK].astype(jnp.float32)
    U = Vg * valT.reshape(F, B, 1, 1).astype(Vg.dtype)
    P = U.transpose(3, 1, 0, 2)                    # [K, B, F(g), F(f)]
    full = jnp.einsum("kbgf,kbfg->b", P, P,
                      preferred_element_type=jnp.float32)
    own = jnp.einsum("kbgg->bkg", P).astype(jnp.float32)
    diag = (own * own).sum((1, 2))
    return (wg * valT).sum(0) + 0.5 * (full - diag)


def phi_premat(s):
    """materialize the transposed C once (block-friendly axes order) and
    use a plain elementwise-product reduction."""
    Vg = s[..., :FK].reshape(F, B, F, K)
    wg = s[..., FK].astype(jnp.float32)
    U = Vg * valT.reshape(F, B, 1, 1).astype(Vg.dtype)
    Ct = U.transpose(2, 1, 0, 3)                   # [F(f), B, F(g), K]
    full = (U.astype(jnp.float32) * Ct.astype(jnp.float32)
            ).sum((0, 2, 3))
    own = jnp.einsum("gbgk->bgk", U).astype(jnp.float32)
    diag = (own * own).sum((1, 2))
    return (wg * valT).sum(0) + 0.5 * (full - diag)


def phi_bmajor(s):
    """b-major: move B to the front once (big contiguous blocks), then the
    g<->f swap is a minor-axes transpose of [F, FK]-ish tiles."""
    Vg = s[..., :FK].reshape(F, B, F, K)
    wg = s[..., FK].astype(jnp.float32)
    U = Vg * valT.reshape(F, B, 1, 1).astype(Vg.dtype)
    Cb = U.transpose(1, 0, 2, 3)                   # [B, F(g), F(f), K]
    full = jnp.einsum("bgfk,bfgk->b", Cb, Cb,
                      preferred_element_type=jnp.float32)
    own = jnp.einsum("bggk->bgk", Cb).astype(jnp.float32)
    diag = (own * own).sum((1, 2))
    return (wg * valT).sum(0) + 0.5 * (full - diag)


def loss_of(phi_fn):
    def f(s):
        phi = phi_fn(s)
        p = jax.nn.sigmoid(lab * phi)
        return -(jnp.log(jnp.maximum(p, 1e-12))).sum()
    return f


variants = [("current", phi_current), ("kmajor", phi_kmajor),
            ("premat", phi_premat), ("bmajor", phi_bmajor)]

ref = None
for name, fn in variants:
    fwd = jax.jit(lambda s, fn=fn: fn(s))
    g = jax.jit(jax.grad(loss_of(fn)))
    out = np.asarray(fwd(slab), np.float64)
    if ref is None:
        ref = out
    else:
        err = np.max(np.abs(out - ref) / (np.abs(ref) + 1e-3))
        assert err < 2e-2, (name, err)
    t_f = timeit(lambda: fwd(slab))
    t_g = timeit(lambda: g(slab))
    print(f"{name:10s} fwd {t_f*1e3:7.2f} ms   fwd+bwd {t_g*1e3:7.2f} ms",
          flush=True)
