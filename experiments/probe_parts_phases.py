"""Phase breakdown of the flagship parts step (round 5).

Where do the 44 ms go?  Floors: gather ~10.7 ns + RMW ~17 ns per slot
x 1.31M slots = 36 ms; anything above that is fwd/bwd compute, packing,
and the kernel's opt tail — the only head-room left after
probe_preagg.py killed duplicate pre-aggregation (85.5 ns/slot pipeline
vs <=17 ns/slot saving).
"""
from __future__ import annotations

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

import hivemall_tpu.ops.fm_pallas as fp
from hivemall_tpu.ops.losses import get_loss

B, L, F, K = 32768, 40, 40, 4
dims = 1 << 24
MRF, wp, hp = fp.parts_geometry(dims, F, K)
FK = F * K
loss = get_loss("logloss")
rng = np.random.default_rng(0)


def eta_fn(t):
    return 0.05


def sync(x):
    return float(np.asarray(jnp.asarray(x).astype(jnp.float32).sum(),
                            np.float64))


def timeit(fn, iters=20, repeats=3):
    sync(fn())
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


idx = jnp.asarray(rng.integers(1, dims, (B, L)).astype(np.int32))
lab = jnp.asarray((rng.integers(0, 2, B) * 2 - 1).astype(np.float32))
mask = jnp.ones((B,), jnp.float32)
T2 = jnp.asarray(rng.standard_normal((F * MRF * hp, 128)) * 0.01,
                 jnp.bfloat16)
S2 = jnp.zeros((F * MRF * hp, 128), jnp.float32)
w0 = jnp.zeros((), jnp.float32)
params = {"T2": T2, "w0": w0}
opt_state = {"T2": {"gg": S2}, "w0": {"gg": jnp.zeros(())}}

# --- full step (donating copies so the timed loop is steady-state) -----
step = fp.make_parts_step(loss, eta_fn, (0.0, 0.0, 0.0), F, K, MRF,
                          unit_val=True)
state = [params, opt_state]


def full():
    p, s, l0 = step(state[0], state[1], 0.0, idx, lab, mask)
    state[0], state[1] = p, s
    return l0


t_full = timeit(full)
print(f"full step:            {t_full*1e3:7.2f} ms  "
      f"-> {B/t_full/1e3:5.0f}k ex/s", flush=True)

# --- gather only -------------------------------------------------------


@jax.jit
def gather_only(T2, idx):
    idxT = idx.T
    fieldT = (jnp.arange(L, dtype=jnp.int32) % F)[:, None]
    rows = fp.parts_row_hash(idxT, fieldT, MRF)
    T4 = T2.reshape(F, MRF, hp, 128)
    local_rows = rows - fieldT * MRF
    slab = jnp.stack([T4[g][local_rows[g]] for g in range(F)])
    return slab.astype(jnp.float32).sum()


t_g = timeit(lambda: gather_only(state[0]["T2"], idx))
print(f"slab gather only:     {t_g*1e3:7.2f} ms", flush=True)

# --- gather + fwd/bwd (no kernel, no packing) --------------------------


@jax.jit
def fwdbwd(T2, w0, idx, lab, mask):
    idxT = idx.T
    val = (idx != 0).astype(jnp.float32)
    valT = val.T
    fieldT = (jnp.arange(L, dtype=jnp.int32) % F)[:, None]
    rows = fp.parts_row_hash(idxT, fieldT, MRF)
    T4 = T2.reshape(F, MRF, hp, 128)
    local_rows = rows - fieldT * MRF
    slab = jnp.stack([T4[g][local_rows[g]] for g in range(F)])

    def batch_loss(w0f, slabf):
        s = slabf.reshape(L, B, wp)
        phi = fp._phi_parts(w0f, s, valT, F, K)
        return (loss.loss(phi, lab) * mask).sum()

    ls, (g0, gslab) = jax.value_and_grad(batch_loss, argnums=(0, 1))(
        w0.astype(jnp.float32), slab)
    return ls + gslab.astype(jnp.float32).sum()


t_fb = timeit(lambda: fwdbwd(state[0]["T2"], state[0]["w0"], idx, lab, mask))
print(f"gather+fwd/bwd:       {t_fb*1e3:7.2f} ms  "
      f"(fwd/bwd share ~{(t_fb-t_g)*1e3:.2f})", flush=True)

# --- kernel only (fixed packed inputs) ---------------------------------
chunk = min(2048, B)
r_opt = min(1024, MRF * hp)
kern = fp._make_scatter_opt_kernel(B, L, F, MRF, hp, chunk, r_opt, FK,
                                   0.0, 0.0)
gpack = jnp.asarray(rng.standard_normal((F, B * hp // 16, 16, 128)) * 1e-3,
                    jnp.bfloat16)
local = jnp.asarray(
    rng.integers(0, MRF, (F, B // 128, 128)).astype(np.int32))
eta_t = jnp.full((1, 1), 0.05, jnp.float32)
pat = jnp.zeros((8, 128), jnp.float32)
kstate = [state[0]["T2"], state[1]["T2"]["gg"]]
kern_j = jax.jit(kern, donate_argnums=(5, 6))


def kern_only():
    Tn, Sn = kern_j(local, eta_t, pat, pat, gpack, kstate[0], kstate[1])
    kstate[0], kstate[1] = Tn, Sn
    return Tn[0]


t_k = timeit(kern_only)
print(f"pallas kernel only:   {t_k*1e3:7.2f} ms  "
      f"(accumulate+opt tail)", flush=True)

print(f"\nunaccounted (pack/transpose/w0/overlap): "
      f"{(t_full - t_fb - t_k)*1e3:+.2f} ms")
print(f"floors: gather {1.31e6*10.7e-9*1e3:.1f} + RMW "
      f"{1.31e6*17e-9*1e3:.1f} = {1.31e6*27.7e-9*1e3:.1f} ms "
      f"-> ceiling {B/(1.31e6*27.7e-9)/1e3:.0f}k ex/s")
