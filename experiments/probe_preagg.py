"""Probe: would per-field duplicate pre-aggregation beat the Pallas RMW?

VERDICT r4 #1: at flagship shapes (B=32768 slots into MRF=8192-row field
partitions) the mean slot duplication is >=4x by pigeonhole, and the RMW
pass (~22 ms of the 44 ms step) pays ~17 ns per SLOT.  A sort-by-row-id +
segment-sum could reduce the RMW to unique rows only (~0.25x the slots).

The question this probe answers with numbers: does the pre-aggregation
pipeline (sort keys, permute the [B, 2, 128] bf16 gradient slab into
sorted order, segment-sum runs, RMW unique rows) cost LESS than the
17 ns/slot x duplicated-fraction it saves?

Cost model going in (docs/PERFORMANCE.md "cost model" table): every
per-row index op — gather, scatter, RMW — costs 10.7-26 ns/row nearly
independent of row width, and pre-aggregation ADDS one permutation
gather per slot before it REMOVES any RMW.  Sort measured ~120 ms / 13M
int32 keys (~9 ns/key).  So the pipeline's floor is
  sort (~9) + permute-gather (~10.7-17) + segsum + boundary ops
per slot, against a maximum saving of 17 x (1 - unique/slots) ns/slot
(= ~12.8 ns at uniform 4.07x duplication, ~17 ns at infinite
duplication).  If permute-gather alone costs ~>= the RMW it replaces,
the design can NEVER win, on any duplication (Zipf included).
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

B, F, K, MRF = 32768, 40, 4, 8192
HP, W = 2, 256
N = B * F

rng = np.random.default_rng(0)


def sync(x):
    return float(np.asarray(jnp.asarray(x).astype(jnp.float32).sum(),
                            np.float64))


def timeit(fn, iters=5, repeats=3):
    out = fn()
    sync(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def report(name, secs, n=N):
    print(f"{name:52s} {secs*1e3:9.3f} ms  {secs/n*1e9:6.2f} ns/slot",
          flush=True)


def dup_stats(rows, label):
    u = np.unique(rows.reshape(F, B), axis=1)  # not meaningful per-axis; do per field
    uniq = sum(len(np.unique(rows[g])) for g in range(F))
    print(f"{label}: unique {uniq} / {N} slots = {uniq/N:.3f} "
          f"(dup factor {N/uniq:.2f}x); RMW saving ceiling "
          f"{17*(1-uniq/N):.1f} ns/slot", flush=True)
    return uniq


# --- batch row ids: uniform (bench synthetic) and Zipf (Criteo-like) ----
rows_u = rng.integers(0, MRF, (F, B)).astype(np.int32)
zipf_ids = rng.zipf(1.25, (F, B)).astype(np.int64)
h = (zipf_ids * 0x9E3779B1) & 0xFFFFFFFF
h ^= h >> 15
h = (h * 0xC2B2AE35) & 0xFFFFFFFF
rows_z = (h & (MRF - 1)).astype(np.int32)

uniq_u = dup_stats(rows_u, "uniform")
uniq_z = dup_stats(rows_z, "zipf(1.25)")

grad = jnp.asarray(rng.standard_normal((F, B, HP * 128)),
                   jnp.bfloat16)
keys_u = jnp.asarray(rows_u)
keys_z = jnp.asarray(rows_z)
iota = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32), (F, B))

# --- 1. sort keys + slot-id payload, per field (batched axis 1) ---------
sortf = jax.jit(lambda k: jax.lax.sort_key_val(k, iota, dimension=1))
t = timeit(lambda: sortf(keys_u)[0])
report("sort [F,B] int32 keys + slot payload", t)

# --- 2. permute-gather the gradient slab into sorted order --------------
perm_u = jax.jit(lambda k: jax.lax.sort_key_val(k, iota, dimension=1)[1]
                 )(keys_u)
permf = jax.jit(lambda g, p: jnp.take_along_axis(
    g, p[:, :, None], axis=1))
t_perm = timeit(lambda: permf(grad, perm_u))
report("permute [F,B,256] bf16 grad by sorted order", t_perm)

# --- 3. segment-sum of sorted runs via cumsum + boundary gather ---------
sorted_keys = jax.jit(lambda k: jax.lax.sort_key_val(k, iota, dimension=1)[0]
                      )(keys_u)


@jax.jit
def segsum(gs, ks):
    cs = jnp.cumsum(gs.astype(jnp.float32), axis=1)          # [F, B, 256]
    last = jnp.concatenate([ks[:, 1:] != ks[:, :-1],
                            jnp.ones((F, 1), bool)], axis=1)  # run ends
    # per-field compaction of run-end positions costs another B index ops;
    # for the probe, charge only the cumsum + mask (lower bound).
    return cs * last[:, :, None]


gsorted = permf(grad, perm_u)
t_seg = timeit(lambda: segsum(gsorted, sorted_keys))
report("cumsum segment-sum [F,B,256] f32 (lower bound)", t_seg)

# --- 4. reference: XLA scatter-add of ALL slots vs UNIQUE rows ----------
g32 = grad.astype(jnp.float32)


@jax.jit
def scat_all(g, k):
    out = jnp.zeros((F, MRF, HP * 128), jnp.float32)
    return jax.vmap(lambda o, gg, kk: o.at[kk].add(gg))(out, g, k)


t_scat = timeit(lambda: scat_all(g32, keys_u))
report("XLA scatter-add ALL slots (baseline analog)", t_scat)

# RMW-only production cost: cite the measured kernel share
print("\nmeasured production RMW share: ~22 ms for 1.31M slots = "
      "~17 ns/slot (docs/PERFORMANCE.md)", flush=True)

tot = t + t_perm + t_seg
print(f"\npre-agg pipeline total (sort + permute + segsum lower bound): "
      f"{tot*1e3:.1f} ms = {tot/N*1e9:.1f} ns/slot")
print(f"RMW saving at uniform dup ({N/uniq_u:.2f}x): "
      f"{17*(1-uniq_u/N):.1f} ns/slot -> net "
      f"{tot/N*1e9 - 17*(1-uniq_u/N):+.1f} ns/slot")
print(f"RMW saving ceiling (infinite dup): 17.0 ns/slot -> net "
      f"{tot/N*1e9 - 17.0:+.1f} ns/slot")
