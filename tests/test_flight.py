"""Black-box flight recorder tests (docs/OBSERVABILITY.md "Flight
recorder"): ring round-trip, torn-slot tolerance after a simulated
mid-write kill, the disabled one-attribute-check contract, the id
run-length codec, the fleet-wide merge (death gaps + uncompleted
requests), the auto-emitted post-mortem artifact, and the serving
seams' admit/complete correlation through a live ring."""

import os
import struct
import threading
import time

import numpy as np
import pytest

import hivemall_tpu.obs.flight as flight_mod
from hivemall_tpu.obs.flight import (DEFAULT_SLOT, FS, HEADER_SIZE,
                                     FlightRecorder, configure_flight,
                                     emit_postmortem, flight_stub,
                                     get_flight, merge_dir, pack_ids,
                                     read_ring, render_postmortem,
                                     unpack_ids)


@pytest.fixture
def live(tmp_path):
    """The process recorder bound to a tmp ring for one test, always
    left dark afterwards (it is process-global)."""
    fr = configure_flight(str(tmp_path), label="t")
    assert fr.enabled
    yield fr, str(tmp_path)
    configure_flight(None)


def _only_ring(d):
    rings = [os.path.join(d, f) for f in os.listdir(d)
             if f.endswith(".ring")]
    assert len(rings) == 1
    return rings[0]


# --- writer contract ---------------------------------------------------------

def test_disabled_record_is_one_attribute_check():
    fr = FlightRecorder()
    assert fr.enabled is False
    # no mapping exists; record must be a pure attribute check + return
    fr.record("req.admit", req=1, rows=4)
    fr.record("req.admit", f"req=1{FS}rows=4")
    fr.record("bare")
    assert fr.events == 0 and fr.truncated == 0
    assert fr.obs_section() == flight_stub()


def test_record_never_raises_after_close(tmp_path):
    fr = FlightRecorder().open(str(tmp_path / "a.ring"))
    fr.record("x", a=1)
    fr.close()
    fr.record("x", a=2)                  # dropped, not raised
    # racing close: enabled flipped back but the mapping is gone
    fr.enabled = True
    fr.record("x", a=3)
    fr.enabled = False


def test_ring_round_trip(tmp_path):
    path = str(tmp_path / "rt.ring")
    fr = FlightRecorder().open(path, label="unit")
    fr.record("req.admit", req=1, rows=4, depth=0)
    fr.record("req.admit", f"req=2{FS}rows=8{FS}trace=zz11")
    fr.record("batch.done",
              f"reqs={pack_ids([1, 2])}{FS}rows=12{FS}p=1.25")
    fr.record("engine.reload", ok=1, to=512)
    fr.close()

    r = read_ring(path)
    assert r["label"] == "unit" and r["pid"] == os.getpid()
    assert r["torn"] == 0
    kinds = [e["kind"] for e in r["events"]]
    assert kinds == ["req.admit", "req.admit", "batch.done",
                     "engine.reload"]
    e1, e2, bd, rl = r["events"]
    assert e1["fields"] == {"req": 1, "rows": 4, "depth": 0}
    assert e2["fields"]["trace"] == "zz11"        # strings survive
    assert bd["fields"]["p"] == 1.25              # floats coerce
    assert unpack_ids(bd["fields"]["reqs"]) == [1, 2]
    assert rl["fields"] == {"ok": 1, "to": 512}
    # timestamps are wall-clock and ordered
    ts = [e["ts"] for e in r["events"]]
    # wall-clock anchor: ring timestamps ARE wall time by design
    assert ts == sorted(ts) and abs(ts[0] - time.time()) < 60  # graftcheck: disable=GC02


def test_ring_wraps_and_counts_dropped(tmp_path):
    path = str(tmp_path / "wrap.ring")
    fr = FlightRecorder().open(path, nslots=8)
    for i in range(20):
        fr.record("tick", i=i)
    assert fr.events == 20 and fr.dropped == 12
    assert fr.obs_section()["utilization"] == 1.0
    fr.close()
    r = read_ring(path)
    assert [e["fields"]["i"] for e in r["events"]] == list(range(12, 20))


def test_torn_slot_detected_and_skipped(tmp_path):
    path = str(tmp_path / "torn.ring")
    fr = FlightRecorder().open(path, nslots=8)
    for i in range(5):
        fr.record("tick", i=i)
    fr.close()
    # simulate SIGKILL mid-write of slot 2: head stamped, tail stale
    with open(path, "r+b") as f:
        off = HEADER_SIZE + 2 * DEFAULT_SLOT
        f.seek(off + DEFAULT_SLOT - 4)
        f.write(struct.pack("<I", 0xDEAD))
    r = read_ring(path)
    assert r["torn"] == 1
    assert [e["fields"]["i"] for e in r["events"]] == [0, 1, 3, 4]


def test_oversized_payload_truncated_not_lost(tmp_path):
    path = str(tmp_path / "big.ring")
    fr = FlightRecorder().open(path)
    fr.record("huge", blob="x" * 10_000)
    assert fr.truncated == 1
    fr.close()
    r = read_ring(path)
    assert r["torn"] == 0 and len(r["events"]) == 1
    assert r["events"][0]["kind"] == "huge"


def test_not_a_ring_rejected(tmp_path):
    p = tmp_path / "nope.ring"
    p.write_bytes(b"\x00" * 1024)
    with pytest.raises(ValueError, match="bad magic"):
        read_ring(str(p))
    p2 = tmp_path / "short.ring"
    p2.write_bytes(b"hi")
    with pytest.raises(ValueError, match="truncated"):
        read_ring(str(p2))


def test_record_is_thread_safe(tmp_path):
    path = str(tmp_path / "mt.ring")
    fr = FlightRecorder().open(path, nslots=4096)

    def work():
        for i in range(300):
            fr.record("tick", i=i)

    ts = [threading.Thread(target=work) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # _last_seq is a benign-race plain store: some reserved seq, not
    # necessarily the max — the ring itself is the real guarantee
    assert 1 <= fr.events <= 1200
    fr.close()
    r = read_ring(path)
    assert r["torn"] == 0 and len(r["events"]) == 1200
    assert [e["seq"] for e in r["events"]] == list(range(1, 1201))


# --- id codec ---------------------------------------------------------------

def test_pack_unpack_ids_round_trip():
    for ids in ([], [7], [1, 2, 3], [5, 6, 7, 40], [3, 1, 2],
                list(range(1, 257))):
        assert unpack_ids(pack_ids(ids)) == ids
    assert pack_ids(range(1, 257)) == "1-256"     # a full batch fits
    # truncation tolerance: garbage tokens are skipped, a clipped
    # trailing range degrades to its start
    assert unpack_ids("1-3,5,9x") == [1, 2, 3, 5]
    assert unpack_ids("1-3,5,9-") == [1, 2, 3, 5, 9]
    assert unpack_ids("") == []


# --- merge + post-mortem -----------------------------------------------------

def _write_fleet(tmp_path):
    """A router ring, a victim ring that goes silent mid-flight, and a
    survivor that keeps serving — the SIGKILL post-mortem shape."""
    d = str(tmp_path)
    router = FlightRecorder().open(os.path.join(d, "router.ring"),
                                   label="router")
    victim = FlightRecorder().open(os.path.join(d, "replica-s0.ring"),
                                   label="replica-s0")
    survivor = FlightRecorder().open(os.path.join(d, "replica-s1.ring"),
                                     label="replica-s1")
    t0 = time.time() - 10.0   # wall-clock anchor # graftcheck: disable=GC02

    def stamp(fr, ts, kind, **fields):
        fr.record(kind, **fields)
        # rewrite the slot's wall clock so the scenario spans real time
        off = HEADER_SIZE + (fr.events - 1) % fr._nslots * fr._slot
        head = struct.Struct("<QdI")
        seq, _, n = head.unpack_from(fr._mm, off)
        head.pack_into(fr._mm, off, seq, ts, n)

    stamp(victim, t0 + 0.0, "req.admit", req=1, rows=4)
    stamp(victim, t0 + 0.1, "batch.done", reqs=pack_ids([1]), rows=4)
    stamp(victim, t0 + 0.2, "req.admit", req=2, rows=4, trace="zz11")
    stamp(victim, t0 + 0.3, "req.admit", req=3, rows=4)
    # victim dies here: 2 and 3 admitted, never completed
    for i in range(4, 10):
        stamp(survivor, t0 + i, "req.admit", req=i, rows=2)
        stamp(survivor, t0 + i + 0.05, "batch.done",
              reqs=pack_ids([i]), rows=2)
    stamp(router, t0 + 5.0, "fleet.respawn", slot=0, pid=200)
    stamp(router, t0 + 9.5, "route", rid="r2", status=200)
    for fr in (router, victim, survivor):
        fr.close()
    pid = os.getpid()
    return d, t0, {"router": f"router-{pid}",
                   "victim": f"replica-s0-{pid}",
                   "survivor": f"replica-s1-{pid}"}


def test_merge_dir_flags_death_gap_and_uncompleted(tmp_path):
    d, t0, names = _write_fleet(tmp_path)
    m = merge_dir(d)
    assert {r["name"] for r in m["rings"]} == set(names.values())
    assert not m["unreadable"]
    # events from all rings merge onto one ordered timeline
    ts = [e["ts"] for e in m["events"]]
    assert len(ts) == 18 and ts == sorted(ts)
    gaps = {g["ring"]: g for g in m["gaps"]}
    assert names["victim"] in gaps        # silent ~9.2s before the end
    assert gaps[names["victim"]]["gap_s"] > 5.0
    assert names["survivor"] not in gaps  # kept recording near the end
    dead = next(r for r in m["rings"] if r["name"] == names["victim"])
    assert [u["req"] for u in dead["uncompleted"]] == [2, 3]
    assert dead["uncompleted"][0]["trace"] == "zz11"
    # --since filters the merged timeline, not the gap analysis
    m2 = merge_dir(d, since=t0 + 4.0)
    assert m2["events"] and all(e["ts"] >= t0 + 4.0 for e in m2["events"])
    assert {g["ring"] for g in m2["gaps"]} == {g["ring"] for g in m["gaps"]}


def test_render_and_emit_postmortem(tmp_path):
    d, _t0, _names = _write_fleet(tmp_path)
    text = render_postmortem(merge_dir(d), tail=50)
    assert "DEATH GAP" in text
    assert "admitted but never completed (2): 2 trace=zz11, 3" in text
    assert "fleet.respawn" in text
    out = emit_postmortem(d)
    assert out and os.path.exists(out) and os.path.exists(out + ".json")
    with open(out) as f:
        assert "DEATH GAP" in f.read()
    # never raises, even pointed at a non-directory
    assert emit_postmortem(os.path.join(d, "router.ring")) is None


def test_obs_postmortem_cli(tmp_path, capsys):
    from hivemall_tpu.cli.main import main
    d, t0, _names = _write_fleet(tmp_path)
    assert main(["obs", "postmortem", d, "--tail", "30"]) == 0
    out = capsys.readouterr().out
    assert "flight postmortem: 3 ring(s)" in out and "DEATH GAP" in out
    # --since: absolute epoch narrows the timeline
    assert main(["obs", "postmortem", d, "--since", f"{t0 + 8.0}"]) == 0
    assert "route rid=r2" in capsys.readouterr().out
    empty = str(tmp_path / "void")
    os.makedirs(empty)
    assert main(["obs", "postmortem", empty]) == 1
    assert main(["obs", "postmortem"]) == 2


def test_parse_since_grammar():
    from hivemall_tpu.obs.report import parse_since
    assert parse_since(None) is None
    now = time.time()         # wall-clock anchor # graftcheck: disable=GC02
    rel = parse_since("300")              # seconds-ago form
    assert now - 301 < rel < now - 299    # graftcheck: disable=GC02
    assert parse_since("1754180000.5") == 1754180000.5


# --- process singleton -------------------------------------------------------

def test_get_flight_env_binding(tmp_path, monkeypatch):
    from hivemall_tpu.obs.registry import registry
    orig = get_flight()                   # the real process singleton
    monkeypatch.setattr(flight_mod, "_flight", None)
    monkeypatch.setenv(flight_mod.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(flight_mod.ENV_LABEL, "envtest")
    monkeypatch.setenv(flight_mod.ENV_SLOTS, "64")
    fr = get_flight()
    try:
        assert fr is not orig and fr.enabled
        assert fr.label == "envtest"
        assert fr.obs_section()["ring_slots"] == 64
        assert os.path.basename(fr.path) == f"envtest-{os.getpid()}.ring"
        assert get_flight() is fr
    finally:
        fr.close()
        # get_flight re-registered the temp recorder as the `flight`
        # section; point the registry back at the real singleton
        # (monkeypatch teardown restores flight_mod._flight itself)
        registry.register("flight", orig.obs_section)


def test_configure_flight_rebinds_and_registers(tmp_path):
    from hivemall_tpu.obs.registry import registry
    fr = configure_flight(str(tmp_path / "a"), label="one")
    try:
        assert fr is get_flight() and fr.enabled
        fr.record("x")
        sec = registry.snapshot()["flight"]
        assert sec["enabled"] and sec["events"] == 1
        assert set(sec) == set(flight_stub())     # stub parity, live side
        # rebind closes the old ring and opens a fresh one
        p1 = fr.path
        configure_flight(str(tmp_path / "b"), label="two")
        assert fr.path != p1 and fr.events == 0
    finally:
        configure_flight(None)
        assert registry.snapshot()["flight"]["enabled"] is False


# --- serving-plane correlation ----------------------------------------------

def test_batcher_events_correlate_through_ring(live):
    from hivemall_tpu.serve.batcher import MicroBatcher
    fr, d = live

    def predict(rows):
        return np.zeros(len(rows), np.float32)

    b = MicroBatcher(predict, max_batch=8, max_delay_ms=0.0)
    try:
        for _ in range(6):
            b.submit([("a",), ("b",)]).result(5)
    finally:
        b.close()
    fr.close()
    r = read_ring(_only_ring(d))
    admits = [e for e in r["events"] if e["kind"] == "req.admit"]
    assert [e["fields"]["req"] for e in admits] == list(range(1, 7))
    assert all(e["fields"]["rows"] == 2 for e in admits)
    done = set()
    for e in r["events"]:
        if e["kind"] == "batch.done":
            done.update(unpack_ids(e["fields"]["reqs"]))
    assert done == set(range(1, 7))       # every admit completed
    assert flight_mod._uncompleted(r["events"]) == []


def test_batcher_shed_reaches_ring(live):
    from hivemall_tpu.serve.batcher import MicroBatcher, ServeOverload
    fr, d = live
    started, gate = threading.Event(), threading.Event()

    def predict(rows):
        started.set()
        assert gate.wait(10)
        return np.zeros(len(rows), np.float32)

    b = MicroBatcher(predict, max_batch=8, max_delay_ms=0.0,
                     max_queue_rows=2)
    try:
        first = b.submit([("a",)])        # occupies the worker
        assert started.wait(5)
        queued = b.submit([("b",), ("c",)])
        with pytest.raises(ServeOverload):
            b.submit([("d",)])            # 2 rows queued + 1 > max 2
        gate.set()
        first.result(5)
        queued.result(5)
    finally:
        gate.set()
        b.close()
    fr.close()
    evs = read_ring(_only_ring(d))["events"]
    shed = [e for e in evs if e["kind"] == "req.shed"]
    assert len(shed) == 1
    assert shed[0]["fields"] == {"rows": 1, "depth": 2}
    # the shed request was never admitted: only reqs 1 and 2 exist
    admits = [e["fields"]["req"] for e in evs if e["kind"] == "req.admit"]
    assert admits == [1, 2]
    assert flight_mod._uncompleted(evs) == []


def test_engine_reload_edges_reach_ring(live, tmp_path):
    from hivemall_tpu.io.libsvm import synthetic_classification
    from hivemall_tpu.models.linear import GeneralClassifier
    from hivemall_tpu.serve.engine import PredictEngine
    fr, d = live
    opts = "-dims 256 -loss logloss -mini_batch 32"
    ds, _ = synthetic_classification(64, 32, seed=3)
    t = GeneralClassifier(opts)
    t.fit(ds)
    ck = tmp_path / "ck"
    ck.mkdir()
    t.save_bundle(str(ck / f"{t.NAME}-step{t._t:010d}.npz"))
    eng = PredictEngine("train_classifier", opts,
                        checkpoint_dir=str(ck), warmup=False)
    step0 = eng.model_step
    bad = ck / f"{t.NAME}-step{step0 + 999:010d}.npz"
    bad.write_bytes(b"not a bundle")
    assert eng.poll() is False            # corrupt: failure edge
    t.fit(ds)
    t.save_bundle(str(ck / f"{t.NAME}-step{t._t:010d}.npz"))
    assert eng.poll() is True             # newer valid: success edge
    fr.close()
    evs = [e for e in read_ring(_only_ring(d))["events"]
           if e["kind"] == "engine.reload"]
    assert [e["fields"]["ok"] for e in evs] == [0, 1]
    assert evs[0]["fields"]["err"]        # failure carries the exc type
    assert evs[1]["fields"]["from"] == step0
    assert evs[1]["fields"]["to"] == eng.model_step
