"""Smoke coverage for the last catalog functions no other test names
(fm/ffm/plsa predict assemblers, hashing tail, snr/fmeasure, mapred ctx)."""

import numpy as np

from hivemall_tpu.catalog.registry import lookup


def test_fm_predict_matches_formula():
    fm_predict = lookup("fm_predict").resolve()
    rng = np.random.default_rng(0)
    N, K, L = 16, 3, 4
    w0 = 0.3
    w = rng.normal(size=N).astype(np.float32)
    V = rng.normal(size=(N, K)).astype(np.float32)
    idx = rng.integers(1, N, (2, L)).astype(np.int32)
    val = rng.uniform(0.5, 1.5, (2, L)).astype(np.float32)
    got = np.asarray(fm_predict(w0, w, V, idx, val))
    for b in range(2):
        lin = w0 + sum(w[idx[b, l]] * val[b, l] for l in range(L))
        inter = 0.0
        for i in range(L):
            for j in range(i + 1, L):
                inter += float(V[idx[b, i]] @ V[idx[b, j]]) \
                    * val[b, i] * val[b, j]
        np.testing.assert_allclose(got[b], lin + inter, rtol=1e-4)


def test_ffm_predict_runs():
    ffm_predict = lookup("ffm_predict").resolve()
    rng = np.random.default_rng(1)
    N, F, K, L = 16, 3, 2, 3
    w0 = 0.0
    w = rng.normal(size=N).astype(np.float32)
    V = rng.normal(size=(N, F, K)).astype(np.float32)
    idx = rng.integers(1, N, (2, L)).astype(np.int32)
    val = np.ones((2, L), np.float32)
    fld = np.tile(np.arange(L, dtype=np.int32) % F, (2, 1))
    out = np.asarray(ffm_predict(w0, w, V, idx, val, fld))
    assert out.shape == (2,) and np.all(np.isfinite(out))


def test_plsa_predict_proportions():
    plsa_predict = lookup("plsa_predict").resolve()
    PLSA = lookup("train_plsa").resolve()
    tr = PLSA("-topics 2 -vocab 256 -mini_batch 4")
    for _ in range(10):
        tr.process(["sun", "moon", "star"] * 3)
        tr.process(["cash", "bank", "loan"] * 3)
    rows = list(tr.close())
    pairs = plsa_predict(["sun", "moon"], rows, topics=2)
    assert sorted(k for k, _ in pairs) == [0, 1]     # (topic, proportion)
    np.testing.assert_allclose(sum(p for _, p in pairs), 1.0, rtol=1e-5)


def test_hashing_tail():
    sha1 = lookup("sha1").resolve()
    ahv = lookup("array_hash_values").resolve()
    phv = lookup("prefixed_hash_values").resolve()
    h = sha1("hello")
    assert h == sha1("hello") and 1 <= h <= 2 ** 24
    vals = ahv(["a", "b"])
    assert len(vals) == 2 and all(isinstance(v, int) for v in vals)
    pv = phv(["a", "b"], "city")
    assert len(pv) == 2 and all(isinstance(s, str) for s in pv)


def test_snr_and_fmeasure():
    snr = lookup("snr").resolve()
    fmeasure = lookup("fmeasure").resolve()
    rng = np.random.default_rng(2)
    X = rng.normal(size=(60, 3)).astype(np.float32)
    y = (X[:, 0] > 0).astype(int)
    X[:, 0] += y * 3                       # feature 0 separates the classes
    s = np.asarray(snr(X, y))
    assert s.shape == (3,) and s[0] > s[1] and s[0] > s[2]
    f1 = fmeasure(np.asarray([1, 1, 0, 0]), np.asarray([1, 0, 0, 0]))
    assert 0 < f1 < 1


def test_mapred_context_tail(tmp_path):
    assert isinstance(lookup("rownum").resolve()(), int)
    assert isinstance(lookup("jobid").resolve()(), str)
    p = tmp_path / "cache.tsv"
    p.write_text("k1\tv1\n")
    dg = lookup("distcache_gets").resolve()
    assert dg(str(p), "k1") == "v1"
    assert dg(str(p), "nope", "dflt") == "dflt"
