import numpy as np
import pytest

from hivemall_tpu.frame import tools as T
from hivemall_tpu.frame.nlp import tokenize_cn, tokenize_ja


def test_array_functions():
    assert T.array_concat([1, 2], [3]) == [1, 2, 3]
    assert T.array_avg([[1, 2], [3, 4]]) == [2.0, 3.0]
    assert T.array_sum([[1, 2], [3, 4]]) == [4.0, 6.0]
    assert T.array_append([1], 2) == [1, 2]
    assert T.array_append(None, 1) == [1]
    assert T.array_union([3, 1], [2, 1]) == [1, 2, 3]
    assert T.array_intersect([1, 2, 3], [2, 3, 4]) == [2, 3]
    assert T.array_remove([1, 2, 1], 1) == [2]
    assert T.array_slice([1, 2, 3, 4], 1, 2) == [2, 3]
    assert T.array_slice([1, 2, 3, 4], -2) == [3, 4]
    assert T.array_flatten([[1], [2, 3]]) == [1, 2, 3]
    assert T.element_at([1, 2], 1) == 2
    assert T.element_at([1, 2], 5) is None
    assert T.first_element([7, 8]) == 7
    assert T.last_element([7, 8]) == 8
    assert T.sort_and_uniq_array([3, 1, 3]) == [1, 3]
    assert T.subarray([1, 2, 3], 1, 3) == [2, 3]
    assert T.subarray_startwith([1, 2, 3], 2) == [2, 3]
    assert T.subarray_endwith([1, 2, 3], 2) == [1, 2]
    assert T.to_string_array([1, None]) == ["1", None]
    assert T.array_to_str([1, 2], "-") == "1-2"
    assert T.select_k_best([10, 20, 30], [0.1, 0.9, 0.5], 2) == [20, 30]
    assert T.collect_all(iter([1, 2])) == [1, 2]
    assert list(T.conditional_emit([True, False, True], "abc")) == ["a", "c"]


def test_map_functions():
    assert T.to_map([1, 2], ["a", "b"]) == {1: "a", 2: "b"}
    assert list(T.to_ordered_map([2, 1], ["b", "a"])) == [1, 2]
    assert T.map_get_sum({"a": 1.0, "b": 2.0}, ["a", "b", "z"]) == 3.0
    assert T.map_tail_n({1: "a", 2: "b", 3: "c"}, 2) == {2: "b", 3: "c"}
    assert T.map_include_keys({1: "a", 2: "b"}, [1]) == {1: "a"}
    assert T.map_exclude_keys({1: "a", 2: "b"}, [1]) == {2: "b"}
    assert T.map_key_values({1: "a"}) == [(1, "a")]


def test_list_bits():
    assert T.to_ordered_list(["b", "a", "c"]) == ["a", "b", "c"]
    assert T.to_ordered_list([10, 30, 20], [1, 3, 2],
                             "-k 2 -reverse") == [30, 20]
    bits = T.to_bits([0, 3, 64])
    assert T.unbits(bits) == [0, 3, 64]
    assert T.unbits(T.bits_or(T.to_bits([1]), T.to_bits([2]))) == [1, 2]
    assert T.unbits(T.bits_collect(iter([5, 1]))) == [1, 5]


def test_compress_roundtrip():
    blob = T.deflate("hello world " * 50, level=6)
    assert len(blob) < 120
    assert T.inflate(blob) == "hello world " * 50


def test_text_functions():
    assert T.tokenize("Hello, World!", True) == ["hello", "world"]
    assert T.is_stopword("the") and not T.is_stopword("tpu")
    assert T.split_words("a  b\tc") == ["a", "b", "c"]
    assert T.normalize_unicode("ｱｲｳ") == "アイウ"
    assert T.singularize("berries") == "berry"
    assert T.singularize("children") == "child"
    assert T.singularize("glass") == "glass"
    data = b"\x00\xffhivemall\x01"
    assert T.unbase91(T.base91(data)) == data
    assert T.word_ngrams(["a", "b", "c"], 1, 2) == \
        ["a", "b", "c", "a b", "b c"]


def test_math_matrix():
    assert T.sigmoid(0.0) == 0.5
    assert T.sigmoid(100) == pytest.approx(1.0)
    assert T.sigmoid(-100) == pytest.approx(0.0)
    assert T.l2_norm([3, 4]) == 5.0
    out = T.transpose_and_dot([[1, 0], [0, 1]], [[1, 2], [3, 4]])
    assert out == [[1.0, 2.0], [3.0, 4.0]]


def test_mapred_sanity_json_vector():
    r1, r2 = T.rowid(), T.rowid()
    assert r1 != r2 and "-" in r1
    assert isinstance(T.taskid(), int)
    assert T.jobconf_gets("NOPE_MISSING", "dflt") == "dflt"
    assert T.assert_(True)
    with pytest.raises(AssertionError):
        T.assert_(False, "boom")
    with pytest.raises(RuntimeError):
        T.raise_error("x")
    assert T.from_json(T.to_json({"a": [1, 2]})) == {"a": [1, 2]}
    assert T.vector_add([1, 2], [3, 4]) == [4.0, 6.0]
    assert T.vector_dot([1, 2], [3, 4]) == 11.0
    assert T.vector_dot([1, 2], 2.0) == [2.0, 4.0]


def test_sessionize():
    s = T.sessionize()
    a = s(100, 30)
    b = s(120, 30)
    c = s(200, 30)     # gap 80 > 30 -> new session
    assert a == b != c


def test_sampling_series_topk():
    out = T.reservoir_sample(range(100), 10, seed=1)
    assert len(out) == 10 and len(set(out)) == 10
    assert list(T.generate_series(1, 5, 2)) == [1, 3, 5]
    assert list(T.generate_series(3, 1, -1)) == [3, 2, 1]
    groups = ["a", "a", "a", "b", "b"]
    scores = [0.1, 0.9, 0.5, 0.3, 0.7]
    vals = ["r1", "r2", "r3", "r4", "r5"]
    rows = list(T.each_top_k(2, groups, scores, vals))
    assert rows == [(1, 0.9, "r2"), (2, 0.5, "r3"),
                    (1, 0.7, "r5"), (2, 0.3, "r4")]
    bottom = list(T.each_top_k(-1, groups, scores, vals))
    assert bottom[0] == (1, 0.1, "r1")


def test_nlp_tokenizers():
    ja = tokenize_ja("私はTPUで機械学習を実行します")
    assert "TPU" in ja and len(ja) >= 5
    assert tokenize_ja(None) == []
    cn = tokenize_cn("我爱机器学习ML")
    assert "我" in cn and "ML" in cn
