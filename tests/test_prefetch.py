"""Device prefetcher: same batches, staged ahead, errors surface."""

import numpy as np
import pytest

from conftest import assert_batches_equal as _assert_staged_round_trip
from hivemall_tpu.io.libsvm import synthetic_classification
from hivemall_tpu.io.prefetch import DevicePrefetcher, stage_batch


def test_prefetcher_preserves_stream():
    ds, _ = synthetic_classification(100, 10, seed=1)
    direct = list(ds.batches(16, shuffle=False))
    fetched = list(DevicePrefetcher(ds.batches(16, shuffle=False)))
    assert len(fetched) == len(direct)
    for a, b in zip(direct, fetched):
        np.testing.assert_array_equal(np.asarray(a.idx), np.asarray(b.idx))
        np.testing.assert_array_equal(np.asarray(a.val), np.asarray(b.val))
        assert a.n_valid == b.n_valid


def test_prefetcher_propagates_source_errors():
    def bad():
        ds, _ = synthetic_classification(40, 5, seed=2)
        yield from ds.batches(16, shuffle=False)
        raise RuntimeError("upstream io died")

    it = DevicePrefetcher(bad())
    with pytest.raises(RuntimeError, match="upstream io died"):
        list(it)


def test_stage_batch_keeps_fields():
    ds, _ = synthetic_classification(20, 5, seed=3)
    b = next(iter(ds.batches(8, shuffle=False)))
    staged = stage_batch(b)
    assert staged.field is None and staged.n_valid == b.n_valid


def test_stage_batch_round_trip_sparse():
    """Every SparseBatch field survives staging — incl. field ids, val,
    n_valid and the fieldmajor flag."""
    rng = np.random.default_rng(8)
    B, L = 8, 5
    from hivemall_tpu.io.sparse import SparseBatch
    b = SparseBatch(rng.integers(1, 100, (B, L)).astype(np.int32),
                    rng.uniform(0.5, 1.5, (B, L)).astype(np.float32),
                    rng.normal(0, 1, B).astype(np.float32),
                    rng.integers(0, 4, (B, L)).astype(np.int32),
                    n_valid=6, fieldmajor=False)
    _assert_staged_round_trip(b, stage_batch(b))
    # unit-value elision (val=None) and fieldmajor are preserved as-is
    u = SparseBatch(b.idx, None, b.label, None, n_valid=6, fieldmajor=True)
    _assert_staged_round_trip(u, stage_batch(u))


def test_stage_batch_round_trip_packed():
    """Every PackedBatch field survives staging (B/L/n_valid/fieldmajor
    metadata ride beside the single uint8 buffer)."""
    from hivemall_tpu.io.sparse import (SparseBatch, pack_unit_fieldmajor)
    rng = np.random.default_rng(9)
    B, L = 8, 4
    idx = rng.integers(1, 1 << 20, (B, L)).astype(np.int32)
    hb = pack_unit_fieldmajor(
        SparseBatch(idx, None, rng.normal(0, 1, B).astype(np.float32),
                    None, n_valid=7, fieldmajor=True))
    _assert_staged_round_trip(hb, stage_batch(hb))


def test_fit_with_forced_prefetch():
    """fit() with the prefetcher produces the same model as without."""
    from hivemall_tpu.models.linear import GeneralClassifier

    ds, _ = synthetic_classification(200, 20, seed=4)
    opts = "-dims 256 -loss logloss -opt adagrad -mini_batch 32 -iters 2"
    plain = GeneralClassifier(opts).fit(ds, prefetch=False)
    pre = GeneralClassifier(opts).fit(ds, prefetch=True)
    np.testing.assert_allclose(np.asarray(plain.w), np.asarray(pre.w),
                               rtol=1e-6, atol=1e-7)


def test_prefetcher_close_releases_worker():
    """Abandoning the stream mid-iteration must not leave the worker
    blocked on a full queue."""
    ds, _ = synthetic_classification(400, 5, seed=6)
    it = DevicePrefetcher(ds.batches(8, shuffle=False), depth=1)
    next(it)                       # take one batch, abandon the rest
    it.close()
    assert not it._thread.is_alive()


def test_next_after_close_raises_stopiteration():
    ds, _ = synthetic_classification(100, 5, seed=7)
    it = DevicePrefetcher(ds.batches(8, shuffle=False), depth=1)
    next(it)
    it.close()
    with pytest.raises(StopIteration):
        next(it)


def test_del_releases_worker():
    """__del__ must actually release a worker blocked on a full queue, not
    just set the closed event."""
    ds, _ = synthetic_classification(400, 5, seed=8)
    it = DevicePrefetcher(ds.batches(8, shuffle=False), depth=1)
    next(it)                       # worker now blocked on the full queue
    thread = it._thread
    it.__del__()
    thread.join(timeout=5)
    assert not thread.is_alive()


def test_prefetcher_records_stats():
    from hivemall_tpu.io.pipeline import PipelineStats

    ds, _ = synthetic_classification(64, 5, seed=9)
    stats = PipelineStats()
    n = len(list(DevicePrefetcher(ds.batches(8, shuffle=False), depth=2,
                                  stats=stats)))
    assert stats.batches_staged == n == 8
    assert stats.stage_seconds >= 0.0
