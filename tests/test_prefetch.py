"""Device prefetcher: same batches, staged ahead, errors surface."""

import numpy as np
import pytest

from hivemall_tpu.io.libsvm import synthetic_classification
from hivemall_tpu.io.prefetch import DevicePrefetcher, stage_batch


def test_prefetcher_preserves_stream():
    ds, _ = synthetic_classification(100, 10, seed=1)
    direct = list(ds.batches(16, shuffle=False))
    fetched = list(DevicePrefetcher(ds.batches(16, shuffle=False)))
    assert len(fetched) == len(direct)
    for a, b in zip(direct, fetched):
        np.testing.assert_array_equal(np.asarray(a.idx), np.asarray(b.idx))
        np.testing.assert_array_equal(np.asarray(a.val), np.asarray(b.val))
        assert a.n_valid == b.n_valid


def test_prefetcher_propagates_source_errors():
    def bad():
        ds, _ = synthetic_classification(40, 5, seed=2)
        yield from ds.batches(16, shuffle=False)
        raise RuntimeError("upstream io died")

    it = DevicePrefetcher(bad())
    with pytest.raises(RuntimeError, match="upstream io died"):
        list(it)


def test_stage_batch_keeps_fields():
    ds, _ = synthetic_classification(20, 5, seed=3)
    b = next(iter(ds.batches(8, shuffle=False)))
    staged = stage_batch(b)
    assert staged.field is None and staged.n_valid == b.n_valid


def test_fit_with_forced_prefetch():
    """fit() with the prefetcher produces the same model as without."""
    from hivemall_tpu.models.linear import GeneralClassifier

    ds, _ = synthetic_classification(200, 20, seed=4)
    opts = "-dims 256 -loss logloss -opt adagrad -mini_batch 32 -iters 2"
    plain = GeneralClassifier(opts).fit(ds, prefetch=False)
    pre = GeneralClassifier(opts).fit(ds, prefetch=True)
    np.testing.assert_allclose(np.asarray(plain.w), np.asarray(pre.w),
                               rtol=1e-6, atol=1e-7)


def test_prefetcher_close_releases_worker():
    """Abandoning the stream mid-iteration must not leave the worker
    blocked on a full queue."""
    ds, _ = synthetic_classification(400, 5, seed=6)
    it = DevicePrefetcher(ds.batches(8, shuffle=False), depth=1)
    next(it)                       # take one batch, abandon the rest
    it.close()
    assert not it._thread.is_alive()


def test_next_after_close_raises_stopiteration():
    ds, _ = synthetic_classification(100, 5, seed=7)
    it = DevicePrefetcher(ds.batches(8, shuffle=False), depth=1)
    next(it)
    it.close()
    with pytest.raises(StopIteration):
        next(it)
