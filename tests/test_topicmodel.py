"""LDA/pLSA: topics separate a two-topic synthetic corpus (SURVEY.md §5
convergence-smoke style)."""

import numpy as np
import pytest

from hivemall_tpu.models.topicmodel import (LDATrainer, PLSATrainer,
                                            lda_predict)


def corpus(n=300, seed=0):
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "pony"]
    tech = ["cpu", "gpu", "ram", "ssd"]
    docs, labels = [], []
    for _ in range(n):
        topical = animals if rng.random() < 0.5 else tech
        docs.append([topical[rng.integers(4)] for _ in range(20)])
        labels.append(0 if topical is animals else 1)
    return docs, labels


@pytest.mark.parametrize("cls", [LDATrainer, PLSATrainer])
def test_two_topics_separate(cls):
    docs, labels = corpus()
    t = cls("-topics 2 -vocab 1024 -mini_batch 64 -iter 20 "
            "-tau0 16 -kappa 0.6 -total_docs 300")
    t.fit(docs)
    # every doc's dominant topic should track its true group
    assign = [int(np.argmax(t.transform(d))) for d in docs[:60]]
    labs = labels[:60]
    agree = np.mean([a == l for a, l in zip(assign, labs)])
    assert agree > 0.9 or agree < 0.1, agree      # up to topic relabeling


def test_model_rows_and_predict():
    docs, _ = corpus(200, seed=3)
    t = LDATrainer("-topics 2 -vocab 512 -mini_batch 64 -iter 20 "
                   "-total_docs 200")
    t.fit(docs)
    rows = list(t.close(top_n=4))
    assert len(rows) == 8                       # 2 topics x top 4 words
    words = {w for _, w, _ in rows}
    assert words & {"cat", "dog", "horse", "pony", "cpu", "gpu", "ram", "ssd"}
    # join-side predict agrees with trainer.transform on dominance
    full_rows = list(t.close())
    theta = dict(lda_predict(["cat", "dog", "cat"], full_rows, topics=2))
    assign = max(theta, key=theta.get)
    direct = int(np.argmax(t.transform(["cat", "dog", "cat"])))
    assert assign == direct


def test_udtf_lifecycle():
    t = LDATrainer("-topics 2 -vocab 256 -mini_batch 4 -total_docs 8")
    for _ in range(8):
        t.process(["a", "b", "a"])
    rows = list(t.close())
    assert rows and len(rows[0]) == 3


def test_lda_batch_fit_matches_streaming_process():
    """fit()'s vectorized ingest (intern + mhash_batch + sort/reduceat +
    vectorized padding) must produce the same model as per-doc process()
    — including ':count' tokens, empty docs, and the short-tail buffer."""
    import numpy as np

    from hivemall_tpu.models.topicmodel import LDATrainer

    rng = np.random.default_rng(3)
    vocab = [f"w{i}" for i in range(25)]
    docs = [[vocab[j] for j in rng.integers(0, 25, 12)] + ["heavy:2.5"]
            for _ in range(40)] + [[], ["w1", "w1", "w2"]]
    a = LDATrainer("-topics 2 -mini_batch 16").fit(docs)
    b = LDATrainer("-topics 2 -mini_batch 16")
    for d in docs:
        b.process(d)
    b._flush()
    la, lb = np.asarray(a.lam), np.asarray(b.lam)
    np.testing.assert_allclose(la, lb, rtol=5e-4, atol=5e-4)
    assert a._t == b._t and len(a._buf) == len(b._buf)
    # vocab names flow through for close() emission
    rows_a = sorted(set(w for _, w, _ in a.close(top_n=5)))
    rows_b = sorted(set(w for _, w, _ in b.close(top_n=5)))
    assert rows_a == rows_b
