"""Optimizer updates: quadratic-bowl convergence + state semantics."""

import jax.numpy as jnp
import numpy as np
import pytest

from hivemall_tpu.ops.optimizers import OPTIMIZERS, make_optimizer


def quad_converges(opt, steps=300, dim=8):
    """min ||w - w*||^2 by gradient steps; returns final distance."""
    rng = np.random.default_rng(1)
    target = jnp.asarray(rng.normal(0, 1, dim), jnp.float32)
    w = jnp.zeros(dim)
    state = opt.init(dim)
    for t in range(steps):
        g = w - target
        w, state = opt.update(w, g, state, float(t))
    w = opt.finalize(w, state)
    return float(jnp.abs(w - target).max()), target


@pytest.mark.parametrize("name", ["sgd", "momentum", "nesterov", "adagrad",
                                  "adam", "ftrl"])
def test_converges_to_target(name):
    opt = make_optimizer(name, eta_scheme="fixed", eta0=0.1, reg="no",
                         ftrl_l1=0.0, ftrl_l2=0.0)
    dist, _ = quad_converges(opt)
    assert dist < 0.05, f"{name}: {dist}"


def test_adadelta_makes_progress():
    opt = make_optimizer("adadelta", reg="no")
    dist, target = quad_converges(opt, steps=500)
    assert dist < float(jnp.abs(target).max())


def test_rda_sparsifies():
    """l1-RDA must zero out coordinates whose average gradient < lambda."""
    opt = make_optimizer("adagrad", reg="rda", lam=0.5, eta_scheme="fixed",
                         eta0=0.1)
    w = jnp.zeros(4)
    state = opt.init(4)
    # coordinate 0 has strong signal, coordinate 3 has tiny signal
    for t in range(200):
        g = jnp.asarray([-2.0, -1.0, 0.0, -0.01])
        w, state = opt.update(w, g, state, float(t))
    w = np.asarray(opt.finalize(w, state))
    assert w[0] > 0 and w[3] == 0.0
    assert opt.name == "adagrad_rda"  # '-opt adagrad -reg rda' upgrade


def test_ftrl_l1_sparsifies():
    opt = make_optimizer("ftrl", ftrl_l1=0.5, ftrl_alpha=0.5)
    w = jnp.zeros(2)
    state = opt.init(2)
    for t in range(100):
        g = w - jnp.asarray([3.0, 0.001])   # strong vs negligible pull
        w, state = opt.update(w, g, state, float(t))
    w = np.asarray(opt.finalize(w, state))
    assert abs(w[0]) > 1.0 and w[1] == 0.0


def test_l2_shrinks_weights():
    opt_noreg = make_optimizer("sgd", reg="no", eta_scheme="fixed", eta0=0.1)
    opt_l2 = make_optimizer("sgd", reg="l2", lam=0.5, eta_scheme="fixed",
                            eta0=0.1)
    for opt in (opt_noreg, opt_l2):
        w = jnp.zeros(1)
        s = opt.init(1)
        for t in range(200):
            w, s = opt.update(w, w - 2.0, s, float(t))
        if opt is opt_noreg:
            free = float(w[0])
        else:
            reg = float(w[0])
    assert reg < free


def test_unknown_raises():
    with pytest.raises(ValueError):
        make_optimizer("zzz")


def test_ftrl_sparse_duplicate_ids_subtract_sigma_once():
    """FTRL's -sigma*w term is entry-level (pre-batch -> batch-final n); a
    feature appearing d times in a batch must not subtract it d times."""
    import jax.numpy as jnp
    from hivemall_tpu.ops.optimizers import make_optimizer

    opt = make_optimizer("ftrl", ftrl_alpha=0.5, ftrl_beta=1.0,
                         ftrl_l1=0.0, ftrl_l2=0.0)
    w = jnp.array([0.0, 0.5])
    s = {"z": jnp.array([0.0, 0.1]), "n": jnp.array([0.0, 4.0])}
    g = np.array([0.3, 0.3], np.float32)        # two grads for id 1
    ix = np.array([1, 1], np.int32)
    w2, s2 = opt.sparse_update(w, jnp.asarray(g), s, jnp.asarray(ix), 0.0)
    n_final = 4.0 + 2 * 0.3 ** 2
    sigma = (np.sqrt(n_final) - np.sqrt(4.0)) / 0.5
    z_want = 0.1 + 0.6 - sigma * 0.5            # sigma applied ONCE
    np.testing.assert_allclose(float(s2["z"][1]), z_want, rtol=1e-6)
    np.testing.assert_allclose(float(s2["n"][1]), n_final, rtol=1e-6)
    # untouched id 0 stays put
    np.testing.assert_allclose(float(s2["z"][0]), 0.0)
