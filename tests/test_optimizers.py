"""Optimizer updates: quadratic-bowl convergence + state semantics."""

import jax.numpy as jnp
import numpy as np
import pytest

from hivemall_tpu.ops.optimizers import OPTIMIZERS, make_optimizer


def quad_converges(opt, steps=300, dim=8):
    """min ||w - w*||^2 by gradient steps; returns final distance."""
    rng = np.random.default_rng(1)
    target = jnp.asarray(rng.normal(0, 1, dim), jnp.float32)
    w = jnp.zeros(dim)
    state = opt.init(dim)
    for t in range(steps):
        g = w - target
        w, state = opt.update(w, g, state, float(t))
    w = opt.finalize(w, state)
    return float(jnp.abs(w - target).max()), target


@pytest.mark.parametrize("name", ["sgd", "momentum", "nesterov", "adagrad",
                                  "adam", "ftrl"])
def test_converges_to_target(name):
    opt = make_optimizer(name, eta_scheme="fixed", eta0=0.1, reg="no",
                         ftrl_l1=0.0, ftrl_l2=0.0)
    dist, _ = quad_converges(opt)
    assert dist < 0.05, f"{name}: {dist}"


def test_adadelta_makes_progress():
    opt = make_optimizer("adadelta", reg="no")
    dist, target = quad_converges(opt, steps=500)
    assert dist < float(jnp.abs(target).max())


def test_rda_sparsifies():
    """l1-RDA must zero out coordinates whose average gradient < lambda."""
    opt = make_optimizer("adagrad", reg="rda", lam=0.5, eta_scheme="fixed",
                         eta0=0.1)
    w = jnp.zeros(4)
    state = opt.init(4)
    # coordinate 0 has strong signal, coordinate 3 has tiny signal
    for t in range(200):
        g = jnp.asarray([-2.0, -1.0, 0.0, -0.01])
        w, state = opt.update(w, g, state, float(t))
    w = np.asarray(opt.finalize(w, state))
    assert w[0] > 0 and w[3] == 0.0
    assert opt.name == "adagrad_rda"  # '-opt adagrad -reg rda' upgrade


def test_ftrl_l1_sparsifies():
    opt = make_optimizer("ftrl", ftrl_l1=0.5, ftrl_alpha=0.5)
    w = jnp.zeros(2)
    state = opt.init(2)
    for t in range(100):
        g = w - jnp.asarray([3.0, 0.001])   # strong vs negligible pull
        w, state = opt.update(w, g, state, float(t))
    w = np.asarray(opt.finalize(w, state))
    assert abs(w[0]) > 1.0 and w[1] == 0.0


def test_l2_shrinks_weights():
    opt_noreg = make_optimizer("sgd", reg="no", eta_scheme="fixed", eta0=0.1)
    opt_l2 = make_optimizer("sgd", reg="l2", lam=0.5, eta_scheme="fixed",
                            eta0=0.1)
    for opt in (opt_noreg, opt_l2):
        w = jnp.zeros(1)
        s = opt.init(1)
        for t in range(200):
            w, s = opt.update(w, w - 2.0, s, float(t))
        if opt is opt_noreg:
            free = float(w[0])
        else:
            reg = float(w[0])
    assert reg < free


def test_unknown_raises():
    with pytest.raises(ValueError):
        make_optimizer("zzz")
