#!/usr/bin/env python
"""Deterministic dataset-shaped fixtures for golden convergence tests.

The reference commits LIBSVM snippets of a9a/news20/MovieLens as test
resources (SURVEY.md §5.2). This environment has no network access and no
copy of those datasets, so the committed fragments are SYNTHETIC but
dataset-SHAPED: schema, dimensionality, sparsity, label balance and
achievable quality are matched to the public datasets' documented
statistics, and generation is seed-pinned so the files are reproducible
from this script (python make_fragments.py regenerates byte-identical
outputs).

Shapes:
  a9a.frag       — 123 binary features (a9a's one-hot Adult encoding),
                   ~14 active per row, ~24% positive, logistic ground
                   truth with noise calibrated so 1-epoch AdaGrad logloss
                   lands near a9a's documented ~0.33 ballpark.
  news20b.frag   — news20.binary-shaped: 2^20 hashed dims, ~150 active
                   text-like features per row, balanced labels.
  movielens.frag — (user, item, rating) integer ratings 1..5 from a
                   low-rank + bias model, ML-100k-like margins.
  criteo_ffm.frag — field:index:value categorical rows whose labels are
                   dominated by rank-3 field-pair interactions; FFM must
                   beat a linear model on it by a wide AUC margin.
"""

import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def make_a9a(n_train=2000, n_test=1000, seed=101):
    rng = np.random.default_rng(seed)
    d = 123
    # block structure like one-hot groups: 15 categorical groups
    groups = np.array_split(np.arange(1, d + 1), 15)
    w = rng.normal(0, 1.0, d + 1)
    w[0] = 0.0
    rows = []
    labels = []
    for _ in range(n_train + n_test):
        feats = [int(rng.choice(g)) for g in groups if rng.random() < 0.93]
        margin = w[feats].sum() - 1.05    # shift for ~24% positive rate
        p = 1.0 / (1.0 + np.exp(-1.1 * margin))
        labels.append(1 if rng.random() < p else -1)
        rows.append(sorted(feats))
    return rows, labels, n_train


def write_libsvm(path, rows, labels):
    with open(path, "w") as f:
        for r, y in zip(rows, labels):
            f.write(f"{y} " + " ".join(f"{i}:1" for i in r) + "\n")


def make_news20b(n_train=600, n_test=300, seed=202):
    rng = np.random.default_rng(seed)
    dims = 1 << 20
    # zipf-weighted vocabulary: frequent terms shared, rare terms classy
    vocab = 50_000
    topic_a = rng.integers(1, dims, vocab)
    topic_b = rng.integers(1, dims, vocab)
    rows, labels = [], []
    for _ in range(n_train + n_test):
        y = 1 if rng.random() < 0.5 else -1
        src = topic_a if y > 0 else topic_b
        n_tok = int(rng.integers(80, 220))
        ranks = np.minimum((rng.zipf(1.35, n_tok) - 1), vocab - 1)
        common = rng.random(n_tok) < 0.35       # shared background terms
        ids = np.where(common, topic_a[ranks], src[ranks])
        uniq, cnt = np.unique(ids, return_counts=True)
        # tf-idf-ish weights, l2-normalized like news20.binary
        v = np.log1p(cnt.astype(np.float64))
        v /= np.linalg.norm(v) + 1e-12
        rows.append(list(zip(uniq.tolist(), np.round(v, 6).tolist())))
        labels.append(y)
    return rows, labels, n_train


def write_libsvm_valued(path, rows, labels):
    with open(path, "w") as f:
        for r, y in zip(rows, labels):
            f.write(f"{y} " + " ".join(f"{i}:{v:g}" for i, v in r) + "\n")


def make_criteo_ffm(n=6000, fields=6, vocab_per_field=12, seed=404):
    """Criteo-shaped FFM fragment: one categorical per field, labels from
    field-PAIR interactions (plus weak unary effects) so factorized
    interaction models separate from linear ones on it."""
    rng = np.random.default_rng(seed)
    F = fields
    # labels driven DOMINANTLY by field-pair interactions (weak unary), so
    # factorized interaction models separate from linear ones
    unary = rng.normal(0, 0.15, (F, vocab_per_field))
    k = 3
    emb = rng.normal(0, 0.9, (F, vocab_per_field, k))
    rows = []
    labels = []
    for _ in range(n):
        vals = rng.integers(0, vocab_per_field, F)
        s = unary[np.arange(F), vals].sum()
        for a in range(F):
            for b in range(a + 1, F):
                s += emb[a, vals[a]] @ emb[b, vals[b]] / np.sqrt(F)
        p = 1.0 / (1.0 + np.exp(-0.8 * s))
        labels.append(1 if rng.random() < p else -1)
        # feature string "field:index:1" with a global per-(field,value) id
        rows.append([f"{f}:{1 + f * vocab_per_field + int(v)}:1"
                     for f, v in enumerate(vals)])
    return rows, labels


def make_movielens(n=8000, users=400, items=300, k=6, seed=303):
    rng = np.random.default_rng(seed)
    P = rng.normal(0, 0.45, (users, k))
    Q = rng.normal(0, 0.45, (items, k))
    bu = rng.normal(0, 0.35, users)
    bi = rng.normal(0, 0.35, items)
    mu = 3.6                                    # ML-ish global mean
    u = rng.integers(0, users, n)
    i = rng.integers(0, items, n)
    r = mu + bu[u] + bi[i] + (P[u] * Q[i]).sum(1) + rng.normal(0, 0.4, n)
    r = np.clip(np.round(r), 1, 5).astype(int)
    return u, i, r


def main():
    rows, labels, nt = make_a9a()
    write_libsvm(os.path.join(HERE, "a9a.frag.train.libsvm"),
                 rows[:nt], labels[:nt])
    write_libsvm(os.path.join(HERE, "a9a.frag.test.libsvm"),
                 rows[nt:], labels[nt:])

    rows, labels, nt = make_news20b()
    write_libsvm_valued(os.path.join(HERE, "news20b.frag.train.libsvm"),
                        rows[:nt], labels[:nt])
    write_libsvm_valued(os.path.join(HERE, "news20b.frag.test.libsvm"),
                        rows[nt:], labels[nt:])

    u, i, r = make_movielens()
    with open(os.path.join(HERE, "movielens.frag.tsv"), "w") as f:
        for a, b, c in zip(u, i, r):
            f.write(f"{a}\t{b}\t{c}\n")

    rows, labels = make_criteo_ffm()
    with open(os.path.join(HERE, "criteo_ffm.frag.tsv"), "w") as f:
        for feats, y in zip(rows, labels):
            f.write(f"{y}\t" + " ".join(feats) + "\n")
    print("fragments written to", HERE)


if __name__ == "__main__":
    main()
