"""Test config: run JAX on CPU with 8 virtual devices.

Mirrors the reference's "distributed without a cluster" trick (SURVEY.md §5
item 3 — in-process localhost MixServer): mix/psum semantics are exercised on
an 8-device virtual CPU mesh, no TPU pod needed. Must run before jax imports.
"""

import os

# Force CPU: the session environment pins JAX_PLATFORMS=axon (the tunneled
# TPU chip), which (a) makes every jitted test compile over the tunnel and
# (b) deadlocks if two processes touch it concurrently. Tests always run on
# the virtual 8-device CPU mesh; only bench.py uses the real chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The axon sitecustomize (triggered by PALLAS_AXON_POOL_IPS at interpreter
# startup) registers the tunneled-TPU PJRT plugin and overrides the platform
# selection to "axon,cpu" via jax.config — which makes the JAX_PLATFORMS env
# var above a no-op and every backends() call block on the tunnel. Re-pin the
# config to cpu AFTER that registration (jax is already imported by
# sitecustomize, so this import is cheap and backends are not yet
# initialized).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soak variants (fault-injection soaks etc.); excluded "
        "from the tier-1 `-m 'not slow'` run")


def assert_batches_equal(a, b):
    """``a`` == ``b`` over EVERY dataclass field — tree structure and
    values. Introspects dataclasses.fields so staging/prep paths can never
    silently drop metadata the batch dataclass grows later. Handles host
    and device (staged) arrays alike, ``None`` fields included."""
    import dataclasses

    import numpy as np
    assert type(a) is type(b)
    for f in dataclasses.fields(a):
        x, y = getattr(a, f.name), getattr(b, f.name)
        if x is None or y is None:
            assert x is None and y is None, f.name
        elif isinstance(x, np.ndarray) or hasattr(x, "shape"):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg=f.name)
        else:
            assert x == y, f.name
