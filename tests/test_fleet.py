"""Scale-out serving fleet (hivemall_tpu/serve/{router,fleet}.py,
docs/SERVING.md "Fleet topology"): router placement policy (least-loaded
with consistent-hash fallback), health gating, transport retry on dead
replicas, verbatim relay, aggregated fleet obs — against real in-process
PredictServers as replicas (cheap: no worker processes). The full
multi-process lifecycle (spawn, kill+respawn, rolling reload under
traffic) is pinned by the fleet smoke in run_tests.sh and by the `slow`
test at the bottom.
"""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from hivemall_tpu.serve.router import RouterServer, _Ring

OPTS = "-dims 1024 -loss logloss -opt adagrad -mini_batch 32"


@pytest.fixture()
def trained(tmp_path):
    from hivemall_tpu.io.libsvm import synthetic_classification
    from hivemall_tpu.models.linear import GeneralClassifier
    ds, _ = synthetic_classification(120, 64, seed=11)
    t = GeneralClassifier(OPTS)
    t.fit(ds)
    path = os.path.join(tmp_path, f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(path)
    return t, ds, str(tmp_path), path


def _replica(ckdir):
    """A real PredictServer used as an in-process 'replica'."""
    from hivemall_tpu.serve.engine import PredictEngine
    from hivemall_tpu.serve.http import PredictServer
    eng = PredictEngine("train_classifier", OPTS, checkpoint_dir=ckdir,
                        warmup=False)
    return PredictServer(eng, port=0, max_delay_ms=1.0, watch=False).start()


def _rows_of(ds, n):
    out = []
    for i in range(n):
        idx, val = ds.row(i)
        out.append([f"{int(a)}:{float(v)!r}" for a, v in zip(idx, val)])
    return out


def _post(url, obj, timeout=15.0):
    req = urllib.request.Request(url, json.dumps(obj).encode(),
                                 {"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=timeout).read())


# --- consistent-hash ring ----------------------------------------------------

def test_ring_stability_and_coverage():
    ring = _Ring()
    ring.rebuild(["a", "b", "c"])
    picks = {ring.pick(k * 2654435761 % (1 << 64), {"a", "b", "c"})
             for k in range(200)}
    assert picks == {"a", "b", "c"}          # every replica reachable
    # same key -> same replica, deterministically
    for k in (1, 99, 12345):
        assert ring.pick(k, {"a", "b", "c"}) == ring.pick(k, {"a", "b", "c"})
    # removing one replica only remaps ITS keys: survivors keep theirs
    before = {k: ring.pick(k, {"a", "b", "c"}) for k in range(500)}
    after = {k: ring.pick(k, {"a", "b"}) for k in range(500)}
    for k, rid in before.items():
        if rid != "c":
            assert after[k] == rid


def test_ring_excludes_ineligible():
    ring = _Ring()
    ring.rebuild(["a", "b"])
    assert ring.pick(7, {"b"}) == "b"
    assert ring.pick(7, set()) is None


# --- router placement + gating ----------------------------------------------

def test_router_health_gating_and_least_loaded(trained):
    t, ds, ckdir, _ = trained
    r1, r2 = _replica(ckdir), _replica(ckdir)
    router = RouterServer(port=0).start()
    try:
        router.add_replica("r1", "127.0.0.1", r1.port)
        router.add_replica("r2", "127.0.0.1", r2.port)
        body = json.dumps({"rows": _rows_of(ds, 1)}).encode()
        # nothing ready: shed with 503, never forwarded
        code, raw, fb = router.route_predict(body)
        assert code == 503 and raw is None and fb["shed"]
        assert router.no_replica == 1
        # only r2 ready: all traffic lands there
        router.set_ready("r2", True)
        for _ in range(5):
            code, raw, _ = router.route_predict(body)
            assert code == 200 and raw is not None
        handles = {h.rid: h for h in router.replicas()}
        assert handles["r1"].forwarded == 0
        assert handles["r2"].forwarded == 5
        # both ready: both take traffic (least-loaded spreads at equal
        # load via the hash fallback over distinct bodies)
        router.set_ready("r1", True)
        rows = _rows_of(ds, 16)
        for i in range(32):
            b = json.dumps({"rows": [rows[i % 16]]}).encode()
            code, _, _ = router.route_predict(b)
            assert code == 200
        assert handles["r1"].forwarded > 0
    finally:
        router.stop()
        r1.stop()
        r2.stop()


def test_router_hash_policy_affinity(trained):
    t, ds, ckdir, _ = trained
    r1, r2 = _replica(ckdir), _replica(ckdir)
    router = RouterServer(port=0, policy="hash").start()
    try:
        router.add_replica("r1", "127.0.0.1", r1.port, ready=True)
        router.add_replica("r2", "127.0.0.1", r2.port, ready=True)
        rows = _rows_of(ds, 4)
        # strict affinity: one body always routes to one replica
        for row in rows:
            body = json.dumps({"rows": [row]}).encode()
            first = {h.rid: h.forwarded for h in router.replicas()}
            for _ in range(4):
                assert router.route_predict(body)[0] == 200
            moved = [rid for rid, h in
                     ((h.rid, h) for h in router.replicas())
                     if h.forwarded - first[rid] not in (0, 4)]
            assert not moved, moved
    finally:
        router.stop()
        r1.stop()
        r2.stop()


def test_router_retries_on_dead_replica_and_relays(trained):
    """The zero-failed-requests property: a replica dying mid-traffic is
    retried transparently on a survivor; the response relays the
    SURVIVOR's scores verbatim."""
    from hivemall_tpu.io.sparse import SparseDataset
    t, ds, ckdir, _ = trained
    live, dead = _replica(ckdir), _replica(ckdir)
    router = RouterServer(port=0).start()
    try:
        router.add_replica("live", "127.0.0.1", live.port, ready=True)
        dead_port = dead.port
        router.add_replica("dead", "127.0.0.1", dead_port, ready=True)
        dead.stop()                       # replica gone; handle still ready
        # DISTINCT bodies: the least-loaded tie-break is consistent-hash,
        # so varied keys guarantee the dead replica gets picked at least
        # once before its first failure gates it out
        rows = _rows_of(ds, 12)
        parsed = [t._parse_row(r) for r in rows]
        ref = t.predict_proba(SparseDataset.from_rows(parsed, [1.0] * 12))
        ok = 0
        for i in range(12):
            body = json.dumps({"rows": [rows[i]]}).encode()
            code, raw, _ = router.route_predict(body)
            assert code == 200, (code, i)    # never a client-visible error
            payload = raw.split(b"\r\n\r\n", 1)[1]
            got = np.float32(json.loads(payload)["scores"][0])
            assert got == ref[i]
            ok += 1
        assert ok == 12
        handles = {h.rid: h for h in router.replicas()}
        assert not handles["dead"].ready     # gated on first failure
        assert handles["dead"].transport_errors >= 1
        assert router.retries >= 1
    finally:
        router.stop()
        live.stop()


def test_router_http_surface_and_fleet_snapshot(trained):
    t, ds, ckdir, _ = trained
    rep = _replica(ckdir)
    router = RouterServer(port=0).start()
    base = f"http://127.0.0.1:{router.port}"
    try:
        # no replica yet: router healthz gates (external LB semantics)
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/healthz", timeout=10)
        assert ei.value.code == 503
        router.add_replica("r0", "127.0.0.1", rep.port, ready=True)
        hz = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=10).read())
        assert hz["status"] == "ok" and hz["ready_replicas"] == 1
        # predict over the router's HTTP front door (verbatim relay)
        rows = _rows_of(ds, 2)
        out = _post(base + "/predict", {"rows": rows})
        assert out["n"] == 2 and out["model_step"] == t._t
        # aggregated snapshot: per-replica serve sections + aggregate
        snap = json.loads(urllib.request.urlopen(
            base + "/snapshot", timeout=10).read())
        fl = snap["fleet"]
        assert "r0" in fl["replicas"]
        assert fl["replicas"]["r0"]["model_step"] == t._t
        assert fl["aggregate"]["requests"] >= 1
        assert fl["aggregate"]["model_step_min"] == t._t
        assert fl["router"]["routed"] >= 1
        prom = urllib.request.urlopen(
            base + "/metrics", timeout=10).read().decode()
        assert "hivemall_tpu_fleet_aggregate_requests" in prom
        assert "hivemall_tpu_fleet_router_routed" in prom
        # unknown path: 404, bad predict body relays the replica's 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base + "/nope", {})
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base + "/predict", {"nope": 1})
        assert ei.value.code == 400
    finally:
        router.stop()
        rep.stop()


def test_router_rejects_unknown_policy():
    with pytest.raises(ValueError, match="policy"):
        RouterServer(policy="round_robin")


# --- the real thing: worker processes (slow; smoke covers it in CI) ---------

@pytest.mark.slow
def test_fleet_processes_end_to_end(trained):
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.serve.fleet import Fleet
    t, ds, ckdir, _ = trained
    fleet = Fleet("train_classifier", OPTS, checkpoint_dir=ckdir,
                  replicas=2, health_interval=0.2, watch_interval=0.3,
                  serve_kwargs={"max_batch": 32, "max_delay_ms": 2.0})
    fleet.start(wait_ready=True, timeout=180.0)
    base = f"http://127.0.0.1:{fleet.port}"
    try:
        rows = _rows_of(ds, 5)
        parsed = [t._parse_row(r) for r in rows]
        ref = t.predict_proba(SparseDataset.from_rows(parsed, [1.0] * 5))
        out = _post(base + "/predict", {"rows": rows})
        assert np.array_equal(np.asarray(out["scores"], np.float32), ref)
        # rolling reload via the router's admin /reload
        t.fit(ds)
        p2 = os.path.join(ckdir, f"{t.NAME}-step{t._t:010d}.npz")
        t.save_bundle(p2)
        rr = _post(base + "/reload", {"path": p2}, timeout=120.0)
        assert rr["reloaded"] and rr["fleet_step"] == t._t
        steps = {r.model_step for r in fleet.manager.replicas()}
        assert steps == {t._t}
    finally:
        fleet.stop()

# --- fleet obs under replica failure + trace merge ---------------------------

def _dead_port():
    """A loopback port with nothing listening."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_fleet_obs_survives_dead_and_wedged_replica(trained):
    """Satellite: /snapshot and /metrics with one replica DEAD (connection
    refused) and one WEDGED mid-scrape (accepts, never responds) — the
    survivor's section is present, the broken ones are flagged with
    errors, and the 2s one-shot obs fetch bounds the whole scrape (no
    stall for the 60s forward timeout)."""
    import socket
    import time as _time
    _, ds, ckdir, _ = trained
    live = _replica(ckdir)
    wedge = socket.create_server(("127.0.0.1", 0))   # accepts, never reads
    router = RouterServer(port=0).start()
    base = f"http://127.0.0.1:{router.port}"
    try:
        router.add_replica("live", "127.0.0.1", live.port, ready=True)
        router.add_replica("dead", "127.0.0.1", _dead_port(), ready=True)
        router.add_replica("wedged", "127.0.0.1",
                           wedge.getsockname()[1], ready=True)
        t0 = _time.monotonic()
        snap = json.loads(urllib.request.urlopen(
            base + "/snapshot", timeout=30).read())
        dt = _time.monotonic() - t0
        assert dt < 10.0                 # 2s one-shot x broken replicas,
        per = snap["fleet"]["replicas"]  # never the 60s forward timeout
        assert set(per) == {"live", "dead", "wedged"}
        assert "model_step" in per["live"]           # survivor intact
        assert "error" in per["dead"]                # dead flagged
        assert "error" in per["wedged"]              # wedged flagged
        assert "router" in per["dead"]               # handle stats still on
        # /metrics flattens the same without stalling
        t0 = _time.monotonic()
        prom = urllib.request.urlopen(
            base + "/metrics", timeout=30).read().decode()
        assert _time.monotonic() - t0 < 10.0
        assert "hivemall_tpu_fleet_replicas_live_model_step" in prom
        assert "hivemall_tpu_fleet_router_replicas 3" in prom
    finally:
        router.stop()
        live.stop()
        wedge.close()


def test_router_trace_merge_and_hop_injection(trained):
    """The router's /trace merges its own tagged spans with the
    replica's; the relayed response stacks x-hivemall-hop-router on the
    replica's breakdown with relay + replica total == router total."""
    from hivemall_tpu.obs.trace import get_tracer
    from hivemall_tpu.serve.http import KeepAliveClient
    _, ds, ckdir, _ = trained
    rep = _replica(ckdir)
    router = RouterServer(port=0, trace_sample=1.0).start()
    tracer = get_tracer()
    tracer.reset()
    tracer.enable()
    try:
        router.add_replica("r0", "127.0.0.1", rep.port, ready=True)
        cli = KeepAliveClient("127.0.0.1", router.port)
        rows = _rows_of(ds, 1)
        code, _ = cli.post_json("/predict", {"rows": rows},
                                headers={"x-hivemall-trace": "mrk-1"})
        assert code == 200
        hdrs = {k.lower(): v for k, v in cli.last_headers.items()}
        assert hdrs["x-hivemall-trace"] == "mrk-1"
        rhop = dict(kv.split("=")
                    for kv in hdrs["x-hivemall-hop-router"].split(","))
        hop = dict(kv.split("=")
                   for kv in hdrs["x-hivemall-hop"].split(","))
        assert float(rhop["relay"]) + float(hop["total"]) == \
            pytest.approx(float(rhop["total"]), abs=0.02)
        # sampling path: with trace_sample=1.0 an untraced request gets
        # a minted id echoed back
        code, _ = cli.post_json("/predict", {"rows": rows})
        hdrs = {k.lower(): v for k, v in cli.last_headers.items()}
        minted = hdrs.get("x-hivemall-trace")
        assert minted and router.traced >= 2
        # merged /trace: router.forward + the replica's serve spans all
        # carry the explicit id (same process here, distinct in a real
        # fleet — the fleet smoke pins the 2-pid case)
        trace = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/trace", timeout=10).read())
        tagged = {e["name"] for e in trace["traceEvents"]
                  if "mrk-1" in str((e.get("args") or {}).get("trace"))}
        assert "router.forward" in tagged
        assert "serve.predict" in tagged
        cli.close()
    finally:
        tracer.disable()
        tracer.reset()
        router.stop()
        rep.stop()


def test_router_slo_endpoint_wired_by_fleet_engine(trained):
    """RouterServer serves /slo off an attached SloEngine (404 without
    one) — the Fleet wires a shared engine into router + manager."""
    from hivemall_tpu.obs.slo import SloEngine
    import urllib.error
    router = RouterServer(port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{router.port}/slo", timeout=10)
        assert ei.value.code == 404
    finally:
        router.stop()
    eng = SloEngine(p99_ms=42.0)
    router = RouterServer(port=0, slo=eng).start()
    try:
        out = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/slo", timeout=10).read())
        assert out["configured"] and out["targets"]["p99_ms"] == 42.0
    finally:
        router.stop()
