"""Catalog conformance (SURVEY.md §5 rebuild plan item 4): every registered
function resolves to a callable and its option grammar parses."""

from hivemall_tpu.catalog import all_functions, define_all, help_for, lookup
from hivemall_tpu.utils.options import HelpRequested


def test_all_entries_resolve():
    funcs = all_functions()
    assert len(funcs) >= 3
    for name, e in funcs.items():
        obj = e.resolve()
        assert callable(obj) or isinstance(obj, type), name
        assert e.kind in ("UDF", "UDAF", "UDTF"), name


def test_option_grammars_parse():
    for name, e in all_functions().items():
        if e.options is not None:
            ns = e.options.parse(None)
            assert isinstance(ns, dict)
            try:
                e.options.parse("-help")
                assert False, f"{name}: -help did not raise"
            except HelpRequested as h:
                assert name in h.usage


def test_define_all_renders():
    ddl = define_all()
    assert "hivemall_version" in ddl
    assert "CREATE FUNCTION" in ddl


def test_lookup_and_help():
    e = lookup("mhash")
    assert e.reference.startswith("hivemall.")
    assert "mhash" in help_for("mhash")


def test_functions_manifest_in_sync():
    """FUNCTIONS.md is generated from the registry and must list every
    function (regenerate: python -m hivemall_tpu.catalog.manifest)."""
    import os
    from hivemall_tpu.catalog.manifest import render_markdown
    path = os.path.join(os.path.dirname(__file__), "..", "FUNCTIONS.md")
    assert open(path, encoding="utf-8").read() == render_markdown(), \
        "FUNCTIONS.md is stale — regenerate with " \
        "`python -m hivemall_tpu.catalog.manifest > FUNCTIONS.md`"


def test_define_all_spark_and_td():
    from hivemall_tpu.catalog.registry import define_all_spark, define_udfs_td
    spark = define_all_spark()
    assert "CREATE TEMPORARY FUNCTION train_classifier" in spark
    assert "cosine_sim" in spark          # aliases registered too
    td = define_udfs_td()
    assert "CREATE FUNCTION train_ffm" in td
    assert "CREATE FUNCTION auc" in td
    # curated subset: low-level tools stay out
    assert "map_tail_n" not in td
    assert len(td.splitlines()) < len(spark.splitlines())
