"""Online top-k retrieval plane (ISSUE 20): knn/ann two-stage ANN,
serve/retrieve.RetrievalEngine, the HMR1 response frame, the /retrieve
route on both serving planes, and the promotion gate's recall guardrail.

The seconds-scale concurrent/hot-reload acceptance surface lives in the
run_tests.sh smoke (``python -m hivemall_tpu.serve.retrieve_smoke``
under tsan+leaktrack on both planes); these tests pin the semantics at
suite-friendly shapes."""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from hivemall_tpu.frame.tools import each_top_k
from hivemall_tpu.knn.ann import (SrpIndex, exact_top_ids, mips_augment,
                                  mips_query, recall_at_k)
from hivemall_tpu.serve.retrieve import (KIND_ITEM_NEIGHBORS,
                                         KIND_USER_ITEMS, RetrievalEngine)

OPTS = "-factors 4 -users 8 -items 16 -mini_batch 64 -iters 1"
N_USERS, N_ITEMS = 8, 16


def _train_mf(ckdir, seed=7, epochs=2):
    from hivemall_tpu.models.mf import MFTrainer
    t = MFTrainer(OPTS)
    rng = np.random.default_rng(seed)
    t.fit(rng.integers(0, N_USERS, 512), rng.integers(0, N_ITEMS, 512),
          rng.normal(3.0, 1.0, 512).astype(np.float32), epochs=epochs)
    os.makedirs(ckdir, exist_ok=True)
    path = os.path.join(ckdir, f"train_mf_sgd-step{int(t._t):010d}.npz")
    t.save_bundle(path)
    return t, path


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    ck = str(tmp_path_factory.mktemp("retrieve_ck"))
    t, path = _train_mf(ck)
    return {"trainer": t, "bundle": path, "ckdir": ck}


def _engine(trained, **kw):
    kw.setdefault("rescore", "numpy")
    return RetrievalEngine("train_mf_sgd", OPTS,
                           bundle=trained["bundle"], **kw)


def _oracle_ids(eng, kind, qid, k):
    s = eng.exact_scores(kind, qid)
    return [int(v) for _rank, _s, v in
            each_top_k(k, [qid] * len(s), [float(x) for x in s],
                       list(range(len(s))))]


# --- knn/ann primitives ------------------------------------------------------

def test_exact_top_ids_matches_each_top_k():
    """exact_top_ids == the reference UDTF's ranking, including the tie
    rule (descending score, ties by arrival order)."""
    rng = np.random.default_rng(1)
    s = np.round(rng.standard_normal(200), 1).astype(np.float32)  # ties
    for k in (1, 5, 17, 200):
        want = [int(v) for _r, _s, v in
                each_top_k(k, [0] * len(s), [float(x) for x in s],
                           list(range(len(s))))]
        assert exact_top_ids(s, k).tolist() == want, k
    assert exact_top_ids(s, 0).tolist() == []


def test_mips_reduction_preserves_dot_order():
    """Neyshabur–Srebro: cosine order in the augmented space == inner
    product (+bias) order in the raw space, and every augmented row has
    norm M."""
    rng = np.random.default_rng(2)
    Q = rng.standard_normal((64, 6)).astype(np.float32) \
        * rng.uniform(0.2, 3.0, (64, 1)).astype(np.float32)  # mixed norms
    bi = rng.standard_normal(64).astype(np.float32)
    for bias in (None, bi):
        aug, M = mips_augment(Q, bias)
        assert aug.shape == (64, Q.shape[1] + (2 if bias is not None
                                               else 1))
        norms = np.sqrt((aug * aug).sum(-1))
        assert np.allclose(norms, M, rtol=1e-5)
        for _ in range(5):
            p = rng.standard_normal(6).astype(np.float32)
            dots = Q @ p + (bias if bias is not None else 0.0)
            qa = mips_query(p, has_bias=bias is not None)
            # equal norms => cosine order == augmented-dot order; the
            # augmented dot IS the raw dot (+bias): fill slot is 0
            assert np.allclose(aug @ qa, dots, atol=1e-4)
            assert exact_top_ids(aug @ qa, 10).tolist() \
                == exact_top_ids(dots, 10).tolist()


def test_srp_index_clamp_determinism_and_stats():
    rng = np.random.default_rng(3)
    V = rng.standard_normal((200, 8)).astype(np.float32)
    idx = SrpIndex(V, n_tables=6, n_bits=10)
    # catalog clamp: 2^b ~ N/4 (200 rows -> 5 bits), never raised
    assert idx.n_bits == 5
    assert SrpIndex(V[:3], n_bits=10).n_bits == 2
    assert SrpIndex(V, n_bits=3).n_bits == 3
    with pytest.raises(ValueError):
        SrpIndex(V, n_bits=0)
    with pytest.raises(ValueError):
        SrpIndex(V[0])
    st = idx.stats()
    assert st["rows"] == 200 and st["tables"] == 6 and st["bits"] == 5
    assert st["buckets"] > 0 and st["max_bucket"] >= st["mean_bucket"] > 0
    # same seed -> identical candidate sets; ascending unique ids; and
    # every probe finds at least its own bucket-mates
    twin = SrpIndex(V, n_tables=6, n_bits=10)
    for i in (0, 7, 199):
        c = idx.candidates(V[i])
        assert np.array_equal(c, twin.candidates(V[i]))
        assert np.array_equal(c, np.unique(c))
        assert i in c


def test_recall_at_k():
    assert recall_at_k([1, 2, 3], [1, 2, 3]) == 1.0
    assert recall_at_k([1, 9, 8], [1, 2, 3]) == pytest.approx(1 / 3)
    assert recall_at_k([], [1, 2]) == 0.0
    assert recall_at_k([1], []) == 1.0          # nothing to find
    assert recall_at_k([1, 2, 9], [1, 9, 5], k=2) == 0.5


# --- RetrievalEngine ---------------------------------------------------------

def test_engine_exact_tier_matches_each_top_k_oracle(trained):
    """Both query kinds through the plane surface
    (retrieve_rows_versioned) bit-match the each_top_k oracle replayed
    over exact_scores; padding is -1 past each query's k."""
    eng = _engine(trained, max_k=20, k_default=5)
    try:
        rows = [eng.parse_query({"user": 3}),
                eng.parse_query({"user": 0, "k": 7}),
                eng.parse_query({"item": 2, "k": 3})]
        packed, step = eng.retrieve_rows_versioned(rows)
        assert packed.shape == (3, 20, 2)
        assert step == int(trained["trainer"]._t)
        for r, (kind, qid, k, _tier) in enumerate(rows):
            ids = packed[r, :, 0]
            got = ids[ids >= 0].astype(int).tolist()
            assert got == _oracle_ids(eng, kind, qid, k), (r, kind, qid)
            assert (ids[k:] == -1).all()
            s = eng.exact_scores(kind, qid)
            assert np.allclose(packed[r, :len(got), 1], s[got], atol=1e-6)
        # item neighbors never include the probe item itself
        nb = _oracle_ids(eng, KIND_ITEM_NEIGHBORS, 2, N_ITEMS - 1)
        assert 2 not in nb and len(nb) == N_ITEMS - 1
        assert eng.queries_user == 2 and eng.queries_item == 1
    finally:
        eng.close()


def test_engine_lsh_tier_recall_and_fallback_counters(trained):
    """At a 16-item catalog the clamped index keeps the candidate union
    dense: the LSH tier's recall vs the exact tier stays high and empty
    unions fall back to exact (counted, never failed)."""
    eng = _engine(trained)
    try:
        recs = []
        for u in range(N_USERS):
            packed, _ = eng.retrieve_rows_versioned(
                [eng.parse_query({"user": u, "k": 5, "tier": "lsh"})])
            ids = packed[0, :, 0]
            got = ids[ids >= 0].astype(int).tolist()
            recs.append(recall_at_k(got, _oracle_ids(
                eng, KIND_USER_ITEMS, u, 5)))
        assert float(np.mean(recs)) >= 0.9, recs
        assert eng.queries_lsh == N_USERS and eng.queries_exact \
            == eng.empty_candidates
    finally:
        eng.close()


def test_engine_parse_query_validation(trained):
    eng = _engine(trained, max_k=10)
    try:
        for bad in ("nope", 7, {}, {"k": 3}, {"user": -1},
                    {"user": 0, "k": 0}, {"user": 0, "k": 11},
                    {"user": 0, "tier": "annoy"}, {"item": "x"}):
            with pytest.raises(ValueError):
                eng.parse_query(bad)
        assert eng.parse_query({"user": 2}) == (KIND_USER_ITEMS, 2,
                                                eng.k_default, 0)
        assert eng.parse_query({"item": 1, "k": 4, "tier": "lsh"}) \
            == (KIND_ITEM_NEIGHBORS, 1, 4, 1)
    finally:
        eng.close()


def test_engine_kernel_rescore_matches_numpy(trained):
    """The jitted kernel dot backend ranks identically to the numpy
    arena twin (same ids; scores to f32 tolerance)."""
    a = _engine(trained, rescore="numpy")
    b = _engine(trained, rescore="kernel")
    try:
        assert b._model.backend == "kernel"
        for q in ({"user": 1, "k": 6}, {"user": 5, "k": 6},
                  {"item": 3, "k": 6}):
            pa, _ = a.retrieve_rows_versioned([a.parse_query(q)])
            pb, _ = b.retrieve_rows_versioned([b.parse_query(q)])
            assert pa[0, :, 0].astype(int).tolist() \
                == pb[0, :, 0].astype(int).tolist(), q
            assert np.allclose(pa[0, :, 1], pb[0, :, 1],
                               rtol=1e-5, atol=1e-5), q
    finally:
        a.close()
        b.close()


def test_engine_int8_scores_within_factor_bound(trained):
    """The int8 tier's exact scores stay inside the arena's published
    per-pair dot-product error bound vs the f32 tier — the ranking can
    only reorder items whose f32 gap is below the summed bounds."""
    from hivemall_tpu.io.weight_arena import factor_score_error_bound
    f32 = _engine(trained, precision="f32")
    i8 = _engine(trained, precision="int8")
    try:
        items = np.arange(N_ITEMS)
        for u in range(N_USERS):
            ref = f32.exact_scores(KIND_USER_ITEMS, u)
            got = i8.exact_scores(KIND_USER_ITEMS, u)
            bound = factor_score_error_bound(
                i8._model.arena, "int8", np.int64(u), items)
            assert (np.abs(got - ref) <= bound + 1e-5).all(), u
        assert (factor_score_error_bound(
            f32._model.arena, "f32", np.int64(0), items) == 0).all()
    finally:
        f32.close()
        i8.close()


def test_engine_follows_promoted_pointer(tmp_path):
    """follow="promoted": poll() swaps on pointer flips (even to an
    OLDER step) and ignores newer unpromoted bundles."""
    from hivemall_tpu.io.checkpoint import promote_bundle
    ck = str(tmp_path)
    t1, p1 = _train_mf(ck, epochs=2)
    promote_bundle(ck, p1)
    eng = RetrievalEngine("train_mf_sgd", OPTS, checkpoint_dir=ck,
                          follow="promoted", rescore="numpy")
    try:
        s1 = eng.model_step
        assert s1 == int(t1._t)
        t2, p2 = _train_mf(ck, epochs=4)           # newer, NOT promoted
        eng.poll()
        assert eng.model_step == s1
        promote_bundle(ck, p2)
        eng.poll()
        assert eng.model_step == int(t2._t) > s1
        assert eng.reloads == 1
        promote_bundle(ck, p1)                     # rollback: older step
        eng.poll()
        assert eng.model_step == s1 and eng.reloads == 2
    finally:
        eng.close()


def test_engine_labels_vocab(trained):
    """labels(): None without a vocab (MF), id->word translation with
    one (the word2vec arena header's vocab list)."""
    eng = _engine(trained)
    try:
        assert eng.labels([0, 1]) is None
        eng._model.vocab = ["a", "b", "c"]
        assert eng.labels([2, 0, 99, -1]) == ["c", "a", None, None]
    finally:
        eng.close()


# --- HMR1 response frame -----------------------------------------------------

def test_response_frame_roundtrip():
    from hivemall_tpu.serve.wire import (decode_response_frame,
                                         encode_response_frame)
    scores = [[0.5, -1.25, 3.0], [], [7.0]]
    ids = [[4, 0, 9], [], [1]]
    for step in (None, 0, 1 << 40):
        for use_ids in (False, True):
            body = encode_response_frame(
                scores, ids if use_ids else None, model_step=step)
            s2, i2, st2 = decode_response_frame(body)
            assert [r.tolist() for r in s2] \
                == [list(map(float, r)) for r in scores]
            if use_ids:
                assert [r.tolist() for r in i2] == ids
            else:
                assert i2 is None
            assert st2 == step


def test_response_frame_malformed():
    from hivemall_tpu.serve.wire import (WireError, decode_response_frame,
                                         encode_response_frame)
    good = encode_response_frame([[1.0, 2.0]], [[3, 4]], model_step=5)
    for bad in (b"", b"HMF1" + good[4:],          # wrong magic
                good[:-3],                        # truncated payload
                good + b"\x00",                   # trailing bytes
                bytes([good[0], good[1], good[2], good[3], 0xFF])
                + good[5:]):                      # unknown flags
        with pytest.raises(WireError):
            decode_response_frame(bad)
    with pytest.raises(WireError):
        encode_response_frame([[1.0]], [[1, 2]])  # ids/scores mismatch
    with pytest.raises(WireError):
        encode_response_frame([[1.0], [2.0]], [[1]])


# --- /retrieve on both serving planes ---------------------------------------

def _post_raw(url, obj, headers=None, timeout=15.0):
    req = urllib.request.Request(
        url, json.dumps(obj).encode(),
        {"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


@pytest.mark.parametrize("plane", ["threaded", "evloop"])
def test_http_retrieve_route(trained, plane):
    """Retrieval-only serving on each plane: /retrieve 200 matches the
    oracle, Accept negotiation returns an HMR1 frame with the model
    step, malformed queries 400 with JSON errors, /predict 404s, and
    the obs snapshot carries the retrieval section."""
    from hivemall_tpu.serve.wire import (CONTENT_TYPE_FRAME,
                                         decode_response_frame)
    if plane == "evloop":
        from hivemall_tpu.serve.evloop import \
            EvloopPredictServer as ServerCls
    else:
        from hivemall_tpu.serve.http import PredictServer as ServerCls
    eng = _engine(trained, k_default=5)
    srv = ServerCls(None, port=0, max_delay_ms=1.0, retrieval=eng).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        code, ctype, body = _post_raw(
            base + "/retrieve",
            {"queries": [{"user": 1, "k": 4}, {"item": 0, "k": 2}]})
        assert code == 200 and "json" in ctype
        r = json.loads(body)
        assert r["results"][0]["ids"] \
            == _oracle_ids(eng, KIND_USER_ITEMS, 1, 4)
        assert r["results"][1]["ids"] \
            == _oracle_ids(eng, KIND_ITEM_NEIGHBORS, 0, 2)
        assert r["model_step"] == eng.model_step

        # bare single-query shorthand + frame negotiation
        code, ctype, body = _post_raw(
            base + "/retrieve", {"user": 1, "k": 4},
            headers={"Accept": CONTENT_TYPE_FRAME})
        assert code == 200 and CONTENT_TYPE_FRAME in ctype
        srows, irows, step = decode_response_frame(body)
        assert irows[0].tolist() \
            == _oracle_ids(eng, KIND_USER_ITEMS, 1, 4)
        assert np.allclose(
            srows[0], eng.exact_scores(KIND_USER_ITEMS, 1)[irows[0]],
            atol=1e-6)
        assert step == eng.model_step

        for bad in ({"k": 3}, {"user": -2}, {"user": 0, "k": 0},
                    {"queries": "x"}, {"user": 0, "tier": "faiss"}):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post_raw(base + "/retrieve", bad)
            assert ei.value.code == 400, bad
            assert "error" in json.loads(ei.value.read()), bad
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_raw(base + "/predict", {"features": ["1:1"]})
        assert ei.value.code == 404

        with urllib.request.urlopen(base + "/snapshot", timeout=15) as rr:
            snap = json.loads(rr.read())
        assert snap["retrieval"]["queries_user"] >= 2
        assert snap["retrieval"]["model_step"] == eng.model_step
    finally:
        srv.stop()


# --- promotion gate recall guardrail ----------------------------------------

def test_promotion_gate_recall_guardrail(tmp_path):
    """Factor candidates are recall-checked: a healthy small-catalog MF
    bundle passes end-to-end (recall ~1 under the clamped index) and a
    geometry whose LSH buckets collapse fails with a recall reason."""
    from hivemall_tpu.serve.promote import PromotionGate
    _t, bundle = _train_mf(str(tmp_path))
    gate = PromotionGate("train_mf_sgd", OPTS)
    report = gate.evaluate(bundle)
    assert report["verdict"] == "pass", report
    assert report["checks"]["recall_at_k"] >= 0.95
    assert report["checks"]["recall_k"] == 10

    class _Collapsed:
        """Big iid-noise catalog: no angular structure, 10-bit codes
        scatter the true top-k across buckets and recall craters."""

        def serving_tables(self):
            rng = np.random.default_rng(13)
            return ({"family": "factor", "item_bias": False},
                    {"P": rng.standard_normal((64, 16)).astype(np.float32),
                     "Q": rng.standard_normal((4096, 16)
                                              ).astype(np.float32)})

    checks, reasons = {}, []
    gate._check_retrieval(_Collapsed(), checks, reasons)
    assert checks["recall_at_k"] < 0.95
    assert any("recall@10" in r for r in reasons), reasons

    class _NonFactor:
        def serving_tables(self):
            return {"family": "linear"}, {}

    checks, reasons = {}, []
    gate._check_retrieval(_NonFactor(), checks, reasons)
    assert not checks and not reasons
