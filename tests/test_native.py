"""C++ native path: builds via g++, bit-exact parity with the Python
implementations (murmur3 + LIBSVM parse)."""

import numpy as np
import pytest

from hivemall_tpu.utils import native
from hivemall_tpu.utils.hashing import mhash_batch, murmurhash3_batch


@pytest.fixture(scope="module")
def lib():
    lb = native.get_lib()
    if lb is None:
        pytest.skip("native lib unavailable (no g++?)")
    return lb


def test_mmh3_parity(lib):
    keys = ["", "a", "hello", "field:12:0.5", "日本語テキスト", "x" * 100]
    got = native.mmh3_batch_native(keys)
    want = murmurhash3_batch(keys, use_native=False)
    np.testing.assert_array_equal(got, want)


def test_mmh3_seed_parity(lib):
    keys = [f"k{i}" for i in range(100)]
    got = native.mmh3_batch_native(keys, seed=7)
    want = murmurhash3_batch(keys, seed=7, use_native=False)
    np.testing.assert_array_equal(got, want)


def test_mhash_parity(lib):
    keys = [f"cat#{i}" for i in range(200)]
    got = native.mhash_batch_native(keys, 1 << 20)
    want = mhash_batch(keys, 1 << 20, use_native=False)
    np.testing.assert_array_equal(got, want)
    assert got.min() >= 1 and got.max() <= 1 << 20


def test_libsvm_parse_parity(lib, tmp_path):
    p = str(tmp_path / "t.libsvm")
    with open(p, "w") as f:
        f.write("# comment line\n")
        f.write("+1 1:0.5 3:1.25 7:2\n")
        f.write("-1 2:1 3:0.25\n")
        f.write("\n")
        f.write("0.5 5 9:1e-3\n")          # bare index -> value 1.0
    ds = native.parse_libsvm_native(p)
    assert ds is not None
    # compare against the pure-python reader
    import hivemall_tpu.io.libsvm as L
    import os
    os.environ["HIVEMALL_TPU_NO_NATIVE"] = "1"
    try:
        native._LIB = None
        native._TRIED = False
        ds_py = L.read_libsvm(p)
    finally:
        del os.environ["HIVEMALL_TPU_NO_NATIVE"]
        native._TRIED = False
    np.testing.assert_array_equal(ds.indices, ds_py.indices)
    np.testing.assert_array_equal(ds.indptr, ds_py.indptr)
    np.testing.assert_allclose(ds.values, ds_py.values)
    np.testing.assert_allclose(ds.labels, ds_py.labels)
    assert ds.labels.tolist() == [1.0, -1.0, 0.5]
    assert ds.row(2)[0].tolist() == [5, 9]
    assert ds.row(2)[1].tolist() == pytest.approx([1.0, 1e-3])


def test_native_parser_speed(lib, tmp_path):
    """The native parser should beat the Python one comfortably."""
    import time
    from hivemall_tpu.io.libsvm import synthetic_classification, write_libsvm
    ds, _ = synthetic_classification(20000, 1000, density=0.02, seed=1)
    p = str(tmp_path / "big.libsvm")
    write_libsvm(ds, p)
    t0 = time.perf_counter()
    a = native.parse_libsvm_native(p)
    t_native = time.perf_counter() - t0
    import os
    os.environ["HIVEMALL_TPU_NO_NATIVE"] = "1"
    try:
        native._LIB = None
        native._TRIED = False
        import hivemall_tpu.io.libsvm as L
        t0 = time.perf_counter()
        b = L.read_libsvm(p)
        t_py = time.perf_counter() - t0
    finally:
        del os.environ["HIVEMALL_TPU_NO_NATIVE"]
        native._TRIED = False
    np.testing.assert_array_equal(a.indices, b.indices)
    assert t_native < t_py, (t_native, t_py)


def test_canonicalize_native_matches_numpy():
    """The C++ canonicalizer is a semantic twin of the numpy path."""
    import numpy as np
    from hivemall_tpu.utils.native import canonicalize_fieldmajor_native
    res0 = canonicalize_fieldmajor_native(
        np.zeros((1, 1), np.int32), np.zeros((1, 1), np.float32),
        np.zeros((1, 1), np.int32), 2, 4)
    if res0 is NotImplemented:
        import pytest
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(9)
    F = 5
    for _ in range(10):
        B, L = 6, 11
        idx = rng.integers(1, 999, (B, L)).astype(np.int32)
        val = rng.uniform(0.1, 1, (B, L)).astype(np.float32)
        fld = rng.integers(-3, 2 * F, (B, L)).astype(np.int32)  # incl. oor
        dead = rng.uniform(size=(B, L)) < 0.4
        val[dead] = 0
        # numpy reference (bypass the native fast path)
        import hivemall_tpu.io.sparse as sp
        import hivemall_tpu.utils.native as nat
        native = canonicalize_fieldmajor_native(idx, val, fld, F, 8)
        saved = nat.canonicalize_fieldmajor_native
        try:
            nat.canonicalize_fieldmajor_native = \
                lambda *a, **k: NotImplemented
            ref = sp.canonicalize_fieldmajor(idx, val, fld, F, max_m=8)
        finally:
            nat.canonicalize_fieldmajor_native = saved
        assert native is not None and ref is not None
        np.testing.assert_array_equal(native[0], ref[0])
        np.testing.assert_array_equal(native[1], ref[1])
        assert native[2] == ref[2]
    # overflow parity
    idx = np.ones((2, 6), np.int32)
    val = np.ones((2, 6), np.float32)
    fld = np.zeros((2, 6), np.int32)
    assert canonicalize_fieldmajor_native(idx, val, fld, F, 4) is None


def test_bin_columns_native_matches_searchsorted_incl_nan():
    """quantize_bins' C++ binner must be BIT-identical to the numpy
    fallback — including NaN inputs (np.searchsorted sorts NaN last)."""
    import numpy as np
    from hivemall_tpu.utils.native import bin_columns_native

    rng = np.random.default_rng(0)
    X = rng.normal(0, 1, (4000, 5)).astype(np.float32)
    X[::37, 2] = np.nan
    X[:, 0] = 0.0                              # constant column
    edges = np.sort(rng.normal(0, 1, (5, 15)).astype(np.float32), 1)
    edges[:, 12:] = np.inf                     # padded tails
    ne = np.full(5, 15, np.int32)
    got = bin_columns_native(X, edges, ne)
    if got is NotImplemented:
        import pytest
        pytest.skip("native lib unavailable")
    want = np.empty_like(got)
    for f in range(5):
        want[:, f] = np.searchsorted(edges[f], X[:, f],
                                     side="left").astype(np.uint8)
    np.testing.assert_array_equal(got, want)
