"""Disk-backed -iters replay (the NioStatefulSegment analog,
SURVEY.md §3.20): a process()-fed trainer must run -iters 3 over more
rows than the RAM budget allows, spilling segments to disk and cleaning
them up."""
import glob
import os
import tempfile

import numpy as np

from hivemall_tpu.io.replay_segment import RowSegmentStore
from hivemall_tpu.models.classifier import PerceptronTrainer


def test_store_spills_and_replays():
    store = RowSegmentStore(budget_bytes=4096)   # tiny: forces spilling
    rng = np.random.default_rng(0)
    ref = []
    for _ in range(40):
        rows = [(rng.integers(1, 100, 5).astype(np.int32),
                 np.ones(5, np.float32)) for _ in range(8)]
        labels = [float(rng.integers(0, 2)) for _ in range(8)]
        store.append(rows, labels)
        ref += [(tuple(r[0].tolist()), y) for r, y in zip(rows, labels)]
    assert store.spilled and store.n_rows == 320
    got = []
    for rows, labels in store.epoch_rows(np.random.default_rng(1)):
        got += [(tuple(r[0].tolist()), y) for r, y in zip(rows, labels)]
    assert sorted(got) == sorted(ref)            # every row exactly once
    tmp = store._tmpdir
    assert tmp and glob.glob(os.path.join(tmp, "seg*.npz"))
    store.cleanup()
    assert not os.path.exists(tmp)


def test_process_iters3_beyond_ram_budget(monkeypatch):
    monkeypatch.setenv("HIVEMALL_TPU_REPLAY_BUDGET_MB", "0.01")  # ~10 KB
    rng = np.random.default_rng(2)
    t = PerceptronTrainer("-dims 512 -mini_batch 32 -iters 3")
    n = 600
    for _ in range(n):
        feats = [f"{i}:1.0" for i in rng.choice(np.arange(1, 512), 6,
                                                replace=False)]
        y = 1.0 if int(feats[0].split(":")[0]) % 2 else -1.0
        t.process(feats, y)
    assert t._replay.spilled                      # budget forced disk use
    rows = list(t.close())
    assert len(rows) > 1
    assert t._examples == n * 3                   # all 3 epochs ran
    assert t._replay._tmpdir is None              # cleaned up


def test_no_spill_keeps_exact_in_ram_replay():
    rng = np.random.default_rng(3)
    a = PerceptronTrainer("-dims 256 -mini_batch 16 -iters 2")
    b = PerceptronTrainer("-dims 256 -mini_batch 16 -iters 2")
    data = [([f"{i}:1.0" for i in rng.choice(np.arange(1, 256), 4,
                                             replace=False)],
             float(rng.integers(0, 2)) * 2 - 1) for _ in range(100)]
    for t in (a, b):
        for f, y in data:
            t.process(f, y)
        list(t.close())
    np.testing.assert_array_equal(np.asarray(a.w), np.asarray(b.w))
