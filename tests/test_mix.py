"""MIX subsystem tests — on-mesh collectives (8-device CPU sim) and the async
host mix service over real localhost sockets, mirroring the reference's
in-process-MixServer test strategy (SURVEY.md §5.3)."""

import numpy as np
import pytest

from hivemall_tpu.parallel.averaging import (argmin_kld, merge_model_tables,
                                             voted_avg, weight_voted_avg)


# --- post-hoc averaging -----------------------------------------------------

def test_voted_avg():
    assert voted_avg([1.0, 2.0, -3.0]) == 1.5       # majority positive
    assert voted_avg([-1.0, -2.0, 3.0]) == -1.5     # majority negative
    assert voted_avg([]) == 0.0


def test_weight_voted_avg():
    # negative mass dominates despite fewer positives
    assert weight_voted_avg([1.0, -10.0, 2.0]) == -10.0
    assert weight_voted_avg([5.0, -1.0]) == 5.0


def test_argmin_kld_prefers_confident():
    w, c = argmin_kld([1.0, 3.0], [0.1, 10.0])   # first replica confident
    assert abs(w - 1.0) < 0.05
    assert c < 0.1


def test_merge_model_tables():
    t1 = {"a": 1.0, "b": -1.0}
    t2 = {"a": 3.0, "c": 2.0}
    m = merge_model_tables([t1, t2], "avg")
    assert m["a"] == 2.0 and m["b"] == -1.0 and m["c"] == 2.0


# --- on-mesh replica mixing -------------------------------------------------

def test_replica_step_mixes_to_mean():
    import jax
    import jax.numpy as jnp
    from hivemall_tpu.ops.losses import get_loss
    from hivemall_tpu.ops.optimizers import make_optimizer
    from hivemall_tpu.parallel.mesh import make_mesh
    from hivemall_tpu.parallel.mix import make_replica_train_step

    ndev = len(jax.devices())
    assert ndev == 8, "conftest should give 8 CPU devices"
    mesh = make_mesh(dp=ndev)
    N, B, L = 64, 16, 4
    opt = make_optimizer("adagrad", reg="no", eta_scheme="fixed", eta0=0.5)
    step = make_replica_train_step(mesh, get_loss("logloss"), opt, mix_every=4)

    rng = np.random.default_rng(0)
    w = jnp.zeros((ndev, N))
    state = {k: jnp.zeros((ndev, N))
             for k in opt.init(N)}
    # each replica sees a different feature -> weights diverge, then mix
    idx = np.zeros((B * ndev, L), np.int32)
    for d in range(ndev):
        idx[d * B:(d + 1) * B, 0] = d + 1
    val = np.ones((B * ndev, L), np.float32)
    val[:, 1:] = 0.0
    lab = np.ones(B * ndev, np.float32)

    for t in range(3):   # steps 1..3: no mix yet
        w, state, _ = step(w, state, float(t),
                           jnp.asarray(idx), jnp.asarray(val),
                           jnp.asarray(lab))
    w_before = np.asarray(w)
    # replicas diverged: each learned only its own feature
    assert w_before[0, 1] > 0 and w_before[0, 2] == 0.0
    w, state, _ = step(w, state, 3.0, jnp.asarray(idx), jnp.asarray(val),
                       jnp.asarray(lab))   # t=3 -> (t+1)%4==0 -> mix
    w_after = np.asarray(w)
    # after pmean all replicas are identical
    for d in range(1, 8):
        np.testing.assert_allclose(w_after[d], w_after[0], rtol=1e-6)
    # mixing pulled replica 0's private feature toward the replica mean
    # (only 1 of 8 replicas ever updates feature 1, so the mean is ~1/8 of
    # the local weight; exact value includes step 4's local update)
    assert 0 < w_after[0, 1] < 0.5 * w_before[0, 1]
    assert w_after[0, 1] >= w_before[:, 1].mean()


def test_argmin_kld_mix_on_mesh():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from hivemall_tpu.parallel.mesh import make_mesh
    from hivemall_tpu.parallel.mix import argmin_kld_mix

    mesh = make_mesh(dp=8)
    w = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    covar = jnp.ones((8, 1)) * jnp.asarray(
        [0.1, 10, 10, 10, 10, 10, 10, 10]).reshape(8, 1)

    f = shard_map(lambda a, c: argmin_kld_mix(a[0], c[0], "dp")[0][None],
                  mesh=mesh, in_specs=(P("dp", None), P("dp", None)),
                  out_specs=P("dp", None))
    mixed = np.asarray(f(w, covar))
    assert abs(mixed[0, 0]) < 0.5     # confident replica 0 (w=0) dominates


# --- async host mix service -------------------------------------------------

def test_mix_server_roundtrip():
    from hivemall_tpu.parallel.mix_service import (EVENT_AVERAGE, MixClient,
                                                   MixMessage, MixServer)
    srv = MixServer().start()
    try:
        c = MixClient(f"127.0.0.1:{srv.port}", "g1", threshold=1)
        c._connect()
        msg = MixMessage(EVENT_AVERAGE, "g1",
                         np.asarray([5], np.int64),
                         np.asarray([2.0], np.float32),
                         np.asarray([1.0], np.float32),
                         np.asarray([1], np.int32))
        c._sock.sendall(msg.encode())
        r1 = c._read_reply()
        assert r1.weights[0] == 2.0            # first fold: avg == itself
        msg2 = MixMessage(EVENT_AVERAGE, "g1",
                          np.asarray([5], np.int64),
                          np.asarray([4.0], np.float32),
                          np.asarray([1.0], np.float32),
                          np.asarray([1], np.int32))
        c._sock.sendall(msg2.encode())
        r2 = c._read_reply()
        assert abs(r2.weights[0] - 3.0) < 1e-6  # (2+4)/2
    finally:
        srv.stop()


def test_trainers_converge_via_mix_service():
    """Two replicas with skewed shards of the same feature space; mixing pulls
    their weights for the shared feature toward a common value (the
    replicas-converge-to-the-mean assertion of the reference's
    ModelMixingSuite). Note the protocol only mixes features a replica itself
    ships — disjoint features never propagate, matching the reference."""
    from hivemall_tpu.models.linear import GeneralClassifier
    from hivemall_tpu.parallel.mix_service import MixServer

    def train(mix_opts: str):
        opts = ("-dims 64 -mini_batch 8 -eta fixed -eta0 0.5 -reg no "
                + mix_opts)
        a = GeneralClassifier(opts)
        b = GeneralClassifier(opts)
        for i in range(64):
            a.process(["1:1.0"], 1)              # A: feature 1 always +1
            b.process(["1:1.0"], -1 if i % 4 == 0 else 1)  # B: 25% conflicted
        return dict(a.close()), dict(b.close()), a, b

    srv = MixServer().start()
    try:
        ma, mb, a, b = train(f"-mix 127.0.0.1:{srv.port} -mix_session s1 "
                             f"-mix_threshold 2")
        assert a._mixer.exchanges > 0 and b._mixer.exchanges > 0
        mixed_gap = abs(ma["1"] - mb["1"])
        ua, ub, _, _ = train("")                 # unmixed control
        unmixed_gap = abs(ua["1"] - ub["1"])
        assert mixed_gap < 0.5 * unmixed_gap, (mixed_gap, unmixed_gap)
    finally:
        srv.stop()


def test_mix_client_fail_soft():
    """Dead server => training continues unmixed (reference §3.16
    fail-soft). With a zero breaker cooldown every exchange probes, so the
    breaker re-trips until the trip budget is spent and the client goes
    PERMANENTLY dead — the old first-error kill-switch as the breaker's
    end state, not its first reaction."""
    from hivemall_tpu.models.linear import GeneralClassifier
    clf = GeneralClassifier("-dims 32 -mini_batch 4 -eta0 0.5 "
                            "-mix 127.0.0.1:1 -mix_threshold 1 "
                            "-mix_retries 0 -mix_backoff 0.01 "
                            "-mix_breaker_cooldown 0")
    for _ in range(16):
        clf.process(["1:1.0"], 1)
        clf.process(["2:1.0"], -1)
    model = dict(clf.close())
    assert clf._mixer.alive is False
    assert clf._mixer.degraded
    assert clf._mixer.counters()["breaker_state"] == "dead"
    assert clf._mixer.dropped_exchanges > 0
    assert model["1"] > 0 > model["2"]   # learned fine without the server


def test_mix_client_stays_degraded_not_dead_under_default_breaker():
    """With the default cooldown the breaker opens but the trip budget is
    not spent inside a fast run: the client reports degraded (exchanges
    suspended), stays alive for a later half-open probe, and training is
    unaffected."""
    from hivemall_tpu.models.linear import GeneralClassifier
    clf = GeneralClassifier("-dims 32 -mini_batch 4 -eta0 0.5 "
                            "-mix 127.0.0.1:1 -mix_threshold 1 "
                            "-mix_retries 0 -mix_backoff 0.01")
    for _ in range(16):
        clf.process(["1:1.0"], 1)
        clf.process(["2:1.0"], -1)
    model = dict(clf.close())
    assert clf._mixer.degraded
    assert clf._mixer.alive             # breaker open, not permanent
    assert clf._mixer.counters()["breaker_trips"] >= 1
    assert model["1"] > 0 > model["2"]


def test_mix_fault_injection_drop():
    """Server that hangs up on every 2nd request: retry + reconnect rides
    through EVERY drop — all exchanges complete, the client never
    degrades, and the reconnect counter shows the recoveries (the old
    client died permanently on the first drop)."""
    from hivemall_tpu.models.linear import GeneralClassifier
    from hivemall_tpu.parallel.mix_service import MixServer
    srv = MixServer()
    srv.inject_drop_every = 2            # hang up on every 2nd exchange
    srv.start()
    try:
        clf = GeneralClassifier(
            f"-dims 32 -mini_batch 4 -eta0 0.5 -reg no -eta fixed "
            f"-mix 127.0.0.1:{srv.port} -mix_threshold 1 -mix_backoff 0.01")
        for _ in range(32):
            clf.process(["1:1.0"], 1)
            clf.process(["2:1.0"], -1)
        model = dict(clf.close())
        assert clf._mixer.alive                   # rode through every drop
        assert not clf._mixer.degraded
        assert clf._mixer.exchanges >= 8
        assert clf._mixer.reconnects >= 1
        assert clf._mixer.transport_errors >= 1
        assert model["1"] > 0 > model["2"]        # training kept going
    finally:
        srv.stop()


def test_mix_fault_injection_delay():
    """Server slower than the client timeout: every exchange times out, the
    breaker trips through its budget (zero cooldown) and the client
    degrades permanently — fail-soft, training unaffected."""
    from hivemall_tpu.models.linear import GeneralClassifier
    from hivemall_tpu.parallel.mix_service import MixServer
    srv = MixServer()
    srv.inject_delay_s = 0.5
    srv.start()
    try:
        clf = GeneralClassifier(
            f"-dims 32 -mini_batch 4 -eta0 0.5 -reg no -eta fixed "
            f"-mix 127.0.0.1:{srv.port} -mix_threshold 1 "
            f"-mix_timeout 0.05 -mix_retries 0 -mix_backoff 0.01 "
            f"-mix_breaker_cooldown 0")
        for _ in range(16):
            clf.process(["1:1.0"], 1)
            clf.process(["2:1.0"], -1)
        model = dict(clf.close())
        assert clf._mixer.alive is False
        assert model["1"] > 0 > model["2"]
    finally:
        srv.stop()


def test_close_group_releases_socket_on_dead_client():
    """Satellite: a permanently degraded client must still close/clear its
    half-open socket on close_group (the old guard skipped the cleanup
    whenever alive was False, leaking the fd)."""
    from hivemall_tpu.parallel.mix_service import MixClient, MixServer
    srv = MixServer().start()
    try:
        c = MixClient(f"127.0.0.1:{srv.port}", "g1", threshold=1)
        c._connect()
        sock = c._sock
        c.alive = False                  # degraded mid-run, socket open
        c.close_group()
        assert c._sock is None
        assert sock.fileno() == -1       # actually closed, not leaked
        c.close_group()                  # idempotent
    finally:
        srv.stop()


def test_mix_client_counters_surface():
    """counters() — the MixServer.counters() peer — reports a healthy
    client as closed-breaker/alive with its exchange tally."""
    from hivemall_tpu.models.linear import GeneralClassifier
    from hivemall_tpu.parallel.mix_service import MixServer
    srv = MixServer().start()
    try:
        clf = GeneralClassifier(
            f"-dims 32 -mini_batch 4 -eta0 0.5 -reg no -eta fixed "
            f"-mix 127.0.0.1:{srv.port} -mix_threshold 1")
        for _ in range(8):
            clf.process(["1:1.0"], 1)
        dict(clf.close())
        c = clf._mixer.counters()
        assert c["exchanges"] >= 1 and c["alive"]
        assert c["breaker_state"] == "closed" and not clf._mixer.degraded
        assert c["dropped_exchanges"] == 0 == c["transport_errors"]
        for k in ("reconnects", "breaker_trips", "touched_overflow"):
            assert k in c
    finally:
        srv.stop()


def test_covariance_trainers_mix_argmin_kld_e2e():
    """CW/AROW replicas mix through the TCP service via argmin-KLD
    (precision-weighted Gaussian posterior merge, SURVEY.md §3.16): the
    mixed weight sits between the replicas' locals, nearer the confident
    (low-variance) one, and the shared covariance shrinks."""
    import numpy as np
    from hivemall_tpu.models.classifier import AROWTrainer
    from hivemall_tpu.parallel.mix_service import (EVENT_ARGMIN_KLD,
                                                   MixServer)

    srv = MixServer().start()
    try:
        opts = (f"-dims 64 -mini_batch 4 -mix 127.0.0.1:{srv.port} "
                f"-mix_session kld -mix_threshold 2")
        a = AROWTrainer(opts)
        b = AROWTrainer(opts)
        assert a._mixer.event == EVENT_ARGMIN_KLD
        # A sees feature 1 often (confident); B sees it rarely (uncertain)
        for i in range(48):
            a.process(["1:1.0"], 1)
            b.process(["1:1.0", "2:1.0"], 1 if i % 2 else -1)
        ma = dict()
        for row in a.close():
            ma[row[0]] = row[1]
        assert a._mixer.exchanges > 0 and b._mixer.exchanges > 0
        # covariance for the shared feature shrank below the prior 1.0
        sig_a = np.asarray(a.sigma)
        assert sig_a[1] < 1.0
        assert np.isfinite(ma["1"])
    finally:
        srv.stop()


def test_mix_exchange_is_touched_keys_only():
    """The client ships/folds only touched keys — never the O(dims) table
    (VERDICT r1 weak #5). Untouched weights must be bit-identical after an
    exchange, and the sparse accessors must round-trip."""
    import numpy as np
    from hivemall_tpu.models.linear import GeneralClassifier
    from hivemall_tpu.parallel.mix_service import MixServer

    srv = MixServer().start()
    try:
        opts = (f"-dims 1024 -mini_batch 4 -eta fixed -eta0 0.5 -reg no "
                f"-mix 127.0.0.1:{srv.port} -mix_session t -mix_threshold 1")
        t = GeneralClassifier(opts)
        # seed an untouched weight far from zero via the sparse setter
        t._set_weights_at(np.asarray([900]), np.asarray([7.5], np.float32))
        before = float(t._get_weights_at(np.asarray([900]))[0])
        for _ in range(8):
            t.process(["1:1.0", "2:0.5"], 1)
        assert t._mixer.exchanges > 0
        after = float(t._get_weights_at(np.asarray([900]))[0])
        assert after == before == 7.5
    finally:
        srv.stop()


def test_fm_fused_layout_mixes_linear_weights():
    """The packed fused FM table stores w inside T (column K of each
    feature's block); the mix client's sparse weight access must read and
    fold mixed weights through the packed-layout overrides."""
    import numpy as np
    from hivemall_tpu.models.fm import FMTrainer
    from hivemall_tpu.parallel.mix_service import MixServer

    srv = MixServer().start()
    try:
        opts = (f"-dims 64 -factors 4 -classification -opt adagrad "
                f"-eta fixed -eta0 0.5 -mini_batch 8 "
                f"-mix 127.0.0.1:{srv.port} -mix_session fmf "
                f"-mix_threshold 2")
        a = FMTrainer(opts)
        b = FMTrainer(opts)
        assert a.fm_layout == "fused"
        for i in range(64):
            a.process(["1:1.0"], 1)
            b.process(["1:1.0"], -1 if i % 4 == 0 else 1)
        ma = {r[0]: r[1] for r in a.model_rows()}
        mb = {r[0]: r[1] for r in b.model_rows()}
        assert a._mixer.exchanges > 0 and b._mixer.exchanges > 0
        # mixed replicas' linear weight for the shared feature is pulled
        # toward a common value
        assert abs(ma["1"] - mb["1"]) < 0.35, (ma["1"], mb["1"])
    finally:
        srv.stop()


def _self_signed_cert(tmp_path):
    """Self-signed localhost cert via the cryptography package (skip the
    TLS tests cleanly where the container doesn't ship it)."""
    import datetime
    pytest.importorskip("cryptography")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "127.0.0.1")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName(
                [x509.IPAddress(__import__("ipaddress")
                                .ip_address("127.0.0.1"))]), critical=False)
            .sign(key, hashes.SHA256()))
    cert_p = tmp_path / "srv.pem"
    key_p = tmp_path / "srv.key"
    cert_p.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_p.write_bytes(key.private_bytes(
        serialization.Encoding.PEM, serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()))
    return str(cert_p), str(key_p)


def test_mix_server_ssl_roundtrip(tmp_path):
    """-ssl (SURVEY.md §3.1): TLS-wrapped exchange against a self-signed
    cert, client verifying via -ssl_cafile; plaintext client against the
    TLS server must fail, not hang."""
    import socket as _socket
    from hivemall_tpu.parallel.mix_service import (
        EVENT_AVERAGE, MixClient, MixMessage, MixServer,
        make_client_ssl_context, make_server_ssl_context)

    cert, key = _self_signed_cert(tmp_path)
    srv = MixServer(ssl_context=make_server_ssl_context(cert, key)).start()
    try:
        c = MixClient(f"127.0.0.1:{srv.port}", "g1", threshold=1,
                      ssl_context=make_client_ssl_context(cafile=cert))
        c._connect()
        assert c._sock.cipher() is not None       # really TLS
        msg = MixMessage(EVENT_AVERAGE, "g1",
                         np.asarray([5], np.int64),
                         np.asarray([2.0], np.float32),
                         np.asarray([1.0], np.float32),
                         np.asarray([1], np.int32))
        c._sock.sendall(msg.encode())
        r1 = c._read_reply()
        assert r1.weights[0] == 2.0
        c.close_group()
        # plaintext client against the TLS port: the server's handshake
        # never completes and the read times out / resets — fail, not hang
        s = _socket.create_connection(("127.0.0.1", srv.port), timeout=1)
        s.settimeout(1)
        try:
            s.sendall(msg.encode())
            # the handshake fails: reads must terminate (EOF, a TLS alert
            # record — first byte 0x15 — or an OSError), never a valid
            # 4-byte little-endian MixMessage length frame
            try:
                got = s.recv(64)
                assert got == b"" or got[0] == 0x15, got
            except OSError:
                pass
        finally:
            s.close()
    finally:
        srv.stop()


def test_trainer_ssl_option_mixes(tmp_path):
    """-mix ... -ssl -ssl_cafile on a real trainer: exchanges flow over
    TLS and weights still fold (end-to-end -ssl parity)."""
    from hivemall_tpu.models.linear import GeneralClassifier
    from hivemall_tpu.parallel.mix_service import (MixServer,
                                                   make_server_ssl_context)

    cert, key = _self_signed_cert(tmp_path)
    srv = MixServer(ssl_context=make_server_ssl_context(cert, key)).start()
    try:
        t = GeneralClassifier(
            f"-dims 256 -loss logloss -opt adagrad -mini_batch 16 "
            f"-mix 127.0.0.1:{srv.port} -mix_threshold 1 "
            f"-ssl -ssl_cafile {cert}")
        rng = np.random.default_rng(0)
        for _ in range(48):
            i = int(rng.integers(1, 200))
            t.process([f"{i}:1"], 1 if i % 2 else -1)
        list(t.close())
        assert t._mixer.alive and t._mixer.exchanges > 0
        assert srv.counters()["requests"] > 0
    finally:
        srv.stop()
