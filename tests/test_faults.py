"""Fault-injection harness tests (docs/RELIABILITY.md): FlakyProxy-driven
MIX outages, server frame hardening, and crash/resume bit-exactness — the
acceptance spine of the fault-tolerant runtime."""

import socket
import struct
import time

import numpy as np
import pytest

from hivemall_tpu.io.libsvm import synthetic_classification
from hivemall_tpu.models.linear import GeneralClassifier
from hivemall_tpu.parallel.mix_service import (EVENT_AVERAGE, MixClient,
                                               MixMessage, MixServer)
from hivemall_tpu.testing.faults import (CrashingSource, FlakyProxy,
                                         crash_on_nth)


def _one_key_msg(group="g1", key=5, w=2.0):
    return MixMessage(EVENT_AVERAGE, group,
                      np.asarray([key], np.int64),
                      np.asarray([w], np.float32),
                      np.asarray([1.0], np.float32),
                      np.asarray([1], np.int32))


# --- FlakyProxy ------------------------------------------------------------

def test_flaky_proxy_passthrough():
    """No schedule: the proxy is transparent to a real mix roundtrip."""
    srv = MixServer().start()
    proxy = FlakyProxy(("127.0.0.1", srv.port)).start()
    try:
        c = MixClient(f"127.0.0.1:{proxy.port}", "g1", threshold=1)
        c._connect()
        c._sock.sendall(_one_key_msg().encode())
        assert c._read_reply().weights[0] == 2.0
        c.close_group()
        assert proxy.chunks_forwarded >= 1 and proxy.faults_applied == 0
    finally:
        proxy.stop()
        srv.stop()


def test_faults_never_reach_fit_loop():
    """RST, drop, and truncate on scheduled exchanges: every fault is
    absorbed by retry/reconnect — the fit loop never sees an exception,
    every exchange completes, and the model trains normally."""
    srv = MixServer().start()
    proxy = FlakyProxy(("127.0.0.1", srv.port),
                       schedule={1: "rst", 3: "drop", 5: "truncate"}).start()
    try:
        clf = GeneralClassifier(
            f"-dims 32 -mini_batch 4 -eta fixed -eta0 0.5 -reg no "
            f"-mix 127.0.0.1:{proxy.port} -mix_threshold 1 "
            f"-mix_timeout 0.3 -mix_backoff 0.01")
        for _ in range(20):
            clf.process(["1:1.0"], 1)
            clf.process(["2:1.0"], -1)
        model = dict(clf.close())
        assert clf._mixer.alive and not clf._mixer.degraded
        assert clf._mixer.exchanges == 10          # all windows completed
        assert clf._mixer.transport_errors >= 3    # one per scheduled fault
        assert clf._mixer.reconnects >= 3
        assert proxy.faults_applied == 3
        assert model["1"] > 0 > model["2"]
    finally:
        proxy.stop()
        srv.stop()


def test_mix_kill_and_restart_reconnects():
    """ACCEPTANCE: the mix path dies mid-run and comes back; training never
    stops, the client reconnects (reconnect counter > 0), exchanges resume,
    and final weights are finite."""
    srv = MixServer().start()
    proxy = FlakyProxy(("127.0.0.1", srv.port)).start()
    try:
        clf = GeneralClassifier(
            f"-dims 64 -mini_batch 4 -eta fixed -eta0 0.5 -reg no "
            f"-mix 127.0.0.1:{proxy.port} -mix_threshold 1 "
            f"-mix_timeout 0.5 -mix_retries 1 -mix_backoff 0.01 "
            f"-mix_breaker_cooldown 0.05 -mix_breaker_trips 1000")

        def feed(n):
            for _ in range(n):
                clf.process(["1:1.0"], 1)
                clf.process(["2:1.0"], -1)

        feed(8)                                # healthy warm-up
        ex_before = clf._mixer.exchanges
        assert ex_before > 0
        proxy.kill()                           # the mix server "dies"
        feed(8)                                # outage: unmixed, no crash
        assert clf._mixer.dropped_exchanges >= 1
        proxy.restart()
        time.sleep(0.08)                       # past the breaker cooldown
        feed(16)                               # half-open probe reconnects
        model = dict(clf.close())
        c = clf._mixer.counters()
        assert clf._mixer.alive
        assert c["reconnects"] >= 1, c
        assert clf._mixer.exchanges > ex_before, c   # resumed exchanging
        assert np.isfinite(model["1"]) and np.isfinite(model["2"])
    finally:
        proxy.stop()
        srv.stop()


@pytest.mark.slow
def test_mix_kill_restart_soak():
    """Soak variant: three kill/restart cycles; exchanges must resume after
    every comeback and the client must never degrade permanently."""
    srv = MixServer().start()
    proxy = FlakyProxy(("127.0.0.1", srv.port)).start()
    try:
        clf = GeneralClassifier(
            f"-dims 64 -mini_batch 4 -eta fixed -eta0 0.5 -reg no "
            f"-mix 127.0.0.1:{proxy.port} -mix_threshold 1 "
            f"-mix_timeout 0.5 -mix_retries 1 -mix_backoff 0.01 "
            f"-mix_breaker_cooldown 0.05 -mix_breaker_trips 1000")

        def feed(n):
            for _ in range(n):
                clf.process(["1:1.0"], 1)
                clf.process(["2:1.0"], -1)

        for cycle in range(3):
            feed(8)
            before = clf._mixer.exchanges
            assert before > 0
            proxy.kill()
            feed(8)
            proxy.restart()
            time.sleep(0.1)
            feed(16)
            assert clf._mixer.exchanges > before, (cycle,
                                                   clf._mixer.counters())
        model = dict(clf.close())
        assert clf._mixer.alive
        assert clf._mixer.reconnects >= 3
        assert np.isfinite(model["1"]) and np.isfinite(model["2"])
    finally:
        proxy.stop()
        srv.stop()


# --- server frame hardening ------------------------------------------------

def test_mix_server_survives_malformed_frame():
    """A garbage frame closes ITS connection only; other clients keep
    exchanging and the bad_frames counter records the event."""
    srv = MixServer().start()
    try:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=2)
        s.settimeout(2)
        garbage = b"\x07" + b"\xff" * 40      # bogus event + torn header
        s.sendall(struct.pack("<I", len(garbage)) + garbage)
        assert s.recv(16) == b""              # server closed this conn
        s.close()
        c = MixClient(f"127.0.0.1:{srv.port}", "g1", threshold=1)
        c._connect()
        c._sock.sendall(_one_key_msg().encode())
        assert c._read_reply().weights[0] == 2.0   # still serving
        c.close_group()
        assert srv.counters()["bad_frames"] == 1
    finally:
        srv.stop()


def test_mix_server_rejects_oversized_frame():
    """A corrupt length prefix must not buffer gigabytes: the connection
    closes before the body is read."""
    srv = MixServer().start()
    try:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=2)
        s.settimeout(2)
        s.sendall(struct.pack("<I", 1 << 31))     # 2 GiB claimed frame
        assert s.recv(16) == b""
        s.close()
        assert srv.counters()["oversized_frames"] == 1
        c = MixClient(f"127.0.0.1:{srv.port}", "g1", threshold=1)
        c._connect()
        c._sock.sendall(_one_key_msg().encode())
        assert c._read_reply().weights[0] == 2.0
        c.close_group()
    finally:
        srv.stop()


def test_corrupt_reply_is_fail_soft_not_crash():
    """Satellite: a server replying garbage (valid length prefix, torn
    body) must degrade the client, never raise into the fit loop — the old
    client let struct.error/ValueError escape maybe_mix."""
    done = []

    def evil_server(port_box):
        ls = socket.socket()
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind(("127.0.0.1", 0))
        ls.listen(4)
        port_box.append(ls.getsockname()[1])
        ls.settimeout(5)
        while not done:
            try:
                c, _ = ls.accept()
            except socket.timeout:
                break
            try:
                c.recv(1 << 16)                      # swallow the request
                c.sendall(struct.pack("<I", 64) + b"\x01" * 64)
            except OSError:
                pass
            finally:
                c.close()
        ls.close()

    import threading
    box: list = []
    th = threading.Thread(target=evil_server, args=(box,), daemon=True)
    th.start()
    while not box:
        time.sleep(0.005)
    clf = GeneralClassifier(
        f"-dims 32 -mini_batch 4 -eta fixed -eta0 0.5 -reg no "
        f"-mix 127.0.0.1:{box[0]} -mix_threshold 1 -mix_timeout 0.3 "
        f"-mix_retries 0 -mix_backoff 0.01 -mix_breaker_cooldown 0")
    for _ in range(16):                              # must not raise
        clf.process(["1:1.0"], 1)
        clf.process(["2:1.0"], -1)
    model = dict(clf.close())
    done.append(True)
    assert clf._mixer.degraded
    assert clf._mixer.transport_errors >= 1
    assert model["1"] > 0 > model["2"]


# --- crash wrappers through the ingest pipeline ----------------------------

def test_crashing_source_fires_deterministically():
    src = CrashingSource(iter(range(10)), 4)
    got = []
    with pytest.raises(RuntimeError, match="injected source crash"):
        for v in src:
            got.append(v)
    assert got == [0, 1, 2, 3]


def test_crash_on_nth_worker_surfaces_in_order():
    """The nth prep call raises inside the pool; the consumer sees it in
    stream position after every earlier batch, and the stats count it."""
    from hivemall_tpu.io.pipeline import IngestPipeline, PipelineStats
    stats = PipelineStats()
    it = IngestPipeline(iter(range(20)), crash_on_nth(lambda x: x * 2, 6),
                        workers=3, stats=stats)
    got = []
    with pytest.raises(RuntimeError, match="injected worker crash"):
        for v in it:
            got.append(v)
    assert got == [0, 2, 4, 6, 8, 10]     # items 0..5, delivered in order
    assert stats.worker_errors == 1


# --- checkpoint crash + resume ---------------------------------------------

def _stream_opts(extra=""):
    return ("-dims 512 -mini_batch 16 -loss logloss -opt adagrad "
            "-steps_per_dispatch 1 " + extra)


def test_crash_resume_bit_exact_trajectory(tmp_path):
    """ACCEPTANCE: crash at an arbitrary step, resume() from the autosaved
    bundle, and the post-restore loss trajectory AND final weights are
    bit-exact vs. an uninterrupted run at -steps_per_dispatch 1."""
    ds, _ = synthetic_classification(192, 10, seed=23)

    def stream():
        return ds.batches(16, shuffle=True, seed=31)

    cont = GeneralClassifier(_stream_opts())
    cont._trace_losses = []
    cont.fit_stream(stream())

    ckdir = str(tmp_path / "ck")
    tr = GeneralClassifier(_stream_opts(
        f"-checkpoint_dir {ckdir} -checkpoint_every 4"))
    with pytest.raises(RuntimeError, match="injected source crash"):
        tr.fit_stream(CrashingSource(stream(), 9))

    r = GeneralClassifier(_stream_opts(f"-checkpoint_dir {ckdir}"))
    assert r.resume()
    assert r._t == 8 and r._stream_pos == 8    # newest cadence bundle
    r._trace_losses = []
    r.fit_stream(stream(), resume=True)

    assert r._trace_losses == cont._trace_losses[8:]   # bit-exact floats
    np.testing.assert_array_equal(np.asarray(r.w), np.asarray(cont.w))
    assert r._t == cont._t and r._examples == cont._examples


def test_resume_falls_back_past_corrupt_latest(tmp_path):
    """A truncated newest bundle (crash mid-copy, disk bitrot) is skipped
    with a warning; resume() restores the previous one from the retention
    window."""
    ds, _ = synthetic_classification(128, 8, seed=4)
    ckdir = str(tmp_path / "ck")
    tr = GeneralClassifier(_stream_opts(
        f"-checkpoint_dir {ckdir} -checkpoint_every 3"))
    tr.fit_stream(ds.batches(16, shuffle=False))
    from hivemall_tpu.io.checkpoint import list_bundles
    bundles = list_bundles(ckdir, tr.NAME)
    assert len(bundles) >= 2
    with open(bundles[0], "r+b") as f:         # truncate the newest
        f.truncate(100)
    r = GeneralClassifier(_stream_opts(f"-checkpoint_dir {ckdir}"))
    with pytest.warns(RuntimeWarning, match="skipping unusable checkpoint"):
        assert r.resume()
    assert r._t > 0 and r._t < tr._t           # restored an older step


def test_stream_pos_resets_on_fresh_stream(tmp_path):
    """Sequential fit_stream calls on one trainer (FFM's per-epoch loop,
    any reuse) restart stream-position accounting — a second stream's
    checkpoints must not record positions offset by the first stream."""
    ds, _ = synthetic_classification(96, 8, seed=9)
    tr = GeneralClassifier(_stream_opts())
    tr.fit_stream(ds.batches(16, shuffle=False))       # 6 batches
    assert tr._stream_pos == 6
    tr.fit_stream(ds.batches(16, shuffle=False))
    assert tr._stream_pos == 6                         # reset, not 12


def test_ffm_fit_stream_accepts_resume_kwarg():
    """The CLI streaming branch passes resume= unconditionally; the FFM
    override must accept it (single-stream form) and reject it on the
    multi-epoch replay form, which has no stream position to skip into."""
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.models.fm import FFMTrainer

    rng = np.random.default_rng(11)
    n, L, F = 64, 4, 4
    idx = rng.integers(1, 512, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32) % F, (n, 1))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L, dtype=np.int64)
    ds = SparseDataset(idx.ravel(), indptr, np.ones(n * L, np.float32),
                       lab, fld.ravel())
    cfg = "-dims 512 -factors 2 -fields 4 -mini_batch 16 -classification"
    t = FFMTrainer(cfg)
    t.fit_stream(ds.batches(16, shuffle=False), resume=False)
    assert t._t > 0
    with pytest.raises(ValueError, match="single-stream"):
        FFMTrainer(cfg).fit_stream(
            lambda: ds.batches(16, shuffle=False), epochs=2, resume=True)


def test_resume_skip_rejects_short_stream(tmp_path):
    """resume=True against a stream shorter than the checkpointed position
    fails loudly (the caller re-opened the wrong stream), not silently."""
    ds, _ = synthetic_classification(96, 8, seed=6)
    ckdir = str(tmp_path / "ck")
    tr = GeneralClassifier(_stream_opts(
        f"-checkpoint_dir {ckdir} -checkpoint_every 2"))
    tr.fit_stream(ds.batches(16, shuffle=False))       # 6 batches
    r = GeneralClassifier(_stream_opts(f"-checkpoint_dir {ckdir}"))
    assert r.resume() and r._stream_pos == 6
    short = list(ds.batches(16, shuffle=False))[:3]
    with pytest.raises(ValueError, match="stream exhausted"):
        r.fit_stream(iter(short), resume=True)
