"""Catalog conformance: every train_* SQL name resolves, instantiates,
round-trips a smoke input through process()/close(), and emits rows
(SURVEY.md §5 "catalog conformance test ... round-trips a smoke input")."""

import numpy as np
import pytest

from hivemall_tpu.catalog.registry import all_functions, lookup

RNG = np.random.default_rng(0)

SPARSE = [([f"f{j}:{v:.3f}" for j, v in enumerate(RNG.normal(size=3))],
           1 if i % 2 else -1) for i in range(24)]
DENSE = [(list(RNG.normal(size=4)), i % 2) for i in range(40)]
FFM = [([f"{f}:{f * 7 + i % 5 + 1}:1.0" for f in range(3)], 1 if i % 2 else -1)
       for i in range(24)]
TRIPLES = [(int(RNG.integers(6)), int(RNG.integers(5)),
            float(RNG.normal() + 3)) for _ in range(30)]
DOCS = [(["alpha", "beta", "gamma", "delta"] * 3,) for _ in range(12)]

# name -> (constructor options, rows). Rows are *args tuples for process().
SMOKE = {}
for name in ["train_classifier", "train_perceptron", "train_pa", "train_pa1",
             "train_pa2", "train_cw", "train_arow", "train_arowh",
             "train_scw", "train_scw2", "train_adagrad_rda", "train_kpa"]:
    SMOKE[name] = ("-mini_batch 8 -dims 1024", [(f, y) for f, y in SPARSE])
for name in ["train_regressor", "train_logregr", "train_adagrad_regr",
             "train_adadelta_regr", "train_pa1_regr", "train_pa1a_regr",
             "train_pa2_regr", "train_pa2a_regr", "train_arow_regr",
             "train_arowe_regr", "train_arowe2_regr"]:
    SMOKE[name] = ("-mini_batch 8 -dims 1024",
                   [(f, float(max(0, y))) for f, y in SPARSE])
for name in ["train_multiclass_perceptron", "train_multiclass_pa",
             "train_multiclass_pa1", "train_multiclass_pa2",
             "train_multiclass_cw", "train_multiclass_arow",
             "train_multiclass_scw", "train_multiclass_scw2"]:
    SMOKE[name] = ("-classes 3 -mini_batch 8 -dims 1024",
                   [(f, i % 3) for i, (f, _) in enumerate(SPARSE)])
SMOKE["train_fm"] = ("-factors 4 -mini_batch 8 -dims 1024 -classification",
                     [(f, y) for f, y in SPARSE])
SMOKE["train_ffm"] = ("-factors 4 -fields 4 -mini_batch 8 -dims 1024 "
                      "-classification", FFM)
SMOKE["train_mf_sgd"] = ("-factors 4 -users 8 -items 8 -mini_batch 8 -mu 3.0",
                         TRIPLES)
SMOKE["train_mf_adagrad"] = SMOKE["train_mf_sgd"]
SMOKE["train_bprmf"] = ("-factors 4 -users 8 -items 8 -mini_batch 8",
                        [(u, i, (i + 1) % 5) for u, i, _ in TRIPLES])
SMOKE["train_slim"] = ("-l1 0.01 -iters 5",
                       [(u, i % 6, r) for u, i, r in TRIPLES])
SMOKE["train_word2vec"] = ("-dim 8 -window 2 -neg 2 -min_count 1 "
                           "-mini_batch 64 -iters 1 -sample 0", DOCS)
SMOKE["train_lda"] = ("-topics 2 -vocab 256 -mini_batch 4", DOCS)
SMOKE["train_plsa"] = ("-topics 2 -vocab 256 -mini_batch 4", DOCS)
for name in ["train_randomforest_classifier", "train_xgboost_classifier",
             "train_multiclass_xgboost_classifier"]:
    SMOKE[name] = ("-trees 2 -depth 3" if "randomforest" in name
                   else "-num_round 2 -max_depth 3", DENSE)
SMOKE["train_randomforest_regressor"] = (
    "-trees 2 -depth 3", [(f, float(y)) for f, y in DENSE])
SMOKE["train_xgboost_regr"] = (
    "-num_round 2 -max_depth 3", [(f, float(y)) for f, y in DENSE])


def test_every_trainer_is_smoke_covered():
    trainers = [n for n in all_functions() if n.startswith("train_")]
    missing = [n for n in trainers if n not in SMOKE]
    assert not missing, f"no smoke spec for: {missing}"


@pytest.mark.parametrize("name", sorted(SMOKE))
def test_trainer_smoke(name):
    opts, rows = SMOKE[name]
    cls = lookup(name).resolve()
    tr = cls(opts)
    for args in rows:
        tr.process(*args)
    out = list(tr.close())
    assert out, f"{name} emitted no model rows"
