"""End-to-end linear trainers: UDTF lifecycle + columnar fit + convergence
(SURVEY.md §5: golden-convergence smoke — loss decreases, AUC above threshold)."""

import numpy as np
import pytest

from hivemall_tpu.catalog import lookup
from hivemall_tpu.frame.evaluation import auc, logloss
from hivemall_tpu.io.libsvm import synthetic_classification
from hivemall_tpu.models.linear import (GeneralClassifier, GeneralRegressor,
                                        LogressTrainer)


def test_udtf_lifecycle_tiny():
    """Drive the trainer exactly as the reference's unit tests drive UDTFs:
    initialize -> process(row)* -> close() collecting emitted model rows."""
    clf = GeneralClassifier("-dims 256 -mini_batch 4 -eta0 0.5")
    # AND-ish toy: feature 1 -> positive, feature 2 -> negative
    rows = [(["1:1.0"], 1), (["2:1.0"], -1)] * 20
    for f, y in rows:
        clf.process(f, y)
    model = dict(clf.close())
    assert model["1"] > 0 > model["2"]


def test_classifier_converges_synthetic():
    ds, _ = synthetic_classification(2000, 100, seed=5)
    clf = GeneralClassifier(
        "-dims 256 -loss logloss -opt adagrad -reg no -eta fixed -eta0 0.3 "
        "-mini_batch 64 -iters 3")
    clf.fit(ds)
    p = clf.predict_proba(ds)
    a = auc(ds.labels, p)
    ll = logloss(ds.labels, p)
    assert a > 0.9, a
    assert ll < 0.45, ll


def test_hinge_rda_default_converges():
    ds, _ = synthetic_classification(1500, 80, seed=7)
    clf = GeneralClassifier("-dims 256 -eta0 0.3 -mini_batch 64 -iters 2")
    clf.fit(ds)
    assert auc(ds.labels, clf.decision_function(ds)) > 0.85


def test_string_features_roundtrip():
    clf = GeneralClassifier("-dims 4096 -mini_batch 2 -eta0 0.5")
    for _ in range(10):
        clf.process(["cat#tokyo", "height:1.2"], 1)
        clf.process(["cat#osaka"], -1)
    model = dict(clf.close())
    assert "cat#tokyo" in model and "cat#osaka" in model
    assert model["cat#tokyo"] > 0 > model["cat#osaka"]


def test_regressor_fits_line():
    rng = np.random.default_rng(0)
    n = 500
    x = rng.uniform(-1, 1, n).astype(np.float32)
    rows = [(np.array([1], np.int32), np.array([xx], np.float32)) for xx in x]
    from hivemall_tpu.io.sparse import SparseDataset
    ds = SparseDataset.from_rows(rows, 3.0 * x)
    reg = GeneralRegressor("-dims 16 -opt adagrad -reg no -eta fixed "
                           "-eta0 0.5 -mini_batch 32 -iters 10")
    reg.fit(ds)
    w = reg._finalized_weights()
    assert abs(w[1] - 3.0) < 0.2, w[1]


def test_logress_zero_one_labels():
    ds, _ = synthetic_classification(1000, 60, seed=9)
    labels01 = (ds.labels > 0).astype(np.float32)
    from hivemall_tpu.io.sparse import SparseDataset
    ds01 = SparseDataset(ds.indices, ds.indptr, ds.values, labels01)
    t = LogressTrainer("-dims 256 -eta fixed -eta0 0.5 -mini_batch 64 -iters 3")
    t.fit(ds01)
    assert auc(labels01, t.predict_proba(ds01)) > 0.85


def test_warm_start_loadmodel(tmp_path):
    ds, _ = synthetic_classification(800, 50, seed=11)
    a_ = GeneralClassifier("-dims 128 -eta0 0.3 -mini_batch 64")
    a_.fit(ds)
    p = str(tmp_path / "model.tsv")
    a_.save_model(p)
    b_ = GeneralClassifier(f"-dims 128 -loadmodel {p}")
    # warm-started model scores like the original without any training
    np.testing.assert_allclose(b_.decision_function(ds),
                               a_.decision_function(ds), rtol=1e-4, atol=1e-4)


def test_catalog_resolves_trainers():
    e = lookup("train_classifier")
    cls = e.resolve()
    assert cls is GeneralClassifier
    assert e.options is not None
    ns = e.options.parse("-loss logloss -opt adagrad")
    assert ns.loss == "logloss"


def test_halffloat_bf16():
    ds, _ = synthetic_classification(500, 40, seed=13)
    clf = GeneralClassifier("-dims 128 -halffloat -eta0 0.3 -mini_batch 64")
    clf.fit(ds)
    import jax.numpy as jnp
    assert clf.w.dtype == jnp.bfloat16
    assert auc(ds.labels, clf.decision_function(ds)) > 0.8


def test_unit_val_elision_trains_identically():
    """Categorical (all-unit) batches drop the val array; the step rebuilds
    it on device — same model as the explicit-val path."""
    import numpy as np
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.models.linear import GeneralClassifier
    rng = np.random.default_rng(0)
    rows = [(rng.choice(np.arange(1, 64), 5, replace=False).astype(np.int32),
             np.ones(5, np.float32)) for _ in range(200)]
    labels = [1.0 if r[0][0] % 2 else -1.0 for r in rows]
    ds = SparseDataset.from_rows(rows, labels)
    opts = "-dims 64 -loss logloss -opt adagrad -mini_batch 32 -iters 3"
    t1 = GeneralClassifier(opts)
    t1.fit(ds)
    b = next(ds.batches(32))
    pb = t1._preprocess_batch(b)
    assert pb.val is None                      # elision engaged
    t2 = GeneralClassifier(opts)
    t2.UNIT_VAL_ELISION = False                # force explicit val path
    t2.fit(ds)
    np.testing.assert_allclose(np.asarray(t1.w, np.float32),
                               np.asarray(t2.w, np.float32),
                               rtol=1e-5, atol=1e-6)
