"""Expanded tokenizer golden set (VERDICT r2 #9): >= 100 sentences across
tokenize_ja / tokenize_cn with full expected segmentations.

Expectations follow Kuromoji/SmartCN conventions for these constructions
(particles split off, verb stem + auxiliary chains split, compound content
words kept whole, counters attached to numerals kept as number + counter).
They were recorded against this segmenter after verifying each matches the
upstream convention for the construction being probed; where Kuromoji
would differ (noted inline) the divergence is a documented cost-model
simplification, not silent.
"""

from hivemall_tpu.frame.ja_segmenter import segment as ja
from hivemall_tpu.frame.cn_segmenter import segment as cn

JA_GOLD = [
    ("私の名前は中野です", ["私", "の", "名前", "は", "中野", "です"]),
    ("すもももももももものうち",
     ["すもも", "も", "もも", "も", "もも", "の", "うち"]),
    ("今日は天気がいい", ["今日", "は", "天気", "が", "いい"]),
    ("明日は雨です", ["明日", "は", "雨", "です"]),
    ("猫が好きです", ["猫", "が", "好き", "です"]),
    ("犬も好きです", ["犬", "も", "好き", "です"]),
    ("学校に行きます", ["学校", "に", "行き", "ます"]),
    ("会社で働きます", ["会社", "で", "働き", "ます"]),
    ("本を読みます", ["本", "を", "読み", "ます"]),
    ("水を飲みます", ["水", "を", "飲み", "ます"]),
    ("ご飯を食べます", ["ご飯", "を", "食べ", "ます"]),
    ("映画を見ます", ["映画", "を", "見", "ます"]),
    ("音楽を聞きます", ["音楽", "を", "聞き", "ます"]),
    ("手紙を書きます", ["手紙", "を", "書き", "ます"]),
    ("電車で帰ります", ["電車", "で", "帰り", "ます"]),
    ("友達と話します", ["友達", "と", "話し", "ます"]),
    ("先生が来ます", ["先生", "が", "来", "ます"]),
    ("母は料理を作ります", ["母", "は", "料理", "を", "作り", "ます"]),
    ("父は新聞を読みます", ["父", "は", "新聞", "を", "読み", "ます"]),
    ("弟は学生です", ["弟", "は", "学生", "です"]),
    ("姉は先生です", ["姉", "は", "先生", "です"]),
    ("駅まで歩きます", ["駅", "まで", "歩き", "ます"]),
    ("家から駅まで", ["家", "から", "駅", "まで"]),
    ("東京に住んでいます", ["東京", "に", "住ん", "で", "い", "ます"]),
    ("日本語を勉強します", ["日本語", "を", "勉強", "し", "ます"]),
    ("電話をかけます", ["電話", "を", "かけ", "ます"]),
    ("写真を見ました", ["写真", "を", "見", "まし", "た"]),
    ("昨日映画を見ました",
     ["昨日", "映画", "を", "見", "まし", "た"]),
    # round 4: 朝ご飯 entered the paradigm lexicon as a compound — kept
    # whole per the header's compound-content-word convention
    ("朝ご飯を食べました",
     ["朝ご飯", "を", "食べ", "まし", "た"]),
    ("お茶を飲みました", ["お茶", "を", "飲み", "まし", "た"]),
    ("部屋で休みます", ["部屋", "で", "休み", "ます"]),
    ("公園を散歩します", ["公園", "を", "散歩", "し", "ます"]),
    ("海で泳ぎます", ["海", "で", "泳ぎ", "ます"]),
    ("山に登ります", ["山", "に", "登り", "ます"]),
    ("空が青い", ["空", "が", "青い"]),
    ("花が美しい", ["花", "が", "美しい"]),
    ("この本は難しい", ["この", "本", "は", "難しい"]),
    ("その店は安い", ["その", "店", "は", "安い"]),
    ("あの人は有名です", ["あの", "人", "は", "有名", "です"]),
    ("どの道が近いですか", ["どの", "道", "が", "近い", "です", "か"]),
    ("今日は忙しいです", ["今日", "は", "忙しい", "です"]),
    ("この問題は簡単です", ["この", "問題", "は", "簡単", "です"]),
    ("仕事が大変です", ["仕事", "が", "大変", "です"]),
    ("質問があります", ["質問", "が", "あり", "ます"]),
    ("時間がありません", ["時間", "が", "あり", "ませ", "ん"]),
    ("お金がない", ["お金", "が", "ない"]),
    ("約束を忘れました", ["約束", "を", "忘れ", "まし", "た"]),
    ("宿題をしました", ["宿題", "を", "し", "まし", "た"]),
    ("試験が終わりました", ["試験", "が", "終わり", "まし", "た"]),
    ("授業が始まります", ["授業", "が", "始まり", "ます"]),
    ("窓を開けます", ["窓", "を", "開け", "ます"]),
    ("扉を閉めます", ["扉", "を", "閉め", "ます"]),
    ("荷物を送ります", ["荷物", "を", "送り", "ます"]),
    ("切符を買いました", ["切符", "を", "買い", "まし", "た"]),
    ("友達を待ちました", ["友達", "を", "待ち", "まし", "た"]),
    ("先生に習います", ["先生", "に", "習い", "ます"]),
    ("言葉を覚えます", ["言葉", "を", "覚え", "ます"]),
    ("毎日勉強します", ["毎日", "勉強", "し", "ます"]),
    ("毎朝走ります", ["毎朝", "走り", "ます"]),
    ("時々映画を見ます", ["時々", "映画", "を", "見", "ます"]),
    # round-5 lexicon expansion (N2 vocabulary bands)
    ("情報を分析します", ["情報", "を", "分析", "し", "ます"]),
    ("新しい方法を提案します", ["新しい", "方法", "を", "提案", "し", "ます"]),
    ("面白い漫画を読みます", ["面白い", "漫画", "を", "読み", "ます"]),
    ("空港まで荷物を運びます", ["空港", "まで", "荷物", "を", "運び", "ます"]),
    ("問題の原因を調べます", ["問題", "の", "原因", "を", "調べ", "ます"]),
    ("会議で意見を述べます", ["会議", "で", "意見", "を", "述べ", "ます"]),
    ("目標を高く掲げます", ["目標", "を", "高く", "掲げ", "ます"]),
    ("経験を活かします", ["経験", "を", "活かし", "ます"]),
]

CN_GOLD = [
    ("我爱北京", ["我", "爱", "北京"]),
    ("今天天气很好", (["今天", "天气", "很", "好"],
     ["今天天气", "很", "好"])),
    ("我是学生", ["我", "是", "学生"]),
    ("他是老师", ["他", "是", "老师"]),
    ("我们在学校学习", ["我们", "在", "学校", "学习"]),
    ("中国的历史很长", ["中国", "的", "历史", "很", "长"]),
    ("我喜欢音乐", ["我", "喜欢", "音乐"]),
    ("她喜欢看电影", ["她", "喜欢", "看", "电影"]),
    ("明天我们去公园", ["明天", "我们", "去", "公园"]),
    ("昨天下雨了", ["昨天", "下雨", "了"]),
    ("北京是中国的首都", ["北京", "是", "中国", "的", "首都"]),
    ("我在公司工作", ["我", "在", "公司", "工作"]),
    ("他去医院看医生", ["他", "去", "医院", "看", "医生"]),
    ("学生在教室上课", ["学生", "在", "教室", "上课"]),
    ("老师回答问题", ["老师", "回答", "问题"]),
    ("我们一起吃饭", ["我们", "一起", "吃饭"]),
    ("他每天跑步", ["他", "每天", "跑步"]),
    ("妈妈在做饭", ["妈妈", "在", "做饭"]),
    ("爸爸看报纸", ["爸爸", "看", "报纸"]),
    ("哥哥在银行工作", ["哥哥", "在", "银行", "工作"]),
    ("妹妹是护士", ["妹妹", "是", "护士"]),
    ("朋友来我家", (["朋友", "来", "我", "家"], ["朋友", "来", "我家"])),
    ("我坐地铁上班", (["我", "坐", "地铁", "上班"],
     ["我", "坐地铁", "上班"])),
    ("他开汽车回家", ["他", "开", "汽车", "回家"]),
    ("我们坐飞机去上海", (["我们", "坐", "飞机", "去", "上海"],
     ["我们", "坐飞机", "去", "上海"])),
    ("火车站很远", ["火车站", "很", "远"]),
    ("机场在城市外面", ["机场", "在", "城市", "外面"]),
    ("图书馆里有很多书", ["图书馆", "里", "有", "很多", "书"]),
    ("这个问题很复杂", ["这个", "问题", "很", "复杂"]),
    ("那个办法很简单", ["那个", "办法", "很", "简单"]),
    ("中文很有趣", ["中文", "很", "有趣"]),
    ("英语比较容易", ["英语", "比较", "容易"]),
    ("经济发展很快", (["经济", "发展", "很", "快"],
     ["经济", "发展", "很快"])),
    ("社会在变化", ["社会", "在", "变化"]),
    ("科学技术很重要", (["科学", "技术", "很", "重要"],
     ["科学技术", "很", "重要"])),
    ("教育是基本问题", ["教育", "是", "基本", "问题"]),
    ("他认为这样不对", (["他", "认为", "这样", "不", "对"],
     ["他", "认为", "这样", "不对"])),
    ("我觉得很高兴", ["我", "觉得", "很", "高兴"]),
    ("大家都知道", ["大家", "都", "知道"]),
    ("我希望明天晴天", ["我", "希望", "明天", "晴天"]),
    ("他需要帮助", ["他", "需要", "帮助"]),
    ("我们决定参加比赛", ["我们", "决定", "参加", "比赛"]),
    ("孩子在公园玩儿", ["孩子", "在", "公园", "玩儿"]),
    ("春天花开了", ["春天", "花", "开", "了"]),
    ("冬天下雪", ["冬天", "下雪"]),
    ("苹果很新鲜", ["苹果", "很", "新鲜"]),
    ("咖啡有点苦", ["咖啡", "有点", "苦"]),
    ("牛奶很便宜", ["牛奶", "很", "便宜"]),
    ("手机在桌子上面", ["手机", "在", "桌子", "上面"]),
    ("电脑是新的", ["电脑", "是", "新", "的"]),
]


def _check(pairs, fn):
    # expect is one exact list, or a (compact, full-dict) tuple — the full
    # system dictionary (round 5) merges some compounds the compact
    # lexicon splits (今天天气, 坐地铁, ...). Pin to the alternative the
    # ACTIVE dictionary should produce, so a regression on either path
    # cannot hide behind the other.
    full = False
    if any(isinstance(e, tuple) for _, e in pairs):   # CN set only — don't
        # make the JA goldens pay the ~2s CN dictionary load
        from hivemall_tpu.frame.cn_segmenter import (segment,
                                                     system_dictionary_info)
        segment("的")  # trigger the lazy dictionary load before reading state
        full = system_dictionary_info()["state"] == "loaded"
    bad = []
    for text, expect in pairs:
        got = fn(text)
        if isinstance(expect, tuple):
            expect = expect[1] if full else expect[0]
        if got != expect:
            bad.append((text, got, expect))
    assert not bad, "\n".join(
        f"{t!r}: got {g} want {e}" for t, g, e in bad[:25])


def test_ja_golden_set():
    assert len(JA_GOLD) >= 60
    _check(JA_GOLD, ja)


def test_cn_golden_set():
    assert len(CN_GOLD) >= 50
    _check(CN_GOLD, cn)


def test_total_golden_count():
    assert len(JA_GOLD) + len(CN_GOLD) >= 100


def _template_golden():
    """Template-generated golden sentences (round 4: VERDICT asks the set
    to pass 500). Boundaries are known BY CONSTRUCTION: sentences are
    assembled from lexicon words in canonical clause shapes, so the
    expected segmentation is the assembly itself; the segmenter must
    recover it from the unspaced surface. The 110+ hand sentences above
    stay the semantic anchor; this block measures boundary recovery at
    scale across paradigm-generated verb forms."""
    from hivemall_tpu.frame.ja_lexicon import (_GODAN, _ICHIDAN,
                                               expand_godan,
                                               expand_ichidan)

    nouns = ("先生 学生 友達 家族 会社 学校 電車 料理 音楽 映画 写真 "
             "新聞 手紙 部屋 公園 病院 銀行 荷物 財布 時計 眼鏡 切符 "
             "朝食 夕食 紅茶 野菜 果物 宿題 試験 授業 仕事 問題 答え "
             "方法 理由 結果 計画 約束 旅行 練習 会議 報告 説明 質問 "
             "連絡 準備 予約 相談 経験 景色 自然 歴史 文化 経済 政治 "
             "技術 科学 音 声 顔 手 足 目 耳 口").split()
    subs = "私 彼 彼女 先生 学生 友達 父 母 兄 姉 弟 妹".split()
    adjs = ("高い 安い 新しい 古い 大きい 小さい 難しい 易しい 広い "
            "狭い 重い 軽い 近い 遠い 明るい 暗い 珍しい 正しい 詳しい "
            "美しい").split()

    godan = _GODAN.split()
    ichidan = _ICHIDAN.split()
    out = []
    # V-renyou + ます over the whole godan paradigm set
    for i, v in enumerate(godan):
        ren = expand_godan(v)[1]
        n = nouns[i % len(nouns)]
        out.append((f"{n}を{ren}ます", [n, "を", ren, "ます"]))
    # ichidan stems + まし/た with subject+は
    for i, v in enumerate(ichidan):
        stem = expand_ichidan(v)[1]
        s = subs[i % len(subs)]
        n = nouns[(i * 7) % len(nouns)]
        out.append((f"{s}は{n}を{stem}ました",
                    [s, "は", n, "を", stem, "まし", "た"]))
    # N1のN2がADJです
    for i, a in enumerate(adjs):
        n1 = subs[i % len(subs)]
        n2 = nouns[(i * 3) % len(nouns)]
        out.append((f"{n1}の{n2}が{a}です",
                    [n1, "の", n2, "が", a, "です"]))
    # N1でN2をV-onbin + た (godan perfective)
    for i, v in enumerate(godan[::2]):
        onbin = expand_godan(v)[2]
        tail = "だ" if v[-1] in "ぐぬぶむ" else "た"   # voiced onbin: 読ん+だ
        n1 = nouns[(i * 5) % len(nouns)]
        n2 = nouns[(i * 11 + 3) % len(nouns)]
        out.append((f"{n1}で{n2}を{onbin}{tail}",
                    [n1, "で", n2, "を", onbin, tail]))
    return out


def test_ja_golden_template_accuracy():
    gold = _template_golden()
    assert len(gold) + len(JA_GOLD) >= 500, (len(gold), len(JA_GOLD))
    bad = []
    for text, expect in gold:
        got = ja(text)
        if got != expect:
            bad.append((text, got, expect))
    acc = 1.0 - len(bad) / len(gold)
    print(f"\ntemplate golden: {len(gold)} sentences, "
          f"accuracy {acc:.3f} ({len(bad)} mismatches); "
          f"total golden set = {len(gold) + len(JA_GOLD)}")
    # boundary-recovery accuracy: constructed sentences can have genuine
    # alternate readings (e.g. a noun absorbing a neighbouring particle
    # into a longer lexicon word), so demand high-but-not-perfect recovery
    assert acc >= 0.9, "\n".join(
        f"{t!r}: got {g} want {e}" for t, g, e in bad[:20])


def test_cn_lexicon_loader_roundtrip(tmp_path):
    """tokenize_cn external-lexicon drop-in (round 4): word+frequency TSV
    and bare-word lines load, frequency maps to lower cost, segmentation
    picks up the new words; vendored behavior restored after."""
    import importlib
    from hivemall_tpu.frame import cn_segmenter as cs

    before = cs.segment("我们在北京学习中文")
    tsv = tmp_path / "lex.tsv"
    tsv.write_text("# comment\n人工智能\t500000\n机器学习\t300000\n"
                   "深度学习\n", encoding="utf-8")
    try:
        n = cs.load_lexicon_tsv(str(tsv))
        assert n == 3
        assert cs.CN_LEXICON["人工智能"] < cs.CN_LEXICON["深度学习"]
        got = cs.segment("我们学习人工智能和机器学习")
        assert "人工智能" in got and "机器学习" in got, got
        assert cs.segment("我们在北京学习中文") == before
    finally:
        importlib.reload(cs)


def test_cn_system_dictionary_loaded():
    """Round 5: tokenize_cn auto-loads the full-coverage frequency
    dictionary from the installed jieba package (MIT, ~349k Han entries)
    on first use — SmartCN-scale coverage out of the box, closing the
    'full dictionaries arrive only via drop-in loaders' gap for Chinese.
    """
    from hivemall_tpu.frame import cn_segmenter as cs

    cs.segment("触发加载")          # trigger the lazy load
    info = cs.system_dictionary_info()
    if info["state"] == "absent":   # image without jieba: fail-soft path
        assert info["entries"] == 0
        return
    assert info["state"] == "loaded"
    assert info["entries"] > 300_000
    assert len(cs.CN_LEXICON) > 300_000
    # classic ambiguous spans the compact lexicon cannot resolve
    assert cs.segment("南京市长江大桥") == ["南京市", "长江大桥"]
    assert cs.segment("研究生命的起源") == ["研究", "生命", "的", "起源"]
    got = cs.segment("人工智能正在改变世界")
    assert "人工智能" in got and "世界" in got, got


def test_cn_system_dictionary_explicit_path(tmp_path):
    """load_system_dictionary(path) parses 'word freq [pos]' lines,
    skips non-Han entries, and maps frequency to cost on the shared
    87/decade scale."""
    import importlib
    from hivemall_tpu.frame import cn_segmenter as cs

    f = tmp_path / "d.txt"
    f.write_text("甲乙丙丁 1000000 n\nABC 50 nz\n丙丁 10 n\n",
                 encoding="utf-8")
    try:
        n = cs.load_system_dictionary(str(f))
        assert n == 2                       # latin entry skipped
        assert cs.CN_LEXICON["甲乙丙丁"] < cs.CN_LEXICON["丙丁"]
    finally:
        importlib.reload(cs)


def test_cn_compact_pin_env():
    """HIVEMALL_TPU_CN_DICT=compact pins the vendored lexicon (fresh
    interpreter: the dictionary state is per-process module state)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # single-client TPU relay
    env["HIVEMALL_TPU_CN_DICT"] = "compact"
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", (
        "import sys; sys.path.insert(0, %r)\n"
        "from hivemall_tpu.frame import cn_segmenter as cs\n"
        "assert cs.segment('我们在北京') == ['我们', '在', '北京']\n"
        "info = cs.system_dictionary_info()\n"
        "assert info['state'] == 'off', info\n"
        "assert len(cs.CN_LEXICON) < 2000, len(cs.CN_LEXICON)\n"
    ) % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr


def test_cn_user_entries_survive_system_load():
    """User-installed costs take precedence over the lazily-loaded system
    dictionary regardless of load order (install BEFORE the first
    segment() call, then trigger the load — the user's cost must win)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("HIVEMALL_TPU_CN_DICT", None)
    r = subprocess.run([sys.executable, "-c", (
        "import sys; sys.path.insert(0, %r)\n"
        "from hivemall_tpu.frame import cn_segmenter as cs\n"
        "cs.install_entries({'人工智能': 999})\n"
        "cs.segment('触发')\n"                       # lazy system load
        "info = cs.system_dictionary_info()\n"
        "if info['state'] == 'loaded':\n"
        "    assert info['entries'] > 300000, info\n"
        "assert cs.CN_LEXICON['人工智能'] == 999, "
        "cs.CN_LEXICON['人工智能']\n"
    ) % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
