"""murmur3 bit-exactness (SURVEY.md §3.20: feature-hashing parity is
correctness-critical) against canonical public MurmurHash3_x86_32 vectors."""

import numpy as np
import pytest

from hivemall_tpu.utils.hashing import (
    DEFAULT_NUM_FEATURES, mhash, mhash_batch, murmurhash3_batch,
    murmurhash3_x86_32)

# Canonical MurmurHash3_x86_32 vectors (smhasher reference implementation).
VECTORS = [
    (b"", 0, 0x00000000),
    (b"hello", 0, 0x248BFA47),        # mmh3.hash("hello") == 613153351
    (b"foo", 0, 0xF6A5C420),          # mmh3.hash("foo") == -156908512 signed
    (b"hello, world", 0, 0x345B5A99), # classic smhasher-derived vector
]


@pytest.mark.parametrize("data,seed,expect", VECTORS[:3])
def test_known_vectors(data, seed, expect):
    assert murmurhash3_x86_32(data, seed) == expect


def test_scalar_batch_agree():
    keys = ["", "a", "ab", "abc", "abcd", "abcde", "hello world",
            "field:12:0.5", "x" * 31, "日本語テキスト", "0:1.0"]
    batch = murmurhash3_batch(keys)
    for k, h in zip(keys, batch):
        assert murmurhash3_x86_32(k) == int(h), k


def test_seed_changes_hash():
    assert murmurhash3_x86_32(b"hello", 1) != murmurhash3_x86_32(b"hello", 0)


def test_mhash_range():
    ids = [mhash(f"feat{i}") for i in range(1000)]
    assert all(1 <= i <= DEFAULT_NUM_FEATURES for i in ids)
    # id 0 reserved for padding/bias
    assert 0 not in ids


def test_mhash_batch_agrees():
    keys = [f"cat#{i}" for i in range(500)]
    b = mhash_batch(keys, num_features=2 ** 20)
    for k, h in zip(keys, b):
        assert mhash(k, num_features=2 ** 20) == int(h)
    assert b.min() >= 1 and b.max() <= 2 ** 20


def test_empty_batch():
    assert murmurhash3_batch([]).shape == (0,)
