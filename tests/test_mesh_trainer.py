"""Sharded training as a product feature (SURVEY.md §3.17 TP row, §8 M3).

The trainers' OWN sparse steps run GSPMD-partitioned via the ``-mesh`` option
— batch over dp, dims-sized state axes over tp — and must match the
single-device model to float tolerance on identical batch streams. This is
the multi-chip path the driver's dryrun exercises; here it runs on the
8-virtual-device CPU mesh (conftest).
"""

import numpy as np
import pytest

from hivemall_tpu.io.sparse import SparseDataset
from hivemall_tpu.models.fm import FFMTrainer
from hivemall_tpu.models.linear import GeneralClassifier
from hivemall_tpu.parallel.mesh import parse_mesh_spec


def _ffm_ds(n=384, L=6, F=8, seed=0):
    rng = np.random.default_rng(seed)
    idx = rng.integers(1, 200, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32) % F, (n, 1))
    val = np.ones((n, L), np.float32)
    w_true = rng.normal(0, 1, 201)
    y = np.sign(w_true[idx].sum(1) + rng.normal(0, 0.1, n)).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L)
    return SparseDataset(idx.ravel(), indptr, val.ravel(), y, fld.ravel())


def _linear_ds(n=512, L=8, seed=1):
    rng = np.random.default_rng(seed)
    idx = rng.integers(1, 300, (n, L)).astype(np.int32)
    val = rng.uniform(0.5, 1.5, (n, L)).astype(np.float32)
    w_true = rng.normal(0, 1, 301)
    y = np.sign((w_true[idx] * val).sum(1)).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L)
    return SparseDataset(idx.ravel(), indptr, val.ravel(), y)


def test_parse_mesh_spec():
    assert parse_mesh_spec("dp=2,tp=4") == (2, 4)
    assert parse_mesh_spec("dp=8") == (8, 1)
    assert parse_mesh_spec("tp=8") == (1, 8)
    assert parse_mesh_spec("auto", n_devices=8) == (8, 1)
    with pytest.raises(ValueError):
        parse_mesh_spec("pp=2")
    with pytest.raises(ValueError):
        parse_mesh_spec("dp=0")


def test_mesh_requires_divisible_batch():
    with pytest.raises(ValueError, match="divisible"):
        FFMTrainer("-dims 1024 -fields 8 -mini_batch 100 -mesh dp=8")


def test_ffm_joint_mesh_matches_single_device():
    ds = _ffm_ds()
    opts = "-dims 4096 -factors 4 -fields 8 -mini_batch 128 -opt adagrad " \
           "-classification"
    single = FFMTrainer(opts).fit(ds, epochs=2)
    sharded = FFMTrainer(opts + " -mesh dp=2,tp=4").fit(ds, epochs=2)
    assert sharded.params["T"].shape == (sharded.Mr, sharded.W)
    np.testing.assert_allclose(np.asarray(single.params["T"]),
                               np.asarray(sharded.params["T"]), atol=1e-4)


def test_fm_minibatch_mesh_matches_single_device():
    """train_fm's round-5 default (minibatch scatter + dense AdaGrad over
    the packed fused table) under GSPMD: the -mesh model must match the
    single-device model on identical batch streams — the scatter into G
    and the dense optimizer pass both partition over (dp, tp)."""
    from hivemall_tpu.models.fm import FMTrainer

    ds = _linear_ds(n=384)
    opts = ("-dims 4096 -factors 4 -mini_batch 128 -opt adagrad "
            "-classification")
    single = FMTrainer(opts).fit(ds, epochs=2)
    sharded = FMTrainer(opts + " -mesh dp=2,tp=4").fit(ds, epochs=2)
    assert single._step is not None
    np.testing.assert_allclose(np.asarray(single.params["T"]),
                               np.asarray(sharded.params["T"]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(single.params["w0"]),
                               np.asarray(sharded.params["w0"]), atol=1e-5)


def test_ffm_ftrl_mesh_matches_single_device():
    ds = _ffm_ds(seed=3)
    opts = "-dims 4096 -factors 4 -fields 8 -mini_batch 128 -opt ftrl " \
           "-classification"
    single = FFMTrainer(opts).fit(ds, epochs=1)
    sharded = FFMTrainer(opts + " -mesh dp=4,tp=2").fit(ds, epochs=1)
    np.testing.assert_allclose(np.asarray(single.params["T"]),
                               np.asarray(sharded.params["T"]), atol=1e-4)


def test_linear_mesh_matches_single_device():
    ds = _linear_ds()
    opts = "-dims 2048 -loss logloss -opt adagrad -reg no -mini_batch 128"
    single = GeneralClassifier(opts).fit(ds, epochs=2)
    sharded = GeneralClassifier(opts + " -mesh dp=2,tp=4").fit(ds, epochs=2)
    np.testing.assert_allclose(single._finalized_weights(),
                               sharded._finalized_weights(), atol=1e-4)
    # scoring works off the sharded state
    p1 = single.predict_proba(ds)
    p2 = sharded.predict_proba(ds)
    np.testing.assert_allclose(p1, p2, atol=1e-4)


def test_sharded_bundle_roundtrip(tmp_path):
    ds = _ffm_ds(seed=5)
    opts = "-dims 4096 -factors 4 -fields 8 -mini_batch 128 -opt adagrad " \
           "-classification -mesh dp=2,tp=4"
    t = FFMTrainer(opts).fit(ds, epochs=1)
    path = str(tmp_path / "ffm_mesh.npz")
    t.save_bundle(path)
    t2 = FFMTrainer(opts)
    t2.load_bundle(path)
    np.testing.assert_allclose(np.asarray(t.params["T"]),
                               np.asarray(t2.params["T"]), atol=0)
    # restored state is re-sharded onto the mesh and trainable
    t2.fit(ds, epochs=1)
    assert np.isfinite(t2.cumulative_loss)


def test_mesh_dp_only_auto():
    ds = _linear_ds(seed=7)
    opts = "-dims 2048 -loss logloss -opt sgd -reg no -mini_batch 128"
    single = GeneralClassifier(opts).fit(ds, epochs=1)
    sharded = GeneralClassifier(opts + " -mesh auto").fit(ds, epochs=1)
    np.testing.assert_allclose(single._finalized_weights(),
                               sharded._finalized_weights(), atol=1e-4)


def test_mesh_with_parquet_stream(tmp_path):
    """Out-of-core streaming composes with GSPMD sharding: the same
    ParquetStream batches train a -mesh FFM trainer and match the
    single-device in-RAM result."""
    pytest.importorskip("pyarrow")
    from hivemall_tpu.io.arrow import ParquetStream, write_parquet_shards

    ds = _ffm_ds(seed=11)
    write_parquet_shards(ds, str(tmp_path / "s"), rows_per_shard=100)
    opts = "-dims 4096 -factors 4 -fields 8 -mini_batch 64 -opt adagrad " \
           "-classification"
    ram = FFMTrainer(opts).fit(ds, epochs=1, shuffle=False)
    stream = ParquetStream(str(tmp_path / "s"))
    sharded = FFMTrainer(opts + " -mesh dp=2,tp=4")
    sharded.fit_stream(stream.batches(64, epochs=1, shuffle=False))
    # same rows, same shard order when unshuffled with one pass
    np.testing.assert_allclose(np.asarray(ram.params["T"]),
                               np.asarray(sharded.params["T"]), atol=1e-3)


def test_mf_mesh_matches_single_device():
    """-mesh on the MF family: dp-sharded batches + tp-sharded P/Q tables
    train to the same model as the unsharded trainer."""
    import numpy as np
    from hivemall_tpu.models.mf import MFAdaGradTrainer
    rng = np.random.default_rng(3)
    n, U, I = 512, 64, 32
    u = rng.integers(0, U, n).astype(np.int32)
    i = rng.integers(0, I, n).astype(np.int32)
    r = (3.0 + 0.5 * rng.normal(0, 1, n)).astype(np.float32)
    opts = (f"-factors 8 -users {U} -items {I} -mini_batch 128 "
            f"-eta0 0.05 -iters 2")
    t0 = MFAdaGradTrainer(opts)
    t0.fit(u, i, r, shuffle=False)
    t1 = MFAdaGradTrainer(opts + " -mesh dp=2,tp=4")
    assert t1.mesh is not None
    t1.fit(u, i, r, shuffle=False)
    P1 = np.asarray(t1.params["P"], np.float32)
    shard = t1.params["P"].sharding.shard_shape(t1.params["P"].shape)
    assert shard[0] == U // 4        # tp=4 row sharding
    np.testing.assert_allclose(np.asarray(t0.params["P"], np.float32), P1,
                               rtol=1e-4, atol=1e-5)
    preds0 = t0.predict(u[:32], i[:32])
    preds1 = t1.predict(u[:32], i[:32])
    np.testing.assert_allclose(preds0, preds1, rtol=1e-4, atol=1e-5)


def test_parts_layout_shards_over_mesh():
    """-ffm_table parts -mesh dp=2,tp=4 (VERDICT r3 next #2): fields shard
    over tp (rank-local slab gathers), batch over dp with a G psum before
    the XLA optimizer tail. Equivalence to the single-chip fused kernel is
    asserted in FUNCTION SPACE (epoch loss + scores): raw T2 entries can
    differ by O(eta) where bf16 gradient rounding flips near-zero grads
    through AdaGrad's G/(|G|+eps)."""
    import numpy as np
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.models.fm import FFMTrainer

    B, L, F, K, dims, n = 256, 8, 8, 16, 1 << 12, 512
    rng = np.random.default_rng(2)
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32), (n, 1))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L, dtype=np.int64)
    ds = SparseDataset(idx.ravel(), indptr, np.ones(n * L, np.float32),
                       lab, fld.ravel())
    cfg = (f"-dims {dims} -factors {K} -fields {F} -mini_batch {B} "
           "-opt adagrad -classification -halffloat -ffm_table parts "
           "-seed 5")
    a = FFMTrainer(cfg)
    a.fit(ds, epochs=1, shuffle=False, prefetch=False)
    b = FFMTrainer(cfg + " -mesh dp=2,tp=4")
    b.fit(ds, epochs=1, shuffle=False, prefetch=False)
    ss = b.params["T2"].sharding.shard_shape(b.params["T2"].shape)
    assert ss[0] == (F * b.MRF * 2) // 4, ss     # tp=4 field partitions
    la, lb = a.cumulative_loss, b.cumulative_loss
    assert abs(la - lb) / max(abs(la), 1e-9) < 1e-3, (la, lb)
    pa = np.asarray(a.predict(ds))
    pb = np.asarray(b.predict(ds))
    assert np.abs(pa - pb).max() < 0.02, np.abs(pa - pb).max()
    # gradient SCALE parity: shard_map transposes psum to psum, so an
    # unowned (replicated) data loss would make every slab cotangent tp-x
    # and the AdaGrad accumulators tp^2-x (~16 here). The S2 ratio is the
    # sharp detector AdaGrad's scale-invariance hides from loss/scores.
    Sa = np.asarray(a.opt_state["T2"]["gg"], np.float64)
    Sb = np.asarray(b.opt_state["T2"]["gg"], np.float64)
    touched = Sa > 1e-12
    med = float(np.median(Sb[touched] / Sa[touched]))
    assert 0.9 < med < 1.1, med


def test_parts_mesh_option_validation():
    import pytest
    from hivemall_tpu.models.fm import FFMTrainer

    with pytest.raises(ValueError, match="divisible by the tp axis"):
        FFMTrainer("-dims 4096 -factors 16 -fields 8 -mini_batch 256 "
                   "-opt adagrad -classification -halffloat "
                   "-ffm_table parts -mesh dp=2,tp=3")
    with pytest.raises(ValueError, match="128\\*dp"):
        FFMTrainer("-dims 4096 -factors 16 -fields 8 -mini_batch 192 "
                   "-opt adagrad -classification -halffloat "
                   "-ffm_table parts -mesh dp=2,tp=4")
