"""Numeric sanitizers (SURVEY.md §6 race-detection/sanitizer analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hivemall_tpu.utils.debug import checked, debug_nans


def test_checked_clean_function_passes():
    f = checked(jax.jit(lambda x: jnp.log1p(jnp.exp(-jnp.abs(x)))))
    out = f(jnp.asarray([0.5, -2.0]))
    assert np.all(np.isfinite(np.asarray(out)))


def test_checked_raises_on_nan():
    f = checked(jax.jit(lambda x: jnp.sqrt(x)))   # sqrt(-1) -> NaN
    with pytest.raises(Exception, match="nan"):
        f(jnp.asarray([-1.0]))


def test_debug_nans_context_restores_flag():
    prev = jax.config.jax_debug_nans
    with debug_nans(True):
        assert jax.config.jax_debug_nans is True
    assert jax.config.jax_debug_nans == prev


def test_linear_step_is_nan_clean():
    """A representative trainer kernel stays finite under checkify."""
    from hivemall_tpu.models.linear import GeneralClassifier
    tr = GeneralClassifier("-dims 128 -mini_batch 8 -opt adagrad "
                           "-loss logloss")
    with debug_nans(True):
        rng = np.random.default_rng(0)
        for i in range(24):
            x = rng.normal(size=3)
            tr.process([f"f{j}:{x[j]:.4f}" for j in range(3)],
                       1 if x.sum() > 0 else -1)
        rows = dict(tr.close())
    assert all(np.isfinite(v) for v in rows.values())
