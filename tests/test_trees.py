"""Trees: RF + GBDT accuracy on separable synthetic data, serialization
roundtrip, tree_predict/rf_ensemble semantics."""

import numpy as np
import pytest

from hivemall_tpu.models.trees import (GradientBoosting,
                                       RandomForestClassifier,
                                       RandomForestRegressor,
                                       XGBoostClassifier,
                                       XGBoostMulticlassClassifier,
                                       XGBoostRegressor, deserialize_tree,
                                       guess_attribute_types, rf_ensemble,
                                       tree_predict)


def two_moons_ish(n=600, seed=0):
    """Nonlinear binary task solvable by axis-aligned splits."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, (n, 4)).astype(np.float32)
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0.3)).astype(int)
    return X, y


def test_rf_classifier_fits_xor():
    X, y = two_moons_ish()
    rf = RandomForestClassifier("-trees 15 -depth 6 -bins 32 -seed 3")
    rf.fit(X, y)
    acc = (rf.predict(X) == y).mean()
    assert acc > 0.95, acc


def test_rf_wide_feature_space_routes_exactly():
    """d > 256 features: the routing decode must take the exact gather
    path (bf16 one-hot matvec rounds integer feature ids above 256 —
    ADVICE r3). Signal lives in a high feature index so a rounded id
    would mis-split and tank accuracy."""
    rng = np.random.default_rng(7)
    n, d = 400, 300
    X = rng.uniform(-1, 1, (n, d)).astype(np.float32)
    y = ((X[:, 290] > 0) ^ (X[:, 299] > 0.2)).astype(int)
    rf = RandomForestClassifier(f"-trees 8 -depth 6 -bins 16 -vars {d} "
                                "-seed 11")
    rf.fit(X, y)
    acc = (rf.predict(X) == y).mean()
    assert acc > 0.9, acc


def test_rf_oob_and_rows():
    X, y = two_moons_ish(300)
    rf = RandomForestClassifier("-trees 5 -depth 5 -bins 32")
    for row, label in zip(X, y):
        rf.process(row, int(label))
    rows = list(rf.close())
    assert len(rows) == 5
    for mid, blob, oob in rows:
        assert 0.0 <= oob <= 0.6
        tree, extra = deserialize_tree(blob)
        assert "classes" in extra


def test_rf_regressor_fits():
    rng = np.random.default_rng(1)
    X = rng.uniform(-1, 1, (500, 3)).astype(np.float32)
    y = np.where(X[:, 0] > 0, 2.0, -1.0) + 0.05 * rng.normal(size=500)
    # -vars 3 = all features per node: with only d=3, the default mtry=d/3=1
    # makes trees too weak for a single-feature step target
    rf = RandomForestRegressor("-trees 10 -depth 4 -bins 32 -vars 3")
    rf.fit(X, y.astype(np.float32))
    rmse = float(np.sqrt(np.mean((rf.predict(X) - y) ** 2)))
    assert rmse < 0.4, rmse


def test_gbdt_binary_beats_chance_and_converges():
    X, y = two_moons_ish(800, seed=5)
    gb = XGBoostClassifier("-num_round 25 -max_depth 4 -eta 0.3 -bins 32")
    gb.fit(X, y)
    p = gb.predict(X)
    acc = ((p > 0.5).astype(int) == y).mean()
    assert acc > 0.97, acc


def test_gbdt_regression():
    rng = np.random.default_rng(2)
    X = rng.uniform(-2, 2, (600, 3)).astype(np.float32)
    y = np.sin(X[:, 0]) * 2 + X[:, 1]
    gb = XGBoostRegressor("-num_round 40 -max_depth 4 -eta 0.2 -bins 64")
    gb.fit(X, y.astype(np.float32))
    rmse = float(np.sqrt(np.mean((gb.predict(X) - y) ** 2)))
    assert rmse < 0.35, rmse


def test_xgb_multiclass():
    rng = np.random.default_rng(3)
    X = rng.uniform(-1, 1, (600, 2)).astype(np.float32)
    y = (X[:, 0] > 0).astype(int) + 2 * (X[:, 1] > 0).astype(int)  # 4 classes
    gb = XGBoostMulticlassClassifier("-num_round 12 -max_depth 3 -eta 0.5")
    gb.fit(X, y)
    acc = (gb.predict(X) == y).mean()
    assert acc > 0.95, acc


def test_gbdt_udtf_blob_roundtrip_and_tree_predict():
    X, y = two_moons_ish(300, seed=7)
    gb = XGBoostClassifier("-num_round 5 -max_depth 3")
    for row, label in zip(X, y):
        gb.process(row, float(label))
    blobs = list(gb.close())
    assert len(blobs) == 5
    # margin assembled from per-tree tree_predict matches decision_function
    x0 = X[:3]
    manual = np.zeros(3)
    for _, blob in blobs:
        for i in range(3):
            manual[i] += gb.eta * tree_predict(blob, x0[i])
    np.testing.assert_allclose(manual, gb.decision_function(x0), rtol=1e-5)


def test_rf_tree_predict_and_ensemble():
    X, y = two_moons_ish(300, seed=9)
    rf = RandomForestClassifier("-trees 7 -depth 5 -bins 32")
    rf.fit(X, y)
    rows = list(rf.close())
    votes = [tree_predict(blob, X[0]) for _, blob, _ in rows]
    label, prob, dist = rf_ensemble(votes)
    assert label in (0, 1)
    assert 0.5 <= prob <= 1.0
    assert abs(sum(dist) - 1.0) < 1e-9


def test_guess_attribute_types():
    assert guess_attribute_types(1.5, "tokyo", 3) == "Q,C,Q"


def test_multiclass_gbt_blob_prediction_assembles():
    """Multiclass blobs carry (cls, leaf): the SQL group-by-class pattern
    reconstructs the trainer's own prediction."""
    import numpy as np
    from hivemall_tpu.models.trees import (XGBoostMulticlassClassifier,
                                           tree_model_meta, tree_predict)
    rng = np.random.default_rng(5)
    X = rng.normal(size=(150, 4)).astype(np.float32)
    y = np.argmax(X[:, :3], axis=1)
    gb = XGBoostMulticlassClassifier("-num_round 4 -max_depth 3")
    for i in range(len(X)):
        gb.process(list(X[i]), int(y[i]))
    rows = list(gb.close())
    assert len(rows) == 4 * 3
    eta = tree_model_meta(rows[0][1])["eta"]
    direct = gb.predict(X[:20])
    for i in range(20):
        margins = {}
        for _, blob in rows:
            cls, leaf = tree_predict(blob, list(X[i]))
            margins[cls] = margins.get(cls, 0.0) + eta * leaf
        assert max(margins, key=margins.get) == direct[i]


def test_gbt_fit_then_close_serializes(tmp_path):
    import numpy as np
    from hivemall_tpu.models.trees import XGBoostClassifier
    rng = np.random.default_rng(6)
    X = rng.normal(size=(80, 3)).astype(np.float32)
    y = (X[:, 0] > 0).astype(int)
    gb = XGBoostClassifier("-num_round 3 -max_depth 3").fit(X, y)
    rows = list(gb.close())          # no process() buffer: must not refit
    assert len(rows) == 3


def test_rf_poisson_bootstrap_converges():
    """-bootstrap poisson: device-generated Poisson(1) counts replace the
    host multinomial (streaming-bootstrap approximation) — accuracy and
    OOB behavior must hold; -bootstrap validates its value."""
    import pytest

    X, y = two_moons_ish(500, seed=2)
    rf = RandomForestClassifier("-trees 10 -depth 6 -bins 32 -seed 3 "
                                "-bootstrap poisson")
    rf.fit(X, y)
    acc = (rf.predict(X) == y).mean()
    assert acc > 0.93, acc
    assert all(0.0 <= e <= 0.6 for e in rf.oob_errors)
    rr = RandomForestRegressor("-trees 8 -depth 4 -bins 32 -vars 4 "
                               "-bootstrap poisson")
    rng = np.random.default_rng(1)
    Xr = rng.uniform(-1, 1, (400, 4)).astype(np.float32)
    yr = np.where(Xr[:, 0] > 0, 2.0, -1.0).astype(np.float32)
    rr.fit(Xr, yr)
    rmse = float(np.sqrt(np.mean((rr.predict(Xr) - yr) ** 2)))
    assert rmse < 0.5, rmse
    with pytest.raises(ValueError, match="exact|poisson"):
        RandomForestClassifier("-trees 2 -bootstrap wild").fit(X, y)


def test_nan_binning_fit_predict_roundtrip():
    """NaN must take the SAME bin code at fit time (quantize_bins /
    bin_columns_native over the full inf-padded edge row -> n_bins-1) and
    at raw-predict time (bin_raw). Columns with few distinct values
    produce duplicate quantile edges, which is exactly where a truncated
    edge search would code NaN differently (ADVICE r4 #1)."""
    from hivemall_tpu.ops.trees import bin_raw, quantize_bins

    rng = np.random.default_rng(0)
    # col 0: only 3 distinct values -> heavy edge duplication after unique()
    X = np.stack([rng.choice([0.0, 1.0, 2.0], 400),
                  rng.normal(size=400)], axis=1).astype(np.float32)
    X[::7, 0] = np.nan
    X[::11, 1] = np.nan
    codes, edges = quantize_bins(X, n_bins=64)
    codes2 = bin_raw(X, edges)
    np.testing.assert_array_equal(codes, codes2)
    assert (codes[::7, 0] == 63).all()

    # e2e: a model trained with NaNs routes the same rows to the same
    # leaves through predict (fit-time codes vs raw-predict codes)
    y = np.where(np.nan_to_num(X[:, 1], nan=5.0) > 0, 1, 0)
    rf = RandomForestClassifier("-trees 5 -depth 5 -bins 32 -seed 1")
    rf.fit(X, y)
    acc = (rf.predict(X) == y).mean()
    assert acc > 0.9, acc


def test_staged_matrix_fits_match_raw():
    """StagedMatrix (pre-binned device-staged X, the DMatrix analog) must
    train the same models as raw-X fits for RF, GBT, and multiclass —
    same seeds, same bins => identical trees."""
    from hivemall_tpu.models.trees import StagedMatrix, XGBoostMulticlassClassifier

    X, y = two_moons_ish(500, seed=4)
    Xs = StagedMatrix.stage(X, 32)
    a = RandomForestClassifier("-trees 6 -depth 5 -bins 32 -seed 3").fit(X, y)
    b = RandomForestClassifier("-trees 6 -depth 5 -bins 32 -seed 3").fit(Xs, y)
    np.testing.assert_array_equal(a.tree.feat, b.tree.feat)
    np.testing.assert_allclose(a.tree.thr, b.tree.thr)
    np.testing.assert_array_equal(a.predict(X), b.predict(X))

    Xs64 = StagedMatrix.stage(X, 64)
    ga = XGBoostClassifier("-num_round 4 -max_depth 4 -seed 5").fit(X, y)
    gb = XGBoostClassifier("-num_round 4 -max_depth 4 -seed 5").fit(Xs64, y)
    np.testing.assert_allclose(ga.decision_function(X),
                               gb.decision_function(X), rtol=1e-6)

    rng = np.random.default_rng(2)
    Xm = rng.normal(size=(300, 4)).astype(np.float32)
    ym = rng.integers(0, 3, 300)
    ma = XGBoostMulticlassClassifier("-num_round 3 -max_depth 3").fit(Xm, ym)
    mb = XGBoostMulticlassClassifier("-num_round 3 -max_depth 3").fit(
        StagedMatrix.stage(Xm, 64), ym)
    np.testing.assert_array_equal(ma.predict(Xm), mb.predict(Xm))

    rr = RandomForestRegressor("-trees 4 -depth 4 -bins 32")
    yr = X[:, 0].astype(np.float32)
    ra = RandomForestRegressor("-trees 4 -depth 4 -bins 32").fit(X, yr)
    rb = RandomForestRegressor("-trees 4 -depth 4 -bins 32").fit(Xs, yr)
    np.testing.assert_allclose(ra.predict(X), rb.predict(X), rtol=1e-6)

    with pytest.raises(ValueError, match="n_bins"):
        RandomForestClassifier("-trees 2 -bins 64").fit(Xs, y)   # staged 32


def test_nominal_categorical_split_beats_ordinal():
    """-attrs C (SURVEY §3.9): y = [x2 == 30] with category 30 in the
    MIDDLE of the value order. A depth-1 ordinal threshold can only cut
    the order into a prefix/suffix (best acc ~0.8 here); the nominal
    one-hot membership column makes the perfect split reachable in one
    level. The expander must ride predict AND serialized tree blobs."""
    from hivemall_tpu.models.trees import tree_predict

    rng = np.random.default_rng(0)
    n = 600
    cats = rng.choice([10.0, 20.0, 30.0, 40.0, 50.0], n)
    noise = rng.normal(size=n).astype(np.float32)
    X = np.stack([noise, cats], axis=1).astype(np.float32)
    y = (cats == 30.0).astype(int)

    ordinal = RandomForestClassifier(
        "-trees 5 -depth 1 -bins 32 -seed 3 -vars 2").fit(X, y)
    acc_ord = (ordinal.predict(X) == y).mean()
    assert acc_ord < 0.99, acc_ord         # prefix cut can't isolate {30}

    nominal = RandomForestClassifier(
        "-trees 5 -depth 1 -bins 32 -seed 3 -vars 6 -attrs Q,C").fit(X, y)
    acc_nom = (nominal.predict(X) == y).mean()
    assert acc_nom == 1.0, acc_nom

    # serialized blob round trip carries the expander
    blob = next(iter(nominal.close()))[1]
    row = [0.3, 30.0]
    assert tree_predict(blob, row, True) == 1
    assert tree_predict(blob, [0.3, 40.0], True) == 0

    # regressor path + validation errors
    yr = np.where(cats == 30.0, 5.0, -1.0).astype(np.float32)
    rr = RandomForestRegressor(
        "-trees 4 -depth 1 -bins 32 -vars 6 -attrs Q,C").fit(X, yr)
    rmse = float(np.sqrt(np.mean((rr.predict(X) - yr) ** 2)))
    assert rmse < 0.2, rmse

    with pytest.raises(ValueError, match="attrs"):
        RandomForestClassifier("-trees 2 -attrs Q").fit(X, y)
    from hivemall_tpu.models.trees import StagedMatrix
    with pytest.raises(ValueError, match="StagedMatrix"):
        RandomForestClassifier("-trees 2 -attrs Q,C").fit(
            StagedMatrix.stage(X, 64), y)


def test_oob_from_builder_nodes_matches_repredict():
    """Round 5: OOB error comes from the builder's own row routing
    (return_nodes) instead of re-predicting the forest — both paths must
    agree exactly (same tree, same bins, same leaf values)."""
    import jax.numpy as jnp

    from hivemall_tpu.ops.trees import predict_bins_device, quantize_bins

    X, y = two_moons_ish(400, seed=6)
    rf = RandomForestClassifier("-trees 6 -depth 5 -bins 32 -seed 3")
    rf.fit(X, y)
    # recompute OOB the old way from the serialized model + train bins
    bins, _ = quantize_bins(X, 32)
    w = rf._bootstrap(len(y), 6, np.random.default_rng(3))
    # _bootstrap(exact) consumed the same rng stream inside fit; rebuild
    # it the same way fit did (seed -> quantize uses no rng)
    labels = np.asarray(y)
    yy = np.searchsorted(np.unique(labels), labels)
    preds = predict_bins_device(rf.tree, jnp.asarray(bins))
    pe = np.asarray(preds.argmax(-1))
    oob = np.asarray(w) == 0
    n_oob = np.maximum(oob.sum(1), 1)
    err = ((pe != yy[None, :]) & oob).sum(1) / n_oob
    err = np.where(oob.sum(1) == 0, 0.0, err)
    np.testing.assert_allclose(rf.oob_errors, err, atol=1e-12)
