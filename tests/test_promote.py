"""Gated model promotion (docs/RELIABILITY.md "Promotion and rollback"):
the PROMOTED pointer protocol in io.checkpoint, the PromotionGate /
CanaryBake / PromotionController math in serve.promote, the engine's
pointer-follow mode + corrupt-bundle skip-cache regression fix, and the
fleet canary/rollback/recovery lifecycle — against real in-process
PredictServers as replicas (cheap: no worker processes; the full
multi-process canary under live traffic is pinned by the promotion smoke
in run_tests.sh, and the SIGKILL-the-manager scenario by the `slow` test
at the bottom)."""

import json
import os
import shutil
import urllib.request

import numpy as np
import pytest

from hivemall_tpu.io import checkpoint as ck

OPTS = "-dims 1024 -loss logloss -opt adagrad -mini_batch 32"


@pytest.fixture()
def trained(tmp_path):
    from hivemall_tpu.io.libsvm import synthetic_classification
    from hivemall_tpu.models.linear import GeneralClassifier
    ds, _ = synthetic_classification(200, 64, seed=11)
    t = GeneralClassifier(OPTS)
    t.fit(ds)
    path = os.path.join(tmp_path, f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(path)
    return t, ds, str(tmp_path), path


def _save_next(trainer, ckdir, ds=None, bump=0):
    """Save the trainer's state as the next candidate bundle (optionally
    after more training / a step bump)."""
    if ds is not None:
        trainer.fit(ds)
    if bump:
        trainer._t += bump
    path = os.path.join(ckdir, f"{trainer.NAME}-step{trainer._t:010d}.npz")
    trainer.save_bundle(path)
    return path


def _poisoned(ckdir, base_path, bump=5):
    """A deliberately-poisoned candidate: the promoted weights scaled and
    shifted (diverged-learning-rate shape) at a higher step."""
    import jax.numpy as jnp
    from hivemall_tpu.models.linear import GeneralClassifier
    bad = GeneralClassifier(OPTS)
    bad.load_bundle(base_path)
    bad.w = jnp.asarray(np.asarray(bad.w) * 25.0 + 3.0)
    bad._t += bump
    path = os.path.join(ckdir, f"{bad.NAME}-step{bad._t:010d}.npz")
    bad.save_bundle(path)
    return path


def _rows_of(ds, n):
    out = []
    for i in range(n):
        idx, val = ds.row(i)
        out.append([f"{int(a)}:{float(v)!r}" for a, v in zip(idx, val)])
    return out


# --- pointer protocol --------------------------------------------------------

def test_pointer_promote_finalize_rollback(trained):
    t, ds, ckdir, pA = trained
    stepA = t._t
    m = ck.promote_bundle(ckdir, pA)
    assert m["current"]["step"] == stepA and m["state"] == "serving"
    assert m["current"]["digest"] and m["current"]["trainer"] == t.NAME
    assert ck.promoted_bundle(ckdir, t.NAME) == (stepA, pA)
    pB = _save_next(t, ckdir, ds)
    m = ck.promote_bundle(ckdir, pB, state="canary",
                          gate={"verdict": "pass"})
    assert m["state"] == "canary"
    assert m["history"][0]["step"] == stepA     # rollback target
    assert ck.read_promoted(ckdir)["current"]["gate"]["verdict"] == "pass"
    assert ck.finalize_promotion(ckdir)["state"] == "serving"
    m = ck.rollback_promoted(ckdir, "injected burn")
    assert m["current"]["step"] == stepA and m["rollbacks"] == 1
    assert m["last_rollback"]["from"]["step"] == t._t
    assert m["last_rollback"]["reason"] == "injected burn"
    assert ck.promoted_bundle(ckdir, t.NAME) == (stepA, pA)
    # nothing older: a second rollback refuses
    assert ck.rollback_promoted(ckdir, "again") is None
    # wrong trainer name never resolves
    assert ck.promoted_bundle(ckdir, "train_ffm") is None


def test_reject_marker_roundtrip(trained):
    _, _, ckdir, pA = trained
    assert not ck.is_rejected(pA)
    marker = ck.reject_bundle(pA, "poisoned shard")
    assert os.path.exists(marker) and ck.is_rejected(pA)
    assert ck.rejected_reason(pA) == "poisoned shard"


def test_retention_never_deletes_promoted_or_rollback_target(tmp_path):
    """Satellite: keep=2 across 10 saves while the pointer pins save 3
    (and later a rollback target) — pinned bundles survive GC."""
    from hivemall_tpu.io.libsvm import synthetic_classification
    from hivemall_tpu.models.linear import GeneralClassifier
    ds, _ = synthetic_classification(64, 16, seed=3)
    t = GeneralClassifier("-dims 256 -loss logloss -mini_batch 16")
    mgr = ck.CheckpointManager(str(tmp_path), t.NAME, keep=2, every=1)
    saved = []
    for i in range(10):
        t.fit(ds)
        saved.append(mgr.save(t))
        if i == 2:                       # promote save 3
            ck.promote_bundle(str(tmp_path), saved[2])
        if i == 5:                       # save 6 promoted: save 3 becomes
            ck.promote_bundle(str(tmp_path), saved[5])   # rollback target
    live = set(ck.list_bundles(str(tmp_path), t.NAME))
    assert saved[2] in live, "rollback target was GC'd"
    assert saved[5] in live, "promoted bundle was GC'd"
    assert saved[8] in live and saved[9] in live      # the k=2 window
    for gone in (saved[0], saved[1], saved[3], saved[4], saved[6],
                 saved[7]):
        assert gone not in live
    # rollback: the pinned save 3 must still load bit-exact
    m = ck.rollback_promoted(str(tmp_path), "bad save 6")
    assert m["current"]["bundle"] == os.path.basename(saved[2])
    fresh = GeneralClassifier("-dims 256 -loss logloss -mini_batch 16")
    fresh.load_bundle(saved[2])          # digest-validated


def test_prune_removes_orphaned_reject_markers(tmp_path):
    from hivemall_tpu.io.libsvm import synthetic_classification
    from hivemall_tpu.models.linear import GeneralClassifier
    ds, _ = synthetic_classification(64, 16, seed=3)
    t = GeneralClassifier("-dims 256 -loss logloss -mini_batch 16")
    mgr = ck.CheckpointManager(str(tmp_path), t.NAME, keep=1, every=1)
    t.fit(ds)
    first = mgr.save(t)
    ck.reject_bundle(first, "bad")
    t.fit(ds)
    mgr.save(t)
    assert not os.path.exists(first)
    assert not os.path.exists(first + ".rejected")


# --- engine: follow the pointer, skip-cache regression -----------------------

def _engine(ckdir, **kw):
    from hivemall_tpu.serve.engine import PredictEngine
    kw.setdefault("warmup", False)
    return PredictEngine("train_classifier", OPTS, checkpoint_dir=ckdir,
                         **kw)


def test_engine_follows_pointer_not_newest(trained):
    from hivemall_tpu.io.sparse import SparseDataset
    t, ds, ckdir, pA = trained
    stepA = t._t
    refA = np.asarray(t.predict_proba(ds), np.float32)
    pB = _save_next(t, ckdir, ds)
    ck.promote_bundle(ckdir, pA)         # pointer at the OLDER bundle
    eng = _engine(ckdir, follow="promoted")
    assert eng.model_step == stepA, "promoted-follow served the newest"
    assert eng.poll() is False           # pointer unchanged: no churn
    ck.promote_bundle(ckdir, pB)
    assert eng.poll() is True and eng.model_step == t._t
    # rollback = the pointer moves BACKWARD; the engine must follow and
    # restore bit-identical scores to the pre-canary bundle
    ck.rollback_promoted(ckdir, "bake failed")
    assert eng.poll() is True and eng.model_step == stepA
    rows = _rows_of(ds, 9)
    got = eng.predict_rows([eng.parse(r) for r in rows])
    assert np.array_equal(got, refA[:9])


def test_engine_boots_stable_side_during_canary(trained):
    """While the pointer is in state "canary" its current entry is an
    UNBAKED candidate — an engine booting on its own (a respawned
    replica) must serve the prior stable entry; canary membership is an
    explicit manager /reload, never a side effect of churn."""
    t, ds, ckdir, pA = trained
    stepA = t._t
    ck.promote_bundle(ckdir, pA)
    pB = _save_next(t, ckdir, ds)
    ck.promote_bundle(ckdir, pB, state="canary")
    eng = _engine(ckdir, follow="promoted")
    assert eng.model_step == stepA       # the stable side, not the canary
    assert eng.poll() is False
    ck.finalize_promotion(ckdir)         # bake completed: candidate is
    assert eng.poll() is True            # now THE promoted model
    assert eng.model_step == t._t


def test_engine_promoted_bootstraps_from_newest_without_pointer(trained):
    t, _, ckdir, _ = trained
    eng = _engine(ckdir, follow="promoted")
    assert eng.model_step == t._t        # no pointer yet: newest usable
    with pytest.raises(ValueError, match="follow mode"):
        _engine(ckdir, follow="nonsense")


def test_engine_skip_cache_reexamines_rewritten_bundle(trained):
    """Regression (ISSUE 10 satellite): the corrupt-bundle skip memo was
    keyed by mtime alone, so a bundle rewritten IN PLACE with a
    preserved mtime was never re-examined. Now keyed by (mtime, size)
    with a head/tail digest fallback on full collision."""
    t, ds, ckdir, pA = trained
    eng = _engine(ckdir)
    bad = os.path.join(ckdir, f"{t.NAME}-step{t._t + 99:010d}.npz")
    with open(bad, "wb") as f:
        f.write(b"not a bundle" * 64)
    st = os.stat(bad)
    assert eng.poll() is False and eng.reload_failures == 1
    assert eng.poll() is False and eng.reload_failures == 1   # memo holds
    # rewrite in place with VALID content, mtime preserved (size differs):
    # the old mtime-only memo would skip this forever
    shutil.copy(pA, bad)
    os.utime(bad, (st.st_atime, st.st_mtime))
    assert eng.poll() is True, "rewritten-in-place bundle never re-read"
    assert eng.reloads == 1              # (its META step is A's: 7)
    # (mtime, size) full collision: different bytes, same size AND mtime
    # — the content-tag fallback must still re-examine
    bad2 = os.path.join(ckdir, f"{t.NAME}-step{t._t + 200:010d}.npz")
    with open(bad2, "wb") as f:
        f.write(b"A" * 5000)
    st2 = os.stat(bad2)
    eng.poll()
    n = eng.reload_failures
    with open(bad2, "wb") as f:
        f.write(b"B" * 5000)
    os.utime(bad2, (st2.st_atime, st2.st_mtime))
    eng.poll()
    assert eng.reload_failures == n + 1, "collided rewrite not re-examined"
    eng.poll()
    assert eng.reload_failures == n + 1   # unchanged content: memo holds


def test_engine_skips_quarantined_bundles(trained):
    t, ds, ckdir, pA = trained
    stepA = t._t
    pB = _save_next(t, ckdir, ds)
    ck.reject_bundle(pB, "failed the gate")
    eng = _engine(ckdir)                 # newest-wins mode
    assert eng.model_step == stepA, "quarantined bundle was served"
    assert eng.poll() is False and eng.reload_failures == 0


# --- the gate ----------------------------------------------------------------

def test_gate_blocks_injected_logloss_regression(trained):
    from hivemall_tpu.serve.promote import PromotionGate
    t, ds, ckdir, pA = trained
    gate = PromotionGate("train_classifier", OPTS, holdout=ds)
    pBad = _poisoned(ckdir, pA)
    report = gate.evaluate(pBad, pA)
    assert report["verdict"] == "fail"
    assert any("logloss regressed" in r for r in report["reasons"])
    assert report["checks"]["logloss"] > report["checks"][
        "baseline_logloss"] + 0.05
    # a genuinely-better candidate passes the same gate
    pGood = _save_next(t, ckdir, ds)
    report = gate.evaluate(pGood, pA)
    assert report["verdict"] == "pass" and not report["reasons"]
    assert gate.counters() == {"candidates": 2, "gate_passes": 1,
                               "gate_failures": 1, "arena_published": 1,
                               "last_verdict": "pass"}


def test_gate_corrupt_candidate_fails(trained):
    from hivemall_tpu.serve.promote import PromotionGate
    t, ds, ckdir, pA = trained
    gate = PromotionGate("train_classifier", OPTS, holdout=ds)
    bad = os.path.join(ckdir, f"{t.NAME}-step{t._t + 9:010d}.npz")
    with open(bad, "wb") as f:
        f.write(b"torn mid-write")
    report = gate.evaluate(bad, pA)
    assert report["verdict"] == "fail"
    assert any("unusable" in r for r in report["reasons"])


def test_gate_calibration_drift_flagged_by_driftwatch(trained):
    """Satellite: calibration drift is flagged by the shared DriftWatch
    changefinder — a gap individually under the absolute bound still
    fails when it breaks the history of admitted candidates. Every
    OTHER guardrail is disabled here so the changefinder is the only
    judge (it only sees candidates that pass the explicit checks)."""
    from hivemall_tpu.serve.promote import PromotionGate
    t, ds, ckdir, pA = trained
    gate = PromotionGate("train_classifier", OPTS, holdout=ds,
                         max_logloss_increase=None,
                         max_auc_decrease=None,
                         max_score_shift=None,
                         max_calibration_gap=None,   # absolute check off:
                         drift_warmup=4, drift_sigma=1.0)   # drift only
    rng = np.random.default_rng(5)
    for _ in range(24):                  # history of well-calibrated
        ev = gate._calibration_drift(0.02 + rng.uniform(-0.005, 0.005))
        assert ev is None
    pBad = _poisoned(ckdir, pA)          # saturated probs: gap ~0.5
    report = gate.evaluate(pBad, pA)
    assert report["verdict"] == "fail"
    assert any("calibration drift" in r for r in report["reasons"]), \
        report["reasons"]
    assert report["checks"].get("calibration_drift") is not None


def test_gate_drift_baseline_sees_only_admitted_candidates(trained):
    """A candidate rejected on OTHER guardrails must not feed (and so
    pollute) the calibration changefinder's admitted-history baseline."""
    from hivemall_tpu.serve.promote import PromotionGate
    t, ds, ckdir, pA = trained
    gate = PromotionGate("train_classifier", OPTS, holdout=ds)
    pBad = _poisoned(ckdir, pA)          # fails logloss/AUC/shift
    gate.evaluate(pBad, pA)
    assert gate.calibration_watch.n == 0
    gate.evaluate(pA, pA)                # passes: gap joins the history
    assert gate.calibration_watch.n == 1


def test_gate_nonfinite_baseline_degrades_to_absolute_checks(trained):
    """A NaN-scoring BASELINE must not vacuously pass candidates (NaN
    comparisons are all False) — the gate degrades to absolute-only
    checks and records it."""
    import jax.numpy as jnp
    from hivemall_tpu.models.linear import GeneralClassifier
    from hivemall_tpu.serve.promote import PromotionGate
    t, ds, ckdir, pA = trained
    nan = GeneralClassifier(OPTS)
    nan.load_bundle(pA)
    nan.w = jnp.asarray(np.full_like(np.asarray(nan.w), np.nan))
    pNan = os.path.join(ckdir, f"{nan.NAME}-step{nan._t + 1:010d}.npz")
    nan.save_bundle(pNan)
    gate = PromotionGate("train_classifier", OPTS, holdout=ds)
    # a POISONED candidate against the NaN baseline: the absolute
    # calibration check must still catch it
    pBad = _poisoned(ckdir, pA, bump=7)
    report = gate.evaluate(pBad, pNan)
    assert report["checks"].get("baseline_nonfinite") is True
    assert report["verdict"] == "fail", report
    # and a NaN CANDIDATE fails outright
    report = gate.evaluate(pNan, pA)
    assert report["verdict"] == "fail"
    assert any("not finite" in r for r in report["reasons"])


def test_gate_shadow_scores_mirrored_traffic(trained):
    """The batcher tee mirrors live rows into the ShadowBuffer off the
    request path; the gate compares candidate vs baseline score
    distributions on them."""
    from hivemall_tpu.serve.batcher import MicroBatcher
    from hivemall_tpu.serve.promote import PromotionGate, ShadowBuffer
    t, ds, ckdir, pA = trained
    shadow = ShadowBuffer(capacity=64)
    mb = MicroBatcher(lambda rows: np.zeros(len(rows), np.float32),
                      max_delay_ms=0.5)
    mb.set_tee(shadow.add)
    parsed = [t._parse_row(r) for r in _rows_of(ds, 40)]
    futs = [mb.submit([p]) for p in parsed]
    for f in futs:
        f.result(timeout=5)
    mb.close()
    assert shadow.mirrored == 40 and len(shadow.rows()) == 40
    gate = PromotionGate("train_classifier", OPTS, shadow=shadow,
                         min_shadow_rows=16)
    pBad = _poisoned(ckdir, pA)
    report = gate.evaluate(pBad, pA)
    assert report["verdict"] == "fail"
    assert any("shadow score distribution shifted" in r
               for r in report["reasons"]), report["reasons"]
    assert report["checks"]["shadow_rows"] == 40
    # the good twin of the same bundle: no shift on the same traffic
    report = gate.evaluate(pA, pA)
    assert report["verdict"] == "pass"
    # a buffer past capacity drops (counted), never grows
    shadow.add(parsed * 2)
    assert len(shadow.rows()) == 64 and shadow.dropped > 0


# --- canary bake math --------------------------------------------------------

def _totals(req, bad=0, lat_s=0.0, lat_n=0, score=(0.0, 0.0, 0)):
    return {"requests": req, "errors": bad,
            "latency": {"sum": lat_s, "count": lat_n},
            "score_sum": score[0], "score_sumsq": score[1],
            "score_n": score[2]}


def test_canary_bake_pass_and_failures():
    from hivemall_tpu.serve.promote import CanaryBake
    kw = dict(bake_seconds=5.0, min_requests=10,
              max_bad_frac_increase=0.05, max_latency_factor=2.0,
              latency_floor_ms=10.0)
    b = CanaryBake(**kw)
    b.start(_totals(100, 0, 1.0, 100), _totals(300, 0, 3.0, 300), now=0.0)
    # under min_requests: no verdict either way
    assert b.update(_totals(105, 0, 1.05, 105),
                    _totals(330, 0, 3.3, 330), now=1.0) is None
    # healthy canary, window elapsed: pass
    assert b.update(_totals(160, 0, 1.6, 160),
                    _totals(500, 0, 5.0, 500), now=6.0) == "pass"
    # latency regression: fail with the reason
    b = CanaryBake(**kw)
    b.start(_totals(100, 0, 1.0, 100), _totals(300, 0, 3.0, 300), now=0.0)
    v = b.update(_totals(160, 0, 16.0, 160),
                 _totals(500, 0, 5.0, 500), now=1.0)
    assert v.startswith("fail:") and "latency" in v
    # error-rate regression
    b = CanaryBake(**kw)
    b.start(_totals(100), _totals(300), now=0.0)
    v = b.update(_totals(160, 30), _totals(500, 0), now=1.0)
    assert v.startswith("fail:") and "bad-fraction" in v
    # score-mean shift vs the stable cohort
    b = CanaryBake(**kw, max_score_shift=3.0, score_shift_floor=0.05)
    b.start(_totals(100, score=(50.0, 25.5, 100)),
            _totals(300, score=(150.0, 76.0, 300)), now=0.0)
    v = b.update(_totals(200, score=(140.0, 106.0, 200)),
                 _totals(600, score=(300.0, 152.0, 600)), now=1.0)
    assert v.startswith("fail:") and "score mean" in v
    # an idle canary (never reaches min_requests) passes at max_bake
    b = CanaryBake(**kw, max_bake_seconds=30.0)
    b.start(_totals(0), _totals(0), now=0.0)
    assert b.update(_totals(2), _totals(5), now=10.0) is None
    assert b.update(_totals(2), _totals(5), now=31.0) == "pass"
    # a cohort counter RESET (replica respawn mid-bake — possibly killed
    # by the candidate) voids the window: the bake restarts instead of
    # clamping to an "idle" no-evidence pass at max_bake
    b = CanaryBake(**kw, max_bake_seconds=30.0)
    b.start(_totals(500, 0, 5.0, 500), _totals(900, 0, 9.0, 900), now=0.0)
    assert b.update(_totals(30, 0, 0.3, 30),         # canary respawned
                    _totals(950, 0, 9.5, 950), now=31.0) is None
    assert b.resets == 1
    assert b.started_at == 31.0                      # window re-opened
    # the restarted window judges honestly from the new base
    assert b.update(_totals(90, 0, 0.9, 90),
                    _totals(1100, 0, 11.0, 1100), now=37.0) == "pass"


# --- controller --------------------------------------------------------------

def test_controller_gates_quarantines_and_promotes(trained):
    from hivemall_tpu.serve.promote import (PromotionController,
                                            PromotionGate, promotion_stub)
    t, ds, ckdir, pA = trained
    gate = PromotionGate("train_classifier", OPTS, holdout=ds)
    ctrl = PromotionController(ckdir, gate)
    # bootstrap: first candidate promotes on absolute checks
    r = ctrl.check_once()
    assert r["promoted"] is True
    assert ck.promoted_bundle(ckdir, t.NAME) == (t._t, pA)
    assert ctrl.check_once() is None     # nothing new
    pBad = _poisoned(ckdir, pA)
    r = ctrl.check_once()
    assert r["promoted"] is False and ck.is_rejected(pBad)
    assert ck.promoted_bundle(ckdir, t.NAME)[1] == pA   # still serving A
    assert ctrl.check_once() is None     # quarantined: never retried
    pGood = _save_next(t, ckdir, ds, bump=10)   # step past the reject
    r = ctrl.check_once()
    assert r["promoted"] is True
    assert ck.promoted_bundle(ckdir, t.NAME)[1] == pGood
    sec = ctrl.obs_section()
    assert sec["configured"] and sec["promotions"] == 2
    assert sec["quarantined"] == 1 and sec["gate_failures"] == 1
    assert set(sec) == set(promotion_stub())


def test_http_promotion_endpoint(trained):
    from hivemall_tpu.serve.http import PredictServer
    t, ds, ckdir, pA = trained
    ck.promote_bundle(ckdir, pA, gate={"verdict": "pass"})
    srv = PredictServer(_engine(ckdir, follow="promoted"), port=0,
                        watch=False, slo=False).start()
    try:
        out = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/promotion", timeout=10).read())
        assert out["configured"] is True
        assert out["follow"] == "promoted"
        assert out["promoted_step"] == t._t
        assert out["manifest"]["current"]["gate"]["verdict"] == "pass"
    finally:
        srv.stop()


# --- fleet canary lifecycle (in-process replicas) ----------------------------

class _FakeProc:
    def poll(self):
        return None

    def terminate(self):
        pass

    def kill(self):
        pass

    def wait(self, timeout=None):
        return 0


def _replica_server(ckdir):
    from hivemall_tpu.serve.engine import PredictEngine
    from hivemall_tpu.serve.http import PredictServer
    eng = PredictEngine("train_classifier", OPTS, checkpoint_dir=ckdir,
                        warmup=False, follow="promoted")
    return PredictServer(eng, port=0, max_delay_ms=1.0, watch=False,
                         slo=False).start()


def _manager(ckdir, servers, **kw):
    """A promote-mode ReplicaManager over in-process replica servers
    (no worker spawn — check_and_roll is driven by hand)."""
    from hivemall_tpu.serve.fleet import ReplicaManager, _Replica
    kw.setdefault("bake_opts", {"bake_seconds": 0.0, "min_requests": 0,
                                "max_bake_seconds": 0.0})
    mgr = ReplicaManager("train_classifier", OPTS, checkpoint_dir=ckdir,
                         replicas=len(servers), promote=True, **kw)
    for i, srv in enumerate(servers):
        r = _Replica(f"t{i}", _FakeProc(), i)
        r.port = srv.port
        r.model_step = srv.engine.model_step
        mgr._replicas[r.rid] = r
    return mgr


@pytest.fixture()
def fleet2(trained):
    t, ds, ckdir, pA = trained
    ck.promote_bundle(ckdir, pA)
    servers = [_replica_server(ckdir) for _ in range(2)]
    yield t, ds, ckdir, pA, servers
    for srv in servers:
        srv.stop()


def test_fleet_gate_canary_promote_and_injected_rollback(fleet2):
    from hivemall_tpu.serve.promote import PromotionGate
    from hivemall_tpu.testing.faults import inject_canary_regression
    t, ds, ckdir, pA, servers = fleet2
    stepA = t._t
    gate = PromotionGate("train_classifier", OPTS, holdout=ds)
    mgr = _manager(ckdir, servers, gate=gate, canary_fraction=0.5)
    assert mgr.check_and_roll() is False          # nothing new
    # poisoned candidate: blocked at the gate, fleet untouched
    pBad = _poisoned(ckdir, pA)
    assert mgr.check_and_roll() is False
    assert ck.is_rejected(pBad) and mgr.quarantined == 1
    assert all(r.model_step == stepA for r in mgr.replicas())
    # good candidate: pass -> one-replica canary -> clean bake -> roll
    pC = _save_next(t, ckdir, ds, bump=10)
    stepC = t._t
    assert mgr.check_and_roll() is False          # canary started
    assert ck.read_promoted(ckdir)["state"] == "canary"
    assert sorted(r.model_step for r in mgr.replicas()) == [stepA, stepC]
    assert mgr.check_and_roll() is True           # bake pass: completed
    assert ck.read_promoted(ckdir)["state"] == "serving"
    assert all(r.model_step == stepC for r in mgr.replicas())
    assert mgr.promotions == 1 and mgr.fleet_step == stepC
    # next candidate: injected latency regression -> auto-rollback
    pD = _save_next(t, ckdir, ds, bump=10)
    mgr.bake_opts = {"bake_seconds": 60.0, "min_requests": 1,
                     "max_bake_seconds": 600.0}
    assert mgr.check_and_roll() is False          # canary for D started
    rows = _rows_of(ds, 20)
    for srv in servers:                           # traffic on both cohorts
        for r_ in rows:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/predict",
                json.dumps({"rows": [r_]}).encode(),
                {"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=10).read()
    inject_canary_regression(mgr, latency_ms=500.0)
    assert mgr.check_and_roll() is False          # bake fail: rolled back
    m = ck.read_promoted(ckdir)
    assert m["current"]["step"] == stepC and m["state"] == "serving"
    assert m["rollbacks"] == 1 and ck.is_rejected(pD)
    assert all(r.model_step == stepC for r in mgr.replicas())
    assert mgr.canary_rollbacks == 1
    # rollback restored bit-identical scores to the pre-canary bundle
    from hivemall_tpu.models.linear import GeneralClassifier
    tc = GeneralClassifier(OPTS)
    tc.load_bundle(pC)
    refC = np.asarray(tc.predict_proba(ds), np.float32)
    eng = servers[0].engine
    got = eng.predict_rows([eng.parse(r_) for r_ in rows[:9]])
    assert np.array_equal(got, refC[:9])
    sec = mgr.promotion_section()
    assert sec["rollbacks"] == 1 and sec["gate_failures"] == 1


def test_fleet_recovers_mid_canary_from_manifest(fleet2):
    """Satellite: a manager killed mid-canary leaves pointer state
    "canary" on disk; a FRESH manager must re-bake and converge — no
    half-rolled fleet, steps converge."""
    t, ds, ckdir, pA, servers = fleet2
    pB = _save_next(t, ckdir, ds)
    stepB = t._t
    ck.promote_bundle(ckdir, pB, state="canary")
    # half-rolled: one replica already on the candidate (as a dying
    # manager would leave it), one still on the old model
    servers[0].engine.reload(pB)
    mgr = _manager(ckdir, servers)
    for r, srv in zip(mgr.replicas(), servers):
        r.model_step = srv.engine.model_step
    assert mgr.check_and_roll() is False          # canary re-baked
    assert mgr._canary is not None and mgr._canary["step"] == stepB
    assert mgr.check_and_roll() is True           # bake(0s) completes
    assert all(r.model_step == stepB for r in mgr.replicas())
    assert ck.read_promoted(ckdir)["state"] == "serving"


def test_fleet_recovers_mid_rollback_from_manifest(fleet2):
    """Satellite: a rollback killed between the quarantine marker and
    the pointer flip recovers as a completed rollback — the quarantined
    bundle never serves again."""
    t, ds, ckdir, pA, servers = fleet2
    stepA = t._t
    pB = _save_next(t, ckdir, ds)
    ck.promote_bundle(ckdir, pB, state="canary")
    servers[1].engine.reload(pB)                  # canary replica on B
    ck.reject_bundle(pB, "injected burn")         # crash right after this
    mgr = _manager(ckdir, servers)
    for r, srv in zip(mgr.replicas(), servers):
        r.model_step = srv.engine.model_step
    assert mgr.check_and_roll() is True           # rollback completed
    m = ck.read_promoted(ckdir)
    assert m["current"]["step"] == stepA and m["state"] == "serving"
    assert m["rollbacks"] == 1
    assert all(r.model_step == stepA for r in mgr.replicas())
    assert mgr.check_and_roll() is False          # B quarantined: no retry


def test_fleet_promoted_reload_rejects_explicit_path(trained):
    """A promotion-gated fleet's /reload must not bypass the gate."""
    from hivemall_tpu.serve.fleet import Fleet
    t, ds, ckdir, pA = trained
    ck.promote_bundle(ckdir, pA)
    fleet = Fleet.__new__(Fleet)          # wiring only — no spawn
    fleet.manager = _manager(ckdir, [])
    out = fleet._on_reload(json.dumps({"path": pA}).encode())
    assert "promotion-gated" in out["error"]


# --- real processes: SIGKILL the manager (slow; smoke covers the rest) -------

@pytest.mark.slow
def test_sigkill_fleet_manager_mid_canary_recovers(trained):
    """SIGKILL the whole fleet process mid-canary; a fresh Fleet on the
    same checkpoint dir must recover a consistent state from the
    PROMOTED manifest: canary re-baked, steps converge, state serving."""
    import signal
    import subprocess
    import sys
    import time
    t, ds, ckdir, pA = trained
    ck.promote_bundle(ckdir, pA)
    pB = _save_next(t, ds=ds, ckdir=ckdir)
    stepB = t._t
    ck.promote_bundle(ckdir, pB, state="canary")   # mid-canary on disk
    driver = (
        "import json,sys,time\n"
        "from hivemall_tpu.serve.fleet import Fleet\n"
        f"f = Fleet('train_classifier', {OPTS!r}, checkpoint_dir="
        f"{ckdir!r}, replicas=2, promote=True, watch_interval=0.5,\n"
        "          bake_opts={'bake_seconds': 3600.0, 'min_requests': 1})\n"
        "f.start(wait_ready=True)\n"
        "print(json.dumps({'pids': [r.proc.pid for r in"
        " f.manager.replicas()]}), flush=True)\n"
        "time.sleep(3600)\n")
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen([sys.executable, "-c", driver],
                            stdout=subprocess.PIPE, text=True, env=env,
                            cwd=os.path.dirname(os.path.dirname(
                                os.path.abspath(__file__))))
    try:
        line = proc.stdout.readline()
        pids = json.loads(line)["pids"]
        os.kill(proc.pid, signal.SIGKILL)      # the manager dies hard
        proc.wait(timeout=10)
        for pid in pids:                        # host death takes the
            try:                                # orphaned workers too
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
    finally:
        if proc.poll() is None:
            proc.kill()
    assert ck.read_promoted(ckdir)["state"] == "canary"   # crash window
    from hivemall_tpu.serve.fleet import Fleet
    fleet = Fleet("train_classifier", OPTS, checkpoint_dir=ckdir,
                  replicas=2, promote=True, watch_interval=0.3,
                  bake_opts={"bake_seconds": 0.5, "min_requests": 0,
                             "max_bake_seconds": 0.5})
    fleet.start(wait_ready=True)
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            steps = {r.model_step for r in fleet.manager.replicas()}
            if steps == {stepB} \
                    and ck.read_promoted(ckdir)["state"] == "serving":
                break
            time.sleep(0.3)
        assert {r.model_step for r in fleet.manager.replicas()} \
            == {stepB}
        assert ck.read_promoted(ckdir)["state"] == "serving"
    finally:
        fleet.stop()
