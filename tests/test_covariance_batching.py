"""Batched-mode convergence evidence for the covariance family (CW/AROW/SCW)
on the a9a-shaped fragment (VERDICT r1 weak #4 / SURVEY.md §8 "online-learner
semantics under batching").

Measured on the committed fragment (tests/resources), 1 epoch, test AUC:

  trainer   mb=1 (oracle)  mb=16   mb=64   mb=256
  AROW      0.936          0.934   0.893   0.800
  CW        0.931          0.929   0.661   0.745
  SCW1      0.938          0.938   0.903   0.748

and mb=64 with 4 epochs recovers to 0.92-0.93 while mb=256 does not (CW
diverges). Hence the documented guidance: -mini_batch 1 is exact reference
semantics (the default), <=16 matches the sequential oracle within noise,
64 needs extra epochs, beyond that the closed-form per-batch update departs
from the online semantics. These tests pin the <=16 equivalence and the
large-batch degradation so the trade-off stays measured, not assumed.
"""

import os

import numpy as np
import pytest

from hivemall_tpu.frame.evaluation import auc
from hivemall_tpu.io.libsvm import read_libsvm
from hivemall_tpu.models.classifier import (AROWTrainer,
                                            ConfidenceWeightedTrainer,
                                            SCW1Trainer)

RES = os.path.join(os.path.dirname(__file__), "resources")


@pytest.fixture(scope="module")
def a9a():
    return (read_libsvm(os.path.join(RES, "a9a.frag.train.libsvm")),
            read_libsvm(os.path.join(RES, "a9a.frag.test.libsvm")))


@pytest.mark.parametrize("cls", [AROWTrainer, ConfidenceWeightedTrainer,
                                 SCW1Trainer])
def test_minibatch16_matches_sequential_oracle(cls, a9a):
    tr, te = a9a
    oracle = cls("-dims 256 -mini_batch 1")
    oracle.fit(tr, epochs=1)
    a1 = auc(te.labels, oracle.decision_function(te))
    batched = cls("-dims 256 -mini_batch 16")
    batched.fit(tr, epochs=1)
    a16 = auc(te.labels, batched.decision_function(te))
    assert a1 > 0.90                     # the oracle itself converges
    assert abs(a1 - a16) < 0.01, (a1, a16)


def test_minibatch64_recovers_with_epochs(a9a):
    tr, te = a9a
    t = AROWTrainer("-dims 256 -mini_batch 64")
    t.fit(tr, epochs=4)
    assert auc(te.labels, t.decision_function(te)) > 0.90


def test_large_batch_degradation_is_real(a9a):
    """Document-by-test: the 1-epoch mb=256 model is measurably worse than
    the oracle — the reason the default stays -mini_batch 1."""
    tr, te = a9a
    oracle = AROWTrainer("-dims 256 -mini_batch 1")
    oracle.fit(tr, epochs=1)
    big = AROWTrainer("-dims 256 -mini_batch 256")
    big.fit(tr, epochs=1)
    a1 = auc(te.labels, oracle.decision_function(te))
    a256 = auc(te.labels, big.decision_function(te))
    assert a1 - a256 > 0.05, (a1, a256)


@pytest.mark.parametrize("cls_name", ["ConfidenceWeightedTrainer",
                                      "AROWTrainer", "SCW1Trainer"])
def test_sequential_batch_mode_is_bit_equivalent_to_row_dispatch(cls_name):
    """-batch_mode sequential: a lax.scan minibatch must reproduce the
    -mini_batch 1 dispatch loop exactly (same per-row update order)."""
    import hivemall_tpu.models.classifier as C
    from hivemall_tpu.io.sparse import SparseDataset
    cls = getattr(C, cls_name)
    rng = np.random.default_rng(5)
    rows = [(rng.choice(np.arange(1, 64), 4, replace=False).astype(np.int32),
             rng.uniform(0.5, 1.5, 4).astype(np.float32))
            for _ in range(96)]
    labels = [1.0 if r[0].sum() % 2 else -1.0 for r in rows]
    ds = SparseDataset.from_rows(rows, labels)

    seq = cls("-dims 64 -mini_batch 32 -batch_mode sequential")
    seq.fit(ds, shuffle=False)
    ref = cls("-dims 64 -mini_batch 1")
    ref.fit(ds, shuffle=False)

    np.testing.assert_allclose(np.asarray(seq.w, np.float32),
                               np.asarray(ref.w, np.float32),
                               rtol=1e-5, atol=1e-6)
    if seq.sigma is not None:
        np.testing.assert_allclose(np.asarray(seq.sigma),
                                   np.asarray(ref.sigma),
                                   rtol=1e-5, atol=1e-6)


def test_sequential_batch_mode_validates():
    from hivemall_tpu.models.classifier import AROWTrainer
    with pytest.raises(ValueError):
        AROWTrainer("-dims 64 -batch_mode nope")


@pytest.mark.parametrize("cls_name", ["MulticlassCWTrainer",
                                      "MulticlassAROWTrainer"])
def test_multiclass_sequential_matches_row_dispatch(cls_name):
    import hivemall_tpu.models.multiclass as M
    cls = getattr(M, cls_name)
    rng = np.random.default_rng(7)
    feats = [[f"{i}:1.0" for i in
              rng.choice(np.arange(1, 64), 4, replace=False)]
             for _ in range(60)]
    labels = [int(rng.integers(0, 3)) for _ in range(60)]

    seq = cls("-dims 64 -classes 4 -mini_batch 20 -batch_mode sequential")
    ref = cls("-dims 64 -classes 4 -mini_batch 1")
    for t in (seq, ref):
        for f, y in zip(feats, labels):
            t.process(f, y)
        list(t.close())
    np.testing.assert_allclose(np.asarray(seq.W), np.asarray(ref.W),
                               rtol=1e-5, atol=1e-6)
    if seq.sigma is not None:
        np.testing.assert_allclose(np.asarray(seq.sigma),
                                   np.asarray(ref.sigma),
                                   rtol=1e-5, atol=1e-6)
