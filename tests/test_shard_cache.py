"""Packed shard cache (io/shard_cache.py): bit-exact warm epochs,
digest-keyed invalidation, atomic rewrite, obs counters."""

import json
import os

import numpy as np
import pytest

from hivemall_tpu.io import shard_cache as sc
from hivemall_tpu.io.sparse import SparseDataset
from hivemall_tpu.models.fm import FFMTrainer


def _ffm_unit_ds(n=700, L=8, F=8, dims=1 << 11, seed=5):
    """Criteo-shaped unit-value FFM dataset (one feature per field)."""
    rng = np.random.default_rng(seed)
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32) % F, (n, 1))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    return SparseDataset(idx.ravel(),
                         np.arange(0, n * L + 1, L, dtype=np.int64),
                         np.ones(n * L, np.float32), lab, fld.ravel())


_CFG = ("-dims 2048 -factors 2 -fields 8 -mini_batch 64 "
        "-classification -pack_input on")


def _traj(cfg, ds, epochs=3, shuffle=True):
    t = FFMTrainer(cfg)
    t._trace_losses = []
    t.fit(ds, epochs=epochs, shuffle=shuffle)
    return np.asarray(t._trace_losses), t


# --- container format -------------------------------------------------------

def test_container_roundtrip_and_validation(tmp_path):
    path = str(tmp_path / "x.pack")
    a = np.arange(999, dtype=np.uint8).reshape(27, 37)
    b = np.linspace(0, 1, 55).astype(np.float32)
    sc.write_cache_file(path, {"kind": "t", "who": "roundtrip"},
                        {"a": a, "b": b})
    header, views = sc.read_cache_file(path)
    assert header["who"] == "roundtrip"
    np.testing.assert_array_equal(np.asarray(views["a"]), a)
    np.testing.assert_array_equal(np.asarray(views["b"]), b)
    # bit flip in the payload -> CacheInvalid
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size - 8)
        f.write(b"\x7f")
    with pytest.raises(sc.CacheInvalid, match="digest"):
        sc.read_cache_file(path)
    # truncation -> CacheInvalid before any digest work
    sc.write_cache_file(path, {"kind": "t"}, {"a": a})
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 5)
    with pytest.raises(sc.CacheInvalid, match="truncated"):
        sc.read_cache_file(path)
    # not a cache file at all
    with open(path, "wb") as f:
        f.write(b"definitely not a cache")
    with pytest.raises(sc.CacheInvalid, match="magic"):
        sc.read_cache_file(path)
    # header-only read degrades to None, never raises
    assert sc.read_cache_header(path) is None


# --- bit-exactness of the cached fit path -----------------------------------

@pytest.mark.parametrize("shuffle", [True, False])
def test_cached_epochs_bit_match_streamed(tmp_path, shuffle):
    """Shuffled and unshuffled epochs through the shard cache reproduce
    the streamed path's loss trajectory EXACTLY — cold (build + tee) and
    warm (fresh trainer, pure mmap replay) both. -checkpoint_dir forces
    the per-epoch streamed loop on the reference so both sides run the
    same seed schedule."""
    ds = _ffm_unit_ds()
    ref, _ = _traj(_CFG + f" -checkpoint_dir {tmp_path}/ck0", ds,
                   shuffle=shuffle)
    cold, _ = _traj(_CFG + f" -checkpoint_dir {tmp_path}/ck1 "
                           f"-shard_cache_dir {tmp_path}/cache", ds,
                    shuffle=shuffle)
    np.testing.assert_array_equal(ref, cold)
    warm, tw = _traj(_CFG + f" -checkpoint_dir {tmp_path}/ck2 "
                            f"-shard_cache_dir {tmp_path}/cache", ds,
                     shuffle=shuffle)
    np.testing.assert_array_equal(ref, warm)
    # the warm run never ran live prep: parse/canonicalize/pack at zero
    d = tw.pipeline_stats.as_dict()
    assert d["batches_prepared"] == 0 and d["prep_seconds"] == 0.0
    assert d["cache_batches"] > 0


def test_cached_device_replay_orchestration_matches_no_cache(tmp_path):
    """Without -checkpoint_dir the epochs>1 path keeps the HBM/device
    replay orchestration; adding -shard_cache_dir must not change the
    trajectory — cold (tee rides along) or warm (epoch 1 served from the
    cache feeds the same retention)."""
    ds = _ffm_unit_ds(seed=7)
    ref, _ = _traj(_CFG, ds)
    cold, _ = _traj(_CFG + f" -shard_cache_dir {tmp_path}/c", ds)
    np.testing.assert_array_equal(ref, cold)
    warm, tw = _traj(_CFG + f" -shard_cache_dir {tmp_path}/c", ds)
    np.testing.assert_array_equal(ref, warm)
    assert tw.pipeline_stats.batches_prepared == 0
    assert tw.pipeline_stats.cache_batches > 0


def test_cached_restart_bit_matches_and_counts(tmp_path):
    """A fresh process-restart-shaped trainer on a warm cache reproduces
    the cold run and the obs counters record the hit/rebuild."""
    ds = _ffm_unit_ds(seed=9)
    sc.counters.reset()
    cfg = _CFG + f" -shard_cache_dir {tmp_path}/c"
    cold, _ = _traj(cfg, ds, epochs=1)
    warm, _ = _traj(cfg, ds, epochs=1)
    np.testing.assert_array_equal(cold, warm)
    d = sc.counters.as_dict()
    assert d["misses"] == 1 and d["rebuilds"] == 1 and d["hits"] == 1
    assert d["bytes_mmapped"] > 0 and d["bytes_written"] > 0


def test_model_tables_equal_through_cache(tmp_path):
    ds = _ffm_unit_ds(seed=11)
    a = FFMTrainer(_CFG).fit(ds, epochs=2)
    b = FFMTrainer(_CFG + f" -shard_cache_dir {tmp_path}/c").fit(ds,
                                                                 epochs=2)
    c = FFMTrainer(_CFG + f" -shard_cache_dir {tmp_path}/c").fit(ds,
                                                                 epochs=2)
    sa = json.dumps(a.model_table(), sort_keys=True, default=str)
    assert sa == json.dumps(b.model_table(), sort_keys=True, default=str)
    assert sa == json.dumps(c.model_table(), sort_keys=True, default=str)


# --- invalidation safety ----------------------------------------------------

def test_corrupt_cache_falls_back_and_rewrites_atomically(tmp_path):
    """A corrupted cache file must read as a MISS (invalid counted), the
    fit must fall back to live prep with an unchanged trajectory, and the
    cache must be rewritten atomically (tmp -> fsync -> os.replace: the
    published file is valid again, no .tmp litter)."""
    ds = _ffm_unit_ds(seed=3)
    cdir = tmp_path / "c"
    cfg = _CFG + f" -shard_cache_dir {cdir}"
    ref, _ = _traj(cfg, ds, epochs=1)
    (path,) = [str(cdir / f) for f in os.listdir(cdir)]
    for corruption in ("flip", "truncate"):
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            if corruption == "flip":
                f.seek(size - 64)
                f.write(b"\xde\xad\xbe\xef")
            else:
                f.truncate(size // 3)
        sc.counters.reset()
        got, _ = _traj(cfg, ds, epochs=1)
        np.testing.assert_array_equal(ref, got)
        d = sc.counters.as_dict()
        assert d["invalid"] == 1 and d["misses"] == 1 and d["rebuilds"] == 1
        sc.read_cache_file(path)            # rewritten file validates
        assert not [f for f in os.listdir(cdir) if ".tmp" in f]


def test_source_mutation_invalidates_file_keyed_cache(tmp_path):
    """A dataset carrying a file identity (source_id) must miss when the
    source's mtime changes, fall back to live prep, and rewrite."""
    ds = _ffm_unit_ds(seed=13)
    src = tmp_path / "src.libsvm"
    src.write_text("synthetic source stand-in\n")
    cdir = tmp_path / "c"
    cfg = _CFG + f" -shard_cache_dir {cdir}"

    def fit_with_sid():
        d2 = SparseDataset(ds.indices, ds.indptr, ds.values, ds.labels,
                           ds.fields)
        d2.source_id = sc.file_source_id(str(src))
        return _traj(cfg, d2, epochs=1)

    ref, _ = fit_with_sid()
    sc.counters.reset()
    same, _ = fit_with_sid()                # unchanged source: pure hit
    d = sc.counters.as_dict()
    assert d["hits"] == 1 and d["misses"] == 0
    np.testing.assert_array_equal(ref, same)
    st = os.stat(src)
    os.utime(src, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    sc.counters.reset()
    again, _ = fit_with_sid()               # mutated mtime: miss + rebuild
    d = sc.counters.as_dict()
    assert d["hits"] == 0 and d["misses"] == 1 and d["rebuilds"] == 1
    np.testing.assert_array_equal(ref, again)
    assert len(os.listdir(cdir)) == 1       # stale file REPLACED in place


def test_prep_config_change_never_false_hits(tmp_path):
    ds = _ffm_unit_ds(seed=15)
    cdir = tmp_path / "c"
    _traj(_CFG + f" -shard_cache_dir {cdir}", ds, epochs=1)
    sc.counters.reset()
    _traj(_CFG.replace("-dims 2048", "-dims 1024")
          + f" -shard_cache_dir {cdir}", ds, epochs=1)
    d = sc.counters.as_dict()
    assert d["hits"] == 0 and d["misses"] >= 1
    assert len(os.listdir(cdir)) == 2       # distinct prep-config keys


def test_non_unit_dataset_declines_cache_and_still_trains(tmp_path):
    """Real-valued batches never pack, so the build must fail open: no
    cache file, identical training outcome."""
    ds = _ffm_unit_ds(seed=17)
    ds = SparseDataset(ds.indices, ds.indptr,
                       np.linspace(0.5, 1.5, len(ds.values))
                       .astype(np.float32), ds.labels, ds.fields)
    cdir = tmp_path / "c"
    sc.counters.reset()
    a, _ = _traj(_CFG, ds, epochs=1)
    b, _ = _traj(_CFG + f" -shard_cache_dir {cdir}", ds, epochs=1)
    np.testing.assert_array_equal(a, b)
    assert sc.counters.as_dict()["build_failed"] == 1
    assert not os.path.exists(cdir) or not os.listdir(cdir)


# --- ParquetStream decoded-shard cache --------------------------------------

def test_parquet_decode_cache_bit_exact_and_invalidates(tmp_path):
    pytest.importorskip("pyarrow")
    from hivemall_tpu.io.arrow import ParquetStream, write_parquet_shards

    ds = _ffm_unit_ds(n=300, seed=21)
    pq_dir = str(tmp_path / "pq")
    write_parquet_shards(ds, pq_dir, rows_per_shard=64)
    cdir = str(tmp_path / "cache")
    plain = list(ParquetStream(pq_dir).batches(32, epochs=2, shuffle=True,
                                               seed=9))
    sc.counters.reset()
    cold = list(ParquetStream(pq_dir, cache_dir=cdir)
                .batches(32, epochs=2, shuffle=True, seed=9))
    from conftest import assert_batches_equal
    assert len(plain) == len(cold) > 0
    for x, y in zip(plain, cold):
        assert_batches_equal(x, y)
    n_shards = sc.counters.as_dict()["rebuilds"]
    assert n_shards == len(ParquetStream(pq_dir).files)
    # epoch 2 of the same traversal already hit the cache
    assert sc.counters.as_dict()["hits"] >= n_shards
    sc.counters.reset()
    warm = list(ParquetStream(pq_dir, cache_dir=cdir)
                .batches(32, epochs=2, shuffle=True, seed=9))
    for x, y in zip(plain, warm):
        assert_batches_equal(x, y)
    d = sc.counters.as_dict()
    assert d["misses"] == 0 and d["rebuilds"] == 0 and d["hits"] > 0
    # mutate one shard's mtime: that shard misses + rebuilds, output equal
    shard0 = ParquetStream(pq_dir).files[0]
    st = os.stat(shard0)
    os.utime(shard0, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    sc.counters.reset()
    again = list(ParquetStream(pq_dir, cache_dir=cdir)
                 .batches(32, epochs=2, shuffle=True, seed=9))
    for x, y in zip(plain, again):
        assert_batches_equal(x, y)
    d = sc.counters.as_dict()
    assert d["misses"] >= 1 and d["rebuilds"] == 1


def test_fit_stream_with_decode_cache_matches(tmp_path):
    pytest.importorskip("pyarrow")
    from hivemall_tpu.io.arrow import ParquetStream, write_parquet_shards

    ds = _ffm_unit_ds(n=256, seed=23)
    pq_dir = str(tmp_path / "pq")
    write_parquet_shards(ds, pq_dir, rows_per_shard=128)
    cdir = str(tmp_path / "cache")

    def run(cache):
        t = FFMTrainer(_CFG)
        t._trace_losses = []
        stream = ParquetStream(pq_dir, cache_dir=cdir if cache else None)
        t.fit_stream(stream.batches(64, epochs=1, shuffle=False))
        return np.asarray(t._trace_losses)

    a = run(False)
    b = run(True)                           # cold: builds shard caches
    c = run(True)                           # warm: decode skipped
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, c)


# --- obs surface ------------------------------------------------------------

def test_registry_section_and_prometheus_surface():
    from hivemall_tpu.obs.http import to_prometheus
    from hivemall_tpu.obs.registry import registry

    snap = registry.snapshot()
    assert "ingest_cache" in snap
    for key in ("hits", "misses", "rebuilds", "bytes_mmapped"):
        assert key in snap["ingest_cache"]
    text = to_prometheus(snap)
    assert "hivemall_tpu_ingest_cache_hits" in text
    assert "hivemall_tpu_ingest_cache_bytes_mmapped" in text


def test_source_id_distinguishes_parse_configs(tmp_path):
    """The same file parsed under different reader options is a DIFFERENT
    dataset — its source_id must differ so the packed cache can never
    serve one parse's records for another's key."""
    from hivemall_tpu.io.libsvm import read_libsvm

    p = str(tmp_path / "t.libsvm")
    with open(p, "w") as f:
        f.write("1 1:1 2:1\n-1 3:1\n")
    a = read_libsvm(p)
    b = read_libsvm(p, zero_based=True)
    c = read_libsvm(p)
    assert a.source_id != b.source_id
    assert a.source_id == c.source_id


# --- native canonicalizer default (tentpole leg 3) --------------------------

def test_fit_native_and_python_canonicalizer_bit_equal(tmp_path):
    """The C++ canonicalizer is the default in every prep path; a fit
    with it active must be bit-equal to the numpy fallback (the automatic
    degradation when _native.so is absent)."""
    import hivemall_tpu.utils.native as nat

    ds = _ffm_unit_ds(seed=25)
    a = FFMTrainer(_CFG)
    a._trace_losses = []
    a.fit(ds, epochs=1, shuffle=True)
    saved = nat.canonicalize_fieldmajor_native
    try:
        nat.canonicalize_fieldmajor_native = lambda *a_, **k: NotImplemented
        b = FFMTrainer(_CFG)
        b._trace_losses = []
        b.fit(ds, epochs=1, shuffle=True)
    finally:
        nat.canonicalize_fieldmajor_native = saved
    np.testing.assert_array_equal(np.asarray(a._trace_losses),
                                  np.asarray(b._trace_losses))
