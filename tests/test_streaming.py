"""StreamingScorer — the HivemallStreamingOps analog (SURVEY.md §3.18)."""

import numpy as np

from hivemall_tpu.frame.streaming import StreamingScorer
from hivemall_tpu.models.linear import GeneralClassifier


def _trained():
    rng = np.random.default_rng(2)
    tr = GeneralClassifier("-dims 4096 -loss logloss -opt adagrad -reg no "
                           "-eta fixed -eta0 0.5 -mini_batch 16")
    rows = []
    for _ in range(200):
        x = rng.normal(size=3)
        feats = [f"f{j}:{x[j]:.4f}" for j in range(3)]
        tr.process(feats, 1 if x[0] > 0 else -1)
        rows.append((feats, 1 if x[0] > 0 else -1))
    return dict(tr.close()), rows


def test_stream_scores_match_direction():
    model, rows = _trained()
    scorer = StreamingScorer(model, dims=4096, sigmoid=True)
    feats = [r[0] for r in rows]
    labels = np.asarray([r[1] for r in rows])
    scores = scorer.score(feats)
    acc = ((scores > 0.5) == (labels > 0)).mean()
    assert acc > 0.9, acc
    assert np.all((scores >= 0) & (scores <= 1))


def test_stream_chunked_equals_batch():
    model, rows = _trained()
    scorer = StreamingScorer(model, dims=4096)
    feats = [r[0] for r in rows]
    whole = scorer.score(feats)
    chunked = np.concatenate(
        list(scorer.score_stream([feats[i:i + 32]
                                  for i in range(0, len(feats), 32)])))
    np.testing.assert_allclose(whole, chunked, rtol=1e-6, atol=1e-6)


def test_empty_chunk():
    model, _ = _trained()
    assert StreamingScorer(model, dims=4096).score([]).shape == (0,)
