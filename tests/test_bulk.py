"""Bulk offline scoring (ISSUE 17): multi-shard ``predict --input <dir>``,
fused score->each_top_k, promoted-pointer model resolution.

The process-pool + sanitizer coverage (bit-match under 2 spawned workers,
int8 error bound, fd/thread leak census) lives in the run_tests.sh smoke
(``python -m hivemall_tpu.io.bulk --smoke``); these tests pin the
composition semantics at suite-friendly shapes with in-process pools."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from hivemall_tpu.catalog import lookup
from hivemall_tpu.frame.evaluation import auc, logloss
from hivemall_tpu.frame.tools import TopKAccumulator, each_top_k
from hivemall_tpu.io.arrow import _parquet_files, write_parquet_shards
from hivemall_tpu.io.bulk import _synth, bulk_predict, resolve_model_bundle

DIMS = 512
OPTS = f"-dims {DIMS} -mini_batch 64"


def _trained(ckdir, n=192, seed=1):
    cls = lookup("train_classifier").resolve()
    tr = cls(OPTS)
    tr.fit(_synth(n, DIMS, 8, seed=seed))
    os.makedirs(ckdir, exist_ok=True)
    path = os.path.join(ckdir, f"{cls.NAME}-step{int(tr._t):010d}.npz")
    tr.save_bundle(path)
    return tr, path


def _scores(out_dir):
    return np.concatenate([
        pq.read_table(f).column("score").to_numpy(
            zero_copy_only=False).astype(np.float32)
        for f in _parquet_files(out_dir)])


def test_topk_accumulator_matches_each_top_k():
    """Interleaved-arrival accumulation == the reference UDTF over
    CLUSTER BY input: ranks, scores, stable ties, and bottom-k."""
    rng = np.random.default_rng(3)
    n, k = 400, 5
    groups = rng.integers(0, 11, n).tolist()
    scores = np.round(rng.standard_normal(n), 2)   # force score ties
    vals = [f"v{i}" for i in range(n)]

    for kk in (k, -k):
        acc = TopKAccumulator(kk)
        acc.add_many(groups, scores, vals)
        got = {}
        for g, rank, s, v in acc.result():
            got.setdefault(g, []).append((rank, s, v))
        order = np.argsort(groups, kind="stable")  # CLUSTER BY arrival
        want = {}
        cg = [groups[i] for i in order]
        rows = list(each_top_k(kk, cg, [float(scores[i]) for i in order],
                               [vals[i] for i in order]))
        j = 0
        for g in dict.fromkeys(cg):                # first-seen group order
            want[g] = []
            while j < len(rows) and (not want[g] or rows[j][0] > 1):
                want[g].append(rows[j])
                j += 1
        assert got == want, f"k={kk}"


def test_bulk_topk_composes_with_each_top_k(tmp_path):
    """End-to-end: multi-shard Parquet (ragged tail + an EMPTY shard) with
    a per-row group column, scored through a 2-worker thread pool. The f32
    output bit-matches predict_proba, the streamed eval UDAFs match the
    frame ones, and topk.tsv matches each_top_k replayed over the scored
    output — and an independent numpy argsort oracle."""
    tr, bundle = _trained(str(tmp_path / "ck"))
    n = 300
    test = _synth(n, DIMS, 8, seed=2)
    in_dir = str(tmp_path / "in")
    write_parquet_shards(test, in_dir, rows_per_shard=128)  # 128/128/44
    rng = np.random.default_rng(5)
    parts = []
    for f in _parquet_files(in_dir):
        t = pq.read_table(f)
        g = rng.integers(0, 7, t.num_rows).astype(np.int64)
        parts.append(g)
        pq.write_table(t.append_column("user", pa.array(g)), f)
    groups = np.concatenate(parts)
    empty = pq.read_table(_parquet_files(in_dir)[0]).slice(0, 0)
    pq.write_table(empty, os.path.join(in_dir, "shard-00099.parquet"))

    out = str(tmp_path / "out")
    r = bulk_predict("train_classifier", in_dir, out, options=OPTS,
                     bundle=bundle, backend="kernel", workers=2,
                     pool="thread", top_k=3, group_col="user",
                     cache_dir=str(tmp_path / "cache"))
    assert r["rows"] == n and r["shards"] == 4
    assert r["bundle_source"] == "explicit" and r["pool"] == "thread"

    want = np.asarray(tr.predict_proba(test), np.float32)
    got = _scores(out)
    assert np.array_equal(got, want)
    got_groups = np.concatenate([
        pq.read_table(f).column("user").to_numpy()
        for f in _parquet_files(out)])
    assert np.array_equal(got_groups, groups)
    assert abs(r["metrics"]["logloss"] - logloss(test.labels, want)) < 1e-5
    assert abs(r["metrics"]["auc"] - auc(test.labels, want)) < 1e-5
    assert r["metrics"]["auc_method"] == "exact"

    # topk.tsv: ref is "<shard_index>:<row_in_shard>" -> global row
    offs = [0, 128, 256, 300]
    topk = {}
    with open(r["topk_file"]) as fh:
        for line in fh:
            g, rank, s, ref = line.rstrip("\n").split("\t")
            si, row = (int(x) for x in ref.split(":"))
            topk.setdefault(int(g), []).append(
                (int(rank), float(s), offs[si] + row))
    assert r["topk_rows"] == sum(len(v) for v in topk.values())

    # oracle 1: each_top_k replayed over the scored output, clustered by
    # group (rank==1 marks each group's first emitted row)
    order = np.argsort(groups, kind="stable")
    rows = list(each_top_k(3, groups[order].tolist(),
                           want[order].tolist(), order.tolist()))
    seen = list(dict.fromkeys(groups[order].tolist()))
    replay = {g: [] for g in seen}
    git = iter(seen)
    cur = None
    for rank, s, gi in rows:
        if rank == 1:
            cur = next(git)
        replay[cur].append((rank, gi))
    assert set(replay) == set(topk)
    for g, rws in topk.items():
        assert [(rk, gi) for rk, _s, gi in sorted(rws)] == replay[g], \
            f"group {g}: bulk topk diverged from each_top_k replay"
    # oracle 2: per-group numpy argsort (independent of frame/tools)
    for g in np.unique(groups):
        idx = np.flatnonzero(groups == g)
        best = idx[np.argsort(-want[idx].astype(np.float64),
                              kind="stable")][:3]
        rows_g = sorted(topk[int(g)])
        assert [r_[2] for r_ in rows_g] == best.tolist(), f"group {g}"
        assert [r_[0] for r_ in rows_g] == list(range(1, len(best) + 1))
        for rank, s, gi in rows_g:
            assert np.isclose(s, want[gi], rtol=1e-4), (g, rank)


def test_group_aware_shard_routing(tmp_path):
    """Group-aware routing (ROADMAP item 5 follow-up): shards sharing
    group values union into one pooled component so per-group top-k
    never splits a group across workers; disjoint shards stay separate
    tasks; routed results are identical to the unrouted single-worker
    scan."""
    from hivemall_tpu.io.bulk import _group_components
    tr, bundle = _trained(str(tmp_path / "ck"), n=128, seed=7)
    n = 256
    test = _synth(n, DIMS, 8, seed=8)
    in_dir = str(tmp_path / "in")
    write_parquet_shards(test, in_dir, rows_per_shard=64)  # 4 shards
    files = _parquet_files(in_dir)
    assert len(files) == 4
    # shards 0+1 share groups {0..3}, shards 2+3 share {10..13}: two
    # components, each spanning two shards, mutually disjoint
    rng = np.random.default_rng(9)
    for si, f in enumerate(files):
        t = pq.read_table(f)
        lo = 0 if si < 2 else 10
        g = rng.integers(lo, lo + 4, t.num_rows).astype(np.int64)
        pq.write_table(t.append_column("user", pa.array(g)), f)

    comps = _group_components(files, "user")
    assert comps == [[0, 1], [2, 3]]

    kw = dict(options=OPTS, bundle=bundle, backend="kernel",
              top_k=3, group_col="user")
    routed = bulk_predict("train_classifier", in_dir,
                          str(tmp_path / "out_routed"), workers=2,
                          pool="thread", **kw)
    assert routed["group_components"] == 2
    baseline = bulk_predict("train_classifier", in_dir,
                            str(tmp_path / "out_base"), workers=1, **kw)
    with open(routed["topk_file"]) as fh:
        got = fh.read()
    with open(baseline["topk_file"]) as fh:
        want = fh.read()
    assert got == want and routed["topk_rows"] == baseline["topk_rows"]
    assert np.array_equal(_scores(str(tmp_path / "out_routed")),
                          _scores(str(tmp_path / "out_base")))

    # a chain shard bridging both halves collapses routing to ONE
    # component (transitive closure, not pairwise overlap)
    bridge = pq.read_table(files[0]).slice(0, 2)
    bridge = bridge.set_column(
        bridge.column_names.index("user"), "user",
        pa.array(np.array([3, 10], np.int64)))
    pq.write_table(bridge, os.path.join(in_dir, "shard-bridge.parquet"))
    comps = _group_components(_parquet_files(in_dir), "user")
    assert sorted(len(c) for c in comps) == [5] or len(comps) == 1


def test_bulk_promoted_pointer_default(tmp_path):
    """The promotion pointer is the default model source (the nightly-job
    contract): promoted beats newest, explicit beats both, and the scored
    output provably comes from the PROMOTED (older) weights."""
    from hivemall_tpu.io.checkpoint import promote_bundle
    ck = str(tmp_path / "ck")
    old, p_old = _trained(ck, n=128, seed=3)
    new, p_new = _trained(ck, n=256, seed=4)
    assert p_new != p_old                      # distinct step filenames

    path, src = resolve_model_bundle("train_classifier", checkpoint_dir=ck)
    assert (path, src) == (p_new, "newest")
    promote_bundle(ck, p_old)
    path, src = resolve_model_bundle("train_classifier", checkpoint_dir=ck)
    assert (path, src) == (p_old, "promoted")
    path, src = resolve_model_bundle("train_classifier", bundle=p_new,
                                     checkpoint_dir=ck)
    assert (path, src) == (p_new, "explicit")

    test = _synth(96, DIMS, 8, seed=5)
    in_dir = str(tmp_path / "in")
    write_parquet_shards(test, in_dir, rows_per_shard=64)
    r = bulk_predict("train_classifier", in_dir, str(tmp_path / "out"),
                     options=OPTS, checkpoint_dir=ck, backend="kernel")
    assert r["bundle_source"] == "promoted"
    assert r["model_step"] == int(old._t)
    got = _scores(str(tmp_path / "out"))
    assert np.array_equal(got,
                          np.asarray(old.predict_proba(test), np.float32))
    assert not np.array_equal(got,
                              np.asarray(new.predict_proba(test),
                                         np.float32))
