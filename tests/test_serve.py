"""Online serving subsystem (hivemall_tpu/serve, docs/SERVING.md):
micro-batcher coalescing/deadline/shedding semantics, engine hot-reload
(corrupt bundles ignored, newer steps swapped mid-traffic), HTTP front
end + obs registry integration, and the shared shape-bucketing helper
the offline scoring path reuses."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from hivemall_tpu.serve.batcher import (MicroBatcher, ServeDeadline,
                                        ServeOverload)


class GatedPredict:
    """Fake predict fn whose completion is gated by an Event — makes the
    coalescing-window tests deterministic (requests submitted while the
    worker is blocked MUST coalesce into the next batch)."""

    def __init__(self):
        self.gate = threading.Event()
        self.calls = []                 # row count per predict call

    def __call__(self, rows):
        self.calls.append(len(rows))
        assert self.gate.wait(timeout=10), "test gate never opened"
        return np.arange(len(rows), dtype=np.float32)


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.002)
    return False


# --- batcher ----------------------------------------------------------------

def test_batcher_coalesces_concurrent_requests():
    p = GatedPredict()
    b = MicroBatcher(p, max_batch=64, max_delay_ms=2.0)
    try:
        f1 = b.submit([("a",)])
        assert _wait(lambda: len(p.calls) == 1)     # worker holds batch 1
        f2 = b.submit([("b",), ("c",)])
        f3 = b.submit([("d",)])
        assert _wait(lambda: b.queue_depth == 2)
        p.gate.set()
        assert np.array_equal(f1.result(5), [0.0])
        # both requests queued behind the gate land in ONE batch,
        # split back per request
        assert np.array_equal(f2.result(5), [0.0, 1.0])
        assert np.array_equal(f3.result(5), [2.0])
        assert p.calls == [1, 3]
        st = b.stats()
        assert st["batches"] == 2 and st["requests"] == 3
        assert st["mean_coalesced"] == 1.5
        assert st["batch_hist"] == {"1": 1, "4": 1}  # pow2 rows buckets
    finally:
        p.gate.set()
        b.close()


def test_batcher_respects_max_batch_and_never_splits():
    p = GatedPredict()
    p.gate.set()
    b = MicroBatcher(p, max_batch=4, max_delay_ms=0.0)
    try:
        p.gate.clear()
        f0 = b.submit([(0,)])
        assert _wait(lambda: len(p.calls) == 1)
        futs = [b.submit([(i,), (i,), (i,)]) for i in range(3)]
        p.gate.set()
        for f in futs + [f0]:
            f.result(5)
        # 3-row requests against max_batch=4: one request per batch —
        # a request is never split across batches
        assert p.calls == [1, 3, 3, 3]
    finally:
        p.gate.set()
        b.close()


def test_batcher_deadline_expires_queued_request():
    p = GatedPredict()
    b = MicroBatcher(p, max_batch=8, max_delay_ms=1.0)
    try:
        fa = b.submit([("a",)])                    # occupies the worker
        assert _wait(lambda: len(p.calls) == 1)
        fb = b.submit([("b",)], deadline_ms=15.0)
        time.sleep(0.08)                           # let B's deadline pass
        p.gate.set()
        assert np.array_equal(fa.result(5), [0.0])
        with pytest.raises(ServeDeadline):
            fb.result(5)
        assert b.expired == 1
        assert b.stats()["expired"] == 1
    finally:
        p.gate.set()
        b.close()


def test_batcher_sheds_on_full_queue():
    p = GatedPredict()
    b = MicroBatcher(p, max_batch=8, max_delay_ms=0.0, max_queue_rows=4)
    try:
        first = b.submit([("a",)])                 # taken by the worker
        assert _wait(lambda: len(p.calls) == 1)
        q1 = b.submit([("b",), ("c",)])
        q2 = b.submit([("d",), ("e",)])            # queue now at 4 rows
        with pytest.raises(ServeOverload):
            b.submit([("f",)])                     # fail-fast shed
        assert b.shed == 1 and b.stats()["shed"] == 1
        p.gate.set()
        for f in (first, q1, q2):
            f.result(5)                            # queued work unharmed
    finally:
        p.gate.set()
        b.close()


def test_batcher_oversized_request_admitted_alone():
    p = GatedPredict()
    p.gate.set()
    b = MicroBatcher(p, max_batch=4, max_delay_ms=0.0, max_queue_rows=4)
    try:
        f = b.submit([(i,) for i in range(9)])     # > max_queue_rows but
        assert len(f.result(5)) == 9               # queue was empty
    finally:
        b.close()


def test_batcher_predict_error_fails_only_that_batch():
    calls = []

    def boom(rows):
        calls.append(len(rows))
        if len(calls) == 1:
            raise RuntimeError("kernel exploded")
        return np.zeros(len(rows), np.float32)

    b = MicroBatcher(boom, max_batch=8, max_delay_ms=0.0)
    try:
        f1 = b.submit([("a",)])
        with pytest.raises(RuntimeError, match="kernel exploded"):
            f1.result(5)
        f2 = b.submit([("b",)])
        assert len(f2.result(5)) == 1              # dispatch loop survived
        assert b.errors == 1
    finally:
        b.close()


def test_batcher_passes_meta_through():
    """A predict fn returning (scores, meta) resolves every request in
    the batch to (slice, meta) — how /predict tags responses with the
    step of the model version that ACTUALLY scored them."""
    p = GatedPredict()
    inner = p

    def with_meta(rows):
        return inner(rows), 42

    b = MicroBatcher(with_meta, max_batch=8, max_delay_ms=2.0)
    try:
        f1 = b.submit([("a",)])
        assert _wait(lambda: len(p.calls) == 1)
        f2 = b.submit([("b",), ("c",)])
        p.gate.set()
        s1, m1 = f1.result(5)
        s2, m2 = f2.result(5)
        assert m1 == 42 and m2 == 42
        assert np.array_equal(s1, [0.0]) and np.array_equal(s2, [0.0, 1.0])
    finally:
        p.gate.set()
        b.close()


def test_batcher_isolates_bad_request_in_coalesced_batch():
    """One request whose rows raise at score time must not fail the
    innocent requests coalesced into the same batch."""
    gate = threading.Event()
    calls = []

    def picky(rows):
        calls.append(len(rows))
        assert gate.wait(10), "test gate never opened"
        if ("bad",) in rows:
            raise ValueError("unscorable row")
        return np.zeros(len(rows), np.float32)

    b = MicroBatcher(picky, max_batch=8, max_delay_ms=2.0)
    try:
        f0 = b.submit([("x",)])                     # occupies the worker
        assert _wait(lambda: len(calls) == 1)
        f_bad = b.submit([("bad",)])
        f_ok = b.submit([("ok",)])
        assert _wait(lambda: b.queue_depth == 2)
        gate.set()
        f0.result(5)
        with pytest.raises(ValueError, match="unscorable"):
            f_bad.result(5)
        assert len(f_ok.result(5)) == 1             # batchmate survived
        assert b.errors == 1
    finally:
        gate.set()
        b.close()


def test_batcher_close_fails_pending():
    p = GatedPredict()
    b = MicroBatcher(p, max_batch=8, max_delay_ms=50.0)
    f1 = b.submit([("a",)])
    assert _wait(lambda: len(p.calls) == 1)
    f2 = b.submit([("b",)])
    p.gate.set()
    b.close()
    f1.result(5)                                   # in-flight completed
    with pytest.raises(RuntimeError, match="closed"):
        f2.result(5)
    with pytest.raises(RuntimeError, match="closed"):
        b.submit([("c",)])


def test_batcher_drain_completes_queued_and_rejects_new():
    """Graceful shutdown (the fleet replica's SIGTERM path): close(
    drain=True) mid-traffic completes every ACCEPTED request — the one
    in flight at the predict fn AND the ones still queued behind it —
    while new submits are rejected cleanly."""
    p = GatedPredict()
    b = MicroBatcher(p, max_batch=2, max_delay_ms=50.0)
    f1 = b.submit([("a",)])
    assert _wait(lambda: len(p.calls) == 1)        # in-flight, gated
    queued = [b.submit([(f"q{i}",)]) for i in range(5)]
    assert b.queue_depth == 5

    done = threading.Event()

    def closer():
        b.close(drain=True, timeout=30.0)
        done.set()

    t = threading.Thread(target=closer)
    t.start()
    # close() has been called: new work must already be rejected even
    # though the queue is still draining behind the gate
    assert _wait(lambda: b._closed)
    with pytest.raises(RuntimeError, match="closed"):
        b.submit([("late",)])
    p.gate.set()                                   # release the scorer
    assert np.array_equal(f1.result(10), [0.0])
    for f in queued:                               # every queued request
        assert len(f.result(10)) == 1              # scored, none dropped
    assert done.wait(10)
    t.join(5)
    assert sum(p.calls) == 6                       # all 6 rows scored


def test_batcher_drain_mid_traffic_under_load():
    """Drain while concurrent submitters are still racing: accepted
    requests all complete, late ones all fail with the closed error —
    nothing hangs and nothing is silently dropped."""
    import numpy as _np

    def predict(rows):
        time.sleep(0.001)
        return _np.zeros(len(rows), _np.float32)

    b = MicroBatcher(predict, max_batch=8, max_delay_ms=0.5)
    results = {"ok": 0, "closed": 0, "other": []}
    lock = threading.Lock()

    def submitter():
        for _ in range(50):
            try:
                f = b.submit([("x",)])
                f.result(10)
                with lock:
                    results["ok"] += 1
            except RuntimeError as e:
                if "closed" in str(e):
                    with lock:
                        results["closed"] += 1
                else:
                    with lock:
                        results["other"].append(str(e))
    ts = [threading.Thread(target=submitter) for _ in range(4)]
    for t in ts:
        t.start()
    time.sleep(0.03)                               # traffic in flight
    b.close(drain=True, timeout=30.0)
    for t in ts:
        t.join(15)
    assert not results["other"], results
    assert results["ok"] > 0 and results["closed"] > 0
    assert results["ok"] + results["closed"] == 200


# --- shared shape bucketing (io.sparse) -------------------------------------

def test_bucket_size_clamps():
    from hivemall_tpu.io.sparse import bucket_size
    assert bucket_size(0) == 1
    assert bucket_size(3) == 4
    assert bucket_size(3, lo=8) == 8
    assert bucket_size(100, hi=64) == 64
    assert bucket_size(64, lo=8, hi=256) == 64
    # non-power-of-two cap: the bucket is hi ITSELF (the body batch
    # shape, already compiled), never pow2(hi) > hi
    assert bucket_size(70, lo=8, hi=100) == 100


def test_score_batches_buckets_and_coverage():
    from hivemall_tpu.io.libsvm import synthetic_classification
    from hivemall_tpu.io.sparse import pow2_len, score_batches
    ds, _ = synthetic_classification(100, 50, seed=3)
    L = pow2_len(ds.max_row_len)
    seen = np.zeros(100, bool)
    shapes = []
    for s, b in score_batches(ds, 32):
        nv = b.n_valid or b.batch_size
        assert np.array_equal(np.asarray(b.label[:nv]),
                              ds.labels[s:s + nv])
        seen[s:s + nv] = True
        shapes.append(b.idx.shape)
    assert seen.all()
    # body at (32, L); the 4-row tail padded to its pow2 bucket (>= 8),
    # not the full batch size
    assert shapes[:-1] == [(32, L)] * 3
    assert shapes[-1] == (8, L)


def test_offline_scoring_unchanged_by_bucketing():
    from hivemall_tpu.io.libsvm import synthetic_classification
    from hivemall_tpu.models.linear import GeneralClassifier
    ds, _ = synthetic_classification(70, 40, seed=5)
    t = GeneralClassifier("-dims 512 -loss logloss -mini_batch 32")
    t.fit(ds)
    proba = t.predict_proba(ds)
    # reference: per-row margins computed directly from the weight table
    w = t._finalized_weights()
    ref = np.empty(len(ds), np.float32)
    for i in range(len(ds)):
        idx, val = ds.row(i)
        ref[i] = float((w[idx] * val).sum())
    ref = np.where(ref >= 0, 1.0 / (1.0 + np.exp(-ref)),
                   np.exp(ref) / (1.0 + np.exp(ref)))
    np.testing.assert_allclose(proba, ref, rtol=1e-5, atol=1e-6)


# --- engine -----------------------------------------------------------------

OPTS = "-dims 1024 -loss logloss -opt adagrad -mini_batch 32"


@pytest.fixture()
def trained(tmp_path):
    from hivemall_tpu.io.libsvm import synthetic_classification
    from hivemall_tpu.models.linear import GeneralClassifier
    ds, _ = synthetic_classification(120, 64, seed=11)
    t = GeneralClassifier(OPTS)
    t.fit(ds)
    path = os.path.join(tmp_path, f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(path)
    return t, ds, str(tmp_path), path


def _engine(ckdir, **kw):
    from hivemall_tpu.serve.engine import PredictEngine
    kw.setdefault("warmup", False)
    return PredictEngine("train_classifier", OPTS, checkpoint_dir=ckdir,
                         **kw)


def _rows_of(ds, n):
    out = []
    for i in range(n):
        idx, val = ds.row(i)
        out.append([f"{int(a)}:{float(v)!r}" for a, v in zip(idx, val)])
    return out


def test_engine_bitmatches_offline_predict_proba(trained):
    from hivemall_tpu.io.sparse import SparseDataset
    t, ds, ckdir, _ = trained
    eng = _engine(ckdir)
    rows = _rows_of(ds, 17)
    parsed = [t._parse_row(r) for r in rows]
    ref = t.predict_proba(SparseDataset.from_rows(parsed,
                                                  [1.0] * len(parsed)))
    # batched and one-at-a-time land in different (B, L) buckets; both
    # must bit-match the offline path (padding is inert)
    got = eng.predict_rows([eng.parse(r) for r in rows])
    assert np.array_equal(got, ref)
    one = np.concatenate([eng.predict_rows([eng.parse(r)]) for r in rows])
    assert np.array_equal(one, ref)


def test_engine_requires_a_model_source(tmp_path):
    from hivemall_tpu.serve.engine import PredictEngine
    with pytest.raises(ValueError, match="model source"):
        PredictEngine("train_classifier", OPTS)
    with pytest.raises(FileNotFoundError):
        PredictEngine("train_classifier", OPTS,
                      checkpoint_dir=str(tmp_path))


def test_engine_warmup_compiles_buckets(trained):
    _, _, ckdir, _ = trained
    eng = _engine(ckdir, max_batch=16)
    assert eng.warmup(8) == 5          # B = 1,2,4,8,16


def test_engine_ignores_corrupt_bundle_and_swaps_newer(trained):
    t, ds, ckdir, _ = trained
    eng = _engine(ckdir)
    step0 = eng.model_step
    # a corrupt bundle with the HIGHEST step: must be skipped (and
    # remembered), never served
    bad = os.path.join(ckdir, f"{t.NAME}-step{step0 + 999:010d}.npz")
    with open(bad, "wb") as f:
        f.write(b"this is not a checkpoint bundle")
    assert eng.poll() is False
    assert eng.model_step == step0
    assert eng.reload_failures == 1
    assert "step" in (eng.last_reload_error or "")
    eng.poll()
    assert eng.reload_failures == 1    # known-bad file not re-read
    # train on: a newer VALID bundle behind the corrupt one swaps in
    t.fit(ds)
    good = os.path.join(ckdir, f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(good)
    assert eng.poll() is True
    assert eng.model_step == t._t and eng.reloads == 1
    # served scores now come from the NEW weights
    rows = _rows_of(ds, 5)
    from hivemall_tpu.io.sparse import SparseDataset
    parsed = [t._parse_row(r) for r in rows]
    ref = t.predict_proba(SparseDataset.from_rows(parsed, [1.0] * 5))
    assert np.array_equal(eng.predict_rows([eng.parse(r) for r in rows]),
                          ref)


def test_engine_rejects_wide_rows_and_out_of_tree_reload(trained):
    t, ds, ckdir, path = trained
    eng = _engine(ckdir, max_row_features=4)
    with pytest.raises(ValueError, match="max_row_features"):
        eng.parse([f"{i}:1" for i in range(1, 7)])   # 6 features > cap
    eng.parse(["1:1", "2:1"])                        # under the cap: fine
    # /reload trust boundary: only paths INSIDE the watched dir load
    outside = os.path.join(os.path.dirname(ckdir), "planted.npz")
    with pytest.raises(ValueError, match="outside the watched"):
        eng.reload(outside)
    assert eng.reload(path) is True                  # in-tree: allowed
    # a bundle-pinned server (no watched dir) rejects any explicit path
    from hivemall_tpu.serve.engine import PredictEngine
    eng2 = PredictEngine("train_classifier", OPTS, bundle=path,
                         warmup=False)
    with pytest.raises(ValueError, match="watched checkpoint dir"):
        eng2.reload(path)


def test_engine_readiness_gates_and_background_warmup(trained):
    """warmup="background": the engine is constructed NOT ready (healthz
    must 503 so a router/LB keeps the replica out of rotation), flips
    ready when the warmup thread finishes; explicit warmup=False means
    the operator opted into cold serving => ready immediately."""
    _, _, ckdir, _ = trained
    eng = _engine(ckdir)                   # warmup=False
    assert eng.ready                       # opted out => ready
    ev = threading.Event()

    from hivemall_tpu.serve.engine import PredictEngine
    orig = PredictEngine._warm_model

    def slow_warm(self, m, warmup_len):
        assert ev.wait(10)
        return orig(self, m, warmup_len)

    PredictEngine._warm_model = slow_warm
    try:
        bg = PredictEngine("train_classifier", OPTS, checkpoint_dir=ckdir,
                           warmup="background", max_batch=4)
        assert not bg.ready                # cold: gated out
        ev.set()
        assert bg.wait_ready(10)
        assert bg.ready
        bg.close()
    finally:
        PredictEngine._warm_model = orig
    assert eng.bundle_age_seconds is not None
    assert eng.bundle_age_seconds >= 0


def test_http_healthz_reports_readiness(trained):
    import urllib.error
    from hivemall_tpu.serve.engine import PredictEngine
    from hivemall_tpu.serve.http import PredictServer
    _, _, ckdir, _ = trained
    ev = threading.Event()
    orig = PredictEngine._warm_model

    def slow_warm(self, m, warmup_len):
        assert ev.wait(10)
        return orig(self, m, warmup_len)

    PredictEngine._warm_model = slow_warm
    try:
        eng = PredictEngine("train_classifier", OPTS, checkpoint_dir=ckdir,
                            warmup="background", max_batch=4)
        srv = PredictServer(eng, port=0, watch=False).start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/healthz", timeout=10)
            assert ei.value.code == 503        # warming => gated
            warming = json.loads(ei.value.read())
            assert warming["status"] == "warming"
            assert warming["ready"] is False
            ev.set()
            assert eng.wait_ready(10)
            hz = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=10).read())
            assert hz["status"] == "ok" and hz["ready"] is True
            # readiness body carries the gating/diagnosis fields the
            # fleet manager folds into its cached obs section
            for k in ("model_step", "bundle_age_seconds", "queue_depth",
                      "requests", "shed", "expired"):
                assert k in hz, k
        finally:
            srv.stop()
    finally:
        PredictEngine._warm_model = orig


def test_engine_prewarms_scorer_before_swap(trained):
    """A warmed engine never swaps in a cold scorer: the reload path
    warms the NEW model's buckets before the atomic ref swap."""
    from hivemall_tpu.serve.engine import PredictEngine
    t, ds, ckdir, _ = trained
    eng = _engine(ckdir, max_batch=4)
    eng.warmup(8)
    warmed = []
    orig = PredictEngine._warm_model

    def spy(self, m, warmup_len):
        warmed.append(m.step)
        return orig(self, m, warmup_len)

    PredictEngine._warm_model = spy
    try:
        t.fit(ds)
        p2 = os.path.join(ckdir, f"{t.NAME}-step{t._t:010d}.npz")
        t.save_bundle(p2)
        assert eng.poll() is True
        assert warmed == [t._t]            # new version warmed pre-swap
        assert eng.ready
    finally:
        PredictEngine._warm_model = orig


def test_engine_sharded_scorer_matches_unsharded(trained):
    """The GSPMD serving path (`-mesh dp=..,tp=..` in the serve options):
    tables load tp-sharded across the virtual 8-device CPU mesh, request
    batches place over dp when the bucket divides — and scores BIT-match
    the unsharded engine on the same bundle."""
    from hivemall_tpu.serve.engine import PredictEngine
    t, ds, ckdir, path = trained
    plain = _engine(ckdir)
    sharded = PredictEngine("train_classifier", OPTS + " -mesh dp=2,tp=4",
                            checkpoint_dir=ckdir, warmup=False)
    w = sharded._model.trainer.w
    shard_rows = w.sharding.shard_shape(w.shape)[0]
    assert shard_rows == w.shape[0] // 4   # tp=4 table sharding
    assert sharded.obs_section()["mesh"] == "dp=2,tp=4"
    rows = _rows_of(ds, 9)                 # pow2 bucket 16 (dp-divisible)
    a = plain.predict_rows([plain.parse(r) for r in rows])
    b = sharded.predict_rows([sharded.parse(r) for r in rows])
    assert np.array_equal(a, b)
    # single-row requests land in the B=1 bucket (< dp): replicated path
    one = np.concatenate([sharded.predict_rows([sharded.parse(r)])
                          for r in rows])
    assert np.array_equal(one, a)


def test_engine_swap_keeps_inflight_model(trained):
    """A hot swap mid-batch never mixes versions: the batch scored with
    the ref it grabbed."""
    t, ds, ckdir, _ = trained
    eng = _engine(ckdir)
    m0 = eng._model
    t.fit(ds)
    p2 = os.path.join(ckdir, f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(p2)
    assert eng.poll() is True
    assert eng._model is not m0        # new ref swapped in
    # the OLD ref still scores (an in-flight request holding it finishes)
    rows = [eng.parse(r) for r in _rows_of(ds, 3)]
    out_old = np.asarray(m0.scorer(eng._pad(rows, m0.needs_field)))[:3]
    assert out_old.shape == (3,)


# --- HTTP front end + obs ---------------------------------------------------

def _post(url, obj, timeout=15.0):
    req = urllib.request.Request(url, json.dumps(obj).encode(),
                                 {"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=timeout).read())


def test_http_predict_healthz_reload_and_obs(trained):
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.serve.http import PredictServer
    t, ds, ckdir, _ = trained
    eng = _engine(ckdir)
    srv = PredictServer(eng, port=0, max_delay_ms=1.0, watch=False).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        rows = _rows_of(ds, 4)
        r = _post(base + "/predict", {"rows": rows})
        parsed = [t._parse_row(x) for x in rows]
        ref = t.predict_proba(SparseDataset.from_rows(parsed, [1.0] * 4))
        assert np.array_equal(np.asarray(r["scores"], np.float32), ref)
        assert r["model_step"] == eng.model_step and r["n"] == 4
        # single-row "features" form
        r1 = _post(base + "/predict", {"features": rows[0]})
        assert np.float32(r1["scores"][0]) == ref[0]
        # healthz
        hz = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=10).read())
        assert hz["status"] == "ok" and hz["model_step"] == eng.model_step
        # bad request -> 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base + "/predict", {"nope": 1})
        assert ei.value.code == 400
        # reload with nothing newer -> no swap, but a clean 200
        rr = _post(base + "/reload", {})
        assert rr["reloaded"] is False
        assert rr["model_step"] == eng.model_step
        # obs: serve section present in /snapshot and /metrics
        snap = json.loads(urllib.request.urlopen(
            base + "/snapshot", timeout=10).read())
        sv = snap["serve"]
        for k in ("qps", "queue_depth", "batch_hist", "shed",
                  "model_step", "model_age_seconds"):
            assert k in sv, k
        assert sv["requests"] >= 2
        prom = urllib.request.urlopen(base + "/metrics",
                                      timeout=10).read().decode()
        assert "hivemall_tpu_serve_model_step" in prom
        assert "hivemall_tpu_serve_shed 0" in prom
        # the central registry carries the same section (any obs surface
        # — the trainer's -obs_port server included — would export it)
        from hivemall_tpu.obs.registry import registry
        assert "serve" in registry.snapshot()
    finally:
        srv.stop()


def test_http_deadline_maps_to_504(trained):
    from hivemall_tpu.serve.http import PredictServer
    _, ds, ckdir, _ = trained
    eng = _engine(ckdir)
    srv = PredictServer(eng, port=0, max_delay_ms=1.0, watch=False).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        gate = threading.Event()
        orig = srv.batcher._predict

        def slow(rows):
            gate.wait(timeout=10)
            return orig(rows)

        srv.batcher._predict = slow
        rows = _rows_of(ds, 1)
        first = threading.Thread(
            target=lambda: _post(base + "/predict", {"rows": rows}))
        first.start()                  # occupies the dispatch thread
        time.sleep(0.05)
        err = {}

        def second():
            try:
                _post(base + "/predict",
                      {"rows": rows, "deadline_ms": 10})
            except urllib.error.HTTPError as e:
                err["code"] = e.code
        t2 = threading.Thread(target=second)
        t2.start()
        # deterministic ordering: wait until the second request is
        # actually QUEUED, then let its deadline lapse, then release —
        # fixed sleeps alone race the HTTP connect under CI load
        assert _wait(lambda: srv.batcher.queue_depth == 1)
        time.sleep(0.05)
        gate.set()
        first.join(10)
        t2.join(10)
        assert err.get("code") == 504
        assert srv.batcher.expired == 1
    finally:
        gate.set()
        srv.stop()


# --- request tracing + per-hop breakdown + histograms ------------------------

def test_http_hop_breakdown_and_trace_echo(trained):
    """Every /predict response carries x-hivemall-hop whose parts sum to
    its total; an x-hivemall-trace id is echoed and tags the serve spans
    in the process tracer's Chrome export."""
    from hivemall_tpu.obs.trace import get_tracer
    from hivemall_tpu.serve.http import KeepAliveClient, PredictServer
    _, ds, ckdir, _ = trained
    eng = _engine(ckdir)
    srv = PredictServer(eng, port=0, max_delay_ms=1.0, watch=False,
                        slo=False).start()
    tracer = get_tracer()
    tracer.reset()
    tracer.enable()
    try:
        cli = KeepAliveClient("127.0.0.1", srv.port)
        rows = _rows_of(ds, 2)
        code, _ = cli.post_json("/predict", {"rows": rows},
                                headers={"x-hivemall-trace": "t-9"})
        assert code == 200
        hdrs = {k.lower(): v for k, v in cli.last_headers.items()}
        assert hdrs["x-hivemall-trace"] == "t-9"
        hop = dict(kv.split("=")
                   for kv in hdrs["x-hivemall-hop"].split(","))
        assert set(hop) == {"parse", "queue", "assemble", "predict",
                            "other", "total"}
        total = float(hop.pop("total"))
        parts = sum(float(v) for v in hop.values())
        # "other" closes the residual, so the decomposition is additive
        assert parts == pytest.approx(total, abs=0.02)
        assert float(hop["predict"]) > 0
        # an UNtraced request still gets the breakdown, no trace echo
        code, _ = cli.post_json("/predict", {"rows": rows})
        hdrs = {k.lower(): v for k, v in cli.last_headers.items()}
        assert "x-hivemall-hop" in hdrs
        assert "x-hivemall-trace" not in hdrs
        # the trace id tagged the serve spans
        evs = tracer.chrome_dict()["traceEvents"]
        tagged = {e["name"] for e in evs
                  if (e.get("args") or {}).get("trace") == "t-9"}
        assert {"serve.enqueue", "serve.batch",
                "serve.predict"} <= tagged
        cli.close()
    finally:
        tracer.disable()
        tracer.reset()
        srv.stop()


def test_http_metrics_exports_latency_and_batch_histograms(trained):
    import urllib.request
    from hivemall_tpu.serve.http import PredictServer
    _, ds, ckdir, _ = trained
    eng = _engine(ckdir)
    srv = PredictServer(eng, port=0, max_delay_ms=1.0, watch=False,
                        slo=False).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        for _ in range(3):
            _post(base + "/predict", {"rows": _rows_of(ds, 2)})
        prom = urllib.request.urlopen(base + "/metrics",
                                      timeout=10).read().decode()
        for fam in ("hivemall_tpu_serve_request_latency_seconds",
                    "hivemall_tpu_serve_batch_size_rows"):
            assert f"# TYPE {fam} histogram" in prom
            assert f'{fam}_bucket{{le="+Inf"}}' in prom
            assert f"{fam}_sum" in prom and f"{fam}_count" in prom
        # cumulative consistency: +Inf bucket == _count
        import re as _re
        inf = int(_re.search(
            r'request_latency_seconds_bucket\{le="\+Inf"\} (\d+)',
            prom).group(1))
        cnt = int(_re.search(
            r"request_latency_seconds_count (\d+)", prom).group(1))
        assert inf == cnt >= 3
    finally:
        srv.stop()


def test_batcher_score_moments_and_hop_attribute():
    b = MicroBatcher(lambda rows: np.full(len(rows), 0.25, np.float32),
                     max_batch=8, max_delay_ms=0.5)
    try:
        futs = [b.submit([("r", i)]) for i in range(4)]
        for f in futs:
            f.result(5)
        st = b.stats()
        assert st["score_mean"] == pytest.approx(0.25)
        assert st["score_std"] == pytest.approx(0.0, abs=1e-6)
        assert st["request_latency_seconds"]["count"] == 4
        hop = futs[0].hop
        assert hop["queue_s"] >= 0 and hop["predict_s"] >= 0
        tot = b.slo_totals()
        assert tot["requests"] == 4 and tot["score_n"] == 4
        assert tot["latency"]["count"] == 4
    finally:
        b.close()
