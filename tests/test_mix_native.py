"""Native (C++) MIX server: protocol + semantics parity with the asyncio
implementation, driven through the SAME MixClient / trainer surface the
Python server's tests use (native/mix_server.cpp, parallel/mix_native.py).
Skips cleanly where no g++ toolchain exists."""

import json
import socket
import struct

import numpy as np
import pytest

from hivemall_tpu.parallel.mix_native import NativeMixServer, native_available
from hivemall_tpu.parallel.mix_service import (EVENT_ARGMIN_KLD,
                                               EVENT_AVERAGE,
                                               EVENT_CLOSEGROUP, EVENT_STATS,
                                               MixClient, MixMessage,
                                               MixServer)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="no g++ toolchain for the native "
                                       "mix server")


def _roundtrip(sock, msg):
    sock.sendall(msg.encode())
    (ln,) = struct.unpack("<I", sock.recv(4, socket.MSG_WAITALL))
    body = b""
    while len(body) < ln:
        body += sock.recv(ln - len(body))
    return MixMessage.decode(body)


def test_native_server_mixclient_roundtrip():
    with NativeMixServer() as srv:
        c = MixClient(f"127.0.0.1:{srv.port}", "g1", threshold=1)
        c._connect()
        msg = MixMessage(EVENT_AVERAGE, "g1", np.asarray([5], np.int64),
                         np.asarray([2.0], np.float32),
                         np.asarray([1.0], np.float32),
                         np.asarray([1], np.int32))
        c._sock.sendall(msg.encode())
        assert c._read_reply().weights[0] == 2.0
        msg2 = MixMessage(EVENT_AVERAGE, "g1", np.asarray([5], np.int64),
                          np.asarray([4.0], np.float32),
                          np.asarray([1.0], np.float32),
                          np.asarray([1], np.int32))
        c._sock.sendall(msg2.encode())
        assert abs(c._read_reply().weights[0] - 3.0) < 1e-6
        c.close_group()


def test_native_matches_python_fold_semantics():
    """Same message sequence (dup keys, delta weights, KLD covar merge)
    against both servers -> identical replies."""
    rng = np.random.default_rng(3)
    msgs = []
    for i in range(6):
        n = int(rng.integers(1, 12))
        msgs.append(MixMessage(
            EVENT_AVERAGE if i % 2 else EVENT_ARGMIN_KLD,
            "g", rng.integers(0, 9, n).astype(np.int64),
            rng.normal(size=n).astype(np.float32),
            rng.uniform(0.1, 2.0, n).astype(np.float32),
            rng.integers(1, 5, n).astype(np.int32)))

    def run(server):
        out = []
        s = socket.create_connection(("127.0.0.1", server.port))
        try:
            for m in msgs:
                r = _roundtrip(s, m)
                out.append((r.weights.copy(), r.covars.copy()))
        finally:
            s.close()
        return out

    with NativeMixServer() as nat:
        got_n = run(nat)
    py = MixServer().start()
    try:
        got_p = run(py)
    finally:
        py.stop()
    for (wn, cn), (wp, cp) in zip(got_n, got_p):
        np.testing.assert_allclose(wn, wp, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(cn, cp, rtol=1e-6, atol=1e-7)


def test_native_closegroup_and_stats():
    with NativeMixServer() as srv:
        s = socket.create_connection(("127.0.0.1", srv.port))
        try:
            one = lambda ev, g, k, w: MixMessage(   # noqa: E731
                ev, g, np.asarray([k], np.int64),
                np.asarray([w], np.float32), np.asarray([1.0], np.float32),
                np.asarray([1], np.int32))
            _roundtrip(s, one(EVENT_AVERAGE, "gone", 1, 10.0))
            # closegroup drops the session: the next fold restarts at w
            s.sendall(one(EVENT_CLOSEGROUP, "gone", 0, 0.0).encode())
            r = _roundtrip(s, one(EVENT_AVERAGE, "gone", 1, 4.0))
            assert r.weights[0] == 4.0
            st = json.loads(_roundtrip(
                s, MixMessage(EVENT_STATS, "", np.zeros(0, np.int64),
                              np.zeros(0, np.float32),
                              np.zeros(0, np.float32),
                              np.zeros(0, np.int32))).group)
            assert st["impl"] == "native" and st["requests"] == 2
            assert st["groups"] == 1
        finally:
            s.close()


def test_trainers_converge_via_native_mix():
    """The Python-server trainer convergence test, against the C++ server:
    two replicas' shared-feature weights pull together through -mix."""
    from hivemall_tpu.models.linear import GeneralClassifier

    def train(mix_opts: str):
        opts = ("-dims 64 -mini_batch 8 -eta fixed -eta0 0.5 -reg no "
                + mix_opts)
        a = GeneralClassifier(opts)
        b = GeneralClassifier(opts)
        for i in range(64):
            a.process(["1:1.0"], 1)
            b.process(["1:1.0"], -1 if i % 4 == 0 else 1)
        return dict(a.close()), dict(b.close()), a, b

    with NativeMixServer() as srv:
        ma, mb, a, b = train(f"-mix 127.0.0.1:{srv.port} -mix_session s1 "
                             f"-mix_threshold 2")
        assert a._mixer.exchanges > 0 and b._mixer.exchanges > 0
        mixed_gap = abs(ma["1"] - mb["1"])
    ua, ub, _, _ = train("")
    unmixed_gap = abs(ua["1"] - ub["1"])
    assert mixed_gap < 0.5 * unmixed_gap, (mixed_gap, unmixed_gap)
