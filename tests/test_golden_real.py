"""Golden convergence on REAL datasets — activates when drop-ins appear.

The committed fragments (test_golden.py) are dataset-SHAPED synthetic
stand-ins: real a9a/news20/MovieLens are unreachable from this offline
environment (VERDICT r2 weak #6). This module is the re-validation hook:
drop the real files into ``tests/resources/real/`` with the names below
and these tests activate automatically — no code change needed. Until
then every test skips with a pointer.

Expected drop-ins (reference quality baselines in parentheses):
  real/a9a            LIBSVM train  (AdaGrad logloss@1ep ~0.33, AUC ~0.90)
  real/a9a.t          LIBSVM test
  real/news20.binary  LIBSVM        (AUC ~0.97 on a held-out tail split)
  real/ml-100k.tsv    user \t item \t rating (MF RMSE < 1.0 @2 epochs)
  real/text8          unzipped text8 corpus (word2vec similarity sanity:
                      related pairs beat unrelated on >= 75%)
"""

import os

import numpy as np
import pytest

from hivemall_tpu.frame.evaluation import auc, logloss, rmse
from hivemall_tpu.io.libsvm import read_libsvm

REAL = os.path.join(os.path.dirname(__file__), "resources", "real")


def _need(*names):
    paths = [os.path.join(REAL, n) for n in names]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        pytest.skip(f"real-data drop-ins absent: {missing} "
                    "(synthetic fragments cover CI; see module docstring)")
    return paths


def test_a9a_real_logreg():
    train_p, test_p = _need("a9a", "a9a.t")
    from hivemall_tpu.models.classifier import GeneralClassifierTrainer
    tr = read_libsvm(train_p)
    te = read_libsvm(test_p)
    t = GeneralClassifierTrainer("-dims 262144 -mini_batch 256 "
                                 "-opt adagrad -loss logloss")
    t.fit(tr, epochs=1, shuffle=True)
    scores = t.decision_function(te)
    y = (np.asarray(te.labels) > 0).astype(np.int32)
    assert auc(y, scores) > 0.88
    assert logloss(y, 1 / (1 + np.exp(-scores))) < 0.40


def test_news20_real_auc():
    (p,) = _need("news20.binary")
    from hivemall_tpu.models.classifier import GeneralClassifierTrainer
    from hivemall_tpu.io.sparse import SparseDataset
    ds = read_libsvm(p)
    n = len(ds.labels)
    cut = int(n * 0.9)

    def span(a, b):
        s0, s1 = ds.indptr[a], ds.indptr[b]
        return SparseDataset(ds.indices[s0:s1],
                             ds.indptr[a:b + 1] - s0,
                             ds.values[s0:s1], ds.labels[a:b])

    tr, te = span(0, cut), span(cut, n)
    t = GeneralClassifierTrainer("-dims 2097152 -mini_batch 256 "
                                 "-opt adagrad -loss logloss")
    t.fit(tr, epochs=1)
    scores = t.decision_function(te)
    y = (np.asarray(te.labels) > 0).astype(np.int32)
    assert auc(y, scores) > 0.95


def test_movielens_real_mf_rmse():
    (p,) = _need("ml-100k.tsv")
    from hivemall_tpu.models.mf import MFAdaGradTrainer
    raw = np.loadtxt(p, delimiter="\t", dtype=np.float64)
    u = raw[:, 0].astype(np.int32)
    i = raw[:, 1].astype(np.int32)
    r = raw[:, 2].astype(np.float32)
    n = len(r)
    cut = int(n * 0.9)
    t = MFAdaGradTrainer(f"-factors 32 -users {u.max() + 1} "
                         f"-items {i.max() + 1} -mini_batch 4096")
    t.fit(u[:cut], i[:cut], r[:cut], epochs=2)
    pred = t.predict(u[cut:], i[cut:])
    assert rmse(r[cut:], pred) < 1.0


def test_text8_real_word2vec_similarity():
    """BASELINE config #4 quality side (VERDICT r4 weak #7): drop the
    text8 corpus (mattmahoney.net/dc/text8.zip, unzipped) into
    tests/resources/real/text8 and this trains SkipGram-NS on the first
    ~2M tokens, then asserts a word-similarity sanity metric: for known
    related/unrelated word pairs, cosine(related) must beat
    cosine(unrelated) on a clear majority — the cheap, stable slice of
    the wordsim/analogy evaluations the reference families are judged
    by. The metric value is printed for the record."""
    (p,) = _need("text8")
    from hivemall_tpu.models.word2vec import Word2VecTrainer

    with open(p) as f:
        toks = f.read(12_000_000).split()       # ~2M tokens
    t = Word2VecTrainer("-dim 100 -window 5 -neg 10 -min_count 5 "
                        "-mini_batch 16384 -sample 1e-4 -iter 2")
    t.train([toks])
    vecs = t.vectors()

    def cos(a, b):
        va, vb = vecs.get(a), vecs.get(b)
        if va is None or vb is None:
            return None
        return float(np.dot(va, vb)
                     / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))

    pairs = [("king", "queen", "king", "cat"),
             ("man", "woman", "man", "tree"),
             ("paris", "france", "paris", "dog"),
             ("water", "river", "water", "king"),
             ("three", "four", "three", "music"),
             ("day", "night", "day", "metal"),
             ("good", "bad", "good", "seven"),
             ("war", "army", "war", "fruit")]
    wins, total, margins = 0, 0, []
    for a, b, c, d in pairs:
        s_rel, s_unrel = cos(a, b), cos(c, d)
        if s_rel is None or s_unrel is None:
            continue
        total += 1
        margins.append(s_rel - s_unrel)
        if s_rel > s_unrel:
            wins += 1
    assert total >= 5, f"vocabulary too small ({total} pairs scored)"
    print(f"text8 similarity: {wins}/{total} related>unrelated, "
          f"mean margin {np.mean(margins):.3f}")
    assert wins / total >= 0.75, (wins, total, margins)
