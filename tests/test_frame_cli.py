"""Frame (HivemallOps analog) + CLI end-to-end (systemtest analog,
SURVEY.md §5.4: real workflow through the public operational surface)."""

import json
import subprocess
import sys

import numpy as np
import pytest

from hivemall_tpu.frame.dataframe import Frame
from hivemall_tpu.ftvec import add_bias


def test_frame_basics():
    f = Frame({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    assert len(f) == 3
    assert f.select("a").columns == ["a"]
    g = f.with_column("c", [7, 8, 9]).filter([True, False, True])
    assert g["c"] == [7, 9]
    assert list(g.rows())[1]["b"] == "z"


def test_frame_train_method_and_each_top_k():
    rng = np.random.default_rng(0)
    feats, labels = [], []
    for _ in range(200):
        y = 1 if rng.random() < 0.5 else -1
        feats.append([f"{1 if y > 0 else 2}:1.0"])
        labels.append(y)
    df = Frame({"features": feats, "label": labels})
    df = df.map_column("features", "features", add_bias)
    model = df.train_classifier("features", "label",
                                "-dims 256 -mini_batch 16 -eta0 0.5")
    assert "feature" in model.columns
    w = dict(zip(model["feature"], model["weight"]))
    assert w["1"] > 0 > w["2"]

    scores = Frame({"g": ["a", "a", "b"], "s": [0.1, 0.9, 0.5],
                    "item": ["i1", "i2", "i3"]})
    top = scores.each_top_k(1, "g", "s", "item")
    assert top["item"] == ["i2", "i3"]
    assert top["rank"] == [1, 1]


def test_frame_unknown_trainer_raises():
    with pytest.raises(AttributeError):
        Frame({"x": [1]}).train_nonexistent


def _cli(args):
    import hivemall_tpu.cli.main as m
    return m.main(args)


def test_cli_train_predict_roundtrip(tmp_path, capsys):
    from hivemall_tpu.io.libsvm import synthetic_classification, write_libsvm
    ds, _ = synthetic_classification(400, 50, seed=21)
    train_p = str(tmp_path / "train.libsvm")
    model_p = str(tmp_path / "model.tsv")
    out_p = str(tmp_path / "scores.tsv")
    write_libsvm(ds, train_p)

    rc = _cli(["train", "--algo", "train_classifier", "--input", train_p,
               "--options",
               "-dims 256 -loss logloss -opt adagrad -reg no -eta fixed "
               "-eta0 0.3 -mini_batch 64 -iters 3",
               "--model", model_p])
    assert rc == 0
    train_out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # the final record is the obs-registry snapshot; the run summary is
    # its `run` section. examples counts PROCESSED rows: 400 x -iters 3
    assert train_out["run"]["examples"] == 1200
    assert "pipeline" in train_out and "train" in train_out

    rc = _cli(["predict", "--algo", "train_classifier", "--model", model_p,
               "--input", train_p, "--output", out_p,
               "--options", "-dims 256", "--metric", "auc"])
    assert rc == 0
    pred_out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert pred_out["auc"] > 0.9
    assert len(open(out_p).readlines()) == 400


def test_cli_predict_fm_classification_scores_are_probabilities(tmp_path,
                                                                capsys):
    """FM sets -classification per instance (class attr stays False); the
    predict dispatch must still score in probability space for logloss."""
    from hivemall_tpu.io.libsvm import synthetic_classification, write_libsvm
    ds, _ = synthetic_classification(300, 40, seed=5)
    train_p = str(tmp_path / "train.libsvm")
    model_p = str(tmp_path / "model.msgpack")
    out_p = str(tmp_path / "scores.tsv")
    write_libsvm(ds, train_p)

    opts = "-dims 128 -factors 4 -classification -mini_batch 64 -iters 2"
    rc = _cli(["train", "--algo", "train_fm", "--input", train_p,
               "--options", opts, "--model", model_p])
    assert rc == 0
    capsys.readouterr()

    rc = _cli(["predict", "--algo", "train_fm", "--model", model_p,
               "--input", train_p, "--output", out_p,
               "--options", opts, "--metric", "logloss"])
    assert rc == 0
    pred_out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert 0.0 < pred_out["logloss"] < 0.69  # better than chance, in prob space
    scores = [float(l.split("\t")[1]) for l in open(out_p)]
    assert all(0.0 <= s <= 1.0 for s in scores)


def test_cli_define_all_and_help(capsys):
    assert _cli(["define-all"]) == 0
    ddl = capsys.readouterr().out
    assert "train_ffm" in ddl and "each_top_k" in ddl
    assert _cli(["help", "train_ffm"]) == 0
    h = capsys.readouterr().out
    assert "-factors" in h and "hivemall.fm" in h


def test_cli_train_bundle_resume(tmp_path, capsys):
    from hivemall_tpu.io.libsvm import synthetic_classification, write_libsvm
    ds, _ = synthetic_classification(200, 30, seed=4)
    train_p = str(tmp_path / "t.libsvm")
    bundle_p = str(tmp_path / "ck.npz")
    model_p = str(tmp_path / "m.tsv")
    write_libsvm(ds, train_p)
    opts = "-dims 256 -loss logloss -opt adagrad -mini_batch 64"

    rc = _cli(["train", "--algo", "train_classifier", "--input", train_p,
               "--options", opts, "--save-bundle", bundle_p])
    assert rc == 0 and json.loads(
        capsys.readouterr().out.strip().splitlines()[-1]
    )["run"]["examples"] == 200

    rc = _cli(["train", "--algo", "train_classifier", "--input", train_p,
               "--options", opts, "--load-bundle", bundle_p,
               "--model", model_p])
    assert rc == 0
    capsys.readouterr()
    assert len(open(model_p).readlines()) > 0


def test_frame_group_by_model_averaging():
    """HivemallGroupedDataset analog: the post-hoc model-averaging query
    GROUP BY feature + voted_avg(weight) (SURVEY.md §3.17 row 3)."""
    from hivemall_tpu.frame.dataframe import Frame
    # two replicas' model rows for the same features
    f = Frame({"feature": ["a", "b", "a", "b", "c"],
               "weight": [1.0, -2.0, 3.0, -4.0, 5.0]})
    out = f.group_by("feature").agg(weight=("weight", "voted_avg"),
                                    n=("weight", "count"))
    assert out["feature"] == ["a", "b", "c"]
    assert out["weight"] == [2.0, -3.0, 5.0]    # same-sign majority mean
    assert out["n"] == [2, 2, 1]
    # callables and numpy reductions work too
    out2 = f.group_by("feature").agg(mx=("weight", "max"),
                                     all=("weight", "collect_all"))
    assert out2["mx"] == [3.0, -2.0, 5.0]
    assert out2["all"][0] == [1.0, 3.0]
    import pytest
    with pytest.raises(ValueError):
        f.group_by("feature").agg(x=("weight", "nope"))


def test_cli_ffm_train_predict_roundtrip(tmp_path, capsys):
    """FFM LIBSVM triples (field:index:value) work through BOTH CLI paths:
    train ingests fields, predict reloads and scores with them."""
    data_p = str(tmp_path / "ffm.libsvm")
    model_p = str(tmp_path / "ffm_model")
    with open(data_p, "w") as f:
        f.write("1 0:3:1 1:7:1\n-1 0:3:1 1:9:1\n"
                "1 0:5:1 1:9:1\n-1 0:5:1 1:7:1\n" * 8)
    opts = ("-dims 64 -factors 2 -fields 4 -classification -mini_batch 8 "
            "-iters 10 -eta0 0.3 -sigma 0.3")
    rc = _cli(["train", "--algo", "train_ffm", "--input", data_p,
               "--options", opts, "--model", model_p])
    assert rc == 0
    capsys.readouterr()
    rc = _cli(["predict", "--algo", "train_ffm", "--model", model_p,
               "--input", data_p,
               "--options", "-dims 64 -factors 2 -fields 4 -classification",
               "--metric", "auc"])
    assert rc == 0
    pred_out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert pred_out["auc"] > 0.95


def test_group_by_key_collision_raises():
    from hivemall_tpu.frame.dataframe import Frame
    import pytest
    f = Frame({"k": ["a", "b"], "v": [1.0, 2.0]})
    with pytest.raises(ValueError, match="collides"):
        f.group_by("k").agg(k=("v", "sum"))


def test_cli_train_from_parquet_shard_dir(tmp_path, capsys):
    """Out-of-core CLI path: --input <dir of parquet shards> streams
    through fit_stream (the NioStatefulSegment analog at corpus scale)."""
    import numpy as np
    from hivemall_tpu.io.arrow import write_parquet_shards
    from hivemall_tpu.io.libsvm import synthetic_classification
    ds, _ = synthetic_classification(300, 40, seed=5)
    shard_dir = str(tmp_path / "shards")
    write_parquet_shards(ds, shard_dir, rows_per_shard=100)
    rc = _cli(["train", "--algo", "train_classifier", "--input", shard_dir,
               "--options",
               "-dims 256 -loss logloss -opt adagrad -reg no -eta fixed "
               "-eta0 0.3 -mini_batch 64 -iters 2"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["run"]["examples"] == 600   # 300 rows x 2 epochs
    assert np.isfinite(out["run"]["cumulative_loss"])
