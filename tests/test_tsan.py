"""Lockset race sanitizer tests (hivemall_tpu.testing.tsan).

The dynamic half of the graftcheck v2 gate: the Eraser-style state
machine must detect a genuine write/write race (no common lock between
two writer threads) with both stacks attached, stay SILENT on the
lock-guarded twin, absorb the constructor->worker ownership handoff
without a false positive, and keep ``threading.Condition``/``Event``
working through the lock wrappers. The seeded-race non-vacuity pin
(the PR 11 ``PredictEngine.last_reload_error`` shape) runs in
``graftcheck --selfcheck`` too; here it is exercised in-process.
"""

import threading

import pytest

from hivemall_tpu.testing import tsan


@pytest.fixture
def sanitizer():
    """enable/disable bracket with full state cleanup."""
    registered = []

    def reg(cls):
        registered.append(cls)
        return tsan.register(cls)

    # auto_register=False: instrument only the test's own fixture
    # classes, not the whole serving fleet
    tsan.enable(auto_register=False)
    tsan.reset()
    try:
        yield reg
    finally:
        tsan.reset()
        for cls in registered:
            tsan.unregister(cls)
        tsan.disable()


def _run_threads(*targets):
    ts = [threading.Thread(target=t, name=f"w{i}")
          for i, t in enumerate(targets)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def test_unguarded_two_writer_race_detected(sanitizer):
    class Obj:
        def __init__(self):
            self.x = 0

    sanitizer(Obj)
    o = Obj()
    _run_threads(lambda: setattr(o, "x", 1), lambda: setattr(o, "x", 2))
    rs = tsan.races()
    assert len(rs) == 1
    r = rs[0]
    assert r["class"] == "Obj" and r["attr"] == "x"
    # both writers' stacks attached, and they are distinct threads
    assert r["stack_prev"] and r["stack_cur"]
    assert r["threads"][0] != r["threads"][1]


def test_guarded_writers_clean(sanitizer):
    class Obj:
        def __init__(self):
            self.lock = threading.Lock()
            self.x = 0

        def bump(self):
            with self.lock:
                self.x += 1

    sanitizer(Obj)
    o = Obj()
    _run_threads(o.bump, o.bump)
    assert tsan.races() == []


def test_constructor_handoff_no_false_positive(sanitizer):
    """init writes on the constructing thread + ONE worker thread
    writing lock-free is the blessed single-writer pattern
    (Thread.start() is the happens-before edge) — no race."""
    class Obj:
        def __init__(self):
            self.counter = 0

        def work(self):
            for _ in range(100):
                self.counter += 1

    sanitizer(Obj)
    o = Obj()
    t = threading.Thread(target=o.work)
    t.start()
    t.join()
    assert tsan.races() == []


def test_third_thread_after_handoff_detected(sanitizer):
    """Ownership hands off ONCE; a second distinct writer thread with no
    common lock is a race even though each write alone looks benign."""
    class Obj:
        def __init__(self):
            self.y = 0

    sanitizer(Obj)
    o = Obj()
    t1 = threading.Thread(target=lambda: setattr(o, "y", 1))
    t1.start()
    t1.join()
    t2 = threading.Thread(target=lambda: setattr(o, "y", 2))
    t2.start()
    t2.join()
    assert [r["attr"] for r in tsan.races()] == ["y"]


def test_distinct_attrs_tracked_independently(sanitizer):
    class Obj:
        def __init__(self):
            self.lock = threading.Lock()
            self.safe = 0
            self.racy = 0

        def writer(self):
            with self.lock:
                self.safe += 1
            self.racy += 1

    sanitizer(Obj)
    o = Obj()
    _run_threads(o.writer, o.writer, o.writer)
    assert sorted({r["attr"] for r in tsan.races()}) == ["racy"]


def test_rlock_and_condition_still_work(sanitizer):
    """Condition/Event compose over the wrappers: wait/notify and the
    private _release_save/_acquire_restore hooks keep lockset tracking
    consistent (writes under the condition lock count as guarded)."""
    class Q:
        def __init__(self):
            self.cv = threading.Condition()
            self.item = None

        def put(self, v):
            with self.cv:
                self.item = v
                self.cv.notify()

        def take(self):
            with self.cv:
                while self.item is None:
                    self.cv.wait(timeout=5)
                v, self.item = self.item, None
                return v

    sanitizer(Q)
    q = Q()
    got = []
    t = threading.Thread(target=lambda: got.append(q.take()))
    t.start()
    q.put(42)
    t.join(timeout=10)
    assert got == [42]
    assert tsan.races() == []


def test_event_works_under_wrappers(sanitizer):
    ev = threading.Event()
    t = threading.Thread(target=ev.set)
    t.start()
    assert ev.wait(timeout=5)
    t.join()


def test_disable_restores_lock_constructors():
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    was = tsan.enabled()
    tsan.enable(auto_register=False)
    try:
        assert threading.Lock is not orig_lock
    finally:
        if not was:
            tsan.disable()
    if not was:
        assert threading.Lock is orig_lock \
            and threading.RLock is orig_rlock


def test_maybe_enable_respects_env(monkeypatch):
    monkeypatch.delenv(tsan.ENV_FLAG, raising=False)
    assert tsan.maybe_enable() is False or tsan.enabled()
    # (already-enabled state from another test is tolerated; the
    # assertion is that an unset env never TURNS it on)
    if not tsan.enabled():
        monkeypatch.setenv(tsan.ENV_FLAG, "1")
        try:
            assert tsan.maybe_enable() is True
        finally:
            tsan.disable()


def test_race_log_emitted(sanitizer, tmp_path, monkeypatch):
    log = tmp_path / "races.jsonl"
    monkeypatch.setenv(tsan.ENV_LOG, str(log))

    class Obj:
        def __init__(self):
            self.z = 0

    sanitizer(Obj)
    o = Obj()
    _run_threads(lambda: setattr(o, "z", 1), lambda: setattr(o, "z", 2))
    assert tsan.races()
    import json
    lines = [json.loads(x) for x in log.read_text().splitlines()]
    assert lines and lines[0]["attr"] == "z"


def test_auto_register_instruments_fleet_without_prod_imports():
    """The layering pin: enable() signs the serving fleet up ITSELF
    (every _AUTO_REGISTER class ends up patched), and no serve/obs
    production module imports testing.tsan at module level — a prod
    image that prunes testing/ must still import the serving stack."""
    import ast
    import importlib
    from pathlib import Path

    assert not tsan.enabled()
    tsan.enable()
    try:
        for modname, clsname in tsan._AUTO_REGISTER:
            cls = getattr(importlib.import_module(modname), clsname)
            assert cls in tsan._patched, f"{clsname} not instrumented"
    finally:
        for modname, clsname in tsan._AUTO_REGISTER:
            cls = getattr(importlib.import_module(modname), clsname)
            tsan.unregister(cls)
        tsan.disable()

    import hivemall_tpu
    pkg = Path(hivemall_tpu.__file__).parent
    for sub in ("serve", "obs"):
        for path in sorted((pkg / sub).glob("*.py")):
            tree = ast.parse(path.read_text(encoding="utf-8"))
            for node in tree.body:          # MODULE level only: lazy
                #                             in-function imports (the
                #                             smokes' maybe_enable) are
                #                             the sanctioned gate
                if isinstance(node, ast.ImportFrom):
                    assert "testing" not in (node.module or ""), \
                        f"{path.name} imports testing at module level"
                elif isinstance(node, ast.Import):
                    assert not any("testing" in a.name
                                   for a in node.names), \
                        f"{path.name} imports testing at module level"


def test_selfcheck_race_nonvacuous():
    """The re-seeded PR 11 last_reload_error race: detected unguarded,
    silent when both writers take _reload_lock."""
    ok, detail = tsan.selfcheck_race()
    assert ok, detail
    assert "last_reload_error" in detail
    assert not tsan.enabled()            # bracket restored


def test_check_and_report_counts(sanitizer, capsys):
    class Obj:
        def __init__(self):
            self.w = 0

    sanitizer(Obj)
    o = Obj()
    _run_threads(lambda: setattr(o, "w", 1), lambda: setattr(o, "w", 2))
    n = tsan.check_and_report("unit")
    assert n == 1
    err = capsys.readouterr().err
    assert "RACE" in err and "Obj.w" in err
