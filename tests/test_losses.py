"""Loss loss()/dloss() consistency: explicit dloss must match autodiff."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hivemall_tpu.ops.losses import LOSSES, get_loss


@pytest.mark.parametrize("name", sorted(LOSSES))
def test_dloss_matches_autodiff(name):
    loss = LOSSES[name]
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.normal(0, 2, 64), jnp.float32)
    y = (jnp.asarray(rng.integers(0, 2, 64)) * 2 - 1).astype(jnp.float32) \
        if loss.for_classification else \
        jnp.asarray(rng.normal(0, 2, 64), jnp.float32)
    auto = jax.grad(lambda pp: loss.loss(pp, y).sum())(p)
    manual = loss.dloss(p, y)
    # subgradient points (hinge kinks etc.) can disagree; mask exact kinks
    ok = jnp.abs(auto - manual) < 1e-4
    frac = float(ok.mean())
    assert frac > 0.95, f"{name}: only {frac:.2f} agree"


def test_logloss_stable_extreme():
    loss = get_loss("logloss")
    v = loss.loss(jnp.asarray([100.0, -100.0]), jnp.asarray([1.0, 1.0]))
    assert np.isfinite(np.asarray(v)).all()
    assert float(v[0]) < 1e-6 and float(v[1]) > 50


def test_aliases():
    assert get_loss("logistic").name == "logloss"
    assert get_loss("hinge").name == "hingeloss"
    assert get_loss("SquaredLoss").name == "squaredloss"
    with pytest.raises(ValueError):
        get_loss("nope")


def test_classification_guard():
    assert not get_loss("huberloss").for_classification
    assert not get_loss("hingeloss").for_regression
