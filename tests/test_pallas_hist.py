"""Pallas histogram kernel vs. the scatter-add reference path.

The kernel runs in interpreter mode on CPU (tests); on TPU the same code
compiles via Mosaic. SURVEY.md §3.9: "Pallas histogram kernels (bin-count +
split-gain scan)".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hivemall_tpu.ops import trees as T
from hivemall_tpu.ops.pallas_hist import level_histogram


def _ref_hist(bins, loc, ws, M, B):
    n, d = bins.shape
    S = ws.shape[1]
    out = np.zeros((M, d, B, S), np.float32)
    for r in range(n):
        if loc[r] < 0:
            continue
        for f in range(d):
            out[loc[r], f, bins[r, f]] += ws[r]
    return out


@pytest.mark.parametrize("n,d,M,B,S", [(33, 3, 2, 8, 1),
                                       (70, 5, 4, 16, 3),
                                       (17, 2, 1, 64, 4)])
def test_level_histogram_matches_scatter(n, d, M, B, S):
    rng = np.random.default_rng(7)
    bins = rng.integers(0, B, (n, d)).astype(np.uint8)
    loc = rng.integers(-1, M, n).astype(np.int32)   # -1 = inactive
    ws = rng.normal(size=(n, S)).astype(np.float32)
    got = np.asarray(level_histogram(jnp.asarray(bins), jnp.asarray(loc),
                                     jnp.asarray(ws), M, B))
    np.testing.assert_allclose(got, _ref_hist(bins, loc, ws, M, B),
                               rtol=1e-5, atol=1e-5)


def test_pallas_builder_matches_scatter_builder():
    """Full tree build: pallas-histogram path == scatter path."""
    rng = np.random.default_rng(3)
    n, d, C = 120, 4, 3
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, C, n)
    bins, _ = T.quantize_bins(X, n_bins=16)
    onehot = jax.nn.one_hot(y, C)
    w = jnp.ones((2, n), jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(0), 2)

    outs = []
    for use_pallas in (False, True):
        build = T._make_builder(C, lambda aux: aux, T._gini_gain,
                                lambda p: p, lambda s: s.sum(-1),
                                depth=3, n_bins=16, mtry=0, min_split=2.0,
                                min_leaf=1.0, min_gain=1e-7,
                                use_pallas=use_pallas)
        build = jax.jit(jax.vmap(build, in_axes=(None, None, 0, 0)))
        outs.append(build(jnp.asarray(bins), onehot, w, keys))

    for a, b in zip(*outs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n,d,M,B,S", [(70, 5, 4, 16, 3),
                                       (500, 3, 64, 64, 2),
                                       (1000, 7, 256, 64, 3)])
def test_sorted_histogram_matches_flat(n, d, M, B, S):
    from hivemall_tpu.ops.pallas_hist import level_histogram_sorted
    rng = np.random.default_rng(11)
    bins = rng.integers(0, B, (n, d)).astype(np.uint8)
    loc = rng.integers(-1, M, n).astype(np.int32)
    ws = rng.normal(size=(n, S)).astype(np.float32)
    a = np.asarray(level_histogram(jnp.asarray(bins), jnp.asarray(loc),
                                   jnp.asarray(ws), M, B))
    b = np.asarray(level_histogram_sorted(jnp.asarray(bins),
                                          jnp.asarray(loc),
                                          jnp.asarray(ws), M, B))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_sorted_histogram_skewed_nodes():
    """All rows on one node: every other window is spill-free and masked."""
    from hivemall_tpu.ops.pallas_hist import level_histogram_sorted
    rng = np.random.default_rng(12)
    n, d, M, B = 400, 3, 128, 64
    bins = rng.integers(0, B, (n, d)).astype(np.uint8)
    loc = np.full(n, 77, np.int32)           # single hot node
    ws = np.ones((n, 1), np.float32)
    out = np.asarray(level_histogram_sorted(jnp.asarray(bins),
                                            jnp.asarray(loc),
                                            jnp.asarray(ws), M, B))
    assert out.sum() == n * d
    assert np.all(out[np.arange(M) != 77] == 0)


def test_sorted_histogram_trailing_inactive_chunks():
    """>= one full chunk of inactive rows at the end must not clobber
    window 0 (regression: all-inactive chunks forward-fill their home
    window instead of defaulting to 0)."""
    from hivemall_tpu.ops.pallas_hist import level_histogram_sorted
    rng = np.random.default_rng(13)
    n, d, M, B = 1000, 3, 128, 64
    bins = rng.integers(0, B, (n, d)).astype(np.uint8)
    loc = rng.integers(0, M, n).astype(np.int32)
    loc[n // 2:] = -1                    # half the rows inactive (sorted last)
    ws = rng.normal(size=(n, 2)).astype(np.float32)
    a = np.asarray(level_histogram(jnp.asarray(bins), jnp.asarray(loc),
                                   jnp.asarray(ws), M, B))
    b = np.asarray(level_histogram_sorted(jnp.asarray(bins),
                                          jnp.asarray(loc),
                                          jnp.asarray(ws), M, B))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_sorted_histogram_many_channels_and_odd_bins():
    from hivemall_tpu.ops.pallas_hist import level_histogram_sorted
    rng = np.random.default_rng(14)
    n, d, M = 300, 3, 32
    # S > 8: channel slabs share one sort; B=100 falls back to flat kernel
    for B, S in ((32, 11), (100, 2)):
        bins = rng.integers(0, B, (n, d)).astype(np.uint8)
        loc = rng.integers(-1, M, n).astype(np.int32)
        ws = rng.normal(size=(n, S)).astype(np.float32)
        a = np.asarray(level_histogram(jnp.asarray(bins), jnp.asarray(loc),
                                       jnp.asarray(ws), M, B))
        b = np.asarray(level_histogram_sorted(jnp.asarray(bins),
                                              jnp.asarray(loc),
                                              jnp.asarray(ws), M, B))
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)
