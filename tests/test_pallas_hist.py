"""Pallas histogram kernel vs. the scatter-add reference path.

The kernel runs in interpreter mode on CPU (tests); on TPU the same code
compiles via Mosaic. SURVEY.md §3.9: "Pallas histogram kernels (bin-count +
split-gain scan)".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hivemall_tpu.ops import trees as T
from hivemall_tpu.ops.pallas_hist import level_histogram


def _ref_hist(bins, loc, ws, M, B):
    n, d = bins.shape
    S = ws.shape[1]
    out = np.zeros((M, d, B, S), np.float32)
    for r in range(n):
        if loc[r] < 0:
            continue
        for f in range(d):
            out[loc[r], f, bins[r, f]] += ws[r]
    return out


@pytest.mark.parametrize("n,d,M,B,S", [(33, 3, 2, 8, 1),
                                       (70, 5, 4, 16, 3),
                                       (17, 2, 1, 64, 4)])
def test_level_histogram_matches_scatter(n, d, M, B, S):
    rng = np.random.default_rng(7)
    bins = rng.integers(0, B, (n, d)).astype(np.uint8)
    loc = rng.integers(-1, M, n).astype(np.int32)   # -1 = inactive
    ws = rng.normal(size=(n, S)).astype(np.float32)
    got = np.asarray(level_histogram(jnp.asarray(bins), jnp.asarray(loc),
                                     jnp.asarray(ws), M, B))
    np.testing.assert_allclose(got, _ref_hist(bins, loc, ws, M, B),
                               rtol=1e-5, atol=1e-5)


def test_pallas_builder_matches_scatter_builder():
    """Full tree build: pallas-histogram path == scatter path."""
    rng = np.random.default_rng(3)
    n, d, C = 120, 4, 3
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, C, n)
    bins, _ = T.quantize_bins(X, n_bins=16)
    onehot = jax.nn.one_hot(y, C)
    w = jnp.ones((2, n), jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(0), 2)

    outs = []
    for use_pallas in (False, True):
        build = T._make_builder(C, lambda aux: aux, T._gini_gain,
                                lambda p: p, lambda s: s.sum(-1),
                                depth=3, n_bins=16, mtry=0, min_split=2.0,
                                min_leaf=1.0, min_gain=1e-7,
                                use_pallas=use_pallas)
        build = jax.jit(jax.vmap(build, in_axes=(None, None, 0, 0)))
        outs.append(build(jnp.asarray(bins), onehot, w, keys))

    for a, b in zip(*outs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)
