"""MF/BPR/SLIM/samplers: convergence + semantics (SURVEY.md §5 style)."""

import numpy as np
import pytest

from hivemall_tpu.models.mf import (BPRMFTrainer, MFAdaGradTrainer, MFTrainer,
                                    bprmf_predict, mf_predict)


def synthetic_ratings(U=50, I=40, K=3, n=3000, seed=0):
    rng = np.random.default_rng(seed)
    P = rng.normal(0, 1, (U, K))
    Q = rng.normal(0, 1, (I, K))
    users = rng.integers(0, U, n)
    items = rng.integers(0, I, n)
    ratings = (P[users] * Q[items]).sum(-1) + rng.normal(0, 0.1, n)
    return users, items, ratings.astype(np.float32)


def test_mf_sgd_fits():
    users, items, ratings = synthetic_ratings()
    t = MFTrainer("-factors 3 -eta0 0.05 -lambda 0.001 -iters 30 "
                  "-users 64 -items 64 -mini_batch 256 -sigma 0.3")
    t.fit(users, items, ratings)
    pred = t.predict(users, items)
    rmse = float(np.sqrt(np.mean((pred - ratings) ** 2)))
    assert rmse < 0.6, rmse


def test_mf_adagrad_fits():
    users, items, ratings = synthetic_ratings(seed=2)
    t = MFAdaGradTrainer("-factors 3 -eta0 0.3 -lambda 0.001 -iters 25 "
                         "-users 64 -items 64 -mini_batch 256 -sigma 0.3")
    t.fit(users, items, ratings)
    rmse = float(np.sqrt(np.mean((t.predict(users, items) - ratings) ** 2)))
    assert rmse < 0.6, rmse


def test_mf_udtf_lifecycle_and_rows():
    t = MFTrainer("-factors 2 -users 8 -items 8 -mini_batch 4 -eta0 0.1")
    for _ in range(5):
        t.process(1, 2, 4.0)
        t.process(0, 3, 1.0)
    rows = list(t.close())
    # user rows carry Pu (slot 1), item rows carry Qi (slot 2)
    assert any(r[1] is not None and r[0] == 1 for r in rows)
    assert any(r[2] is not None and r[0] == 2 for r in rows)


def test_bprmf_ranks_pos_above_neg():
    rng = np.random.default_rng(1)
    U, I = 20, 30
    # users prefer even items
    t = BPRMFTrainer("-factors 4 -eta0 0.05 -lambda 0.001 -users 32 "
                     "-items 32 -mini_batch 128 -iters 3 -sigma 0.2")
    for _ in range(4000):
        u = int(rng.integers(0, U))
        pos = int(rng.integers(0, I // 2)) * 2
        neg = int(rng.integers(0, I // 2)) * 2 + 1
        t.process(u, pos, neg)
    list(t.close())
    users = np.repeat(np.arange(U), I // 2)
    even = t.predict(users, np.tile(np.arange(0, I, 2), U))
    odd = t.predict(users, np.tile(np.arange(1, I, 2), U))
    assert (even > odd).mean() > 0.9


def test_predict_udfs_cold_start():
    assert mf_predict([1.0, 2.0], [3.0, 4.0], 0.5, 0.25, 3.0) == \
        pytest.approx(3.0 + 0.5 + 0.25 + 11.0)
    assert mf_predict(None, [1.0], None, 0.5, 3.0) == pytest.approx(3.5)
    assert bprmf_predict([1.0], [2.0], 0.5) == pytest.approx(2.5)
    assert bprmf_predict(None, None, None) == 0.0


def test_slim_recovers_structure():
    from hivemall_tpu.models.slim import SlimTrainer
    rng = np.random.default_rng(3)
    # item 1 == copy of item 0; item 2 independent
    U = 40
    base = rng.uniform(1, 5, U)
    t = SlimTrainer("-l1 0.01 -l2 0.01 -iters 20")
    for u in range(U):
        t.process(u, 0, float(base[u]))
        t.process(u, 1, float(base[u]))
        t.process(u, 2, float(rng.uniform(1, 5)))
    W = {(j, i): w for j, i, w in t.close()}
    # W[0 -> 1] strong (item 0 explains item 1), both >> any weight into 2
    assert W.get((0, 1), 0.0) > 0.5
    assert W.get((0, 1), 0.0) > abs(W.get((0, 2), 0.0))
    assert (0, 0) not in W     # diag forced to zero


def test_samplers():
    from hivemall_tpu.ftvec.ranking import (bpr_sampling, item_pairs_sampling,
                                            populate_not_in)
    trips = list(bpr_sampling(7, [1, 2, 3], 10, 2.0, seed=0))
    assert len(trips) == 6
    for u, p, n in trips:
        assert u == 7 and p in (1, 2, 3) and n not in (1, 2, 3)
        assert 0 <= n <= 10
    pairs = list(item_pairs_sampling([4], 6, 3.0, seed=1))
    assert all(p == 4 and q != 4 for p, q in pairs)
    assert list(populate_not_in([0, 2], 4)) == [1, 3, 4]
