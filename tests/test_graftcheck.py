"""graftcheck static-analyzer tests (docs/STATIC_ANALYSIS.md).

Per rule: a seeded violation MUST be caught and the known-good repo
idiom MUST pass clean. Then the repo-level contracts: the ~67
compile-factory sites across models/, ops/ and parallel/ pass GC01
(floor 60 asserted below), the atomic
write helpers in io/ pass GC03, the whole tree gates clean with an
EMPTY baseline, the baseline flags stale entries, and graftcheck runs
clean on its own source (self-lint).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from hivemall_tpu.tools.graftcheck import run_paths
from hivemall_tpu.tools.graftcheck.engine import (gate, load_baseline,
                                                  scan_file,
                                                  write_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "hivemall_tpu")


def check_src(tmp_path, src, rel="pkg/mod.py"):
    """Write one module into a scratch tree and scan it."""
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return run_paths([str(tmp_path)], root=str(tmp_path))


def codes(findings):
    return sorted({f.code for f in findings})


# -- GC01 retrace-hazard ----------------------------------------------------

def test_gc01_per_call_jit_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax
        def predict(f, x):
            g = jax.jit(f)
            return g(x)
    """)
    assert codes(out) == ["GC01"]


def test_gc01_immediate_invoke_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax
        def predict(f, x):
            return jax.jit(f)(x)
    """)
    assert codes(out) == ["GC01"]


def test_gc01_loop_creation_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax
        def build_all(fns):
            out = []
            for f in fns:
                out.append(jax.jit(f))
            return out
    """)
    assert codes(out) == ["GC01"]


def test_gc01_nested_lru_cache_flagged(tmp_path):
    out = check_src(tmp_path, """
        from functools import lru_cache
        def make():
            @lru_cache(maxsize=8)
            def factory(n):
                return n
            return factory
    """)
    assert codes(out) == ["GC01"]


def test_gc01_factory_returning_closure_clean(tmp_path):
    # the repo's _make_step idiom: jit closure escapes via return
    out = check_src(tmp_path, """
        import jax
        class Trainer:
            def _make_step(self):
                lam = 0.1
                @jax.jit
                def step(w, x):
                    return w - lam * x
                return step
    """)
    assert out == []


def test_gc01_memoized_factory_with_warmup_call_clean(tmp_path):
    # lru_cache factory may warm the closure before returning it
    out = check_src(tmp_path, """
        import jax
        from functools import lru_cache
        @lru_cache(maxsize=64)
        def _step_cached(dims):
            f = jax.jit(lambda w: w * dims)
            f(0.0)
            return f
    """)
    assert out == []


def test_gc01_self_store_clean(tmp_path):
    out = check_src(tmp_path, """
        import jax
        class Engine:
            def __init__(self, f):
                self._scorer = jax.jit(f)
    """)
    assert out == []


def test_gc01_known_good_compile_factories_pass():
    """The known-good compile-factory population — every lru_cache/jit
    site across models/, ops/ and parallel/ — must pass GC01 clean, and
    the site count proves the assertion is not vacuous."""
    dirs = [os.path.join(PKG, d) for d in ("models", "ops", "parallel")]
    out = run_paths(dirs, root=REPO)
    assert [f for f in out if f.code == "GC01"] == []
    n_sites = 0
    for base in dirs:
        for fname in os.listdir(base):
            if fname.endswith(".py"):
                with open(os.path.join(base, fname)) as f:
                    src = f.read()
                n_sites += src.count("jax.jit") + src.count("lru_cache(")
    assert n_sites >= 60, f"factory population shrank? saw {n_sites}"


# -- GC02 clock-discipline --------------------------------------------------

def test_gc02_direct_subtraction_flagged(tmp_path):
    out = check_src(tmp_path, """
        import time
        def age(t0):
            return time.time() - t0
    """)
    assert codes(out) == ["GC02"]


def test_gc02_deadline_compare_flagged(tmp_path):
    out = check_src(tmp_path, """
        import time
        def wait(seconds):
            deadline = time.time() + seconds
            while time.time() < deadline:
                pass
    """)
    assert codes(out) == ["GC02"]


def test_gc02_tainted_name_flagged(tmp_path):
    out = check_src(tmp_path, """
        import time
        def span(t_hi):
            now = time.time()
            return now - t_hi
    """)
    assert codes(out) == ["GC02"]


def test_gc02_wall_anchor_export_clean(tmp_path):
    # plain timestamping (no duration math) is the legitimate use
    out = check_src(tmp_path, """
        import time
        def record():
            return {"ts": round(time.time(), 3)}
    """)
    assert out == []


def test_gc02_monotonic_clean(tmp_path):
    out = check_src(tmp_path, """
        import time
        def wait(seconds):
            deadline = time.monotonic() + seconds
            while time.monotonic() < deadline:
                pass
    """)
    assert out == []


def test_gc02_suppression_trailing_and_line_above(tmp_path):
    out = check_src(tmp_path, """
        import time
        def age(mtime, other):
            a = time.time() - mtime  # graftcheck: disable=GC02
            # graftcheck: disable=GC02
            b = time.time() - other
            return a + b
    """)
    assert out == []


# -- GC03 atomic-write ------------------------------------------------------

GC03_BAD = """
    def save_pointer(path, obj):
        with open(path, "w") as f:
            f.write(obj)
"""

GC03_GOOD = """
    import os
    def save_pointer(path, obj):
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(obj)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
"""


def test_gc03_bare_write_in_io_flagged(tmp_path):
    assert codes(check_src(tmp_path, GC03_BAD, "pkg/io/x.py")) == ["GC03"]
    assert codes(check_src(tmp_path, GC03_BAD, "pkg/serve/x.py")) \
        == ["GC03"]


def test_gc03_atomic_idiom_clean(tmp_path):
    assert check_src(tmp_path, GC03_GOOD, "pkg/io/x.py") == []


def test_gc03_outside_io_serve_not_scanned(tmp_path):
    assert check_src(tmp_path, GC03_BAD, "pkg/models/x.py") == []


def test_gc03_read_open_clean(tmp_path):
    out = check_src(tmp_path, """
        def load(path):
            with open(path) as f:
                return f.read()
    """, "pkg/io/x.py")
    assert out == []


def test_gc03_repo_atomic_helpers_pass():
    for rel in ("io/checkpoint.py", "io/shard_cache.py"):
        out = scan_file(os.path.join(PKG, rel), root=REPO)
        assert [f for f in out if f.code == "GC03"] == [], rel


# -- GC04 lock-discipline ---------------------------------------------------

GC04_RACY = """
    import threading
    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
            threading.Thread(target=self._a).start()
            threading.Thread(target=self._b).start()
        def _a(self):
            self.n += 1
        def _b(self):
            self.n -= 1
"""


def test_gc04_two_entry_unguarded_flagged(tmp_path):
    out = check_src(tmp_path, GC04_RACY)
    assert codes(out) == ["GC04"] and len(out) == 2


def test_gc04_guarded_writes_clean(tmp_path):
    out = check_src(tmp_path, GC04_RACY.replace(
        "self.n += 1", "with self._lock:\n                self.n += 1")
        .replace("self.n -= 1",
                 "with self._lock:\n                self.n -= 1"))
    assert out == []


def test_gc04_single_entry_clean(tmp_path):
    out = check_src(tmp_path, """
        import threading
        class W:
            def __init__(self):
                threading.Thread(target=self._a).start()
            def _a(self):
                self.n = 1
            def stop(self):
                self.done = True
    """)
    assert out == []


def test_gc04_acquire_without_with_flagged(tmp_path):
    out = check_src(tmp_path, """
        def f(lock):
            lock.acquire()
            try:
                pass
            finally:
                lock.release()
    """)
    assert codes(out) == ["GC04"]


def test_gc04_with_lock_clean(tmp_path):
    out = check_src(tmp_path, """
        def f(lock):
            with lock:
                pass
    """)
    assert out == []


# -- GC05 surface-parity ----------------------------------------------------

def test_gc05_live_extra_key_flagged(tmp_path):
    out = check_src(tmp_path, """
        FOO_STUB = {"ok": 0}
        class P:
            def obs_section(self):
                return {"ok": 0, "extra": 1}
            def _register_obs(self):
                def p():
                    return (self.obs_section() if self else
                            dict(FOO_STUB))
                registry.register("foo", p)
    """)
    assert codes(out) == ["GC05"]
    assert any("extra" in f.message for f in out)


def test_gc05_stub_key_never_emitted_flagged(tmp_path):
    out = check_src(tmp_path, """
        FOO_STUB = {"ok": 0, "ghost": 0}
        class P:
            def obs_section(self):
                return {"ok": 0}
            def _register_obs(self):
                def p():
                    return (self.obs_section() if self else
                            dict(FOO_STUB))
    """)
    assert any("ghost" in f.message for f in out if f.code == "GC05")


def test_gc05_matching_and_dynamic_clean(tmp_path):
    out = check_src(tmp_path, """
        FOO_STUB = {"ok": 0, "n": 0}
        class P:
            def gather(self):
                return {}
            def obs_section(self):
                d = {"ok": 1, "n": 2}
                d.update(self.gather())
                return d
            def _register_obs(self):
                def p():
                    return (self.obs_section() if self else
                            dict(FOO_STUB))
    """)
    assert out == []


def test_gc05_name_grammar_flagged(tmp_path):
    out = check_src(tmp_path, """
        BAR_STUB = {"bad-dash": 0}
        registry.register("bad.name", lambda: {})
    """)
    msgs = [f.message for f in out if f.code == "GC05"]
    assert len(msgs) == 2
    assert any("bad.name" in m for m in msgs)
    assert any("bad-dash" in m for m in msgs)


def test_gc05_repo_stub_parity_clean():
    """The real registry stubs vs their live providers, from source."""
    out = run_paths([PKG], root=REPO)
    assert [f for f in out if f.code == "GC05"] == []


# -- GC06 broad-except ------------------------------------------------------

def test_gc06_unannotated_flagged(tmp_path):
    out = check_src(tmp_path, """
        def f():
            try:
                pass
            except Exception:
                pass
    """, "pkg/serve/x.py")
    assert codes(out) == ["GC06"]


def test_gc06_annotated_clean(tmp_path):
    out = check_src(tmp_path, """
        def f():
            try:
                pass
            except Exception:   # isolation: obs must never kill serving
                pass
            try:
                pass
            except Exception:
                pass            # second style: comment on the body line
    """, "pkg/obs/x.py")
    assert out == []


def test_gc06_outside_hot_dirs_clean(tmp_path):
    out = check_src(tmp_path, """
        def f():
            try:
                pass
            except Exception:
                pass
    """, "pkg/models/x.py")
    assert out == []


# -- whole-repo gate + baseline + self-lint ---------------------------------

def test_repo_gates_clean_with_empty_baseline():
    """The acceptance bar: the tree carries ZERO findings — no baseline
    debt at all (docs/STATIC_ANALYSIS.md records the contract)."""
    out = run_paths([PKG], root=REPO)
    assert out == [], "\n".join(f.render() for f in out)


def test_self_lint():
    out = run_paths([os.path.join(PKG, "tools")], root=REPO)
    assert out == [], "\n".join(f.render() for f in out)


def test_baseline_roundtrip_and_stale_detection(tmp_path):
    findings = check_src(tmp_path, GC03_BAD, "pkg/io/x.py")
    assert findings
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), findings)
    fresh, stale = gate(findings, load_baseline(str(bl)))
    assert fresh == [] and stale == []
    # the violation gets fixed -> its entry must go stale (gate fails)
    fresh, stale = gate([], load_baseline(str(bl)))
    assert fresh == [] and len(stale) == len(findings)


def test_baseline_stale_scoped_to_scanned_paths(tmp_path):
    """A PARTIAL scan must not flag baseline entries for files outside
    the scanned roots as stale; entries under a scanned root (e.g. a
    deleted file) still go stale."""
    findings = check_src(tmp_path, GC03_BAD, "pkg/io/x.py")
    other = "pkg/serve/other.py::GC03::f::bare open elsewhere"
    gone = "pkg/io/gone.py::GC03::f::file was deleted"
    baseline = [f.fingerprint for f in findings] + [other, gone]
    # scanning only pkg/io: `other` (serve/) is out of scope, `gone`
    # (io/, no longer present) is stale
    fresh, stale = gate(findings, baseline, covered=["pkg/io"])
    assert fresh == [] and stale == [gone]
    # a full scan judges everything
    fresh, stale = gate(findings, baseline, covered=["pkg"])
    assert sorted(stale) == sorted([other, gone])


def test_slo_explicit_wall_ts_vs_default_evaluate():
    """Samples fed with explicit wall-clock ts + evaluate() on the
    default clock: the epoch-mismatch guard anchors the window to the
    freshest sample instead of degrading windows to lifetime totals."""
    import time as _time

    from hivemall_tpu.obs.slo import SloEngine
    eng = SloEngine(p99_ms=100.0, availability=0.999)
    t0 = _time.time()                  # wall epoch, ~1.7e9
    for i in range(6):
        eng.sample({"requests": 100 * (i + 1)}, ts=t0 + 400.0 * i)
    out = eng.evaluate()               # default (monotonic) clock
    w5 = out["windows"]["5m"]
    # the 5m window must anchor at the newest sample and reach only the
    # 400s-older neighbor — NOT the 2000s-old first sample
    assert w5["requests"] == 100, w5
    assert out["windows"]["1h"]["requests"] == 500


def test_baseline_fingerprint_line_insensitive(tmp_path):
    a = check_src(tmp_path, GC03_BAD, "pkg/io/a.py")
    b = check_src(tmp_path, "\n\n# moved two lines down\n"
                  + textwrap.dedent(GC03_BAD), "pkg/io/a.py")
    assert [f.fingerprint for f in a] == [f.fingerprint for f in b]
    assert a[0].line != b[0].line


@pytest.mark.parametrize("mode", ["violation", "baselined", "stale"])
def test_cli_exit_codes(tmp_path, mode):
    tree = tmp_path / "pkg" / "io"
    tree.mkdir(parents=True)
    bad = tree / "bad.py"
    bad.write_text(textwrap.dedent(GC03_BAD))
    bl = tmp_path / "bl.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "hivemall_tpu.tools.graftcheck",
             str(tmp_path / "pkg"), "--root", str(tmp_path), *extra],
            capture_output=True, text=True, cwd=REPO, env=env)

    if mode == "violation":
        r = run()
        assert r.returncode == 1 and "GC03" in r.stdout
    elif mode == "baselined":
        assert run("--write-baseline", str(bl)).returncode == 0
        r = run("--baseline", str(bl))
        assert r.returncode == 0 and "clean" in r.stdout
    else:
        assert run("--write-baseline", str(bl)).returncode == 0
        data = json.loads(bl.read_text())
        data["findings"].append(
            "pkg/io/gone.py::GC03::save::already fixed")
        bl.write_text(json.dumps(data))
        r = run("--baseline", str(bl))
        assert r.returncode == 1 and "STALE" in r.stdout


@pytest.mark.slow
def test_cli_selfcheck():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, "-m", "hivemall_tpu.tools.graftcheck",
         "--selfcheck"], capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    assert "bidirectional" in r.stdout
