"""graftcheck static-analyzer tests (docs/STATIC_ANALYSIS.md).

Per rule: a seeded violation MUST be caught and the known-good repo
idiom MUST pass clean. Then the repo-level contracts: the ~67
compile-factory sites across models/, ops/ and parallel/ pass GC01
(floor 60 asserted below), the atomic
write helpers in io/ pass GC03, the whole tree gates clean with an
EMPTY baseline, the baseline flags stale entries, and graftcheck runs
clean on its own source (self-lint).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from hivemall_tpu.tools.graftcheck import run_paths
from hivemall_tpu.tools.graftcheck.engine import (gate, load_baseline,
                                                  scan_file,
                                                  write_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "hivemall_tpu")


@pytest.fixture(scope="module")
def extended_scan():
    """ONE scan of the full default CI surface (package + tests/ +
    bench.py + graft entry), shared by every repo-clean pin below —
    five independent repo-wide scans cost ~75 s of tier-1 wall on the
    2-core container and the suite runs against an 870 s budget."""
    paths = [PKG, os.path.join(REPO, "tests"),
             os.path.join(REPO, "bench.py"),
             os.path.join(REPO, "__graft_entry__.py")]
    return run_paths([p for p in paths if os.path.exists(p)], root=REPO)


@pytest.fixture(scope="module")
def repo_index():
    """ONE interprocedural index over the repo, shared by the GC10/GC11
    non-vacuity pins (same wall-budget rationale as extended_scan)."""
    from hivemall_tpu.tools.graftcheck import engine as eng
    from hivemall_tpu.tools.graftcheck.rules import collect_project
    ctxs = []
    for rel, ap in _repo_files().items():
        ctx, err = eng._parse_one(ap, rel)
        if ctx is not None:
            ctxs.append(ctx)
    idx = collect_project(ctxs).interproc
    assert idx is not None
    return idx


def check_src(tmp_path, src, rel="pkg/mod.py"):
    """Write one module into a scratch tree and scan it."""
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return run_paths([str(tmp_path)], root=str(tmp_path))


def codes(findings):
    return sorted({f.code for f in findings})


# -- GC01 retrace-hazard ----------------------------------------------------

def test_gc01_per_call_jit_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax
        def predict(f, x):
            g = jax.jit(f)
            return g(x)
    """)
    assert codes(out) == ["GC01"]


def test_gc01_immediate_invoke_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax
        def predict(f, x):
            return jax.jit(f)(x)
    """)
    assert codes(out) == ["GC01"]


def test_gc01_loop_creation_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax
        def build_all(fns):
            out = []
            for f in fns:
                out.append(jax.jit(f))
            return out
    """)
    assert codes(out) == ["GC01"]


def test_gc01_nested_lru_cache_flagged(tmp_path):
    out = check_src(tmp_path, """
        from functools import lru_cache
        def make():
            @lru_cache(maxsize=8)
            def factory(n):
                return n
            return factory
    """)
    assert codes(out) == ["GC01"]


def test_gc01_factory_returning_closure_clean(tmp_path):
    # the repo's _make_step idiom: jit closure escapes via return
    out = check_src(tmp_path, """
        import jax
        class Trainer:
            def _make_step(self):
                lam = 0.1
                @jax.jit
                def step(w, x):
                    return w - lam * x
                return step
    """)
    assert out == []


def test_gc01_memoized_factory_with_warmup_call_clean(tmp_path):
    # lru_cache factory may warm the closure before returning it
    out = check_src(tmp_path, """
        import jax
        from functools import lru_cache
        @lru_cache(maxsize=64)
        def _step_cached(dims):
            f = jax.jit(lambda w: w * dims)
            f(0.0)
            return f
    """)
    assert out == []


def test_gc01_self_store_clean(tmp_path):
    out = check_src(tmp_path, """
        import jax
        class Engine:
            def __init__(self, f):
                self._scorer = jax.jit(f)
    """)
    assert out == []


def test_gc01_known_good_compile_factories_pass(extended_scan):
    """The known-good compile-factory population — every lru_cache/jit
    site across models/, ops/ and parallel/ — must pass GC01 clean, and
    the site count proves the assertion is not vacuous."""
    assert [f for f in extended_scan if f.code == "GC01"
            and f.path.startswith(("hivemall_tpu/models/",
                                   "hivemall_tpu/ops/",
                                   "hivemall_tpu/parallel/"))] == []
    n_sites = 0
    for d in ("models", "ops", "parallel"):
        base = os.path.join(PKG, d)
        for fname in os.listdir(base):
            if fname.endswith(".py"):
                with open(os.path.join(base, fname)) as f:
                    src = f.read()
                n_sites += src.count("jax.jit") + src.count("lru_cache(")
    assert n_sites >= 60, f"factory population shrank? saw {n_sites}"


# -- GC02 clock-discipline --------------------------------------------------

def test_gc02_direct_subtraction_flagged(tmp_path):
    out = check_src(tmp_path, """
        import time
        def age(t0):
            return time.time() - t0
    """)
    assert codes(out) == ["GC02"]


def test_gc02_deadline_compare_flagged(tmp_path):
    out = check_src(tmp_path, """
        import time
        def wait(seconds):
            deadline = time.time() + seconds
            while time.time() < deadline:
                pass
    """)
    assert codes(out) == ["GC02"]


def test_gc02_tainted_name_flagged(tmp_path):
    out = check_src(tmp_path, """
        import time
        def span(t_hi):
            now = time.time()
            return now - t_hi
    """)
    assert codes(out) == ["GC02"]


def test_gc02_wall_anchor_export_clean(tmp_path):
    # plain timestamping (no duration math) is the legitimate use
    out = check_src(tmp_path, """
        import time
        def record():
            return {"ts": round(time.time(), 3)}
    """)
    assert out == []


def test_gc02_monotonic_clean(tmp_path):
    out = check_src(tmp_path, """
        import time
        def wait(seconds):
            deadline = time.monotonic() + seconds
            while time.monotonic() < deadline:
                pass
    """)
    assert out == []


def test_gc02_suppression_trailing_and_line_above(tmp_path):
    out = check_src(tmp_path, """
        import time
        def age(mtime, other):
            a = time.time() - mtime  # graftcheck: disable=GC02
            # graftcheck: disable=GC02
            b = time.time() - other
            return a + b
    """)
    assert out == []


# -- GC03 atomic-write ------------------------------------------------------

GC03_BAD = """
    def save_pointer(path, obj):
        with open(path, "w") as f:
            f.write(obj)
"""

GC03_GOOD = """
    import os
    def save_pointer(path, obj):
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(obj)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
"""


def test_gc03_bare_write_in_io_flagged(tmp_path):
    assert codes(check_src(tmp_path, GC03_BAD, "pkg/io/x.py")) == ["GC03"]
    assert codes(check_src(tmp_path, GC03_BAD, "pkg/serve/x.py")) \
        == ["GC03"]


def test_gc03_atomic_idiom_clean(tmp_path):
    assert check_src(tmp_path, GC03_GOOD, "pkg/io/x.py") == []


def test_gc03_outside_io_serve_not_scanned(tmp_path):
    assert check_src(tmp_path, GC03_BAD, "pkg/models/x.py") == []


def test_gc03_read_open_clean(tmp_path):
    out = check_src(tmp_path, """
        def load(path):
            with open(path) as f:
                return f.read()
    """, "pkg/io/x.py")
    assert out == []


def test_gc03_repo_atomic_helpers_pass():
    for rel in ("io/checkpoint.py", "io/shard_cache.py"):
        out = scan_file(os.path.join(PKG, rel), root=REPO)
        assert [f for f in out if f.code == "GC03"] == [], rel


# -- GC04 lock-discipline ---------------------------------------------------

GC04_RACY = """
    import threading
    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
            threading.Thread(target=self._a).start()
            threading.Thread(target=self._b).start()
        def _a(self):
            self.n += 1
        def _b(self):
            self.n -= 1
"""


def test_gc04_two_entry_unguarded_flagged(tmp_path):
    out = check_src(tmp_path, GC04_RACY)
    assert codes(out) == ["GC04"] and len(out) == 2


def test_gc04_guarded_writes_clean(tmp_path):
    out = check_src(tmp_path, GC04_RACY.replace(
        "self.n += 1", "with self._lock:\n                self.n += 1")
        .replace("self.n -= 1",
                 "with self._lock:\n                self.n -= 1"))
    assert out == []


def test_gc04_single_entry_clean(tmp_path):
    out = check_src(tmp_path, """
        import threading
        class W:
            def __init__(self):
                threading.Thread(target=self._a).start()
            def _a(self):
                self.n = 1
            def stop(self):
                self.done = True
    """)
    assert out == []


def test_gc04_acquire_without_with_flagged(tmp_path):
    out = check_src(tmp_path, """
        def f(lock):
            lock.acquire()
            try:
                pass
            finally:
                lock.release()
    """)
    assert codes(out) == ["GC04"]


def test_gc04_with_lock_clean(tmp_path):
    out = check_src(tmp_path, """
        def f(lock):
            with lock:
                pass
    """)
    assert out == []


# -- GC05 surface-parity ----------------------------------------------------

def test_gc05_live_extra_key_flagged(tmp_path):
    out = check_src(tmp_path, """
        FOO_STUB = {"ok": 0}
        class P:
            def obs_section(self):
                return {"ok": 0, "extra": 1}
            def _register_obs(self):
                def p():
                    return (self.obs_section() if self else
                            dict(FOO_STUB))
                registry.register("foo", p)
    """)
    assert codes(out) == ["GC05"]
    assert any("extra" in f.message for f in out)


def test_gc05_stub_key_never_emitted_flagged(tmp_path):
    out = check_src(tmp_path, """
        FOO_STUB = {"ok": 0, "ghost": 0}
        class P:
            def obs_section(self):
                return {"ok": 0}
            def _register_obs(self):
                def p():
                    return (self.obs_section() if self else
                            dict(FOO_STUB))
    """)
    assert any("ghost" in f.message for f in out if f.code == "GC05")


def test_gc05_matching_and_dynamic_clean(tmp_path):
    out = check_src(tmp_path, """
        FOO_STUB = {"ok": 0, "n": 0}
        class P:
            def gather(self):
                return {}
            def obs_section(self):
                d = {"ok": 1, "n": 2}
                d.update(self.gather())
                return d
            def _register_obs(self):
                def p():
                    return (self.obs_section() if self else
                            dict(FOO_STUB))
    """)
    assert out == []


def test_gc05_name_grammar_flagged(tmp_path):
    out = check_src(tmp_path, """
        BAR_STUB = {"bad-dash": 0}
        registry.register("bad.name", lambda: {})
    """)
    msgs = [f.message for f in out if f.code == "GC05"]
    assert len(msgs) == 2
    assert any("bad.name" in m for m in msgs)
    assert any("bad-dash" in m for m in msgs)


def test_gc05_repo_stub_parity_clean(extended_scan):
    """The real registry stubs vs their live providers, from source."""
    assert [f for f in extended_scan if f.code == "GC05"] == []


# -- GC06 broad-except ------------------------------------------------------

def test_gc06_unannotated_flagged(tmp_path):
    out = check_src(tmp_path, """
        def f():
            try:
                pass
            except Exception:
                pass
    """, "pkg/serve/x.py")
    assert codes(out) == ["GC06"]


def test_gc06_annotated_clean(tmp_path):
    out = check_src(tmp_path, """
        def f():
            try:
                pass
            except Exception:   # isolation: obs must never kill serving
                pass
            try:
                pass
            except Exception:
                pass            # second style: comment on the body line
    """, "pkg/obs/x.py")
    assert out == []


def test_gc06_outside_hot_dirs_clean(tmp_path):
    out = check_src(tmp_path, """
        def f():
            try:
                pass
            except Exception:
                pass
    """, "pkg/models/x.py")
    assert out == []


# -- whole-repo gate + baseline + self-lint ---------------------------------

def test_repo_gates_clean_with_empty_baseline(extended_scan):
    """The acceptance bar: the package carries ZERO findings — no
    baseline debt at all (docs/STATIC_ANALYSIS.md records the
    contract)."""
    out = [f for f in extended_scan
           if f.path.startswith("hivemall_tpu/")]
    assert out == [], "\n".join(f.render() for f in out)


def test_self_lint():
    out = run_paths([os.path.join(PKG, "tools")], root=REPO)
    assert out == [], "\n".join(f.render() for f in out)


def test_baseline_roundtrip_and_stale_detection(tmp_path):
    findings = check_src(tmp_path, GC03_BAD, "pkg/io/x.py")
    assert findings
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), findings)
    fresh, stale = gate(findings, load_baseline(str(bl)))
    assert fresh == [] and stale == []
    # the violation gets fixed -> its entry must go stale (gate fails)
    fresh, stale = gate([], load_baseline(str(bl)))
    assert fresh == [] and len(stale) == len(findings)


def test_baseline_stale_scoped_to_scanned_paths(tmp_path):
    """A PARTIAL scan must not flag baseline entries for files outside
    the scanned roots as stale; entries under a scanned root (e.g. a
    deleted file) still go stale."""
    findings = check_src(tmp_path, GC03_BAD, "pkg/io/x.py")
    other = "pkg/serve/other.py::GC03::f::bare open elsewhere"
    gone = "pkg/io/gone.py::GC03::f::file was deleted"
    baseline = [f.fingerprint for f in findings] + [other, gone]
    # scanning only pkg/io: `other` (serve/) is out of scope, `gone`
    # (io/, no longer present) is stale
    fresh, stale = gate(findings, baseline, covered=["pkg/io"])
    assert fresh == [] and stale == [gone]
    # a full scan judges everything
    fresh, stale = gate(findings, baseline, covered=["pkg"])
    assert sorted(stale) == sorted([other, gone])


def test_slo_explicit_wall_ts_vs_default_evaluate():
    """Samples fed with explicit wall-clock ts + evaluate() on the
    default clock: the epoch-mismatch guard anchors the window to the
    freshest sample instead of degrading windows to lifetime totals."""
    import time as _time

    from hivemall_tpu.obs.slo import SloEngine
    eng = SloEngine(p99_ms=100.0, availability=0.999)
    t0 = _time.time()                  # wall epoch, ~1.7e9
    for i in range(6):
        eng.sample({"requests": 100 * (i + 1)}, ts=t0 + 400.0 * i)
    out = eng.evaluate()               # default (monotonic) clock
    w5 = out["windows"]["5m"]
    # the 5m window must anchor at the newest sample and reach only the
    # 400s-older neighbor — NOT the 2000s-old first sample
    assert w5["requests"] == 100, w5
    assert out["windows"]["1h"]["requests"] == 500


def test_baseline_fingerprint_line_insensitive(tmp_path):
    a = check_src(tmp_path, GC03_BAD, "pkg/io/a.py")
    b = check_src(tmp_path, "\n\n# moved two lines down\n"
                  + textwrap.dedent(GC03_BAD), "pkg/io/a.py")
    assert [f.fingerprint for f in a] == [f.fingerprint for f in b]
    assert a[0].line != b[0].line


@pytest.mark.parametrize("mode", ["violation", "baselined", "stale"])
def test_cli_exit_codes(tmp_path, mode):
    tree = tmp_path / "pkg" / "io"
    tree.mkdir(parents=True)
    bad = tree / "bad.py"
    bad.write_text(textwrap.dedent(GC03_BAD))
    bl = tmp_path / "bl.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "hivemall_tpu.tools.graftcheck",
             str(tmp_path / "pkg"), "--root", str(tmp_path), *extra],
            capture_output=True, text=True, cwd=REPO, env=env)

    if mode == "violation":
        r = run()
        assert r.returncode == 1 and "GC03" in r.stdout
    elif mode == "baselined":
        assert run("--write-baseline", str(bl)).returncode == 0
        r = run("--baseline", str(bl))
        assert r.returncode == 0 and "clean" in r.stdout
    else:
        assert run("--write-baseline", str(bl)).returncode == 0
        data = json.loads(bl.read_text())
        data["findings"].append(
            "pkg/io/gone.py::GC03::save::already fixed")
        bl.write_text(json.dumps(data))
        r = run("--baseline", str(bl))
        assert r.returncode == 1 and "STALE" in r.stdout


@pytest.mark.slow
def test_cli_selfcheck():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, "-m", "hivemall_tpu.tools.graftcheck",
         "--selfcheck"], capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    assert "bidirectional" in r.stdout


# ===========================================================================
# graftcheck v2: interprocedural dataflow, GC07/GC08, cache, --fix
# ===========================================================================

def check_srcs(tmp_path, files, cache=None):
    """Write a multi-module scratch tree and scan it (the
    interprocedural fixtures need more than one file)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_paths([str(tmp_path)], root=str(tmp_path), cache=cache)


# -- interprocedural non-vacuity: each fixture is INVISIBLE to the PR 11
# intra-module analysis (the single-module scan is pinned clean) and
# MUST be caught once the summaries connect the modules ------------------

GC02_HELPER = """
    import time
    def now_s():
        return time.time()
"""

GC02_USER = """
    from pkg.utils.clockutil import now_s
    def wait(seconds):
        deadline = now_s() + seconds
        while now_s() < deadline:
            pass
"""


def test_gc02_cross_module_taint_flagged(tmp_path):
    out = check_srcs(tmp_path, {"pkg/utils/clockutil.py": GC02_HELPER,
                                "pkg/io/dl.py": GC02_USER})
    hits = [f for f in out if f.code == "GC02"]
    assert hits and hits[0].path == "pkg/io/dl.py"
    assert "now_s" in hits[0].message


def test_gc02_cross_module_missed_by_single_module_scan(tmp_path):
    """The PR 11 miss, pinned: without the helper module in the scan the
    taint trail dies at the function boundary."""
    out = check_srcs(tmp_path, {"pkg/io/dl.py": GC02_USER})
    assert [f for f in out if f.code == "GC02"] == []


def test_gc02_transitive_helper_chain(tmp_path):
    """Taint survives TWO function boundaries (helper returning a
    helper's return)."""
    out = check_srcs(tmp_path, {
        "pkg/utils/clockutil.py": GC02_HELPER,
        "pkg/utils/indirect.py": """
            from pkg.utils.clockutil import now_s
            def stamp():
                return now_s()
        """,
        "pkg/io/dl.py": """
            from pkg.utils.indirect import stamp
            def age(t0):
                return stamp() - t0
        """})
    assert [f.path for f in out if f.code == "GC02"] == ["pkg/io/dl.py"]


GC01_FACTORY = """
    import jax
    def make_step(f):
        return jax.jit(f)
"""


def test_gc01_cross_module_factory_in_loop(tmp_path):
    out = check_srcs(tmp_path, {
        "pkg/ops/fac.py": GC01_FACTORY,
        "pkg/models/use.py": """
            from pkg.ops.fac import make_step
            def score_all(fns, x):
                return [make_step(f)(x) for f in fns]
        """})
    hits = [f for f in out if f.code == "GC01"]
    assert hits and hits[0].path == "pkg/models/use.py"
    assert "make_step" in hits[0].message


def test_gc01_cross_module_missed_by_single_module_scan(tmp_path):
    out = check_srcs(tmp_path, {"pkg/models/use.py": """
        from pkg.ops.fac import make_step
        def score_all(fns, x):
            return [make_step(f)(x) for f in fns]
    """})
    assert [f for f in out if f.code == "GC01"] == []


def test_gc01_factory_product_escapes_clean(tmp_path):
    """Callers that STORE the factory product (the repo's _make_step
    idiom) must stay clean — only loop/immediate-invoke calls fire."""
    out = check_srcs(tmp_path, {
        "pkg/ops/fac.py": GC01_FACTORY,
        "pkg/models/use.py": """
            from pkg.ops.fac import make_step
            class T:
                def __init__(self, f):
                    self._step = make_step(f)
        """})
    assert out == []


def test_gc01_memoized_factory_calls_clean(tmp_path):
    """A memoized factory returns the SAME closure per config — calling
    it per step (even in a loop) is a cache hit, never a recompile."""
    out = check_srcs(tmp_path, {
        "pkg/ops/fac.py": """
            import jax
            from functools import lru_cache
            @lru_cache(maxsize=8)
            def make_step(n):
                return jax.jit(lambda v: v * n)
        """,
        "pkg/models/use.py": """
            from pkg.ops.fac import make_step
            def score_all(xs):
                return [make_step(8)(x) for x in xs]
        """})
    assert out == []


GC04_CROSS = """
    import threading
    from pkg.serve.helper import bump_counter
    class X:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            threading.Thread(target=self._a).start()
            threading.Thread(target=self._b).start()
        def _a(self):
            bump_counter(self)
        def _b(self):
            with self._lock:
                self.count -= 1
"""


def test_gc04_cross_module_param_write_flagged(tmp_path):
    out = check_srcs(tmp_path, {
        "pkg/serve/helper.py": "def bump_counter(obj):\n"
                               "    obj.count += 1\n",
        "pkg/serve/w.py": GC04_CROSS})
    hits = [f for f in out if f.code == "GC04"]
    assert hits and any("via bump_counter" in f.message for f in hits)


def test_gc04_cross_module_missed_by_single_module_scan(tmp_path):
    out = check_srcs(tmp_path, {"pkg/serve/w.py": GC04_CROSS})
    assert [f for f in out if f.code == "GC04"] == []


def test_gc04_write_via_method_chain_flagged(tmp_path):
    """A write buried one method call below the thread entry — invisible
    to the PR 11 entry-local walk."""
    out = check_srcs(tmp_path, {"pkg/serve/w.py": """
        import threading
        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self._a).start()
                threading.Thread(target=self._b).start()
            def _a(self):
                self._bump()
            def _bump(self):
                self.n += 1
            def _b(self):
                self.n -= 1
    """})
    hits = [f for f in out if f.code == "GC04"]
    assert any("via self._bump" in f.message for f in hits)


def test_gc04_nested_closure_write_still_flagged(tmp_path):
    """Writes inside a nested helper closure of a summarized thread
    entry: the closure is absent from the entry's summary and a bare
    call to it resolves to None, so the rule must ALSO walk the entry's
    nested defs (regression — the v2 summary path once replaced the
    walk entirely and this PR 11-era catch went silent)."""
    out = check_srcs(tmp_path, {"pkg/serve/w.py": """
        import threading
        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                threading.Thread(target=self._a).start()
                threading.Thread(target=self._b).start()
            def _a(self):
                def bump():
                    self.count += 1
                for _ in range(10):
                    bump()
            def _b(self):
                with self._lock:
                    self.count = 0
    """})
    hits = [f for f in out if f.code == "GC04" and "count" in f.message]
    assert hits and hits[0].symbol == "W._a"


def test_gc04_lock_held_at_call_site_propagates(tmp_path):
    """A write is guarded when the CALL EDGE held the lock, even though
    the write site itself shows no with-block (the engine.poll() ->
    _load_newest() shape)."""
    out = check_srcs(tmp_path, {"pkg/serve/w.py": """
        import threading
        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self._a).start()
                threading.Thread(target=self._b).start()
            def _a(self):
                with self._lock:
                    self._bump()
            def _bump(self):
                self.n += 1
            def _b(self):
                with self._lock:
                    self.n -= 1
    """})
    assert [f for f in out if f.code == "GC04"] == []


# -- GC07 transfer-discipline --------------------------------------------

def test_gc07_direct_transfer_in_loop_flagged(tmp_path):
    out = check_src(tmp_path, """
        import numpy as np
        def train(step, batches):
            losses = []
            for b in batches:
                losses.append(float(np.asarray(step(b))))
            return losses
    """, "pkg/models/hot.py")
    assert codes(out) == ["GC07"]


def test_gc07_one_hop_helper_flagged(tmp_path):
    out = check_srcs(tmp_path, {
        "pkg/ops/fetch.py": "import numpy as np\n"
                            "def fetch(x):\n"
                            "    return float(np.asarray(x))\n",
        "pkg/models/hot.py": """
            from pkg.ops.fetch import fetch
            def train(step, batches):
                return [fetch(step(b)) for b in batches]
        """})
    hits = [f for f in out if f.code == "GC07"]
    assert hits and hits[0].path == "pkg/models/hot.py"
    assert "fetch" in hits[0].message


def test_gc07_transfer_outside_loop_clean(tmp_path):
    out = check_src(tmp_path, """
        import numpy as np
        def train(step, batches):
            acc = None
            for b in batches:
                acc = step(b, acc)
            return float(np.asarray(acc))
    """, "pkg/models/hot.py")
    assert out == []


def test_gc07_outside_models_ops_not_scanned(tmp_path):
    out = check_src(tmp_path, """
        import numpy as np
        def drain(batches):
            return [np.asarray(b) for b in batches]
    """, "pkg/io/x.py")
    assert out == []


def test_gc07_loop_iter_expression_clean(tmp_path):
    """The iterable evaluates ONCE — np.asarray in the for-iter position
    is not a per-iteration sync."""
    out = check_src(tmp_path, """
        import numpy as np
        def walk(xs):
            total = 0
            for v in np.asarray(xs):
                total += v
            return total
    """, "pkg/models/x.py")
    assert out == []


def test_gc07_block_until_ready_flagged(tmp_path):
    out = check_src(tmp_path, """
        def train(step, batches):
            for b in batches:
                step(b).block_until_ready()
    """, "pkg/ops/x.py")
    assert codes(out) == ["GC07"]


# -- GC08 thread-lifecycle -----------------------------------------------

GC08_LEAKY = """
    import threading
    class Daemon:
        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()
        def _run(self):
            while True:
                pass
"""


def test_gc08_unjoined_looping_thread_flagged(tmp_path):
    out = check_src(tmp_path, GC08_LEAKY)
    assert codes(out) == ["GC08"]


def test_gc08_joined_thread_clean(tmp_path):
    out = check_src(tmp_path, """
        import threading
        class Daemon:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()
            def _run(self):
                while True:
                    pass
            def close(self):
                self._t.join(timeout=5)
    """)
    assert out == []


def test_gc08_poison_pill_event_clean(tmp_path):
    out = check_src(tmp_path, """
        import threading
        class Daemon:
            def start(self):
                self._stop = threading.Event()
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()
            def _run(self):
                while not self._stop.wait(1.0):
                    pass
            def close(self):
                self._stop.set()
    """)
    assert out == []


def test_gc08_event_gate_never_set_flagged(tmp_path):
    """A loop gated on an Event nothing ever set()s is NOT a shutdown
    path — the finding names the dangling gate."""
    out = check_src(tmp_path, """
        import threading
        class Daemon:
            def start(self):
                self._stop = threading.Event()
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()
            def _run(self):
                while not self._stop.wait(1.0):
                    pass
    """)
    assert codes(out) == ["GC08"]
    assert "_stop" in out[0].message


def test_gc08_loop_join_over_thread_list_clean(tmp_path):
    """The fleet idiom: threads appended to self._threads, joined in a
    for-loop at stop()."""
    out = check_src(tmp_path, """
        import threading
        class M:
            def start(self):
                self._threads = []
                for name in ("a", "b"):
                    t = threading.Thread(target=self._loop, daemon=True)
                    t.start()
                    self._threads.append(t)
            def _loop(self):
                while True:
                    pass
            def stop(self):
                for t in self._threads:
                    t.join(timeout=5)
    """)
    assert out == []


def test_gc08_run_once_target_clean(tmp_path):
    """No loop in the target: the thread ends on its own — no shutdown
    obligation (the engine's background-warmup shape)."""
    out = check_src(tmp_path, """
        import threading
        class W:
            def start(self):
                self._t = threading.Thread(target=self._work, daemon=True)
                self._t.start()
            def _work(self):
                x = 1 + 1
                return x
    """)
    assert out == []


def test_gc08_anonymous_local_thread_out_of_scope(tmp_path):
    """Fire-and-forget threads never stored on self (per-connection
    handlers, locally-joined workers) are out of GC08's scope."""
    out = check_src(tmp_path, """
        import threading
        class A:
            def handle(self, conns):
                for c in conns:
                    threading.Thread(target=self._serve,
                                     args=(c,), daemon=True).start()
            def _serve(self, c):
                while c.alive():
                    pass
    """)
    assert out == []


# -- pass-1 robustness: exotic constructs degrade, never crash ------------

def test_pass1_decorated_async_lambda_property_no_crash(tmp_path):
    """Decorated defs, async defs, lambdas as thread targets and
    properties must all survive pass 1; unresolvable constructs degrade
    to 'unknown' (no findings invented)."""
    out = check_srcs(tmp_path, {"pkg/serve/exotic.py": """
        import threading
        import functools

        def mystery(fn):
            @functools.wraps(fn)
            def inner(*a, **k):
                return fn(*a, **k)
        　
        class E:
            def __init__(self):
                self._t = threading.Thread(target=lambda: self._spin())
                self._t.start()

            @property
            def size(self):
                return 1

            @size.setter
            def size(self, v):
                self._size = v

            @mystery
            def decorated(self):
                return self.size

            async def poll(self):
                return self.size

            def _spin(self):
                while True:
                    pass

            def close(self):
                self._t.join(timeout=1)
    """.replace("　", "")})
    assert [f for f in out if f.code == "GC00"] == []
    # the lambda target resolves through to _spin or degrades silently;
    # either way the joined thread must not produce a GC08 finding
    assert [f for f in out if f.code == "GC08"] == []


def test_pass1_lambda_thread_target_degrades_unknown(tmp_path):
    """A lambda target that cannot be resolved produces NO GC08 finding
    even without a join — unknown degrades to silence, not certainty."""
    out = check_src(tmp_path, """
        import threading
        class E:
            def start(self, job):
                self._t = threading.Thread(target=lambda: job.run())
                self._t.start()
    """)
    assert [f for f in out if f.code == "GC08"] == []


def test_summaries_degrade_on_dynamic_dispatch(tmp_path):
    """getattr dispatch is unresolvable: no GC02 finding is invented for
    a helper the analysis cannot identify."""
    out = check_src(tmp_path, """
        import time
        def get_clock(name):
            return getattr(time, name)
        def wait(seconds):
            clock = get_clock("monotonic")
            deadline = clock() + seconds
            while clock() < deadline:
                pass
    """)
    assert out == []


# -- findings cache -------------------------------------------------------

def test_cache_warm_replay_identical(tmp_path):
    cache = str(tmp_path / "cache.json")
    files = {"pkg/io/bad.py": GC03_BAD}
    cold = check_srcs(tmp_path, files, cache=cache)
    assert cold and os.path.exists(cache)
    warm = run_paths([str(tmp_path)], root=str(tmp_path), cache=cache)
    assert [f.fingerprint for f in warm] == [f.fingerprint for f in cold]
    assert [(f.line, f.col) for f in warm] == [(f.line, f.col)
                                              for f in cold]


def test_cache_invalidated_by_edit(tmp_path):
    cache = str(tmp_path / "cache.json")
    check_srcs(tmp_path, {"pkg/io/bad.py": GC03_BAD}, cache=cache)
    # fix the violation on disk: the cached findings must NOT be replayed
    (tmp_path / "pkg" / "io" / "bad.py").write_text(
        textwrap.dedent(GC03_GOOD))
    out = run_paths([str(tmp_path)], root=str(tmp_path), cache=cache)
    assert out == []


def test_cache_invalidated_by_rulestamp(tmp_path):
    from hivemall_tpu.tools.graftcheck.engine import _cache_load
    cache = str(tmp_path / "cache.json")
    check_srcs(tmp_path, {"pkg/io/bad.py": GC03_BAD}, cache=cache)
    data = json.loads((tmp_path / "cache.json").read_text())
    data["stamp"] = "graftcheck-v0-ancient"
    (tmp_path / "cache.json").write_text(json.dumps(data))
    shas = {rel: e["sha"] for rel, e in data["files"].items()}
    assert _cache_load(cache, shas) is None


def test_cache_invalidated_by_new_file(tmp_path):
    """Interprocedural coupling: ADDING a module must invalidate the
    whole cache (its summaries can change other files' findings)."""
    cache = str(tmp_path / "cache.json")
    check_srcs(tmp_path, {"pkg/io/dl.py": GC02_USER}, cache=cache)
    out = check_srcs(tmp_path, {"pkg/io/dl.py": GC02_USER,
                                "pkg/utils/clockutil.py": GC02_HELPER},
                     cache=cache)
    assert [f for f in out if f.code == "GC02"]


# -- --fix ---------------------------------------------------------------

def test_fix_gc02_rewrites_clock_and_taint_sources(tmp_path):
    tree = tmp_path / "pkg"
    tree.mkdir()
    bad = tree / "clockbad.py"
    bad.write_text(textwrap.dedent("""
        import time
        def wait(s):
            deadline = time.time() + s
            while time.time() < deadline:
                pass
    """))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "hivemall_tpu.tools.graftcheck",
             str(tree), "--root", str(tmp_path), *extra],
            capture_output=True, text=True, cwd=REPO, env=env)

    r = run("--fix")
    assert r.returncode == 1
    assert "-    deadline = time.time() + s" in r.stdout
    assert "+    deadline = time.monotonic() + s" in r.stdout
    assert bad.read_text().count("time.time()") == 2  # diff only
    r = run("--fix", "--write")
    assert r.returncode == 0, r.stderr
    assert "time.time()" not in bad.read_text()
    assert run().returncode == 0          # post-fix scan gates clean


def test_fix_gc06_inserts_annotation(tmp_path):
    tree = tmp_path / "pkg" / "serve"
    tree.mkdir(parents=True)
    bad = tree / "x.py"
    bad.write_text(textwrap.dedent("""
        def f():
            try:
                pass
            except Exception:
                pass
    """))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, "-m", "hivemall_tpu.tools.graftcheck",
         str(tmp_path / "pkg"), "--root", str(tmp_path),
         "--fix", "--write"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    assert "except Exception:  #" in bad.read_text()


# -- repo-level: the EXTENDED default scan gates clean --------------------

def test_extended_repo_surface_gates_clean(extended_scan):
    """tests/, bench.py and the graft entry obey the same invariants as
    the package (the PR 12 scan-coverage satellite): the full default
    surface carries ZERO findings."""
    assert extended_scan == [], "\n".join(
        f.render() for f in extended_scan)


# -- review-pass regressions ----------------------------------------------

def test_fix_helper_tainted_gc02_not_claimed_fixable(tmp_path):
    """A GC02 finding whose taint source is a HELPER return carries no
    literal time.time() to rewrite: --fix --write must not report
    success on a no-op (the gate would still fail next run)."""
    files = {"pkg/utils/clockutil.py": GC02_HELPER,
             "pkg/io/dl.py": """
                 from pkg.utils.clockutil import now_s
                 def over(limit):
                     t0 = now_s()
                     return limit - t0 > 5
             """}
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    out = run_paths([str(tmp_path)], root=str(tmp_path))
    hits = [f for f in out if f.code == "GC02"]
    assert hits and all(f.fix_kind is None for f in hits)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    before = (tmp_path / "pkg" / "io" / "dl.py").read_text()
    r = subprocess.run(
        [sys.executable, "-m", "hivemall_tpu.tools.graftcheck",
         str(tmp_path / "pkg"), "--root", str(tmp_path),
         "--fix", "--write"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert "rewrote 0 finding(s)" in r.stderr, r.stderr
    assert (tmp_path / "pkg" / "io" / "dl.py").read_text() == before


def test_dotted_module_alias_resolution(tmp_path):
    """`import pkg.utils as utils` + `utils.clockutil.now_s()` must
    resolve through the alias even when the alias equals the target's
    last component (the review-caught resolution bug)."""
    out = check_srcs(tmp_path, {
        "pkg/utils/clockutil.py": GC02_HELPER,
        "pkg/utils/__init__.py": "",
        "pkg/__init__.py": "",
        "pkg/io/dl.py": """
            import pkg.utils as utils
            def wait(seconds):
                deadline = utils.clockutil.now_s() + seconds
                while utils.clockutil.now_s() < deadline:
                    pass
        """})
    hits = [f for f in out if f.code == "GC02"]
    assert hits and hits[0].path == "pkg/io/dl.py", \
        "\n".join(f.render() for f in out)


def test_package_reexport_hop_resolves(tmp_path):
    """`from .clockutil import now_s` inside pkg/utils/__init__.py is a
    PACKAGE-relative import: consumers importing through the package
    re-export must still carry the taint (review-caught: packages
    resolved one level too high and the hop silently went dark)."""
    out = check_srcs(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/utils/clockutil.py": GC02_HELPER,
        "pkg/utils/__init__.py": "from .clockutil import now_s\n",
        "pkg/io/dl.py": """
            from pkg.utils import now_s
            def wait(seconds):
                deadline = now_s() + seconds
                while now_s() < deadline:
                    pass
        """})
    hits = [f for f in out if f.code == "GC02"]
    assert hits and hits[0].path == "pkg/io/dl.py", \
        "\n".join(f.render() for f in out)


def test_fix_rewrites_every_taint_source_line(tmp_path):
    """A name assigned from time.time() on SEVERAL lines: --fix --write
    must rewrite all of them so the rescan gates clean (review-caught:
    only the last-seen assignment line was recorded)."""
    tree = tmp_path / "pkg"
    tree.mkdir()
    bad = tree / "multi.py"
    bad.write_text(textwrap.dedent("""
        import time
        def span(flag, t1):
            t0 = time.time()
            if flag:
                t0 = time.time()
            return t0 - t1
    """))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "hivemall_tpu.tools.graftcheck",
             str(tree), "--root", str(tmp_path), *extra],
            capture_output=True, text=True, cwd=REPO, env=env)

    assert run("--fix", "--write").returncode == 0
    assert "time.time()" not in bad.read_text()
    assert run().returncode == 0, "rescan after --fix --write must gate"


def test_fix_gc02_spares_wall_anchor_assignments(tmp_path):
    """A tainted name that ALSO feeds an epoch export (`ts = start *
    1e6`, the chrome-trace anchor pattern) must not be claimed fixable:
    rewriting its assignment would corrupt the anchor, and rewriting
    just the arithmetic would mix clocks (review-caught — --fix --write
    silently monotonic-ized wall anchors)."""
    out = check_srcs(tmp_path, {"pkg/io/dl.py": """
        import time
        def dual():
            start = time.time()
            ts_epoch_us = start * 1e6
            dur = time.time() - start
            return ts_epoch_us, dur
    """})
    hits = [f for f in out if f.code == "GC02"]
    assert hits, "dual-use anchor arithmetic must still be FLAGGED"
    assert all(f.fix_kind is None and not f.fix_lines for f in hits), \
        [f.to_json() for f in hits]


def test_cache_mangled_entry_rescans(tmp_path):
    """A cache whose per-file entry is not a dict (hand-edit / merge
    damage) must degrade to a full re-scan, never crash the gate
    (review-caught AttributeError)."""
    from hivemall_tpu.tools.graftcheck.engine import _cache_load
    from hivemall_tpu.tools.graftcheck.rules import RULESTAMP
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps({"stamp": RULESTAMP,
                                 "files": {"a.py": "xyz"}}))
    assert _cache_load(str(cache), {"a.py": "xyz"}) is None
    # and end-to-end: a scan handed the mangled cache still completes
    out = check_srcs(tmp_path, {"pkg/io/bad.py": GC03_BAD},
                     cache=str(cache))
    assert [f for f in out if f.code == "GC03"]


def test_tsan_env_negatives_stay_disabled(monkeypatch):
    from hivemall_tpu.testing import tsan
    for v in ("0", "false", "False", "NO", "off", ""):
        monkeypatch.setenv(tsan.ENV_FLAG, v)
        if not tsan.enabled():
            assert tsan.maybe_enable() is False, v


# =========================================================================
# v3 (PR 14): GC09-GC12 — XLA compile contract + resource lifecycle
# =========================================================================

# -- GC09 tracer-safety ----------------------------------------------------

def test_gc09_np_cast_and_branch_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def step(w, g):
            lr = float(np.mean(g))
            if g > 0:
                w = w - lr * g
            return w
    """)
    hits = [f for f in out if f.code == "GC09"]
    msgs = " | ".join(f.message for f in hits)
    assert "np.mean" in msgs            # the numpy concretization
    assert "float" in msgs              # the cast
    assert "control flow" in msgs       # the Python branch
    # the np call is the mechanical --fix subset
    assert any(f.fix_kind == "gc09-jnp" for f in hits)


def test_gc09_item_tolist_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax

        @jax.jit
        def fetch(x):
            return x.sum().item()
    """)
    hits = [f for f in out if f.code == "GC09"]
    assert hits and ".item()" in hits[0].message


def test_gc09_concrete_attrs_and_is_none_clean(tmp_path):
    """shape/dtype reads and `is None` checks are static under trace —
    the repo's cores lean on both (val-None elision, B = shape[1])."""
    out = check_src(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def core(w, idx, val):
            B = idx.shape[0]
            if val is None:
                val = (idx != 0).astype(jnp.float32)
            return (w[idx] * val).sum() / B
    """)
    assert [f for f in out if f.code == "GC09"] == []


def test_gc09_static_argnums_params_clean(tmp_path):
    """A static_argnums position is concrete — branching on it is the
    POINT of marking it static."""
    out = check_src(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(1,))
        def step(w, mode):
            if mode == "train":
                return w * 2.0
            return w
    """)
    assert [f for f in out if f.code == "GC09"] == []


def test_gc09_lax_scan_body_params_traced(tmp_path):
    out = check_src(tmp_path, """
        import jax
        import numpy as np

        def run(xs, w0):
            def body(carry, x):
                bad = np.sum(x)
                return carry + bad, bad
            return jax.lax.scan(body, w0, xs)
    """)
    hits = [f for f in out if f.code == "GC09"]
    assert hits and "np.sum" in hits[0].message


GC09_HELPER = """
    import numpy as np

    def host_norm(v):
        return np.sum(v * v)
"""

GC09_JIT_USER = """
    import jax
    from pkg.ops.helper_np import host_norm

    @jax.jit
    def fused(x):
        return host_norm(x * 2.0)
"""


def test_gc09_cross_module_taint_flagged(tmp_path):
    """The np call lives in a helper module; it is only a hazard
    because a jit body in ANOTHER module hands it a tracer."""
    out = check_srcs(tmp_path, {"pkg/ops/helper_np.py": GC09_HELPER,
                                "pkg/models/user.py": GC09_JIT_USER})
    hits = [f for f in out if f.code == "GC09"]
    assert hits and hits[0].path == "pkg/ops/helper_np.py"
    assert "host_norm" in hits[0].message


def test_gc09_cross_module_missed_by_single_module_scan(tmp_path):
    """Without the jit caller in the scan, the helper is just host-side
    numpy — no tracer ever reaches it."""
    out = check_srcs(tmp_path, {"pkg/ops/helper_np.py": GC09_HELPER})
    assert [f for f in out if f.code == "GC09"] == []


def test_gc09_untraced_host_helper_clean(tmp_path):
    """The same helper called from plain host code stays clean — GC09
    is about TRACED reachability, not numpy style."""
    out = check_srcs(tmp_path, {
        "pkg/ops/helper_np.py": GC09_HELPER,
        "pkg/models/host.py": """
            from pkg.ops.helper_np import host_norm
            def evaluate(rows):
                return [host_norm(r) for r in rows]
        """})
    assert [f for f in out if f.code == "GC09"] == []


def test_gc09_suppression_honored(tmp_path):
    out = check_src(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def step(w):
            return np.asarray(w)  # graftcheck: disable=GC09,GC07
    """)
    assert [f for f in out if f.code == "GC09"] == []


def test_gc09_tests_dir_exempt(tmp_path):
    out = check_src(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def step(w):
            return np.asarray(w)
    """, rel="tests/test_adhoc.py")
    assert [f for f in out if f.code == "GC09"] == []


# -- GC10 carry-stability --------------------------------------------------

def test_gc10_scalar_literal_carry_leaf_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax

        def run(xs, w):
            def body(carry, x):
                w, t = carry
                return (w + x, 0.0), w
            return jax.lax.scan(body, (w, 0.0), xs)
    """)
    hits = [f for f in out if f.code == "GC10"]
    assert hits and "0.0" in hits[0].message


def test_gc10_astype_literal_dtype_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax

        def run(xs, s0):
            def body(carry, x):
                s, t = carry
                return (s + x, t.astype('float32')), s
            return jax.lax.scan(body, s0, xs)
    """)
    hits = [f for f in out if f.code == "GC10"]
    assert hits and "astype" in hits[0].message


def test_gc10_astype_of_input_dtype_clean(tmp_path):
    """x.astype(w.dtype) PRESERVES the carry leaf dtype — the linear
    core's w_new.astype(w.dtype) idiom must pass."""
    out = check_src(tmp_path, """
        import jax

        def run(xs, w0):
            def body(carry, x):
                w, t = carry
                w2 = (w + x).astype(w.dtype)
                return (w2, t + 1.0), w2
            return jax.lax.scan(body, w0, xs)
    """)
    assert [f for f in out if f.code == "GC10"] == []


def test_gc10_divergent_return_lengths_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax

        def run(xs, s0, flag):
            def body(carry, x):
                s, t = carry
                if x.sum() > 0:
                    return (s, t, s), s
                return (s, t), s
            return jax.lax.scan(body, s0, xs)
    """)
    hits = [f for f in out if f.code == "GC10"]
    assert hits and "differ in length" in hits[0].message


GC10_BODY = """
    def body(carry, x):
        s, t = carry
        return (s + x, t.astype('float32')), s
"""


def test_gc10_cross_module_scan_body_flagged(tmp_path):
    """The body is only a scan body because ANOTHER module hands it to
    lax.scan — the finding lands in the body's module."""
    out = check_srcs(tmp_path, {
        "pkg/ops/scan_body.py": GC10_BODY,
        "pkg/models/runner.py": """
            import jax
            from pkg.ops.scan_body import body
            def run(xs, s0):
                return jax.lax.scan(body, s0, xs)
        """})
    hits = [f for f in out if f.code == "GC10"]
    assert hits and hits[0].path == "pkg/ops/scan_body.py"


def test_gc10_cross_module_missed_by_single_module_scan(tmp_path):
    out = check_srcs(tmp_path, {"pkg/ops/scan_body.py": GC10_BODY})
    assert [f for f in out if f.code == "GC10"] == []


def test_gc10_repo_scan_bodies_pass_clean(repo_index):
    """Non-vacuity pin: the repo's real scan bodies (ops.scan megastep
    body, trees round_fn, the models/ slab bodies) are IN the analyzed
    population and all pass."""
    idx = repo_index
    ops_bodies = {fid for fid in idx.scan_bodies
                  if fid[0].startswith("hivemall_tpu/")}
    assert len(ops_bodies) >= 5, sorted(ops_bodies)
    assert ("hivemall_tpu/ops/scan.py",
            "make_megastep.megastep.body") in ops_bodies
    assert ("hivemall_tpu/ops/trees.py",
            "boost_loop_xgb.loop.round_fn") in ops_bodies


def _repo_files():
    from hivemall_tpu.tools.graftcheck import engine as eng
    files = {}
    for p in eng.iter_py_files(eng._default_paths()):
        rel = os.path.relpath(os.path.abspath(p), REPO).replace(
            os.sep, "/")
        files[rel] = os.path.abspath(p)
    return files


# -- GC11 donation-discipline ----------------------------------------------

def test_gc11_read_after_donate_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax

        def core(w, s, x):
            return w + x, s

        def train(w, s, xs):
            step = jax.jit(core, donate_argnums=(0, 1))
            out, s2 = step(w, s)
            return out, s2, w.sum()
    """)
    hits = [f for f in out if f.code == "GC11"]
    assert hits and "'w'" in hits[0].message
    assert "DONATED" in hits[0].message


def test_gc11_rebind_pattern_clean(tmp_path):
    """state = step(state, batch) — the donated name is REBOUND by the
    call's own assignment (the repo's universal dispatch shape)."""
    out = check_src(tmp_path, """
        import jax

        def core(w, s, x):
            return w + x, s

        def train(w, s, xs):
            step = jax.jit(core, donate_argnums=(0, 1))
            for x in xs:
                w, s = step(w, s)
            return w, s
    """)
    assert [f for f in out if f.code == "GC11"] == []


def test_gc11_scannable_without_donation_flagged(tmp_path):
    out = check_src(tmp_path, """
        import jax

        def scannable(step, core):
            step.core = core
            return step

        def make_step():
            def core(w, s, t, idx):
                return w, s, 0.0
            return scannable(jax.jit(core), core)
    """, rel="pkg/ops/mystep.py")
    hits = [f for f in out if f.code == "GC11"]
    assert hits and "donate_argnums" in hits[0].message


def test_gc11_scannable_with_donation_clean(tmp_path):
    out = check_src(tmp_path, """
        import jax
        from functools import partial

        def scannable(step, core):
            step.core = core
            return step

        def make_step():
            def core(w, s, t, idx):
                return w, s, 0.0
            return scannable(
                partial(jax.jit, donate_argnums=(0, 1))(core), core)
    """, rel="pkg/ops/mystep.py")
    assert [f for f in out if f.code == "GC11"] == []


GC11_FACTORY = """
    import jax

    def make_step(core):
        return jax.jit(core, donate_argnums=(0, 1))
"""

GC11_BAD_READER = """
    from pkg.ops.donate_factory import make_step

    def train(core, w, s, xs):
        step = make_step(core)
        w2, s2 = step(w, s)
        return w2, s2, w.sum()
"""


def test_gc11_cross_module_donated_factory_flagged(tmp_path):
    """The donation is declared in the factory's module; the
    read-after-donate happens in the caller's."""
    out = check_srcs(tmp_path, {
        "pkg/ops/donate_factory.py": GC11_FACTORY,
        "pkg/models/reader.py": GC11_BAD_READER})
    hits = [f for f in out if f.code == "GC11"]
    assert hits and hits[0].path == "pkg/models/reader.py"


def test_gc11_cross_module_missed_by_single_module_scan(tmp_path):
    out = check_srcs(tmp_path, {"pkg/models/reader.py": GC11_BAD_READER})
    assert [f for f in out if f.code == "GC11"] == []


def test_gc11_repo_donation_population(repo_index):
    """Non-vacuity pin: the repo's donate_argnums population (the ops/
    scannable cores, make_megastep, the models/ step factories) is in
    the index — at least 6 donated defs and 6 donating factories."""
    idx = repo_index
    donated_defs = [s for s in idx.functions.values()
                    if s.donated_positions]
    factories = [s for s in idx.functions.values() if s.returns_donated]
    assert len(donated_defs) >= 6
    assert len(factories) >= 6
    assert ("hivemall_tpu/ops/scan.py", "make_megastep") in \
        {s.fid for s in factories}
    # and the traced-parameter closure is populated (GC09 non-vacuity)
    assert len(idx.traced) >= 200


# -- GC12 resource-lifecycle -----------------------------------------------

def test_gc12_never_closed_flagged(tmp_path):
    out = check_src(tmp_path, """
        import socket

        def ping(addr):
            s = socket.create_connection(addr)
            s.sendall(b'x')
            return s.recv(4)
    """, rel="pkg/serve/conn.py")
    hits = [f for f in out if f.code == "GC12"]
    assert hits and "never closed" in hits[0].message


def test_gc12_straight_line_close_flagged(tmp_path):
    out = check_src(tmp_path, """
        import socket

        def probe(addr):
            s = socket.create_connection(addr)
            s.sendall(b'ping')
            data = s.recv(16)
            s.close()
            return data
    """, rel="pkg/serve/conn.py")
    hits = [f for f in out if f.code == "GC12"]
    assert hits and "straight-line" in hits[0].message


def test_gc12_with_and_finally_clean(tmp_path):
    out = check_src(tmp_path, """
        import socket

        def a(addr):
            with socket.create_connection(addr) as s:
                return s.recv(4)

        def b(addr):
            s = socket.create_connection(addr)
            try:
                s.sendall(b'x')
                return s.recv(4)
            finally:
                s.close()
    """, rel="pkg/serve/conn.py")
    assert [f for f in out if f.code == "GC12"] == []


def test_gc12_cleanup_and_reraise_clean(tmp_path):
    """The router _RawConn idiom after the PR 14 fix: close in an
    except handler that re-raises."""
    out = check_src(tmp_path, """
        import socket

        class Conn:
            def __init__(self, addr):
                self.sock = socket.create_connection(addr)
                try:
                    self.sock.setsockopt(1, 1, 1)
                    self.rfile = self.sock.makefile('rb')
                except OSError:
                    self.sock.close()
                    raise

            def close(self):
                self.rfile.close()
                self.sock.close()
    """, rel="pkg/serve/conn.py")
    assert [f for f in out if f.code == "GC12"] == []


def test_gc12_init_store_without_guard_flagged(tmp_path):
    """The pre-fix _RawConn shape: acquire, store on self, then raising
    calls with no close-and-reraise."""
    out = check_src(tmp_path, """
        import socket

        class Conn:
            def __init__(self, addr):
                self.sock = socket.create_connection(addr)
                self.sock.setsockopt(1, 1, 1)
                self.rfile = self.sock.makefile('rb')

            def close(self):
                self.sock.close()
    """, rel="pkg/serve/conn.py")
    hits = [f for f in out if f.code == "GC12"]
    assert hits and "mid-constructor" in hits[0].message


def test_gc12_self_store_with_release_path_clean(tmp_path):
    out = check_src(tmp_path, """
        import socket

        class Server:
            def start(self, addr):
                self._sock = socket.create_connection(addr)

            def stop(self):
                self._sock.close()
    """, rel="pkg/serve/srv.py")
    assert [f for f in out if f.code == "GC12"] == []


def test_gc12_self_store_without_release_flagged(tmp_path):
    out = check_src(tmp_path, """
        import socket

        class Server:
            def start(self, addr):
                self._sock = socket.create_connection(addr)
    """, rel="pkg/serve/srv.py")
    hits = [f for f in out if f.code == "GC12"]
    assert hits and "ever releases" in hits[0].message


def test_gc12_pool_swap_release_credited(tmp_path):
    """The router close_pool idiom: pool, self._pool = self._pool, []
    then loop-close over the swapped local."""
    out = check_src(tmp_path, """
        import socket

        class Pool:
            def grab(self, addr):
                self._live = socket.create_connection(addr)

            def close_all(self):
                live, self._live = self._live, None
                live.close()
    """, rel="pkg/serve/pool.py")
    assert [f for f in out if f.code == "GC12"] == []


def test_gc12_httperror_read_without_close_flagged(tmp_path):
    out = check_src(tmp_path, """
        import json
        import urllib.error
        import urllib.request

        def probe(url):
            try:
                with urllib.request.urlopen(url) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                return json.loads(e.read())
    """, rel="pkg/serve/probe.py")
    hits = [f for f in out if f.code == "GC12"]
    assert hits and "HTTPError" in hits[0].message


def test_gc12_httperror_closed_clean(tmp_path):
    out = check_src(tmp_path, """
        import json
        import urllib.error
        import urllib.request

        def probe(url):
            try:
                with urllib.request.urlopen(url) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                try:
                    return json.loads(e.read())
                finally:
                    e.close()
    """, rel="pkg/serve/probe.py")
    assert [f for f in out if f.code == "GC12"] == []


def test_gc12_urlopen_chain_flagged(tmp_path):
    out = check_src(tmp_path, """
        import urllib.request

        def fetch(url):
            return urllib.request.urlopen(url).read()
    """, rel="pkg/serve/fetch.py")
    hits = [f for f in out if f.code == "GC12"]
    assert hits and "call chain" in hits[0].message


def test_gc12_outside_scoped_dirs_clean(tmp_path):
    out = check_src(tmp_path, """
        import socket

        def ping(addr):
            s = socket.create_connection(addr)
            return s.recv(4)
    """, rel="pkg/models/conn.py")
    assert [f for f in out if f.code == "GC12"] == []


GC12_OPENER = """
    import socket

    def dial(addr):
        return socket.create_connection(addr)
"""

GC12_CROSS_USER = """
    from pkg.io.opener import dial

    def ping(addr):
        c = dial(addr)
        c.sendall(b'x')
        return c.recv(4)
"""


def test_gc12_cross_module_returned_resource_flagged(tmp_path):
    """A helper RETURNING a fresh socket transfers ownership — the
    returns_resource closure makes the call site an acquisition."""
    out = check_srcs(tmp_path, {"pkg/io/opener.py": GC12_OPENER,
                                "pkg/serve/user.py": GC12_CROSS_USER})
    hits = [f for f in out if f.code == "GC12"]
    assert hits and hits[0].path == "pkg/serve/user.py"


def test_gc12_cross_module_missed_by_single_module_scan(tmp_path):
    out = check_srcs(tmp_path, {"pkg/serve/user.py": GC12_CROSS_USER})
    assert [f for f in out if f.code == "GC12"] == []


def test_gc12_escape_to_thread_owner_clean(tmp_path):
    """The accept-loop shape: a fresh connection handed straight to a
    handler thread is the handler's to close."""
    out = check_src(tmp_path, """
        import socket
        import threading

        class L:
            def accept_loop(self):
                while True:
                    conn, _ = self._sock.accept()
                    threading.Thread(target=self._serve,
                                     args=(conn,), daemon=True).start()
    """, rel="pkg/serve/listener.py")
    assert [f for f in out if f.code == "GC12"] == []


# -- engine v3: parallel scan, wall breakdown, --fix gc09 ------------------

def test_parallel_scan_matches_serial(tmp_path):
    """The fork-based 2-worker scan must produce byte-identical findings
    to the serial path (same fingerprints, same order)."""
    files = {}
    for i in range(30):                  # above _PARALLEL_MIN_FILES
        files[f"pkg/serve/m{i:02d}.py"] = """
            import socket
            def ping%d(addr):
                s = socket.create_connection(addr)
                s.sendall(b'x')
                return s.recv(4)
        """ % i
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    serial = run_paths([str(tmp_path)], root=str(tmp_path), jobs=1)
    t_par = {}
    parallel = run_paths([str(tmp_path)], root=str(tmp_path), jobs=2,
                         timings=t_par)
    assert [f.fingerprint for f in serial] == \
        [f.fingerprint for f in parallel]
    assert len(serial) == 30
    assert t_par.get("jobs") == 2
    assert "GC12" in t_par["rules_s"]


def test_rule_wall_breakdown_in_json_out(tmp_path):
    """--json-out carries the per-rule wall breakdown (the <=30 s CI
    budget evidence)."""
    from hivemall_tpu.tools.graftcheck.engine import main as gc_main
    p = tmp_path / "pkg" / "io" / "m.py"
    p.parent.mkdir(parents=True)
    p.write_text("import time\n\ndef wait(d):\n"
                 "    t0 = time.time()\n"
                 "    return time.time() - t0\n")
    report_path = tmp_path / "report.json"
    rc = gc_main([str(tmp_path / "pkg"), "--root", str(tmp_path),
                  "--json-out", str(report_path)])
    assert rc == 1                       # the GC02 finding
    report = json.loads(report_path.read_text())
    wall = report["wall"]
    assert set(wall["rules_s"]) == set(
        __import__("hivemall_tpu.tools.graftcheck.rules",
                   fromlist=["RULES"]).RULES)
    assert wall["total_s"] > 0


def test_fix_gc09_rewrites_np_to_jnp(tmp_path):
    """--fix's mechanical GC09 subset: np.<fn> -> jnp.<fn> on the
    flagged tracer-reaching call lines, same workflow as GC02/GC06."""
    from hivemall_tpu.tools.graftcheck.engine import _apply_fixes
    p = tmp_path / "pkg" / "models" / "m.py"
    p.parent.mkdir(parents=True)
    p.write_text(textwrap.dedent("""
        import jax
        import numpy as np
        import jax.numpy as jnp

        @jax.jit
        def step(w, g):
            return w - np.mean(g)
    """))
    findings = run_paths([str(tmp_path)], root=str(tmp_path))
    fixable = [f for f in findings if f.fix_kind == "gc09-jnp"]
    assert fixable
    diff, fixed = _apply_fixes(findings, str(tmp_path), write=True)
    assert fixed >= 1
    assert "-    return w - np.mean(g)" in diff
    assert "+    return w - jnp.mean(g)" in diff
    # the rewritten tree rescans clean on GC09
    again = run_paths([str(tmp_path)], root=str(tmp_path))
    assert [f for f in again if f.code == "GC09"] == []


def test_fix_gc09_inserts_missing_jnp_import(tmp_path):
    """A flagged module that only imports numpy — exactly the
    host-helper shape GC09 exists to catch — must gain the jnp binding
    with the rewrite, or --fix --write would leave it raising
    NameError at import while the rescan reads clean."""
    from hivemall_tpu.tools.graftcheck.engine import _apply_fixes
    p = tmp_path / "pkg" / "models" / "m.py"
    p.parent.mkdir(parents=True)
    p.write_text(textwrap.dedent("""
        import jax
        import numpy as np

        @jax.jit
        def step(w, g):
            return w - np.mean(g)
    """))
    findings = run_paths([str(tmp_path)], root=str(tmp_path))
    assert [f for f in findings if f.fix_kind == "gc09-jnp"]
    diff, fixed = _apply_fixes(findings, str(tmp_path), write=True)
    assert fixed >= 1
    assert "+import jax.numpy as jnp" in diff
    text = p.read_text()
    # the binding lands right after the numpy import, before first use
    assert text.index("import jax.numpy as jnp") > text.index(
        "import numpy as np")
    assert text.index("import jax.numpy as jnp") < text.index("jnp.mean")
    compile(text, str(p), "exec")        # still a valid module
    again = run_paths([str(tmp_path)], root=str(tmp_path))
    assert [f for f in again if f.code == "GC09"] == []


def test_fix_gc09_scopes_rewrite_to_twin_calls(tmp_path):
    """The mechanical rewrite must not mint jnp.random/jnp.save
    AttributeErrors or mutate string/comment text on a flagged line —
    only twin-allowlisted np.<fn> calls in code spans change, and a
    non-twin finding survives the rescan for a human."""
    from hivemall_tpu.tools.graftcheck.engine import _apply_fixes
    p = tmp_path / "pkg" / "models" / "m.py"
    p.parent.mkdir(parents=True)
    p.write_text(textwrap.dedent("""
        import jax
        import numpy as np
        import jax.numpy as jnp

        @jax.jit
        def step(w, g):
            w = w - np.mean(g) + len("np.sum x")  # np.sum comment
            np.save("x.npy", w)
            return w
    """))
    findings = run_paths([str(tmp_path)], root=str(tmp_path))
    assert [f for f in findings if f.fix_kind == "gc09-jnp"]
    _apply_fixes(findings, str(tmp_path), write=True)
    text = p.read_text()
    assert "jnp.mean(g)" in text                  # the twin rewrote
    assert 'len("np.sum x")' in text              # string untouched
    assert "# np.sum comment" in text             # comment untouched
    assert 'np.save("x.npy", w)' in text          # no jnp.save minted
    again = run_paths([str(tmp_path)], root=str(tmp_path))
    assert [f for f in again if f.code == "GC09"]  # np.save still flagged


def test_extract_module_degrades_per_function(tmp_path, monkeypatch):
    """One intractable function degrades ALONE — the module's stubs
    (GC05's raw material) and sibling summaries survive instead of the
    whole module vanishing from the project index."""
    from hivemall_tpu.tools.graftcheck import engine as eng
    from hivemall_tpu.tools.graftcheck import interproc
    from hivemall_tpu.tools.graftcheck.rules import collect_project
    p = tmp_path / "pkg" / "obs" / "reg.py"
    p.parent.mkdir(parents=True)
    p.write_text(textwrap.dedent("""
        FOO_STUB = {"a": 1, "b": 2}

        def good():
            return 1

        def poison():
            return 2
    """))
    ctx, err = eng._parse_one(str(p), "pkg/obs/reg.py")
    assert err is None and ctx is not None
    real = interproc._summarize_function

    def boom(ctx_, mi, fn, cls, direct, bare):
        if fn.name == "poison":
            raise RuntimeError("seeded analyzer crash")
        return real(ctx_, mi, fn, cls, direct, bare)

    monkeypatch.setattr(interproc, "_summarize_function", boom)
    project = collect_project([ctx])
    assert "FOO_STUB" in project.stubs            # stubs survived
    assert project.interproc is not None
    names = {fid[1] for fid in project.interproc.functions
             if fid[0] == "pkg/obs/reg.py"}
    assert "good" in names                        # sibling summarized
    assert "poison" not in names                  # only the bad one gone


def test_selfcheck_covers_v3_rules():
    """Every GC09-GC12 fixture is wired into --selfcheck (the CI proof
    that the new rules fire)."""
    from hivemall_tpu.tools.graftcheck.engine import _FIXTURES
    want = {"GC09", "GC10", "GC11", "GC12"}
    seeded = set()
    for _rel, (_src, codes_) in _FIXTURES.items():
        seeded |= codes_
    assert want <= seeded
