"""Online classifier family: sequential-oracle equivalence at mini_batch=1
(SURVEY.md §8: covariance trainers validated against tiny-batch sequential
oracles) + convergence on separable data."""

import numpy as np
import pytest

from hivemall_tpu.frame.evaluation import auc
from hivemall_tpu.io.libsvm import synthetic_classification
from hivemall_tpu.models.classifier import (AROWTrainer, AdaGradRDATrainer,
                                            ConfidenceWeightedTrainer,
                                            KernelizedPATrainer, PA1Trainer,
                                            PA2Trainer,
                                            PARegressionTrainer,
                                            AROWRegressionTrainer,
                                            PassiveAggressiveTrainer,
                                            PerceptronTrainer, SCW1Trainer,
                                            SCW2Trainer)

ALL_BINARY = [PerceptronTrainer, PassiveAggressiveTrainer, PA1Trainer,
              PA2Trainer, ConfidenceWeightedTrainer, AROWTrainer,
              SCW1Trainer, SCW2Trainer, AdaGradRDATrainer]


@pytest.mark.parametrize("cls", ALL_BINARY)
def test_converges_separable(cls):
    ds, _ = synthetic_classification(600, 40, seed=8)
    t = cls("-dims 128 -mini_batch 16 -iters 3")
    t.fit(ds)
    score = auc(ds.labels, t.decision_function(ds))
    assert score > 0.85, (cls.NAME, score)


def test_pa_sequential_oracle():
    """mini_batch=1 PA must match the closed-form sequential updates."""
    t = PassiveAggressiveTrainer("-dims 16 -mini_batch 1")
    rows = [([1, 2], [1.0, 0.5], 1.0), ([2, 3], [1.0, 1.0], -1.0),
            ([1, 3], [0.5, 1.0], 1.0)]
    w_ref = np.zeros(16)
    for idx, val, y in rows:
        t.process((np.asarray(idx, np.int32), np.asarray(val, np.float32)), y)
        m = y * sum(w_ref[i] * v for i, v in zip(idx, val))
        loss = max(0.0, 1.0 - m)
        if loss > 0:
            xx = sum(v * v for v in val)
            tau = loss / xx
            for i, v in zip(idx, val):
                w_ref[i] += tau * y * v
    w_got = t._finalized_weights()
    np.testing.assert_allclose(w_got[:16], w_ref, rtol=1e-5, atol=1e-6)


def test_arow_sequential_oracle():
    t = AROWTrainer("-dims 8 -mini_batch 1 -r 0.1")
    rows = [([1, 2], [1.0, 1.0], 1.0), ([1, 3], [1.0, 0.5], -1.0),
            ([2, 3], [0.5, 1.0], 1.0)]
    w_ref = np.zeros(8)
    s_ref = np.ones(8)
    for idx, val, y in rows:
        t.process((np.asarray(idx, np.int32), np.asarray(val, np.float32)), y)
        m = y * sum(w_ref[i] * v for i, v in zip(idx, val))
        v_ = sum(s_ref[i] * v * v for i, v in zip(idx, val))
        if m < 1.0:
            beta = 1.0 / (v_ + 0.1)
            alpha = (1.0 - m) * beta
            for i, v in zip(idx, val):
                w_ref[i] += alpha * y * s_ref[i] * v
            for i, v in zip(idx, val):
                s_ref[i] -= beta * (s_ref[i] * v) ** 2
    np.testing.assert_allclose(t._finalized_weights()[:8], w_ref,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(t.sigma)[:8], s_ref,
                               rtol=1e-5, atol=1e-6)


def test_covar_rows_emitted():
    t = AROWTrainer("-dims 64 -mini_batch 4")
    for _ in range(8):
        t.process(["1:1.0"], 1)
        t.process(["2:1.0"], -1)
    rows = list(t.close())
    assert all(len(r) == 3 for r in rows)     # (feature, weight, covar)
    covars = {r[0]: r[2] for r in rows}
    assert 0 < covars["1"] < 1.0              # confidence grew (covar shrank)


def test_kpa_solves_xor():
    t = KernelizedPATrainer("-dims 4096 -mini_batch 8 -iters 6 -c 1")
    rng = np.random.default_rng(0)
    rows = []
    for _ in range(400):
        a, b = int(rng.integers(0, 2)), int(rng.integers(0, 2))
        feats = [f"a:{1.0 if a else -1.0}", f"b:{1.0 if b else -1.0}"]
        rows.append((feats, 1 if a != b else -1))
    for f, y in rows:
        t.process(f, y)
    # linear features alone cannot separate XOR; kernel crosses can
    correct = 0
    from hivemall_tpu.io.sparse import SparseDataset
    for f, y in rows[:100]:
        idx, val = t._parse_row(f)
        w = t._finalized_weights()
        s = (w[idx] * val).sum()
        correct += (s > 0) == (y > 0)
    assert correct > 85, correct


def test_pa_regression():
    rng = np.random.default_rng(1)
    t = PARegressionTrainer("-dims 8 -mini_batch 1 -epsilon 0.01 -c 10")
    for _ in range(300):
        x = rng.uniform(-1, 1)
        t.process((np.asarray([1], np.int32),
                   np.asarray([x], np.float32)), 2.5 * x)
    w = t._finalized_weights()
    assert abs(w[1] - 2.5) < 0.2, w[1]


def test_arow_regression():
    rng = np.random.default_rng(2)
    t = AROWRegressionTrainer("-dims 8 -mini_batch 1 -epsilon 0.01 -r 0.5")
    for _ in range(300):
        x = rng.uniform(-1, 1)
        t.process((np.asarray([1], np.int32),
                   np.asarray([x], np.float32)), -1.5 * x)
    w = t._finalized_weights()
    assert abs(w[1] + 1.5) < 0.25, w[1]


def test_multiclass_families():
    from hivemall_tpu.models.multiclass import (MulticlassAROWTrainer,
                                                MulticlassCWTrainer,
                                                MulticlassPA1Trainer,
                                                MulticlassPerceptronTrainer,
                                                MulticlassSCWTrainer,
                                                MulticlassSCW2Trainer)
    rng = np.random.default_rng(4)
    for cls in (MulticlassPerceptronTrainer, MulticlassPA1Trainer,
                MulticlassCWTrainer, MulticlassAROWTrainer,
                MulticlassSCWTrainer, MulticlassSCW2Trainer):
        t = cls("-dims 64 -classes 8 -mini_batch 4 -iters 1")
        for _ in range(300):
            c = int(rng.integers(0, 3))
            feats = [f"{c + 1}:1.0", f"{(c + 1) * 10}:0.5"]
            t.process(feats, f"class{c}")
        acc = 0
        for c in range(3):
            acc += t.classify([f"{c + 1}:1.0", f"{(c + 1) * 10}:0.5"]) \
                == f"class{c}"
        assert acc == 3, (cls.NAME, acc)
        rows = list(t.model_rows())
        assert rows and rows[0][0].startswith("class")


def test_steps_shared_across_instances_cw_arow():
    """Round 5: CW/AROW/SCW/multiclass steps are config-cached (the
    generic shared_step) — two same-config instances share one compiled
    step; different configs don't; state stays independent."""
    from hivemall_tpu.models.classifier import AROWTrainer, SCW1Trainer
    from hivemall_tpu.models.multiclass import MulticlassAROWTrainer

    a = AROWTrainer("-dims 128 -mini_batch 16")
    b = AROWTrainer("-dims 128 -mini_batch 16")
    c = AROWTrainer("-dims 128 -mini_batch 16 -r 2.0")
    assert a._step is b._step
    assert a._step is not c._step
    assert SCW1Trainer("-dims 128")._step is not a._step
    m1 = MulticlassAROWTrainer("-dims 128")
    m2 = MulticlassAROWTrainer("-dims 128")
    assert m1._step is m2._step
    # independence: training one must not touch the other's tables
    rng = np.random.default_rng(0)
    for _ in range(30):
        ids = np.sort(rng.choice(np.arange(1, 100), 5, replace=False))
        a.process([f"{i}:1" for i in ids], 1 if ids[0] % 2 else -1)
    a._flush()
    assert float(np.abs(np.asarray(b.w)).sum()) == 0.0
