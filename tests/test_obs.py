"""Unified telemetry tests (docs/OBSERVABILITY.md): span tracer, central
registry, Prometheus/HTTP surface, metrics-stream hardening, and the
under-concurrency guarantees — spans from multi-worker ingest and faulted
MIX exchanges are complete, the jsonl stream is never torn, and the
registry snapshot stays stable while a fit is running."""

import json
import os
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

import hivemall_tpu.utils.metrics as M
from hivemall_tpu.io.sparse import SparseBatch
from hivemall_tpu.models.linear import GeneralClassifier
from hivemall_tpu.obs.http import ObsServer, to_prometheus
from hivemall_tpu.obs.registry import Registry, registry
from hivemall_tpu.obs.trace import Tracer, get_tracer


@pytest.fixture
def tracer():
    """The process tracer, enabled and reset for one test, always left
    disabled+clean (it is process-global)."""
    t = get_tracer()
    t.reset()
    t.enable()
    yield t
    t.disable()
    t.reset()


def _batches(n, bs=16, dims=256, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        idx = rng.integers(1, dims, (bs, 4)).astype(np.int32)
        val = rng.normal(size=(bs, 4)).astype(np.float32)
        lab = (rng.integers(0, 2, bs) * 2 - 1).astype(np.float32)
        out.append(SparseBatch(idx, val, lab))
    return out


# --- Tracer ----------------------------------------------------------------

def test_tracer_disabled_is_noop():
    t = Tracer(enabled=False)
    s1, s2 = t.span("a"), t.span("b")
    assert s1 is s2                     # shared null object, no allocation
    with s1:
        pass
    assert t.rollup() == {}


def test_tracer_records_rollup_percentiles():
    t = Tracer(enabled=True)
    for dur in (0.001, 0.002, 0.003):
        with t.span("stage"):
            time.sleep(dur)
    r = t.rollup()
    assert set(r) == {"stage"}
    st = r["stage"]
    assert st["count"] == 3
    assert st["total_s"] >= 0.006
    assert 0 < st["p50"] <= st["p99"]
    t.reset()
    assert t.rollup() == {}


def test_tracer_thread_safe_recording():
    t = Tracer(enabled=True)

    def work():
        for _ in range(200):
            with t.span("conc"):
                pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert t.rollup()["conc"]["count"] == 800


def test_tracer_chrome_export(tmp_path):
    t = Tracer(enabled=True)
    with t.span("exported"):
        pass
    p = str(tmp_path / "trace.json")
    assert t.export_chrome(p) == p
    trace = json.loads(open(p).read())
    evs = trace["traceEvents"]
    assert evs and evs[0]["name"] == "exported" and evs[0]["ph"] == "X"
    assert evs[0]["dur"] >= 0 and "ts" in evs[0]


def test_tracer_ring_is_bounded():
    t = Tracer(enabled=True, ring=8)
    for _ in range(100):
        with t.span("r"):
            pass
    assert len(t._events) == 8          # ring, not unbounded growth
    assert t.rollup()["r"]["count"] == 100   # aggregates keep the truth


# --- Registry --------------------------------------------------------------

def test_registry_snapshot_merges_and_overrides():
    r = Registry()
    r.register("a", lambda: {"x": 1})
    r.register("a", lambda: {"x": 2})   # last wins
    r.register("b", lambda: {"y": True})
    snap = r.snapshot()
    assert snap["a"] == {"x": 2} and snap["b"] == {"y": True}
    assert "ts" in snap
    r.unregister("a")
    assert "a" not in r.snapshot()


def test_registry_provider_failure_is_isolated():
    r = Registry()
    r.register("bad", lambda: 1 / 0)
    r.register("good", lambda: {"ok": 1})
    snap = r.snapshot()
    assert snap["good"] == {"ok": 1}
    assert "ZeroDivisionError" in snap["bad"]["error"]


def test_global_registry_has_default_sections():
    snap = registry.snapshot()
    assert "mix" in snap and "checkpoint" in snap


def test_trainer_registers_pipeline_and_train_sections():
    tr = GeneralClassifier("-dims 128 -mini_batch 8")
    tr.fit_stream(iter(_batches(4, bs=8, dims=128)))
    snap = registry.snapshot()
    assert snap["train"]["trainer"] == "train_classifier"
    assert snap["train"]["step"] == 4
    assert snap["pipeline"]["batches_prepared"] == 4


def test_new_trainer_resets_mix_and_checkpoint_sections(tmp_path):
    """A later trainer without a mixer/autosaver must not inherit a still-
    alive earlier trainer's mix/checkpoint sections — construction is the
    reset (last-wins registration, every section trainer-bound)."""
    from hivemall_tpu.parallel.mix_service import MixServer
    srv = MixServer().start()
    try:
        a = GeneralClassifier(
            f"-dims 64 -mini_batch 8 -mix 127.0.0.1:{srv.port} "
            f"-mix_threshold 1 -mix_timeout 0.3 "
            f"-checkpoint_dir {tmp_path / 'ck'} -checkpoint_every 2")
        a.fit_stream(iter(_batches(4, bs=8, dims=64)))
        snap = registry.snapshot()
        assert snap["mix"]["active"] is True
        assert snap["checkpoint"]["configured"] is True
        b = GeneralClassifier("-dims 64 -mini_batch 8")   # a stays alive
        snap = registry.snapshot()
        # the inactive forms are the SHARED registry stubs (full key
        # mirrors of the live providers, so dashboards keep their keys)
        from hivemall_tpu.obs.registry import CHECKPOINT_STUB, MIX_STUB
        assert snap["mix"] == MIX_STUB
        assert snap["checkpoint"] == CHECKPOINT_STUB
        assert a is not b                                 # keep a referenced
        a._mixer.close_group()
    finally:
        srv.stop()


# --- Prometheus / HTTP surface ---------------------------------------------

def test_to_prometheus_exposition_format():
    text = to_prometheus({"ts": 1.5,
                          "pipeline": {"batches": 3, "busy_s": 0.25,
                                       "name": "skipped-string"},
                          "train": {"examples": 44776121,
                                    "ts": 1754180000.123},
                          "mix": {"alive": True,
                                  "nested": {"deep": 7}}})
    lines = text.splitlines()
    assert text.endswith("\n")
    assert "hivemall_tpu_pipeline_batches 3" in lines
    assert "hivemall_tpu_pipeline_busy_s 0.25" in lines
    assert "hivemall_tpu_mix_alive 1" in lines
    assert "hivemall_tpu_mix_nested_deep 7" in lines
    # full precision: %g-style 6-sig-digit truncation would corrupt
    # large counters and epoch timestamps
    assert "hivemall_tpu_train_examples 44776121" in lines
    assert "hivemall_tpu_train_ts 1754180000.123" in lines
    assert not any("skipped-string" in l for l in lines)
    # exposition validity: every non-comment line is `name value`
    metric = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]* -?[0-9.eE+-]+$")
    for l in lines:
        assert l.startswith(("# TYPE ", "# HELP ")) or metric.match(l), l


def test_to_prometheus_name_collision_disambiguated():
    """Sanitization is lossy ('a.b' and 'a_b' flatten to one name): two
    families under one name is invalid exposition, so the later arrival
    must be renamed with a _dup suffix and the event surfaced as a
    name_collisions gauge."""
    text = to_prometheus({"sec": {"a.b": 1, "a_b": 2,
                                  "a-b": 3}})     # three-way collision
    lines = text.splitlines()
    # keys walk in sorted order: 'a-b' arrives first and keeps the name
    assert "hivemall_tpu_sec_a_b 3" in lines
    assert "hivemall_tpu_sec_a_b_dup2 1" in lines
    assert "hivemall_tpu_sec_a_b_dup3 2" in lines
    assert "hivemall_tpu_name_collisions 2" in lines
    # HELP carries each family's TRUE dot-path, so the rename is
    # recoverable from the scrape itself
    assert "# HELP hivemall_tpu_sec_a_b_dup2 sec.a.b" in lines
    # emitted names are unique — the invalid-exposition hazard is gone
    names = [l.split()[0] for l in lines if not l.startswith("#")]
    assert len(names) == len(set(names))
    # still grammar-valid exposition
    metric = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]* -?[0-9.eE+-]+$")
    for l in lines:
        assert l.startswith(("# TYPE ", "# HELP ")) or metric.match(l), l


def test_to_prometheus_no_false_collision():
    """Distinct dot-paths that sanitize to distinct names must NOT pay
    the _dup rename, and the collisions gauge must stay absent."""
    text = to_prometheus({"pipeline": {"batches": 1},
                          "train": {"batches": 2}})
    assert "hivemall_tpu_pipeline_batches 1" in text
    assert "hivemall_tpu_train_batches 2" in text
    assert "_dup" not in text and "name_collisions" not in text


def test_to_prometheus_empty_histogram_and_nonfinite():
    """An empty histogram (no observations yet) exports sum/count only;
    NaN/inf gauge values export as Prometheus' case-insensitive
    'nan'/'inf' literals instead of corrupting the exposition."""
    text = to_prometheus({
        "serve": {"lat": {"_type": "histogram", "buckets": [],
                          "sum": 0.0, "count": 0},
                  "bad": float("nan"),
                  "hot": float("inf"),
                  "cold": float("-inf")}})
    lines = text.splitlines()
    assert "hivemall_tpu_serve_lat_sum 0.0" in lines
    assert "hivemall_tpu_serve_lat_count 0" in lines
    assert not any("_bucket" in l for l in lines)
    assert "hivemall_tpu_serve_bad nan" in lines
    assert "hivemall_tpu_serve_hot inf" in lines
    assert "hivemall_tpu_serve_cold -inf" in lines


def test_flight_section_round_trips_through_obs_server(tmp_path):
    """The flight recorder's self-census scrapes end to end: /snapshot
    carries the section (path included), /metrics its numeric gauges."""
    from hivemall_tpu.obs.flight import configure_flight
    from hivemall_tpu.obs.registry import registry as process_registry
    fr = configure_flight(str(tmp_path), label="scrape")
    srv = ObsServer(0, obs_registry=process_registry).start()
    try:
        fr.record("req.admit", req=1, rows=2)
        fr.record("req.admit", req=2, rows=2)
        base = f"http://127.0.0.1:{srv.port}"
        snap = json.loads(urllib.request.urlopen(f"{base}/snapshot",
                                                 timeout=5).read())
        assert snap["flight"]["enabled"] is True
        assert snap["flight"]["events"] == 2
        assert snap["flight"]["label"] == "scrape"
        assert snap["flight"]["path"] == fr.path
        text = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=5).read().decode()
        lines = text.splitlines()
        assert "hivemall_tpu_flight_enabled 1" in lines
        assert "hivemall_tpu_flight_events 2" in lines
        assert "hivemall_tpu_flight_dropped 0" in lines
        assert "hivemall_tpu_flight_ring_slots 4096" in lines
    finally:
        srv.stop()
        configure_flight(None)


def test_obs_http_server_snapshot_and_metrics():
    r = Registry()
    r.register("unit", lambda: {"value": 42})
    srv = ObsServer(0, obs_registry=r).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        snap = json.loads(urllib.request.urlopen(f"{base}/snapshot",
                                                 timeout=5).read())
        assert snap["unit"]["value"] == 42
        resp = urllib.request.urlopen(f"{base}/metrics", timeout=5)
        assert "text/plain" in resp.headers["Content-Type"]
        assert "hivemall_tpu_unit_value 42" in resp.read().decode()
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5)
    finally:
        srv.stop()


def test_obs_http_idle_connection_cannot_wedge_server():
    """A client that connects and never sends a request (half-open TCP,
    port scanner) must not block the single-threaded server forever —
    the handler timeout closes it and the next scrape succeeds."""
    import socket
    r = Registry()
    r.register("unit", lambda: {"value": 1})
    srv = ObsServer(0, obs_registry=r).start()
    srv._httpd.RequestHandlerClass.timeout = 0.3   # keep the test fast
    try:
        idle = socket.create_connection(("127.0.0.1", srv.port))
        try:
            snap = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/snapshot", timeout=10).read())
            assert snap["unit"]["value"] == 1
        finally:
            idle.close()
    finally:
        srv.stop()


# --- MetricsStream hardening -----------------------------------------------

class _FailingIO:
    """IO stub whose write starts failing after ``ok`` successes."""

    def __init__(self, ok: int):
        self.ok = ok
        self.lines = []

    def write(self, s):
        if self.ok <= 0:
            raise OSError("disk full")
        self.ok -= 1
        self.lines.append(s)


def test_stream_counts_dropped_events_after_write_failure():
    io = _FailingIO(ok=2)
    s = M.MetricsStream(io)
    s.emit("a")
    s.emit("b")
    assert s.dropped_events == 0 and len(io.lines) == 2
    s.emit("c")                          # write fails -> disable + count
    assert not s.enabled and s.dropped_events == 1
    s.emit("d")                          # post-disable emits keep counting
    s.emit("e")
    assert s.dropped_events == 3
    assert s.counters()["dropped_events"] == 3


def test_stream_never_counts_drops_when_deliberately_disabled():
    s = M.MetricsStream(None)
    s.emit("a")
    assert s.dropped_events == 0


def test_stream_size_rotation(tmp_path, monkeypatch):
    monkeypatch.setenv("HIVEMALL_TPU_METRICS_MAX_MB", "0.0005")  # 500 bytes
    p = str(tmp_path / "m.jsonl")
    s = M.MetricsStream(p)
    for i in range(40):
        s.emit("ev", i=i, pad="x" * 64)
    s.close()
    assert s.rotations >= 1
    assert os.path.exists(p + ".1")
    # every surviving line in both generations is intact jsonl
    for path in (p, p + ".1"):
        for line in open(path):
            assert json.loads(line)["event"] == "ev"


# --- telemetry emission from the fit loop ----------------------------------

def test_telemetry_every_and_train_done_snapshot(tmp_path, monkeypatch):
    p = tmp_path / "t.jsonl"
    monkeypatch.setattr(M, "_stream", M.MetricsStream(str(p)))
    tr = GeneralClassifier("-dims 128 -mini_batch 8 -telemetry_every 4")
    tr.fit_stream(iter(_batches(10, bs=8, dims=128)))
    M._stream.close()
    recs = [json.loads(l) for l in p.read_text().splitlines()]
    tele = [r for r in recs if r["event"] == "telemetry"]
    assert len(tele) == 2                # steps 4 and 8 of 10
    assert all("pipeline" in r["snapshot"] and "train" in r["snapshot"]
               for r in tele)
    done = [r for r in recs if r["event"] == "train_done"]
    assert len(done) == 1
    for section in ("pipeline", "train", "mix", "checkpoint", "spans"):
        assert section in done[0]["telemetry"]


def test_ffm_multi_epoch_stream_emits_one_train_done(tmp_path, monkeypatch):
    """FFM's multi-epoch fit_stream wrapper runs one base fit_stream per
    epoch; the run must still report exactly ONE train_done record."""
    from hivemall_tpu.models.fm import FFMTrainer
    p = tmp_path / "ffm.jsonl"
    monkeypatch.setattr(M, "_stream", M.MetricsStream(str(p)))
    rng = np.random.default_rng(5)

    def epoch():
        for _ in range(4):
            idx = rng.integers(1, 64, (8, 4)).astype(np.int32)
            fld = np.tile(np.arange(4, dtype=np.int32), (8, 1))
            lab = (rng.integers(0, 2, 8) * 2 - 1).astype(np.float32)
            yield SparseBatch(idx, np.ones((8, 4), np.float32), lab, fld)

    tr = FFMTrainer("-dims 64 -factors 2 -fields 4 -classification "
                    "-mini_batch 8")
    tr.fit_stream(epoch, epochs=3)
    M._stream.close()
    recs = [json.loads(l) for l in p.read_text().splitlines()]
    done = [r for r in recs if r["event"] == "train_done"]
    assert len(done) == 1
    assert done[0]["step"] == tr._t      # the FINAL step, all epochs in


def test_span_rollup_emitted_at_fold_cadence(tmp_path, monkeypatch, tracer):
    p = tmp_path / "r.jsonl"
    monkeypatch.setattr(M, "_stream", M.MetricsStream(str(p)))
    tr = GeneralClassifier("-dims 128 -mini_batch 8")
    tr.fit_stream(iter(_batches(260, bs=8, dims=128)))
    M._stream.close()
    recs = [json.loads(l) for l in p.read_text().splitlines()]
    rolls = [r for r in recs if r["event"] == "span_rollup"]
    assert len(rolls) == 1               # one 256-step boundary crossed
    stages = rolls[0]["stages"]
    assert stages["dispatch.step"]["count"] >= 256
    assert stages["ingest.prep"]["count"] >= 256
    assert {"count", "total_s", "p50", "p99"} <= set(
        stages["dispatch.step"])


def test_epoch_checkpoint_event_via_shared_helper(tmp_path, monkeypatch):
    """Both epoch-bundle sites (base + fm adareg) now ride
    _save_epoch_bundle/_emit_checkpoint_event; the event schema is one."""
    from hivemall_tpu.io.libsvm import synthetic_classification
    p = tmp_path / "c.jsonl"
    monkeypatch.setattr(M, "_stream", M.MetricsStream(str(p)))
    ds, _ = synthetic_classification(64, 16, seed=3)
    ck = str(tmp_path / "ck")
    tr = GeneralClassifier(f"-dims 128 -mini_batch 16 -iters 2 "
                           f"-checkpoint_dir {ck}")
    tr.fit(ds)
    M._stream.close()
    recs = [json.loads(l) for l in p.read_text().splitlines()]
    cks = [r for r in recs if r["event"] == "checkpoint"]
    assert [r["epoch"] for r in cks] == [1, 2]
    assert all(r["trainer"] == "train_classifier" and "path" in r
               for r in cks)


# --- concurrency: the live-surface guarantees ------------------------------

def test_concurrent_workers_spans_and_stable_snapshot(tmp_path, monkeypatch,
                                                      tracer):
    """Spans from ingest_workers>1 pipeline workers land complete, the
    jsonl stream has no interleaved/torn lines, and registry.snapshot()
    called from another thread DURING the fit never fails or blocks."""
    p = tmp_path / "conc.jsonl"
    monkeypatch.setattr(M, "_stream", M.MetricsStream(str(p)))
    tr = GeneralClassifier("-dims 256 -mini_batch 16 -ingest_workers 3")
    stop = threading.Event()
    snaps, errors = [], []

    def poll():
        while not stop.is_set():
            try:
                snaps.append(registry.snapshot())
            except Exception as e:      # noqa: BLE001 — the assertion
                errors.append(e)
            time.sleep(0.002)

    poller = threading.Thread(target=poll)
    poller.start()
    try:
        tr.fit_stream(iter(_batches(300, bs=16, dims=256)))
    finally:
        stop.set()
        poller.join()
    M._stream.close()
    assert not errors
    assert len(snaps) > 2
    assert all("pipeline" in s and "spans" in s for s in snaps)
    # every line written under concurrency parses — no torn writes
    recs = [json.loads(l) for l in p.read_text().splitlines()]
    assert {"train_step", "span_rollup", "train_done"} <= \
        {r["event"] for r in recs}
    roll = tr._tracer.rollup()
    assert roll["ingest.prep"]["count"] == 300     # every worker span landed
    assert roll["dispatch.step"]["count"] == 300


def test_mix_exchange_spans_under_faults(tracer):
    """FlakyProxy-faulted MIX exchanges still record complete
    mix.exchange spans (one per exchange window, faults absorbed inside),
    and the registry's mix section tracks the client."""
    from hivemall_tpu.parallel.mix_service import MixServer
    from hivemall_tpu.testing.faults import FlakyProxy
    srv = MixServer().start()
    proxy = FlakyProxy(("127.0.0.1", srv.port),
                       schedule={1: "rst", 3: "drop"}).start()
    try:
        clf = GeneralClassifier(
            f"-dims 32 -mini_batch 4 -eta fixed -eta0 0.5 -reg no "
            f"-mix 127.0.0.1:{proxy.port} -mix_threshold 1 "
            f"-mix_timeout 0.3 -mix_backoff 0.01")
        for _ in range(12):
            clf.process(["1:1.0"], 1)
            clf.process(["2:1.0"], -1)
            clf._flush()
        roll = tracer.rollup()
        assert roll["mix.exchange"]["count"] == clf._mixer.exchanges
        assert clf._mixer.exchanges > 0
        assert proxy.faults_applied >= 1          # the faults really fired
        snap = registry.snapshot()
        assert snap["mix"]["active"] is True
        assert snap["mix"]["exchanges"] == clf._mixer.exchanges
        clf._mixer.close_group()
    finally:
        proxy.stop()
        srv.stop()


# --- obs CLI ---------------------------------------------------------------

def test_obs_cli_renders_stream(tmp_path, capsys):
    from hivemall_tpu.cli.main import main
    p = tmp_path / "s.jsonl"
    lines = [
        {"ts": 1.0, "event": "train_step", "trainer": "t", "step": 256,
         "examples": 4096, "examples_per_sec": 100.0, "avg_loss": 0.5},
        {"ts": 2.0, "event": "span_rollup", "trainer": "t", "step": 256,
         "stages": {"dispatch.step": {"count": 256, "total_s": 1.0,
                                      "p50": 0.004, "p99": 0.01}}},
        {"ts": 3.0, "event": "checkpoint", "trainer": "t", "step": 256,
         "path": "/tmp/x.npz"},
    ]
    p.write_text("\n".join(json.dumps(r) for r in lines)
                 + "\n{torn-line")
    assert main(["obs", str(p)]) == 0
    out = capsys.readouterr().out
    assert "train_step x1" in out
    assert "dispatch.step" in out
    assert "unparsable" in out           # the torn tail is counted, not fatal
    assert "ckpt:" in out


def test_obs_cli_missing_file(capsys):
    from hivemall_tpu.cli.main import main
    assert main(["obs", "/nonexistent/x.jsonl"]) == 1


# --- Histogram primitive + Prometheus histogram families --------------------

def test_histogram_cumulative_buckets_and_quantile():
    from hivemall_tpu.obs.histo import Histogram, quantile_from_buckets
    h = Histogram([0.001, 0.01, 0.1])
    for v in (0.0005, 0.001, 0.005, 0.05, 5.0):
        h.observe(v)
    s = h.snapshot()
    assert s["_type"] == "histogram"
    # le semantics: a value exactly on a bound counts into that bucket
    assert s["buckets"] == [[0.001, 2], [0.01, 3], [0.1, 4], ["+Inf", 5]]
    assert s["count"] == 5 and abs(s["sum"] - 5.0565) < 1e-9
    # interpolated quantile stays inside the winning bucket
    q = quantile_from_buckets(s["buckets"], 0.5)
    assert 0.001 <= q <= 0.01
    # +Inf winner clamps to the largest finite bound
    assert quantile_from_buckets(s["buckets"], 0.999) == 0.1
    assert quantile_from_buckets([], 0.99) == 0.0


def test_histogram_concurrent_observers_lose_nothing():
    from hivemall_tpu.obs.histo import Histogram
    h = Histogram([1.0, 10.0])
    n, threads = 2000, 4

    def work():
        for i in range(n):
            h.observe(0.5 if i % 2 else 5.0)

    ts = [threading.Thread(target=work) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    s = h.snapshot()
    assert s["count"] == n * threads
    assert s["buckets"][-1][1] == n * threads


def _parse_prometheus_strict(text):
    """Strict text-format 0.0.4 grammar: returns {family: (type, samples)}
    and asserts every line is a well-formed HELP/TYPE/sample line, HELP
    and TYPE precede their family's samples exactly once, histogram
    families carry monotonic _bucket series + _sum/_count."""
    name_re = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
    sample_re = re.compile(
        rf"^({name_re})(?:\{{le=\"([^\"]+)\"\}})? (-?[0-9.eE+-]+|NaN)$")
    help_re = re.compile(rf"^# HELP ({name_re}) (.+)$")
    type_re = re.compile(rf"^# TYPE ({name_re}) (gauge|histogram|counter)$")
    assert text.endswith("\n")
    families = {}
    cur = None
    for line in text.splitlines():
        m = help_re.match(line)
        if m:
            assert m.group(1) not in families, f"duplicate HELP {line}"
            families[m.group(1)] = {"type": None, "samples": []}
            cur = m.group(1)
            continue
        m = type_re.match(line)
        if m:
            assert m.group(1) == cur, f"TYPE without HELP: {line}"
            assert families[cur]["type"] is None, f"duplicate TYPE {line}"
            families[cur]["type"] = m.group(2)
            continue
        m = sample_re.match(line)
        assert m, f"unparsable exposition line: {line!r}"
        base = m.group(1)
        fam = base
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) and base[:-len(suffix)] in families \
                    and families[base[:-len(suffix)]]["type"] == "histogram":
                fam = base[:-len(suffix)]
        assert fam in families and families[fam]["type"], \
            f"sample before its TYPE: {line!r}"
        float(m.group(3))                # value must parse
        families[fam]["samples"].append((base, m.group(2), m.group(3)))
    for fam, rec in families.items():
        if rec["type"] != "histogram":
            continue
        buckets = [(le, float(v)) for n_, le, v in rec["samples"]
                   if n_ == fam + "_bucket"]
        counts = [v for _, v in buckets]
        assert counts == sorted(counts), f"{fam} buckets not monotonic"
        assert buckets[-1][0] == "+Inf"
        total = [float(v) for n_, _, v in rec["samples"]
                 if n_ == fam + "_count"]
        assert total and total[0] == counts[-1]
    return families


def test_to_prometheus_strict_grammar_with_histograms():
    """Satellite: the exposition parses under a strict grammar even with
    hostile snapshot keys (dots/dashes/leading digits) and histogram
    leaves; histogram series are monotonic with +Inf == _count."""
    from hivemall_tpu.obs.histo import Histogram
    h = Histogram([0.005, 0.05, 0.5])
    for v in (0.001, 0.01, 0.1, 1.0):
        h.observe(v)
    text = to_prometheus({
        "ts": 1754180000.123,
        "serve": {"request_latency_seconds": h.snapshot(),
                  "batch_hist": {"16": 3, "2": 1},
                  "qps": 12.5, "ready": True, "model_path": "/x.npz"},
        "9section": {"with.dots": 1, "and-dashes": 2},
    })
    fams = _parse_prometheus_strict(text)
    lat = "hivemall_tpu_serve_request_latency_seconds"
    assert fams[lat]["type"] == "histogram"
    assert ('%s_bucket' % lat, "+Inf", "4") in fams[lat]["samples"]
    # sanitization: dots/dashes -> underscores, leading digit guarded by
    # the name regex (the section rides behind the prefix)
    assert "hivemall_tpu_9section_with_dots" in fams
    assert "hivemall_tpu_9section_and_dashes" in fams
    assert fams["hivemall_tpu_serve_qps"]["type"] == "gauge"
    # a name that would START with a digit gets the underscore prefix
    from hivemall_tpu.obs.http import _metric_name
    assert _metric_name(["9lives", "x"]) == "_9lives_x"


# --- request-scoped tracing -------------------------------------------------

def test_tracer_context_tags_spans_into_chrome_args(tracer):
    with tracer.span("untagged"):
        pass
    with tracer.context("req-42"):
        with tracer.span("tagged"):
            pass
        # nesting restores the outer tag
        with tracer.context("inner"):
            with tracer.span("nested"):
                pass
        with tracer.span("tagged2"):
            pass
    tracer.add_span("explicit", 0.001, trace="req-42")
    evs = tracer.chrome_dict()["traceEvents"]
    by_name = {e["name"]: e for e in evs if e.get("ph") == "X"}
    assert "args" not in by_name["untagged"]
    assert by_name["tagged"]["args"]["trace"] == "req-42"
    assert by_name["nested"]["args"]["trace"] == "inner"
    assert by_name["tagged2"]["args"]["trace"] == "req-42"
    assert by_name["explicit"]["args"]["trace"] == "req-42"
    # wall-clock anchoring: ts is epoch microseconds, so independently
    # recorded processes merge onto one timeline
    now_us = time.time() * 1e6
    # deliberate wall anchor: trace ts IS epoch time (merged timelines)
    assert abs(by_name["tagged"]["ts"] - now_us) < 60e6  # graftcheck: disable=GC02
    # the export names its process (the merged fleet view's labels)
    metas = [e for e in evs if e.get("ph") == "M"]
    assert metas and metas[0]["args"]["name"] == tracer.process_label


def test_tracer_context_disabled_is_noop():
    t = Tracer(enabled=False)
    ctx = t.context("x")
    with ctx:
        with t.span("s"):
            pass
    assert t.chrome_dict()["traceEvents"][:-1] == []   # only metadata


def test_mint_trace_id_unique():
    from hivemall_tpu.obs.trace import mint_trace_id
    ids = {mint_trace_id() for _ in range(100)}
    assert len(ids) == 100


# --- obs --follow under metrics rotation ------------------------------------

def test_follow_tail_survives_rotation(tmp_path):
    """Satellite: `obs --follow` keeps tailing across a
    HIVEMALL_TPU_METRICS_MAX_MB rotation — the replaced <path> is
    reopened from its head and <path>.1 is never replayed. The rotation
    here is the exact MetricsStream._rotate sequence (os.replace to
    <path>.1, fresh file continues), driven by hand so every phase is
    deterministic."""
    from hivemall_tpu.obs.report import _FollowTail

    def emit(path, event, **fields):
        with open(path, "a") as f:
            f.write(json.dumps({"ts": 1.0, "event": event, **fields})
                    + "\n")

    p = str(tmp_path / "m.jsonl")
    tail = _FollowTail(p)
    emit(p, "pre_rotation", i=0)
    emit(p, "archived_only", i=1)
    assert tail.tick() is not None
    assert tail.state.counts == {"pre_rotation": 1, "archived_only": 1}
    # rotation: current file -> <path>.1, FRESH file continues — while
    # the follower is mid-tail
    os.replace(p, p + ".1")
    emit(p, "post_rotation", i=2)
    out = tail.tick()                    # inode change -> reopen from 0
    assert out is not None
    assert tail.state.counts.get("post_rotation") == 1
    # no replay: the archived generation's events were folded exactly
    # once (when they were still in <path>), never re-read from <path>.1
    assert tail.state.counts["pre_rotation"] == 1
    assert tail.state.counts["archived_only"] == 1
    # a tick landing IN the replace window (file briefly absent) retries
    os.replace(p, p + ".1")
    assert tail.tick() is None           # no file yet — no crash, no .1
    emit(p, "second_generation", i=3)
    tail.tick()
    assert tail.state.counts.get("second_generation") == 1
    assert tail.state.counts["post_rotation"] == 1   # still exactly once


def test_stream_rotation_under_live_follow(tmp_path, monkeypatch):
    """The integrated version: a real MetricsStream rotating under the
    size cap while a follower tails it — post-rotation events are seen,
    nothing read from <path> is double-counted."""
    from hivemall_tpu.obs.report import _FollowTail
    monkeypatch.setenv("HIVEMALL_TPU_METRICS_MAX_MB", "0.0005")  # 500 B
    p = str(tmp_path / "m.jsonl")
    s = M.MetricsStream(p)
    tail = _FollowTail(p)
    seen = 0
    for i in range(40):
        s.emit("ev", i=i, pad="x" * 64)
        if i % 5 == 0:
            tail.tick()
            seen = tail.state.counts.get("ev", 0)
            assert seen <= i + 1         # never double-counts a line
    assert s.rotations >= 1
    s.emit("final", i=99)
    s.close()
    tail.tick()
    assert tail.state.counts.get("final") == 1
    assert tail.state.counts.get("ev", 0) <= 40


def test_render_slo_report():
    from hivemall_tpu.obs.report import render_slo
    text = render_slo({
        "targets": {"p99_ms": 50.0, "availability": 0.999},
        "samples": 12,
        "windows": {"5m": {"seconds": 300.0, "qps": 10.0,
                           "availability": 0.995,
                           "availability_burn_rate": 5.0,
                           "p99_ms": 80.0, "frac_over_slo": 0.04,
                           "latency_burn_rate": 4.0}},
        "score": {"mean": 0.5, "std": 0.1},
        "drift": {"latency_events": 2, "score_events": 0,
                  "recent": [{"series": "latency_ms", "value": 80.0,
                              "change_score": 9.1, "ts": 1.0}]},
    }, source="http://x/slo")
    assert "burn 5x" in text and "80.0ms" in text
    assert "latency x2" in text and "change 9.1" in text


# --- stub-vs-live key contract (ISSUE 9 satellite: the drift recurred in
# PR 7 and PR 8 hardening — now every registered stub is pinned against
# its live provider's snapshot keys) -----------------------------------------


def test_stub_sections_match_live_providers(tmp_path):
    """Every registry-default stub section's key set must EXACTLY match
    its live provider's snapshot keys (in the provider's canonical fresh
    state), for all sections — a dashboard keyed on a gauge must never
    see it appear/vanish across subsystem lifecycle."""
    from hivemall_tpu.obs.registry import (CHECKPOINT_STUB, FLEET_STUB,
                                           MIX_STUB, SLO_STUB)

    # mix: MixClient.counters() + the active discriminator (ctor is lazy,
    # no connect)
    from hivemall_tpu.parallel.mix_service import MixClient
    client = MixClient("127.0.0.1:1", group="stubcheck")
    live = {"active": True, **client.counters()}
    assert set(MIX_STUB) == set(live), "mix stub drifted from live keys"

    # checkpoint: CheckpointManager.obs_section()
    from hivemall_tpu.io.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path / "ck"), "stubcheck", keep=1,
                            every=1)
    assert set(CHECKPOINT_STUB) == set(mgr.obs_section()), \
        "checkpoint stub drifted from live keys"

    # slo: SloEngine.obs_section() in its fresh (no samples) state
    from hivemall_tpu.obs.slo import SloEngine
    eng = SloEngine()
    assert set(SLO_STUB) == set(eng.obs_section()), \
        "slo stub drifted from live keys"

    # fleet: ReplicaManager.obs_section() (construction does not spawn)
    from hivemall_tpu.serve.fleet import ReplicaManager
    fm = ReplicaManager("train_classifier",
                        checkpoint_dir=str(tmp_path / "fleet"), replicas=1)
    assert set(FLEET_STUB) == set(fm.obs_section()), \
        "fleet stub drifted from live keys"

    # ingest_cache: the shard-cache counters override the registry stub
    # at import — compare against the stub registered BEFORE that import
    # by rebuilding its dict from the live as_dict
    from hivemall_tpu.io.shard_cache import counters as cache_counters
    stub_keys = {"configured", "hits", "misses", "invalid", "rebuilds",
                 "build_failed", "bytes_mmapped", "bytes_written",
                 "canonicalizer"}
    assert stub_keys == set(cache_counters.as_dict()), \
        "ingest_cache stub drifted from live keys"

    # promotion: PromotionController.obs_section() (gate never run) and
    # the fleet manager's promotion_section() must both mirror the stub
    from hivemall_tpu.serve.promote import (PromotionController,
                                            PromotionGate, promotion_stub)
    gate = PromotionGate("train_classifier", "-dims 64")
    ctrl = PromotionController(str(tmp_path / "promo"), gate)
    assert set(promotion_stub()) == set(ctrl.obs_section()), \
        "promotion stub drifted from controller live keys"
    pm = ReplicaManager("train_classifier",
                        checkpoint_dir=str(tmp_path / "promo"),
                        replicas=1, promote=True)
    assert set(promotion_stub()) == set(pm.promotion_section()), \
        "promotion stub drifted from fleet manager live keys"
    assert set(promotion_stub()["canary"]) \
        == set(pm.promotion_section()["canary"])

    # retrain: RetrainController.obs_section() (never triggered) must
    # mirror RETRAIN_STUB key-for-key, nested replay dict included
    from hivemall_tpu.serve.retrain import (RetrainController,
                                            retrain_stub)
    rc = RetrainController("train_classifier", "-dims 64",
                           checkpoint_dir=str(tmp_path / "retrain"))
    assert set(retrain_stub()) == set(rc.obs_section()), \
        "retrain stub drifted from live keys"
    assert set(retrain_stub()["replay"]) \
        == set(rc.obs_section()["replay"])

    # retrieval: RetrievalEngine.obs_section() over a real factor
    # bundle (the engine has no lazy-construct path — it loads at init),
    # nested index/arena dicts included
    import numpy as np
    from hivemall_tpu.models.mf import MFTrainer
    from hivemall_tpu.serve.retrieve import RetrievalEngine, retrieval_stub
    opts = "-factors 4 -users 8 -items 16 -mini_batch 64 -iters 1"
    t = MFTrainer(opts)
    rng = np.random.default_rng(3)
    t.fit(rng.integers(0, 8, 256), rng.integers(0, 16, 256),
          rng.normal(3, 1, 256).astype(np.float32), epochs=1)
    bdir = tmp_path / "retrieval"
    bdir.mkdir()
    bp = str(bdir / "train_mf_sgd-step000004.npz")
    t.save_bundle(bp)
    reng = RetrievalEngine("train_mf_sgd", opts, bundle=bp,
                           checkpoint_dir=None, rescore="numpy")
    try:
        live = reng.obs_section()
        assert set(retrieval_stub()) == set(live), \
            "retrieval stub drifted from live keys"
        assert set(retrieval_stub()["index"]) == set(live["index"])
        assert set(retrieval_stub()["arena"]) == set(live["arena"])
    finally:
        reng.close()

    # bulk: BulkProgress.obs_section() (no job run) must mirror
    # BULK_STUB key-for-key — the offline-scoring plane's section
    from hivemall_tpu.io.bulk import BulkProgress
    from hivemall_tpu.obs.registry import BULK_STUB
    assert set(BULK_STUB) == set(BulkProgress().obs_section()), \
        "bulk stub drifted from live keys"

    # devprof: the stub constructor IS the contract
    from hivemall_tpu.obs.devprof import devprof_stub, get_devprof
    live_dp = get_devprof().obs_section()
    assert set(devprof_stub()) == set(live_dp), \
        "devprof stub drifted from live keys"
    assert set(devprof_stub()["memory"]) == set(live_dp["memory"])
    assert set(devprof_stub()["drift"]) == set(live_dp["drift"])

    # flight: FlightRecorder.obs_section() — dark AND recording forms
    # must both mirror the stub (the checkpoint-dir ReplicaManagers
    # above flipped the process recorder on; leave it dark again)
    from hivemall_tpu.obs.flight import (FlightRecorder, configure_flight,
                                         flight_stub)
    assert flight_stub() == FlightRecorder().obs_section(), \
        "flight stub drifted from live keys"
    lfr = FlightRecorder().open(str(tmp_path / "parity.ring"))
    lfr.record("x")
    assert set(flight_stub()) == set(lfr.obs_section()), \
        "flight stub drifted from recording-state live keys"
    lfr.close()
    configure_flight(None)

    # trainer-inactive forms reuse the SAME stub dicts (pinned here so a
    # future inline dict can't drift silently)
    tr = GeneralClassifier("-dims 64 -mini_batch 8")
    snap = registry.snapshot()
    assert snap["mix"] == MIX_STUB
    assert snap["checkpoint"] == CHECKPOINT_STUB
    assert tr is not None


# --- span-ring overflow accounting (ISSUE 9 satellite) ----------------------


def test_span_ring_overflow_counts_dropped():
    t = Tracer(enabled=True, ring=4)
    for i in range(10):
        with t.span(f"s{i % 2}"):
            pass
    assert t.dropped == 6                  # 10 recorded into a 4-ring
    assert len(t.chrome_dict()["traceEvents"]) == 4 + 1   # + metadata
    t.reset()
    assert t.dropped == 0


def test_spans_dropped_surfaces_in_registry_and_metrics(tracer):
    with tracer.span("x"):
        pass
    snap = registry.snapshot()
    assert isinstance(snap["spans"]["dropped"], int)
    text = to_prometheus(snap)
    assert "hivemall_tpu_spans_dropped" in text
    # the obs report renders a snapshot whose spans section carries the
    # scalar beside the stage dicts without tripping over it
    from hivemall_tpu.obs.report import summarize
    out = summarize([{"event": "train_done", "ts": 1.0,
                      "telemetry": snap}])
    assert "stages" in out


# --- histo.quantile_from_buckets edge cases (ISSUE 9 satellite) -------------


def test_quantile_from_buckets_edge_cases():
    from hivemall_tpu.obs.histo import quantile_from_buckets as q

    # empty histogram
    assert q([], 0.99) == 0.0
    # zero-total histogram
    assert q([[0.1, 0], [0.5, 0], ["+Inf", 0]], 0.5) == 0.0
    # all mass in +Inf: clamps to the largest finite bound
    assert q([[0.1, 0], [0.5, 0], ["+Inf", 10]], 0.99) == 0.5
    # single (+Inf-only) bucket: nothing finite to clamp to
    assert q([["+Inf", 5]], 0.5) == 0.0
    # single finite bucket: interpolates inside [0, bound]
    v = q([[0.25, 4], ["+Inf", 4]], 0.5)
    assert 0.0 < v <= 0.25
    # zero-width interpolation: the winning bucket is empty (cum ==
    # prev_cum) — returns the bound instead of dividing by zero
    assert q([[0.1, 0], [0.2, 5], ["+Inf", 5]], 0.0) == 0.1
    # monotonicity across the bucket edge
    assert q([[0.1, 5], [0.2, 10], ["+Inf", 10]], 0.25) <= \
        q([[0.1, 5], [0.2, 10], ["+Inf", 10]], 0.75)
