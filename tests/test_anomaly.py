import numpy as np

from hivemall_tpu.models.anomaly import (ChangeFinder, ChangeFinder2D,
                                         SDAR2D, changefinder, sst)


def shifted_series(n1=150, n2=150, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.normal(0.0, 0.2, n1)
    b = rng.normal(4.0, 0.2, n2)     # mean shift at t = n1
    return np.concatenate([a, b])


def test_changefinder_flags_shift():
    x = shifted_series()
    scores = changefinder(x, "-r 0.05 -k 2 -T1 5 -T2 5")
    cp = np.asarray([s[1] for s in scores])
    warm = cp[30:]                       # skip burn-in
    peak = int(np.argmax(warm)) + 30
    assert 145 <= peak <= 175, peak      # change score peaks near the shift
    # scores away from the shift are much lower
    assert cp[100] < cp[peak] * 0.5


def test_changefinder_outlier_spike():
    rng = np.random.default_rng(1)
    x = rng.normal(0, 0.1, 200)
    x[120] = 5.0
    scores = changefinder(x, "-r 0.02 -k 2")
    out = np.asarray([s[0] for s in scores])
    assert np.argmax(out[20:]) + 20 == 120


def test_streaming_matches_batch():
    """The batched scan path must agree with the sequential oracle — the
    scan runs f32 with the warmup embedded as identity blocks, so
    tolerance is float-level, not bitwise."""
    x = shifted_series(40, 40)
    cf = ChangeFinder(0.05, 2, 5, 5)
    stream = np.asarray([cf.update(v) for v in x])
    batch = np.asarray(changefinder(x, "-r 0.05 -k 2 -T1 5 -T2 5"))
    np.testing.assert_allclose(stream, batch, rtol=2e-3, atol=2e-3)


def test_scan_matches_oracle_long_series():
    """Longer series + default k=3: EMA contraction keeps the f32 scan
    within tolerance of the f64 sequential oracle end to end."""
    rng = np.random.default_rng(7)
    x = np.concatenate([rng.normal(0, 1, 400), rng.normal(3, 1.5, 400)])
    cf = ChangeFinder(0.02, 3, 7, 7)
    stream = np.asarray([cf.update(v) for v in x])
    batch = np.asarray(changefinder(x))
    np.testing.assert_allclose(stream, batch, rtol=5e-3, atol=5e-3)


def test_changefinder_vector_stream_2d():
    """array<double> rows (reference ChangeFinder2D): a correlated-mean
    shift in a 2D stream is flagged near the boundary."""
    rng = np.random.default_rng(3)
    a = rng.normal(0.0, 0.3, (150, 2))
    b = rng.normal([3.0, -2.0], 0.3, (150, 2))
    x = np.concatenate([a, b])
    scores = changefinder(x, "-r 0.05 -k 2 -T1 5 -T2 5")
    assert len(scores) == 300
    cp = np.asarray([s[1] for s in scores])
    peak = int(np.argmax(cp[30:])) + 30
    assert 145 <= peak <= 175, peak
    assert cp[100] < cp[peak] * 0.5


def test_streaming_2d_matches_batch():
    rng = np.random.default_rng(4)
    x = np.concatenate([rng.normal(0, 0.5, (60, 3)),
                        rng.normal(2, 0.5, (60, 3))])
    cf = ChangeFinder2D(3, 0.05, 2, 5, 5)
    stream = np.asarray([cf.update(v) for v in x])
    batch = np.asarray(changefinder(x, "-r 0.05 -k 2 -T1 5 -T2 5"))
    np.testing.assert_allclose(stream, batch, rtol=5e-3, atol=5e-3)


def test_sdar2d_d1_matches_sdar1d():
    """SDAR2D with d=1 must reduce to the scalar recurrence."""
    from hivemall_tpu.models.anomaly import SDAR1D

    rng = np.random.default_rng(5)
    x = rng.normal(0, 1, 100)
    s1 = SDAR1D(0.03, 3)
    s2 = SDAR2D(0.03, 3, 1)
    a = [s1.update(v) for v in x]
    b = [s2.update(np.asarray([v])) for v in x]
    np.testing.assert_allclose(a, b, rtol=1e-8, atol=1e-10)


def test_changefinder_empty_and_tiny():
    assert changefinder([]) == []
    out = changefinder([1.0])
    assert len(out) == 1 and np.isfinite(out[0]).all()


def test_sst_flags_frequency_change():
    # classic SST scenario: the oscillation frequency changes at t=120
    # (a mean-only shift inside zero-mean noise has no stable principal
    # subspace, so frequency change is the discriminative regime here)
    t = np.arange(240)
    rng = np.random.default_rng(2)
    x = np.where(t < 120, np.sin(0.2 * np.pi * t),
                 np.sin(0.7 * np.pi * t)) + 0.02 * rng.normal(size=240)
    scores = np.asarray(sst(x, "-w 16 -r 2"))
    assert scores.shape[0] == 240
    peak = int(np.argmax(scores))
    assert 105 <= peak <= 140, peak
    assert scores[60] < 0.1 and scores[200] < 0.1


def test_sst_short_series_zero():
    assert sst([1.0, 2.0, 3.0], "-w 16") == [0.0, 0.0, 0.0]


def test_changefinder_constant_and_single_point_series():
    """Degenerate streams: a constant series (zero variance — the sigma
    floor must keep NLLs finite) and near-empty series."""
    out = changefinder(np.ones(400), "-r 0.05 -k 2")
    assert np.isfinite(out).all()
    out2 = changefinder(np.ones((50, 3)) * 2.5, "-r 0.05 -k 2")
    assert np.isfinite(out2).all()


def test_solve_small_matches_linalg_solve():
    """Closed-form n<=3 batched solves (round 5: 7.2x the batched LU on
    v5e for the default 1D changefinder) agree with jnp.linalg.solve;
    n > 3 falls through to it. Inputs are PD (B B^T + I) per the
    helper's documented contract — ridged covariance systems."""
    import jax.numpy as jnp
    import numpy as np

    from hivemall_tpu.models.anomaly import _solve_small

    rng = np.random.default_rng(7)
    for n in (1, 2, 3, 4):
        B = rng.standard_normal((64, n, n))
        G = jnp.asarray(B @ B.transpose(0, 2, 1) + np.eye(n), jnp.float32)
        R = jnp.asarray(rng.standard_normal((64, n, 2)), jnp.float32)
        got = np.asarray(_solve_small(G, R))
        want = np.asarray(jnp.linalg.solve(G, R))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_solve_small_large_magnitude_no_overflow():
    """Jacobi equilibration inside _solve_small keeps the closed-form
    LDL solve finite and accurate at covariance magnitudes (~1e13) a
    |x| ~ 5e6 series produces — the original unscaled explicit 3x3
    determinant overflowed f32 there (round-5 review finding), and
    changefinder itself must stay finite end to end."""
    import jax.numpy as jnp
    import numpy as np

    from hivemall_tpu.models.anomaly import _solve_small, changefinder

    rng = np.random.default_rng(11)
    B = rng.standard_normal((32, 3, 3))
    G = jnp.asarray((B @ B.transpose(0, 2, 1) + 4 * np.eye(3))
                    * 2.5e13, jnp.float32)   # symmetric, like every caller
    R = jnp.asarray(rng.standard_normal((32, 3, 1)) * 2.5e13, jnp.float32)
    got = np.asarray(_solve_small(G, R))
    assert np.isfinite(got).all()
    want = np.asarray(jnp.linalg.solve(G, R))
    np.testing.assert_allclose(got, want, rtol=5e-3, atol=1e-4)

    x = rng.standard_normal(512) * 5e6 + 5e6
    scores = np.asarray(changefinder(x))
    assert np.isfinite(scores).all()
    x2 = rng.standard_normal((256, 2)) * 5e6
    scores2 = np.asarray(changefinder(x2, "-r 0.05 -k 2"))
    assert np.isfinite(scores2).all()


def test_solve_small_1x1_degenerate_and_logdet():
    """The n==1 branch follows the n>=2 contract: the (single) pivot is
    equilibrated and floored at 1e-7, so a zero/denormal 1x1 system
    returns a FINITE solve and logdet instead of inf (the raw divide
    returned inf and log(0) = -inf); with_logdet asserts pd."""
    import jax.numpy as jnp
    import numpy as np
    import pytest

    from hivemall_tpu.models.anomaly import _solve_small

    G = jnp.asarray([[[4.0]], [[0.0]], [[2.5e13]]], jnp.float32)
    R = jnp.asarray([[[2.0]], [[3.0]], [[5e13]]], jnp.float32)
    x, ld = _solve_small(G, R, pd=True, with_logdet=True)
    x, ld = np.asarray(x), np.asarray(ld)
    assert np.isfinite(x).all() and np.isfinite(ld).all()
    np.testing.assert_allclose(x[0, 0, 0], 0.5, rtol=1e-6)
    np.testing.assert_allclose(ld[0], np.log(4.0), rtol=1e-6)
    np.testing.assert_allclose(x[2, 0, 0], 2.0, rtol=1e-6)
    np.testing.assert_allclose(ld[2], np.log(2.5e13), rtol=1e-6)
    # indefinite (pd=False): sign-preserving floor, still finite
    Gm = jnp.asarray([[[-4.0]], [[0.0]]], jnp.float32)
    Rm = jnp.asarray([[[2.0]], [[1.0]]], jnp.float32)
    xm = np.asarray(_solve_small(Gm, Rm))
    assert np.isfinite(xm).all()
    np.testing.assert_allclose(xm[0, 0, 0], -0.5, rtol=1e-6)
    with pytest.raises(AssertionError):
        _solve_small(Gm, Rm, pd=False, with_logdet=True)


def test_streaming_large_magnitude_matches_batch():
    """|x| ~ 5e6 series (covariances ~1e13): the streaming SDAR oracles
    now apply the SAME relative per-diagonal ridge as the batch path, so
    the >256MB changefinder() fallback route can't hit the near-singular
    warmup solve the batch path was fixed for — and both paths agree."""
    import numpy as np

    from hivemall_tpu.models.anomaly import (ChangeFinder, ChangeFinder2D,
                                             changefinder)

    rng = np.random.default_rng(17)
    x = np.concatenate([rng.normal(5e6, 2e5, 120),
                        rng.normal(-3e6, 2e5, 120)])
    cf = ChangeFinder(0.05, 2, 5, 5)
    stream = np.asarray([cf.update(v) for v in x])
    assert np.isfinite(stream).all()
    batch = np.asarray(changefinder(x, "-r 0.05 -k 2 -T1 5 -T2 5"))
    np.testing.assert_allclose(stream, batch, rtol=5e-3, atol=5e-3)

    x2 = np.stack([rng.normal(5e6, 3e5, 150),
                   rng.normal(-4e6, 3e5, 150)], axis=1)
    x2[75:] *= 0.4                      # change point at t=75
    cf2 = ChangeFinder2D(2, 0.05, 2, 5, 5)
    stream2 = np.asarray([cf2.update(v) for v in x2])
    assert np.isfinite(stream2).all()
    batch2 = np.asarray(changefinder(x2, "-r 0.05 -k 2 -T1 5 -T2 5"))
    # skip the first handful of warmup rows: at covariance scale ~1e13
    # the f32 scan's first rank-1 systems round differently from the f64
    # oracle; past warmup the EMA contraction holds both to ~2%
    np.testing.assert_allclose(stream2[10:], batch2[10:],
                               rtol=2.5e-2, atol=2.5e-2)


def test_solve_small_heterogeneous_diagonal():
    """Jacobi equilibration (not global max-scaling) keeps _solve_small
    exact when diagonal entries span many decades — diag(2e10, 2e4, 2e4)
    is perfectly conditioned per-row, and the round-5 review showed a
    single global scale returned answers 1e5x off."""
    import jax.numpy as jnp
    import numpy as np

    from hivemall_tpu.models.anomaly import _solve_small

    G = jnp.asarray(np.diag([2e10, 2e4, 2e4]), jnp.float32)[None]
    R = jnp.asarray(np.array([1.5e10, 3e4, -1e4])[:, None],
                    jnp.float32)[None]
    got = np.asarray(_solve_small(G, R))[0, :, 0]
    np.testing.assert_allclose(got, [0.75, 1.5, -0.5], rtol=1e-5)


def test_changefinder_heterogeneous_channel_scales():
    """A 2-channel stream with scales 1e6 and 1e-3: an outlier injected
    into the SMALL channel must still spike the outlier score, and the
    batch path must track the streaming oracle (the global-max relative
    ridge regressed exactly this: the small channel's variance drowned
    and the spike vanished)."""
    import numpy as np

    from hivemall_tpu.models.anomaly import ChangeFinder2D, changefinder

    rng = np.random.default_rng(5)
    x = np.stack([rng.normal(0, 1e6, 400),
                  rng.normal(0, 1e-3, 400)], axis=1)
    x[200, 1] += 0.5                     # ~500 sigma in the small channel
    scores = np.asarray(changefinder(x, "-r 0.02 -k 2"))
    assert np.isfinite(scores).all()
    out = scores[:, 0]
    assert int(np.argmax(out[30:])) + 30 == 200, int(np.argmax(out[30:])) + 30

    cf = ChangeFinder2D(2, 0.02, 2, 7, 7)
    stream = np.asarray([cf.update(v) for v in x])
    np.testing.assert_allclose(stream[:, 0], out, rtol=5e-3, atol=5e-3)


def test_solve_small_indefinite_yw_system():
    """The discounted-moment Toeplitz is INDEFINITE in general (its
    lags are cross-moments). This is the measured stage-2 t=4 system
    whose correlation det is negative: the sign-preserving pivot floor
    must reproduce the LU solution (a positive clamp returned
    coefficients ~1e5 off and broke the anomaly example's change
    detection)."""
    import jax.numpy as jnp
    import numpy as np

    from hivemall_tpu.models.anomaly import _solve_small

    T = np.array([[5.10714, 4.55693, 2.98017],
                  [4.55693, 5.10714, 4.55693],
                  [2.98017, 4.55693, 5.10714]]) + 1e-6 * np.eye(3)
    R = np.array([4.55693, 2.98017, 0.0])[:, None]
    got = np.asarray(_solve_small(jnp.asarray(T, jnp.float32)[None],
                                  jnp.asarray(R, jnp.float32)[None]))[0]
    want = np.linalg.solve(T, R)
    np.testing.assert_allclose(got, want, rtol=2e-3)


def test_sst_ika_matches_svd_detection():
    """-scorefunc ika (power/subspace iteration, SURVEY.md:265) agrees
    with the exact SVD score: same change-point peak, bounded score
    difference, and ~100x cheaper on TPU (batched matmuls only)."""
    import numpy as np

    from hivemall_tpu.models.anomaly import sst

    x = np.concatenate([np.sin(np.arange(600) * 0.1),
                        np.sin(np.arange(600) * 0.33)])
    si = np.asarray(sst(x, "-w 24 -r 3 -scorefunc ika"))
    sv = np.asarray(sst(x, "-w 24 -r 3 -scorefunc svd"))
    # both score functions build the SAME future window (first future
    # column ends at t+g — the base_f off-by-one is fixed), so the only
    # residual disagreement is power-iteration convergence on the flat
    # pre-change spectrum: the argmax may wobble one offset, never five
    assert abs(int(np.argmax(si)) - int(np.argmax(sv))) <= 1
    assert np.abs(si - sv).max() < 0.12
    assert np.isfinite(si).all() and (si >= 0).all() and (si <= 1).all()


def test_sst_scorefunc_validation_and_short_series():
    import numpy as np
    import pytest

    from hivemall_tpu.models.anomaly import sst

    with pytest.raises(ValueError, match="scorefunc"):
        sst(np.zeros(100), "-scorefunc qr")
    assert sst([1.0, 2.0], "-scorefunc ika") == [0.0, 0.0]
