import numpy as np

from hivemall_tpu.models.anomaly import ChangeFinder, changefinder, sst


def shifted_series(n1=150, n2=150, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.normal(0.0, 0.2, n1)
    b = rng.normal(4.0, 0.2, n2)     # mean shift at t = n1
    return np.concatenate([a, b])


def test_changefinder_flags_shift():
    x = shifted_series()
    scores = changefinder(x, "-r 0.05 -k 2 -T1 5 -T2 5")
    cp = np.asarray([s[1] for s in scores])
    warm = cp[30:]                       # skip burn-in
    peak = int(np.argmax(warm)) + 30
    assert 145 <= peak <= 175, peak      # change score peaks near the shift
    # scores away from the shift are much lower
    assert cp[100] < cp[peak] * 0.5


def test_changefinder_outlier_spike():
    rng = np.random.default_rng(1)
    x = rng.normal(0, 0.1, 200)
    x[120] = 5.0
    scores = changefinder(x, "-r 0.02 -k 2")
    out = np.asarray([s[0] for s in scores])
    assert np.argmax(out[20:]) + 20 == 120


def test_streaming_matches_batch():
    x = shifted_series(40, 40)
    cf = ChangeFinder(0.05, 2, 5, 5)
    stream = [cf.update(v) for v in x]
    batch = changefinder(x, "-r 0.05 -k 2 -T1 5 -T2 5")
    np.testing.assert_allclose(stream, batch, rtol=1e-9)


def test_sst_flags_frequency_change():
    # classic SST scenario: the oscillation frequency changes at t=120
    # (a mean-only shift inside zero-mean noise has no stable principal
    # subspace, so frequency change is the discriminative regime here)
    t = np.arange(240)
    rng = np.random.default_rng(2)
    x = np.where(t < 120, np.sin(0.2 * np.pi * t),
                 np.sin(0.7 * np.pi * t)) + 0.02 * rng.normal(size=240)
    scores = np.asarray(sst(x, "-w 16 -r 2"))
    assert scores.shape[0] == 240
    peak = int(np.argmax(scores))
    assert 105 <= peak <= 140, peak
    assert scores[60] < 0.1 and scores[200] < 0.1


def test_sst_short_series_zero():
    assert sst([1.0, 2.0, 3.0], "-w 16") == [0.0, 0.0, 0.0]
