"""Examples run in CI on the committed fragments (VERDICT r1 weak #7:
'examples are unverifiable in CI'). Each runs as a real subprocess —
the user-facing invocation — against tests/resources fixtures."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RES = os.path.join(REPO, "tests", "resources")


def _run(args):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no TPU tunnel from subprocess
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable] + args, capture_output=True,
                         text=True, env=env, timeout=600, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    payload = [l for l in out.stdout.splitlines() if l.startswith("{")]
    assert payload, out.stdout
    return json.loads(payload[-1])


def test_a9a_example_on_fragment():
    rec = _run(["examples/a9a_logreg.py",
                "--data", os.path.join(RES, "a9a.frag.train.libsvm"),
                "--test", os.path.join(RES, "a9a.frag.test.libsvm")])
    assert rec["logloss_at_1_epoch"] < 0.5
    assert rec["auc"] > 0.90


def test_movielens_example_on_fragment():
    rec = _run(["examples/movielens_mf.py",
                "--data", os.path.join(RES, "movielens.frag.tsv")])
    assert rec["mf_rmse"] < 0.85


def test_criteo_ffm_example_on_fragment():
    rec = _run(["examples/criteo_ffm.py",
                "--data", os.path.join(RES, "criteo_ffm.frag.tsv")])
    assert rec["train_auc"] > 0.72
    assert rec["cumulative_logloss"] < 0.75


def test_anomaly_stream_example():
    rec = _run(["examples/anomaly_stream.py", "--points", "600"])
    n, half = rec["points"], rec["points"] // 2
    assert abs(rec["scalar_outlier_at"] - rec["scalar_outlier_true"]) <= 2
    assert abs(rec["scalar_change_at"] - half) <= 40
    assert abs(rec["vector_change_at"] - half) <= 40


def test_higgs_trees_example():
    rec = _run(["examples/higgs_trees.py", "--rows", "2048"])
    assert rec["rf_train_accuracy"] > 0.8
    assert rec["gbdt_train_accuracy"] > 0.8
    assert rec["rf_rows_per_sec"] > 0


def test_text8_word2vec_example():
    rec = _run(["examples/text8_word2vec.py", "--docs", "120"])
    assert rec["vocab"] > 0
    # tiny synthetic corpora need not separate topics; the contract here
    # is the pipeline runs and reports finite similarity metrics
    assert -1.0 <= rec["within_topic_cos"] <= 1.0
    assert -1.0 <= rec["across_topic_cos"] <= 1.0


def test_nlp_topics_example():
    rec = _run(["examples/nlp_topics.py", "--docs", "80"])
    assert rec["cn_dictionary"] in ("loaded", "absent")
    assert rec["topic_purity"] >= 0.9
