"""Parallel host ingest pipeline: order, bit-exactness, errors, metrics."""

import threading
import time

import numpy as np
import pytest

from conftest import assert_batches_equal as _assert_batches_equal
from hivemall_tpu.io.libsvm import synthetic_classification
from hivemall_tpu.io.pipeline import (IngestPipeline, PipelineStats,
                                      auto_workers)


def test_nworker_prep_matches_sequential_in_order():
    """N-worker prep produces byte-identical batches in identical order vs
    the sequential path — seeded shuffle included."""
    ds, _ = synthetic_classification(400, 12, seed=11)

    def prep(b):
        # a non-trivial deterministic transform (scales + re-types)
        return type(b)(b.idx * np.int32(3), b.val * 2.0, b.label,
                       b.field, n_valid=b.n_valid, fieldmajor=b.fieldmajor)

    seq = list(map(prep, ds.batches(32, shuffle=True, seed=5)))
    par = list(IngestPipeline(ds.batches(32, shuffle=True, seed=5), prep,
                              workers=4))
    assert len(par) == len(seq)
    for a, b in zip(seq, par):
        _assert_batches_equal(a, b)


def test_worker_exception_propagates_within_one_batch():
    ds, _ = synthetic_classification(640, 8, seed=12)

    def src():
        for i, b in enumerate(ds.batches(32, shuffle=False)):
            yield (i, b)

    def prep(t):
        i, b = t
        if i == 5:           # deterministic per batch, not per worker order
            raise RuntimeError("prep blew up")
        return b

    got = 0
    it = IngestPipeline(src(), prep, workers=4)
    with pytest.raises(RuntimeError, match="prep blew up"):
        for _ in it:
            got += 1
    assert got == 5          # delivered everything before the failed batch


def test_source_error_propagates():
    def bad_src():
        ds, _ = synthetic_classification(64, 8, seed=13)
        yield from ds.batches(16, shuffle=False)
        raise RuntimeError("source io died")

    with pytest.raises(RuntimeError, match="source io died"):
        list(IngestPipeline(bad_src(), lambda b: b, workers=3))


def test_sequential_fallback_uses_no_threads():
    ds, _ = synthetic_classification(100, 8, seed=14)
    # compare thread SETS, not counts: leftover daemon threads from earlier
    # tests may die mid-test and an exact active_count() equality flakes
    before = set(threading.enumerate())
    out = list(IngestPipeline(ds.batches(16, shuffle=False), lambda b: b,
                              workers=1))
    assert len(out) == 7
    assert not (set(threading.enumerate()) - before)


def test_close_releases_workers_after_abandon():
    ds, _ = synthetic_classification(640, 8, seed=15)
    it = IngestPipeline(ds.batches(16, shuffle=False), lambda b: b,
                        workers=3)
    next(it)
    it.close()
    assert not it._submitter.is_alive()
    with pytest.raises(StopIteration):
        next(it)


def test_stats_populated():
    ds, _ = synthetic_classification(320, 8, seed=16)
    stats = PipelineStats()
    out = list(IngestPipeline(ds.batches(32, shuffle=False), lambda b: b,
                              workers=2, stats=stats))
    assert stats.batches_prepared == len(out) == 10
    assert stats.workers == 2 and stats.pool == "thread"
    d = stats.as_dict()
    for k in ("prep_seconds", "prep_wait_seconds",
              "prep_backpressure_seconds", "avg_queue_occupancy",
              "queue_peak"):
        assert k in d


def test_process_pool_with_picklable_fn():
    ds, _ = synthetic_classification(96, 8, seed=17)
    src = list(ds.batches(16, shuffle=False))
    seq = [_double_idx(b) for b in src]
    par = list(IngestPipeline(iter(src), _double_idx, workers=2,
                              pool="process"))
    for a, b in zip(seq, par):
        _assert_batches_equal(a, b)


def _double_idx(b):
    return type(b)(b.idx * np.int32(2), b.val, b.label, b.field,
                   n_valid=b.n_valid, fieldmajor=b.fieldmajor)


def test_ffm_process_pool_prep_bit_exact():
    """-ingest_pool process on the flagship prep (canonicalize + pack via
    the picklable FFMPrep config, NOT a bound trainer method): bit-exact
    vs the thread pool and the sequential path, in order."""
    import json
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.models.fm import FFMTrainer

    rng = np.random.default_rng(23)
    n, L, F = 256, 8, 8
    idx = rng.integers(1, 2048, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32) % F, (n, 1))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L, dtype=np.int64)
    ds = SparseDataset(idx.ravel(), indptr, np.ones(n * L, np.float32),
                       lab, fld.ravel())
    cfg = ("-dims 2048 -factors 2 -fields 8 -mini_batch 64 "
           "-classification -iters 2")
    seq = FFMTrainer(cfg + " -ingest_workers 1").fit(ds)
    thr = FFMTrainer(cfg + " -ingest_workers 3 -ingest_pool thread").fit(ds)
    prc = FFMTrainer(cfg + " -ingest_workers 2 -ingest_pool process").fit(ds)
    assert prc.pipeline_stats.pool == "process"
    s = json.dumps(seq.model_table(), sort_keys=True, default=str)
    assert s == json.dumps(thr.model_table(), sort_keys=True, default=str)
    assert s == json.dumps(prc.model_table(), sort_keys=True, default=str)


def test_process_pool_without_picklable_prep_falls_back_to_threads():
    """A trainer whose parallel prep leg is bound-only must warn and run
    the thread pool instead of crashing in the child."""
    from hivemall_tpu.models.linear import GeneralClassifier

    class BoundPrep(GeneralClassifier):
        def _preprocess_train_parallel(self, batch):
            return batch

    ds, _ = synthetic_classification(128, 8, seed=24)
    t = BoundPrep("-dims 256 -mini_batch 32 -ingest_workers 2 "
                  "-ingest_pool process")
    with pytest.warns(RuntimeWarning, match="picklable"):
        t.fit(ds)
    assert t.pipeline_stats.pool == "thread"
    assert t.pipeline_stats.batches_prepared > 0


def test_base_trainer_process_pool_matches_sequential():
    from hivemall_tpu.models.linear import GeneralClassifier

    ds, _ = synthetic_classification(300, 20, seed=25)
    opts = "-dims 512 -loss logloss -opt adagrad -mini_batch 32"
    seq = GeneralClassifier(opts + " -ingest_workers 1").fit(ds)
    prc = GeneralClassifier(opts + " -ingest_workers 2 "
                                   "-ingest_pool process").fit(ds)
    np.testing.assert_array_equal(np.asarray(seq.w), np.asarray(prc.w))


def test_backpressure_bounds_inflight():
    """A slow consumer must not let the pipeline race ahead unbounded."""
    produced = []

    def src():
        for i in range(50):
            produced.append(i)
            yield i

    it = IngestPipeline(src(), lambda x: x, workers=2, depth=2)
    next(it)
    time.sleep(0.2)          # give the submitter time to run ahead
    # depth(2) queued + 2 executing + 1 pending put + 1 consumed, plus a
    # small scheduling margin — far below the 50-item source
    assert len(produced) <= 8
    it.close()


def test_auto_workers_positive():
    assert auto_workers() >= 1


def test_close_idempotent_all_modes():
    """close() is safe to call repeatedly, before or after consumption,
    in both threaded and sequential modes (the trainer's finally block and
    __del__ can both fire)."""
    ds, _ = synthetic_classification(160, 8, seed=30)
    it = IngestPipeline(ds.batches(16, shuffle=False), lambda b: b,
                        workers=3)
    next(it)
    it.close()
    it.close()                                # second close: no-op
    assert not it._submitter.is_alive()
    with pytest.raises(StopIteration):
        next(it)
    # close before any consumption
    it2 = IngestPipeline(ds.batches(16, shuffle=False), lambda b: b,
                         workers=3)
    it2.close()
    it2.close()
    assert not it2._submitter.is_alive()
    # sequential fallback has no threads to release but must stay safe
    it3 = IngestPipeline(ds.batches(16, shuffle=False), lambda b: b,
                         workers=1)
    next(it3)
    it3.close()
    it3.close()
    with pytest.raises(StopIteration):
        next(it3)


def test_drain_until_dead_wedged_producer_cancels():
    """The cancel=True path with a producer wedged OUTSIDE a queue op
    (e.g. a device_put hung on the relay): drain must give up after its
    timeout — abandoning the daemon thread — while still emptying the
    queue and cancelling every drained future."""
    import queue

    from hivemall_tpu.io.pipeline import drain_until_dead

    wedge = threading.Event()
    th = threading.Thread(target=wedge.wait, daemon=True)
    th.start()

    class _Fut:
        def __init__(self):
            self.cancelled = False

        def cancel(self):
            self.cancelled = True

    q: "queue.Queue" = queue.Queue()
    futs = [_Fut() for _ in range(3)]
    for f in futs:
        q.put(f)
    t0 = time.monotonic()
    drain_until_dead(q, th, timeout=0.2, cancel=True)
    assert time.monotonic() - t0 < 2.0       # returned despite live thread
    assert th.is_alive()                     # wedged producer abandoned
    assert q.empty()
    assert all(f.cancelled for f in futs)
    wedge.set()
    th.join(1)


def test_fit_ingest_workers_matches_sequential():
    """-ingest_workers N produces the same model as the sequential path."""
    from hivemall_tpu.models.linear import GeneralClassifier

    ds, _ = synthetic_classification(300, 20, seed=18)
    opts = "-dims 512 -loss logloss -opt adagrad -mini_batch 32 -iters 3"
    seq = GeneralClassifier(opts + " -ingest_workers 1").fit(ds)
    par = GeneralClassifier(opts + " -ingest_workers 4").fit(ds)
    np.testing.assert_array_equal(np.asarray(seq.w), np.asarray(par.w))
    assert par.pipeline_stats.batches_prepared > 0
    assert seq.pipeline_stats.batches_prepared > 0   # sequential also counts


def test_fit_stream_ingest_workers_matches_sequential():
    from hivemall_tpu.models.linear import GeneralClassifier

    ds, _ = synthetic_classification(256, 16, seed=19)
    opts = "-dims 512 -loss logloss -opt adagrad -mini_batch 32"
    seq = GeneralClassifier(opts + " -ingest_workers 1")
    seq.fit_stream(ds.batches(32, shuffle=False))
    par = GeneralClassifier(opts + " -ingest_workers 3")
    par.fit_stream(ds.batches(32, shuffle=False))
    np.testing.assert_array_equal(np.asarray(seq.w), np.asarray(par.w))


def test_ffm_fit_ingest_workers_matches_sequential():
    """The flagship path: canonicalize + (packed) prep across workers is
    bit-identical to sequential, shuffle included."""
    import json
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.models.fm import FFMTrainer

    rng = np.random.default_rng(20)
    n, L, F = 256, 8, 8
    idx = rng.integers(1, 2048, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32) % F, (n, 1))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L, dtype=np.int64)
    ds = SparseDataset(idx.ravel(), indptr, np.ones(n * L, np.float32),
                       lab, fld.ravel())
    cfg = ("-dims 2048 -factors 2 -fields 8 -mini_batch 64 "
           "-classification -iters 2")
    a = FFMTrainer(cfg + " -ingest_workers 1").fit(ds)
    b = FFMTrainer(cfg + " -ingest_workers 3").fit(ds)
    sa = json.dumps(a.model_table(), sort_keys=True, default=str)
    sb = json.dumps(b.model_table(), sort_keys=True, default=str)
    assert sa == sb


def test_elision_latch_deterministic_on_mixed_dataset():
    """The unit-value elision latch is stream-order state: it must run on
    the serial leg, so a MIXED dataset (real-valued batches before
    unit-valued ones) preps to identical representations under N workers
    as sequentially — batch for batch, val=None included."""
    from hivemall_tpu.io.sparse import SparseBatch, SparseDataset
    from hivemall_tpu.models.linear import GeneralClassifier

    rng = np.random.default_rng(22)
    n, L = 320, 4
    idx = rng.integers(1, 200, (n, L)).astype(np.int32)
    val = np.ones((n, L), np.float32)
    val[:40] = rng.uniform(0.5, 1.5, (40, L))   # first batches non-unit
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L, dtype=np.int64)
    ds = SparseDataset(idx.ravel(), indptr, val.ravel(), lab)

    def run(workers):
        t = GeneralClassifier("-dims 256 -mini_batch 32")
        closers = []
        t.opts["ingest_workers"] = workers
        out = list(t._ingest_iter(ds.batches(32, shuffle=False), closers))
        for c in closers:
            c()
        return out

    for a, b in zip(run(1), run(4)):
        _assert_batches_equal(a, b)
        assert a.val is not None       # latch tripped by the first batch


def test_parquet_decode_ahead_bit_exact():
    """Decode-ahead only moves the shard read/parse off the consuming
    thread; shuffled epoch batches stay bit-identical."""
    pytest.importorskip("pyarrow")
    import tempfile
    from hivemall_tpu.io.arrow import ParquetStream, write_parquet_shards
    from hivemall_tpu.io.sparse import SparseDataset

    rng = np.random.default_rng(21)
    n, L = 300, 6
    idx = rng.integers(1, 512, (n, L)).astype(np.int32)
    lab = rng.normal(0, 1, n).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L, dtype=np.int64)
    ds = SparseDataset(idx.ravel(), indptr,
                       rng.uniform(0.5, 1.5, n * L).astype(np.float32), lab)
    with tempfile.TemporaryDirectory() as tmp:
        write_parquet_shards(ds, tmp, rows_per_shard=64)
        sync = ParquetStream(tmp, decode_ahead=0)
        ahead = ParquetStream(tmp, decode_ahead=2)
        a = list(sync.batches(32, epochs=2, shuffle=True, seed=9))
        b = list(ahead.batches(32, epochs=2, shuffle=True, seed=9))
        assert len(a) == len(b) > 0
        for x, y in zip(a, b):
            _assert_batches_equal(x, y)
        assert ahead.stats.batches_prepared == len(ahead.files) * 2
