import numpy as np
import pytest

from hivemall_tpu.knn import (angular_distance, angular_similarity,
                              bbit_minhash, cosine_distance,
                              cosine_similarity, dimsum_mapper,
                              distance2similarity, euclid_distance,
                              euclid_similarity, hamming_distance,
                              jaccard_distance, jaccard_similarity, kld,
                              manhattan_distance, minhash, minhashes,
                              minkowski_distance)


def test_distances_on_feature_strings():
    a = ["1:1.0", "2:2.0"]
    b = ["1:1.0", "3:1.0"]
    assert euclid_distance(a, b) == pytest.approx(np.sqrt(4 + 1))
    assert manhattan_distance(a, b) == pytest.approx(3.0)
    assert minkowski_distance(a, b, 1.0) == pytest.approx(3.0)
    assert jaccard_distance(a, b) == pytest.approx(1 - 1 / 3)
    assert cosine_distance(a, a) == pytest.approx(0.0)
    assert angular_distance(a, a) == pytest.approx(0.0, abs=1e-4)


def test_numeric_vectors():
    assert euclid_distance([0, 0], [3, 4]) == pytest.approx(5.0)
    assert cosine_similarity([1, 0], [0, 1]) == pytest.approx(0.0)
    assert cosine_similarity([1, 1], [1, 1]) == pytest.approx(1.0)


def test_hamming():
    assert hamming_distance(0b1010, 0b0011) == 2
    assert hamming_distance([1, 2, 3], [1, 9, 3]) == 1


def test_kld_zero_for_same():
    assert kld(0.0, 1.0, 0.0, 1.0) == pytest.approx(0.0)
    assert kld(1.0, 1.0, 0.0, 1.0) > 0


def test_similarities():
    assert euclid_similarity([0], [0]) == 1.0
    assert distance2similarity(0.0) == 1.0
    assert jaccard_similarity(["a"], ["a"]) == 1.0
    assert angular_similarity([1, 0], [1, 0]) == pytest.approx(1.0, abs=1e-4)


def test_minhash_similarity_correlates():
    """Jaccard-similar sets share more minhash buckets than dissimilar."""
    a = [f"f{i}" for i in range(40)]
    b = a[:36] + ["x1", "x2", "x3", "x4"]          # ~0.8 similar
    c = [f"g{i}" for i in range(40)]               # disjoint
    k = 64
    ha, hb, hc = minhashes(a, k), minhashes(b, k), minhashes(c, k)
    share_ab = sum(x == y for x, y in zip(ha, hb)) / k
    share_ac = sum(x == y for x, y in zip(ha, hc)) / k
    assert share_ab > 0.5 > share_ac
    rows = list(minhash(a, 5))
    assert len(rows) == 5 and rows[0][1] == a


@pytest.mark.parametrize("shared,unique,jaccard", [
    (8, 1, 0.8),    # |A∩B|=8, each side +1 unique → 8/10
    (2, 1, 0.5),    # 2/4
    (1, 1, 1 / 3),  # 1/3
    (1, 4, 1 / 9),  # 1/9
])
def test_minhash_collision_probability_tracks_jaccard(shared, unique,
                                                      jaccard):
    """Property: a single minhash collides with probability exactly the
    Jaccard similarity, and an r-hash BAND collides with probability
    J^r — the banding amplification the SRP index in knn/ann.py reuses
    for vectors. Empirical rates over seeded corpora (many independent
    pairs x k hash families) must track both within sampling tolerance.
    """
    rng = np.random.default_rng(1234 + shared * 100 + unique)
    k, band_r, n_pairs = 128, 2, 40
    hash_hits = band_hits = hash_n = band_n = 0
    for p in range(n_pairs):
        # distinct token universe per pair -> independent trials (the
        # hash families are fixed; fresh NAMES re-randomize the draw)
        toks = [f"p{p}_t{v}" for v in
                rng.choice(10 ** 6, size=shared + 2 * unique,
                           replace=False)]
        a = toks[:shared] + toks[shared:shared + unique]
        b = toks[:shared] + toks[shared + unique:]
        ha, hb = minhashes(a, k), minhashes(b, k)
        eq = [x == y for x, y in zip(ha, hb)]
        hash_hits += sum(eq)
        hash_n += k
        for i in range(0, k, band_r):   # bands = consecutive r-tuples
            band_hits += all(eq[i:i + band_r])
            band_n += 1
    hash_rate = hash_hits / hash_n
    band_rate = band_hits / band_n
    # binomial std at n=5120: <=0.007 — 4 sigma plus hash-family bias slack
    assert hash_rate == pytest.approx(jaccard, abs=0.05), \
        f"per-hash collision rate {hash_rate:.3f} vs J={jaccard:.3f}"
    assert band_rate == pytest.approx(jaccard ** band_r, abs=0.05), \
        f"band collision rate {band_rate:.3f} vs J^{band_r}=" \
        f"{jaccard ** band_r:.3f}"


def test_bbit_minhash_length():
    sig = bbit_minhash(["a", "b"], k=16, b=2)
    assert len(sig) == 32 and set(sig) <= {"0", "1"}


def test_dimsum_mapper_partials_sum_to_cosine():
    rng = np.random.default_rng(0)
    X = rng.uniform(0, 1, (200, 3)).astype(np.float64)
    norms = {str(j): float(np.linalg.norm(X[:, j])) for j in range(3)}
    acc = {}
    for r in range(200):
        row = [f"{j}:{X[r, j]}" for j in range(3)]
        for a, b, p in dimsum_mapper(row, norms, threshold=1e-6, seed=r):
            acc[(a, b)] = acc.get((a, b), 0.0) + p
    true = float(X[:, 0] @ X[:, 1] / (norms["0"] * norms["1"]))
    # with sqrt_gamma >> norms every pair is emitted exactly
    assert acc[("0", "1")] == pytest.approx(true, rel=1e-6)
