"""word2vec: embeddings place co-occurring words together (convergence-smoke,
SURVEY.md §5 style — structure, not exact numbers)."""

import numpy as np
import pytest

from hivemall_tpu.models.word2vec import Word2VecTrainer


def synthetic_corpus(n_docs=400, seed=0):
    """Two topic clusters; words within a cluster co-occur."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "cow"]
    tech = ["cpu", "gpu", "ram", "disk"]
    docs = []
    for _ in range(n_docs):
        group = animals if rng.random() < 0.5 else tech
        docs.append([group[rng.integers(len(group))] for _ in range(12)])
    return docs


@pytest.mark.parametrize("mode", ["skipgram", "cbow"])
def test_clusters_separate(mode):
    docs = synthetic_corpus()
    if mode == "cbow":
        # CBOW emits ~2w-fold fewer training pairs per corpus pass than
        # SkipGram, so it needs more epochs / a hotter lr to separate
        opts = ("-dim 16 -window 3 -neg 4 -min_count 2 -alpha 1.0 "
                "-mini_batch 512 -iters 12 -sample 0 -cbow -pacing mean")
    else:
        opts = ("-dim 16 -window 3 -neg 4 -min_count 2 -alpha 0.5 "
                "-mini_batch 512 -iters 8 -sample 0 -pacing mean")
    t = Word2VecTrainer(opts).train(docs)
    same = t.similarity("cat", "dog")
    cross = t.similarity("cat", "gpu")
    assert same > cross + 0.2, (same, cross)


def test_udtf_lifecycle_and_vocab():
    t = Word2VecTrainer("-dim 8 -min_count 1 -mini_batch 64 -iters 1")
    for doc in synthetic_corpus(50):
        t.process(doc)
    rows = dict(t.close())
    assert "cat" in rows and len(rows["cat"]) == 8


def test_min_count_filters():
    t = Word2VecTrainer("-dim 4 -min_count 5 -mini_batch 32")
    docs = [["rare"], ["common"] * 10]
    t.train(docs)
    assert "rare" not in t.vocab and "common" in t.vocab


def test_empty_vocab_raises():
    t = Word2VecTrainer("-dim 4 -min_count 100")
    with pytest.raises(ValueError):
        t.train([["a", "b"]])


def test_vectorized_skipgram_pairs_window_constraint():
    import numpy as np
    from hivemall_tpu.models.word2vec import Word2VecTrainer
    d = np.arange(64, dtype=np.int32)
    rng = np.random.default_rng(0)
    c, x = Word2VecTrainer._skipgram_pairs(d, 3, rng)
    # token ids equal positions here, so |c - x| is the pair distance
    dist = np.abs(c.astype(int) - x.astype(int))
    assert (dist >= 1).all() and (dist <= 3).all()
    # expected pair count: interior tokens emit ~2*E[w] pairs, E[w] = 2
    assert 2.5 * 64 < len(x) < 4.5 * 64


def test_vectorized_cbow_windows_shape():
    import numpy as np
    from hivemall_tpu.models.word2vec import Word2VecTrainer
    d = np.arange(32, dtype=np.int32)
    rng = np.random.default_rng(0)
    ctx, tgt = Word2VecTrainer._cbow_windows(d, 4, rng)
    assert ctx.shape[1] == 8
    assert len(tgt) == len(ctx)
    valid = ctx >= 0
    assert valid.any(1).all()            # every kept row has context
    # every context id is within 4 of its target position
    for r in range(len(tgt)):
        ids = ctx[r][valid[r]]
        assert (np.abs(ids - tgt[r]) <= 4).all()


def test_pair_generation_is_fast():
    """Host pair gen must not regress to per-token Python (VERDICT r1 weak
    #3). The vectorized path runs ~50M pairs/sec; the old scalar loop ran
    <1M. The 2M floor catches the regression with a wide margin for loaded
    CI machines (prod target 10M+ is asserted by bench.py, not here)."""
    import time
    import numpy as np
    from hivemall_tpu.models.word2vec import Word2VecTrainer
    rng = np.random.default_rng(0)
    d = rng.integers(0, 30000, 1_000_000).astype(np.int32)
    t0 = time.perf_counter()
    c, x = Word2VecTrainer._skipgram_pairs(d, 5, rng)
    rate = len(x) / (time.perf_counter() - t0)
    assert rate > 2e6, f"pair gen too slow: {rate/1e6:.1f}M pairs/sec"


def test_sparse_step_selected_for_large_vocab_updates_touched_only():
    """Vocab above the dense threshold uses slab-level scatter updates:
    untouched embedding rows must be bit-identical after a step."""
    import jax.numpy as jnp
    import numpy as np
    from hivemall_tpu.models.word2vec import Word2VecTrainer
    t = Word2VecTrainer("-dim 16 -neg 2 -mini_batch 4")
    step = t._make_step(False, vocab_size=1 << 20, dim=16)  # sparse branch
    V = 64
    ie = jnp.ones((V, 16))
    oe = jnp.ones((V, 16)) * 0.5
    center = jnp.asarray([1, 2, 3, 1])
    ctx = jnp.asarray([4, 5, 6, 7])
    ntab = jnp.asarray([8, 9, 10, 11, 12, 13, 14, 15])  # negatives pool
    ie0, oe0 = np.asarray(ie), np.asarray(oe)   # donation invalidates ie/oe
    ie2, oe2, loss = step(ie, oe, ntab, center, ctx, 4, 1, 0.1)
    ie, oe = ie0, oe0
    assert float(loss) > 0
    assert not np.allclose(np.asarray(ie2[1]), np.asarray(ie[1]))
    np.testing.assert_array_equal(np.asarray(ie2[20]), np.asarray(ie[20]))
    np.testing.assert_array_equal(np.asarray(oe2[30]), np.asarray(oe[30]))
    assert not np.allclose(np.asarray(oe2[4]), np.asarray(oe[4]))


def test_word2vec_mesh_trains():
    """-mesh shards pair batches over dp and embedding tables over tp."""
    import numpy as np
    from hivemall_tpu.models.word2vec import Word2VecTrainer
    rng = np.random.default_rng(0)
    words = [f"w{t}" for t in rng.integers(0, 50, 20000)]
    t = Word2VecTrainer("-dim 16 -window 3 -neg 2 -min_count 1 "
                        "-mini_batch 512 -mesh dp=2,tp=4")
    assert t.mesh is not None
    t.train([words])
    emb = t.in_emb
    assert emb.sharding.shard_shape(emb.shape)[0] == emb.shape[0] // 4
    assert np.isfinite(np.asarray(emb)).all()
    # similar-context words should still embed meaningfully
    v = t.vectors()
    assert len(v) == 50


def test_pair_pacing_converges_at_word2vec_c_alpha():
    """-pacing pair (the default): word2vec.c option values work as-is —
    alpha 0.025/pair separates the synthetic clusters without the x10
    round-2 footgun scaling."""
    rng = np.random.default_rng(0)
    A = [f"a{i}" for i in range(6)]
    B = [f"b{i}" for i in range(6)]
    docs = []
    for _ in range(300):
        docs.append(list(rng.permutation(A)))
        docs.append(list(rng.permutation(B)))
    t = Word2VecTrainer("-dim 16 -window 3 -neg 4 -min_count 2 "
                        "-alpha 0.025 -mini_batch 512 -iters 10 -sample 0")
    assert str(t.opts.pacing) == "pair"
    t.train(docs)
    within = np.mean([t.similarity("a0", "a1"), t.similarity("a2", "a3"),
                      t.similarity("b0", "b1")])
    across = np.mean([t.similarity("a0", "b0"), t.similarity("a1", "b3"),
                      t.similarity("a4", "b2")])
    assert within > across + 0.2, (within, across)


def test_device_pairgen_matches_numpy_reference():
    """The jitted device pair grid (shifted rolls + masks) must agree with
    a direct numpy enumeration: slot (i, j) of the [Nc, 2*win] grid is
    (T[i], T[i + sgn*delta]), masked for SEP endpoints, halo centers, and
    (sample policy) delta > w[i]; weighted policy carries
    (win-delta+1)/win."""
    import jax.numpy as jnp
    win, sep = 2, 9
    t = Word2VecTrainer(f"-dim 4 -window {win} -min_count 1")
    Nc = 16
    T = np.array([sep, sep, 1, 2, 3, sep, sep, 4, 5, 6, 7, 8, sep, sep,
                  sep, sep], np.int32)
    gen = t._make_pairgen(Nc, win, sep, "weighted", 7, np.int32)
    c, x, m, s = gen(jnp.asarray(T), jnp.int32(0), jnp.uint32(0))
    c, x, m = np.asarray(c), np.asarray(x), np.asarray(m)
    assert x.shape == (Nc, 2 * win) and m.shape == (Nc, 2 * win)
    np.testing.assert_array_equal(c, T)       # grid centers ARE the chunk
    slots = [(d, sg) for d in range(1, win + 1) for sg in (1, -1)]
    for i in range(Nc):
        for j, (delta, sgn) in enumerate(slots):
            jpos = i + sgn * delta
            ok = (win <= i < Nc - win and T[i] != sep
                  and 0 <= jpos < Nc and T[jpos] != sep)
            want = (win - delta + 1) / win if ok else 0.0
            assert abs(m[i, j] - want) < 1e-6, (i, j, m[i, j], want)
            if ok:
                assert x[i, j] == T[jpos], (i, j)
    # sample policy: masks are a subset of weighted's support, w in [1,win]
    gen2 = t._make_pairgen(Nc, win, sep, "sample", 7, np.int32)
    _, _, m2, _ = gen2(jnp.asarray(T), jnp.int32(0), jnp.uint32(0))
    m2 = np.asarray(m2)
    assert set(np.unique(m2)).issubset({0.0, 1.0})
    assert ((m2 > 0) <= (m > 0)).all()
    # delta=1 slots valid for any drawn w: where weighted is valid, sample
    # keeps every delta=1 slot
    d1 = np.zeros_like(m, bool)
    d1[:, :2] = True
    assert (m2[(m > 0) & d1] == 1.0).all()


@pytest.mark.parametrize("policy", ["sample", "weighted"])
def test_clusters_separate_device_pairgen(policy):
    docs = synthetic_corpus()
    t = Word2VecTrainer(
        "-dim 16 -window 3 -neg 4 -neg_sharing batch -min_count 2 "
        "-alpha 0.5 -mini_batch 512 -iters 8 -sample 0 -pacing mean "
        f"-pair_gen device -window_policy {policy}").train(docs)
    same = t.similarity("cat", "dog")
    cross = t.similarity("cat", "gpu")
    assert same > cross + 0.2, (same, cross)
