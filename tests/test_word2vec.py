"""word2vec: embeddings place co-occurring words together (convergence-smoke,
SURVEY.md §5 style — structure, not exact numbers)."""

import numpy as np
import pytest

from hivemall_tpu.models.word2vec import Word2VecTrainer


def synthetic_corpus(n_docs=400, seed=0):
    """Two topic clusters; words within a cluster co-occur."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "cow"]
    tech = ["cpu", "gpu", "ram", "disk"]
    docs = []
    for _ in range(n_docs):
        group = animals if rng.random() < 0.5 else tech
        docs.append([group[rng.integers(len(group))] for _ in range(12)])
    return docs


@pytest.mark.parametrize("mode", ["skipgram", "cbow"])
def test_clusters_separate(mode):
    docs = synthetic_corpus()
    if mode == "cbow":
        # CBOW emits ~2w-fold fewer training pairs per corpus pass than
        # SkipGram, so it needs more epochs / a hotter lr to separate
        opts = ("-dim 16 -window 3 -neg 4 -min_count 2 -alpha 1.0 "
                "-mini_batch 512 -iters 12 -sample 0 -cbow")
    else:
        opts = ("-dim 16 -window 3 -neg 4 -min_count 2 -alpha 0.5 "
                "-mini_batch 512 -iters 8 -sample 0")
    t = Word2VecTrainer(opts).train(docs)
    same = t.similarity("cat", "dog")
    cross = t.similarity("cat", "gpu")
    assert same > cross + 0.2, (same, cross)


def test_udtf_lifecycle_and_vocab():
    t = Word2VecTrainer("-dim 8 -min_count 1 -mini_batch 64 -iters 1")
    for doc in synthetic_corpus(50):
        t.process(doc)
    rows = dict(t.close())
    assert "cat" in rows and len(rows["cat"]) == 8


def test_min_count_filters():
    t = Word2VecTrainer("-dim 4 -min_count 5 -mini_batch 32")
    docs = [["rare"], ["common"] * 10]
    t.train(docs)
    assert "rare" not in t.vocab and "common" in t.vocab


def test_empty_vocab_raises():
    t = Word2VecTrainer("-dim 4 -min_count 100")
    with pytest.raises(ValueError):
        t.train([["a", "b"]])
