"""Property tests (SURVEY.md §5: hypothesis for codecs and parsers)."""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings
from hypothesis import strategies as st

from hivemall_tpu.frame.tools import base91, deflate, inflate, unbase91
from hivemall_tpu.utils.hashing import mhash, murmurhash3_x86_32
from hivemall_tpu.utils.options import OptionSpec


@settings(max_examples=200, deadline=None)
@given(st.binary(max_size=512))
def test_base91_roundtrip(data):
    assert unbase91(base91(data)) == data


@settings(max_examples=100, deadline=None)
@given(st.text(max_size=512))
def test_deflate_inflate_roundtrip(text):
    assert inflate(deflate(text)) == text


@settings(max_examples=200, deadline=None)
@given(st.text(min_size=0, max_size=64))
def test_mmh3_is_deterministic_and_bounded(s):
    a, b = murmurhash3_x86_32(s), murmurhash3_x86_32(s)
    assert a == b
    assert 0 <= a < 2 ** 32
    h = mhash(s, 2 ** 24 - 1)
    assert 1 <= h <= 2 ** 24 - 1          # reference mhash range [1, 2^24)


@settings(max_examples=100, deadline=None)
@given(st.lists(st.sampled_from(["-eta0 0.5", "-iters 3", "-dense",
                                 "-loss logloss"]), max_size=4))
def test_option_parser_accepts_any_known_combo(opts):
    spec = OptionSpec("t")
    spec.add("eta0", type=float, default=0.1, help="")
    spec.add("iters", type=int, default=1, help="")
    spec.add("loss", default="hingeloss", help="")
    spec.flag("dense", help="")
    parsed = spec.parse(" ".join(opts))
    # last-wins + defaults always produce a complete namespace
    assert parsed.eta0 is not None and parsed.iters is not None


@settings(max_examples=50, deadline=None)
@given(st.lists(st.tuples(st.integers(1, 1000),
                          st.floats(-100, 100, allow_nan=False, width=32,
                                    allow_subnormal=False)),
                min_size=1, max_size=20))
def test_feature_string_parse_roundtrip(pairs):
    """'idx:val' strings parse back to the same (idx, val) arrays."""
    from hivemall_tpu.models.linear import GeneralClassifier
    tr = GeneralClassifier("-dims 2048 -int_feature")
    feats = [f"{i}:{v:.6g}" for i, v in pairs]
    idx, val = tr._parse_row(feats)
    assert list(idx) == [i for i, _ in pairs]
    np.testing.assert_allclose(val, [float(f"{v:.6g}") for _, v in pairs],
                               rtol=1e-6)


@settings(max_examples=50, deadline=None)
@given(st.integers(2, 7), st.integers(1, 4), st.integers(2, 12),
       st.integers(0, 2**31 - 1))
def test_canonicalize_fieldmajor_preserves_multiset(F, B, L, seed):
    """Field-major canonicalization (numpy or C++ twin) keeps every live
    (feature, value, field mod F) triple and assigns slot s field s % F."""
    from hivemall_tpu.io.sparse import canonicalize_fieldmajor
    rng = np.random.default_rng(seed)
    idx = rng.integers(1, 500, (B, L)).astype(np.int32)
    val = rng.uniform(0.1, 1, (B, L)).astype(np.float32)
    fld = rng.integers(-2, 2 * F, (B, L)).astype(np.int32)
    val[rng.uniform(size=(B, L)) < 0.3] = 0
    res = canonicalize_fieldmajor(idx, val, fld, F, max_m=L)
    assert res is not None
    idx2, val2, m = res
    assert idx2.shape == (B, m * F) and (m & (m - 1)) == 0
    for b in range(B):
        orig = sorted((int(i), float(v), int(f) % F) for i, v, f in
                      zip(idx[b], val[b], fld[b]) if v != 0)
        got = sorted((int(idx2[b, s]), float(val2[b, s]), s % F)
                     for s in range(m * F) if val2[b, s] != 0)
        assert orig == got
