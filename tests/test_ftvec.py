import numpy as np
import pytest

from hivemall_tpu.ftvec import (add_bias, add_feature_index, build_bins,
                                categorical_features, chi2, extract_feature,
                                extract_weight, feature, feature_binning,
                                feature_hashing, ffm_features,
                                indexed_features, l1_normalize, l2_normalize,
                                onehot_encoding, polynomial_features,
                                powered_features, quantify,
                                quantitative_features, rescale,
                                sort_by_feature, to_dense_features,
                                to_sparse_features, vectorize_features,
                                zscore)


def test_core_helpers():
    assert add_bias(["1:2.0"]) == ["1:2.0", "0:1.0"]
    assert extract_feature("height:1.7") == "height"
    assert extract_weight("height:1.7") == 1.7
    assert extract_weight("bare") == 1.0
    assert feature("a", 2) == "a:2"
    assert add_feature_index([0.5, 0.25]) == ["1:0.5", "2:0.25"]
    assert list(sort_by_feature({"2": 1, "1": 2})) == ["1", "2"]


def test_feature_hashing_semantics():
    out = feature_hashing(["cat#tokyo", "10:0.5", "height:1.7"])
    # integer index passes through; names hashed to ints keeping value
    assert out[1] == "10:0.5"
    h, v = out[2].rsplit(":", 1)
    assert int(h) >= 1 and v == "1.7"
    # idempotent on already-hashed output
    assert feature_hashing(out) == out
    # -features bounds the space
    small = feature_hashing(["a", "b", "c"], "-features 8")
    assert all(1 <= int(s) <= 8 for s in small)


def test_scaling():
    assert rescale(5, 0, 10) == 0.5
    assert rescale(3, 3, 3) == 0.5
    assert zscore(12, 10, 2) == 1.0
    l1 = l1_normalize(["a:1", "b:3"])
    assert l1 == ["a:0.25", "b:0.75"]
    l2 = l2_normalize(["a:3", "b:4"])
    assert [extract_weight(f) for f in l2] == [0.6, 0.8]


def test_conv():
    dense = to_dense_features(["1:0.5", "3:2.0"], 4)
    assert dense == [0.0, 0.5, 0.0, 2.0, 0.0]
    assert to_sparse_features(dense) == ["1:0.5", "3:2.0"]
    q = quantify()
    assert q(["a", 5]) == [0, 5]
    assert q(["b", 6]) == [1, 6]
    assert q(["a", 7]) == [0, 7]
    assert q.mapping(0) == {"a": 0, "b": 1}


def test_pairing():
    out = polynomial_features(["a:2", "b:3"], "-degree 2")
    assert "a^b:6.0" in out
    assert "a^a:4.0" in out
    io = polynomial_features(["a:2", "b:3"], "-degree 2 -interaction_only")
    assert "a^a:4.0" not in io and "a^b:6.0" in io
    pw = powered_features(["a:2"], 3)
    assert "a^2:4.0" in pw and "a^3:8.0" in pw


def test_trans():
    assert categorical_features(["c1", "c2"], "x", None) == ["c1#x"]
    assert quantitative_features(["q1"], 2) == ["q1:2.0"]
    assert vectorize_features(["a", "b"], "x", 3) == ["a#x", "b:3.0"]
    assert indexed_features(5, 7) == ["1:5.0", "2:7.0"]
    rows = list(__import__("hivemall_tpu.ftvec.trans", fromlist=["binarize_label"]
                           ).binarize_label(2, 1, "payload"))
    assert rows == [("payload", 1), ("payload", 1), ("payload", 0)]
    enc = onehot_encoding([["b", "a"], ["x"]])
    assert enc[0] == {"a": 1, "b": 2} and enc[1] == {"x": 3}
    from hivemall_tpu.ftvec.trans import quantified_features
    qf = quantified_features()
    assert qf(["a", 5]) == [0.0, 5.0]
    assert qf(["b", 6.5]) == [1.0, 6.5]
    assert qf(["a", 7]) == [0.0, 7.0]


def test_ffm_features():
    out = ffm_features(["user", "movie", "age"], "john", "m1", 25)
    assert len(out) == 3
    f0 = out[0].split(":")
    assert f0[0] == "0" and f0[2] == "1"      # categorical -> value 1
    f2 = out[2].split(":")
    assert f2[0] == "2" and float(f2[2]) == 25.0


def test_chi2_discriminates():
    # feature 0 differs strongly across classes; feature 1 matches expectation
    obs = np.asarray([[30.0, 10.0], [10.0, 10.0]])
    exp = np.asarray([[20.0, 10.0], [20.0, 10.0]])
    stat, p = chi2(obs, exp)
    assert stat[0] > stat[1]
    assert p[0] < 0.05 < p[1]


def test_binning():
    edges = build_bins(list(range(100)), 4)
    assert edges[0] == -np.inf and edges[-1] == np.inf
    assert len(edges) == 5
    assert feature_binning(-5, edges) == 0
    assert feature_binning(99, edges) == 3
    assert feature_binning(50, edges) in (1, 2)
